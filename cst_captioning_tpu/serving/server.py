"""Front end for the serving engine: stdin/JSONL + optional localhost socket.

Protocol (one JSON object per line, either direction):

  request:   {"id": <any>, "video_id": "<key>"}
             optional: "op": "caption" (default) | "stream" | "health",
                       "deadline_ms": <per-request TTL override>,
                       "no_cache": true  (skip the exact-result cache),
                       "trace": {"id", "recv_s"}  — cross-process trace
                       context stamped by a supervising front end
                       (SERVING.md "Wire format"); echoed into this
                       process's lifecycle events (`trace_id`) so
                       scripts/fleet_trace.py can stitch the request's
                       async track across the process boundary.
                       Ignored when absent — single-process wire
                       traffic is unchanged.
                       "idem": "<key>" — the client's idempotency key
                       (ISSUE 20).  This process does NOT dedup (the
                       supervisor's intake journal owns exactly-once);
                       the key is validated (string) and echoed on the
                       request's terminal response so callers can
                       correlate answers across a reconnect.
  duplicate: a supervising front end with the intake journal armed
             (scripts/serve_supervisor.py --journal_dir) answers a
             resubmitted idempotency key from the journal:
             {"id", ...the journaled terminal..., "idempotent": true}
             with zero decode work (SERVING.md "Durable intake
             journal")
  response:  {"id", "video_id", "caption", "latency_ms", "decode_steps"}
             (cache hits add "cached": true; streamed finals add
             "stream": true, "final": true, "chunks": N, "ttft_ms")
  stream:    {"id", "video_id", "stream": true, "seq": k,
              "tokens": [..], "text": "<new words>", "final": false}
             — one line per scheduler chunk as the resident's new tokens
             are harvested; the concatenation of the "text" fragments is
             the final caption (SERVING.md "Streaming & result cache")
  health:    {"op": "health", "status": "ok"|"degraded"|"draining",
              "queue_depth", "residents", "recovery": {...}}
  stats:     {"op": "stats", ...engine/fleet stats()...} — the full
             scheduler statistics view, including the per-request
             latency-attribution report when the lifecycle tracer is
             armed (SERVING.md "Wire format")
  ping:      {"op": "ping", "seq": k, "t0": <sender monotonic>} ->
             {"op": "ping", "seq", "t0", "mono": <this process's
             monotonic>, "wall": <this process's wall clock>, "pid"} —
             the clock-offset handshake: the supervisor's midpoint
             estimate (offset = child wall - (send wall + rtt/2),
             uncertainty <= rtt/2) feeds the skew table trace
             stitching rebases child events with (ISSUE 17)
  dump:      {"op": "dump"} -> the flight recorder writes blackbox.json
             (atomic) and answers {"op": "dump", "path", "events",
             "emitted"}; "path" in the request overrides the configured
             target.  Errors: "no_recorder" (tracing disarmed),
             "no_path" (nowhere configured to write)
  reject:    {"id", "error": "shed" | "bad_request" | "unknown_video"
                            | "unknown_op" | "rejected_draining"
                            | "expired" | "admit_failed", ...}

Scheduling model: reader threads (stdin, or one per socket connection)
only parse lines into a thread-safe inbox; the single scheduler loop owns
the engine — submit, step, respond.  Backpressure is explicit: when the
engine's bounded queue sheds a request the client gets ``"error": "shed"``
immediately instead of silently growing latency.  Intake is hardened: a
malformed line, an unknown ``op``, or any per-line handling error yields
a per-line ``error`` response and a ``serve_bad_lines`` counter bump —
one bad client line must never kill the scheduler loop.

Shutdown contract (SERVING.md "Drain"): a SIGTERM/SIGINT (via the shared
``resilience.preemption.PreemptionHandler``) closes admissions, DRAINS
the in-flight residents to completion, answers everything still queued
with ``rejected_draining``, and exits ``exitcodes.EXIT_PREEMPTED`` (75) —
the same resumable classification the training loop uses, so a fleet
harness treats a drained server exactly like a preempted trainer.  A
SECOND signal during the drain is the hard stop: the drain aborts,
unfinished residents are answered ``rejected_draining``, and the exit is
``exitcodes.EXIT_SIGTERM`` (143, sigterm_unwind — still resumable in the
taxonomy, but the lost in-flight work is honest).  Stdin EOF is the
natural end: finish everything, exit 0.

Liveness: with a ``watchdog`` attached (``utils/watchdog.ProgressWatchdog``
— the serving ``heartbeat.json``), the scheduler loop beats it once per
iteration; a loop wedged inside a dead transport stops beating and the
watchdog exits 124 through the same taxonomy.
"""

from __future__ import annotations

import json
import logging
import os
import queue
import socket
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..utils.locksan import LockOrderViolation, declare_order, named_lock
from ..resilience.exitcodes import EXIT_OK, EXIT_PREEMPTED, EXIT_SIGTERM
from ..resilience.garble import health_status
from .engine import Completion, Dropped, ServingEngine, StreamChunk

log = logging.getLogger("cst_captioning_tpu.serving.server")

#: Declared acquisition order (cstlint:lock-order + the runtime
#: sanitizer): ``_write`` serializes whole response lines under the
#: server-wide write lock and the socket ``respond`` closure then takes
#: its per-connection send lock — so write-before-conn is the law, and
#: the sanitizer proves no path ever takes them the other way around.
LOCK_ORDER = ("serving.server.write", "serving.server.conn")
declare_order(*LOCK_ORDER)


class CaptionServer:
    """Line-protocol server around one :class:`ServingEngine`.

    ``engine`` is anything speaking the engine scheduler surface — one
    :class:`ServingEngine`, or a :class:`serving.fleet.FleetRouter`
    spreading the same wire format over N replicas.  ``feats_for
    (video_id)`` -> per-modality feature list (or None for an unknown
    id) — the deployment decides where features come from (h5 lookup,
    upstream extractor, demo table).  ``handler`` is anything with
    ``requested`` (bool) and ``signal_count`` (int) attributes — the
    preemption handler, or a test stub.  ``watchdog`` (optional) is
    beaten once per scheduler iteration; ``registry`` (optional) counts
    intake errors and health queries.  ``health_source`` (optional)
    replaces ``engine.health`` as the ``{"op": "health"}`` payload body
    — the fleet front end plugs the router's worst-of-replicas view
    (per-replica detail included) in here; the server still folds its
    own draining state on top.
    """

    def __init__(self, engine: ServingEngine, vocab, feats_for,
                 *, handler=None, out=None, idle_sleep: float = 0.002,
                 watchdog=None, registry=None, health_source=None,
                 lifecycle=None, blackbox_path=None):
        # The engine is single-owner state: reader threads parse lines
        # into the inbox, ONLY the scheduler loop may touch the engine
        # (cstlint:thread-ownership — the inbox-owns-intake discipline).
        self.engine = engine  # cstlint: owned_by=scheduler
        self.vocab = vocab
        self.feats_for = feats_for
        self.handler = handler
        self.out = out if out is not None else sys.stdout
        self.idle_sleep = idle_sleep
        self.watchdog = watchdog
        self.registry = registry
        self._health_source = health_source
        # Request-lifecycle tracing (telemetry/lifecycle.py): the BASE
        # tracer — the server stamps the terminal "responded" events and
        # owns the {"op": "dump"} flight-recorder wire op, writing the
        # blackbox to ``blackbox_path``.  None = untraced.
        self._lifecycle = lifecycle
        self.blackbox_path = blackbox_path
        if registry is not None:
            registry.declare("serve_bad_lines", "serve_health_queries",
                             "serve_stats_queries", "serve_dump_queries",
                             "serve_ping_queries")
        self._inbox: "queue.Queue" = queue.Queue()
        self._eof = threading.Event()
        self._write_lock = named_lock("serving.server.write")
        self._draining = False  # cstlint: owned_by=scheduler
        #: The socket front end's bound port; None until run_socket
        #: binds.  In-process callers (the reader-lifecycle drill) poll
        #: this instead of scraping the stderr announcement.
        self.bound_port: Optional[int] = None

    # -- responses ---------------------------------------------------------

    def _write(self, respond: Callable[[str], None], obj: Dict[str, Any]):
        with self._write_lock:
            respond(json.dumps(obj))

    @staticmethod
    def _mark_stream_terminal(obj: Dict[str, Any], streamed) -> Dict[str, Any]:
        """The ONE source of the protocol invariant that every streamed
        request's LAST line carries ``"final": true`` — applied at every
        terminal write (completion, drop, shed, drain reject) so a
        client reading chunks until the terminal can never hang."""
        if streamed:
            obj["stream"] = True
            obj["final"] = True
        return obj

    def _respond_completion(self, comp: Completion) -> None:
        meta = comp.meta or {}
        respond = meta.get("respond", self._stdout_respond)
        obj = {
            "id": meta.get("id"),
            "video_id": meta.get("video_id"),
            "caption": self.vocab.decode(comp.tokens),
            "latency_ms": round(comp.latency_s * 1e3, 3),
            "decode_steps": int(comp.decode_steps),
        }
        if comp.cache_hit:
            obj["cached"] = True
        if meta.get("stream"):
            # The terminal line of a streamed response: carries the full
            # caption (authoritative — equal to the concatenated chunks).
            obj["stream"] = True
            obj["final"] = True
            obj["chunks"] = int(comp.stream_chunks)
            if comp.ttft_s is not None:
                obj["ttft_ms"] = round(comp.ttft_s * 1e3, 3)
        if meta.get("idem") is not None:
            obj["idem"] = meta["idem"]
        self._write(respond, obj)
        if self._lifecycle is not None:
            self._lifecycle.emit("responded", comp.request_id,
                                 status="ok")

    def _respond_stream_chunk(self, chunk: StreamChunk) -> None:
        meta = chunk.meta or {}
        respond = meta.get("respond", self._stdout_respond)
        self._write(respond, {
            "id": meta.get("id"),
            "video_id": meta.get("video_id"),
            "stream": True,
            "seq": int(chunk.seq),
            "tokens": [int(t) for t in chunk.tokens],
            "text": self.vocab.decode(chunk.tokens),
            "final": False,
        })

    def _respond_stream_all(self) -> bool:
        chunks = self.engine.pop_stream_chunks()
        for chunk in chunks:
            self._respond_stream_chunk(chunk)
        return bool(chunks)

    def _respond_dropped(self, drop: Dropped) -> None:
        meta = drop.meta or {}
        respond = meta.get("respond", self._stdout_respond)
        error = ("admit_failed" if drop.reason == "admit_failed"
                 else "expired")
        obj = self._mark_stream_terminal(
            {"id": meta.get("id"), "video_id": meta.get("video_id"),
             "error": error}, meta.get("stream"))
        if meta.get("idem") is not None:
            obj["idem"] = meta["idem"]
        if drop.reason == "expired":
            obj["where"] = drop.where              # "queued" | "resident"
        elif drop.reason == "deadline_shed":
            obj["error"] = "expired"
            # "queued" (the engine's p99 floor) or "fleet" (the router
            # proved the deadline unmeetable at EVERY replica and shed
            # at the fleet edge — SERVING.md "Fleet").
            obj["where"] = drop.where
            obj["why"] = "deadline_unmeetable"
        elif drop.reason == "admit_failed" and drop.where == "fleet":
            obj["where"] = "fleet"
        self._write(respond, obj)
        if self._lifecycle is not None:
            self._lifecycle.emit("responded", drop.request_id,
                                 status=obj["error"])

    def _respond_dropped_all(self) -> bool:
        drops = self.engine.pop_dropped()
        for drop in drops:
            self._respond_dropped(drop)
        return bool(drops)

    def _stdout_respond(self, line: str) -> None:
        self.out.write(line + "\n")
        self.out.flush()

    def _count_bad_line(self) -> None:
        if self.registry is not None:
            self.registry.inc("serve_bad_lines")

    # -- the health plane --------------------------------------------------

    def health_payload(self) -> Dict[str, Any]:
        """The ``{"op": "health"}`` response body — the health source's
        view (``engine.health()`` by default; the fleet router's
        worst-of-replicas payload when plugged in) with the server's
        draining state folded in (``draining`` dominates ``degraded``
        dominates ``ok``; a source already reporting ``draining`` — a
        rotating fleet replica — stays ``draining``)."""
        source = (self._health_source if self._health_source is not None
                  else self.engine.health)
        h = source()
        if h["status"] not in ("draining",):
            h["status"] = health_status(
                draining=self._draining or bool(
                    self.handler is not None and self.handler.requested),
                recovering=(h["status"] == "degraded"))
        h["op"] = "health"
        return h

    # -- request intake (reader threads -> inbox -> scheduler loop) --------

    def _handle_line(self, line: str, respond: Callable[[str], None]):
        """Parse and act on one client line.  EVERY failure path answers
        with a per-line error and counts it — the scheduler loop survives
        any input (pinned by tests/test_serving_resilience.py)."""
        try:
            self._handle_line_inner(line, respond)
        except LockOrderViolation:
            # A sanitizer violation is a programming error in THIS
            # process, not a bad client line: die loudly so the chaos
            # drill fails (the receipt is already durably on disk).
            raise
        except Exception as e:  # one bad line must never kill the loop
            self._count_bad_line()
            try:
                self._write(respond, {"id": None, "error": "bad_request",
                                      "detail": f"line handling failed: {e}"})
            except LockOrderViolation:
                raise  # same die-loudly contract as the outer handler
            except Exception as werr:
                # The error ANSWER failed too (client hung up mid-line):
                # already counted above; log so the double fault is
                # visible (cstlint:bare-except-swallow).
                log.debug("error response write failed: %r", werr)

    def _handle_line_inner(self, line: str,
                           respond: Callable[[str], None]):
        line = line.strip()
        if not line:
            return
        try:
            req = json.loads(line)
        except ValueError:
            self._count_bad_line()
            self._write(respond, {"id": None, "error": "bad_request",
                                  "detail": "unparseable JSON line"})
            return
        if not isinstance(req, dict):
            self._count_bad_line()
            self._write(respond, {"id": None, "error": "bad_request",
                                  "detail": "expected {'id', 'video_id'}"})
            return
        op = req.get("op", "caption")
        if op == "health":
            if self.registry is not None:
                self.registry.inc("serve_health_queries")
            self._write(respond, self.health_payload())
            return
        if op == "stats":
            # The scheduler-statistics wire op: the same stats() dict
            # the exit line prints, latency attribution included when
            # the lifecycle tracer is armed (SERVING.md "Wire format").
            if self.registry is not None:
                self.registry.inc("serve_stats_queries")
            self._write(respond, {"op": "stats", **self.engine.stats()})
            return
        if op == "ping":
            # Clock-sync echo (module docstring): answer immediately
            # with this process's clocks — both reads taken back to
            # back so the echo's own skew stays inside the sender's
            # rtt/2 uncertainty bound.
            if self.registry is not None:
                self.registry.inc("serve_ping_queries")
            self._write(respond, {"op": "ping", "seq": req.get("seq"),
                                  "t0": req.get("t0"),
                                  "mono": time.monotonic(),
                                  "wall": time.time(),
                                  "pid": os.getpid()})
            return
        if op == "dump":
            # On-demand flight-recorder dump: write blackbox.json NOW
            # (atomic_json_write) and answer with where it landed —
            # the operator's live forensic snapshot.
            if self.registry is not None:
                self.registry.inc("serve_dump_queries")
            if self._lifecycle is None:
                self._write(respond, {"op": "dump", "error": "no_recorder",
                                      "detail": "lifecycle tracing is "
                                                "disarmed"})
                return
            path = req.get("path") or self.blackbox_path
            if not path:
                self._write(respond, {"op": "dump", "error": "no_path",
                                      "detail": "no blackbox path "
                                                "configured or supplied"})
                return
            doc = self._lifecycle.dump(path, reason="wire_dump")
            self._write(respond, {"op": "dump", "path": str(path),
                                  "events": doc["events_retained"],
                                  "emitted": doc["events_emitted"]})
            return
        if op not in ("caption", "stream"):
            self._count_bad_line()
            self._write(respond, {"id": req.get("id"), "error": "unknown_op",
                                  "op": op,
                                  "detail": "expected op 'caption', "
                                            "'stream', 'health', 'stats', "
                                            "'ping' or 'dump'"})
            return
        stream = (op == "stream")
        if stream and self.engine.chunk >= self.engine.max_len:
            # --decode_chunk 0 ran the rollout as one max_len-sized
            # chunk: streaming degenerates to a single terminal chunk.
            # Warn ONCE (opts.py owns the warn-once discipline).
            from ..opts import warn_stream_legacy_scan

            warn_stream_legacy_scan()
        rid = req.get("id")
        vid = req.get("video_id")
        if vid is None:
            self._count_bad_line()
            self._write(respond, {"id": rid, "error": "bad_request",
                                  "detail": "expected {'id', 'video_id'}"})
            return
        deadline_ms = req.get("deadline_ms")
        if deadline_ms is not None:
            try:
                deadline_ms = float(deadline_ms)
                if deadline_ms < 0:
                    raise ValueError
            except (TypeError, ValueError):
                self._count_bad_line()
                self._write(respond, {"id": rid, "error": "bad_request",
                                      "detail": "deadline_ms must be a "
                                                "number >= 0"})
                return
        idem = req.get("idem")
        if idem is not None and not isinstance(idem, str):
            # Same wire verdict as the supervisor front end: the
            # idempotency key is a string or absent, never coerced.
            self._count_bad_line()
            self._write(respond, {"id": rid, "error": "bad_request",
                                  "detail": "idem must be a string"})
            return
        feats = self.feats_for(vid)
        if feats is None:
            self._write(respond, {"id": rid, "error": "unknown_video",
                                  "video_id": vid})
            return
        meta = {"id": rid, "video_id": vid, "respond": respond,
                "stream": stream}
        if idem is not None:
            meta["idem"] = idem   # echoed on the terminal (docstring)
        tr = req.get("trace")
        if isinstance(tr, dict):
            # Cross-process trace context rides the meta into the
            # engine's lifecycle emits (module docstring).
            meta["trace"] = tr
        try:
            ok = self.engine.submit(
                (rid, vid), [np.asarray(f) for f in feats],
                meta=meta,
                deadline_ms=deadline_ms, stream=stream,
                no_cache=bool(req.get("no_cache")))
        except ValueError as e:
            self._count_bad_line()
            self._write(respond, {"id": rid, "error": "bad_request",
                                  "detail": str(e)})
            return
        if not ok:
            # queue_depth via the cheap property, NOT stats(): with the
            # lifecycle tracer armed stats() walks the whole event ring,
            # and sheds happen exactly when the scheduler is saturated.
            self._write(respond, self._mark_stream_terminal(
                {"id": rid, "error": "shed", "video_id": vid,
                 "queue_depth": self.engine.queue_depth},
                stream))
            if self._lifecycle is not None:
                self._lifecycle.emit("responded", (rid, vid),
                                     status="shed")

    # -- scheduler loop ----------------------------------------------------

    def _drain_and_exit(self) -> int:
        self._draining = True
        # A SECOND signal during the drain aborts it — the operator's (or
        # scheduler's) "stop now".  signal_count counts absorbed repeats;
        # the baseline is read BEFORE the drain-start announcement, so any
        # signal landing after the announcement is guaranteed to abort.
        count0 = getattr(self.handler, "signal_count", 0)

        def aborted() -> bool:
            return getattr(self.handler, "signal_count", 0) > count0

        print(f"serve: draining {self.engine.resident_count} resident(s), "
              f"{self.engine.stats()['queue_depth']} queued; a second "
              "signal aborts", file=sys.stderr)
        sys.stderr.flush()
        done, rejected = self.engine.drain(abort=aborted)
        self._respond_stream_all()     # chunks before their finals
        for comp in done:
            self._respond_completion(comp)
        self._respond_dropped_all()
        unfinished = self.engine.resident_count
        # An aborted drain abandons its residents (no partial captions) —
        # but every request still gets an answer: the abandoned residents
        # are rejected like the queued ones, so a client correlating ids
        # never waits on a caption that will not come.
        abandoned = self.engine.resident_requests()
        for req, was_resident in ([(r, False) for r in rejected]
                                  + [(r, True) for r in abandoned]):
            meta = req.meta or {}
            self._write(meta.get("respond", self._stdout_respond),
                        self._mark_stream_terminal(
                            {"id": meta.get("id"),
                             "video_id": meta.get("video_id"),
                             "error": "rejected_draining"},
                            meta.get("stream")))
            if self._lifecycle is not None:
                # The abandoned residents' terminal: the engine never
                # harvested them, but every one WAS answered — the
                # lifecycle stream records that, so the abort blackbox
                # below still accounts for every id.  (Rejected queued
                # requests already got their "dropped" from the
                # engine's drain.)
                if was_resident:
                    self._lifecycle.emit("dropped", req.request_id,
                                         reason="rejected_draining",
                                         where="drain_abort")
                self._lifecycle.emit("responded", req.request_id,
                                     status="rejected_draining")
        if aborted() and self._lifecycle is not None and self.blackbox_path:
            # The hard-abort drain is a forensic moment by definition:
            # what was in flight when the operator said "stop now".
            self._lifecycle.dump(self.blackbox_path, reason="drain_abort")
        if aborted():
            print(f"serve: drain aborted by a second signal with "
                  f"{unfinished} resident(s) unfinished; exiting "
                  f"{EXIT_SIGTERM} (sigterm_unwind)", file=sys.stderr)
            return EXIT_SIGTERM
        print(f"serve: drained {len(done)} in-flight, rejected "
              f"{len(rejected)} queued; exiting "
              f"{EXIT_PREEMPTED} (preempted/resumable)", file=sys.stderr)
        return EXIT_PREEMPTED

    def _loop(self) -> int:
        while True:
            if self.watchdog is not None:
                self.watchdog.beat()
            if self.handler is not None and self.handler.requested:
                return self._drain_and_exit()
            moved = False
            while True:
                try:
                    line, respond = self._inbox.get_nowait()
                except queue.Empty:
                    break
                self._handle_line(line, respond)
                moved = True
            comps = self.engine.step()
            # Stream chunks first: a request's incremental lines must
            # precede its final ("final": true) response.
            if self._respond_stream_all():
                moved = True
            for comp in comps:
                self._respond_completion(comp)
            if comps:
                moved = True
            if self._respond_dropped_all():
                moved = True
            if self._eof.is_set() and self.engine.idle \
                    and self._inbox.empty():
                return EXIT_OK
            if not moved and self.engine.idle:
                time.sleep(self.idle_sleep)

    # -- stdin front end ---------------------------------------------------

    def run_stdin(self, lines=None) -> int:
        """Serve JSONL requests from ``lines`` (default: sys.stdin) until
        EOF (exit 0) or a preemption signal (drain, exit 75)."""
        src = lines if lines is not None else sys.stdin

        def read():
            try:
                for line in src:
                    self._inbox.put((line, self._stdout_respond))
            finally:
                self._eof.set()

        threading.Thread(target=read, name="serve-stdin",
                         daemon=True).start()
        return self._loop()

    # -- localhost socket front end ---------------------------------------

    def run_socket(self, port: int) -> int:
        """Serve line-protocol requests on 127.0.0.1:``port`` (0 = pick an
        ephemeral port; the bound port is announced on stderr as
        ``serve: listening on 127.0.0.1:<port>``).  Runs until a
        preemption signal drains it."""
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("127.0.0.1", int(port)))
        srv.listen()
        srv.settimeout(0.2)
        bound = srv.getsockname()[1]
        self.bound_port = bound
        print(f"serve: listening on 127.0.0.1:{bound}", file=sys.stderr)
        sys.stderr.flush()
        conns: List[socket.socket] = []

        def reader(conn: socket.socket) -> None:
            lock = named_lock("serving.server.conn")

            def respond(line: str) -> None:
                with lock:
                    try:
                        conn.sendall(line.encode() + b"\n")
                    except OSError:
                        pass  # client went away; the caption is dropped

            try:
                with conn.makefile("r", encoding="utf-8",
                                   errors="replace") as f:
                    for line in f:
                        self._inbox.put((line, respond))
            except OSError:
                pass

        def accept() -> None:
            while not self._eof.is_set():
                try:
                    conn, _ = srv.accept()
                except socket.timeout:
                    continue
                except OSError:
                    return
                conns.append(conn)
                threading.Thread(target=reader, args=(conn,),
                                 name="serve-conn", daemon=True).start()

        threading.Thread(target=accept, name="serve-accept",
                         daemon=True).start()
        try:
            return self._loop()
        finally:
            self._eof.set()  # stops the accept loop
            for conn in conns:
                try:
                    conn.close()
                except OSError:
                    pass
            srv.close()
