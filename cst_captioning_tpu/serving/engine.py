"""Step-driven continuous-batching scheduler over the compiled decode path.

The offline decoders (``ops/sampling.py`` / ``ops/beam.py``) process a
fixed batch from BOS to the all-finished predicate.  Serving traffic
instead arrives one video at a time and finishes one caption at a time,
so the engine runs the SAME per-step decode machinery — ``make_decode_step``
with the PR-6 ``decode_kernel`` routing, the same greedy/beam step bodies
— but owns the batch dimension as a set of SLOTS:

- **Admission costs one encoder pass.**  A queued request is encoded at
  batch 1 and its encoder outputs + fresh decoder carry are written into
  the free slot's rows IN PLACE (``lax.dynamic_update_slice_in_dim`` at a
  traced row index — one compiled admit program serves every slot).
  Resident rows are never re-decoded.
- **Each engine step runs one compiled chunk program**: ``decode_chunk``
  decode steps over the whole slot batch as a fused ``lax.scan`` —
  the PR-3 chunk geometry, so the tuned ``decode_chunk`` applies directly.
- **A per-row finished predicate frees a slot mid-flight.**  The chunk
  returns the per-beam finished buffer; ``ops.sampling.finished_mask``
  (the same reduction the early-exit chunks use) tells the scheduler
  which slots completed, and each freed slot admits the next queued video
  before the following chunk.
- **Bit-identity.**  A resident row's caption is bit-identical to the
  offline ``eval.py`` decode of the same video (greedy and beam, either
  decode kernel): the chunk bodies are the offline bodies with the
  step-0 beam mask folded into the admission scores (an exactly-equal
  formulation — see ``_build_beam_chunk``) and the per-slot force-finish
  replacing the global step clamp.  Pinned by tests/test_serving.py.

Programs compile once per bucket through ``buckets.ProgramCache``; under
steady load the build counter must not move (SERVING.md).

Fault tolerance (RESILIENCE.md "Serving faults"):

- **Deadlines.**  A request may carry a deadline (engine default or
  per-request override).  An expired resident is evicted mid-flight —
  its slot frees through the same recycling an EOS uses, the caller gets
  an ``expired`` drop record — and a queued request whose deadline has
  lapsed, or cannot cover even ONE chunk at the current p99 chunk
  latency, is dropped instead of admitted.
- **Self-healing** (``recover=True``): a chunk dispatch that raises
  (transient device/transport error, or the injected ``serve_wedge``) or
  returns the device-scalar garble signature (``resilience/garble.py``;
  injected as ``serve_garble``) is retried as a bounded DETERMINISTIC
  re-run — recovery mode compiles its programs WITHOUT buffer donation,
  so the pre-chunk state survives the failed dispatch and a clean retry
  is bit-identical to a clean first attempt.  After ``retry_limit``
  failures the engine REBUILDS: fresh slot state, residents re-admitted
  from their requests (their already-emitted tokens persist host-side as
  the replay-verification prefix), all through the warm ``ProgramCache``
  — a rebuild that compiles anything bumps ``serve_rebuild_recompiles``,
  the contract violation counter.  ``rebuild_limit`` consecutive
  failed rebuilds raise :class:`ServingUnrecoverable`, which the front
  end maps onto the exit-code taxonomy (124) for supervised restart.
- **Admission errors** (injected as ``admit_err``) re-queue the request
  at the head and retry next step, bounded per request.

Latency floor (SERVING.md "Streaming & result cache"):

- **Streaming** (``Request.stream``): a greedy resident's NEW caption
  tokens are emitted as a :class:`StreamChunk` after every scheduler
  chunk — no new device programs, the chunks are sliced from the same
  one-batched-harvest the scheduler already fetches — so a client sees
  its first words after one chunk instead of after the whole caption.
  The concatenation of a request's stream chunks is BIT-IDENTICAL to its
  final caption (prefix consistency; an engine rebuild's replayed steps
  re-emit nothing).  Beam search cannot stream honestly — the best
  hypothesis is unknown until the backtrack — so a streamed beam request
  emits ONE terminal chunk at harvest.  Time-to-first-token and
  inter-chunk gaps feed ``serve_ttft_ms`` / ``serve_chunk_gap_ms``.
- **Exact-result cache** (``result_cache=``, serving/cache.py): submits
  are looked up by (config identity, params fingerprint, feature
  fingerprint) BEFORE admission — a hit completes instantly with the
  cached caption, paying zero encoder/decode program invocations
  (``chunk_dispatches`` and ``serve_admitted`` provably unmoved); a miss
  decodes normally and writes back at harvest.  The identity reuses the
  bench cache-config axes, so a tuned-config, kernel, or beam change
  invalidates correctly.  A cache failure (injected as
  ``serve_cache@req=N``) is absorbed: counted, health-degraded, and the
  request decodes fresh — the cache may only ever make a request
  cheaper, never wronger.

Threading: the engine is single-owner — ``submit``/``step``/``drain``
must be called from one thread (the server's scheduler loop); front-end
reader threads hand lines to that loop, never to the engine directly.
The scheduler-owned state carries ``owned_by=scheduler`` annotations and
the server's reader threads are checked against them
(cstlint:thread-ownership); deadlines run on ``time.monotonic`` — the
``clock`` default the monotonic-deadline rule holds the rest of the
tree to.
"""

from __future__ import annotations

import logging
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..ops.beam import NEG_INF, _expand_to_beams, _reorder_beams
from ..ops.sampling import finished_mask, make_decode_step
from ..resilience.faults import InjectedFault
from ..resilience.garble import GarbledChunk, garbled_decode_slots, \
    health_status
from ..telemetry.spans import trace_span
from .buckets import DEFAULT_BUCKETS, ProgramCache, config_key, pick_bucket
from .cache import ResultCache, feature_fingerprint, params_fingerprint

log = logging.getLogger("cst_captioning_tpu.serving.engine")

#: Counters the engine owns (declared at 0 so snapshots distinguish
#: "armed, nothing happened" from "feature absent" — registry.declare).
COUNTERS = ("serve_requests", "serve_admitted", "serve_completed",
            "serve_shed", "serve_rejected_drain", "serve_compiles",
            # Fault-tolerance audit trail (RESILIENCE.md "Serving faults").
            "serve_expired", "serve_deadline_shed", "serve_chunk_retries",
            "serve_rebuilds", "serve_rebuild_recompiles",
            "serve_garble_detected", "serve_wedge_detected",
            "serve_admit_errors", "serve_replay_divergence",
            "serve_slow_chunks",
            # Latency floor (SERVING.md "Streaming & result cache").
            "serve_stream_chunks", "serve_cache_hits", "serve_cache_misses",
            "serve_cache_evictions", "serve_cache_bypass",
            "serve_cache_errors")


class ServingUnrecoverable(RuntimeError):
    """The self-healing ladder is exhausted: retries failed, rebuilds
    failed.  The front end maps this onto ``exitcodes.EXIT_WEDGE`` (124)
    so a ``scale_chain``-style supervisor restarts the server once the
    environment heals — in-process recovery has proven impossible."""


@dataclass
class Request:
    """One queued video: opaque id + per-modality ``(T, D)`` features."""

    request_id: Any
    feats: List[np.ndarray]
    arrival: float = 0.0
    meta: Optional[dict] = None
    #: Submission ordinal (0-based) — the ``@req=N`` fault-plan axis.
    index: int = -1
    #: Absolute engine-clock deadline; None = no TTL.
    deadline: Optional[float] = None
    admit_attempts: int = 0
    #: Emit per-chunk StreamChunk records ({"op": "stream"} traffic).
    stream: bool = False
    #: The request's explicit per-request cache bypass — kept on the
    #: request so a fleet requeue honors it on the new engine too.
    no_cache: bool = False
    #: Result-cache write-back key (None = bypassed / cache disabled /
    #: lookup faulted); set at submit, consumed at harvest.
    cache_key: Optional[tuple] = None


@dataclass
class Completion:
    """One finished caption, 0-terminated in the label convention."""

    request_id: Any
    tokens: np.ndarray            # (max_len,) int32
    slot: int
    admit_at: float
    done_at: float
    latency_s: float
    decode_steps: int
    meta: Optional[dict] = None
    #: Streaming bookkeeping (0 / None on non-streamed requests): chunks
    #: emitted before this completion, and time-to-first-token seconds.
    stream_chunks: int = 0
    ttft_s: Optional[float] = None
    #: True when the caption came from the exact-result cache (zero
    #: encoder/decode invocations paid).
    cache_hit: bool = False


@dataclass
class StreamChunk:
    """One incremental slice of a streamed caption.

    ``tokens`` are the NEW caption tokens this chunk produced (EOS/pad
    trimmed; possibly the whole caption for beam/cache-hit terminals).
    Prefix consistency: concatenating a request's chunks in ``seq`` order
    reproduces the final caption's tokens bit for bit — pinned by
    tests/test_serving_stream.py and end-to-end by the serving bench.
    """

    request_id: Any
    seq: int
    tokens: np.ndarray
    meta: Optional[dict] = None


@dataclass
class Dropped:
    """A request the scheduler gave up on (never a silent loss).

    ``reason`` is ``"expired"`` (deadline lapsed — ``where`` says whether
    it was still queued or already resident), ``"deadline_shed"`` (queued,
    deadline cannot cover one p99 chunk — conservative by design), or
    ``"admit_failed"`` (admission errored past its retry bound)."""

    request_id: Any
    reason: str
    where: str
    deadline: Optional[float] = None
    meta: Optional[dict] = None


@dataclass
class _Resident:
    request: Request
    slot: int
    admit_at: float
    steps: int = 0
    toks: List[np.ndarray] = field(default_factory=list)
    pars: List[np.ndarray] = field(default_factory=list)
    #: Tokens emitted before an engine rebuild — the persisted prefix the
    #: deterministic replay is verified against at harvest.
    prefix: Optional[np.ndarray] = None
    #: Streaming state: caption tokens already emitted as chunks (a
    #: rebuild's replayed steps re-derive but never re-emit them), chunk
    #: ordinal, and emission clocks for the TTFT / inter-chunk-gap
    #: metrics.
    streamed: int = 0
    chunks_emitted: int = 0
    first_emit: Optional[float] = None
    last_emit: Optional[float] = None


class ServingEngine:
    """Continuous batching over the compiled greedy/beam decode.

    ``variables`` is the flax variable dict (``{"params": params}``);
    ``feat_shapes`` the per-modality ``(T, D)`` geometry every request
    must match (one compiled admit program per bucket — a request with a
    different feature shape is a config error, not a recompile).
    ``queue_limit`` bounds the submit queue (0/None = unbounded, the
    offline-parity mode); ``clock`` is injectable for deterministic
    scheduler tests.

    Fault-tolerance knobs: ``deadline_ms`` is the default request TTL
    (0 = none; a per-request ``deadline_ms`` in ``submit`` overrides);
    ``fault_plan`` threads the chaos plan's ``@req=N`` kinds in;
    ``recover`` arms the self-healing ladder (retry -> rebuild -> raise;
    it trades the chunk/admit programs' buffer donation for a re-runnable
    pre-chunk state); ``retry_limit``/``rebuild_limit`` bound it;
    ``step_budget_ms`` flags slow chunks (0 = off) into the health plane;
    ``degraded_window_s`` is how long after a recovery event ``health()``
    reports ``degraded``.

    ``result_cache`` (serving/cache.py, shareable across engines) arms
    the exact-result cache in front of admission: a hit completes without
    touching the encoder or decode programs.  None = every request
    decodes (the historical behavior; nothing is counted as bypass).
    """

    def __init__(self, model, variables, feat_shapes: Sequence[Tuple[int, int]],
                 *, max_len: int, beam_size: int = 1, length_norm: float = 0.0,
                 decode_chunk: int = 8,
                 bucket_sizes: Sequence[int] = DEFAULT_BUCKETS,
                 queue_limit: Optional[int] = 64,
                 deadline_ms: float = 0.0,
                 fault_plan=None,
                 recover: bool = False,
                 retry_limit: int = 2,
                 rebuild_limit: int = 2,
                 step_budget_ms: float = 0.0,
                 degraded_window_s: float = 60.0,
                 result_cache: Optional[ResultCache] = None,
                 program_cache: Optional[ProgramCache] = None,
                 registry=None, tracer=None, lifecycle=None,
                 clock: Callable[[], float] = time.monotonic):
        if getattr(model, "decoder_type", "lstm") != "lstm":
            raise ValueError(
                "serving requires per-row decoder state; the transformer "
                "carry holds a batch-shared position counter, so a slot "
                "admitted mid-flight cannot start at position 0 "
                "(SERVING.md 'Model support')")
        self.model = model
        self._variables = variables
        self._feat_shapes = tuple(tuple(int(x) for x in s)
                                  for s in feat_shapes)
        self.max_len = int(max_len)
        self.beam_size = max(1, int(beam_size))
        self.length_norm = float(length_norm)
        chunk = int(decode_chunk)
        # chunk 0 (legacy full-length scan) has no mid-caption boundary to
        # recycle slots at; run it as one max_len-sized chunk (opts.py
        # warns once when this combination is requested).
        self.chunk = chunk if 0 < chunk < self.max_len else self.max_len
        self.buckets = tuple(sorted(set(int(b) for b in bucket_sizes)))
        if not self.buckets or self.buckets[0] < 1:
            raise ValueError(f"bad bucket_sizes {bucket_sizes!r}")
        self.queue_limit = int(queue_limit or 0)
        self.deadline_ms = float(deadline_ms or 0.0)
        self._plan = fault_plan
        self.recover = bool(recover)
        self.retry_limit = max(0, int(retry_limit))
        self.rebuild_limit = max(0, int(rebuild_limit))
        self.step_budget_ms = float(step_budget_ms or 0.0)
        self.degraded_window_s = float(degraded_window_s)
        self._registry = registry
        self._tracer = tracer
        # Request-lifecycle tracing plane (telemetry/lifecycle.py): a
        # LifecycleTracer, a fleet replica's labeled view of one, or
        # None (the default — every hook below is one is-None check).
        self._lifecycle = lifecycle
        self.clock = clock

        # ``program_cache`` may be SHARED across engines (the fleet
        # router's replicas, and a replica's restarted engine): keys
        # carry the full configuration identity, so same-config engines
        # reuse each other's programs — a replica restart re-warms with
        # ZERO new builds (SERVING.md "Fleet").  Explicit None check: a
        # fresh shared cache is empty and __len__-falsy.
        self._cache = (ProgramCache(registry) if program_cache is None
                       else program_cache)
        # Single-owner scheduler state (the module-docstring threading
        # contract): if this file ever grows a thread whose target
        # touches these, cstlint:thread-ownership fires.
        self._queue: deque = deque()  # cstlint: owned_by=scheduler
        self._residents: List[Optional[_Resident]] = []  # cstlint: owned_by=scheduler
        self._slots_n = 0
        self._dev: Optional[Dict[str, Any]] = None  # cstlint: owned_by=scheduler
        self._latencies: deque = deque(maxlen=1024)
        self._chunk_wall: deque = deque(maxlen=128)
        self._dropped: List[Dropped] = []
        # Latency-floor state (all scheduler-owned, like the queue).
        self._stream_chunks: List[StreamChunk] = []  # cstlint: owned_by=scheduler
        self._hits: List[Completion] = []  # cstlint: owned_by=scheduler
        self._ttft: deque = deque(maxlen=1024)
        self._gaps: deque = deque(maxlen=4096)
        self._stream_emitted = 0
        self._chunk_dispatches = 0
        self._result_cache = result_cache
        self._cache_hits = 0
        self._cache_misses = 0
        self._cache_evictions = 0
        self._cache_bypass = 0
        self._cache_errors = 0
        if result_cache is not None:
            # Paid once: a shared cache must never replay captions across
            # different weights or decode configurations (cache.py).
            # Built from config_key directly, NOT _config_key: the recover
            # mode's "-recover" program suffix compiles the same math, so
            # recover-on and recover-off engines share result entries.
            self._params_fp = params_fingerprint(variables)
            self._result_identity = config_key(
                kind="result", bucket=0, beam_size=self.beam_size,
                max_len=self.max_len, decode_chunk=self.chunk,
                length_norm=self.length_norm,
                decode_kernel=getattr(model, "decode_kernel", "reference"),
                scan_unroll=getattr(model, "scan_unroll", 1),
                feat_shapes=self._feat_shapes,
                dtype=str(getattr(model, "dtype", jnp.float32)))
        self._submitted = 0
        self._completed = 0
        self._shed = 0
        self._rejected = 0
        self._expired = 0
        self._deadline_shed = 0
        self._chunk_retries = 0
        self._rebuilds = 0
        self._rebuild_recompiles = 0
        self._garbles = 0
        self._wedges = 0
        self._admit_errors = 0
        self._replay_divergence = 0
        self._last_recovery_at: Optional[float] = None
        self._avals = self._request_avals()
        for leaf in jax.tree_util.tree_leaves(self._avals[3]):
            if getattr(leaf, "ndim", 0) < 1 or leaf.shape[0] != self.beam_size:
                raise ValueError(
                    "serving requires every decoder-carry leaf to be "
                    f"per-row; got leaf shape {getattr(leaf, 'shape', ())}")
        if registry is not None:
            registry.declare(*COUNTERS)

    # -- shapes and programs -----------------------------------------------

    def _request_avals(self):
        """Shapes/dtypes of one request's encoder outputs + carry (batch
        ``beam_size`` rows), via ``eval_shape`` — no device work."""
        k = self.beam_size
        feats = [jax.ShapeDtypeStruct((1,) + s, jnp.float32)
                 for s in self._feat_shapes]

        def enc(variables, feats):
            memory, proj_mem, pooled = self.model.apply(
                variables, feats, method="encode")
            if k > 1:
                memory, proj_mem, pooled = _expand_to_beams(
                    (memory, proj_mem, pooled), k, 1)
            carry = self.model.apply(variables, pooled, self.max_len,
                                     method="init_carry")
            return memory, proj_mem, pooled, carry

        return jax.eval_shape(enc, self._variables, feats)

    def _config_key(self, slots: int, kind: str) -> tuple:
        # Recovery mode compiles the SAME math without buffer donation
        # (the pre-chunk state must survive a failed dispatch), so the two
        # variants could compile differently and must never share a key.
        if self.recover:
            kind = kind + "-recover"
        return config_key(
            kind=kind, bucket=slots, beam_size=self.beam_size,
            max_len=self.max_len, decode_chunk=self.chunk,
            length_norm=self.length_norm,
            decode_kernel=getattr(self.model, "decode_kernel", "reference"),
            scan_unroll=getattr(self.model, "scan_unroll", 1),
            feat_shapes=self._feat_shapes,
            dtype=str(getattr(self.model, "dtype", jnp.float32)),
        )

    def _donate(self) -> tuple:
        """Donation spec for the state argument: donated on the legacy
        fast path, kept alive under ``recover`` so a chunk/admit that
        raises or garbles leaves a valid pre-dispatch state to re-run."""
        return () if self.recover else (1,)

    def _programs(self, slots: int) -> Dict[str, Callable]:
        build = (self._build_beam_programs if self.beam_size > 1
                 else self._build_greedy_programs)
        return self._cache.get(self._config_key(slots, "programs"),
                               lambda: build(slots))

    def _init_state(self, slots: int) -> Dict[str, Any]:
        """All-slots-empty device state: finished=True / steps=max_len so
        empty rows are provable no-ops until an admission claims them."""
        mem_a, proj_a, pooled_a, carry_a = self._avals
        k = self.beam_size
        rows = slots * k

        def z(a):
            return jnp.zeros((rows,) + tuple(a.shape[1:]), a.dtype)

        state = {
            "carry": jax.tree_util.tree_map(z, carry_a),
            "memory": z(mem_a), "proj_mem": z(proj_a), "pooled": z(pooled_a),
            "steps": jnp.full((slots,), self.max_len, jnp.int32),
        }
        if k == 1:
            state["prev"] = jnp.zeros((slots,), jnp.int32)
            state["finished"] = jnp.ones((slots,), bool)
        else:
            state["prev"] = jnp.zeros((slots, k), jnp.int32)
            state["finished"] = jnp.ones((slots, k), bool)
            state["scores"] = jnp.zeros((slots, k), jnp.float32)
            state["lengths"] = jnp.zeros((slots, k), jnp.int32)
        return state

    def _build_admit(self, slots: int) -> Callable:
        """One compiled program: encode one request (batch 1), expand to
        beam rows, write encodings + fresh carry + reset per-slot columns
        into ``row``'s rows of the (legacy path: donated) state."""
        k = self.beam_size
        max_len = self.max_len
        model = self.model

        def fn(variables, state, feats, row):
            memory, proj_mem, pooled = model.apply(variables, feats,
                                                   method="encode")
            if k > 1:
                memory, proj_mem, pooled = _expand_to_beams(
                    (memory, proj_mem, pooled), k, 1)
            carry = model.apply(variables, pooled, max_len,
                                method="init_carry")
            r = row * k

            def wr(buf, val):
                return jax.lax.dynamic_update_slice_in_dim(
                    buf, val.astype(buf.dtype), r, axis=0)

            def wrow(buf, val):
                return jax.lax.dynamic_update_slice_in_dim(
                    buf, jnp.asarray(val, buf.dtype)[None], row, axis=0)

            new = dict(state)
            new["carry"] = jax.tree_util.tree_map(wr, state["carry"], carry)
            new["memory"] = wr(state["memory"], memory)
            new["proj_mem"] = wr(state["proj_mem"], proj_mem)
            new["pooled"] = wr(state["pooled"], pooled)
            new["steps"] = wrow(state["steps"], 0)
            if k == 1:
                new["prev"] = wrow(state["prev"], 0)
                new["finished"] = wrow(state["finished"], False)
            else:
                new["prev"] = wrow(state["prev"], jnp.zeros((k,), jnp.int32))
                new["finished"] = wrow(state["finished"],
                                       jnp.zeros((k,), bool))
                # Step-0 beam mask as ADMISSION SCORES: only beam 0 live.
                # (0 + logp) + NEG_INF == NEG_INF + logp bit-exactly, so
                # this reproduces ops/beam.py's t==0 init_mask without a
                # per-slot step counter inside the chunk body.
                new["scores"] = wrow(
                    state["scores"],
                    jnp.full((k,), NEG_INF, jnp.float32).at[0].set(0.0))
                new["lengths"] = wrow(state["lengths"],
                                      jnp.zeros((k,), jnp.int32))
            return new

        return jax.jit(fn, donate_argnums=self._donate())

    def _build_greedy_programs(self, slots: int) -> Dict[str, Callable]:
        chunk = self.chunk
        max_len = self.max_len
        model = self.model
        unroll = getattr(model, "scan_unroll", 1)

        def chunk_fn(variables, state):
            step = make_decode_step(model, variables, state["memory"],
                                    state["proj_mem"], state["pooled"])

            # The offline greedy body (ops.sampling.sample_tokens,
            # greedy=True) minus the unused logprob bookkeeping, plus a
            # per-slot force-finish at max_len (a no-op while
            # steps < max_len, so resident rows compute bit-identically).
            def body(s, _):
                carry, prev, finished, steps = s
                finished = finished | (steps >= max_len)
                carry, logits = step(carry, prev)
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                emit = jnp.where(finished, 0, nxt)
                finished = finished | (emit == 0)
                return (carry, emit, finished, steps + 1), emit

            (carry, prev, finished, steps), toks = jax.lax.scan(
                body,
                (state["carry"], state["prev"], state["finished"],
                 state["steps"]),
                None, length=chunk, unroll=unroll)
            new = dict(state, carry=carry, prev=prev, finished=finished,
                       steps=steps)
            return new, toks.T                      # (slots, chunk)

        return {"admit": self._build_admit(slots),
                "chunk": jax.jit(chunk_fn, donate_argnums=self._donate())}

    def _build_beam_programs(self, slots: int) -> Dict[str, Callable]:
        chunk = self.chunk
        max_len = self.max_len
        model = self.model
        k = self.beam_size

        def chunk_fn(variables, state):
            step = make_decode_step(model, variables, state["memory"],
                                    state["proj_mem"], state["pooled"])

            # ops.beam.beam_search_tokens' body with the t==0 init mask
            # handled by the admission scores (see _build_admit) and the
            # body_clamped overrun guard made per-slot via ``steps``.
            def body(s, _):
                carry, prev, scores, finished, lengths, steps = s
                finished = finished | (steps >= max_len)[:, None]
                carry, logits = step(carry, prev.reshape(-1))
                vocab = logits.shape[-1]
                logp = jax.nn.log_softmax(logits, axis=-1).reshape(
                    slots, k, vocab)
                eos_only = jnp.full((vocab,), NEG_INF).at[0].set(0.0)
                logp = jnp.where(finished[:, :, None],
                                 eos_only[None, None, :], logp)
                total = (scores[:, :, None] + logp).reshape(slots, k * vocab)
                new_scores, flat = jax.lax.top_k(total, k)
                parent = flat // vocab
                token = (flat % vocab).astype(jnp.int32)
                carry = _reorder_beams(carry, parent, slots, k)
                was = jnp.take_along_axis(finished, parent, axis=1)
                lengths = jnp.take_along_axis(lengths, parent, axis=1)
                lengths = lengths + jnp.where(was, 0, 1)
                finished = was | (token == 0)
                return (carry, token, new_scores, finished, lengths,
                        steps + 1), (token, parent)

            (carry, prev, scores, finished, lengths, steps), (toks, pars) = \
                jax.lax.scan(
                    body,
                    (state["carry"], state["prev"], state["scores"],
                     state["finished"], state["lengths"], state["steps"]),
                    None, length=chunk)
            new = dict(state, carry=carry, prev=prev, scores=scores,
                       finished=finished, lengths=lengths, steps=steps)
            # (chunk, slots, k) -> (slots, chunk, k) for per-slot harvest.
            return new, (toks.transpose(1, 0, 2), pars.transpose(1, 0, 2))

        return {"admit": self._build_admit(slots),
                "chunk": jax.jit(chunk_fn, donate_argnums=self._donate())}

    # -- queue -------------------------------------------------------------

    def submit(self, request_id, feats: Sequence[np.ndarray],
               meta: Optional[dict] = None,
               deadline_ms: Optional[float] = None,
               stream: bool = False,
               no_cache: bool = False,
               _requeued: bool = False,
               _arrival: Optional[float] = None) -> bool:
        """Queue one request.  Returns False (sheds) when the bounded
        queue is full — the engine's backpressure signal; the front end
        turns it into an explicit reject response.  ``deadline_ms``
        overrides the engine's default TTL for this request (None = use
        the default; 0 = explicitly no deadline).  ``stream`` emits
        per-chunk :class:`StreamChunk` records (``pop_stream_chunks``);
        ``no_cache`` skips the exact-result cache for this request
        (counted as ``serve_cache_bypass`` — the drill's miss twin).
        ``_requeued``/``_arrival`` are the fleet ``requeue`` internals:
        the lifecycle stream records a re-entry instead of a fresh
        intake, and the request keeps its ORIGINAL arrival clock so its
        latency never under-reports across a replica restart."""
        self._submitted += 1
        index = self._submitted - 1        # submission ordinal (@req=N)
        self._inc("serve_requests")
        feats = [np.asarray(f, np.float32) for f in feats]
        shapes = tuple(f.shape for f in feats)
        if shapes != self._feat_shapes:
            raise ValueError(
                f"request {request_id!r} feature shapes {shapes} do not "
                f"match the engine's compiled geometry {self._feat_shapes}")
        arrival = self.clock() if _arrival is None else float(_arrival)
        if self._lifecycle is not None:
            # "received" is stamped at the arrival clock so the event
            # stream reconciles with the engine's latency bookkeeping;
            # a re-entry after a replica kill/rotation is "requeued",
            # stamped NOW (its arrival is the original submission's).
            # A supervising front end's cross-process trace context
            # (meta["trace"], SERVING.md "Wire format") is echoed as
            # `trace_id` so fleet_trace.py can join this process's
            # async track to the supervisor's.
            tr = (meta or {}).get("trace")
            attrs = ({"trace_id": tr.get("id")}
                     if isinstance(tr, dict) else {})
            if _requeued:
                self._lifecycle.emit("requeued", request_id, **attrs)
            else:
                self._lifecycle.emit("received", request_id, ts=arrival,
                                     **attrs)
        # Exact-result cache, IN FRONT of admission (and of the bounded
        # queue: a hit consumes no slot, no queue depth, no decode — it
        # would be self-defeating to shed one).
        cache_key = None
        if self._result_cache is not None:
            if no_cache:
                self._cache_bypass += 1
                self._inc("serve_cache_bypass")
            else:
                row = None
                try:
                    if self._plan is not None and \
                            self._plan.fire("serve_cache", index):
                        raise InjectedFault(
                            f"injected serve_cache at request {index}")
                    cache_key = (self._result_identity, self._params_fp,
                                 feature_fingerprint(feats))
                    row = self._result_cache.get(cache_key)
                except Exception as e:
                    # A broken cache may cost a decode, never a request:
                    # fall through to the miss path (no write-back — the
                    # cache is suspect) and surface the event in health.
                    cache_key = None
                    self._cache_errors += 1
                    self._inc("serve_cache_errors")
                    self._note_recovery_event()
                    log.warning("result-cache lookup failed for request "
                                "%r (%s); decoding fresh", request_id, e)
                if row is not None:
                    self._cache_hits += 1
                    self._inc("serve_cache_hits")
                    self._complete_hit(request_id, row, arrival,
                                       stream=stream, meta=meta)
                    self._update_gauges()
                    return True
        if self.queue_limit and len(self._queue) >= self.queue_limit:
            self._shed += 1
            self._inc("serve_shed")
            if self._lifecycle is not None:
                # Terminal on a standalone engine; a fleet replica's
                # labeled view drops this — the router may still place
                # the request elsewhere and owns the fleet-edge shed.
                self._lifecycle.emit("shed", request_id, where="queue")
            self._update_gauges()
            return False
        # NOTE: a lookup that found nothing is NOT counted a miss here —
        # the request may yet shed, expire pre-admission, be rejected at
        # drain, or exhaust its admit retries without ever decoding.
        # The miss is counted at _harvest, beside the write-back, so
        # misses == write-backs exactly (the hit-rate arithmetic
        # serve_report renders; test-pinned).
        ttl = self.deadline_ms if deadline_ms is None else float(deadline_ms)
        deadline = (self.clock() + ttl / 1e3) if ttl and ttl > 0 else None
        self._queue.append(Request(request_id, feats,
                                   arrival=arrival, meta=meta,
                                   index=index, deadline=deadline,
                                   stream=bool(stream),
                                   no_cache=bool(no_cache),
                                   cache_key=cache_key))
        if self._lifecycle is not None:
            self._lifecycle.emit("queued", request_id,
                                 depth=len(self._queue))
        self._update_gauges()
        return True

    def _complete_hit(self, request_id, row: np.ndarray, arrival: float,
                      *, stream: bool, meta: Optional[dict]) -> None:
        """A cache hit completes at submit time: zero admissions, zero
        chunk dispatches (asserted by the cache tests against
        ``serve_admitted`` / ``chunk_dispatches``).  Streamed hits emit
        their whole caption as one terminal chunk first."""
        now = self.clock()
        chunks = 0
        ttft = None
        if stream:
            trimmed = _trim_eos(row)
            if trimmed.size:
                self._stream_chunks.append(
                    StreamChunk(request_id, 0, trimmed, meta=meta))
                self._stream_emitted += 1
                self._inc("serve_stream_chunks")
                chunks = 1
                ttft = now - arrival
                self._ttft.append(ttft)
                self._observe("serve_ttft_ms", ttft * 1e3)
        comp = Completion(
            request_id=request_id, tokens=row, slot=-1,
            admit_at=now, done_at=now, latency_s=now - arrival,
            decode_steps=0, meta=meta, stream_chunks=chunks,
            ttft_s=ttft, cache_hit=True)
        self._hits.append(comp)
        self._completed += 1
        self._inc("serve_completed")
        self._latencies.append(comp.latency_s)
        self._observe("serve_request_latency_ms", comp.latency_s * 1e3)
        if self._lifecycle is not None:
            self._lifecycle.emit("cache_hit", request_id, ts=now)
            self._lifecycle.emit("completed", request_id, ts=now,
                                 latency_ms=round(comp.latency_s * 1e3, 3),
                                 cached=True)

    @property
    def idle(self) -> bool:
        return (not self._queue and not any(self._residents)
                and not self._hits)

    @property
    def program_cache(self) -> ProgramCache:
        """The (possibly shared) compile-once cache — read-only surface
        for the flight recorder's ProgramCache-state provider."""
        return self._cache

    @property
    def resident_count(self) -> int:
        return sum(1 for r in self._residents if r is not None)

    def resident_requests(self) -> List[Request]:
        """The requests currently holding slots — after an aborted drain,
        these are the abandoned ones the front end still owes an answer."""
        return [r.request for r in self._residents if r is not None]

    def pop_dropped(self) -> List[Dropped]:
        """Drain the drop records (expired / deadline-shed / admit-failed)
        accumulated since the last call; the front end answers each with
        an explicit per-request error response."""
        out, self._dropped = self._dropped, []
        return out

    def pop_stream_chunks(self) -> List[StreamChunk]:
        """Drain the incremental caption chunks accumulated since the
        last call (streamed requests only); the front end writes each as
        a ``"stream": true`` JSONL line BEFORE the final response."""
        out, self._stream_chunks = self._stream_chunks, []
        return out

    # -- fleet surface (serving/fleet.py) ----------------------------------

    @property
    def queue_depth(self) -> int:
        return len(self._queue)

    def min_service_s(self) -> Optional[float]:
        """This engine's shed floor (one p99 chunk; None until the
        window is honest) — the fleet router reads every replica's floor
        for the fleet-edge "provably unmeetable everywhere" shed."""
        return self._min_service_s()

    def degraded(self) -> bool:
        """Cheap health-tier read (the boolean behind ``health()``'s
        ``degraded``) for the router's per-submit candidate ranking —
        no counter dicts built on the routing hot path."""
        return (self._last_recovery_at is not None
                and (self.clock() - self._last_recovery_at)
                < self.degraded_window_s)

    def latency_window_s(self) -> List[float]:
        """Raw end-to-end latencies (seconds) in the retained window;
        the fleet router concatenates replicas' windows so fleet p50/p99
        are computed over samples, never averaged percentiles."""
        return list(self._latencies)

    def stream_windows_s(self) -> Tuple[List[float], List[float]]:
        """Raw (TTFT, inter-chunk-gap) second windows — same
        fleet-aggregation contract as ``latency_window_s``."""
        return list(self._ttft), list(self._gaps)

    def evacuate(self, include_residents: bool = True
                 ) -> Tuple[List[Completion], List[Request]]:
        """Strip this engine of everything it still owes: pending
        cache-hit completions (already finished — returned for the
        caller's response flow) and the queued requests, plus the
        resident ones when ``include_residents`` (returned for
        re-routing; their device rows are abandoned — the re-decode on
        another engine is the same deterministic program on the same
        inputs, so the caption is unchanged).  The fleet router calls
        this with residents on a replica it kills/restarts, and without
        on one it rotates (residents finish in place, queued work moves
        so it never waits out the rotation)."""
        done = list(self._hits)
        self._hits.clear()
        reqs: List[Request] = list(self._queue)
        self._queue.clear()
        if include_residents:
            for slot, res in enumerate(self._residents):
                if res is not None:
                    reqs.append(res.request)
                    self._residents[slot] = None
        self._update_gauges()
        return done, reqs

    def requeue(self, req: Request) -> bool:
        """Adopt a request evacuated from another engine (the fleet
        restart/rotation path): re-enters this engine's admission queue
        as a fresh local submission (new ``@req`` ordinal — per-engine
        fault plans key on local ordinals) while PRESERVING the original
        arrival clock, so the request's latency keeps counting from its
        first submission, and the remaining absolute deadline (an
        already-lapsed one expires at admission instead of silently
        losing its TTL)."""
        if req.deadline is not None:
            remaining_ms = max((req.deadline - self.clock()) * 1e3, 1e-3)
        else:
            remaining_ms = 0.0
        # ``_arrival`` carries the ORIGINAL submission clock straight
        # into the new Request (and into a shared-cache hit's latency),
        # so a request that waited through a replica restart never
        # under-reports; ``_requeued`` makes the lifecycle stream record
        # a re-entry instead of a fresh intake.
        return self.submit(req.request_id, req.feats, meta=req.meta,
                           deadline_ms=remaining_ms, stream=req.stream,
                           no_cache=req.no_cache,
                           _requeued=True, _arrival=req.arrival)

    # -- deadlines ---------------------------------------------------------

    def _drop(self, req: Request, reason: str, where: str) -> None:
        self._dropped.append(Dropped(req.request_id, reason, where,
                                     deadline=req.deadline, meta=req.meta))
        if self._lifecycle is not None:
            self._lifecycle.emit("dropped", req.request_id,
                                 reason=reason, where=where)
        if reason == "expired":
            self._expired += 1
            self._inc("serve_expired")
        elif reason == "deadline_shed":
            self._deadline_shed += 1
            self._inc("serve_deadline_shed")

    def _min_service_s(self) -> Optional[float]:
        """One p99 chunk's worth of wall time — the shed floor: a queued
        request needs at least one chunk, costed at the tail latency so
        the estimate is deliberately CONSERVATIVE (a latency hiccup in
        the 128-chunk window sheds early for a while rather than
        admitting work likely to expire mid-flight and waste decode
        steps).  None until enough samples exist to call a percentile
        honest."""
        if len(self._chunk_wall) < 4:
            return None
        return float(np.percentile(np.asarray(self._chunk_wall), 99))

    def _expire_residents(self, now: float) -> None:
        """TTL eviction mid-flight: a resident past its deadline frees
        its slot immediately (the next admission overwrites the rows, the
        same in-place write an EOS-freed slot gets)."""
        for slot, res in enumerate(self._residents):
            if res is None or res.request.deadline is None:
                continue
            if now >= res.request.deadline:
                self._residents[slot] = None
                self._drop(res.request, "expired", "resident")
                log.info("request %r expired mid-flight (slot %d, "
                         "%d decode steps paid)", res.request.request_id,
                         slot, res.steps)

    def _next_admittable(self) -> Optional[Request]:
        """Pop the next queued request worth admitting: drop outright-
        expired ones and shed those whose remaining deadline cannot cover
        even one chunk at the current p99 chunk latency (conservative by
        design — see ``_min_service_s``)."""
        now = self.clock()
        min_s = self._min_service_s()
        while self._queue:
            req = self._queue.popleft()
            if req.deadline is not None:
                if now >= req.deadline:
                    self._drop(req, "expired", "queued")
                    continue
                if min_s is not None and (req.deadline - now) < min_s:
                    self._drop(req, "deadline_shed", "queued")
                    continue
            return req
        return None

    # -- scheduling --------------------------------------------------------

    def _ensure_bucket(self) -> None:
        needed = self.resident_count + len(self._queue)
        if self._dev is None:
            slots = pick_bucket(self.buckets, max(needed, 1))
            self._dev = self._init_state(slots)
            self._slots_n = slots
            self._residents = [None] * slots
            return
        if needed <= self._slots_n:
            return
        target = pick_bucket(self.buckets, needed)
        if target > self._slots_n:
            self._grow(target)

    def _grow(self, new_slots: int) -> None:
        """Migrate to a larger bucket: pad every buffer with empty-slot
        rows (finished=True / steps=max_len no-ops); residents keep their
        slot indices, so nothing mid-caption is disturbed."""
        k = self.beam_size
        extra = new_slots - self._slots_n
        old = self._dev

        def pad(x, n, fill=0):
            tail = jnp.full((n,) + x.shape[1:], fill, x.dtype)
            return jnp.concatenate([x, tail], axis=0)

        new = {
            "carry": jax.tree_util.tree_map(
                lambda x: pad(x, extra * k), old["carry"]),
            "memory": pad(old["memory"], extra * k),
            "proj_mem": pad(old["proj_mem"], extra * k),
            "pooled": pad(old["pooled"], extra * k),
            "prev": pad(old["prev"], extra),
            "finished": pad(old["finished"], extra, fill=True),
            "steps": pad(old["steps"], extra, fill=self.max_len),
        }
        if k > 1:
            new["scores"] = pad(old["scores"], extra)
            new["lengths"] = pad(old["lengths"], extra)
        self._dev = new
        self._residents.extend([None] * extra)
        self._slots_n = new_slots

    def _admit_pending(self) -> None:
        if not self._queue:
            return
        programs = self._programs(self._slots_n)
        for slot, res in enumerate(self._residents):
            if res is not None:
                continue
            req = self._next_admittable()
            if req is None:
                break
            try:
                if self._plan is not None and \
                        self._plan.fire("admit_err", req.index):
                    raise InjectedFault(
                        f"injected admit_err at request {req.index}")
                with trace_span(self._tracer, "serve.admit"):
                    t0 = time.perf_counter()
                    feats = [jnp.asarray(f[None]) for f in req.feats]
                    self._dev = programs["admit"](self._variables, self._dev,
                                                  feats, slot)
                    admit_ms = (time.perf_counter() - t0) * 1e3
            except Exception as e:
                # A transient admission failure must neither kill the
                # scheduler loop nor silently drop the request.  With the
                # state donated (legacy path) a REAL mid-program failure
                # leaves it unusable, so only injected faults (raised
                # before the dispatch) are absorbed there.
                if not self.recover and not isinstance(e, InjectedFault):
                    raise
                self._inc("serve_admit_errors")
                self._admit_errors += 1
                self._note_recovery_event()
                req.admit_attempts += 1
                if req.admit_attempts > self.retry_limit:
                    self._drop(req, "admit_failed", "admit")
                    log.warning("admission of request %r failed %d times "
                                "(%s); dropping", req.request_id,
                                req.admit_attempts, e)
                else:
                    self._queue.appendleft(req)  # FIFO head: retried next
                    log.warning("admission of request %r failed (%s); "
                                "retry %d/%d at the next scheduler step",
                                req.request_id, e, req.admit_attempts,
                                self.retry_limit)
                break
            self._residents[slot] = _Resident(req, slot,
                                              admit_at=self.clock())
            self._inc("serve_admitted")
            self._observe("serve_admit_ms", admit_ms)
            if self._lifecycle is not None:
                # admit_ms rides on the event so attribution can carve
                # the encoder pass out of the queue-wait interval.
                self._lifecycle.emit("admitted", req.request_id,
                                     slot=slot,
                                     admit_ms=round(admit_ms, 3))

    def _dispatch_chunk(self, programs) -> Tuple[np.ndarray, np.ndarray,
                                                 Optional[np.ndarray]]:
        """Run ONE chunk program and fetch (fin, toks, pars), with the
        fault hooks and the garble detector in the fetch path.  Commits
        ``self._dev`` only on a clean dispatch, so under ``recover`` a
        raise leaves the pre-chunk state valid for a deterministic
        re-run."""
        k = self.beam_size
        live = [(slot, res) for slot, res in enumerate(self._residents)
                if res is not None]
        if self._plan is not None:
            for slot, res in live:
                if self._plan.fire("serve_wedge", res.request.index):
                    raise InjectedFault(
                        f"injected serve_wedge while request "
                        f"{res.request.index} resident in slot {slot}")
        with trace_span(self._tracer, "serve.decode_chunk"):
            t0 = time.perf_counter()
            self._chunk_dispatches += 1
            new_dev, extras = programs["chunk"](self._variables, self._dev)
            # The per-row predicate — the finished_mask helper the
            # early-exit chunks share — reduced on device, fetched once.
            fin = np.asarray(jax.device_get(
                finished_mask(new_dev["finished"])))
            if k == 1:
                toks = np.asarray(jax.device_get(extras))
                pars = None
            else:
                toks, pars = (np.asarray(x) for x in jax.device_get(extras))
            chunk_s = time.perf_counter() - t0
        if self._plan is not None:
            fired = [slot for slot, res in live
                     if self._plan.fire("serve_garble", res.request.index)]
            if fired:
                # The real event zeroes the device buffers wholesale;
                # zeroing the fetch reproduces exactly what the scheduler
                # would read (parallel/dryrun.py's caveat).  device_get
                # views are read-only, hence the copies.
                toks, fin = np.array(toks), np.array(fin)
                for slot in fired:
                    toks[slot] = 0
                    fin[slot] = False
        bad = garbled_decode_slots(toks, fin, [s for s, _ in live])
        if bad:
            self._inc("serve_garble_detected", len(bad))
            self._garbles += len(bad)
            if self.recover:
                raise GarbledChunk(bad)
            self._note_recovery_event()
            log.warning("garbled decode chunk (slots %s) with recovery "
                        "disabled; reporting as computed", bad)
        self._dev = new_dev
        self._chunk_wall.append(chunk_s)
        chunk_ms = chunk_s * 1e3
        self._observe("serve_decode_step_ms", chunk_ms / self.chunk)
        if self.step_budget_ms and chunk_ms > self.step_budget_ms:
            self._inc("serve_slow_chunks")
            self._note_recovery_event()
            log.warning("decode chunk took %.1fms (> %.1fms budget) — "
                        "soft wedge signal", chunk_ms, self.step_budget_ms)
        return fin, toks, pars

    def _run_chunk_recovered(self, programs):
        """The self-healing ladder: bounded deterministic chunk re-runs,
        escalating to an engine rebuild, escalating to
        :class:`ServingUnrecoverable` (RESILIENCE.md recovery table)."""
        attempts = 0
        rebuilds = 0
        while True:
            try:
                return self._dispatch_chunk(programs)
            except (InjectedFault, GarbledChunk, RuntimeError, OSError) as e:
                if isinstance(e, ServingUnrecoverable):
                    raise
                if not isinstance(e, GarbledChunk):
                    # Wedge-class: the dispatch itself failed (injected
                    # serve_wedge, or a real transport/runtime error).
                    # Counted BEFORE the recover gate so detection is
                    # auditable even on the fail-fast path.
                    self._inc("serve_wedge_detected")
                    self._wedges += 1
                    self._note_recovery_event()
                if not self.recover:
                    raise
                self._note_recovery_event()
                attempts += 1
                self._inc("serve_chunk_retries")
                self._chunk_retries += 1
                if self._lifecycle is not None:
                    # Every resident aboard pays the failed dispatch:
                    # the retry lands in each one's recovery component.
                    for res in self._residents:
                        if res is not None:
                            self._lifecycle.emit(
                                "retry", res.request.request_id,
                                attempt=attempts, error=type(e).__name__)
                log.warning("serving chunk failed (%s); deterministic "
                            "re-run %d/%d", e, attempts,
                            max(self.retry_limit, 1))
                if attempts <= self.retry_limit:
                    continue
                rebuilds += 1
                if rebuilds > self.rebuild_limit:
                    raise ServingUnrecoverable(
                        f"serving chunk failed through {attempts} "
                        f"re-run(s) and {rebuilds - 1} rebuild(s); last "
                        f"error: {e}") from e
                self._rebuild(programs)
                attempts = 0

    def _rebuild(self, programs) -> None:
        """Escalated recovery: fresh slot state, residents re-admitted
        from their persisted requests — entirely through the warm
        ``ProgramCache`` (a rebuild must compile NOTHING; any build here
        bumps the ``serve_rebuild_recompiles`` violation counter).  The
        already-emitted tokens move to ``prefix``: the deterministic
        replay re-derives them and harvest verifies the match."""
        builds0 = self._cache.builds
        self._rebuilds += 1
        self._inc("serve_rebuilds")
        log.warning("serving engine rebuild #%d: re-initializing %d slots, "
                    "re-admitting %d resident(s) from persisted requests",
                    self._rebuilds, self._slots_n, self.resident_count)
        self._dev = self._init_state(self._slots_n)
        for slot, res in enumerate(self._residents):
            if res is None:
                continue
            if res.toks:
                prior = np.concatenate(res.toks, axis=0)
                res.prefix = (prior if res.prefix is None
                              else np.concatenate([res.prefix, prior],
                                                  axis=0))
            res.toks, res.pars, res.steps = [], [], 0
            feats = [jnp.asarray(f[None]) for f in res.request.feats]
            self._dev = programs["admit"](self._variables, self._dev,
                                          feats, slot)
            if self._lifecycle is not None:
                self._lifecycle.emit("rebuild", res.request.request_id,
                                     slot=slot, rebuild=self._rebuilds)
        delta = self._cache.builds - builds0
        if delta:
            self._rebuild_recompiles += delta
            self._inc("serve_rebuild_recompiles", delta)
            log.error("engine rebuild compiled %d new program(s) — the "
                      "compile-once contract is violated (SERVING.md "
                      "'Bucket policy')", delta)
        self._note_recovery_event()

    def step(self) -> List[Completion]:
        """One scheduler step: expire/evict past-deadline work, fill free
        slots from the queue, run ONE compiled chunk over the slot batch
        (through the self-healing ladder when ``recover`` is armed),
        harvest every row whose per-row finished mask went True (freeing
        its slot), expire again, refill.  Returns the completions
        harvested this step (possibly []); drop records accumulate for
        ``pop_dropped``.  Cache hits completed since the last step are
        returned first (they never occupied a slot)."""
        done: List[Completion] = list(self._hits)
        self._hits.clear()
        self._expire_residents(self.clock())
        self._ensure_bucket()
        self._admit_pending()
        if self.resident_count == 0:
            self._update_gauges()
            return done
        k = self.beam_size
        programs = self._programs(self._slots_n)
        fin, toks, pars = self._run_chunk_recovered(programs)
        scores_h = lengths_h = None
        for slot, res in enumerate(self._residents):
            if res is None:
                continue
            res.toks.append(toks[slot])
            if pars is not None:
                res.pars.append(pars[slot])
            res.steps += self.chunk
            if self._lifecycle is not None:
                self._lifecycle.emit("decode_chunk",
                                     res.request.request_id,
                                     k=res.steps // self.chunk, slot=slot)
            if res.request.stream and k == 1:
                # Greedy streams honestly: this chunk's emitted tokens
                # are final the moment they leave the device.  (Beam
                # emits its one terminal chunk inside _harvest — the
                # best hypothesis needs the backtrack.)
                self._emit_stream_delta(res)
            if fin[slot] or res.steps >= self.max_len:
                if k > 1 and scores_h is None:
                    # cstlint: disable=device-scalar-fetch -- the designed batched harvest: ONE lazy fetch of all slots' beam scores per chunk (only when some slot finished), not per-step scalars; the host backtrack needs them.
                    scores_h = np.asarray(jax.device_get(self._dev["scores"]))
                    # cstlint: disable=device-scalar-fetch -- same one-per-chunk batched harvest as scores_h above.
                    lengths_h = np.asarray(
                        jax.device_get(self._dev["lengths"]))
                done.append(self._harvest(slot, scores_h, lengths_h))
        # Deadline sweep after the chunk, then freed slots admit the next
        # queued videos — both before the next chunk.
        self._expire_residents(self.clock())
        self._admit_pending()
        self._update_gauges()
        return done

    # -- streaming ---------------------------------------------------------

    def _caption_so_far(self, res: _Resident) -> np.ndarray:
        """The resident's caption tokens as of the latest chunk: the
        harvested chunks only, clamped at max_len, trimmed at the first
        EOS — exactly the tokens the final harvest will keep.  NOT
        ``res.prefix``: a rebuild's deterministic replay re-derives the
        prefix tokens INTO ``res.toks`` from step 0 (harvest's
        ``all_toks`` reads only ``res.toks`` for the same reason), so
        prepending the prefix would double-count everything streamed
        before the rebuild."""
        if not res.toks:
            return np.zeros((0,), np.int32)
        return _trim_eos(np.concatenate(res.toks, axis=0)[:self.max_len])

    def _emit_stream_delta(self, res: _Resident) -> None:
        """Queue the resident's NEW caption tokens (beyond what was
        already streamed) as one chunk.  Empty deltas emit nothing —
        and after a rebuild the deterministic replay's re-derived tokens
        fall inside the ``streamed`` watermark, so clients never see
        duplicates.  The watermark only ever moves FORWARD: mid-replay
        the re-derived caption is shorter than what was already emitted,
        and shrinking it would re-stream the tail once the replay caught
        up."""
        cap = self._caption_so_far(res)
        new = cap[res.streamed:]
        res.streamed = max(res.streamed, int(cap.size))
        if not new.size:
            return
        self._push_stream_chunk(res, new)

    def _push_stream_chunk(self, res: _Resident, tokens: np.ndarray) -> None:
        now = self.clock()
        if res.chunks_emitted == 0:
            res.first_emit = now
            ttft = now - res.request.arrival
            self._ttft.append(ttft)
            self._observe("serve_ttft_ms", ttft * 1e3)
        else:
            gap = now - res.last_emit
            self._gaps.append(gap)
            self._observe("serve_chunk_gap_ms", gap * 1e3)
        res.last_emit = now
        self._stream_chunks.append(
            StreamChunk(res.request.request_id, res.chunks_emitted,
                        np.asarray(tokens, np.int32), meta=res.request.meta))
        res.chunks_emitted += 1
        self._stream_emitted += 1
        self._inc("serve_stream_chunks")

    def _harvest(self, slot: int, scores_h, lengths_h) -> Completion:
        res = self._residents[slot]
        self._residents[slot] = None
        max_len = self.max_len
        all_toks = np.concatenate(res.toks, axis=0)
        diverged = False
        if res.prefix is not None:
            # Replay-verification: a post-rebuild re-decode is the same
            # deterministic program on the same inputs, so the re-emitted
            # tokens must reproduce the persisted prefix bit for bit.
            n = min(len(res.prefix), len(all_toks))
            if not np.array_equal(all_toks[:n], res.prefix[:n]):
                diverged = True
                self._inc("serve_replay_divergence")
                self._replay_divergence += 1
                log.warning("request %r: post-rebuild replay diverged "
                            "from its persisted prefix (slot %d)",
                            res.request.request_id, slot)
        if self.beam_size == 1:
            hist = all_toks[:max_len]
            row = np.zeros((max_len,), np.int32)
            row[:hist.shape[0]] = hist
        else:
            toks = all_toks[:max_len]                            # (T, k)
            pars = np.concatenate(res.pars, axis=0)[:max_len]
            row = _backtrack_best(toks, pars, scores_h[slot],
                                  lengths_h[slot], max_len,
                                  self.length_norm)
            if res.request.stream:
                # Beam's one honest chunk: the backtracked winner, whole.
                trimmed = _trim_eos(row)
                if trimmed.size:
                    self._push_stream_chunk(res, trimmed)
        if res.request.cache_key is not None and self._result_cache \
                is not None:
            if diverged:
                # A replay-diverged caption is SUSPECT: never cache it
                # (and drop any entry a concurrent twin wrote) — the
                # cache may make a request cheaper, never wronger.
                self._result_cache.invalidate(res.request.cache_key)
            else:
                # The miss is counted HERE, beside its write-back:
                # misses == write-backs exactly (submit's note).
                self._cache_misses += 1
                self._inc("serve_cache_misses")
                evicted = self._result_cache.put(res.request.cache_key,
                                                 row)
                if evicted:
                    self._cache_evictions += evicted
                    self._inc("serve_cache_evictions", evicted)
        now = self.clock()
        comp = Completion(
            request_id=res.request.request_id, tokens=row, slot=slot,
            admit_at=res.admit_at, done_at=now,
            latency_s=now - res.request.arrival,
            decode_steps=min(res.steps, max_len), meta=res.request.meta,
            stream_chunks=res.chunks_emitted,
            ttft_s=(None if res.first_emit is None
                    else res.first_emit - res.request.arrival))
        self._completed += 1
        self._inc("serve_completed")
        self._latencies.append(comp.latency_s)
        self._observe("serve_request_latency_ms", comp.latency_s * 1e3)
        if res.request.deadline is not None:
            self._observe("serve_deadline_slack_ms",
                          (res.request.deadline - now) * 1e3)
        if self._lifecycle is not None:
            self._lifecycle.emit("completed", comp.request_id, ts=now,
                                 latency_ms=round(comp.latency_s * 1e3, 3),
                                 slot=slot,
                                 decode_steps=comp.decode_steps)
        return comp

    def drain(self, abort: Optional[Callable[[], bool]] = None
              ) -> Tuple[List[Completion], List[Request]]:
        """Graceful shutdown: reject everything still queued, run the
        resident rows to completion with admissions closed, return
        (completions, rejected requests).  The SIGTERM contract
        (SERVING.md 'Drain'); the caller maps it onto the resilience
        exit-code taxonomy.  ``abort`` is polled between steps: True
        stops the drain immediately (the double-SIGTERM hard stop) with
        residents abandoned."""
        rejected = list(self._queue)
        self._queue.clear()
        if rejected:
            self._rejected += len(rejected)
            self._inc("serve_rejected_drain", len(rejected))
            if self._lifecycle is not None:
                for req in rejected:
                    self._lifecycle.emit("dropped", req.request_id,
                                         reason="rejected_draining",
                                         where="drain")
        done: List[Completion] = list(self._hits)  # cache hits owe nothing
        self._hits.clear()
        while any(r is not None for r in self._residents):
            if abort is not None and abort():
                log.warning("drain aborted with %d resident(s) unfinished",
                            self.resident_count)
                break
            done.extend(self.step())
        self._update_gauges()
        return done, rejected

    def run_until_idle(self) -> List[Completion]:
        """Offline helper (eval parity / tests): step until queue and
        slots are empty.  Progress is guaranteed — every resident
        force-finishes at max_len steps."""
        done: List[Completion] = []
        while not self.idle:
            done.extend(self.step())
        return done

    # -- warmup / stats / health -------------------------------------------

    def warm(self) -> Dict[str, Any]:
        """Build AND execute admit+chunk for EVERY bucket on throwaway
        state, so first requests hit compiled programs and steady load can
        be pinned at 0 new builds (the bench probe's recompile assert).
        Returns ``stats()`` — snapshot ``compiles`` to define "after
        warmup"."""
        for slots in self.buckets:
            programs = self._programs(slots)
            state = self._init_state(slots)
            feats = [jnp.zeros((1,) + s, jnp.float32)
                     for s in self._feat_shapes]
            state = programs["admit"](self._variables, state, feats, 0)
            state, extras = programs["chunk"](self._variables, state)
            # cstlint: disable=device-scalar-fetch -- warm() runs once at startup, one barrier per bucket ladder entry; the steady-state scheduler loop never passes here.
            jax.block_until_ready(extras)
        return self.stats()

    def stats(self) -> Dict[str, Any]:
        lat = np.asarray(self._latencies, np.float64) * 1e3
        pct = (lambda q: float(np.percentile(lat, q)) if lat.size else None)
        out = {
            "slots": self._slots_n,
            "buckets": list(self.buckets),
            "beam_size": self.beam_size,
            "decode_chunk": self.chunk,
            "residents": self.resident_count,
            "queue_depth": len(self._queue),
            "submitted": self._submitted,
            "completed": self._completed,
            "shed": self._shed,
            "rejected_drain": self._rejected,
            "compiles": self._cache.builds,
            "chunk_dispatches": self._chunk_dispatches,
            "latency_p50_ms": pct(50),
            "latency_p99_ms": pct(99),
            "latency_mean_ms": float(lat.mean()) if lat.size else None,
            # Fault-tolerance audit (host mirrors of the registry
            # counters, so stats are complete registry-less too).
            **self.recovery_counters(),
            **self.cache_counters(),
            **self.stream_stats(),
        }
        # Per-request latency attribution (telemetry/lifecycle.py): a
        # standalone engine holds the base tracer and reports the
        # component percentiles here; a fleet replica holds a labeled
        # view (no report surface) and the ROUTER's stats carry the
        # fleet-wide + per-replica breakdown instead.
        if self._lifecycle is not None and \
                hasattr(self._lifecycle, "attribution_report"):
            out["attribution"] = self._lifecycle.attribution_report()
        return out

    def cache_counters(self) -> Dict[str, Any]:
        """The ONE definition of the result-cache audit view (the
        recovery_counters discipline: stats, probe, and serve_report all
        render exactly this dict)."""
        armed = self._result_cache is not None
        return {
            "cache_armed": armed,
            "cache_hits": self._cache_hits,
            "cache_misses": self._cache_misses,
            "cache_evictions": self._cache_evictions,
            "cache_bypass": self._cache_bypass,
            "cache_errors": self._cache_errors,
            "cache_entries": len(self._result_cache) if armed else 0,
            "cache_capacity": (self._result_cache.capacity if armed
                               else 0),
        }

    def stream_stats(self) -> Dict[str, Any]:
        """Streaming latency view: time-to-first-token and inter-chunk
        gap percentiles over the retained emission windows."""
        ttft = np.asarray(self._ttft, np.float64) * 1e3
        gaps = np.asarray(self._gaps, np.float64) * 1e3
        p = (lambda a, q: round(float(np.percentile(a, q)), 3)
             if a.size else None)
        return {
            "stream_chunks": self._stream_emitted,
            "ttft_p50_ms": p(ttft, 50),
            "ttft_p99_ms": p(ttft, 99),
            "chunk_gap_p50_ms": p(gaps, 50),
            "chunk_gap_p99_ms": p(gaps, 99),
        }

    def recovery_counters(self) -> Dict[str, int]:
        """The ONE definition of the recovery audit view — ``stats()``,
        ``health()``, and the serving bench probe all render exactly this
        dict, so a counter added here reaches every surface at once."""
        return {
            "expired": self._expired,
            "deadline_shed": self._deadline_shed,
            "chunk_retries": self._chunk_retries,
            "rebuilds": self._rebuilds,
            "rebuild_recompiles": self._rebuild_recompiles,
            "garble_detected": self._garbles,
            "wedge_detected": self._wedges,
            "admit_errors": self._admit_errors,
            "replay_divergence": self._replay_divergence,
        }

    def health(self) -> Dict[str, Any]:
        """The health plane's view: ``ok`` | ``degraded`` (a recovery
        event — retry, rebuild, injected fault, slow chunk — happened
        within ``degraded_window_s``) plus queue depth and the recovery
        counters.  Host state only: safe to call from the watchdog's
        heartbeat payload while the scheduler may be wedged."""
        floor = self.min_service_s()
        return {
            "status": health_status(draining=False,
                                    recovering=self.degraded()),
            "queue_depth": len(self._queue),
            "residents": self.resident_count,
            "slots": self._slots_n,
            "completed": self._completed,
            "recovery": self.recovery_counters(),
            "compiles": self._cache.builds,
            # The shed floor (one p99 chunk; None until the latency
            # window is honest), in ms so it travels the health WIRE:
            # the process-fleet supervisor reads every child's floor
            # from {"op": "health"} for the fleet-edge deadline shed —
            # the same policy the in-process router applies via
            # min_service_s() (serving/policy.deadline_unmeetable).
            "min_service_ms": (None if floor is None
                               else round(floor * 1e3, 3)),
        }

    # -- telemetry ---------------------------------------------------------

    def _note_recovery_event(self) -> None:
        self._last_recovery_at = self.clock()

    def _inc(self, name: str, n: float = 1) -> None:
        if self._registry is not None:
            self._registry.inc(name, n)

    def _observe(self, name: str, value: float) -> None:
        if self._registry is not None:
            self._registry.observe(name, value)

    def _update_gauges(self) -> None:
        if self._registry is None:
            return
        self._registry.set_gauge("serve_queue_depth", len(self._queue))
        self._registry.set_gauge(
            "serve_slot_occupancy",
            self.resident_count / self._slots_n if self._slots_n else 0.0)
        self._registry.set_gauge("serve_recompiles", self._cache.builds)
        if self._latencies:
            lat = np.asarray(self._latencies, np.float64) * 1e3
            self._registry.set_gauge("serve_latency_p50_ms",
                                     float(np.percentile(lat, 50)))
            self._registry.set_gauge("serve_latency_p99_ms",
                                     float(np.percentile(lat, 99)))


def _trim_eos(tokens: np.ndarray) -> np.ndarray:
    """Caption tokens up to (excluding) the first EOS/PAD 0 — the slice
    ``vocab.decode`` reads, shared by the streaming deltas and the
    cache-hit terminal chunk so "the caption's tokens" has one meaning."""
    t = np.asarray(tokens, np.int32).reshape(-1)
    nz = np.flatnonzero(t == 0)
    return t[: int(nz[0])] if nz.size else t


def _backtrack_best(toks: np.ndarray, pars: np.ndarray, scores: np.ndarray,
                    lengths: np.ndarray, max_len: int,
                    length_norm: float) -> np.ndarray:
    """Host-side twin of ops/beam.py's backtrack + ranking for ONE slot.

    ``toks``/``pars`` are the slot's executed steps (T <= max_len; chunk
    steps past a slot's finish are the provable all-finished no-op —
    token 0 at parent identity — so backtracking through them reproduces
    the legacy full-length backtrack, the same argument the PR-3 chunked
    beam rides on).  Ranking runs through jnp so pow/argsort tie-breaking
    match the compiled path exactly.
    """
    T, k = toks.shape
    beam_ix = np.arange(k)
    seq = np.zeros((k, max_len), np.int32)
    for t in range(T - 1, -1, -1):
        seq[:, t] = toks[t, beam_ix]
        beam_ix = pars[t, beam_ix]
    ranked = jnp.asarray(scores)
    if length_norm > 0:
        ranked = ranked / jnp.maximum(jnp.asarray(lengths), 1) ** length_norm
    order = np.asarray(jnp.argsort(-ranked))
    return seq[int(order[0])]


def serve_decode_split(model, params, loader, vocab, max_len: int,
                       beam_size: int = 1, length_norm: float = 0.0,
                       decode_chunk: int = 8,
                       bucket_sizes: Sequence[int] = DEFAULT_BUCKETS,
                       registry=None, tracer=None, beat=None):
    """Decode a whole split through the serving engine (batch-offline
    load) -> ``[{"image_id", "caption"}]`` in dataset order.

    The offline twin of ``training.evaluation.decode_split``: every video
    is submitted once (padding dupes skipped), the engine runs to idle,
    captions decode through the same vocab.  ``eval.py --engine serving``
    asserts this output caption-for-caption equal to the legacy path —
    the end-to-end parity drill.
    """
    ds = loader.ds
    engine = ServingEngine(
        model, {"params": params},
        list(zip(ds.feat_times, ds.feat_dims)),
        max_len=max_len, beam_size=beam_size, length_norm=length_norm,
        decode_chunk=decode_chunk, bucket_sizes=bucket_sizes,
        queue_limit=0, registry=registry, tracer=tracer)
    seen = set()
    order = []
    tokens = {}
    for batch in loader.iter_eval():
        for j, vid in enumerate(batch.video_ids):
            if vid in seen:
                continue
            seen.add(vid)
            order.append(vid)
            # cstlint: disable=device-scalar-fetch -- batch.feats are the loader's host-side h5/numpy reads (pre device_put); slicing one row here copies host memory, no device sync.
            engine.submit(vid, [np.asarray(f)[j] for f in batch.feats])
        # Overlap decode with the next batch's feature reads.
        for comp in engine.step():
            tokens[comp.request_id] = comp.tokens
        if beat is not None:
            beat()
    for comp in engine.run_until_idle():
        tokens[comp.request_id] = comp.tokens
    return [{"image_id": vid, "caption": vocab.decode(tokens[vid])}
            for vid in order]
