"""Persistent per-platform tuning records — tuned defaults, not hand-set.

The rollout-throughput knobs (``--decode_chunk``, ``--scan_unroll``,
``--overlap_rewards``, ``--device_rewards``, ``--decode_kernel``) have one
measured best value PER PLATFORM, not per run; re-deriving them by hand for
every deployment is how BENCH_r01-r05 spent five rounds.  Following the
compile-once / cache-keyed discipline of arXiv 2603.09555 (PAPERS.md), the
autotuner (``tuning/sweep.py``) discovers them once, this module persists
them, and ``opts.py`` resolves them as defaults at startup:

    explicit CLI flag  >  tuning record  >  built-in opts default

Record file (``TUNED_CONFIGS.json`` at the repo root, override with the
``CST_TUNED_CONFIGS`` env var; empty string disables resolution entirely):

    {"version": 1,
     "platforms": {
       "<platform>": {            # jax platform string: "tpu", "cpu", ...
         "platform": ...,
         "device_kind": ...,      # e.g. "TPU v5 lite"
         "git_sha": ...,          # code identity that produced the numbers
         "measured_at": ...,
         "sweep": {"mode": "full"|"fast", "steps": N,
                   "base_config": {...}},   # bench-shape identity
         "points": [{"config": {axes...}, "captions_per_sec": x,
                     "path": "device_fused"|"host_pipeline"}, ...],
         "winner": {axes...},     # the tuned values opts.py applies
         "winner_captions_per_sec": x,
         "complete": true|false}}}

Writes go through ``resilience.integrity.atomic_json_write`` (fsync'd tmp +
rename + dir fsync) and MERGE by platform key: a CPU sweep can never
clobber the TPU entry — the invariant the ISSUE-6 satellite pins.

Honesty rules baked in here rather than in callers:

- ``resolve_platform`` never initializes a jax backend (opts parsing must
  stay hang-proof when the remote-TPU tunnel is down): it reads
  ``JAX_PLATFORMS`` first, then falls back to the record's own entries,
  preferring a device entry over ``cpu``.
- Every application is stamped with provenance (record path, platform,
  git SHA, whether the SHA still matches HEAD, exactly which axes were
  applied) so telemetry.json / bench JSON can always answer "where did
  this config come from?".
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional, Tuple

RECORD_VERSION = 1
RECORD_ENV = "CST_TUNED_CONFIGS"
RECORD_BASENAME = "TUNED_CONFIGS.json"

#: The opts axes a tuning record may set (winner keys outside this set are
#: informational — e.g. bench_batch_size — and never applied to a run).
TUNABLE_AXES = ("decode_chunk", "scan_unroll", "overlap_rewards",
                "device_rewards", "decode_kernel")


def _axis_valid(axis: str, value) -> bool:
    """The SAME constraints the CLI validators enforce (opts.py
    _positive_int/_nonneg_int/choices) — a hand-edited or corrupt record
    must not smuggle in a value the flag parser would reject with a
    usage error (e.g. scan_unroll=0 crashing deep inside lax.scan)."""
    if axis == "decode_kernel":
        return value in ("reference", "pallas", "bf16")
    if not isinstance(value, int) or isinstance(value, bool):
        return False
    if axis == "scan_unroll":
        return value >= 1
    if axis == "device_rewards":
        return value in (0, 1)
    return value >= 0  # decode_chunk, overlap_rewards: 0 is a mode


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def default_record_path() -> Optional[str]:
    """Resolution target: $CST_TUNED_CONFIGS if set ('' disables tuned
    resolution and returns None), else <repo>/TUNED_CONFIGS.json."""
    env = os.environ.get(RECORD_ENV)
    if env is not None:
        return env or None
    return os.path.join(repo_root(), RECORD_BASENAME)


def load_record(path: Optional[str] = None) -> Dict[str, Any]:
    """The whole record document (``{"version":1,"platforms":{}}`` when the
    file is missing/unreadable — a torn or absent record must degrade to
    built-in defaults, never crash startup)."""
    if path is None:
        path = default_record_path()
    if not path or not os.path.exists(path):
        return {"version": RECORD_VERSION, "platforms": {}}
    try:
        import json

        with open(path) as f:
            doc = json.load(f)
        if not isinstance(doc.get("platforms"), dict):
            return {"version": RECORD_VERSION, "platforms": {}}
        return doc
    except (OSError, ValueError):
        return {"version": RECORD_VERSION, "platforms": {}}


def platform_entry(platform: str,
                   path: Optional[str] = None) -> Optional[Dict[str, Any]]:
    return load_record(path)["platforms"].get(platform)


def save_platform_entry(entry: Dict[str, Any],
                        path: Optional[str] = None) -> str:
    """Merge ``entry`` into the record under its OWN ``entry['platform']``
    key and atomically rewrite the file.  Other platforms' entries are
    preserved verbatim — the only way a TPU record dies is a TPU sweep
    replacing it."""
    from ..resilience.integrity import atomic_json_write

    platform = entry.get("platform")
    if not platform:
        raise ValueError("tuning entry must carry its 'platform' key")
    if path is None:
        path = default_record_path()
    if not path:
        raise ValueError(f"tuning record disabled ({RECORD_ENV}='')")
    doc = load_record(path)
    doc["version"] = RECORD_VERSION
    doc["platforms"][platform] = entry
    atomic_json_write(path, doc, indent=2, sort_keys=True)
    return path


def resolve_platform(path: Optional[str] = None) -> Optional[str]:
    """Platform key for startup resolution WITHOUT touching a jax backend
    (a downed remote-TPU tunnel blocks inside backend init — bench.py's
    whole probe dance exists because of it; CLI parsing must never pay
    that).  Order: JAX_PLATFORMS env (first entry), else the record's own
    entries — a device entry wins over "cpu" (production runs on a tuned
    machine want the device config; CPU-pinned runs in this repo always
    set JAX_PLATFORMS=cpu, tier-1 included)."""
    env = os.environ.get("JAX_PLATFORMS", "")
    first = env.split(",")[0].strip().lower()
    if first:
        return first
    platforms = sorted(load_record(path)["platforms"])
    if not platforms:
        return None
    non_cpu = [p for p in platforms if p != "cpu"]
    return non_cpu[0] if non_cpu else platforms[0]


def git_sha_matches_head(entry: Dict[str, Any]) -> Optional[bool]:
    """Whether the record was measured at the current HEAD (None when
    either side is unknown).  A mismatch does NOT veto application — every
    commit would otherwise orphan every record — but it is stamped into
    the provenance so a reader can judge staleness."""
    from ..utils.platform import git_head_sha

    want = entry.get("git_sha")
    head = git_head_sha(repo_root())
    if not want or not head or head == "unknown":
        return None
    return want == head


def resolved_tuned_defaults(
    path: Optional[str] = None,
    platform: Optional[str] = None,
) -> Tuple[Dict[str, Any], Optional[Dict[str, Any]]]:
    """-> (tuned axis values, provenance) for startup resolution.

    ``tuned`` holds only TUNABLE_AXES keys present in the platform entry's
    winner; ``provenance`` describes where they came from (path, platform,
    git_sha, sha-vs-HEAD match, measured_at).  ``({}, None)`` when there
    is no applicable record — the caller keeps its built-in defaults.
    Incomplete entries (a sweep killed mid-run) are not applied: a partial
    winner is a provisional minimum, not a measured optimum.
    """
    if path is None:
        path = default_record_path()
    if not path:
        return {}, None
    if platform is None:
        platform = resolve_platform(path)
    if not platform:
        return {}, None
    entry = platform_entry(platform, path)
    if not entry or not entry.get("complete") or "winner" not in entry:
        return {}, None
    winner = entry["winner"] or {}
    tuned = {}
    for axis in TUNABLE_AXES:
        if axis not in winner:
            continue
        if _axis_valid(axis, winner[axis]):
            tuned[axis] = winner[axis]
        else:
            import sys

            print(f"warning: tuning record {path} ({platform}) carries an "
                  f"invalid {axis}={winner[axis]!r}; axis ignored "
                  "(falls back to the built-in default)", file=sys.stderr)
    if not tuned:
        return {}, None
    provenance = {
        "record": os.path.abspath(path),
        "platform": platform,
        "git_sha": entry.get("git_sha"),
        "git_sha_matches_head": git_sha_matches_head(entry),
        "measured_at": entry.get("measured_at"),
        "winner_captions_per_sec": entry.get("winner_captions_per_sec"),
    }
    return tuned, provenance
