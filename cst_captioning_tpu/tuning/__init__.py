"""Autotuning: persistent per-platform rollout-throughput configs.

``sweep`` measures (offline, `make tune` / `make tune-fast`), ``record``
persists and resolves — see the module docs and PARITY.md "Tuned configs"
for the record schema and the flag > record > built-in resolution order.
"""

from .record import (
    RECORD_ENV,
    TUNABLE_AXES,
    default_record_path,
    load_record,
    platform_entry,
    resolve_platform,
    resolved_tuned_defaults,
    save_platform_entry,
)
from .sweep import (
    PARITY_SHAPE_GRID,
    base_namespace,
    pick_winner,
    run_sweep,
    sweep_space,
)

__all__ = [
    "PARITY_SHAPE_GRID", "RECORD_ENV", "TUNABLE_AXES",
    "base_namespace", "default_record_path", "load_record",
    "pick_winner", "platform_entry", "resolve_platform",
    "resolved_tuned_defaults", "run_sweep", "save_platform_entry",
    "sweep_space",
]
