"""Offline rollout-throughput autotuner: sweep, persist, resolve.

Sweeps the CST rollout config space — ``decode_chunk``, ``scan_unroll``,
``overlap_rewards``, ``device_rewards``, the ``decode_kernel``
reference/pallas axis, and the bench batch shape — with bench.py's own
``bench_cst`` measurement harness (the same class/step factories the
trainer ships, so a tuned number IS a trainer number), and persists the
winner as a per-platform record (``tuning/record.py``) that ``opts.py``
resolves as defaults at startup.

Contracts the tests pin:

- **Deterministic**: the point space and its order are pure functions of
  (mode, base shapes); winners tie-break to the earlier point.
- **Resumable**: every measured point is persisted immediately
  (``complete: false``); a rerun re-measures only the missing points, and
  a rerun over a ``complete`` record at the same git SHA + sweep identity
  returns it without measuring anything (``make tune`` twice = one sweep).
- **Platform-honest**: the entry is keyed by the platform that actually
  ran (a CPU-fallback sweep writes ``platform: cpu``) and the per-platform
  merge in ``record.save_platform_entry`` means a CPU sweep can never
  overwrite a TPU record.
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from .record import platform_entry, repo_root, save_platform_entry

#: Device-scorer parity corners (vocab, seq_len, seq_per_img) — the shape
#: grid the sweep's measured configs span.  tests/test_jax_ciderd.py pins
#: ops/jax_ciderd.py against the Python oracle at every corner, so flipping
#: --device_rewards on by default can never change rewards at a swept shape.
PARITY_SHAPE_GRID = (
    (60, 8, 2),      # small-vocab short captions, minimum multi-sample S
    (60, 30, 5),     # short vocab, full MSR-VTT length, many samples
    (500, 8, 5),
    (500, 30, 2),
    (2000, 12, 3),   # larger vocab, mid length
)

#: Incremented by every real measurement — the reuse/resume tests assert
#: on it instead of guessing from timings.
MEASUREMENTS = 0

_BENCH_MOD = "cst_bench_harness"


def load_bench() -> Any:
    """Import bench.py (repo root) by file path under a stable alias, so
    the tuner works no matter what the caller's sys.path looks like."""
    mod = sys.modules.get(_BENCH_MOD)
    if mod is not None:
        return mod
    import importlib.util

    path = os.path.join(repo_root(), "bench.py")
    spec = importlib.util.spec_from_file_location(_BENCH_MOD, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[_BENCH_MOD] = mod
    spec.loader.exec_module(mod)
    return mod


def base_namespace(batch_size: int = 32, seq_per_img: int = 20,
                   seq_len: int = 30, vocab: int = 8000, hidden: int = 512,
                   steps: int = 8, bfloat16: int = 1,
                   native_cider: int = 1) -> argparse.Namespace:
    """The non-swept measurement shape (bench.py's MSR-VTT geometry by
    default) — part of the sweep identity, so records from different
    shapes never masquerade as each other."""
    return argparse.Namespace(
        batch_size=batch_size, seq_per_img=seq_per_img, seq_len=seq_len,
        vocab=vocab, hidden=hidden, steps=steps, bfloat16=bfloat16,
        native_cider=native_cider, probe_eos_bias=10.0,
    )


def sweep_space(base: argparse.Namespace,
                fast: bool = False) -> List[Dict[str, Any]]:
    """Deterministic point list.  ``fast`` is the 2-point smoke sweep that
    rides in tier-1 (shipped fused config + the pallas decode cell);
    the full sweep covers the whole axis grid plus a batch-shape probe."""
    from ..opts import (
        DEFAULT_DECODE_CHUNK,
        DEFAULT_OVERLAP_REWARDS,
        DEFAULT_SCAN_UNROLL,
    )

    def point(decode_chunk, scan_unroll, device_rewards, overlap_rewards,
              decode_kernel, batch_size=None):
        return {
            "decode_chunk": decode_chunk, "scan_unroll": scan_unroll,
            "device_rewards": device_rewards,
            "overlap_rewards": overlap_rewards,
            "decode_kernel": decode_kernel,
            "batch_size": base.batch_size if batch_size is None
            else batch_size,
        }

    shipped = point(DEFAULT_DECODE_CHUNK, DEFAULT_SCAN_UNROLL, 1,
                    DEFAULT_OVERLAP_REWARDS, "reference")
    if fast:
        return [shipped,
                point(DEFAULT_DECODE_CHUNK, DEFAULT_SCAN_UNROLL, 1,
                      DEFAULT_OVERLAP_REWARDS, "pallas")]
    points: List[Dict[str, Any]] = []
    # fused device-reward branch: chunk x unroll x kernel.  "bf16" is the
    # low-precision decode variant (ops/bf16_decode.py) — parity-gated
    # for caption quality by scripts/bf16_parity.py; the sweep's job is
    # the other half of the question: whether it PAYS on this platform
    # (the record's winner then carries decode_kernel=bf16 with
    # provenance, exactly like the pallas axis).
    for decode_chunk in (0, 4, 8, 16):
        for scan_unroll in (1, 2):
            for decode_kernel in ("reference", "pallas", "bf16"):
                points.append(point(decode_chunk, scan_unroll, 1,
                                    DEFAULT_OVERLAP_REWARDS, decode_kernel))
    # host reward branch: overlap depth matters only here
    for overlap in (0, 2):
        for decode_chunk in (0, DEFAULT_DECODE_CHUNK):
            points.append(point(decode_chunk, DEFAULT_SCAN_UNROLL, 0,
                                overlap, "reference"))
    # batch-shape probe at the shipped fused config (informational axis:
    # the winner records it as bench_batch_size; opts.py never applies a
    # tuned batch size to training — see PARITY.md "Tuned configs")
    points.append(point(DEFAULT_DECODE_CHUNK, DEFAULT_SCAN_UNROLL, 1,
                        DEFAULT_OVERLAP_REWARDS, "reference",
                        batch_size=base.batch_size * 2))
    return points


def sweep_identity(base: argparse.Namespace,
                   fast: bool) -> Dict[str, Any]:
    return {
        "mode": "fast" if fast else "full",
        "steps": base.steps,
        "base_config": {k: getattr(base, k) for k in
                        ("batch_size", "seq_per_img", "seq_len", "vocab",
                         "hidden", "bfloat16", "native_cider")},
    }


def point_namespace(base: argparse.Namespace,
                    cfg: Dict[str, Any]) -> argparse.Namespace:
    ns = argparse.Namespace(**vars(base))
    ns.batch_size = cfg["batch_size"]
    ns.decode_chunk = cfg["decode_chunk"]
    ns.scan_unroll = cfg["scan_unroll"]
    ns.decode_kernel = cfg["decode_kernel"]
    ns.device_rewards = cfg["device_rewards"]
    ns.overlap_depth = cfg["overlap_rewards"]
    return ns


def measure_point(base: argparse.Namespace,
                  cfg: Dict[str, Any]) -> Dict[str, Any]:
    """One config point -> {"config", "captions_per_sec", "path"} via
    bench.bench_cst, measuring ONLY the path this point selects (the full
    three-way measurement is bench's job; a sweep pays per point)."""
    global MEASUREMENTS
    MEASUREMENTS += 1
    bench = load_bench()
    ns = point_namespace(base, cfg)
    want = ("fused",) if cfg["device_rewards"] else ("host",)
    out: Dict[str, Any] = {"config": dict(cfg)}
    try:
        res = bench.bench_cst(ns, paths=want, probe=False)
        if cfg["device_rewards"]:
            caps, path = res["fused_captions_per_sec"], "device_fused"
        else:
            caps, path = res["host_pipeline_captions_per_sec"], \
                "host_pipeline"
        out.update(captions_per_sec=caps, path=path,
                   scorer=res.get("scorer"))
        if caps is None:
            out["error"] = "path did not execute on this backend"
    except Exception as e:  # a broken point must not sink the sweep
        out.update(captions_per_sec=None, path=None, error=repr(e))
    return out


def _point_key(cfg: Dict[str, Any]) -> Tuple:
    return tuple(sorted(cfg.items()))


def pick_winner(points: List[Dict[str, Any]],
                batch_size: Optional[int] = None) -> Optional[Dict[str, Any]]:
    """Highest captions/s; ties break to the EARLIER point (deterministic
    across reruns).  None when nothing measured successfully.

    ``batch_size``: compare only points measured at this batch size.
    Captions/s scales with batch, so the full sweep's 2x-batch probe
    point would otherwise win on batch size alone and collapse the
    recorded axes back to whatever config that probe happened to use —
    the batch probe is informational, never the axis winner."""
    best = None
    for p in points:
        caps = p.get("captions_per_sec")
        if caps is None:
            continue
        if (batch_size is not None
                and p.get("config", {}).get("batch_size") != batch_size):
            continue
        if best is None or caps > best["captions_per_sec"]:
            best = p
    return best


def run_sweep(
    base: Optional[argparse.Namespace] = None,
    fast: bool = False,
    record_path: Optional[str] = None,
    force: bool = False,
    progress: Optional[Callable[[str], None]] = None,
) -> Tuple[Dict[str, Any], bool]:
    """Run (or resume, or reuse) the sweep on the CURRENT backend.

    -> (platform entry, reused): ``reused=True`` means a complete record
    for this platform + git SHA + sweep identity already existed and NO
    measurement ran.  Partial records at the same identity resume; any
    identity mismatch (shapes, mode, steps, code) restarts the sweep —
    stale points must not mix into a fresh winner.
    """
    import jax

    from ..utils.platform import git_head_sha

    if base is None:
        base = base_namespace()
    say = progress or (lambda msg: None)
    platform = jax.devices()[0].platform
    device_kind = getattr(jax.devices()[0], "device_kind", "")
    ident = sweep_identity(base, fast)
    sha = git_head_sha(repo_root())
    space = sweep_space(base, fast)

    prior = platform_entry(platform, record_path)
    measured: Dict[Tuple, Dict[str, Any]] = {}
    if (prior is not None and not force and prior.get("git_sha") == sha
            and prior.get("sweep") == ident):
        if prior.get("complete"):
            errors = sum(1 for p in prior.get("points", [])
                         if p.get("captions_per_sec") is None)
            if errors:
                say(f"tune: note — {errors} point(s) in the reused record "
                    "failed to measure (see tune_report); pass --force to "
                    "re-measure them")
            say(f"tune: reusing complete {platform} record "
                f"({len(prior.get('points', []))} points, sha {sha[:12]})")
            return prior, True
        # Resume only SUCCESSFUL points: an errored point in a partial
        # record may be a transient backend failure — re-measure it
        # rather than baking the error into the final record.
        measured = {_point_key(p["config"]): p
                    for p in prior.get("points", [])
                    if p.get("captions_per_sec") is not None}
        say(f"tune: resuming {platform} sweep "
            f"({len(measured)}/{len(space)} points already measured)")

    def entry_doc(points, complete):
        doc = {
            "platform": platform, "device_kind": device_kind,
            "git_sha": sha,
            "measured_at": time.strftime("%Y-%m-%d %H:%M:%S"),
            "sweep": ident, "points": points, "complete": complete,
        }
        # Winner selection is restricted to base-batch points: the full
        # sweep's larger-batch probe reports more captions/s for the
        # batch alone and must never decide the tuned axes.
        winner = pick_winner(points, batch_size=base.batch_size)
        if winner is not None:
            axes = {k: winner["config"][k] for k in
                    ("decode_chunk", "scan_unroll", "overlap_rewards",
                     "device_rewards", "decode_kernel")}
            axes["bench_batch_size"] = winner["config"]["batch_size"]
            doc["winner"] = axes
            doc["winner_captions_per_sec"] = winner["captions_per_sec"]
            doc["winner_path"] = winner["path"]
        return doc

    points: List[Dict[str, Any]] = []
    for i, cfg in enumerate(space):
        key = _point_key(cfg)
        if key in measured:
            points.append(measured[key])
            continue
        say(f"tune: [{i + 1}/{len(space)}] {cfg}")
        point = measure_point(base, cfg)
        points.append(point)
        caps = point.get("captions_per_sec")
        say(f"tune:   -> {caps if caps is None else round(caps, 1)} "
            f"captions/s ({point.get('path')})")
        # Persist after EVERY point: a preempted sweep resumes from here.
        save_platform_entry(entry_doc(points, complete=False), record_path)

    final = entry_doc(points, complete=True)
    save_platform_entry(final, record_path)
    winner = final.get("winner")
    say(f"tune: {platform} winner {winner} at "
        f"{final.get('winner_captions_per_sec')} captions/s")
    return final, False
