#!/usr/bin/env python
"""Stitch a supervised fleet's traces into ONE Perfetto file.

A ``scripts/serve_supervisor.py`` run leaves per-process Chrome traces:
the supervisor's own span/lifecycle trace under ``<root>/trace/`` and
one per child life under ``<root>/replica<K>/trace/`` (each file is
self-described: ``otherData`` carries the writing pid and the wall-clock
anchor of its ``ts=0``).  Those timelines do not share a clock — each
process's ``ts`` is µs since ITS tracer started — so this tool:

1. loads ``<root>/clock_sync.json`` (telemetry/fleetobs.py ClockSync:
   midpoint offset per child *pid*, uncertainty bounded by rtt/2);
2. rebases every event onto the supervisor's wall timeline:
   ``ts_unified = ts + (wall_epoch - skew_s - base_wall) * 1e6`` where
   ``skew_s`` is the child pid's clock offset (0 for the supervisor)
   and ``base_wall`` is the earliest corrected anchor, so the merged
   trace starts at 0;
3. rewrites child async-track ids to the supervisor's request id: any
   child async event carrying ``args.trace_id`` (the stamp the
   supervisor put on the wire and the child's lifecycle echoed) seeds a
   ``(pid, local_id) -> str(trace_id)`` mapping, so each request renders
   as ONE async track crossing the process boundary — routed at the
   supervisor, queued/admitted/decode_chunk in the child, responded
   back at the supervisor;
4. labels process rows (``supervisor (pid N)`` / ``replica<K> (pid
   N)``) and drops a ``clock_skew`` annotation instant per child pid
   carrying the applied offset and its uncertainty.

Output is a single atomic ``fleet_trace.json`` with
``otherData.merged = true`` — load it in Perfetto, or render it with
``scripts/trace_report.py`` (which pairs merged async tracks across
pids).  See OBSERVABILITY.md "Fleet plane".
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

MERGED_SCHEMA = 1

_REPLICA_DIR = re.compile(r"^replica(\d+)$")


def _load_docs(trace_dir: str):
    """-> [(path, doc)] for every loadable Chrome-trace JSON in a dir."""
    docs = []
    for path in sorted(glob.glob(os.path.join(trace_dir, "*.json"))):
        try:
            with open(path, "r", encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            print(f"fleet_trace: skipping unreadable {path}: {e}",
                  file=sys.stderr)
            continue
        if isinstance(doc.get("traceEvents"), list):
            docs.append((path, doc))
    return docs


def _child_trace_dirs(root: str):
    """-> [(replica_index, trace_dir)] for <root>/replica<K>/trace."""
    out = []
    try:
        names = sorted(os.listdir(root))
    except OSError:
        return out
    for name in names:
        m = _REPLICA_DIR.match(name)
        if not m:
            continue
        d = os.path.join(root, name, "trace")
        if os.path.isdir(d):
            out.append((int(m.group(1)), d))
    return out


def merge_fleet_trace(root: str, out_path: str = None) -> dict:
    """Merge one supervised run's traces; returns a summary dict.

    Raises ``FileNotFoundError`` when the supervisor trace dir has no
    loadable files (nothing to anchor the merged timeline on).
    """
    root = os.path.abspath(root)
    out_path = out_path or os.path.join(root, "fleet_trace.json")
    sup_docs = _load_docs(os.path.join(root, "trace"))
    if not sup_docs:
        raise FileNotFoundError(
            f"no supervisor trace files under {os.path.join(root, 'trace')}")

    sync_children: dict = {}
    sync_path = os.path.join(root, "clock_sync.json")
    if os.path.exists(sync_path):
        try:
            with open(sync_path, "r", encoding="utf-8") as f:
                sync_children = json.load(f).get("children", {}) or {}
        except (OSError, ValueError) as e:
            print(f"fleet_trace: clock_sync.json unreadable: {e}",
                  file=sys.stderr)

    # One entry per source file: (role, replica_index, pid,
    # corrected_wall_epoch, skew_record_or_None, doc).
    entries = []
    missing_sync = set()
    for path, doc in sup_docs:
        other = doc.get("otherData") or {}
        entries.append(("supervisor", None, other.get("pid"),
                        float(other.get("wall_epoch_unix_s", 0.0)),
                        None, doc))
    for index, trace_dir in _child_trace_dirs(root):
        for path, doc in _load_docs(trace_dir):
            other = doc.get("otherData") or {}
            pid = other.get("pid")
            epoch = float(other.get("wall_epoch_unix_s", 0.0))
            rec = sync_children.get(str(pid))
            if rec is None:
                missing_sync.add(pid)
            skew = float(rec["skew_s"]) if rec else 0.0
            entries.append(("replica", index, pid, epoch - skew, rec, doc))

    base_wall = min(e[3] for e in entries)

    # Pass 1: the stitch table — any child async event that echoes the
    # supervisor's trace stamp maps its local track id onto the
    # supervisor's request id.
    id_map: dict = {}
    for role, index, pid, _epoch, _rec, doc in entries:
        if role != "replica":
            continue
        for ev in doc["traceEvents"]:
            if ev.get("ph") not in ("b", "n", "e"):
                continue
            args = ev.get("args")
            if isinstance(args, dict) and args.get("trace_id") is not None:
                id_map[(pid, ev.get("id"))] = str(args["trace_id"])

    merged = []
    skew_annotated = set()
    for role, index, pid, epoch, rec, doc in entries:
        shift_us = (epoch - base_wall) * 1e6
        label = (f"supervisor (pid {pid})" if role == "supervisor"
                 else f"replica{index} (pid {pid})")
        if role == "replica" and pid not in skew_annotated:
            skew_annotated.add(pid)
            merged.append({
                "name": "clock_skew", "ph": "i", "s": "p", "cat": "fleet",
                "ts": shift_us, "pid": pid, "tid": 0,
                "args": {
                    "replica": index, "pid": pid,
                    "skew_ms": (round(rec["skew_s"] * 1e3, 3)
                                if rec else None),
                    "uncertainty_ms": (round(rec["uncertainty_s"] * 1e3, 3)
                                       if rec else None),
                    "synced": rec is not None,
                },
            })
        for ev in doc["traceEvents"]:
            ev = dict(ev)
            if ev.get("ph") == "M":
                if ev.get("name") == "process_name":
                    ev["args"] = {"name": label}
                merged.append(ev)
                continue
            if "ts" in ev:
                ev["ts"] = float(ev["ts"]) + shift_us
            if role == "replica" and ev.get("ph") in ("b", "n", "e"):
                new_id = id_map.get((pid, ev.get("id")))
                if new_id is not None:
                    ev["id"] = new_id
            merged.append(ev)

    merged.sort(key=lambda ev: ev.get("ts", 0.0))
    doc = {
        "traceEvents": merged,
        "displayTimeUnit": "ms",
        "otherData": {
            "merged": True,
            "schema": MERGED_SCHEMA,
            "base_wall_epoch_unix_s": base_wall,
            "children": sync_children,
        },
    }
    from cst_captioning_tpu.resilience.integrity import atomic_json_write

    out_parent = os.path.dirname(os.path.abspath(out_path))
    if out_parent:
        os.makedirs(out_parent, exist_ok=True)
    atomic_json_write(out_path, doc)
    return {
        "out": out_path,
        "events": len(merged),
        "sources": len(entries),
        "child_pids": len({e[2] for e in entries if e[0] == "replica"}),
        "stitched_tracks": len(set(id_map.values())),
        "missing_sync_pids": sorted(p for p in missing_sync
                                    if p is not None),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="merge a supervised fleet's per-process traces into "
                    "one clock-skew-corrected Perfetto file")
    ap.add_argument("--dir", required=True,
                    help="the run's --supervise_dir root (expects "
                         "trace/, replica<K>/trace/, clock_sync.json)")
    ap.add_argument("--out", default=None,
                    help="merged trace path (default <dir>/"
                         "fleet_trace.json)")
    args = ap.parse_args(argv)
    try:
        summary = merge_fleet_trace(args.dir, args.out)
    except FileNotFoundError as e:
        print(f"fleet_trace: {e}", file=sys.stderr)
        return 1
    print("fleet_trace: " + json.dumps(summary))
    if summary["missing_sync_pids"]:
        print("fleet_trace: WARNING: no clock-sync sample for pids "
              f"{summary['missing_sync_pids']} (merged with zero skew)",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
