#!/usr/bin/env python
"""Process-fleet serving CLI: N serve.py OS processes under a supervisor.

``scripts/serve_fleet.py`` self-heals N engine replicas inside ONE
process; this front end moves the failure domain to the OS process — a
:class:`serving.supervisor.ProcessFleetSupervisor` owns
``--supervise_replicas`` real ``scripts/serve.py`` child processes (each
on its own localhost socket, its own workdir for blackbox/heartbeat/
telemetry/stderr) and proxies the SAME JSONL wire through a
:class:`serving.supervisor.SupervisorServer`: the wire format,
streaming, deadlines, and result semantics are unchanged (SERVING.md
"Process fleet").

    # zero-setup demo process fleet (3 child processes):
    python scripts/serve_supervisor.py --serve_demo 1 \\
        --supervise_replicas 3

    # the seeded process-chaos drill (SIGKILL replica 1 mid-stream;
    # every request answered, captions bit-identical to a fault-free
    # single-engine reference, blackbox harvested from the dead child):
    python scripts/serve_supervisor.py --serve_demo 1 \\
        --supervise_probe 1 --serve_demo_eos_bias -2

    # the supervisor-death journal drill (SIGKILL the SUPERVISOR
    # process group mid-storm, relaunch on the same --journal_dir,
    # pin exactly-once / bit-identity / prefix-consistent streams):
    python scripts/serve_supervisor.py --serve_demo 1 \\
        --journal_probe 1 --serve_demo_eos_bias -2

Supervisor specifics:

- Child lifecycle is the EXIT TAXONOMY (resilience/exitcodes.py):
  resumable (75/137/143) and wedge (124) exits restart free with
  bounded backoff and their in-flight requests requeued (arrival clocks
  preserved, streams prefix-consistent via supervisor watermarks);
  fatal exits burn ``--supervise_restart_limit``; when every replica is
  dead this process exits 124 for supervised restart one level up.
- Every child death leaves an incident bundle under
  ``<--supervise_dir>/incidents/`` — ``{"op": "dump"}`` is issued
  before a deliberate kill so blackbox.json exists to harvest
  (RESILIENCE.md "Process faults"; scripts/collect_evidence.py bundles
  them).
- ``--fault_plan 'proc_kill@replica=K'`` / ``proc_wedge`` /
  ``proc_preempt`` target OS-process faults at child K;
  ``serve_*@replica=K`` serving kinds are forwarded INTO child K's own
  ``--fault_plan``.
"""

from __future__ import annotations

import json
import logging
import os
import signal
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from cst_captioning_tpu.opts import parse_opts  # noqa: E402

log = logging.getLogger("cst_captioning_tpu.serve_supervisor")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SERVE_METRIC = "serve_captions_per_sec_per_chip"


def child_argv(opt, workdir: str, replica: int, plan=None) -> list:
    """One child's serve.py command line: the parent's serving shape
    flags forwarded EXPLICITLY (never raw argv — supervisor-only flags
    must not leak), socket mode on an ephemeral port, every durable
    artifact routed into the child's own workdir, and child K's slice
    of the fault plan (``FaultPlan.cli_for_child``)."""
    argv = [sys.executable, os.path.join(REPO, "scripts", "serve.py"),
            "--serve_port", "-1",
            "--serve_blackbox", os.path.join(workdir, "blackbox.json"),
            "--serve_heartbeat_file",
            os.path.join(workdir, "heartbeat.json"),
            "--serve_telemetry_file",
            os.path.join(workdir, "telemetry.json"),
            # Per-child span traces (ISSUE 17): each child writes its
            # own Chrome-trace files here; scripts/fleet_trace.py
            # rebases them onto the supervisor's timeline (via
            # clock_sync.json) and merges ONE Perfetto file.
            "--trace_dir", os.path.join(workdir, "trace"),
            "--loglevel", "WARNING"]
    forward = [("--serve_demo", opt.serve_demo),
               ("--serve_demo_eos_bias", opt.serve_demo_eos_bias),
               ("--beam_size", opt.beam_size),
               ("--max_length", opt.max_length),
               ("--length_norm", opt.length_norm),
               ("--decode_chunk", getattr(opt, "decode_chunk", 8)),
               ("--serve_buckets", opt.serve_buckets),
               ("--serve_queue_limit", opt.serve_queue_limit),
               ("--serve_deadline_ms", opt.serve_deadline_ms),
               ("--serve_cache", opt.serve_cache),
               ("--serve_recover", opt.serve_recover),
               ("--serve_retry_limit", opt.serve_retry_limit),
               ("--serve_rebuild_limit", opt.serve_rebuild_limit),
               ("--serve_step_budget_ms", opt.serve_step_budget_ms),
               ("--serve_lifecycle", opt.serve_lifecycle),
               ("--serve_lifecycle_events", opt.serve_lifecycle_events),
               ("--wedge_timeout", opt.wedge_timeout),
               ("--compile_cache_dir",
                getattr(opt, "compile_cache_dir", ""))]
    for flag, val in forward:
        argv += [flag, str(val)]
    if not opt.serve_demo:
        argv += ["--checkpoint_path", opt.checkpoint_path,
                 "--test_label_h5", str(opt.test_label_h5),
                 "--test_info_json", str(opt.test_info_json)]
        argv += ["--test_feat_h5"] + [str(p) for p in opt.test_feat_h5]
        if opt.test_cocofmt_file:
            argv += ["--test_cocofmt_file", str(opt.test_cocofmt_file)]
    if plan is not None:
        child_plan = plan.cli_for_child(replica)
        if child_plan:
            argv += ["--fault_plan", child_plan]
    return argv


def make_launcher(opt, root: str, plan=None):
    """The supervisor's child factory: replica K lives in
    ``<root>/replica<K>/``; a RESTART reuses the same workdir (the
    incident harvest already copied the previous life's evidence)."""
    from cst_captioning_tpu.serving.supervisor import spawn_serve_child

    def launcher(replica: int):
        workdir = os.path.join(root, f"replica{replica}")
        os.makedirs(workdir, exist_ok=True)
        return spawn_serve_child(
            child_argv(opt, workdir, replica, plan=plan),
            workdir, replica, env=dict(os.environ))

    return launcher


def build_autoscaler(opt, root: str, fleet_obs, *, registry=None,
                     lifecycle=None):
    """The attribution-driven autoscaler (serving/autoscale.py, ISSUE
    19) — armed by ``--autoscale_max > 0``, disarmed (None) otherwise.
    The decisions log lands next to fleet_metrics.jsonl so
    collect_evidence bundles them together."""
    if getattr(opt, "autoscale_max", 0) <= 0:
        return None
    from cst_captioning_tpu.serving.autoscale import Autoscaler

    hi = float(opt.autoscale_queue_hi_ms)
    return Autoscaler(
        fleet_obs,
        min_replicas=opt.autoscale_min,
        max_replicas=max(opt.autoscale_max, opt.autoscale_min),
        queue_hi_ms=hi, queue_lo_ms=hi / 10.0,
        up_cooldown_s=float(opt.autoscale_up_cooldown_s),
        down_cooldown_s=float(opt.autoscale_down_cooldown_s),
        out_dir=root, registry=registry, lifecycle=lifecycle)


def build_journal(opt):
    """The durable intake journal (serving/journal.py, ISSUE 20) —
    armed by ``--journal_dir``, disarmed (None) otherwise."""
    if not getattr(opt, "journal_dir", None):
        return None
    from cst_captioning_tpu.serving.journal import IntakeJournal

    return IntakeJournal(opt.journal_dir,
                         segment_bytes=opt.journal_segment_bytes,
                         compact=bool(opt.journal_compact))


def build_supervisor(opt, root: str, *, plan=None, registry=None,
                     lifecycle=None, fleet_obs=None, autoscaler=None,
                     journal=None):
    from cst_captioning_tpu.serving.supervisor import ProcessFleetSupervisor

    # An armed autoscaler owns the fleet size: boot at --autoscale_min
    # and let the decisions log explain every change from there.
    replicas = (opt.autoscale_min if autoscaler is not None
                else opt.supervise_replicas)
    return ProcessFleetSupervisor(
        make_launcher(opt, root, plan=plan), replicas,
        restart_limit=opt.supervise_restart_limit,
        backoff_ms=opt.supervise_backoff_ms,
        wedge_timeout_s=opt.wedge_timeout,
        incident_dir=os.path.join(root, "incidents"),
        fault_plan=plan, registry=registry, lifecycle=lifecycle,
        fleet_obs=fleet_obs, autoscaler=autoscaler, journal=journal)


def replay_and_ledger(sup, root: str) -> dict:
    """Replay the journal into the freshly-built supervisor and write
    the recovery ledger where the incident machinery lives, so every
    replayed id is auditable (collect_evidence bundles it)."""
    from cst_captioning_tpu.resilience.integrity import atomic_json_write

    ledger = sup.replay_journal()
    if not ledger.get("enabled"):
        return ledger
    try:
        atomic_json_write(os.path.join(root, "recovery_ledger.json"),
                          ledger, indent=2)
    except OSError as e:
        print(f"serve_supervisor: recovery ledger write failed: {e}",
              file=sys.stderr)
    n = len(ledger.get("replayed") or [])
    if n or ledger.get("torn_records"):
        print(f"serve_supervisor: journal replay: {n} request(s) "
              f"re-entered, {ledger.get('recovered_terminals', 0)} "
              f"already terminal, {ledger.get('torn_records', 0)} torn "
              "record(s) dropped", file=sys.stderr)
    return ledger


def build_observability(opt, root: str, registry):
    """Arm the supervisor's own telemetry plane (ISSUE 17): span tracer
    (``<root>/trace/``, the supervisor row of the merged fleet trace),
    lifecycle flight recorder, and the FleetObs scraper + clock sync +
    SLO monitor (always on in supervisor runs; cadence from
    ``--fleet_scrape_ms``, objectives from ``--slo_*`` — each 0 simply
    disables that objective, never the scrape).

    Returns ``(tracer, lifecycle, fleet_obs)`` — tracer/lifecycle are
    None when ``--serve_lifecycle 0`` / tracing is declined, fleet_obs
    is always real."""
    from cst_captioning_tpu.telemetry.fleetobs import FleetObs, SLOMonitor

    tracer = None
    lifecycle = None
    if opt.serve_lifecycle:
        from cst_captioning_tpu.telemetry.lifecycle import LifecycleTracer
        from cst_captioning_tpu.telemetry.spans import SpanTracer

        tracer = SpanTracer(os.path.join(root, "trace"))
        lifecycle = LifecycleTracer(opt.serve_lifecycle_events,
                                    tracer=tracer, registry=registry)
    slo = SLOMonitor(p99_ms=opt.slo_p99_ms,
                     availability=opt.slo_availability,
                     error_rate=opt.slo_error_rate,
                     lifecycle=lifecycle, registry=registry)
    fleet_obs = FleetObs(root,
                         scrape_interval_s=opt.fleet_scrape_ms / 1000.0,
                         slo=slo, registry=registry, lifecycle=lifecycle)
    return tracer, lifecycle, fleet_obs


def close_observability(tracer, fleet_obs) -> None:
    """Flush the plane's durable artifacts (final fsync + clock_sync
    + the tracer's trace_<pid>.json) — safe to call on any exit path."""
    try:
        fleet_obs.close()
    except OSError as e:
        print(f"serve_supervisor: fleet_obs close failed: {e}",
              file=sys.stderr)
    if tracer is not None:
        try:
            tracer.close()
        except OSError as e:
            print(f"serve_supervisor: tracer close failed: {e}",
                  file=sys.stderr)


def write_supervisor_exit(root: str, rc: int, sup, registry) -> None:
    """The supervisor's own exit snapshot (the train.py discipline):
    final stats + fleet health + registry telemetry, atomically, where
    collect_evidence finds it next to the incident bundles.  With the
    intake journal armed, the top-level ``journal`` block records the
    durable segment + offset high-water mark so fleet_report.py can
    cross-check that no accepted id is missing from both the journal
    and a terminal response (ISSUE 20)."""
    from cst_captioning_tpu.resilience.integrity import atomic_json_write

    doc = {"rc": rc, "stats": sup.stats(),
           "health": sup.health_payload(),
           "telemetry": registry.snapshot()}
    journal = getattr(sup, "_journal", None)
    if journal is not None:
        doc["journal"] = journal.stats()
    try:
        atomic_json_write(
            os.path.join(root, "supervisor_exit.json"), doc, indent=2)
    except OSError as e:
        print(f"serve_supervisor: exit snapshot write failed: {e}",
              file=sys.stderr)


# ---------------------------------------------------------------------------
# the seeded process-chaos drill (--supervise_probe 1)
# ---------------------------------------------------------------------------


def _single_engine_reference(opt, root: str, video_ids) -> dict:
    """The fault-free twin: ONE serve.py child, no fault plan, each
    unique video captioned once — the bit-identity reference."""
    from cst_captioning_tpu.serving.supervisor import spawn_serve_child

    workdir = os.path.join(root, "reference")
    os.makedirs(workdir, exist_ok=True)
    child = spawn_serve_child(child_argv(opt, workdir, 0, plan=None),
                              workdir, 0, env=dict(os.environ))
    captions = {}
    try:
        for i, vid in enumerate(video_ids):
            child.send_line(json.dumps({"id": f"ref{i}",
                                        "video_id": vid}))
        deadline = time.monotonic() + 300.0
        while len(captions) < len(video_ids):
            if time.monotonic() > deadline:
                raise RuntimeError(
                    "reference child timed out with "
                    f"{len(captions)}/{len(video_ids)} answered")
            if child.poll() is not None:
                raise RuntimeError(
                    f"reference child exited {child.poll()} early")
            got = child.lines()
            if not got:
                time.sleep(0.01)
            for raw in got:
                obj = json.loads(raw)
                if "caption" in obj:
                    captions[obj["video_id"]] = obj["caption"]
    finally:
        child.terminate()
        child.close()
    return captions


def run_probe(opt) -> int:
    """The acceptance drill, machine-checked: SIGKILL one replica
    mid-stream at ``--supervise_replicas`` children; every request must
    be answered, captions bit-identical to the fault-free single-engine
    reference, zero post-warmup compiles per surviving child, and the
    killed replica's blackbox harvested into an incident bundle.
    Prints the one-JSON-line record scripts/serve_report.py renders and
    gates."""
    from cst_captioning_tpu.resilience.faults import FaultPlan
    from cst_captioning_tpu.serving.supervisor import SupervisorUnrecoverable
    from cst_captioning_tpu.telemetry.registry import MetricsRegistry

    root = opt.supervise_dir or tempfile.mkdtemp(prefix="cst_supervise_")
    os.makedirs(root, exist_ok=True)
    plan = FaultPlan.parse(getattr(opt, "fault_plan", None)
                           or "proc_kill@replica=1")
    registry = MetricsRegistry()
    plan.bind_metrics(registry)
    log.warning("CHAOS: process fault plan armed: %s", plan)
    killed_replica = next((s.replica for s in plan.specs
                           if s.kind == "proc_kill"), None)

    num_requests = 18
    video_ids = [f"v{i % 16}" for i in range(num_requests)]
    answers: dict = {i: [] for i in range(num_requests)}

    tracer, lifecycle, fleet_obs = build_observability(opt, root, registry)
    if lifecycle is not None:
        lifecycle.attach(
            counters=lambda: registry.snapshot().get("counters"))
    sup = build_supervisor(opt, root, plan=plan, registry=registry,
                           lifecycle=lifecycle, fleet_obs=fleet_obs)
    rc = 0
    try:
        # Capture every child's post-warm compile baseline BEFORE
        # traffic (engine.warm() ran before the port announcement, so
        # anything beyond this baseline is a post-warmup compile).
        deadline = time.monotonic() + 120.0
        while any(r.live and r.compiles0 is None for r in sup._replicas):
            sup.tick()
            if time.monotonic() > deadline:
                raise RuntimeError("children never answered health")
            time.sleep(0.01)

        t0 = time.monotonic()
        for i, vid in enumerate(video_ids):
            sup.submit(i, vid, respond=answers[i].append, stream=True)
        deadline = time.monotonic() + 600.0
        while sup.outstanding:
            if not sup.tick():
                time.sleep(0.005)
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"drill timed out with {sup.outstanding} of "
                    f"{num_requests} unanswered")
        makespan = time.monotonic() - t0

        # Let the fleet HEAL before judging it: the killed replica's
        # backoff expires and its restart hatches (seconds of jax
        # import in the new child) — the record must show the restart
        # actually happened, not merely that it was scheduled.
        heal = time.monotonic() + 180.0
        while not all(r.live for r in sup._replicas):
            sup.tick()
            if time.monotonic() > heal:
                raise RuntimeError(
                    "fleet never healed: "
                    + str([r.state for r in sup._replicas]))
            time.sleep(0.02)

        # Post-drill: zero post-warmup compiles per SURVIVING child
        # (a restarted child re-warmed before announcing — its own
        # generation's baseline applies).
        for k in range(len(sup._replicas)):
            sup.request_stats(k)
        settle = time.monotonic() + 30.0
        while time.monotonic() < settle and any(
                r.live and r.last_stats is None for r in sup._replicas):
            sup.tick()
            time.sleep(0.01)
        recompiles = 0
        for rep in sup._replicas:
            if not rep.live or rep.compiles0 is None:
                continue
            now_c = (rep.last_stats or rep.health or {}).get("compiles")
            if now_c is not None:
                recompiles += max(0, int(now_c) - int(rep.compiles0))

        finals = {}
        prefix_ok = True
        chunks_total = 0
        completed = 0
        for i in range(num_requests):
            terminal = [a for a in answers[i]
                        if a.get("final") or "error" in a]
            assert len(terminal) == 1, (
                f"request {i} got {len(terminal)} terminals: "
                f"{answers[i]}")
            fin = terminal[0]
            if "caption" in fin:
                completed += 1
                finals[i] = fin["caption"]
                chunks = [a for a in answers[i]
                          if a.get("stream") and not a.get("final")]
                chunks_total += len(chunks)
                seqs = [c["seq"] for c in chunks]
                text = " ".join(c["text"] for c in chunks
                                if c["text"]).strip()
                if seqs != list(range(len(seqs))) \
                        or text != fin["caption"]:
                    prefix_ok = False

        reference = _single_engine_reference(
            opt, root, sorted(set(video_ids)))
        mismatches = sum(
            1 for i, cap in finals.items()
            if reference.get(video_ids[i]) != cap)
        parity_ok = (completed == num_requests and mismatches == 0)

        stats = sup.stats()
        c = stats["supervisor"]
        incidents = stats["incidents"]
        blackbox_harvested = any(
            "blackbox.json" in (inc.get("files") or [])
            for inc in incidents
            if killed_replica is None
            or inc.get("replica") == killed_replica)
        budget_ok = c["sup_replica_deaths"] == 0
        lat = [stats.get("latency_p50_ms"), stats.get("latency_p99_ms")]

        # ISSUE 17 evidence: the SLO verdict and the fleet-plane
        # artifact paths ride the record so serve_report can gate on a
        # burn-rate violation and collect_evidence can bundle the
        # series + clock table + traces.
        slo_status = fleet_obs.slo_status()
        slo_ok = not slo_status.get("firing")
        sync_children = fleet_obs.clock_sync.doc()["children"]

        record = {
            "metric": SERVE_METRIC, "schema": 1,
            "value": round(completed / makespan, 2) if makespan else None,
            "platform": "cpu" if os.environ.get(
                "JAX_PLATFORMS") == "cpu" else "supervised",
            "completed": completed, "num_requests": num_requests,
            "shed": c["sup_shed"], "makespan_s": round(makespan, 3),
            "latency_p50_ms": lat[0], "latency_p99_ms": lat[1],
            "beam_size": opt.beam_size,
            "decode_chunk": getattr(opt, "decode_chunk", 8),
            "buckets": opt.serve_buckets,
            "recompiles_after_warmup": recompiles,
            "stream": {"enabled": True, "prefix_ok": prefix_ok,
                       "chunks": chunks_total},
            "slo": {"enabled": slo_status.get("enabled", False),
                    "firing": slo_status.get("firing", []),
                    "alerts_fired": slo_status.get("alerts_fired", 0),
                    "alerts_cleared": slo_status.get("alerts_cleared", 0),
                    "ok": slo_ok},
            "fleet_obs": {
                "samples": len(fleet_obs.series()),
                "metrics_file": fleet_obs.metrics_path,
                "clock_synced_pids": len(sync_children),
                "trace_dir": os.path.join(root, "trace"),
            },
            "supervisor": {
                "enabled": True,
                "replicas": opt.supervise_replicas,
                "restart_limit": opt.supervise_restart_limit,
                "killed_replica": killed_replica,
                "restarts": c["sup_replica_restarts"],
                "requeued": c["sup_requeued"],
                "deaths": c["sup_replica_deaths"],
                "wedge_kills": c["sup_wedge_kills"],
                "budget_ok": budget_ok,
                "parity_ok": parity_ok,
                "parity_mismatches": mismatches,
                "incidents": len(incidents),
                "blackbox_harvested": blackbox_harvested,
                "per_replica": stats["per_replica"],
            },
        }
        print(json.dumps(record))
        report = {
            "answered": completed == num_requests,
            "parity_ok": parity_ok, "prefix_ok": prefix_ok,
            "recompiles": recompiles, "budget_ok": budget_ok,
            "blackbox_harvested": blackbox_harvested,
            "slo_ok": slo_ok,
        }
        print(f"serve_supervisor: probe {json.dumps(report)}",
              file=sys.stderr)
        if not all([report["answered"], parity_ok, prefix_ok,
                    recompiles == 0, blackbox_harvested, slo_ok]):
            rc = 1
    except SupervisorUnrecoverable as e:
        from cst_captioning_tpu.resilience.exitcodes import (EXIT_WEDGE,
                                                             describe)

        print(f"serve_supervisor: UNRECOVERABLE: {e}; exiting "
              f"{EXIT_WEDGE} ({describe(EXIT_WEDGE)})", file=sys.stderr)
        rc = EXIT_WEDGE
    finally:
        sup.shutdown()
        close_observability(tracer, fleet_obs)
        write_supervisor_exit(root, rc, sup, registry)
        print("serve_supervisor: " + json.dumps(sup.supervisor_counters()),
              file=sys.stderr)
    return rc


# ---------------------------------------------------------------------------
# the seeded 3-phase autoscale drill (--autoscale_probe 1)
# ---------------------------------------------------------------------------


def run_autoscale_probe(opt) -> int:
    """The ISSUE 19 acceptance drill, machine-checked: idle -> 4x burst
    -> idle through the real CLI.  The fleet boots at ``--autoscale_min``
    children, must scale up within the scrape-interval budget once the
    burst's queue_wait attribution burns, scale back down in the final
    idle phase, answer EVERY request exactly once bit-identical to a
    fault-free single-engine reference, and pay zero post-warmup
    compiles on surviving children.  Prints the one-JSON-line record
    scripts/serve_report.py renders and gates; the durable decisions
    log + fleet_metrics.jsonl feed scripts/fleet_report.py's no-thrash
    / no-loss / brownout gates."""
    from cst_captioning_tpu.serving.supervisor import SupervisorUnrecoverable
    from cst_captioning_tpu.telemetry.registry import MetricsRegistry

    root = opt.supervise_dir or tempfile.mkdtemp(prefix="cst_autoscale_")
    os.makedirs(root, exist_ok=True)
    if opt.autoscale_max <= 0:
        opt.autoscale_max = max(3, opt.autoscale_min + 1)
    if not opt.serve_lifecycle:
        # The decision signal IS the children's latency attribution —
        # without their lifecycle plane there is nothing to scale on.
        log.warning("autoscale probe: forcing --serve_lifecycle 1 "
                    "(attribution is the autoscaler's input)")
        opt.serve_lifecycle = 1
    registry = MetricsRegistry()

    idle_n = 3
    video_ids: list = []
    answers: dict = {}

    tracer, lifecycle, fleet_obs = build_observability(opt, root, registry)
    if lifecycle is not None:
        lifecycle.attach(
            counters=lambda: registry.snapshot().get("counters"))
    autoscaler = build_autoscaler(opt, root, fleet_obs,
                                  registry=registry, lifecycle=lifecycle)
    sup = build_supervisor(opt, root, registry=registry,
                           lifecycle=lifecycle, fleet_obs=fleet_obs,
                           autoscaler=autoscaler)
    scrape_s = opt.fleet_scrape_ms / 1000.0
    rc = 0
    try:
        deadline = time.monotonic() + 120.0
        while any(r.live and r.compiles0 is None for r in sup._replicas):
            sup.tick()
            if time.monotonic() > deadline:
                raise RuntimeError("children never answered health")
            time.sleep(0.01)
        assert sup.active_replicas() == opt.autoscale_min, (
            "fleet must START at --autoscale_min, got "
            f"{sup.active_replicas()}")

        def submit(i: int) -> None:
            video_ids.append(f"v{i % 12}")
            answers[i] = []
            sup.submit(i, video_ids[i], respond=answers[i].append,
                       stream=True)

        def pump(until: float, stop=None) -> None:
            while time.monotonic() < until:
                if not sup.tick():
                    time.sleep(0.005)
                if stop is not None and stop():
                    return

        t0 = time.monotonic()
        # Phase 1 — idle trickle: the fleet must NOT grow on this.
        for i in range(idle_n):
            submit(i)
            pump(time.monotonic() + 2 * scrape_s)
        base_after_idle = sup.active_replicas()

        # Phase 2 — the 4x overload storm, open-loop at the fleet's
        # edge: a fleet that is too small grows its QUEUE, not its
        # arrival gaps, so keep ~4 replicas' worth of work standing in
        # front of the --autoscale_min children however fast this
        # machine's demo decode is.  The standing queue keeps the
        # queue_wait attribution burning for full fast+slow windows —
        # a sub-window blip is exactly what the damping must ignore.
        backlog = max(8, 4 * opt.autoscale_min * 4)
        # Scale-up budget: N scrape intervals (the acceptance bar) —
        # generous wall-clock floor so slow CI child spawns don't flake
        # the drill.
        budget_intervals = 40
        up_deadline = time.monotonic() + max(budget_intervals * scrape_s,
                                             60.0)
        i = idle_n
        while time.monotonic() < up_deadline:
            if sup.active_replicas() > opt.autoscale_min:
                break
            while sup.outstanding < backlog:
                submit(i)
                i += 1
            if not sup.tick():
                time.sleep(0.005)
        scaled_up = sup.active_replicas() > opt.autoscale_min
        up_intervals = (time.monotonic() - t0) / scrape_s

        # Drain the storm completely (every request answered, however
        # long the queue got).
        deadline = time.monotonic() + 600.0
        while sup.outstanding:
            if not sup.tick():
                time.sleep(0.005)
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"drill timed out with {sup.outstanding} of "
                    f"{len(answers)} unanswered")

        # Phase 3 — idle again: the extra replicas must drain out.
        for _ in range(idle_n):
            submit(i)
            i += 1
            pump(time.monotonic() + 2 * scrape_s)
        num_requests = len(answers)
        down_deadline = time.monotonic() + 120.0
        pump(down_deadline,
             stop=lambda: (sup.active_replicas() <= opt.autoscale_min
                           and not sup.outstanding))
        while sup.outstanding:
            if not sup.tick():
                time.sleep(0.005)
            if time.monotonic() > deadline:
                raise RuntimeError("phase-3 requests unanswered")
        scaled_down = sup.active_replicas() <= opt.autoscale_min
        makespan = time.monotonic() - t0

        # Post-drill: zero post-warmup compiles on SURVIVING children.
        for k in range(len(sup._replicas)):
            if sup._replicas[k].live:
                sup.request_stats(k)
        settle = time.monotonic() + 30.0
        while time.monotonic() < settle and any(
                r.live and r.last_stats is None for r in sup._replicas):
            sup.tick()
            time.sleep(0.01)
        recompiles = 0
        for rep in sup._replicas:
            if not rep.live or rep.compiles0 is None:
                continue
            now_c = (rep.last_stats or rep.health or {}).get("compiles")
            if now_c is not None:
                recompiles += max(0, int(now_c) - int(rep.compiles0))

        finals = {}
        completed = 0
        prefix_ok = True
        for i in range(num_requests):
            terminal = [a for a in answers[i]
                        if a.get("final") or "error" in a]
            assert len(terminal) == 1, (
                f"request {i} got {len(terminal)} terminals: "
                f"{answers[i]}")
            fin = terminal[0]
            if "caption" in fin:
                completed += 1
                finals[i] = fin["caption"]
                chunks = [a for a in answers[i]
                          if a.get("stream") and not a.get("final")]
                seqs = [c["seq"] for c in chunks]
                text = " ".join(c["text"] for c in chunks
                                if c["text"]).strip()
                if seqs != list(range(len(seqs))) \
                        or text != fin["caption"]:
                    prefix_ok = False

        reference = _single_engine_reference(
            opt, root, sorted(set(video_ids)))
        mismatches = sum(
            1 for i, cap in finals.items()
            if reference.get(video_ids[i]) != cap)
        parity_ok = (completed == num_requests and mismatches == 0)

        stats = sup.stats()
        c = stats["supervisor"]
        asc = stats.get("autoscale") or {}
        budget_ok = c["sup_replica_deaths"] == 0
        slo_status = fleet_obs.slo_status()
        slo_ok = not slo_status.get("firing")
        lat = [stats.get("latency_p50_ms"), stats.get("latency_p99_ms")]
        # No-thrash at the source: the replica count changed exactly
        # twice (one up, one down) in a clean run; <= 4 tolerates one
        # extra round trip without calling the drill dead.
        changes = (asc.get("scale_ups", 0) + asc.get("scale_downs", 0))
        no_thrash = changes <= 4

        record = {
            "metric": SERVE_METRIC, "schema": 1,
            "value": round(completed / makespan, 2) if makespan else None,
            "platform": "cpu" if os.environ.get(
                "JAX_PLATFORMS") == "cpu" else "supervised",
            "completed": completed, "num_requests": num_requests,
            "shed": c["sup_shed"], "makespan_s": round(makespan, 3),
            "latency_p50_ms": lat[0], "latency_p99_ms": lat[1],
            "beam_size": opt.beam_size,
            "decode_chunk": getattr(opt, "decode_chunk", 8),
            "buckets": opt.serve_buckets,
            "recompiles_after_warmup": recompiles,
            "stream": {"enabled": True, "prefix_ok": prefix_ok},
            "slo": {"enabled": slo_status.get("enabled", False),
                    "firing": slo_status.get("firing", []),
                    "alerts_fired": slo_status.get("alerts_fired", 0),
                    "alerts_cleared": slo_status.get("alerts_cleared", 0),
                    "ok": slo_ok},
            "fleet_obs": {
                "samples": len(fleet_obs.series()),
                "metrics_file": fleet_obs.metrics_path,
                "trace_dir": os.path.join(root, "trace"),
            },
            "supervisor": {
                "enabled": True,
                "replicas": len(sup._replicas),
                "restart_limit": opt.supervise_restart_limit,
                "killed_replica": None,
                "restarts": c["sup_replica_restarts"],
                "requeued": c["sup_requeued"],
                "deaths": c["sup_replica_deaths"],
                "wedge_kills": c["sup_wedge_kills"],
                "budget_ok": budget_ok,
                "parity_ok": parity_ok,
                "parity_mismatches": mismatches,
                "incidents": len(stats["incidents"]),
                "blackbox_harvested": True,
                "per_replica": stats["per_replica"],
            },
            "autoscale": {
                "enabled": True,
                "min": opt.autoscale_min, "max": opt.autoscale_max,
                "started_at_min": base_after_idle == opt.autoscale_min,
                "scaled_up": scaled_up,
                "scale_up_intervals": round(up_intervals, 1),
                "scale_up_budget_intervals": budget_intervals,
                "scaled_down": scaled_down,
                "scale_ups": asc.get("scale_ups", 0),
                "scale_downs": asc.get("scale_downs", 0),
                "replica_changes": changes,
                "no_thrash": no_thrash,
                "brownout_entries": asc.get("brownout_entries", 0),
                "rung": asc.get("rung", 0),
                "decisions": asc.get("decisions", 0),
                "decisions_file": autoscaler.decisions_path,
                "answered_ok": completed == num_requests,
            },
        }
        print(json.dumps(record))
        report = {
            "answered": completed == num_requests,
            "parity_ok": parity_ok, "prefix_ok": prefix_ok,
            "recompiles": recompiles, "budget_ok": budget_ok,
            "started_at_min": base_after_idle == opt.autoscale_min,
            "scaled_up": scaled_up, "scaled_down": scaled_down,
            "no_thrash": no_thrash,
        }
        print(f"serve_supervisor: autoscale probe {json.dumps(report)}",
              file=sys.stderr)
        if not all([report["answered"], parity_ok, prefix_ok,
                    recompiles == 0, budget_ok,
                    report["started_at_min"], scaled_up, scaled_down,
                    no_thrash]):
            rc = 1
    except SupervisorUnrecoverable as e:
        from cst_captioning_tpu.resilience.exitcodes import (EXIT_WEDGE,
                                                             describe)

        print(f"serve_supervisor: UNRECOVERABLE: {e}; exiting "
              f"{EXIT_WEDGE} ({describe(EXIT_WEDGE)})", file=sys.stderr)
        rc = EXIT_WEDGE
    finally:
        sup.shutdown()
        close_observability(tracer, fleet_obs)
        write_supervisor_exit(root, rc, sup, registry)
        print("serve_supervisor: " + json.dumps(sup.supervisor_counters()),
              file=sys.stderr)
    return rc


# ---------------------------------------------------------------------------
# the supervisor-death journal drill (--journal_probe 1, ISSUE 20)
# ---------------------------------------------------------------------------


def _supervisor_argv(opt, root: str, journal_dir: str) -> list:
    """A whole serve_supervisor.py command line for the journal drill:
    the drill spawns the SUPERVISOR itself as a subprocess (socket
    mode, ephemeral port) so SIGKILLing it is a real process death,
    not an in-process simulation.  Serving shape flags are forwarded
    explicitly, like :func:`child_argv` — both incarnations get the
    byte-identical argv, which is the point: recovery must come from
    the journal, not from flags."""
    argv = [sys.executable,
            os.path.join(REPO, "scripts", "serve_supervisor.py"),
            "--serve_port", "-1",
            "--supervise_dir", root,
            "--journal_dir", journal_dir,
            "--loglevel", "WARNING"]
    forward = [("--supervise_replicas", opt.supervise_replicas),
               ("--supervise_restart_limit", opt.supervise_restart_limit),
               ("--supervise_backoff_ms", opt.supervise_backoff_ms),
               ("--journal_segment_bytes", opt.journal_segment_bytes),
               ("--journal_compact", opt.journal_compact),
               ("--fleet_scrape_ms", opt.fleet_scrape_ms),
               ("--slo_p99_ms", opt.slo_p99_ms),
               ("--slo_availability", opt.slo_availability),
               ("--slo_error_rate", opt.slo_error_rate),
               ("--serve_demo", opt.serve_demo),
               ("--serve_demo_eos_bias", opt.serve_demo_eos_bias),
               ("--beam_size", opt.beam_size),
               ("--max_length", opt.max_length),
               ("--length_norm", opt.length_norm),
               ("--decode_chunk", getattr(opt, "decode_chunk", 8)),
               ("--serve_buckets", opt.serve_buckets),
               ("--serve_queue_limit", opt.serve_queue_limit),
               ("--serve_deadline_ms", opt.serve_deadline_ms),
               ("--serve_cache", opt.serve_cache),
               ("--serve_recover", opt.serve_recover),
               ("--serve_retry_limit", opt.serve_retry_limit),
               ("--serve_rebuild_limit", opt.serve_rebuild_limit),
               ("--serve_step_budget_ms", opt.serve_step_budget_ms),
               ("--serve_lifecycle", opt.serve_lifecycle),
               ("--serve_lifecycle_events", opt.serve_lifecycle_events),
               ("--wedge_timeout", opt.wedge_timeout),
               ("--compile_cache_dir",
                getattr(opt, "compile_cache_dir", ""))]
    for flag, val in forward:
        argv += [flag, str(val)]
    if not opt.serve_demo:
        argv += ["--checkpoint_path", opt.checkpoint_path,
                 "--test_label_h5", str(opt.test_label_h5),
                 "--test_info_json", str(opt.test_info_json)]
        argv += ["--test_feat_h5"] + [str(p) for p in opt.test_feat_h5]
        if opt.test_cocofmt_file:
            argv += ["--test_cocofmt_file", str(opt.test_cocofmt_file)]
    return argv


def _is_terminal(obj: dict) -> bool:
    return bool(obj.get("final")) or "error" in obj


def _drain_into(child, answers: dict) -> None:
    for raw in child.lines():
        try:
            obj = json.loads(raw)
        except ValueError:
            continue
        rid = obj.get("id")
        if rid is not None:
            answers.setdefault(rid, []).append(obj)


def _wire_stats(child, answers: dict, timeout_s: float = 30.0) -> dict:
    """One {"op": "stats"} round trip; stray request lines that arrive
    interleaved are routed into ``answers``, never dropped."""
    child.send_line(json.dumps({"op": "stats"}))
    end = time.monotonic() + timeout_s
    while time.monotonic() < end:
        for raw in child.lines():
            try:
                obj = json.loads(raw)
            except ValueError:
                continue
            if obj.get("op") == "stats":
                return obj
            if obj.get("id") is not None:
                answers.setdefault(obj["id"], []).append(obj)
        if child.poll() is not None:
            raise RuntimeError(
                f"supervisor exited {child.poll()} during stats query")
        time.sleep(0.005)
    raise RuntimeError("supervisor stats query timed out")


def run_journal_probe(opt) -> int:
    """The ISSUE 20 acceptance drill, machine-checked, through the real
    CLI: storm a journal-armed supervisor SUBPROCESS with streams in
    flight, SIGKILL the whole supervisor process group mid-storm (the
    coordinator and its children die together — the worst-case death),
    relaunch on the same ``--journal_dir``, resubmit every id with its
    idempotency key and stream watermark, and pin:

    - exactly once: every accepted id answered, never twice
      authoritatively — already-terminal ids are answered from the
      journal (``idempotent: true``) with zero decode work;
    - bit-identity: every caption equals the fault-free single-engine
      twin's, across the crash;
    - prefix consistency: pre-kill chunks + post-relaunch chunks form
      one gapless prefix of the final caption;
    - replay accounting: the recovery ledger covers every accepted id
      (replayed + recovered-terminal == accepted), at most one torn
      record, journal open-set empty at clean exit;
    - zero post-warmup compiles in the relaunched incarnation.

    Prints the one-JSON-line record scripts/serve_report.py renders
    and exit-1 gates."""
    from cst_captioning_tpu.resilience.exitcodes import EXIT_PREEMPTED
    from cst_captioning_tpu.serving.supervisor import spawn_serve_child

    root = opt.supervise_dir or tempfile.mkdtemp(prefix="cst_journal_")
    os.makedirs(root, exist_ok=True)
    journal_dir = opt.journal_dir or os.path.join(root, "journal")
    argv = _supervisor_argv(opt, root, journal_dir)

    num_requests = 12
    kill_after_terminals = 2
    video_ids = [f"v{i % 6}" for i in range(num_requests)]
    qid = [f"q{i}" for i in range(num_requests)]

    reference = _single_engine_reference(opt, root, sorted(set(video_ids)))

    # ---- incarnation 1: storm, then SIGKILL the process group --------
    p1: dict = {}
    sup1 = spawn_serve_child(argv, os.path.join(root, "sup1"), 0,
                             env=dict(os.environ), startup_timeout_s=600.0,
                             new_session=True)
    t0 = time.monotonic()
    try:
        for i in range(num_requests):
            sup1.send_line(json.dumps(
                {"id": qid[i], "video_id": video_ids[i], "op": "stream",
                 "idem": f"k{i}"}))
        deadline = time.monotonic() + 300.0
        while True:
            if sup1.poll() is not None:
                raise RuntimeError(
                    f"supervisor exited {sup1.poll()} before the kill")
            _drain_into(sup1, p1)
            terms = sum(1 for objs in p1.values()
                        if any(_is_terminal(o) for o in objs))
            if terms >= kill_after_terminals:
                break
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"storm stalled: only {terms} terminal(s) in 300s")
            time.sleep(0.005)
        # The worst-case death: supervisor AND children in one shot
        # (new_session=True made the supervisor a process-group
        # leader, so killpg reaches every child it spawned).
        os.killpg(sup1.proc.pid, signal.SIGKILL)
        sup1.proc.wait()
        time.sleep(0.2)  # let the reader thread flush buffered lines
        _drain_into(sup1, p1)
    finally:
        sup1.close()

    p1_term = {r: [o for o in objs if _is_terminal(o)]
               for r, objs in p1.items()}
    terminals_at_kill = sum(1 for t in p1_term.values() if t)
    streams_in_flight = sum(
        1 for i in range(num_requests)
        if not p1_term.get(qid[i])
        and any(o.get("stream") and not o.get("final")
                for o in p1.get(qid[i], [])))
    killed_mid_storm = (terminals_at_kill >= 1 and streams_in_flight >= 1)

    # ---- incarnation 2: relaunch on the same journal, resubmit ------
    p2: dict = {}
    rc = 0
    sup2 = spawn_serve_child(argv, os.path.join(root, "sup2"), 0,
                             env=dict(os.environ), startup_timeout_s=600.0,
                             new_session=True)
    try:
        for i in range(num_requests):
            req = {"id": qid[i], "video_id": video_ids[i],
                   "op": "stream", "idem": f"k{i}"}
            seqs = [o["seq"] for o in p1.get(qid[i], [])
                    if o.get("stream") and not o.get("final")]
            if seqs:
                # The client-side watermark: chunks at or below this
                # seq were already delivered pre-kill; the attach path
                # must resume strictly past it.
                req["have_seq"] = max(seqs)
            sup2.send_line(json.dumps(req))
        deadline = time.monotonic() + 600.0
        while True:
            if sup2.poll() is not None:
                raise RuntimeError(
                    f"relaunched supervisor exited {sup2.poll()} early")
            _drain_into(sup2, p2)
            done = sum(1 for i in range(num_requests)
                       if any(_is_terminal(o)
                              for o in p2.get(qid[i], [])))
            if done >= num_requests:
                break
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"relaunch drill timed out with {done} of "
                    f"{num_requests} resubmits answered")
            time.sleep(0.005)
        makespan = time.monotonic() - t0

        # Duplicate-id suppression, pinned against the counters: one
        # extra submit of an already-terminal key must be answered
        # from the journal (idempotent, zero decode) without touching
        # sup_requests.
        stats_before = _wire_stats(sup2, p2)
        sup2.send_line(json.dumps(
            {"id": "qdup", "video_id": video_ids[0], "op": "stream",
             "idem": "k0"}))
        dup_deadline = time.monotonic() + 60.0
        while not any(_is_terminal(o) for o in p2.get("qdup", [])):
            if time.monotonic() > dup_deadline:
                raise RuntimeError("duplicate submit never answered")
            _drain_into(sup2, p2)
            time.sleep(0.005)
        stats_after = _wire_stats(sup2, p2)

        dup_fin = next(o for o in p2["qdup"] if _is_terminal(o))
        dup_suppressed = (
            dup_fin.get("idempotent") is True
            and dup_fin.get("caption") == reference.get(video_ids[0])
            and stats_after["supervisor"]["sup_requests"]
            == stats_before["supervisor"]["sup_requests"]
            and stats_after["supervisor"]["sup_journal_dup_hits"]
            > stats_before["supervisor"]["sup_journal_dup_hits"])

        recompiles = 0
        for rep in stats_after.get("per_replica") or []:
            if rep.get("compiles") is not None \
                    and rep.get("compiles0") is not None:
                recompiles += max(
                    0, int(rep["compiles"]) - int(rep["compiles0"]))
    finally:
        sup2.terminate()
        end = time.monotonic() + 120.0
        rc2 = None
        while time.monotonic() < end:
            rc2 = sup2.poll()
            if rc2 is not None:
                break
            time.sleep(0.05)
        sup2.close()
    clean_exit = rc2 == EXIT_PREEMPTED

    # ---- the durable evidence: ledger + exit snapshot ----------------
    ledger: dict = {}
    try:
        with open(os.path.join(root, "recovery_ledger.json")) as f:
            ledger = json.load(f)
    except (OSError, ValueError):
        pass
    exit_doc: dict = {}
    try:
        with open(os.path.join(root, "supervisor_exit.json")) as f:
            exit_doc = json.load(f)
    except (OSError, ValueError):
        pass

    replayed = ledger.get("replayed") or []
    replayed_keys = {r.get("key") for r in replayed}
    recovered_terminals = int(ledger.get("recovered_terminals") or 0)
    torn_records = int(ledger.get("torn_records") or 0)
    open_at_exit = (exit_doc.get("journal") or {}).get("open")

    # ---- gates -------------------------------------------------------
    completed = 0
    mismatches = 0
    exactly_once = True
    prefix_ok = True
    chunks_total = 0
    idempotent_answers = 0
    for i in range(num_requests):
        objs = p1.get(qid[i], []) + p2.get(qid[i], [])
        terminal = [o for o in objs if _is_terminal(o)]
        authoritative = [o for o in terminal if not o.get("idempotent")]
        idempotent_answers += len(terminal) - len(authoritative)
        if not terminal or len(authoritative) > 1:
            exactly_once = False
        captions = {o.get("caption") for o in terminal
                    if "caption" in o}
        if len(captions) != 1:
            exactly_once = False
            continue
        cap = captions.pop()
        completed += 1
        if cap != reference.get(video_ids[i]):
            mismatches += 1
        # Prefix consistency across the crash: pre-kill + post-attach
        # chunks, deduped by seq (the attach replay may legitimately
        # resend a chunk the OS socket buffer delivered at kill time),
        # must be one gapless prefix of the final caption.  A replay
        # that finished detached delivers the caption via the
        # idempotent terminal with no tail chunks — still a prefix.
        by_seq: dict = {}
        for o in objs:
            if o.get("stream") and not o.get("final"):
                if by_seq.setdefault(o["seq"], o["text"]) != o["text"]:
                    prefix_ok = False
        chunks_total += len(by_seq)
        if sorted(by_seq) != list(range(len(by_seq))):
            prefix_ok = False
            continue
        text = " ".join(by_seq[s] for s in sorted(by_seq)
                        if by_seq[s]).strip()
        if not cap.startswith(text):
            prefix_ok = False
    answered = completed == num_requests
    parity_ok = answered and mismatches == 0
    covered_ok = all(
        f"k{i}" in replayed_keys
        or any(o.get("idempotent") for o in p2.get(qid[i], [])
               if _is_terminal(o))
        for i in range(num_requests))
    replay_accounted = (
        covered_ok
        and len(replayed) + recovered_terminals == num_requests
        and open_at_exit == 0)
    torn_ok = torn_records <= 1

    c = stats_after["supervisor"]
    lat = [stats_after.get("latency_p50_ms"),
           stats_after.get("latency_p99_ms")]
    slo_status = stats_after.get("slo") or {}
    slo_ok = not slo_status.get("firing")
    record = {
        "metric": SERVE_METRIC, "schema": 1,
        "value": round(completed / makespan, 2) if makespan else None,
        "platform": "cpu" if os.environ.get(
            "JAX_PLATFORMS") == "cpu" else "supervised",
        "completed": completed, "num_requests": num_requests,
        "shed": c["sup_shed"], "makespan_s": round(makespan, 3),
        "latency_p50_ms": lat[0], "latency_p99_ms": lat[1],
        "beam_size": opt.beam_size,
        "decode_chunk": getattr(opt, "decode_chunk", 8),
        "buckets": opt.serve_buckets,
        "recompiles_after_warmup": recompiles,
        "stream": {"enabled": True, "prefix_ok": prefix_ok,
                   "chunks": chunks_total},
        "slo": {"enabled": slo_status.get("enabled", False),
                "firing": slo_status.get("firing", []),
                "alerts_fired": slo_status.get("alerts_fired", 0),
                "alerts_cleared": slo_status.get("alerts_cleared", 0),
                "ok": slo_ok},
        "supervisor": {
            "enabled": True,
            "replicas": opt.supervise_replicas,
            "restart_limit": opt.supervise_restart_limit,
            "killed_replica": None,
            "restarts": c["sup_replica_restarts"],
            "requeued": c["sup_requeued"],
            "deaths": c["sup_replica_deaths"],
            "wedge_kills": c["sup_wedge_kills"],
            "budget_ok": c["sup_replica_deaths"] == 0,
            "parity_ok": parity_ok,
            "parity_mismatches": mismatches,
            "incidents": len(stats_after.get("incidents") or []),
            "blackbox_harvested": True,
            "per_replica": stats_after.get("per_replica") or [],
        },
        "journal": {
            "enabled": True,
            "dir": journal_dir,
            "killed_mid_storm": killed_mid_storm,
            "terminals_before_kill": terminals_at_kill,
            "streams_in_flight_at_kill": streams_in_flight,
            "replayed": len(replayed),
            "recovered_terminals": recovered_terminals,
            "replay_accounted": replay_accounted,
            "exactly_once": exactly_once,
            "idempotent_answers": idempotent_answers,
            "dup_suppressed": dup_suppressed,
            "dup_hits": c["sup_journal_dup_hits"],
            "attached": c["sup_journal_attached"],
            "torn_records": torn_records,
            "torn_ok": torn_ok,
            "segments_scanned": ledger.get("segments_scanned"),
            "high_water": ledger.get("high_water"),
            "open_at_exit": open_at_exit,
            "relaunch_rc": rc2,
            "clean_exit": clean_exit,
        },
    }
    print(json.dumps(record))
    report = {
        "answered": answered, "exactly_once": exactly_once,
        "parity_ok": parity_ok, "prefix_ok": prefix_ok,
        "recompiles": recompiles,
        "replay_accounted": replay_accounted,
        "dup_suppressed": dup_suppressed, "torn_ok": torn_ok,
        "killed_mid_storm": killed_mid_storm, "clean_exit": clean_exit,
    }
    print(f"serve_supervisor: journal probe {json.dumps(report)}",
          file=sys.stderr)
    if not all([answered, exactly_once, parity_ok, prefix_ok,
                recompiles == 0, replay_accounted, dup_suppressed,
                torn_ok, killed_mid_storm, clean_exit]):
        rc = 1
    return rc


# ---------------------------------------------------------------------------
# serving mode
# ---------------------------------------------------------------------------


def run_serving(opt) -> int:
    from cst_captioning_tpu.resilience.faults import FaultPlan
    from cst_captioning_tpu.resilience.preemption import PreemptionHandler
    from cst_captioning_tpu.serving.supervisor import (SupervisorServer,
                                                       SupervisorUnrecoverable)
    from cst_captioning_tpu.telemetry.registry import MetricsRegistry

    handler = PreemptionHandler().install()
    registry = MetricsRegistry()
    plan = FaultPlan.parse(getattr(opt, "fault_plan", None))
    if plan is not None:
        plan.bind_metrics(registry)
        log.warning("CHAOS: process fault plan armed: %s", plan)

    root = opt.supervise_dir or tempfile.mkdtemp(prefix="cst_supervise_")
    os.makedirs(root, exist_ok=True)

    # The supervisor's OWN flight recorder + span tracer + the ISSUE 17
    # fleet plane: intake/route/requeue/terminal events per request
    # (dumped by the {"op": "dump"} wire op and the hard-abort/124
    # paths — the children each run their own), the supervisor row of
    # the merged fleet trace, the metrics scraper and the SLO monitor.
    tracer, lifecycle, fleet_obs = build_observability(opt, root, registry)

    autoscaler = build_autoscaler(opt, root, fleet_obs,
                                  registry=registry, lifecycle=lifecycle)
    journal = build_journal(opt)
    sup = build_supervisor(opt, root, plan=plan, registry=registry,
                           lifecycle=lifecycle, fleet_obs=fleet_obs,
                           autoscaler=autoscaler, journal=journal)
    # Children are live: replay the pre-crash journal BEFORE the wire
    # opens, so duplicate resubmits attach to the replay instead of
    # racing it (the recovery ledger lands next to the incidents).
    replay_and_ledger(sup, root)
    blackbox = (os.path.join(root, "blackbox.json")
                if opt.serve_blackbox else None)
    server = SupervisorServer(sup, handler=handler, registry=registry,
                              lifecycle=lifecycle, blackbox_path=blackbox)
    if lifecycle is not None:
        lifecycle.attach(
            health=server.health_payload,
            counters=lambda: registry.snapshot().get("counters"))

    watchdog = None
    if opt.serve_heartbeat_file or opt.wedge_timeout > 0:
        from cst_captioning_tpu.utils.watchdog import ProgressWatchdog

        watchdog = ProgressWatchdog(
            opt.wedge_timeout,
            describe=lambda: "supervisor scheduler loop",
            heartbeat_path=opt.serve_heartbeat_file,
            payload=lambda: {"serving": server.health_payload(),
                             **registry.heartbeat_payload()},
            heartbeat_interval_s=1.0).start()
        server.watchdog = watchdog
    rc = 0
    try:
        try:
            if opt.serve_port:
                port = 0 if opt.serve_port < 0 else opt.serve_port
                rc = server.run_socket(port)
            else:
                rc = server.run_stdin()
        except SupervisorUnrecoverable as e:
            from cst_captioning_tpu.resilience.exitcodes import (
                EXIT_WEDGE,
                describe,
            )

            print(f"serve_supervisor: UNRECOVERABLE: {e}; exiting "
                  f"{EXIT_WEDGE} ({describe(EXIT_WEDGE)})",
                  file=sys.stderr)
            if lifecycle is not None and blackbox:
                try:
                    lifecycle.dump(blackbox, reason="unrecoverable")
                    print(f"serve_supervisor: blackbox written to "
                          f"{blackbox}", file=sys.stderr)
                except OSError as werr:
                    print(f"serve_supervisor: blackbox write failed: "
                          f"{werr}", file=sys.stderr)
            sup.hard_abort()
            rc = EXIT_WEDGE
    finally:
        if watchdog is not None:
            watchdog.stop()
        close_observability(tracer, fleet_obs)
        stats = sup.stats()
        print("serve_supervisor: " + json.dumps(stats), file=sys.stderr)
        if opt.result_file:
            from cst_captioning_tpu.resilience.integrity import (
                atomic_json_write,
            )

            atomic_json_write(opt.result_file,
                              {"stats": stats,
                               "health": sup.health_payload(),
                               "telemetry": registry.snapshot()},
                              indent=2)
        write_supervisor_exit(root, rc, sup, registry)
    return rc


def main(argv=None) -> int:
    opt = parse_opts(argv)
    from cst_captioning_tpu.utils.platform import configure_cli_logging

    configure_cli_logging(opt.loglevel)
    # No jax import in THIS process — the supervisor is pure host code;
    # every accelerator touch happens inside the serve.py children.
    if not opt.serve_demo and not opt.test_feat_h5:
        print("serve_supervisor.py: checkpoint mode needs "
              "--test_feat_h5/--test_label_h5/--test_info_json (or pass "
              "--serve_demo 1)", file=sys.stderr)
        return 2
    if getattr(opt, "journal_probe", 0):
        return run_journal_probe(opt)
    if getattr(opt, "autoscale_probe", 0):
        return run_autoscale_probe(opt)
    if opt.supervise_probe:
        return run_probe(opt)
    return run_serving(opt)


if __name__ == "__main__":
    sys.exit(main())
