#!/usr/bin/env python
"""Zero-setup telemetry demo: short CPU train with --trace_dir, then the
scripts/trace_report.py per-phase table (`make trace-demo`).

Synthesizes a tiny dataset, runs one XE stage and one host-reward CST
stage (the host path is the one with a visible `score` phase) with span
tracing + step timing armed, then summarizes the trace dir and points at
the other artifacts a telemetry-enabled run produces:

- <trace_dir>/trace_*.json — load in Perfetto / chrome://tracing
- <ckpt>/metrics.jsonl     — schema-2 records with *_ms + mfu_pct gauges
- <ckpt>/telemetry.json    — exit snapshot (counters, last records)

OBSERVABILITY.md documents the span/metric taxonomy.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--out_dir", default="/tmp/cst_trace_demo")
    p.add_argument("--epochs", type=int, default=2)
    args = p.parse_args()

    from cst_captioning_tpu.data.synthetic import SyntheticSpec, generate
    from cst_captioning_tpu.data.vocab import load_vocab
    import train as train_cli

    root = os.path.join(args.out_dir, "data")
    ckpt = os.path.join(args.out_dir, "checkpoints")
    trace_dir = os.path.join(args.out_dir, "trace")
    os.makedirs(root, exist_ok=True)

    spec = SyntheticSpec(num_videos=16, captions_per_video=5, max_len=12,
                         feat_dims=(32, 16), feat_times=(4, 1))
    train = generate(root, "train", spec)
    vocab = load_vocab(train["vocab_json"])
    val = generate(root, "val",
                   SyntheticSpec(num_videos=8, captions_per_video=5,
                                 max_len=12, feat_dims=(32, 16),
                                 feat_times=(4, 1)), vocab=vocab)

    common = [
        "--train_feat_h5", *json.loads(train["feat_h5"]),
        "--train_label_h5", train["label_h5"],
        "--train_info_json", train["info_json"],
        "--train_cocofmt_file", train["cocofmt_json"],
        "--val_feat_h5", *json.loads(val["feat_h5"]),
        "--val_label_h5", val["label_h5"],
        "--val_info_json", val["info_json"],
        "--val_cocofmt_file", val["cocofmt_json"],
        "--batch_size", "8", "--seq_per_img", "4",
        "--rnn_size", "64", "--input_encoding_size", "32", "--att_size", "32",
        "--max_length", "12", "--drop_prob", "0.2",
        "--max_epochs", str(args.epochs), "--learning_rate", "0.01",
        "--log_every", "1", "--fast_val", "1", "--max_patience", "0",
        "--trace_dir", trace_dir,
    ]

    print("=== stage 1/2: XE with span tracing ===")
    train_cli.main([*common, "--checkpoint_path", f"{ckpt}/xe"])

    print("=== stage 2/2: CST (host rewards — shows the `score` phase) ===")
    train_cli.main([
        *common, "--checkpoint_path", f"{ckpt}/cst",
        "--start_from", f"{ckpt}/xe",
        "--use_rl", "1", "--rl_baseline", "greedy",
        "--device_rewards", "0", "--overlap_rewards", "1",
        "--train_cached_tokens", train["cached_tokens"],
        "--learning_rate", "0.0005", "--max_epochs", "1",
    ])

    print("\n=== per-phase trace summary ===")
    import trace_report

    spans, instants, asyncs, files = trace_report.load_events(trace_dir)
    wall_ms = trace_report.traced_wall_ms(spans, instants, asyncs)
    rows, _ = trace_report.summarize(spans, wall_ms)
    trace_report.print_table(rows, f"trace summary: {len(files)} file(s), "
                                   f"traced wall {wall_ms:.1f} ms")

    print(f"\ntrace files:   {trace_dir}/trace_*.json "
          "(load in https://ui.perfetto.dev)")
    for stage in ("xe", "cst"):
        print(f"telemetry:     {ckpt}/{stage}/telemetry.json + "
              f"{ckpt}/{stage}/metrics.jsonl")
    return 0


if __name__ == "__main__":
    sys.exit(main())
