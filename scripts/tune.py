#!/usr/bin/env python
"""Offline rollout autotuner CLI — `make tune` / `make tune-fast`.

Sweeps the rollout-throughput config space (decode_chunk, scan_unroll,
overlap_rewards, device_rewards, decode_kernel, batch shape) on the
CURRENT jax backend with bench.py's bench_cst harness and persists the
winner as this platform's tuning record (TUNED_CONFIGS.json, or
$CST_TUNED_CONFIGS), which opts.py then resolves as defaults at startup —
explicit flags always win.

Deterministic + resumable: every measured point is persisted immediately;
rerunning on an unchanged tree (same git SHA, same sweep identity) reuses
the complete record WITHOUT re-measuring.  --force re-measures.

Prints ONE JSON summary line (the repo's artifact convention):
  {"platform": ..., "winner": {...}, "winner_captions_per_sec": ...,
   "points": N, "reused": bool, "record": path}

Run under JAX_PLATFORMS=cpu for a CPU record (never touches a TPU entry —
records are merged per platform); on a TPU host, run bare.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cst_captioning_tpu.tuning import base_namespace, run_sweep  # noqa: E402
from cst_captioning_tpu.tuning.record import default_record_path  # noqa: E402


def parse_args():
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--fast", action="store_true",
                   help="2-point smoke sweep (shipped config + pallas "
                        "decode cell) instead of the full axis grid")
    p.add_argument("--steps", type=int, default=None,
                   help="timed steps per point (default: 8 full, 3 fast)")
    p.add_argument("--batch_size", type=int, default=32)
    p.add_argument("--seq_per_img", type=int, default=20)
    p.add_argument("--seq_len", type=int, default=30)
    p.add_argument("--vocab", type=int, default=8000)
    p.add_argument("--hidden", type=int, default=512)
    p.add_argument("--bfloat16", type=int, default=1)
    p.add_argument("--native_cider", type=int, default=1)
    p.add_argument("--record", default=None,
                   help="tuning-record path (default: TUNED_CONFIGS.json "
                        "at the repo root / $CST_TUNED_CONFIGS)")
    p.add_argument("--force", action="store_true",
                   help="re-measure even when a complete record exists")
    return p.parse_args()


def main() -> int:
    args = parse_args()
    record_path = args.record or default_record_path()
    if not record_path:
        print("tune: tuning record disabled (CST_TUNED_CONFIGS='') and no "
              "--record given; nowhere to persist the sweep", file=sys.stderr)
        return 2
    steps = args.steps if args.steps is not None else (3 if args.fast else 8)
    base = base_namespace(
        batch_size=args.batch_size, seq_per_img=args.seq_per_img,
        seq_len=args.seq_len, vocab=args.vocab, hidden=args.hidden,
        steps=steps, bfloat16=args.bfloat16, native_cider=args.native_cider,
    )
    entry, reused = run_sweep(
        base, fast=args.fast, record_path=record_path, force=args.force,
        progress=lambda msg: print(msg, file=sys.stderr),
    )
    print(json.dumps({
        "platform": entry["platform"],
        "winner": entry.get("winner"),
        "winner_captions_per_sec": entry.get("winner_captions_per_sec"),
        "winner_path": entry.get("winner_path"),
        "points": len(entry.get("points", [])),
        "reused": reused,
        "git_sha": entry.get("git_sha"),
        "record": os.path.abspath(record_path),
    }))
    # A sweep in which no point measured successfully produced no winner —
    # that is a failure, not a record.
    return 0 if entry.get("winner") else 1


if __name__ == "__main__":
    sys.exit(main())
