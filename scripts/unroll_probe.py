#!/usr/bin/env python
"""Measure decoder-scan unroll factors on the live device.

The LSTM decode recurrence is sequential: 30 scan steps of small matmuls
for teacher forcing (XE / RL grad) and for the sampling rollout.  lax.scan
``unroll=k`` executes k steps per loop iteration so XLA can fuse and
pipeline across step boundaries.  This probe times the XE step and the
fused CST step (the two shipped hot loops) at several unroll factors to
pick the default (opts.DEFAULT_SCAN_UNROLL); results table in PARITY.md.

Model/data scaffolding is imported from bench.py (``build`` /
``synthetic_rewarder``) so the probe measures exactly the configuration
the bench headline reports.

Usage: python scripts/unroll_probe.py [--unrolls 1,2,4,8] [--steps 20]
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batch_size", type=int, default=32)
    p.add_argument("--seq_per_img", type=int, default=20)
    p.add_argument("--seq_len", type=int, default=30)
    p.add_argument("--vocab", type=int, default=8000)
    p.add_argument("--hidden", type=int, default=512)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--bfloat16", type=int, default=1)
    p.add_argument("--unrolls", default="1,2,4,8")
    args = p.parse_args()

    import jax
    import numpy as np

    from bench import build, synthetic_rewarder
    from cst_captioning_tpu.training.device_rewards import build_device_tables
    from cst_captioning_tpu.training.steps import make_fused_cst_step, make_xe_step

    print("platform:", jax.devices()[0].platform)
    ncaps = args.batch_size * args.seq_per_img

    _, _, _, refs, vocab = synthetic_rewarder(
        args.batch_size, args.seq_per_img, args.vocab)
    corpus, tables, _ = build_device_tables(refs, vocab.word_to_ix)

    for unroll in [int(u) for u in args.unrolls.split(",")]:
        model, state, feats, labels = build(
            args.batch_size, args.seq_per_img, args.seq_len, args.vocab,
            args.hidden, args.bfloat16, scan_unroll=unroll,
        )
        import jax.numpy as jnp

        weights = jnp.ones((ncaps,))
        vix = np.arange(args.batch_size, dtype=np.int32)

        xe = jax.jit(make_xe_step(model, args.seq_per_img),
                     donate_argnums=(0,))
        fused = jax.jit(
            make_fused_cst_step(model, args.seq_len, args.seq_per_img,
                                corpus, tables), donate_argnums=(0,))

        # Timing barriers are scalar VALUE fetches and the per-step time is
        # the SLOPE between a short and a long loop — both defenses against
        # the remote-tunnel backend: the value fetch is unconditionally
        # trustworthy as a barrier (bench.py barrier note; one unconfirmed
        # block_until_ready anomaly motivated the swap), and the slope
        # cancels the tunnel's fixed round-trip latency which would
        # otherwise pollute a single-loop measurement.
        def timed(fn, fn_args, state, n):
            t0 = time.perf_counter()
            for i in range(n):
                state, m = fn(state, *fn_args, jax.random.PRNGKey(i))
            float(m["loss"])
            return time.perf_counter() - t0, state

        n_lo = max(args.steps // 3, 1)
        results = {}
        for name, fn, fn_args in (
            ("xe ", xe, (feats, labels, weights)),
            ("cst", fused, (feats, vix)),
        ):
            t0 = time.perf_counter()
            _, state = timed(fn, fn_args, state, 1)       # compile + warm
            compile_s = time.perf_counter() - t0
            t_lo, state = timed(fn, fn_args, state, n_lo)
            t_hi, state = timed(fn, fn_args, state, args.steps)
            per = (t_hi - t_lo) / max(args.steps - n_lo, 1)
            results[name] = (ncaps / per, compile_s)
        print(f"unroll {unroll}: "
              f"xe {results['xe '][0]:,.0f} caps/s "
              f"(compile {results['xe '][1]:.1f}s) | fused cst "
              f"{results['cst'][0]:,.0f} caps/s "
              f"(compile {results['cst'][1]:.1f}s)")
        sys.stdout.flush()


if __name__ == "__main__":
    main()
