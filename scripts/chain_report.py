#!/usr/bin/env python
"""Summarize a scale-chain run: per-stage val trajectories + beam-5 evals.

Reads each stage's metrics.jsonl / infos.json under
<out_dir>/checkpoints/<stage>/ and the <stage>_beam5.json result files,
and prints a markdown report — the evidence table for PARITY.md.

Usage: python scripts/chain_report.py --out_dir /tmp/cst_scale_r4b
"""

from __future__ import annotations

import argparse
import json
import os

STAGES = ("xe", "wxe", "cst", "cst_scb", "cst_scb_sample")


def stage_rows(stage_dir: str):
    path = os.path.join(stage_dir, "metrics.jsonl")
    if not os.path.exists(path):
        return []
    rows = []
    with open(path) as f:
        for line in f:
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # torn tail line from a killed run
            if rec.get("scope") == "val":
                rows.append(rec)
    return rows


def sparkline(vals, width: int = 24):
    """Coarse text trajectory: first/min/max/last at a glance."""
    if not vals:
        return ""
    if len(vals) > width:
        idx = [round(i * (len(vals) - 1) / (width - 1)) for i in range(width)]
        vals = [vals[i] for i in idx]
    lo, hi = min(vals), max(vals)
    if hi - lo < 1e-12:
        return "▄" * len(vals)
    blocks = "▁▂▃▄▅▆▇█"
    return "".join(blocks[int((v - lo) / (hi - lo) * 7)] for v in vals)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out_dir", required=True)
    ap.add_argument("--metric", default="CIDEr")
    args = ap.parse_args()
    ckpt = os.path.join(args.out_dir, "checkpoints")

    print(f"## Scale-chain report — {args.out_dir}\n")
    print("| stage | epochs | first | best (step) | last | trajectory |")
    print("|---|---|---|---|---|---|")
    for stage in STAGES:
        d = os.path.join(ckpt, stage)
        rows = [r for r in stage_rows(d) if args.metric in r]
        vals = [r[args.metric] for r in rows]
        if not vals:
            continue
        best_i = max(range(len(vals)), key=vals.__getitem__)
        print(f"| {stage} | {len(vals)} | {vals[0]:.4f} "
              f"| **{vals[best_i]:.4f}** ({rows[best_i]['step']}) "
              f"| {vals[-1]:.4f} | `{sparkline(vals)}` |")

    beam = []
    for stage in STAGES:
        p = os.path.join(args.out_dir, f"{stage}_beam5.json")
        if os.path.exists(p):
            try:
                with open(p) as f:
                    beam.append((stage, json.load(f)["scores"]))
            except (ValueError, KeyError):
                # torn file from a killed eval; report what we have
                print(f"\n(skipping torn/partial {p})")
    if beam:
        keys = sorted({k for _, s in beam for k in s})
        print("\n### Held-out beam-5 eval (best checkpoint per stage)\n")
        print("| stage | " + " | ".join(keys) + " |")
        print("|---" * (len(keys) + 1) + "|")
        for stage, s in beam:
            print(f"| {stage} | " +
                  " | ".join(f"{s.get(k, float('nan')):.4f}" for k in keys) +
                  " |")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
