#!/usr/bin/env python
"""Summarize a scale-chain run: STATUS, per-stage val trajectories, beam evals.

Reads three evidence channels under --out_dir:

- ``chain_events.jsonl`` — the harness's structured lifecycle log
  (written by scripts/scale_chain.py): stage starts, attempts, wedges,
  probe verdicts, heals, aborts.  This is what lets the report say WHY
  there are no learning curves yet — "wedged since 14:34, 37 probes" is
  a blocked chain; silence is a broken one.
- ``checkpoints/<stage>/metrics.jsonl`` — per-stage val trajectories.
- ``<stage>_beam5.json`` — held-out beam-eval scores.

``--log FILE`` additionally parses a console log's ``=== ... ===``
markers for chains started before the event log existed (no timestamps
there — the file's mtime stands in for last activity).

``--json FILE`` writes the whole report (status + curves + beam) as one
JSON document — the committable machine-readable artifact.

Usage: python scripts/chain_report.py --out_dir /tmp/cst_scale_r4b
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

from cst_captioning_tpu.resilience.integrity import (  # noqa: E402
    atomic_json_write,
)

STAGES = ("xe", "wxe", "cst", "cst_scb", "cst_scb_sample")


def stage_rows(stage_dir: str):
    path = os.path.join(stage_dir, "metrics.jsonl")
    if not os.path.exists(path):
        return []
    rows = []
    with open(path) as f:
        for line in f:
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # torn tail line from a killed run
            if rec.get("scope") == "val":
                rows.append(rec)
    return rows


def sparkline(vals, width: int = 24):
    """Coarse text trajectory: first/min/max/last at a glance."""
    if not vals:
        return ""
    if len(vals) > width:
        idx = [round(i * (len(vals) - 1) / (width - 1)) for i in range(width)]
        vals = [vals[i] for i in idx]
    lo, hi = min(vals), max(vals)
    if hi - lo < 1e-12:
        return "▄" * len(vals)
    blocks = "▁▂▃▄▅▆▇█"
    return "".join(blocks[int((v - lo) / (hi - lo) * 7)] for v in vals)


def _ts(t: float) -> str:
    return time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(t))


def _ago(seconds: float) -> str:
    if seconds < 90:
        return f"{seconds:.0f}s"
    if seconds < 5400:
        return f"{seconds / 60:.0f}m"
    return f"{seconds / 3600:.1f}h"


def load_events(out_dir: str):
    path = os.path.join(out_dir, "chain_events.jsonl")
    if not os.path.exists(path):
        return []
    events = []
    with open(path) as f:
        for line in f:
            try:
                events.append(json.loads(line))
            except ValueError:
                continue  # torn tail from a killed harness
    # The chain can be re-invoked into the same out_dir (new stages after
    # a heal); status describes the LATEST run only.
    for i in range(len(events) - 1, -1, -1):
        if events[i].get("event") == "chain_start":
            return events[i:]
    return events


def chain_status(events, now: float | None = None) -> dict:
    """Fold the event stream into 'where is the chain and since when'.

    Returns {state, detail, since, stage, stages: {tag: counters}} with
    state one of: no-events, running, wedged, healing, complete, aborted.
    """
    if not events:
        return {"state": "no-events",
                "detail": "no chain_events.jsonl — chain predates the "
                          "event log or never started; try --log"}
    now = now or time.time()
    per_stage: dict[str, dict] = {}
    stage = None
    state, since, detail = "running", events[-1]["ts"], ""
    for ev in events:
        kind, tag = ev.get("event"), ev.get("tag")
        if kind == "stage_start":
            stage = tag
            per_stage.setdefault(tag, {
                "attempts": 0, "wedges": 0, "probes": 0,
                "probes_since_wedge": 0, "started": ev["ts"], "done": None,
                "abort": None, "best_score": None})
        s = per_stage.get(tag) if tag else None
        if kind == "attempt_start" and s:
            s["attempts"] = max(s["attempts"], ev.get("attempt", 0))
            state, since, detail = "running", ev["ts"], \
                f"attempt {ev.get('attempt')}"
        elif kind == "wedge" and s:
            s["wedges"] += 1
            s["probes_since_wedge"] = 0
            state, since = "wedged", ev["ts"]
            detail = f"stage exited rc={ev.get('rc')}"
        elif kind == "probe" and s:
            s["probes"] += 1
            if state == "wedged":
                s["probes_since_wedge"] += 1
        elif kind == "healed" and s:
            state, since = "healing", ev["ts"]
            detail = f"device back after {_ago(ev.get('waited_s', 0))}"
        elif kind == "stage_done" and s:
            s["done"] = ev["ts"]
            state, since, detail = "running", ev["ts"], f"{tag} done"
        elif kind == "stage_best" and s:
            s["best_score"] = ev.get("best_score")
        elif kind == "stage_abort" and s:
            s["abort"] = ev.get("reason")
            state, since = "aborted", ev["ts"]
            detail = f"{tag}: {ev.get('reason')}"
        elif kind == "chain_done":
            state, since, detail = "complete", ev["ts"], ""
            stage = None
    return {"state": state, "detail": detail, "since": since,
            "age_s": round(now - since, 1), "stage": stage,
            "last_event": events[-1].get("event"),
            "last_event_age_s": round(now - events[-1]["ts"], 1),
            "stages": per_stage}


# Console-marker fallback for chains older than the event log.
_MARKERS = (
    (re.compile(r"^=== stage: (\S+)"), "stage"),
    (re.compile(r"^=== (\S+?): attempt (\d+)"), "attempt"),
    (re.compile(r"^=== (\S+?): wedge \(rc=(-?\d+)\)"), "wedge"),
    (re.compile(r"^=== (\S+?): device probe detail: (.*?) ==="), "detail"),
    (re.compile(r"^=== (\S+?) done"), "done"),
    (re.compile(r"^WATCHDOG:"), "watchdog"),
)


def log_status(log_path: str, now: float | None = None) -> dict:
    """Best-effort status from a console log's marker lines.  The print
    markers carry no timestamps; the file's mtime is the last-activity
    proxy (heal-poll probes do not write, so a wedged chain's log can be
    legitimately old)."""
    counts: dict[str, int] = {}
    last_marker, stage, wedged = None, None, False
    details = []
    try:
        with open(log_path, errors="replace") as f:
            for line in f:
                for rx, kind in _MARKERS:
                    m = rx.match(line.strip())
                    if not m:
                        continue
                    counts[kind] = counts.get(kind, 0) + 1
                    last_marker = line.strip()
                    if kind == "stage":
                        stage, wedged = m.group(1), False
                    elif kind == "wedge":
                        wedged = True
                    elif kind == "attempt":
                        # A resume attempt means the device healed and the
                        # stage is training again — no longer wedged.
                        wedged = False
                    elif kind == "detail":
                        details.append(m.group(2))
                    elif kind == "done":
                        wedged = False
                    break
    except OSError as e:
        return {"state": "no-log", "detail": str(e)}
    now = now or time.time()
    try:
        mtime = os.stat(log_path).st_mtime
    except OSError:
        mtime = now
    return {"state": "wedged" if wedged else "running",
            "stage": stage, "counts": counts, "last_marker": last_marker,
            "last_write_age_s": round(now - mtime, 1),
            "probe_details": details[-3:]}


def print_status(status: dict) -> None:
    print("### Chain status\n")
    state = status.get("state")
    if state == "no-events":
        print(f"- **status unknown** — {status['detail']}")
        return
    if state == "no-log":
        print(f"- **no log** — {status['detail']}")
        return
    if "since" in status:  # event-log status
        line = f"- **{state}**"
        if status.get("stage"):
            line += f" in stage `{status['stage']}`"
        line += f" since {_ts(status['since'])} ({_ago(status['age_s'])} ago)"
        if status.get("detail"):
            line += f" — {status['detail']}"
        print(line)
        print(f"- last event: `{status['last_event']}` "
              f"{_ago(status['last_event_age_s'])} ago")
        for tag, s in status.get("stages", {}).items():
            bits = [f"attempts {s['attempts']}", f"wedges {s['wedges']}",
                    f"probes {s['probes']}"]
            if s["wedges"] and s["probes_since_wedge"]:
                bits.append(f"{s['probes_since_wedge']} since last wedge")
            if s["abort"]:
                bits.append(f"ABORTED: {s['abort']}")
            if s["done"]:
                bits.append("done")
            if s["best_score"] is not None:
                bits.append(f"best {s['best_score']:.4f}")
            print(f"  - `{tag}`: " + ", ".join(bits))
    else:  # console-log status
        line = f"- **{state}** (from console markers)"
        if status.get("stage"):
            line += f" in stage `{status['stage']}`"
        print(line)
        print(f"- marker counts: {status.get('counts', {})}")
        if status.get("last_marker"):
            print(f"- last marker: `{status['last_marker']}`")
        print(f"- log last written {_ago(status['last_write_age_s'])} ago "
              "(heal-poll probes do not write; old is normal while wedged)")
        for d in status.get("probe_details", []):
            print(f"  - probe detail: {d}")


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out_dir", required=True)
    ap.add_argument("--metric", default="CIDEr")
    ap.add_argument("--log", default=None,
                    help="console log to parse when the chain predates "
                         "chain_events.jsonl")
    ap.add_argument("--json", default=None,
                    help="also write the full report as JSON here")
    args = ap.parse_args()
    ckpt = os.path.join(args.out_dir, "checkpoints")
    report: dict = {"out_dir": args.out_dir, "metric": args.metric}

    print(f"## Scale-chain report — {args.out_dir}\n")
    events = load_events(args.out_dir)
    status = chain_status(events)
    if status["state"] == "no-events" and args.log:
        status = log_status(args.log)
    print_status(status)
    report["status"] = status

    report["curves"] = {}
    table = []
    for stage in STAGES:
        d = os.path.join(ckpt, stage)
        rows = [r for r in stage_rows(d) if args.metric in r]
        vals = [r[args.metric] for r in rows]
        if not vals:
            continue
        best_i = max(range(len(vals)), key=vals.__getitem__)
        table.append(f"| {stage} | {len(vals)} | {vals[0]:.4f} "
                     f"| **{vals[best_i]:.4f}** ({rows[best_i]['step']}) "
                     f"| {vals[-1]:.4f} | `{sparkline(vals)}` |")
        report["curves"][stage] = [
            {"step": r["step"], args.metric: r[args.metric]} for r in rows]
    if table:
        print("\n| stage | epochs | first | best (step) | last | trajectory |")
        print("|---|---|---|---|---|---|")
        for row in table:
            print(row)
    else:
        print("\n(no val curves yet — see status above for why)")

    beam = []
    for stage in STAGES:
        p = os.path.join(args.out_dir, f"{stage}_beam5.json")
        if os.path.exists(p):
            try:
                with open(p) as f:
                    blob = json.load(f)
                scores = dict(blob["scores"])
                # Output diversity rides with every beam table: a high
                # consensus metric over a HANDFUL of distinct captions is
                # template collapse (the model exploiting shared
                # function-word n-grams), not content grounding — the
                # judge-facing number must carry that signal itself.
                preds = blob.get("predictions") or []
                caps = [pr.get("caption", "") for pr in preds]
                if caps:
                    scores["unique_captions"] = len(set(caps))
                    scores["n_videos"] = len(caps)
                beam.append((stage, scores))
            except (ValueError, KeyError):
                # torn file from a killed eval; report what we have
                print(f"\n(skipping torn/partial {p})")
    if beam:
        keys = sorted({k for _, s in beam for k in s})
        print("\n### Held-out beam-5 eval (best checkpoint per stage)\n")
        print("| stage | " + " | ".join(keys) + " |")
        print("|---" * (len(keys) + 1) + "|")
        for stage, s in beam:
            print(f"| {stage} | " +
                  " | ".join(f"{s[k]:.4f}" if isinstance(s.get(k), float)
                             else str(s.get(k, "—")) for k in keys) +
                  " |")
    report["beam"] = {stage: s for stage, s in beam}

    if args.json:
        # collect_evidence bundles this file: it must never be torn.
        atomic_json_write(args.json, report, indent=2)
        print(f"\n(report JSON -> {args.json})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
