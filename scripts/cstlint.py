#!/usr/bin/env python
"""cstlint CLI — `make lint` / `make lint-json` (ANALYSIS.md).

Runs the project-native static-analysis pass (analysis/) over the
enforcement surface (cst_captioning_tpu/, scripts/, the top-level CLIs)
and reports every unsuppressed violation of the repo's hard-won
invariants: device-scalar fetches in hot loops, durable JSON writes
bypassing atomic_json_write, undeclared counters, untyped exits,
silent exception swallows, donated-but-unaliased jit buffers, and the
concurrency contracts (guarded-by/ownership annotations, LOCK_ORDER
embedding, signal-handler safety, thread discipline, monotonic
deadlines — ANALYSIS.md "Concurrency contracts").

Usage:
  python scripts/cstlint.py                 # human output, full tree
  python scripts/cstlint.py --json          # machine output (evidence)
  python scripts/cstlint.py --rules exit-taxonomy,atomic-write
  python scripts/cstlint.py --no-trace      # AST rules only (no jax)
  python scripts/cstlint.py --list-rules
  python scripts/cstlint.py scripts/serve.py train.py   # subset of files

Exit codes (resilience/exitcodes.py): 0 clean, 1 violations, 2 usage.
"""

from __future__ import annotations

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from cst_captioning_tpu.resilience.exitcodes import (  # noqa: E402
    EXIT_FAILURE,
    EXIT_OK,
)


def main() -> int:
    ap = argparse.ArgumentParser(
        description="project-native static analysis (ANALYSIS.md)")
    ap.add_argument("paths", nargs="*",
                    help="repo-relative files to lint (default: the "
                         "whole enforcement surface)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule subset (default: all)")
    ap.add_argument("--no-trace", action="store_true",
                    help="skip jax-tracing rules (donation-audit); "
                         "pure-AST pass, no jax import")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args()

    if not args.no_trace:
        # The donation audit lowers real programs; never let that touch
        # a remote-TPU tunnel (conftest rationale: utils/platform.py).
        from cst_captioning_tpu.utils.platform import force_cpu_platform
        force_cpu_platform()

    from cst_captioning_tpu.analysis import (
        RULES,
        lint_tree,
        render_human,
        render_json,
    )

    if args.list_rules:
        by_cat = {}
        for name in sorted(RULES):
            by_cat.setdefault(RULES[name].category, []).append(name)
        for cat in sorted(by_cat):
            print(f"[{cat}]")
            for name in by_cat[cat]:
                print(f"  {name:22s} {RULES[name].doc}")
        return EXIT_OK

    rules = None
    if args.rules:
        rules = [r.strip() for r in args.rules.split(",") if r.strip()]
    try:
        result = lint_tree(REPO, rules=rules, trace=not args.no_trace,
                           paths=args.paths or None)
    except KeyError as e:
        ap.error(str(e.args[0]) if e.args else str(e))
    except OSError as e:
        ap.error(f"cannot read lint target: {e}")

    print(render_json(result) if args.json else render_human(result))
    return EXIT_OK if result.clean else EXIT_FAILURE


if __name__ == "__main__":
    sys.exit(main())
