#!/usr/bin/env python
"""Print the autotuner's sweep table(s) from the tuning record.

One table per platform entry in TUNED_CONFIGS.json (or $CST_TUNED_CONFIGS
/ --record): every measured point with its config axes and captions/s,
the winner starred, plus the record's provenance line (git SHA,
measured_at, completeness) — the human-readable face of the record that
opts.py resolves at startup.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cst_captioning_tpu.tuning import load_record  # noqa: E402
from cst_captioning_tpu.tuning.record import default_record_path  # noqa: E402

AXES = ("decode_chunk", "scan_unroll", "overlap_rewards",
        "device_rewards", "decode_kernel", "batch_size")


def print_entry(platform: str, entry: dict) -> None:
    sweep = entry.get("sweep", {})
    print(f"== {platform} ({entry.get('device_kind') or 'unknown device'}) "
          f"— {sweep.get('mode', '?')} sweep, steps={sweep.get('steps')}")
    print(f"   git_sha {entry.get('git_sha', '?')[:12]}  measured_at "
          f"{entry.get('measured_at', '?')}  "
          f"{'complete' if entry.get('complete') else 'INCOMPLETE (resumable)'}")
    winner = entry.get("winner") or {}
    header = " | ".join(f"{a:>15}" for a in AXES) + " | captions/s | path"
    print("   " + header)
    print("   " + "-" * len(header))
    for p in entry.get("points", []):
        cfg = p.get("config", {})
        caps = p.get("captions_per_sec")
        is_winner = (caps is not None
                     and caps == entry.get("winner_captions_per_sec")
                     and all(cfg.get(a) == winner.get(a) for a in AXES[:-1])
                     and cfg.get("batch_size") == winner.get(
                         "bench_batch_size"))
        row = " | ".join(f"{str(cfg.get(a, '')):>15}" for a in AXES)
        caps_s = "   failed " if caps is None else f"{caps:>10.1f}"
        mark = "  *WINNER*" if is_winner else ""
        err = f"  ({p['error']})" if p.get("error") else ""
        print(f"   {row} | {caps_s} | {p.get('path') or '-'}{mark}{err}")
    if winner:
        print(f"   winner -> {winner} @ "
              f"{entry.get('winner_captions_per_sec')} captions/s")
    print()


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--record", default=None)
    args = ap.parse_args()
    path = args.record or default_record_path()
    if not path or not os.path.exists(path):
        print(f"no tuning record at {path!r} — run `make tune` "
              f"(or `make tune-fast`) first", file=sys.stderr)
        return 1
    doc = load_record(path)
    platforms = doc.get("platforms", {})
    if not platforms:
        print(f"tuning record {path} holds no platform entries",
              file=sys.stderr)
        return 1
    print(f"tuning record: {os.path.abspath(path)}")
    for platform in sorted(platforms):
        print_entry(platform, platforms[platform])
    return 0


if __name__ == "__main__":
    sys.exit(main())
