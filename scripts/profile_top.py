#!/usr/bin/env python
"""Summarize a ``--profile_dir`` trace: top device ops by total time.

The trainer's ``--profile_dir/--profile_start/--profile_steps`` flags
capture a ``jax.profiler`` trace (training/trainer.py); TensorBoard can
render it, but the fastest question — "what dominates the step?" — needs
no UI.  This reads the xplane protobuf back through
``jax.profiler.ProfileData`` and prints per-line (XLA Modules / XLA Ops /
host threads) totals, the tool that found the decoder-cell remat win
(PARITY.md: attention residuals at 2.3 GB/step).

Usage:
  python scripts/profile_top.py /path/to/profile_dir [--top 15]
  python scripts/profile_top.py trace.xplane.pb --line "XLA Ops"
"""
import argparse
import glob
import os
from collections import defaultdict


def find_xplane(path: str) -> str | None:
    """Newest ``*.xplane.pb`` under ``path`` (or ``path`` itself when it
    is a file); None when the directory holds no capture — the caller
    turns that into a one-line argparse usage error (exit 2, the
    taxonomy's EXIT_USAGE: a missing capture is operator input, not a
    failure of this tool)."""
    if os.path.isfile(path):
        return path
    hits = sorted(glob.glob(os.path.join(path, "**", "*.xplane.pb"),
                            recursive=True))
    return hits[-1] if hits else None  # newest capture


def main():
    p = argparse.ArgumentParser()
    p.add_argument("trace", help="profile dir or .xplane.pb file")
    p.add_argument("--top", type=int, default=15)
    p.add_argument("--line", default=None,
                   help="only lines whose name contains this substring "
                        "(e.g. 'XLA Ops'); default: every line with events")
    p.add_argument("--plane", default=None,
                   help="only planes whose name contains this substring "
                        "(e.g. 'TPU'); default: device planes, then host")
    args = p.parse_args()

    # Resolve the capture BEFORE importing jax: a bad path fails in
    # milliseconds with a usage line instead of after backend bring-up.
    xplane = find_xplane(args.trace)
    if xplane is None:
        p.error(f"no *.xplane.pb under {args.trace!r} — was the trace "
                "captured with --profile_dir (or jax.profiler.trace)?")

    from jax.profiler import ProfileData

    pd = ProfileData.from_file(xplane)
    planes = list(pd.planes)
    if args.plane:
        planes = [pl for pl in planes if args.plane in pl.name]
    else:
        dev = [pl for pl in planes if "/device:" in pl.name]
        planes = dev or planes

    for plane in planes:
        for line in plane.lines:
            if args.line and args.line not in line.name:
                continue
            tot = defaultdict(float)
            cnt = defaultdict(int)
            t0, t1 = None, None
            for ev in line.events:
                tot[ev.name] += ev.duration_ns
                cnt[ev.name] += 1
                start = getattr(ev, "start_ns", None)
                if start is not None:
                    t0 = start if t0 is None else min(t0, start)
                    t1 = (start + ev.duration_ns if t1 is None
                          else max(t1, start + ev.duration_ns))
            if not tot:
                continue
            # Span is WALL CLOCK (max end - min start), not the sum of
            # durations: events on a line can nest (TraceAnnotations wrap
            # children), so summing would double-count host lines.  The
            # per-op totals below still include parents' time over their
            # children on such lines.
            span = (t1 - t0) if t0 is not None else sum(tot.values())
            print(f"== {plane.name} :: {line.name} — "
                  f"{len(tot)} distinct, {span / 1e6:.2f} ms span")
            for name, ns in sorted(tot.items(), key=lambda kv: -kv[1])[
                    :args.top]:
                print(f"  {ns / 1e6:10.3f} ms  x{cnt[name]:<6d} "
                      f"{name[:100]}")


if __name__ == "__main__":
    main()
