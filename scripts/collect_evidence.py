#!/usr/bin/env python
"""Copy a scale-chain run's durable evidence into the repo's artifacts/.

Learning claims in PARITY.md / round notes must resolve to committed,
machine-readable files — not /tmp paths that evaporate between rounds
(VERDICT r4, missing #2).  This collects exactly the small, textual
pieces that back a learning-curve table:

- per-stage ``metrics.jsonl`` + ``infos.json`` (val trajectories, best)
- ``<stage>_beam5.json`` held-out beam evals
- ``chain_events.jsonl`` (the harness lifecycle: attempts/wedges/heals)
- ``SCALE_SPEC.json`` (the dataset spec the curves were trained on)
- a freshly generated ``report.json`` / ``report.md`` (chain_report)

and writes a ``MANIFEST.json`` recording the source dir, the git SHA the
evidence was collected under, and the command that regenerates the run.

Usage:
  python scripts/collect_evidence.py --out_dir /tmp/evidence_probe64 \\
      --name probe64 [--regen "python scripts/scale_chain.py ..."]
"""

from __future__ import annotations

import argparse
import json
import os
import shlex
import shutil
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from chain_report import STAGES  # noqa: E402  (one stage list)
from cst_captioning_tpu.resilience.integrity import (  # noqa: E402
    atomic_json_write,
)
from cst_captioning_tpu.utils.platform import git_head_sha  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out_dir", required=True)
    ap.add_argument("--name", required=True,
                    help="artifacts/<name>/ destination")
    ap.add_argument("--regen", default=None,
                    help="command that regenerates the run (recorded in "
                         "MANIFEST.json); defaults to the chain_start "
                         "argv from chain_events.jsonl if present")
    ap.add_argument("--dest", default=os.path.join(REPO, "artifacts"),
                    help="destination root (default: repo artifacts/)")
    args = ap.parse_args()
    src = os.path.abspath(args.out_dir)
    dst = os.path.join(args.dest, args.name)
    os.makedirs(dst, exist_ok=True)

    copied = []

    def take(rel_src: str, rel_dst: str | None = None) -> None:
        s = os.path.join(src, rel_src)
        if not os.path.exists(s):
            return
        d = os.path.join(dst, rel_dst or rel_src)
        os.makedirs(os.path.dirname(d), exist_ok=True)
        shutil.copyfile(s, d)
        copied.append(rel_dst or rel_src)

    take("chain_events.jsonl")
    take("data/SCALE_SPEC.json", "SCALE_SPEC.json")
    for stage in STAGES:
        take(os.path.join("checkpoints", stage, "metrics.jsonl"),
             os.path.join(stage, "metrics.jsonl"))
        take(os.path.join("checkpoints", stage, "infos.json"),
             os.path.join(stage, "infos.json"))
        take(f"{stage}_beam5.json")

    # Process-fleet supervisor evidence (RESILIENCE.md "Process
    # faults"): the per-child-death incident bundles (blackbox/
    # heartbeat/telemetry/stderr harvested from the dead replica's
    # workdir + the incident.json index) and the supervisor's own exit
    # snapshot.  Only textual forensics are taken — stderr logs travel
    # because they are the crash's last words.
    take("supervisor_exit.json")
    take("blackbox.json", "supervisor_blackbox.json")
    incidents_root = os.path.join(src, "incidents")
    if os.path.isdir(incidents_root):
        for incident in sorted(os.listdir(incidents_root)):
            for fn in ("incident.json", "blackbox.json",
                       "heartbeat.json", "telemetry.json", "stderr.log"):
                take(os.path.join("incidents", incident, fn))

    # Fleet-observability evidence (OBSERVABILITY.md "Fleet plane"):
    # the scraped metrics series (active file + every rotated part +
    # the part index), the SLO alert transition log, the clock-offset
    # table that stitched the traces, and the merged fleet trace
    # itself — together they back any latency/SLO claim made about a
    # supervised run.
    take("fleet_metrics.jsonl")
    take("fleet_metrics_index.json")
    for fn in sorted(os.listdir(src)) if os.path.isdir(src) else []:
        if fn.startswith("fleet_metrics_part") and fn.endswith(".jsonl"):
            take(fn)
    take("slo_alerts.jsonl")
    take("clock_sync.json")
    take("fleet_trace.json")
    # The autoscaler's durable decision log (SERVING.md "Autoscaling &
    # brownout"): every scale-up/scale-down/brownout transition with
    # the attribution evidence it acted on.
    take("autoscale_decisions.jsonl")

    # Intake-journal evidence (SERVING.md "Durable intake journal"):
    # the recovery ledger a relaunched supervisor wrote (which ids it
    # replayed vs answered from record) and the raw write-ahead
    # segments themselves — small, line-framed, and the only ground
    # truth for an exactly-once claim across a supervisor death.
    take("recovery_ledger.json")
    journal_root = os.path.join(src, "journal")
    if os.path.isdir(journal_root):
        for fn in sorted(os.listdir(journal_root)):
            if fn.endswith(".wal"):
                take(os.path.join("journal", fn))

    # Regenerate the report against the live out_dir so report + copies
    # agree, then keep both renderings.  A wedged/killed chain_report must
    # degrade to "bundle without report" — the MANIFEST below still gets
    # written (with its nonzero report_rc recording the failure), because
    # a timed-out report leaving a provenance-less bundle would be worse
    # than a report-less one (round-5 advisor).
    report_json = os.path.join(dst, "report.json")
    try:
        with open(os.path.join(dst, "report.md"), "w") as f:
            rc = subprocess.run(
                [sys.executable, "scripts/chain_report.py", "--out_dir", src,
                 "--json", report_json],
                cwd=REPO, stdout=f, stderr=subprocess.STDOUT, timeout=300,
            ).returncode
    except (subprocess.TimeoutExpired, OSError) as e:
        rc = 124 if isinstance(e, subprocess.TimeoutExpired) else 1
        print(f"chain_report failed ({e}); writing MANIFEST with "
              f"report_rc={rc}", file=sys.stderr)
        # A timeout can leave a half-written report.md (the file was
        # opened before the child wedged) and chain_report may have
        # part-written its --json; a truncated artifact in the bundle is
        # worse than none, so drop both rather than list them below.
        for r in ("report.md", "report.json"):
            try:
                os.remove(os.path.join(dst, r))
            except OSError:
                pass
    # The manifest lists what EXISTS, not what was attempted: a failed
    # chain_report must not leave the bundle claiming a report it lacks.
    copied += [r for r in ("report.md", "report.json")
               if os.path.exists(os.path.join(dst, r))]

    # Static-analysis receipt (ANALYSIS.md): the bundle carries the lint
    # JSON so a chaos drill's evidence proves the tree it ran on was
    # clean of invariant violations — same degrade-don't-block contract
    # as chain_report above (a wedged lint leaves lint_rc nonzero, never
    # a missing MANIFEST).
    lint_json = os.path.join(dst, "lint.json")
    lint_env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    lint_rc = None
    try:
        proc = subprocess.run(
            [sys.executable, "scripts/cstlint.py", "--json"],
            cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            timeout=300, env=lint_env,
        )
        lint_rc = proc.returncode
        # Parse-then-atomic-write: a lint child killed mid-print can
        # never leave a torn lint.json in the bundle (exit 1 with
        # violations still prints complete JSON and is bundled).
        atomic_json_write(lint_json, json.loads(proc.stdout), indent=2)
        copied.append("lint.json")
    except (subprocess.TimeoutExpired, OSError, ValueError) as e:
        # lint_rc stays the CHILD's verdict when the lint itself ran —
        # a bundle-write failure must never read as "violations found"
        # (the receipt's absence from `files` records the write failure;
        # lint_rc=1 is reserved for an actually-dirty tree).
        if lint_rc is None:
            lint_rc = 124 if isinstance(e, subprocess.TimeoutExpired) else 1
        print(f"lint receipt not bundled ({type(e).__name__}); writing "
              f"MANIFEST with lint_rc={lint_rc}", file=sys.stderr)

    regen = args.regen
    if not regen:
        try:
            with open(os.path.join(src, "chain_events.jsonl")) as f:
                for line in f:
                    rec = json.loads(line)
                    if rec.get("event") == "chain_start":
                        regen = ("python scripts/scale_chain.py "
                                 + shlex.join(rec.get("argv", [])))
        except (OSError, ValueError):
            pass

    manifest = {
        "source_dir": src,
        "collected_at": time.strftime("%Y-%m-%d %H:%M:%S"),
        "git_sha": git_head_sha(REPO),
        "regen_command": regen,
        "report_rc": rc,
        "lint_rc": lint_rc,
        "files": sorted(copied),
    }
    atomic_json_write(os.path.join(dst, "MANIFEST.json"), manifest,
                      indent=2)
    print(f"collected {len(copied)} files -> {dst}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
