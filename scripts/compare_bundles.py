#!/usr/bin/env python
"""One table across every committed evidence bundle.

Reads ``artifacts/*/report.json`` (written by collect_evidence) and
prints, per bundle: the dataset scale, each stage's best greedy
fast-val score, and each stage's held-out beam-5 score on the chosen
metric — the cross-scale view of the evidence ladder that individual
chain reports can't show.

Usage: python scripts/compare_bundles.py [--root artifacts] [--metric CIDEr]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from chain_report import STAGES  # noqa: E402  (one stage list, not three)


def load_bundles(root: str):
    bundles = []
    try:
        names = sorted(os.listdir(root))
    except OSError:
        return []
    for name in names:
        d = os.path.join(root, name)
        rj = os.path.join(d, "report.json")
        if not os.path.isfile(rj):
            continue
        try:
            with open(rj) as f:
                report = json.load(f)
        except ValueError:
            continue
        spec = {}
        try:
            with open(os.path.join(d, "SCALE_SPEC.json")) as f:
                spec = json.load(f)
        except (OSError, ValueError):
            pass
        bundles.append((name, spec, report))
    return bundles


def fmt(v) -> str:
    return f"{v:.4f}" if isinstance(v, (int, float)) else "—"


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--root", default=os.path.join(REPO, "artifacts"))
    ap.add_argument("--metric", default="CIDEr")
    args = ap.parse_args()
    bundles = load_bundles(args.root)
    if not bundles:
        print(f"no bundles with report.json under {args.root}")
        return 1

    print(f"## Evidence ladder — best val / beam-5 {args.metric} per stage\n")
    print("| bundle | videos | " + " | ".join(STAGES) + " |")
    print("|---" * (len(STAGES) + 2) + "|")
    for name, spec, report in bundles:
        cells = []
        for stage in STAGES:
            curve = report.get("curves", {}).get(stage) or []
            best = max((r.get(args.metric) for r in curve
                        if isinstance(r.get(args.metric), (int, float))),
                       default=None)
            beam = (report.get("beam", {}).get(stage) or {}).get(args.metric)
            cells.append(f"{fmt(best)} / {fmt(beam)}")
        videos = spec.get("num_videos", "—")
        print(f"| {name} | {videos} | " + " | ".join(cells) + " |")
    print("\n(cell = best greedy fast-val / held-out beam-5; — = value "
          "not in the bundle: stage absent, or — for the val half — the "
          "curves were recorded under a different --metric)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
