#!/usr/bin/env python
"""One-off: time each phase of the CST iteration on the current backend.

Phases: rollout (jit), device->host transfer, reward (native + python),
RL grad step (jit).  Mirrors bench.py --stage cst shapes.
"""
import argparse
import time

import numpy as np


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batch_size", type=int, default=32)
    p.add_argument("--seq_per_img", type=int, default=20)
    p.add_argument("--seq_len", type=int, default=30)
    p.add_argument("--vocab", type=int, default=8000)
    p.add_argument("--hidden", type=int, default=512)
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--bfloat16", type=int, default=1)
    p.add_argument("--python_scorer", type=int, default=0)
    args = p.parse_args()

    import jax
    import jax.numpy as jnp

    print("platform:", jax.devices()[0].platform)

    import sys, os
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from bench import build, synthetic_rewarder
    from cst_captioning_tpu.training.steps import make_rl_grad_step, make_rollout

    model, state, feats, labels = build(
        args.batch_size, args.seq_per_img, args.seq_len, args.vocab,
        args.hidden, args.bfloat16,
    )
    rc, video_ids, scorer_kind, _, _ = synthetic_rewarder(
        args.batch_size, args.seq_per_img, args.vocab,
        native=not args.python_scorer,
    )
    print("scorer:", scorer_kind)

    rollout = jax.jit(make_rollout(model, args.seq_len, args.seq_per_img))
    rl_step = jax.jit(make_rl_grad_step(model, args.seq_per_img),
                      donate_argnums=(0,))

    # compile
    t0 = time.perf_counter()
    sampled, greedy = rollout(state.params, feats, jax.random.PRNGKey(0))
    jax.block_until_ready(sampled)
    print(f"rollout compile+run: {time.perf_counter()-t0:.1f}s")
    s = np.asarray(jax.device_get(sampled))
    g = np.asarray(jax.device_get(greedy))
    adv, _ = rc(video_ids, s, g)
    t0 = time.perf_counter()
    state, m = rl_step(state, feats, sampled, jnp.asarray(adv),
                       jax.random.PRNGKey(0))
    jax.block_until_ready(m["loss"])
    print(f"rl_step compile+run: {time.perf_counter()-t0:.1f}s")

    times = {"rollout": 0.0, "get": 0.0, "reward": 0.0, "grad": 0.0}
    n_steps = args.steps
    for i in range(n_steps):
        key = jax.random.PRNGKey(i + 1)
        t0 = time.perf_counter()
        sampled, greedy = rollout(state.params, feats, key)
        jax.block_until_ready(sampled)
        t1 = time.perf_counter()
        s = np.asarray(jax.device_get(sampled))
        g = np.asarray(jax.device_get(greedy))
        t2 = time.perf_counter()
        adv, _ = rc(video_ids, s, g)
        t3 = time.perf_counter()
        state, m = rl_step(state, feats, sampled, jnp.asarray(adv), key)
        jax.block_until_ready(m["loss"])
        t4 = time.perf_counter()
        times["rollout"] += t1 - t0
        times["get"] += t2 - t1
        times["reward"] += t3 - t2
        times["grad"] += t4 - t3
    total = sum(times.values())
    caps = args.batch_size * args.seq_per_img * n_steps
    print({k: f"{v/n_steps*1000:.1f}ms" for k, v in times.items()})
    print(f"total/step: {total/n_steps*1000:.1f}ms  "
          f"captions/s: {caps/total:.0f}")


if __name__ == "__main__":
    main()
