#!/usr/bin/env python
"""bf16 decode parity harness: bound the CIDEr delta vs the fp32 path.

``--decode_kernel bf16`` (ops/bf16_decode.py) is a LOW-PRECISION decode
variant — deliberately not bit-identical — so it ships behind this gate:
decode the SAME checkpoint's test split with the reference (fp32) cell
and the bf16 cell, score both against the references, and require the
CIDEr delta inside the declared bound (``DEFAULT_CIDER_DELTA_BOUND``).
Within the bound the variant is eligible and the tuner's sweep decides
whether it pays per platform; outside it the recommendation is PINNED to
``reference`` (the bit-exact fallback) and the exit code says so.

  # the real gate: a trained checkpoint + its test split
  python scripts/bf16_parity.py --checkpoint_path <dir> \\
      --test_feat_h5 ... --test_label_h5 ... --test_info_json ... \\
      --test_cocofmt_file ... --beam_size 5

  # zero-setup smoke (untrained tiny model on a synthetic split — the
  # pipeline is real, the CIDEr values are not a quality claim)
  python scripts/bf16_parity.py --synthetic 1

Prints ONE JSON line — the `parity_gate` verdict plus per-kernel scores
and token agreement — and exits 0 within the bound, 1 outside it
(EXIT_FAILURE through the taxonomy).  The cpu512_healthy protocol run of
this gate is the record of evidence PARITY.md points at.
"""

from __future__ import annotations

import argparse
import json
import sys

sys.path.insert(0, __import__("os").path.dirname(
    __import__("os").path.dirname(__import__("os").path.abspath(__file__))))

import numpy as np  # noqa: E402


def build_synthetic(opt, tmp_root):
    """Tiny seeded model + synthetic test split -> (model, params, ds,
    loader).  Untrained weights: the harness exercises the REAL decode +
    scoring pipeline; the absolute CIDEr values are meaningless and the
    delta is what the gate reads."""
    import jax

    from cst_captioning_tpu.data.dataset import CaptionDataset, SplitPaths
    from cst_captioning_tpu.data.loader import CaptionLoader
    from cst_captioning_tpu.data.synthetic import SyntheticSpec, generate
    from cst_captioning_tpu.training.state import (create_train_state,
                                                   make_optimizer)
    from cst_captioning_tpu.training.trainer import build_model

    paths = generate(tmp_root, "test", SyntheticSpec(
        num_videos=8, captions_per_video=3, max_len=opt.max_length,
        feat_dims=(16, 8), feat_times=(3, 1)))
    ds = CaptionDataset(SplitPaths(
        feat_h5=json.loads(paths["feat_h5"]), label_h5=paths["label_h5"],
        info_json=paths["info_json"], cocofmt_json=paths["cocofmt_json"]))
    loader = CaptionLoader(ds, batch_size=4, seq_per_img=1, shuffle=False)
    model = build_model(opt, ds.vocab.size_with_pad, ds.seq_length)
    tx, _ = make_optimizer()
    state = create_train_state(
        model, jax.random.PRNGKey(0),
        list(zip(ds.feat_times, ds.feat_dims)), ds.seq_length, 1, tx)
    return model, state.params, ds, loader


def main(argv=None) -> int:
    from cst_captioning_tpu.opts import build_parser

    p = build_parser()
    p.add_argument("--synthetic", type=int, default=0,
                   help="1 = zero-setup smoke: untrained tiny model on a "
                        "generated synthetic split (no checkpoint needed)")
    p.add_argument("--cider_delta_bound", type=float, default=None,
                   help="override the declared CIDEr-delta bound "
                        "(ops/bf16_decode.DEFAULT_CIDER_DELTA_BOUND)")
    opt = p.parse_args(argv)

    from cst_captioning_tpu.utils.platform import (configure_cli_logging,
                                                   enable_compile_cache)

    configure_cli_logging(opt.loglevel)
    enable_compile_cache(getattr(opt, "compile_cache_dir", ""))

    from cst_captioning_tpu.data.dataset import CaptionDataset, SplitPaths
    from cst_captioning_tpu.data.loader import CaptionLoader
    from cst_captioning_tpu.metrics.coco_eval import language_eval
    from cst_captioning_tpu.ops.bf16_decode import (
        DEFAULT_CIDER_DELTA_BOUND,
        bf16_decode_supported,
        parity_gate,
    )
    from cst_captioning_tpu.resilience.exitcodes import (EXIT_FAILURE,
                                                         EXIT_OK,
                                                         EXIT_USAGE)
    from cst_captioning_tpu.training.evaluation import decode_split

    if opt.synthetic:
        if opt.rnn_size > 64:
            # keep the smoke a smoke: the caller can still force big
            # shapes explicitly, but the bare default must stay seconds
            opt.rnn_size = opt.input_encoding_size = opt.att_size = 32
            opt.drop_prob = 0.0
        import tempfile

        tmp = tempfile.mkdtemp(prefix="bf16_parity_")
        model, params, ds, loader = build_synthetic(opt, tmp)
    else:
        if not opt.test_feat_h5 or not opt.checkpoint_path:
            print("bf16_parity: need --checkpoint_path and --test_feat_h5/"
                  "--test_label_h5/--test_info_json/--test_cocofmt_file "
                  "(or pass --synthetic 1)", file=sys.stderr)
            return EXIT_USAGE
        from eval import load_model_for_eval

        ds = CaptionDataset(SplitPaths(
            feat_h5=list(opt.test_feat_h5), label_h5=opt.test_label_h5,
            info_json=opt.test_info_json,
            cocofmt_json=opt.test_cocofmt_file))
        loader = CaptionLoader(ds, batch_size=opt.batch_size,
                               seq_per_img=1, shuffle=False)
        model, params, opt = load_model_for_eval(opt.checkpoint_path, ds,
                                                 opt)

    bound = (DEFAULT_CIDER_DELTA_BOUND if opt.cider_delta_bound is None
             else float(opt.cider_delta_bound))
    ok, reason = bf16_decode_supported(model)
    try:
        if not ok:
            # Nothing to gate: the variant would fall back anyway.
            out = {"supported": False, "reason": reason,
                   "kernel_recommendation": "reference"}
            print(json.dumps(out))
            return EXIT_OK
        kw = dict(beam_size=opt.beam_size, length_norm=opt.length_norm,
                  decode_chunk=getattr(opt, "decode_chunk", 8))
        preds = {}
        for kernel in ("reference", "bf16"):
            m = model.clone(decode_kernel=kernel)
            preds[kernel] = decode_split(m, params, loader, ds.vocab,
                                         opt.max_length, **kw)
        refs = ds.references()
        scores = {k: language_eval(preds[k], refs, scorers=("CIDEr",))
                  for k in preds}
        agree = float(np.mean([
            a["caption"] == b["caption"]
            for a, b in zip(preds["reference"], preds["bf16"])]))
        out = {
            "supported": True,
            **parity_gate(scores["reference"]["CIDEr"],
                          scores["bf16"]["CIDEr"], bound),
            "caption_agreement": round(agree, 4),
            "num_videos": len(preds["reference"]),
            "beam_size": opt.beam_size,
        }
        print(json.dumps(out))
        if not out["within_bound"]:
            print(f"bf16_parity: CIDEr delta {out['delta']:+.4f} exceeds "
                  f"the declared bound {bound:g}; the bit-exact "
                  "'reference' kernel stays the recommendation "
                  "(ops/bf16_decode.py)", file=sys.stderr)
            return EXIT_FAILURE
        return EXIT_OK
    finally:
        ds.close()


if __name__ == "__main__":
    sys.exit(main())
