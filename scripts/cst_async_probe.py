#!/usr/bin/env python
"""Async-dispatch timing: rollout-only, grad-only, and pipelined CST loops.

Measures steady-state device throughput the way the XE bench does (queue N
steps, block once) to separate real device time from tunnel round-trip
latency that per-step block_until_ready measurements include.
"""
import argparse
import time

import numpy as np


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batch_size", type=int, default=32)
    p.add_argument("--seq_per_img", type=int, default=20)
    p.add_argument("--seq_len", type=int, default=30)
    p.add_argument("--vocab", type=int, default=8000)
    p.add_argument("--hidden", type=int, default=512)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--bfloat16", type=int, default=1)
    args = p.parse_args()

    import jax
    import jax.numpy as jnp

    print("platform:", jax.devices()[0].platform)

    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from bench import build, synthetic_rewarder
    from cst_captioning_tpu.training.steps import make_rl_grad_step, make_rollout

    model, state, feats, labels = build(
        args.batch_size, args.seq_per_img, args.seq_len, args.vocab,
        args.hidden, args.bfloat16,
    )
    rc, video_ids, scorer_kind, _, _ = synthetic_rewarder(
        args.batch_size, args.seq_per_img, args.vocab
    )
    print("scorer:", scorer_kind)
    caps = args.batch_size * args.seq_per_img

    rollout = jax.jit(make_rollout(model, args.seq_len, args.seq_per_img))
    rl_step = jax.jit(make_rl_grad_step(model, args.seq_per_img),
                      donate_argnums=(0,))

    # warm up / compile
    sampled, greedy = rollout(state.params, feats, jax.random.PRNGKey(0))
    s = np.asarray(jax.device_get(sampled))
    g = np.asarray(jax.device_get(greedy))
    adv, _ = rc(video_ids, s, g)
    adv = jnp.asarray(adv)
    state, m = rl_step(state, feats, sampled, adv, jax.random.PRNGKey(0))
    jax.block_until_ready(m["loss"])

    # -- rollout-only, async queue ----------------------------------------
    t0 = time.perf_counter()
    outs = []
    for i in range(args.steps):
        sampled, greedy = rollout(state.params, feats, jax.random.PRNGKey(i))
        outs.append(sampled)
    jax.block_until_ready(outs[-1])
    dt = (time.perf_counter() - t0) / args.steps
    print(f"rollout async: {dt*1000:.1f}ms/step  ({caps/dt:.0f} caps/s)")

    # -- grad-only, async queue -------------------------------------------
    t0 = time.perf_counter()
    for i in range(args.steps):
        state, m = rl_step(state, feats, sampled, adv, jax.random.PRNGKey(i))
    jax.block_until_ready(m["loss"])
    dt = (time.perf_counter() - t0) / args.steps
    print(f"rl_step async: {dt*1000:.1f}ms/step  ({caps/dt:.0f} caps/s)")

    # -- pipelined CST loop: reward of step t overlaps rollout t+1 --------
    t0 = time.perf_counter()
    pending = None
    for i in range(args.steps):
        key = jax.random.PRNGKey(100 + i)
        sampled, greedy = rollout(state.params, feats, key)
        try:
            sampled.copy_to_host_async()
            greedy.copy_to_host_async()
        except AttributeError:
            pass
        if pending is not None:
            ps, pg, pkey = pending
            s = np.asarray(ps)
            g = np.asarray(pg)
            adv, _ = rc(video_ids, s, g)
            state, m = rl_step(state, feats, ps, jnp.asarray(adv), pkey)
        pending = (sampled, greedy, key)
    ps, pg, pkey = pending
    adv, _ = rc(video_ids, np.asarray(ps), np.asarray(pg))
    state, m = rl_step(state, feats, ps, jnp.asarray(adv), pkey)
    jax.block_until_ready(m["loss"])
    dt = (time.perf_counter() - t0) / args.steps
    print(f"pipelined cst: {dt*1000:.1f}ms/step  ({caps/dt:.0f} caps/s)")

    # -- pipelined, single fused fetch (concat sampled+greedy on device) --
    @jax.jit
    def rollout_cat(params, f, key):
        s, g = make_rollout(model, args.seq_len, args.seq_per_img)(params, f, key)
        return s, g, jnp.concatenate([s, g], axis=0)

    s, g, cat = rollout_cat(state.params, feats, jax.random.PRNGKey(0))
    jax.block_until_ready(cat)
    t0 = time.perf_counter()
    pending = None
    for i in range(args.steps):
        key = jax.random.PRNGKey(300 + i)
        sampled, greedy, cat = rollout_cat(state.params, feats, key)
        try:
            cat.copy_to_host_async()
        except AttributeError:
            pass
        if pending is not None:
            ps, pcat, pkey = pending
            both = np.asarray(pcat)
            adv, _ = rc(video_ids, both[:caps], both[caps:])
            state, m = rl_step(state, feats, ps, jnp.asarray(adv), pkey)
        pending = (sampled, cat, key)
    ps, pcat, pkey = pending
    both = np.asarray(pcat)
    adv, _ = rc(video_ids, both[:caps], both[caps:])
    state, m = rl_step(state, feats, ps, jnp.asarray(adv), pkey)
    jax.block_until_ready(m["loss"])
    dt = (time.perf_counter() - t0) / args.steps
    print(f"pipelined+cat: {dt*1000:.1f}ms/step  ({caps/dt:.0f} caps/s)")

    # -- depth-2 pipeline + fused fetch -----------------------------------
    from collections import deque
    t0 = time.perf_counter()
    q = deque()
    for i in range(args.steps):
        key = jax.random.PRNGKey(400 + i)
        sampled, greedy, cat = rollout_cat(state.params, feats, key)
        try:
            cat.copy_to_host_async()
        except AttributeError:
            pass
        q.append((sampled, cat, key))
        if len(q) > 2:
            ps, pcat, pkey = q.popleft()
            both = np.asarray(pcat)
            adv, _ = rc(video_ids, both[:caps], both[caps:])
            state, m = rl_step(state, feats, ps, jnp.asarray(adv), pkey)
    while q:
        ps, pcat, pkey = q.popleft()
        both = np.asarray(pcat)
        adv, _ = rc(video_ids, both[:caps], both[caps:])
        state, m = rl_step(state, feats, ps, jnp.asarray(adv), pkey)
    jax.block_until_ready(m["loss"])
    dt = (time.perf_counter() - t0) / args.steps
    print(f"depth2+cat:    {dt*1000:.1f}ms/step  ({caps/dt:.0f} caps/s)")

    # -- serial CST loop (reference semantics, no overlap) ----------------
    t0 = time.perf_counter()
    for i in range(args.steps):
        key = jax.random.PRNGKey(200 + i)
        sampled, greedy = rollout(state.params, feats, key)
        adv, _ = rc(video_ids, np.asarray(jax.device_get(sampled)),
                    np.asarray(jax.device_get(greedy)))
        state, m = rl_step(state, feats, sampled, jnp.asarray(adv), key)
    jax.block_until_ready(m["loss"])
    dt = (time.perf_counter() - t0) / args.steps
    print(f"serial cst:    {dt*1000:.1f}ms/step  ({caps/dt:.0f} caps/s)")


if __name__ == "__main__":
    main()
