#!/usr/bin/env python
"""Regenerate + SHA-fingerprint the north-star dataset (`make
dataset-regen`, VERDICT item 8).

The scale chain's dataset lives in /tmp (scripts/scale_chain.py) — a
host wipe deletes it, and "just regenerate it" is only trustworthy if
the rebuild is PROVABLY the same dataset the committed evidence was
trained on.  This tool regenerates via the same ``generate_data``
recipe the chain uses and fingerprints the artifacts that define the
dataset's identity: the label h5 and the vocab json, per split.

Fingerprints are CONTENT hashes, not file hashes: HDF5 embeds object
modification times in its headers, so the raw bytes of two identical
regenerations differ — instead we hash every dataset's (name, shape,
dtype, array bytes) in sorted name order, and the vocab as canonical
JSON.  Feature h5s are derived deterministically from the label plane
(same seed chain) and are multi-GB, so the label+vocab pair IS the
identity; ``--labels_only`` (default) skips feature synthesis.

    # prove a post-/tmp-wipe rebuild identical to the committed record:
    make dataset-regen          # regen + --check, exit 1 on mismatch
    # refresh the committed record after a DELIBERATE spec change:
    python scripts/dataset_fingerprint.py --update
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_ARTIFACT = os.path.join(REPO, "artifacts",
                                "dataset_fingerprint.json")

#: Fingerprint record format version.
FINGERPRINT_SCHEMA = 1


def h5_content_sha256(path: str) -> str:
    """Content hash of every dataset in an h5 file, sorted by name —
    stable across regeneration (HDF5 header mtimes excluded by
    construction)."""
    import h5py

    h = hashlib.sha256()
    with h5py.File(path, "r") as f:
        names: list = []
        f.visititems(lambda name, obj: names.append(name)
                     if isinstance(obj, h5py.Dataset) else None)
        for name in sorted(names):
            ds = f[name]
            h.update(name.encode("utf-8"))
            h.update(repr(tuple(ds.shape)).encode())
            h.update(str(ds.dtype).encode())
            h.update(ds[()].tobytes())
    return h.hexdigest()


def json_content_sha256(path: str) -> str:
    """Canonical-JSON hash: key order and whitespace cannot perturb it."""
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    canon = json.dumps(doc, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(canon.encode("utf-8")).hexdigest()


def fingerprint_paths(paths: dict) -> dict:
    """Per-split {label_h5, vocab_json} content hashes + one combined
    digest (the headline the Makefile prints)."""
    out: dict = {"schema": FINGERPRINT_SCHEMA, "splits": {}}
    combined = hashlib.sha256()
    for split in sorted(paths):
        p = paths[split]
        rec = {"label_h5": h5_content_sha256(p["label_h5"]),
               "vocab_json": json_content_sha256(p["vocab_json"])}
        out["splits"][split] = rec
        combined.update(split.encode())
        combined.update(rec["label_h5"].encode())
        combined.update(rec["vocab_json"].encode())
    out["combined"] = combined.hexdigest()
    return out


def regenerate(root: str, *, num_videos: int, num_val: int,
               feat_dims, feat_times, rich_vocab: int,
               labels_only: bool) -> dict:
    """The chain's own recipe (scripts/scale_chain.generate_data) —
    never a private reimplementation that could drift.  With
    ``labels_only`` the expensive feature h5s are skipped (they are not
    part of the fingerprint identity)."""
    from scale_chain import generate_data

    if labels_only:
        # The feature synthesis step reads the label plane back, so
        # skipping it is a pure suffix cut: patch generate() to stop
        # after build_split + vocab.
        import cst_captioning_tpu.data.synthetic as synthetic

        real_write = synthetic._write_features

        def skip(*a, **kw):
            return []  # keeps paths["feat_h5"] a valid (empty) path list

        synthetic._write_features = skip
        try:
            return generate_data(root, num_videos, num_val,
                                 feat_dims=tuple(feat_dims),
                                 feat_times=tuple(feat_times),
                                 rich_vocab=rich_vocab)
        finally:
            synthetic._write_features = real_write
    return generate_data(root, num_videos, num_val,
                         feat_dims=tuple(feat_dims),
                         feat_times=tuple(feat_times),
                         rich_vocab=rich_vocab)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="regenerate + content-fingerprint the north-star "
                    "dataset (make dataset-regen)")
    p.add_argument("--out_dir", default=None,
                   help="regenerate here (default: a fresh temp dir — "
                        "the post-wipe-rebuild proof; pass "
                        "/tmp/cst_scale/data to also leave the chain's "
                        "dataset in place)")
    p.add_argument("--artifact", default=DEFAULT_ARTIFACT,
                   help="the committed fingerprint record")
    p.add_argument("--num_videos", type=int, default=6513)
    p.add_argument("--num_val", type=int, default=497)
    p.add_argument("--feat_dims", type=int, nargs="+",
                   default=[2048, 4096])
    p.add_argument("--feat_times", type=int, nargs="+", default=[28, 1])
    p.add_argument("--rich_vocab", type=int, default=8000)
    p.add_argument("--labels_only", type=int, default=1,
                   help="1 (default) = skip feature-h5 synthesis; the "
                        "fingerprint covers label h5 + vocab only")
    p.add_argument("--update", action="store_true",
                   help="write the artifact instead of checking it")
    p.add_argument("--check", action="store_true",
                   help="compare against the artifact; exit 1 on "
                        "mismatch (the default when the artifact "
                        "exists)")
    args = p.parse_args(argv)

    root = args.out_dir or tempfile.mkdtemp(prefix="cst_dataset_fp_")
    os.makedirs(root, exist_ok=True)
    paths = regenerate(root, num_videos=args.num_videos,
                       num_val=args.num_val, feat_dims=args.feat_dims,
                       feat_times=args.feat_times,
                       rich_vocab=args.rich_vocab,
                       labels_only=bool(args.labels_only))
    fp = fingerprint_paths(paths)
    fp["spec"] = {"num_videos": args.num_videos,
                  "num_val": args.num_val,
                  "feat_dims": list(args.feat_dims),
                  "feat_times": list(args.feat_times),
                  "rich_vocab": args.rich_vocab}
    print(json.dumps({"combined": fp["combined"], "root": root}))

    if args.update:
        from cst_captioning_tpu.resilience.integrity import atomic_json_write

        os.makedirs(os.path.dirname(args.artifact), exist_ok=True)
        atomic_json_write(args.artifact, fp, indent=2, sort_keys=True)
        print(f"dataset_fingerprint: wrote {args.artifact}")
        return 0

    if args.check or os.path.exists(args.artifact):
        if not os.path.exists(args.artifact):
            print(f"dataset_fingerprint: no committed artifact at "
                  f"{args.artifact} (run --update first)",
                  file=sys.stderr)
            return 1
        with open(args.artifact) as f:
            want = json.load(f)
        if want.get("spec") != fp["spec"]:
            print("dataset_fingerprint: spec differs from the "
                  "committed record — comparing apples to oranges:\n"
                  f"  committed: {want.get('spec')}\n"
                  f"  this run:  {fp['spec']}", file=sys.stderr)
            return 1
        if want.get("combined") != fp["combined"]:
            for split, rec in fp["splits"].items():
                was = (want.get("splits") or {}).get(split) or {}
                for key, got in rec.items():
                    if was.get(key) != got:
                        print(f"dataset_fingerprint: {split}/{key} "
                              f"mismatch: committed {was.get(key)}, "
                              f"regenerated {got}", file=sys.stderr)
            return 1
        print("dataset_fingerprint: regeneration IDENTICAL to the "
              "committed record")
    return 0


if __name__ == "__main__":
    sys.exit(main())
