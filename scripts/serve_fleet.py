#!/usr/bin/env python
"""Fleet-serving CLI: the JSONL front end over N self-healing replicas.

``scripts/serve.py`` owns one ServingEngine; this front end builds a
:class:`serving.fleet.FleetRouter` over ``--serve_replicas`` engine
replicas (per-device where this host has more than one accelerator,
in-process otherwise) and drives it through the SAME
``serving.server.CaptionServer`` — the wire format, backpressure,
drain/SIGTERM, and health contracts are identical, so a client cannot
tell one engine from a fleet except by throughput (SERVING.md "Fleet").

    # zero-setup demo fleet (3 replicas):
    python scripts/serve_fleet.py --serve_demo 1 --serve_replicas 3

    # checkpoint mode, same flags as serve.py:
    python scripts/serve_fleet.py --checkpoint_path <dir> \\
        --test_feat_h5 ... --test_label_h5 ... --test_info_json ... \\
        --serve_replicas 4

Fleet specifics:

- All replicas share ONE ProgramCache (compile once fleet-wide; a
  replica restart re-warms with zero builds) and ONE exact-result cache
  (a caption decoded anywhere is a hit everywhere).
- ``{"op": "health"}`` answers the FLEET view: worst-of-replicas status
  plus per-replica detail (the router's snapshots), via the server's
  pluggable health source.  The heartbeat file carries the same view.
- ``--fault_plan 'serve_wedge@replica=K'`` (and the other serving kinds)
  targets the fault at replica K's engine (RESILIENCE.md).
- A replica whose self-healing ladder exhausts (in-process exit 124) is
  restarted by the router with its residents re-queued; only when every
  replica burns ``--serve_restart_limit`` does this process exit 124
  (``FleetUnrecoverable``) for supervised restart.
"""

from __future__ import annotations

import json
import logging
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from cst_captioning_tpu.opts import parse_opts  # noqa: E402

log = logging.getLogger("cst_captioning_tpu.serve_fleet")


def main(argv=None) -> int:
    opt = parse_opts(argv)
    from cst_captioning_tpu.opts import (warn_serve_deadline,
                                         warn_serving_decode_chunk)
    from cst_captioning_tpu.utils.platform import (configure_cli_logging,
                                                   enable_compile_cache)

    configure_cli_logging(opt.loglevel)
    warn_serving_decode_chunk(opt)
    warn_serve_deadline(opt)
    enable_compile_cache(getattr(opt, "compile_cache_dir", ""))

    import jax

    from serve import (build_checkpoint_backend,  # noqa: E402
                       build_demo_backend, write_exit_snapshot)
    from cst_captioning_tpu.resilience.faults import FaultPlan
    from cst_captioning_tpu.resilience.preemption import PreemptionHandler
    from cst_captioning_tpu.serving.buckets import ProgramCache, parse_buckets
    from cst_captioning_tpu.serving.cache import ResultCache
    from cst_captioning_tpu.serving.engine import ServingEngine
    from cst_captioning_tpu.serving.fleet import FleetRouter, FleetUnrecoverable
    from cst_captioning_tpu.serving.server import CaptionServer
    from cst_captioning_tpu.telemetry.registry import MetricsRegistry

    handler = PreemptionHandler().install()
    registry = MetricsRegistry()
    plan = FaultPlan.parse(getattr(opt, "fault_plan", None))
    if plan is not None:
        plan.bind_metrics(registry)
        log.warning("CHAOS: fleet fault plan armed: %s", plan)

    ds = None
    if opt.serve_demo:
        model, params, vocab, feat_shapes, feats_for = \
            build_demo_backend(opt)
    else:
        from cst_captioning_tpu.data.dataset import CaptionDataset, SplitPaths

        if not opt.test_feat_h5:
            print("serve_fleet.py: checkpoint mode needs --test_feat_h5/"
                  "--test_label_h5/--test_info_json (or pass "
                  "--serve_demo 1)", file=sys.stderr)
            return 2
        ds = CaptionDataset(SplitPaths(
            feat_h5=list(opt.test_feat_h5), label_h5=opt.test_label_h5,
            info_json=opt.test_info_json,
            cocofmt_json=opt.test_cocofmt_file))
        model, params, vocab, feat_shapes, feats_for, opt = \
            build_checkpoint_backend(opt, ds)

    tracer = None
    if getattr(opt, "trace_dir", None):
        from cst_captioning_tpu.telemetry.spans import SpanTracer

        tracer = SpanTracer(opt.trace_dir)

    # Shared across every replica AND every restarted engine: compile
    # once fleet-wide, one result entry per distinct video fleet-wide.
    programs = ProgramCache(registry)
    result_cache = (ResultCache(opt.serve_cache)
                    if opt.serve_cache else None)

    # Fleet-wide request-lifecycle tracing + flight recorder: ONE base
    # tracer — the router owns intake events, each replica's engine gets
    # a labeled view, and the blackbox carries the per-replica health
    # breakdown (OBSERVABILITY.md "Request lifecycle & flight recorder").
    lifecycle = None
    if opt.serve_lifecycle:
        from cst_captioning_tpu.telemetry.lifecycle import LifecycleTracer

        lifecycle = LifecycleTracer(opt.serve_lifecycle_events,
                                    tracer=tracer, registry=registry)

    def engine_factory(replica: int) -> ServingEngine:
        return ServingEngine(
            model, {"params": params}, feat_shapes,
            max_len=opt.max_length, beam_size=opt.beam_size,
            length_norm=opt.length_norm,
            decode_chunk=getattr(opt, "decode_chunk", 8),
            bucket_sizes=parse_buckets(opt.serve_buckets),
            queue_limit=opt.serve_queue_limit,
            deadline_ms=opt.serve_deadline_ms,
            fault_plan=(plan.for_replica(replica)
                        if plan is not None else None),
            recover=bool(opt.serve_recover),
            retry_limit=opt.serve_retry_limit,
            rebuild_limit=opt.serve_rebuild_limit,
            step_budget_ms=opt.serve_step_budget_ms,
            result_cache=result_cache,
            program_cache=programs,
            registry=registry, tracer=tracer,
            lifecycle=(lifecycle.for_replica(replica)
                       if lifecycle is not None else None))

    local = jax.local_devices()
    devices = local if len(local) > 1 else None
    router = FleetRouter(engine_factory, opt.serve_replicas,
                         devices=devices,
                         restart_limit=opt.serve_restart_limit,
                         registry=registry, lifecycle=lifecycle)
    router.warm()
    log.info("fleet warm: %d replica(s) over %d device(s), buckets=%s "
             "beam=%d chunk=%d compiles=%d", opt.serve_replicas,
             len(devices) if devices else 1, list(router.buckets),
             router.beam_size, router.chunk, router.stats()["compiles"])

    server = CaptionServer(router, vocab, feats_for, handler=handler,
                           registry=registry,
                           health_source=router.health,
                           lifecycle=lifecycle,
                           blackbox_path=(opt.serve_blackbox or None))
    if lifecycle is not None:
        # Blackbox state providers: the server health view (per-replica
        # detail via the router's health source, draining folded in),
        # registry counters, the shared ProgramCache.
        lifecycle.attach(
            health=server.health_payload,
            counters=lambda: registry.snapshot().get("counters"),
            program_cache=lambda: {"builds": programs.builds,
                                   "entries": len(programs)})

    watchdog = None
    if opt.serve_heartbeat_file or opt.wedge_timeout > 0:
        from cst_captioning_tpu.utils.watchdog import ProgressWatchdog

        watchdog = ProgressWatchdog(
            opt.wedge_timeout,
            describe=lambda: "fleet scheduler loop",
            heartbeat_path=opt.serve_heartbeat_file,
            payload=lambda: {"serving": server.health_payload(),
                             **registry.heartbeat_payload()},
            heartbeat_interval_s=1.0).start()
        server.watchdog = watchdog
    try:
        try:
            if opt.serve_port:
                port = 0 if opt.serve_port < 0 else opt.serve_port
                rc = server.run_socket(port)
            else:
                rc = server.run_stdin()
        except FleetUnrecoverable as e:
            from cst_captioning_tpu.resilience.exitcodes import (
                EXIT_WEDGE,
                describe,
            )

            print(f"serve_fleet: UNRECOVERABLE: {e}; exiting {EXIT_WEDGE} "
                  f"({describe(EXIT_WEDGE)})", file=sys.stderr)
            if lifecycle is not None and opt.serve_blackbox:
                # The crash blackbox (exit 124): what was in flight
                # when the last replica died — written BEFORE the exit.
                try:
                    lifecycle.dump(opt.serve_blackbox,
                                   reason="fleet_unrecoverable")
                    print(f"serve_fleet: blackbox written to "
                          f"{opt.serve_blackbox}", file=sys.stderr)
                except OSError as werr:
                    print(f"serve_fleet: blackbox write failed: {werr}",
                          file=sys.stderr)
            rc = EXIT_WEDGE
    finally:
        if watchdog is not None:
            watchdog.stop()
        stats = router.stats()
        print("serve_fleet: " + json.dumps(stats), file=sys.stderr)
        if opt.result_file:
            from cst_captioning_tpu.resilience.integrity import (
                atomic_json_write,
            )

            atomic_json_write(opt.result_file,
                              {"stats": stats,
                               "health": router.health(),
                               "telemetry": registry.snapshot()}, indent=2)
        write_exit_snapshot(opt, registry)
        if tracer is not None:
            tracer.close()
        if ds is not None:
            ds.close()
    return rc


if __name__ == "__main__":
    sys.exit(main())
