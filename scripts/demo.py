#!/usr/bin/env python
"""Zero-setup demo: synthesize a tiny dataset, run XE -> WXE -> CST -> eval.

The fastest way to see every pipeline stage work end to end without MSR-VTT
downloads (`make demo`).  Mirrors tests/test_trainer_e2e.py but as a user
script with readable output.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--out_dir", default="/tmp/cst_demo")
    p.add_argument("--epochs", type=int, default=3)
    args = p.parse_args()

    from cst_captioning_tpu.data.synthetic import SyntheticSpec, generate
    from cst_captioning_tpu.data.vocab import load_vocab
    import eval as eval_cli
    import train as train_cli

    root = os.path.join(args.out_dir, "data")
    ckpt = os.path.join(args.out_dir, "checkpoints")
    os.makedirs(root, exist_ok=True)

    spec = SyntheticSpec(num_videos=16, captions_per_video=5, max_len=12,
                         feat_dims=(32, 16), feat_times=(4, 1))
    train = generate(root, "train", spec)
    vocab = load_vocab(train["vocab_json"])
    val = generate(root, "val", SyntheticSpec(num_videos=8, captions_per_video=5,
                                              max_len=12, feat_dims=(32, 16),
                                              feat_times=(4, 1)), vocab=vocab)

    common = [
        "--train_feat_h5", *json.loads(train["feat_h5"]),
        "--train_label_h5", train["label_h5"],
        "--train_info_json", train["info_json"],
        "--train_cocofmt_file", train["cocofmt_json"],
        "--val_feat_h5", *json.loads(val["feat_h5"]),
        "--val_label_h5", val["label_h5"],
        "--val_info_json", val["info_json"],
        "--val_cocofmt_file", val["cocofmt_json"],
        "--batch_size", "8", "--seq_per_img", "4",
        "--rnn_size", "64", "--input_encoding_size", "32", "--att_size", "32",
        "--max_length", "12", "--drop_prob", "0.2",
        "--max_epochs", str(args.epochs), "--learning_rate", "0.01",
        "--log_every", "2", "--fast_val", "1", "--max_patience", "0",
    ]

    print("=== stage 1/3: XE pretrain ===")
    train_cli.main([*common, "--checkpoint_path", f"{ckpt}/xe"])

    print("=== stage 2/3: WXE (consensus-weighted) warm-start ===")
    train_cli.main([
        *common, "--checkpoint_path", f"{ckpt}/wxe",
        "--start_from", f"{ckpt}/xe",
        "--use_consensus_weights", "1",
        "--train_bcmrscores_pkl", train["consensus_pkl"],
        "--max_epochs", "2",
    ])

    print("=== stage 3/3: CST / REINFORCE (greedy baseline) ===")
    train_cli.main([
        *common, "--checkpoint_path", f"{ckpt}/cst",
        "--start_from", f"{ckpt}/wxe",
        "--use_rl", "1", "--rl_baseline", "greedy",
        "--train_cached_tokens", train["cached_tokens"],
        "--learning_rate", "0.0005", "--max_epochs", "2",
    ])

    print("=== beam-search eval of the CST checkpoint ===")
    eval_cli.main([
        "--checkpoint_path", f"{ckpt}/cst",
        "--test_feat_h5", *json.loads(val["feat_h5"]),
        "--test_label_h5", val["label_h5"],
        "--test_info_json", val["info_json"],
        "--test_cocofmt_file", val["cocofmt_json"],
        "--beam_size", "3", "--batch_size", "8", "--max_length", "12",
        "--result_file", os.path.join(args.out_dir, "test_scores.json"),
    ])
    print("demo artifacts in", args.out_dir)
    return 0


if __name__ == "__main__":
    sys.exit(main())
