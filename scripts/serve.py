#!/usr/bin/env python
"""Caption-serving CLI: continuous batching over the compiled decode path.

Front end for ``cst_captioning_tpu/serving/`` (SERVING.md).  Two backends:

- **checkpoint mode** (default): load a stage's BEST checkpoint exactly
  like eval.py, serve the test split's videos by id —

    python scripts/serve.py --checkpoint_path <dir> \\
        --test_feat_h5 ... --test_label_h5 ... --test_info_json ... \\
        --beam_size 1 --serve_queue_limit 64

- **demo mode** (``--serve_demo 1``): zero-setup tiny untrained model +
  synthetic feature table (ids ``v0``..``v15``); captions are gibberish,
  the serving path — admission, slot recycling, backpressure, drain — is
  the real one.  ``make serve-demo`` pipes a few requests through it.

Protocol: one JSON object per line on stdin/stdout (or, with
``--serve_port``, on a localhost socket):

    {"id": 1, "video_id": "v3"}
    -> {"id": 1, "video_id": "v3", "caption": ..., "latency_ms": ...}

Shutdown: SIGTERM/SIGINT drains in-flight requests, rejects queued ones,
and exits 75 (``resilience/exitcodes.EXIT_PREEMPTED``, resumable); stdin
EOF finishes everything and exits 0.  Engine stats land on stderr and —
when ``--result_file`` is set — as a JSON stats file.
"""

from __future__ import annotations

import json
import logging
import os
import sys

import numpy as np

sys.path.insert(0, __import__("os").path.dirname(
    __import__("os").path.dirname(__import__("os").path.abspath(__file__))))

from cst_captioning_tpu.opts import parse_opts  # noqa: E402

log = logging.getLogger("cst_captioning_tpu.serve")

DEMO_WORDS = ("a", "man", "woman", "dog", "is", "playing", "running",
              "cooking", "guitar", "outside", "the", "park", "ball",
              "talking", "singing", "fast")
DEMO_VIDEOS = 16
DEMO_FEAT_SHAPES = ((4, 16), (1, 8))


def build_demo_backend(opt):
    """Tiny untrained EOS-biased model + seeded feature table -> the
    (model, params, vocab, feat_shapes, feats_for) quintet."""
    import jax
    import jax.numpy as jnp

    from cst_captioning_tpu.data.vocab import Vocab
    from cst_captioning_tpu.models import CaptionModel

    vocab = Vocab({i + 1: w for i, w in enumerate(DEMO_WORDS)})
    model = CaptionModel(
        vocab_size=vocab.size_with_pad, embed_size=16, hidden_size=16,
        attn_size=16, dropout_rate=0.0,
        decode_kernel=getattr(opt, "decode_kernel", "reference"))
    feats0 = [jnp.zeros((1,) + s, jnp.float32) for s in DEMO_FEAT_SHAPES]
    variables = model.init(jax.random.PRNGKey(0), feats0,
                           np.zeros((1, opt.max_length), np.int32))
    params = {**variables["params"]}
    params["logit"] = {**params["logit"]}
    # Bias EOS so untrained captions terminate in a few steps (the
    # bench-probe trick) — the demo shows scheduling, not caption quality.
    # The chaos drills flip the bias negative (--serve_demo_eos_bias) to
    # hold residents in flight for the drain/deadline windows.
    params["logit"]["bias"] = params["logit"]["bias"].at[0].add(
        getattr(opt, "serve_demo_eos_bias", 0.2))
    rng = np.random.default_rng(0)
    table = [rng.standard_normal((DEMO_VIDEOS,) + s).astype(np.float32)
             for s in DEMO_FEAT_SHAPES]

    def feats_for(video_id):
        try:
            ix = int(str(video_id).lstrip("v"))
        except ValueError:
            return None
        if not 0 <= ix < DEMO_VIDEOS:
            return None
        return [t[ix] for t in table]

    return model, params, vocab, list(DEMO_FEAT_SHAPES), feats_for


def write_exit_snapshot(opt, registry) -> None:
    """The train.py exit discipline for the serving CLIs: an atomic
    telemetry.json snapshot on every drain/exit, so serving chaos
    drills leave the same machine-auditable artifact a training run
    does.  ``--serve_telemetry_file`` wins; checkpoint mode defaults to
    ``<checkpoint_path>/telemetry.json``; demo mode defaults to off."""
    snap_path = opt.serve_telemetry_file
    if not snap_path and not opt.serve_demo:
        snap_path = os.path.join(os.path.abspath(opt.checkpoint_path),
                                 "telemetry.json")
    if snap_path:
        os.makedirs(os.path.dirname(os.path.abspath(snap_path)),
                    exist_ok=True)
        registry.write_snapshot(snap_path)


def build_checkpoint_backend(opt, ds):
    """eval.py's checkpoint restore + an h5-lookup feats_for."""
    from eval import load_model_for_eval

    model, params, opt = load_model_for_eval(opt.checkpoint_path, ds, opt)
    row_of = {vid: i for i, vid in enumerate(ds.video_ids)}

    def feats_for(video_id):
        ix = row_of.get(str(video_id))
        if ix is None:
            return None
        return [np.asarray(f)[0] for f in ds.features(np.asarray([ix]))]

    return model, params, ds.vocab, \
        list(zip(ds.feat_times, ds.feat_dims)), feats_for, opt


def main(argv=None) -> int:
    opt = parse_opts(argv)
    from cst_captioning_tpu.opts import (warn_serve_deadline,
                                         warn_serving_decode_chunk)
    from cst_captioning_tpu.utils.platform import (configure_cli_logging,
                                                   enable_compile_cache)

    configure_cli_logging(opt.loglevel)
    warn_serving_decode_chunk(opt)
    warn_serve_deadline(opt)
    enable_compile_cache(getattr(opt, "compile_cache_dir", ""))

    from cst_captioning_tpu.resilience.faults import FaultPlan
    from cst_captioning_tpu.resilience.preemption import PreemptionHandler
    from cst_captioning_tpu.serving.buckets import parse_buckets
    from cst_captioning_tpu.serving.cache import ResultCache
    from cst_captioning_tpu.serving.engine import (ServingEngine,
                                                   ServingUnrecoverable)
    from cst_captioning_tpu.serving.server import CaptionServer
    from cst_captioning_tpu.telemetry.registry import MetricsRegistry

    handler = PreemptionHandler().install()
    registry = MetricsRegistry()
    plan = FaultPlan.parse(getattr(opt, "fault_plan", None))
    if plan is not None:
        plan.bind_metrics(registry)
        log.warning("CHAOS: serving fault plan armed: %s", plan)

    ds = None
    if opt.serve_demo:
        model, params, vocab, feat_shapes, feats_for = \
            build_demo_backend(opt)
    else:
        from cst_captioning_tpu.data.dataset import CaptionDataset, SplitPaths

        if not opt.test_feat_h5:
            print("serve.py: checkpoint mode needs --test_feat_h5/"
                  "--test_label_h5/--test_info_json (or pass "
                  "--serve_demo 1)", file=sys.stderr)
            return 2
        ds = CaptionDataset(SplitPaths(
            feat_h5=list(opt.test_feat_h5), label_h5=opt.test_label_h5,
            info_json=opt.test_info_json,
            cocofmt_json=opt.test_cocofmt_file))
        model, params, vocab, feat_shapes, feats_for, opt = \
            build_checkpoint_backend(opt, ds)

    tracer = None
    if getattr(opt, "trace_dir", None):
        from cst_captioning_tpu.telemetry.spans import SpanTracer

        tracer = SpanTracer(opt.trace_dir)

    # Request-lifecycle tracing + flight recorder (OBSERVABILITY.md
    # "Request lifecycle & flight recorder"): per-request causal events
    # into a bounded ring, mirrored into the Chrome trace when
    # --trace_dir is set; blackbox.json lands on exit 124, on a
    # hard-abort drain, and on the {"op": "dump"} wire op.
    lifecycle = None
    if opt.serve_lifecycle:
        from cst_captioning_tpu.telemetry.lifecycle import LifecycleTracer

        lifecycle = LifecycleTracer(opt.serve_lifecycle_events,
                                    tracer=tracer, registry=registry)

    engine = ServingEngine(
        model, {"params": params}, feat_shapes,
        max_len=opt.max_length, beam_size=opt.beam_size,
        length_norm=opt.length_norm,
        decode_chunk=getattr(opt, "decode_chunk", 8),
        bucket_sizes=parse_buckets(opt.serve_buckets),
        queue_limit=opt.serve_queue_limit,
        deadline_ms=opt.serve_deadline_ms,
        fault_plan=plan,
        recover=bool(opt.serve_recover),
        retry_limit=opt.serve_retry_limit,
        rebuild_limit=opt.serve_rebuild_limit,
        step_budget_ms=opt.serve_step_budget_ms,
        result_cache=(ResultCache(opt.serve_cache)
                      if opt.serve_cache else None),
        registry=registry, tracer=tracer, lifecycle=lifecycle)
    engine.warm()
    log.info("engine warm: buckets=%s beam=%d chunk=%d queue_limit=%d "
             "deadline_ms=%s recover=%d cache=%d",
             engine.buckets, engine.beam_size, engine.chunk,
             opt.serve_queue_limit, opt.serve_deadline_ms,
             int(opt.serve_recover), int(opt.serve_cache))

    server = CaptionServer(engine, vocab, feats_for, handler=handler,
                           registry=registry, lifecycle=lifecycle,
                           blackbox_path=(opt.serve_blackbox or None))
    if lifecycle is not None:
        # The blackbox's state providers: health (server view, so
        # draining shows), registry counters, ProgramCache state.
        lifecycle.attach(
            health=server.health_payload,
            counters=lambda: registry.snapshot().get("counters"),
            program_cache=lambda: {"builds": engine.program_cache.builds,
                                   "entries": len(engine.program_cache)})

    # The serving health plane's liveness file: heartbeat.json once per
    # second (watchdog atomic-write discipline) carrying the SAME health
    # payload the {"op": "health"} query answers (so draining shows up in
    # the file too) + registry counters; with --wedge_timeout the same
    # watchdog also turns a wedged scheduler loop into a fast exit 124.
    watchdog = None
    if opt.serve_heartbeat_file or opt.wedge_timeout > 0:
        from cst_captioning_tpu.utils.watchdog import ProgressWatchdog

        watchdog = ProgressWatchdog(
            opt.wedge_timeout,
            describe=lambda: "serving scheduler loop",
            heartbeat_path=opt.serve_heartbeat_file,
            payload=lambda: {"serving": server.health_payload(),
                             **registry.heartbeat_payload()},
            heartbeat_interval_s=1.0).start()
        server.watchdog = watchdog
    try:
        try:
            if opt.serve_port:
                port = 0 if opt.serve_port < 0 else opt.serve_port
                rc = server.run_socket(port)
            else:
                rc = server.run_stdin()
        except ServingUnrecoverable as e:
            from cst_captioning_tpu.resilience.exitcodes import (
                EXIT_WEDGE,
                describe,
            )

            print(f"serve: UNRECOVERABLE: {e}; exiting {EXIT_WEDGE} "
                  f"({describe(EXIT_WEDGE)})", file=sys.stderr)
            if lifecycle is not None and opt.serve_blackbox:
                # The crash blackbox: what was in flight when the
                # self-healing ladder exhausted — written BEFORE the
                # exit so the evidence outlives the process.
                try:
                    lifecycle.dump(opt.serve_blackbox,
                                   reason="unrecoverable")
                    print(f"serve: blackbox written to "
                          f"{opt.serve_blackbox}", file=sys.stderr)
                except OSError as werr:
                    print(f"serve: blackbox write failed: {werr}",
                          file=sys.stderr)
            rc = EXIT_WEDGE
    finally:
        if watchdog is not None:
            watchdog.stop()
        stats = engine.stats()
        print("serve: " + json.dumps(stats), file=sys.stderr)
        if opt.result_file:
            from cst_captioning_tpu.resilience.integrity import (
                atomic_json_write,
            )

            atomic_json_write(opt.result_file,
                              {"stats": stats,
                               "health": engine.health(),
                               "telemetry": registry.snapshot()}, indent=2)
        write_exit_snapshot(opt, registry)
        if tracer is not None:
            tracer.close()
        if ds is not None:
            ds.close()
    return rc


if __name__ == "__main__":
    sys.exit(main())
