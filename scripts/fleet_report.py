#!/usr/bin/env python
"""Render a supervised fleet's scraped metrics series + SLO verdict.

Reads the append-only ``fleet_metrics.jsonl`` the fleet-observability
plane writes (telemetry/fleetobs.py: one schema-stamped sample per
``--fleet_scrape_ms``, one row per replica SLOT per sample — the
zero-gap contract) and prints the fleet picture over time: fleet-wide
and per-child p50/p99 latency, queue depth, slot occupancy, cache hit
rate, restart counts, and the SLO burn-rate status.

  python scripts/fleet_report.py --dir  <supervise_dir>
  python scripts/fleet_report.py --file <fleet_metrics.jsonl>

Gates (the serve_report discipline — a report that only prints would
hide a broken plane; each failure is one ``!!`` stderr line + exit 1):

- **no samples** — the scraper never ran or the file is unreadable;
- **burn-rate violation** — any sample's SLO status shows a firing
  objective (the supervisor's fast+slow windows both burned over
  threshold);
- **scrape blackout** — the wall-clock gap between consecutive samples
  exceeds ``--blackout_factor`` (default 3) times the stamped scrape
  interval: the plane went dark while the fleet kept running;
- **coverage hole** — a sample is missing replica-slot rows (fewer
  child rows than the fleet's replica count).

Autoscale gates (active only when the run carried autoscaler data —
``fleet.autoscale`` in any sample — so fixed-size runs are untouched):

- **scale-event loss** — the active-replica count changed during the
  run but the final sample still shows outstanding or parked work:
  a scale event stranded requests;
- **thrash** — more than ``--max_scale_changes`` (default 4) replica-
  count changes: the autoscaler is flapping instead of converging;
- **brownout p99 breach** — a sample taken while a brownout rung was
  engaged shows fleet p99 above the SLO p99 target (or
  ``--brownout_p99_ms``): shedding failed to protect admitted work.

Journal gates (``--dir`` runs whose supervisor_exit.json carries a
``journal`` block — ISSUE 20; journal-less runs are untouched): the
journal directory is re-scanned and the report fails on a **coverage
hole** (an accepted id missing from both the journal's terminal
records and any terminal response) or a **high-water violation** (the
exit snapshot's durable segment+offset mark names bytes that no longer
exist).

See OBSERVABILITY.md "Fleet plane" and SERVING.md "Autoscaling &
brownout".
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def load_samples(args) -> list:
    """All parseable fleet_sample rows, parts first then the active
    file (rotation order); a torn final line (crash mid-append) is
    skipped, not fatal."""
    if args.file:
        paths = [args.file]
    else:
        root = os.path.abspath(args.dir)
        paths = []
        index_path = os.path.join(root, "fleet_metrics_index.json")
        if os.path.exists(index_path):
            try:
                with open(index_path, "r", encoding="utf-8") as f:
                    for part in json.load(f).get("parts", []):
                        paths.append(os.path.join(root, part))
            except (OSError, ValueError) as e:
                print(f"fleet_report: part index unreadable: {e}",
                      file=sys.stderr)
        paths.append(os.path.join(root, "fleet_metrics.jsonl"))
    samples = []
    for path in paths:
        try:
            with open(path, "r", encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        row = json.loads(line)
                    except ValueError:
                        continue  # torn tail of a crashed part
                    if isinstance(row, dict) \
                            and row.get("kind") == "fleet_sample":
                        samples.append(row)
        except OSError:
            continue
    return samples


def fmt(v, unit="") -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:,.2f}{unit}"
    return f"{v}{unit}"


def _per_child(samples: list) -> dict:
    """index -> {rows, live, restarts, last} accumulated over the run."""
    acc: dict = {}
    for s in samples:
        for c in s.get("children", []):
            idx = c.get("index")
            a = acc.setdefault(idx, {"rows": 0, "live": 0,
                                     "restarts": 0, "last": None})
            a["rows"] += 1
            if c.get("live"):
                a["live"] += 1
            a["restarts"] = max(a["restarts"], int(c.get("restarts") or 0))
            a["last"] = c
    return acc


def replica_timeline(samples: list) -> list:
    """Run-length-compressed active-replica counts over the sample
    series, e.g. ``[1, 3, 1]`` for a burst that scaled 1→3→1.  Prefers
    ``fleet.active`` (excludes retired slots; written since the
    autoscaler landed) and falls back to ``fleet.replicas`` for old
    records, where the slot count never changes."""
    counts = []
    for s in samples:
        fleet = s.get("fleet") or {}
        n = fleet.get("active", fleet.get("replicas"))
        if n is None:
            continue
        if not counts or counts[-1] != int(n):
            counts.append(int(n))
    return counts


def _autoscale_samples(samples: list) -> list:
    """The samples stamped by an armed autoscaler (fleet.autoscale)."""
    return [s for s in samples
            if (s.get("fleet") or {}).get("autoscale")]


def check_gates(samples: list, blackout_factor: float,
                max_scale_changes: int = 4,
                brownout_p99_ms: float = None) -> list:
    """-> list of '!!' gate messages (empty = healthy)."""
    gates = []
    firing = sorted({name for s in samples
                     for name in (s.get("slo") or {}).get("firing", [])})
    if firing:
        gates.append(
            f"SLO burn-rate violation: objective(s) {','.join(firing)} "
            "fired during the run — fast AND slow windows burned the "
            "error budget over threshold (OBSERVABILITY.md 'Fleet "
            "plane')")
    worst_gap = None
    for prev, cur in zip(samples, samples[1:]):
        interval_ms = float(cur.get("interval_ms") or 0)
        if interval_ms <= 0:
            continue
        gap_ms = (float(cur.get("wall", 0)) - float(prev.get("wall", 0))) \
            * 1e3
        if gap_ms > blackout_factor * interval_ms and \
                (worst_gap is None or gap_ms > worst_gap):
            worst_gap = gap_ms
    if worst_gap is not None:
        gates.append(
            f"scrape blackout: a {worst_gap:,.0f} ms gap between "
            f"consecutive samples (> {blackout_factor:g}x the scrape "
            "interval) — the plane went dark while the fleet ran")
    for s in samples:
        replicas = (s.get("fleet") or {}).get("replicas")
        if replicas and len(s.get("children", [])) < int(replicas):
            gates.append(
                f"coverage hole at sample seq {s.get('seq')}: "
                f"{len(s.get('children', []))} child row(s) for "
                f"{replicas} replica slot(s) — the zero-gap contract "
                "(one row per slot per sample) is broken")
            break
    # Autoscale gates: only judge runs that actually carried autoscaler
    # data, so fixed-size fleets (and every pre-autoscaler record) keep
    # their existing verdicts bit-for-bit.
    scaled = _autoscale_samples(samples)
    timeline = replica_timeline(samples)
    changes = max(0, len(timeline) - 1)
    if changes > 0:
        final = (samples[-1].get("fleet") or {})
        outstanding = int(final.get("outstanding") or 0)
        parked = int(final.get("parked") or 0)
        if outstanding or parked:
            gates.append(
                f"scale-event loss: replica count changed {changes} "
                f"time(s) but the final sample still shows "
                f"{outstanding} outstanding + {parked} parked "
                "request(s) — a scale event stranded work")
    if scaled and changes > max_scale_changes:
        gates.append(
            f"autoscaler thrash: {changes} replica-count change(s) "
            f"(> {max_scale_changes}) — flapping instead of "
            "converging (timeline "
            f"{'->'.join(str(n) for n in timeline)})")
    worst_brownout = None
    for s in scaled:
        fleet = s.get("fleet") or {}
        if int((fleet.get("autoscale") or {}).get("rung") or 0) <= 0:
            continue
        p99 = fleet.get("latency_p99_ms")
        target = brownout_p99_ms
        if target is None:
            target = (((s.get("slo") or {}).get("objectives") or {})
                      .get("p99") or {}).get("target")
        if p99 is not None and target is not None \
                and float(p99) > float(target) \
                and (worst_brownout is None or float(p99) > worst_brownout):
            worst_brownout = float(p99)
    if worst_brownout is not None:
        gates.append(
            f"brownout p99 breach: fleet p99 reached "
            f"{worst_brownout:,.0f} ms while a brownout rung was "
            "engaged — shedding failed to protect admitted work")
    return gates


def check_journal(root: str) -> tuple:
    """Intake-journal coverage cross-check (ISSUE 20) — ``(rows,
    gates)``.  Active only when the run's ``supervisor_exit.json``
    carries a journal block, so journal-less runs keep their verdicts
    untouched.  The exit snapshot records the durable high-water mark
    (segment + offset); re-scanning the journal directory here proves
    no accepted id is missing from BOTH the journal's terminal records
    and a terminal response — accepted work can crash, but it cannot
    vanish."""
    try:
        with open(os.path.join(root, "supervisor_exit.json"),
                  encoding="utf-8") as f:
            jstats = (json.load(f) or {}).get("journal")
    except (OSError, ValueError):
        return [], []
    if not isinstance(jstats, dict):
        return [], []
    from cst_captioning_tpu.serving.journal import scan_dir

    jdir = jstats.get("dir") or os.path.join(root, "journal")
    try:
        rec = scan_dir(jdir)
    except OSError as e:
        return [], [f"journal dir unreadable: {jdir}: {e} — the exit "
                    "snapshot says a journal was armed but its segments "
                    "are gone (SERVING.md 'Durable intake journal')"]
    uncovered = sorted(set(rec.accepts) - set(rec.terminals))
    hw = jstats.get("high_water") or {}
    rows = [("journal",
             f"{len(rec.accepts)} accept(s) / {len(rec.terminals)} "
             f"terminal(s) over {rec.segments_scanned} segment(s), "
             f"{rec.torn_records} torn, high-water "
             f"{hw.get('segment')}@{fmt(hw.get('offset'))}")]
    gates = []
    if uncovered:
        gates.append(
            f"journal coverage hole: {len(uncovered)} accepted id(s) "
            "missing from BOTH the journal's terminal records and any "
            f"terminal response (e.g. {', '.join(uncovered[:3])}) — "
            "accepted work vanished across the run (SERVING.md "
            "'Durable intake journal')")
    seg = hw.get("segment")
    if seg:
        seg_path = os.path.join(jdir, seg)
        if not os.path.exists(seg_path):
            gates.append(
                f"journal high-water segment missing: {seg} named by "
                "the exit snapshot is not in the journal dir — "
                "durable bytes were lost after the fsync that "
                "acknowledged them (SERVING.md 'Durable intake "
                "journal')")
        elif os.path.getsize(seg_path) < int(hw.get("offset") or 0):
            gates.append(
                f"journal high-water truncated: {seg} is "
                f"{os.path.getsize(seg_path)} byte(s), shorter than "
                f"the exit snapshot's {hw.get('offset')} — the tail "
                "the supervisor fsync'd is gone (SERVING.md 'Durable "
                "intake journal')")
    return rows, gates


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    src = p.add_mutually_exclusive_group(required=True)
    src.add_argument("--dir", default=None,
                     help="the run's --supervise_dir (reads "
                          "fleet_metrics.jsonl + rotated parts + "
                          "slo_alerts.jsonl)")
    src.add_argument("--file", default=None,
                     help="one fleet_metrics.jsonl to read directly")
    p.add_argument("--blackout_factor", type=float, default=3.0,
                   help="scrape-gap gate threshold, in multiples of the "
                        "stamped scrape interval (default 3)")
    p.add_argument("--max_scale_changes", type=int, default=4,
                   help="autoscaler thrash gate: more replica-count "
                        "changes than this fails the report (default 4; "
                        "a clean burst drill is up+down = 2)")
    p.add_argument("--brownout_p99_ms", type=float, default=None,
                   help="brownout gate p99 ceiling in ms (default: the "
                        "run's own SLO p99 objective target)")
    p.add_argument("--json", default=None,
                   help="also write the summary as JSON here (atomic)")
    args = p.parse_args(argv)

    samples = load_samples(args)
    if not samples:
        print("fleet_report: no fleet_sample rows found — the scraper "
              "never wrote (or the path is wrong)", file=sys.stderr)
        return 1
    first, last = samples[0], samples[-1]
    fleet = last.get("fleet") or {}
    slo = last.get("slo") or {}
    span_s = float(last.get("wall", 0)) - float(first.get("wall", 0))
    rows = [
        ("samples", f"{len(samples)} over {fmt(span_s, ' s')} "
                    f"(interval {fmt(last.get('interval_ms'), ' ms')})"),
        ("fleet", f"{fmt(fleet.get('in_service'))}/"
                  f"{fmt(fleet.get('replicas'))} in service, "
                  f"{fmt(fleet.get('outstanding'))} outstanding, "
                  f"{fmt(fleet.get('parked'))} parked, "
                  f"{fmt(fleet.get('completed'))} completed"),
        ("fleet latency p50 / p99",
         f"{fmt(fleet.get('latency_p50_ms'), ' ms')} / "
         f"{fmt(fleet.get('latency_p99_ms'), ' ms')}"),
    ]
    timeline = replica_timeline(samples)
    if timeline:
        rows.append(
            ("replica timeline",
             f"{'->'.join(str(n) for n in timeline)} "
             f"({max(0, len(timeline) - 1)} change(s))"))
    autoscale = fleet.get("autoscale") or {}
    if autoscale.get("enabled"):
        rows.append(
            ("autoscale",
             f"bounds {fmt(autoscale.get('min'))}-"
             f"{fmt(autoscale.get('max'))}, "
             f"{fmt(autoscale.get('scale_ups'))} up / "
             f"{fmt(autoscale.get('scale_downs'))} down, "
             f"brownout rung {fmt(autoscale.get('rung'))} "
             f"(entered {fmt(autoscale.get('brownout_entries'))}x), "
             f"{fmt(autoscale.get('decisions'))} decision(s)"))
    if slo.get("enabled"):
        for name, obj in (slo.get("objectives") or {}).items():
            rows.append(
                (f"slo {name}",
                 f"target {obj.get('target')}, burn fast "
                 f"{fmt(obj.get('fast_burn'))} / slow "
                 f"{fmt(obj.get('slow_burn'))}"
                 + (" FIRING" if obj.get("firing") else "")))
        rows.append(("slo alerts",
                     f"{fmt(slo.get('alerts_fired'))} fired / "
                     f"{fmt(slo.get('alerts_cleared'))} cleared"))
    else:
        rows.append(("slo", "disabled (no --slo_* objective set)"))
    for idx, a in sorted(_per_child(samples).items()):
        c = a["last"] or {}
        occ = c.get("slot_occupancy")
        hit = c.get("cache_hit_rate")
        rows.append(
            (f"  child {idx}",
             f"{a['rows']} row(s), live {a['live']}/{a['rows']}, "
             f"{a['restarts']} restart(s); last: state {c.get('state')}, "
             f"queue {fmt(c.get('queue_depth'))}, p50/p99 "
             f"{fmt(c.get('latency_p50_ms'), ' ms')}/"
             f"{fmt(c.get('latency_p99_ms'), ' ms')}, occupancy "
             f"{'-' if occ is None else f'{occ * 100:.0f}%'}, cache hit "
             f"{'-' if hit is None else f'{hit * 100:.0f}%'}"))
    if args.dir:
        alerts_path = os.path.join(args.dir, "slo_alerts.jsonl")
        if os.path.exists(alerts_path):
            try:
                with open(alerts_path, "r", encoding="utf-8") as f:
                    n_alerts = sum(1 for line in f if line.strip())
                rows.append(("alert log", f"{n_alerts} transition(s) in "
                                          f"{alerts_path}"))
            except OSError:
                pass
    journal_rows, journal_gates = ([], []) if not args.dir \
        else check_journal(args.dir)
    rows += journal_rows
    width = max(len(k) for k, _ in rows)
    print("fleet metrics")
    for k, v in rows:
        print(f"  {k:<{width}}  {v}")

    gates = check_gates(samples, args.blackout_factor,
                        max_scale_changes=args.max_scale_changes,
                        brownout_p99_ms=args.brownout_p99_ms)
    gates += journal_gates
    for msg in gates:
        print(f"  !! {msg}", file=sys.stderr)
    if args.json:
        from cst_captioning_tpu.resilience.integrity import atomic_json_write

        atomic_json_write(args.json, {
            "samples": len(samples), "span_s": span_s,
            "fleet": fleet, "slo": slo,
            "replica_timeline": timeline, "gates": gates}, indent=2)
    return 1 if gates else 0


if __name__ == "__main__":
    sys.exit(main())
