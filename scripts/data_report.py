#!/usr/bin/env python
"""Summarize a data-plane feed-probe JSON line into a terminal table.

Reads the one-JSON-line artifact ``bench.py --stage data`` prints (from
stdin, a file, or the newest BENCH_TPU_CACHE entry) and renders the
input-path picture a human wants at a glance:

  python bench.py --stage data | python scripts/data_report.py
  python scripts/data_report.py --file data.json
  python scripts/data_report.py --cache        # last cached device run

Exit 1 (the taxonomy's EXIT_FAILURE) when:
- no data-feed record could be found/parsed, or the probe measured
  nothing (value null) — a silent report would hide a broken probe;
- the record carries a single-worker twin, ran >= 4 workers, and the
  multi-worker feed rate did not sustain >= MIN_SPEEDUP_AT_4 x the twin
  — the multi-worker data plane's acceptance gate (ISSUE 15).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from cst_captioning_tpu.resilience.exitcodes import (  # noqa: E402
    EXIT_FAILURE,
    EXIT_OK,
)

DATA_METRIC = "data_feed_captions_per_sec"

#: The acceptance gate: at >= 4 workers the probe must sustain at least
#: this multiple of its single-worker twin's feed rate.
MIN_SPEEDUP_AT_4 = 2.0


def find_record(args) -> dict | None:
    """First parseable data-feed JSON line from the chosen source."""
    if args.cache:
        try:
            with open(os.path.join(REPO, "BENCH_TPU_CACHE.json")) as f:
                entry = json.load(f)["entries"].get(DATA_METRIC)
            return entry and entry.get("result")
        except (OSError, ValueError, KeyError):
            return None
    lines = open(args.file) if args.file else sys.stdin
    try:
        for line in lines:
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and rec.get("metric") == DATA_METRIC:
                return rec
    finally:
        if args.file:
            lines.close()
    return None


def fmt(v, unit: str = "") -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:,.2f}{unit}"
    return f"{v}{unit}"


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--file", default=None,
                   help="read the bench JSON line from this file "
                        "(default: stdin)")
    p.add_argument("--cache", action="store_true",
                   help="render the last cached device record instead")
    args = p.parse_args(argv)

    rec = find_record(args)
    if rec is None:
        print("data_report: no data-feed record found "
              f"(metric {DATA_METRIC!r})", file=sys.stderr)
        return EXIT_FAILURE
    if rec.get("value") is None:
        print("data_report: record carries no measurement (value=null; "
              f"error={rec.get('error')!r})", file=sys.stderr)
        return EXIT_FAILURE

    rows = [
        ("feed rate", fmt(rec.get("value"), " caps/s")),
        ("batches/s", fmt(rec.get("batches_per_sec"))),
        ("vs 30k caps/s XE rate", fmt(rec.get("vs_xe_rate"), "x")),
        ("loader workers", fmt(rec.get("loader_workers"))),
        ("data shards", f"{fmt(rec.get('data_shard_id'))} of "
                        f"{fmt(rec.get('data_shards'))}"
         if rec.get("data_shards") else "unsharded"),
        ("simulated read latency", fmt(rec.get("read_ms"), " ms/batch")),
        ("data_wait share @ paced consumer",
         fmt(rec.get("data_wait_share"))),
        ("data_wait p99", fmt(rec.get("data_wait_ms_p99"), " ms")),
        ("queue depth (mean/cap)",
         f"{fmt(rec.get('queue_depth_mean'))} / "
         f"{fmt(rec.get('queue_capacity'))}"),
        ("retries", fmt(rec.get("retries"))),
        ("platform", f"{rec.get('platform')}"
         + (" (cpu fallback)" if rec.get("cpu_fallback") else "")),
    ]
    twin = rec.get("single_worker_captions_per_sec")
    if twin is not None:
        rows.insert(2, ("single-worker twin", fmt(twin, " caps/s")))
        rows.insert(3, ("multi-worker speedup",
                        fmt(rec.get("workers_speedup"), "x")))
    width = max(len(r[0]) for r in rows)
    print("data-plane feed probe")
    for k, v in rows:
        print(f"  {k:<{width}}  {v}")

    rc = EXIT_OK
    workers = int(rec.get("loader_workers") or 1)
    speedup = rec.get("workers_speedup")
    if twin is not None and workers >= 4:
        if speedup is None or speedup < MIN_SPEEDUP_AT_4:
            print(f"data_report: GATE FAILED — {workers} workers "
                  f"sustained {fmt(speedup, 'x')} of the single-worker "
                  f"feed rate (need >= {MIN_SPEEDUP_AT_4}x); the "
                  "multi-worker data plane is not paying",
                  file=sys.stderr)
            rc = EXIT_FAILURE
        else:
            print(f"  gate: {workers} workers >= {MIN_SPEEDUP_AT_4}x "
                  "single-worker feed rate — ok")
    return rc


if __name__ == "__main__":
    sys.exit(main())
