#!/usr/bin/env python
"""Summarize a ``--trace_dir`` of Chrome-trace JSON into terminal tables.

The span tracer (cst_captioning_tpu/telemetry/spans.py) writes
``trace_*.json`` files; this reads every one in the directory and prints
where the host wall-time went:

- complete ("ph": "X") duration spans, aggregated by name — count,
  total, mean, p50/p95/max, share of the traced wall span;
- instant ("ph": "i") marker events — count per name (fault firings,
  one-shot markers);
- async-track events ("ph": "b"/"n"/"e", the request-lifecycle tracer's
  Perfetto mirror) — per-track durations matched b->e on (pid, cat, id,
  name), aggregated by name, plus the per-event step counts.  This is
  the terminal view of a request's journey; the same files load
  graphically in Perfetto (https://ui.perfetto.dev) or chrome://tracing.

Merged fleet traces (``scripts/fleet_trace.py`` output, marked
``otherData.merged``) render too: async tracks are then paired WITHOUT
the pid — a stitched request's ``b``/``e`` span different processes by
design — nesting counted, and a per-process row table (supervisor +
each replica, event counts, clock-skew annotations) is added.
Single-process records keep the exact legacy rendering.

Usage:
  python scripts/trace_report.py --trace_dir /tmp/run/trace [--json out.json]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

from cst_captioning_tpu.resilience.integrity import (  # noqa: E402
    atomic_json_write,
)


def load_events(trace_dir: str):
    """Every span/instant/async event from every trace_*.json part file
    -> (complete_spans, instants, async_events, files, meta).

    ``meta`` describes the trace's shape: ``merged`` (True when any
    file is a fleet_trace.py stitch, i.e. ``otherData.merged``) and
    ``processes`` — pid -> {"name", "events"} from the Chrome
    ``process_name`` metadata plus per-pid event counts.
    """
    spans, instants, asyncs = [], [], []
    meta = {"merged": False, "processes": {}}
    files = sorted(glob.glob(os.path.join(trace_dir, "*.json")))
    for path in files:
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            print(f"trace_report: skipping unreadable {path}: {e}",
                  file=sys.stderr)
            continue
        other = doc.get("otherData") if isinstance(doc, dict) else None
        if isinstance(other, dict) and other.get("merged"):
            meta["merged"] = True
        for ev in doc.get("traceEvents", doc if isinstance(doc, list) else []):
            ph = ev.get("ph")
            pid = ev.get("pid")
            if ph == "M":
                if ev.get("name") == "process_name":
                    proc = meta["processes"].setdefault(
                        pid, {"name": None, "events": 0})
                    proc["name"] = (ev.get("args") or {}).get("name")
                continue
            if pid is not None:
                meta["processes"].setdefault(
                    pid, {"name": None, "events": 0})["events"] += 1
            if ph == "X" and "dur" in ev:
                spans.append(ev)
            elif ph == "i":
                instants.append(ev)
            elif ph in ("b", "n", "e"):
                asyncs.append(ev)
    return spans, instants, asyncs, files, meta


def percentile(sorted_vals, q: float) -> float:
    if not sorted_vals:
        return 0.0
    ix = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[ix]


def _dur_rows(by_name, wall_ms: float):
    """name -> [durations ms] into the shared span-table row shape."""
    rows = []
    for name, durs in by_name.items():
        durs.sort()
        total = sum(durs)
        rows.append({
            "span": name,
            "count": len(durs),
            "total_ms": round(total, 3),
            "mean_ms": round(total / len(durs), 3),
            "p50_ms": round(percentile(durs, 0.50), 3),
            "p95_ms": round(percentile(durs, 0.95), 3),
            "max_ms": round(durs[-1], 3),
            "pct_of_wall": round(100.0 * total / wall_ms, 1) if wall_ms
                           else 0.0,
        })
    rows.sort(key=lambda r: -r["total_ms"])
    return rows


def traced_wall_ms(*event_lists) -> float:
    """Wall span (ms) over EVERY timestamped event — duration spans,
    instants, and async steps together, so the pct_of_wall columns of
    both tables share one honest denominator."""
    t_lo, t_hi = None, None
    for events in event_lists:
        for ev in events:
            ts = ev.get("ts")
            if ts is None:
                continue
            end = ts + ev.get("dur", 0.0)
            t_lo = ts if t_lo is None else min(t_lo, ts)
            t_hi = end if t_hi is None else max(t_hi, end)
    return 0.0 if t_lo is None else (t_hi - t_lo) / 1e3


def summarize(events, wall_ms=None):
    """-> (rows sorted by total desc, wall_ms).  Durations in ms."""
    by_name = {}
    for ev in events:
        by_name.setdefault(ev["name"], []).append(ev["dur"] / 1e3)
    if wall_ms is None:
        wall_ms = traced_wall_ms(events)
    return _dur_rows(by_name, wall_ms), wall_ms


def summarize_instants(instants):
    """Instant markers -> [{"name", "count"}] sorted by count desc."""
    counts = {}
    for ev in instants:
        counts[ev["name"]] = counts.get(ev["name"], 0) + 1
    return [{"name": n, "count": c}
            for n, c in sorted(counts.items(), key=lambda kv: -kv[1])]


def summarize_async(asyncs, wall_ms: float, merged: bool = False):
    """Async-track events -> (track_rows, step_counts, open_tracks).

    Tracks are matched ``b`` -> ``e`` on (pid, cat, id, name) — the
    Chrome pairing rule — and their durations aggregate by name in the
    same row shape as the span table.  ``n`` step events count per name
    (the lifecycle event mix).  Tracks begun but never ended (requests
    in flight when the trace closed) are reported, not dropped.

    With ``merged=True`` (a fleet_trace.py stitch) the pid leaves the
    key — a stitched request's events span processes by design — and
    nested ``b``/``e`` pairs on one id (supervisor span enclosing the
    child span) are depth-counted: the track's duration is the OUTER
    span, first ``b`` to the matching last ``e``, i.e. the request's
    full cross-process journey.
    """
    open_at = {}
    by_name = {}
    steps = {}
    unmatched_end = 0
    for ev in sorted(asyncs, key=lambda e: e.get("ts", 0.0)):
        key = ((ev.get("cat"), ev.get("id"), ev["name"]) if merged
               else (ev.get("pid"), ev.get("cat"), ev.get("id"),
                     ev["name"]))
        ph = ev["ph"]
        if ph == "b":
            if merged:
                t0, depth = open_at.get(key, (ev["ts"], 0))
                open_at[key] = (t0, depth + 1)
            else:
                open_at[key] = ev["ts"]
        elif ph == "e":
            rec = open_at.pop(key, None)
            if rec is None:
                unmatched_end += 1
                continue
            if merged:
                t0, depth = rec
                if depth > 1:
                    open_at[key] = (t0, depth - 1)
                    continue
            else:
                t0 = rec
            by_name.setdefault(ev["name"], []).append(
                (ev["ts"] - t0) / 1e3)
        else:  # "n": an instant step on the track
            steps[ev["name"]] = steps.get(ev["name"], 0) + 1
    rows = _dur_rows(by_name, wall_ms)
    step_rows = [{"name": n, "count": c}
                 for n, c in sorted(steps.items(), key=lambda kv: -kv[1])]
    return rows, step_rows, {"open_tracks": len(open_at),
                             "unmatched_end": unmatched_end}


def summarize_processes(meta, instants):
    """Merged-trace process rows: one per pid with its Perfetto row
    label, event count, and the clock-skew annotation fleet_trace.py
    stamped (None for the supervisor row)."""
    skews = {}
    for ev in instants:
        if ev.get("name") == "clock_skew":
            args = ev.get("args") or {}
            skews[ev.get("pid")] = args
    rows = []
    for pid, proc in sorted(meta["processes"].items(),
                            key=lambda kv: str(kv[0])):
        sk = skews.get(pid)
        rows.append({
            "pid": pid,
            "name": proc["name"] or f"pid {pid}",
            "events": proc["events"],
            "skew_ms": sk.get("skew_ms") if sk else None,
            "uncertainty_ms": sk.get("uncertainty_ms") if sk else None,
        })
    return rows


def print_table(rows, title: str) -> None:
    cols = ("span", "count", "total_ms", "mean_ms", "p50_ms", "p95_ms",
            "max_ms", "pct_of_wall")
    widths = {c: max(len(c), *(len(str(r[c])) for r in rows)) if rows
              else len(c) for c in cols}
    print(title)
    print("  ".join(c.ljust(widths[c]) for c in cols))
    print("  ".join("-" * widths[c] for c in cols))
    for r in rows:
        print("  ".join(str(r[c]).ljust(widths[c]) for c in cols))


def print_counts(rows, title: str) -> None:
    width = max(len(r["name"]) for r in rows)
    print(title)
    for r in rows:
        print(f"  {r['name']:<{width}}  {r['count']}")


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--trace_dir", required=True,
                    help="directory a --trace_dir run wrote trace_*.json to")
    ap.add_argument("--json", default=None,
                    help="also write the summary rows as JSON here")
    args = ap.parse_args()

    spans, instants, asyncs, files, meta = load_events(args.trace_dir)
    if not files:
        print(f"trace_report: no trace files under {args.trace_dir}",
              file=sys.stderr)
        return 1
    wall_ms = traced_wall_ms(spans, instants, asyncs)
    rows, _ = summarize(spans, wall_ms)
    print_table(rows, f"trace summary: {len(files)} file(s), traced wall "
                      f"{wall_ms:.1f} ms"
                      + (" [merged fleet trace]" if meta["merged"] else ""))
    if rows:
        print("\nnote: nested spans overlap (e.g. host-path `score` runs "
              "inside `compute`), so pct_of_wall columns need not sum "
              "to 100.")
    proc_rows = []
    if meta["merged"]:
        proc_rows = summarize_processes(meta, instants)
        print()
        print("process rows (merged fleet trace)")
        for r in proc_rows:
            skew = ("-" if r["skew_ms"] is None
                    else f"{r['skew_ms']:+.3f} ms "
                         f"(±{r['uncertainty_ms']} ms)")
            print(f"  {r['name']:<28}  {r['events']} event(s), "
                  f"clock skew {skew}")
    async_rows, step_rows, async_meta = summarize_async(
        asyncs, wall_ms, merged=meta["merged"])
    if async_rows or step_rows:
        print()
        print_table(async_rows,
                    "async tracks (request lifecycle; b->e durations"
                    + (", stitched across processes)" if meta["merged"]
                       else ")"))
        if async_meta["open_tracks"]:
            print(f"  ({async_meta['open_tracks']} track(s) still open — "
                  "in flight when the trace closed)")
        if step_rows:
            print()
            print_counts(step_rows, "lifecycle steps (async 'n' events)")
    if instants:
        print()
        print_counts(summarize_instants(instants),
                     "instant markers ('i' events)")
    if args.json:
        atomic_json_write(args.json,
                          {"wall_ms": wall_ms, "files": files,
                           "merged": meta["merged"],
                           "processes": proc_rows,
                           "spans": rows,
                           "instants": summarize_instants(instants),
                           "async_tracks": async_rows,
                           "async_steps": step_rows,
                           "async_meta": async_meta}, indent=2)
        print(f"\nwrote {args.json}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
