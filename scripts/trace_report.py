#!/usr/bin/env python
"""Summarize a ``--trace_dir`` of Chrome-trace JSON into a per-phase table.

The trainer's span tracer (cst_captioning_tpu/telemetry/spans.py) writes
``trace_*.json`` files; this reads every one in the directory, aggregates
the complete ("ph": "X") events by span name, and prints where the host
wall-time went — count, total, mean, p50/p95/max, and share of the traced
wall span.  The same files load graphically in Perfetto
(https://ui.perfetto.dev) or chrome://tracing; this is the terminal view.

Usage:
  python scripts/trace_report.py --trace_dir /tmp/run/trace [--json out.json]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

from cst_captioning_tpu.resilience.integrity import (  # noqa: E402
    atomic_json_write,
)


def load_events(trace_dir: str):
    """Every complete span event from every trace_*.json part file."""
    events = []
    files = sorted(glob.glob(os.path.join(trace_dir, "*.json")))
    for path in files:
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            print(f"trace_report: skipping unreadable {path}: {e}",
                  file=sys.stderr)
            continue
        for ev in doc.get("traceEvents", doc if isinstance(doc, list) else []):
            if ev.get("ph") == "X" and "dur" in ev:
                events.append(ev)
    return events, files


def percentile(sorted_vals, q: float) -> float:
    if not sorted_vals:
        return 0.0
    ix = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[ix]


def summarize(events):
    """-> (rows sorted by total desc, wall_ms).  Durations in ms."""
    by_name = {}
    t_lo, t_hi = None, None
    for ev in events:
        by_name.setdefault(ev["name"], []).append(ev["dur"] / 1e3)
        ts, end = ev["ts"], ev["ts"] + ev["dur"]
        t_lo = ts if t_lo is None else min(t_lo, ts)
        t_hi = end if t_hi is None else max(t_hi, end)
    wall_ms = 0.0 if t_lo is None else (t_hi - t_lo) / 1e3
    rows = []
    for name, durs in by_name.items():
        durs.sort()
        total = sum(durs)
        rows.append({
            "span": name,
            "count": len(durs),
            "total_ms": round(total, 3),
            "mean_ms": round(total / len(durs), 3),
            "p50_ms": round(percentile(durs, 0.50), 3),
            "p95_ms": round(percentile(durs, 0.95), 3),
            "max_ms": round(durs[-1], 3),
            "pct_of_wall": round(100.0 * total / wall_ms, 1) if wall_ms
                           else 0.0,
        })
    rows.sort(key=lambda r: -r["total_ms"])
    return rows, wall_ms


def print_table(rows, wall_ms: float, nfiles: int) -> None:
    cols = ("span", "count", "total_ms", "mean_ms", "p50_ms", "p95_ms",
            "max_ms", "pct_of_wall")
    widths = {c: max(len(c), *(len(str(r[c])) for r in rows)) if rows
              else len(c) for c in cols}
    print(f"trace summary: {nfiles} file(s), traced wall {wall_ms:.1f} ms")
    print("  ".join(c.ljust(widths[c]) for c in cols))
    print("  ".join("-" * widths[c] for c in cols))
    for r in rows:
        print("  ".join(str(r[c]).ljust(widths[c]) for c in cols))
    if rows:
        print("\nnote: nested spans overlap (e.g. host-path `score` runs "
              "inside `compute`), so pct_of_wall columns need not sum "
              "to 100.")


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--trace_dir", required=True,
                    help="directory a --trace_dir run wrote trace_*.json to")
    ap.add_argument("--json", default=None,
                    help="also write the summary rows as JSON here")
    args = ap.parse_args()

    events, files = load_events(args.trace_dir)
    if not files:
        print(f"trace_report: no trace files under {args.trace_dir}",
              file=sys.stderr)
        return 1
    rows, wall_ms = summarize(events)
    print_table(rows, wall_ms, len(files))
    if args.json:
        atomic_json_write(args.json,
                          {"wall_ms": wall_ms, "files": files,
                           "spans": rows}, indent=2)
        print(f"\nwrote {args.json}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
