#!/usr/bin/env python
"""Summarize a serving-probe JSON line into a terminal latency table.

Reads the one-JSON-line artifact `bench.py --stage serving` prints (from
stdin, a file, or the newest BENCH_TPU_CACHE entry) and renders the
latency/throughput picture a human wants at a glance:

  python bench.py --stage serving | python scripts/serve_report.py
  python scripts/serve_report.py --file serving.json
  python scripts/serve_report.py --cache          # last cached device run

Exit 1 when no serving record could be found/parsed (a report that
silently prints nothing would hide a broken probe).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SERVE_METRIC = "serve_captions_per_sec_per_chip"


def find_record(args) -> dict | None:
    """First parseable serving JSON line from the chosen source."""
    if args.cache:
        try:
            with open(os.path.join(REPO, "BENCH_TPU_CACHE.json")) as f:
                entry = json.load(f)["entries"].get(SERVE_METRIC)
            return entry and entry.get("result")
        except (OSError, ValueError, KeyError):
            return None
    lines = open(args.file) if args.file else sys.stdin
    try:
        for line in lines:
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and rec.get("metric") == SERVE_METRIC:
                return rec
    finally:
        if args.file:
            lines.close()
    return None


def fmt(v, unit="") -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:,.2f}{unit}"
    return f"{v}{unit}"


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--file", default=None,
                   help="read the JSON line from this file (default: stdin)")
    p.add_argument("--cache", action="store_true",
                   help="read the last cached device serving entry instead")
    args = p.parse_args(argv)
    rec = find_record(args)
    if not rec:
        print("serve_report: no serving-probe JSON line found "
              f"(metric {SERVE_METRIC!r}); run "
              "`python bench.py --stage serving`", file=sys.stderr)
        return 1
    fleet = rec.get("fleet") or {}
    rows = [
        ("captions/s" + ("/fleet" if fleet.get("enabled") else ""),
         fmt(rec.get("value"))),
        ("latency p50", fmt(rec.get("latency_p50_ms"), " ms")),
        ("latency p99", fmt(rec.get("latency_p99_ms"), " ms")),
        ("latency mean", fmt(rec.get("latency_mean_ms"), " ms")),
        ("requests", f"{fmt(rec.get('completed'))} completed / "
                     f"{fmt(rec.get('num_requests'))} offered "
                     f"({fmt(rec.get('shed'))} shed)"),
        ("arrival rate", fmt(rec.get("rate_hz"), " req/s (Poisson, seed "
                             f"{rec.get('arrival_seed')})")),
        ("makespan", fmt(rec.get("makespan_s"), " s")),
        ("buckets", f"{rec.get('buckets')} -> ran at "
                    f"{fmt(rec.get('slots'))} slots"),
        ("beam / chunk", f"{fmt(rec.get('beam_size'))} / "
                         f"{fmt(rec.get('decode_chunk'))}"),
    ]
    stream = rec.get("stream") or {}
    if stream.get("enabled"):
        rows += [
            ("ttft p50 / p99", f"{fmt(stream.get('ttft_p50_ms'), ' ms')} / "
                               f"{fmt(stream.get('ttft_p99_ms'), ' ms')}"),
            ("inter-chunk gap p50 / p99",
             f"{fmt(stream.get('chunk_gap_p50_ms'), ' ms')} / "
             f"{fmt(stream.get('chunk_gap_p99_ms'), ' ms')}"),
            ("stream chunks", f"{fmt(stream.get('chunks'))} "
                              f"(prefix_ok={stream.get('prefix_ok')})"),
        ]
    cache = rec.get("cache") or {}
    if cache.get("enabled"):
        hit_rate = cache.get("hit_rate")
        rows += [
            ("cache hit rate",
             ("-" if hit_rate is None else f"{hit_rate * 100:.1f}%")
             + f" ({fmt(cache.get('hits'))} hits / "
               f"{fmt(cache.get('misses'))} misses, "
               f"{fmt(cache.get('evictions'))} evicted, "
               f"{fmt(cache.get('bypass'))} bypassed, "
               f"{fmt(cache.get('errors'))} errors)"),
            ("cache drill", f"parity_ok={cache.get('parity_ok')} "
                            f"({fmt(cache.get('parity_mismatches'))} "
                            "hit/miss-twin mismatches)"),
        ]
        if rec.get("cache_off_captions_per_sec") is not None:
            rows.append(
                ("cache-off twin", f"{fmt(rec.get('cache_off_captions_per_sec'))}"
                                   " caps/s (speedup "
                                   f"{fmt(rec.get('cache_speedup'))}x)"))
    if fleet.get("enabled"):
        killed = fleet.get("killed_replica")
        rows += [
            ("fleet", f"{fmt(fleet.get('replicas'))} replicas — routed "
                      f"{fmt(fleet.get('fleet_routed'))} "
                      f"(rerouted {fmt(fleet.get('fleet_rerouted'))}, "
                      f"fleet-shed {fmt(fleet.get('fleet_shed'))})"),
            ("fleet lifecycle",
             f"{fmt(fleet.get('fleet_replica_restarts'))} restarts / "
             f"{fmt(fleet.get('fleet_replica_kills'))} kills"
             + (f" (drill killed replica {killed})"
                if killed is not None else "")),
            ("fleet parity", f"parity_ok={fleet.get('parity_ok')} "
                             f"({fmt(fleet.get('parity_mismatches'))} "
                             "caption(s) != the single-engine run)"),
        ]
        for pr in fleet.get("per_replica") or []:
            rows.append(
                (f"  replica {pr.get('replica')}",
                 f"{fmt(pr.get('completed'))} completed, "
                 f"status {pr.get('status')}, "
                 f"{fmt(pr.get('restarts'))} restart(s) / "
                 f"{fmt(pr.get('kills'))} kill(s)"))
    sup = rec.get("supervisor") or {}
    if sup.get("enabled"):
        sup_killed = sup.get("killed_replica")
        rows += [
            ("process fleet",
             f"{fmt(sup.get('replicas'))} child process(es) — "
             f"{fmt(sup.get('restarts'))} restart(s), "
             f"{fmt(sup.get('requeued'))} requeued, "
             f"{fmt(sup.get('deaths'))} dead "
             f"(fatal budget {fmt(sup.get('restart_limit'))}, "
             f"budget_ok={sup.get('budget_ok')})"),
            ("process incidents",
             f"{fmt(sup.get('incidents'))} harvested, "
             f"blackbox_harvested={sup.get('blackbox_harvested')}"
             + (f" (drill killed replica {sup_killed})"
                if sup_killed is not None else "")),
            ("process parity",
             f"parity_ok={sup.get('parity_ok')} "
             f"({fmt(sup.get('parity_mismatches'))} caption(s) != the "
             "single-engine reference)"),
        ]
        for pr in sup.get("per_replica") or []:
            rows.append(
                (f"  child {pr.get('replica')}",
                 f"{fmt(pr.get('completed'))} completed, "
                 f"state {pr.get('state')}, "
                 f"{fmt(pr.get('restarts'))} restart(s) / "
                 f"{fmt(pr.get('kills'))} kill(s), "
                 f"last_rc {fmt(pr.get('last_rc'))}"))
    autoscale = rec.get("autoscale") or {}
    if autoscale.get("enabled"):
        rows += [
            ("autoscale",
             f"bounds {fmt(autoscale.get('min'))}-"
             f"{fmt(autoscale.get('max'))} — "
             f"{fmt(autoscale.get('scale_ups'))} up / "
             f"{fmt(autoscale.get('scale_downs'))} down "
             f"({fmt(autoscale.get('replica_changes'))} change(s), "
             f"{fmt(autoscale.get('decisions'))} decision(s), "
             f"no_thrash={autoscale.get('no_thrash')})"),
            ("autoscale drill",
             f"started_at_min={autoscale.get('started_at_min')}, "
             f"scaled_up={autoscale.get('scaled_up')} (in "
             f"{fmt(autoscale.get('scale_up_intervals'))} of "
             f"{fmt(autoscale.get('scale_up_budget_intervals'))} scrape "
             "interval(s)), "
             f"scaled_down={autoscale.get('scaled_down')}, "
             f"answered_ok={autoscale.get('answered_ok')}"),
            ("brownout",
             f"rung {fmt(autoscale.get('rung'))} at probe end, "
             f"{fmt(autoscale.get('brownout_entries'))} entr(ies)"),
        ]
    journal = rec.get("journal") or {}
    if journal.get("enabled"):
        hw = journal.get("high_water") or {}
        rows += [
            ("journal drill",
             f"killed_mid_storm={journal.get('killed_mid_storm')} "
             f"({fmt(journal.get('terminals_before_kill'))} terminal(s), "
             f"{fmt(journal.get('streams_in_flight_at_kill'))} stream(s) "
             "in flight at SIGKILL)"),
            ("journal replay",
             f"{fmt(journal.get('replayed'))} replayed + "
             f"{fmt(journal.get('recovered_terminals'))} already "
             f"terminal (accounted={journal.get('replay_accounted')}, "
             f"{fmt(journal.get('segments_scanned'))} segment(s), "
             f"high-water {hw.get('segment')}@{fmt(hw.get('offset'))})"),
            ("journal exactly-once",
             f"exactly_once={journal.get('exactly_once')} — "
             f"{fmt(journal.get('idempotent_answers'))} idempotent "
             f"answer(s), {fmt(journal.get('dup_hits'))} dup hit(s), "
             f"{fmt(journal.get('attached'))} attach(es), "
             f"dup_suppressed={journal.get('dup_suppressed')}"),
            ("journal torn tail",
             f"{fmt(journal.get('torn_records'))} torn record(s) "
             f"(torn_ok={journal.get('torn_ok')}), open_at_exit="
             f"{fmt(journal.get('open_at_exit'))}, relaunch rc "
             f"{fmt(journal.get('relaunch_rc'))} "
             f"(clean_exit={journal.get('clean_exit')})"),
        ]
    slo = rec.get("slo") or {}
    if slo.get("enabled"):
        firing = slo.get("firing") or []
        rows.append(
            ("slo", f"ok={slo.get('ok')} — firing "
                    f"{','.join(firing) if firing else 'none'}, "
                    f"{fmt(slo.get('alerts_fired'))} fired / "
                    f"{fmt(slo.get('alerts_cleared'))} cleared"))
    attribution = rec.get("attribution") or {}
    lifecycle = rec.get("lifecycle") or {}
    if attribution:
        comps = attribution.get("components") or {}
        for name in ("queue_wait", "admit", "decode", "recovery",
                     "requeue"):
            c = comps.get(name) or {}
            rows.append(
                (f"attr {name} p50 / p99",
                 f"{fmt(c.get('p50_ms'), ' ms')} / "
                 f"{fmt(c.get('p99_ms'), ' ms')}"))
        rows.append(
            ("attr reconcile",
             f"ok={attribution.get('reconcile_ok')} over "
             f"{fmt(attribution.get('reconciled'))} request(s), max "
             f"residual {fmt(attribution.get('max_residual_ms'), ' ms')} "
             f"(tol {fmt(attribution.get('tolerance_ms'), ' ms')})"))
        for rep_ix, comp in (attribution.get("per_replica") or {}).items():
            dec = comp.get("decode") or {}
            qw = comp.get("queue_wait") or {}
            rq = comp.get("requeue") or {}
            rows.append(
                (f"  replica {rep_ix} attr",
                 f"queue {fmt(qw.get('p50_ms'), ' ms')} / decode "
                 f"{fmt(dec.get('p50_ms'), ' ms')} / requeue "
                 f"{fmt(rq.get('p50_ms'), ' ms')} (p50)"))
    if lifecycle.get("enabled"):
        rows.append(
            ("lifecycle accounting",
             f"terminal_ok={lifecycle.get('terminal_ok')} — "
             f"{fmt(lifecycle.get('submitted'))} submitted, "
             f"{fmt(lifecycle.get('unterminated'))} unterminated, "
             f"{fmt(lifecycle.get('multi_terminal'))} multi-terminal "
             f"({fmt(lifecycle.get('events'))} events, "
             f"{fmt(lifecycle.get('retained'))} retained)"))
        if lifecycle.get("blackbox"):
            rows.append(("blackbox", str(lifecycle["blackbox"])))
    rows += [
        ("recompiles after warmup", fmt(rec.get("recompiles_after_warmup"))),
        ("expired / deadline-shed", f"{fmt(rec.get('expired'))} / "
                                    f"{fmt(rec.get('deadline_shed'))}"),
        ("recovery", f"{fmt(rec.get('chunk_retries'))} chunk retries, "
                     f"{fmt(rec.get('rebuilds'))} rebuilds "
                     f"({fmt(rec.get('rebuild_recompiles'))} recompiled), "
                     f"{fmt(rec.get('garble_detected'))} garbles / "
                     f"{fmt(rec.get('wedge_detected'))} wedges / "
                     f"{fmt(rec.get('admit_errors'))} admit errors seen"),
        ("platform", f"{rec.get('platform')}"
                     + (" (CPU FALLBACK — not a device number)"
                        if rec.get("cpu_fallback") else "")),
    ]
    width = max(len(k) for k, _ in rows)
    print("serving probe" + (f" [{rec.get('metric')}]" if rec.get("metric")
                             else ""))
    for k, v in rows:
        print(f"  {k:<{width}}  {v}")
    rc = 0
    recomp = rec.get("recompiles_after_warmup")
    if recomp not in (0, None):
        print("  !! recompiles under steady load: the bucket discipline "
              "is broken (SERVING.md)", file=sys.stderr)
        rc = 1
    if rec.get("rebuild_recompiles") not in (0, None):
        print("  !! an engine rebuild compiled new programs: recovery "
              "must re-warm from the existing ProgramCache "
              "(RESILIENCE.md 'Serving faults')", file=sys.stderr)
        rc = 1
    if cache.get("enabled") and cache.get("parity_ok") is False:
        print("  !! cache-hit caption(s) not bit-identical to their miss "
              "twin in the drill record: the exact-result cache is "
              "replaying wrong captions (SERVING.md 'Streaming & result "
              "cache')", file=sys.stderr)
        rc = 1
    twin_cps = rec.get("cache_off_captions_per_sec")
    if cache.get("enabled") and twin_cps is not None \
            and rec.get("value") is not None \
            and rec["value"] <= twin_cps:
        print("  !! the cached probe did not beat its cache-off twin "
              f"({rec['value']} <= {twin_cps} caps/s): the result cache "
              "is not paying on this run", file=sys.stderr)
        rc = 1
    if fleet.get("enabled") and fleet.get("parity_ok") is False:
        print("  !! fleet caption(s) not bit-identical to the fault-free "
              "single-engine reference run: the fleet bit-identity "
              "contract is broken (SERVING.md 'Fleet')", file=sys.stderr)
        rc = 1
    if sup.get("enabled") and sup.get("parity_ok") is False:
        print("  !! process-fleet caption(s) not bit-identical to the "
              "fault-free single-engine reference run: crash-proof "
              "requeue re-decoded something differently (SERVING.md "
              "'Process fleet')", file=sys.stderr)
        rc = 1
    if sup.get("enabled") and sup.get("budget_ok") is False:
        print("  !! a supervised replica exhausted its fatal-exit "
              "restart budget during the drill: the process fleet is "
              "losing capacity it should have kept (SERVING.md "
              "'Process fleet')", file=sys.stderr)
        rc = 1
    if autoscale.get("enabled"):
        if autoscale.get("started_at_min") is False:
            print("  !! the autoscaled fleet did not start at "
                  "--autoscale_min replicas: the probe began over- or "
                  "under-provisioned (SERVING.md 'Autoscaling & "
                  "brownout')", file=sys.stderr)
            rc = 1
        if autoscale.get("scaled_up") is False:
            print("  !! the burst never triggered a scale-up within the "
                  "scrape-interval budget: the attribution signal path "
                  "(queue_wait p99 rising, decode p99 flat) is broken "
                  "(SERVING.md 'Autoscaling & brownout')", file=sys.stderr)
            rc = 1
        if autoscale.get("scaled_down") is False:
            print("  !! the fleet never drained back to --autoscale_min "
                  "after the burst: scale-down (quiet slow window + "
                  "drain-based retire) is broken (SERVING.md "
                  "'Autoscaling & brownout')", file=sys.stderr)
            rc = 1
        if autoscale.get("no_thrash") is False:
            print("  !! the autoscaler flapped: more replica-count "
                  "changes than a clean burst drill warrants — "
                  "hysteresis/cooldowns are not holding (SERVING.md "
                  "'Autoscaling & brownout')", file=sys.stderr)
            rc = 1
        if autoscale.get("answered_ok") is False:
            print("  !! request(s) lost or double-answered across scale "
                  "events: the drain/requeue discipline dropped work "
                  "(SERVING.md 'Autoscaling & brownout')", file=sys.stderr)
            rc = 1
    if journal.get("enabled") and (
            journal.get("replay_accounted") is False
            or journal.get("exactly_once") is False
            or journal.get("clean_exit") is False):
        print("  !! journal replay accounting broken: replayed + "
              "recovered-terminal must cover every accepted id exactly "
              "once and the relaunched supervisor must drain clean — "
              "the write-ahead intake journal lost or double-served "
              "work across the supervisor death (SERVING.md 'Durable "
              "intake journal')", file=sys.stderr)
        rc = 1
    if journal.get("enabled") and journal.get("dup_suppressed") is False:
        print("  !! duplicate-id suppression broken: a resubmit of an "
              "already-terminal idempotency key must be answered from "
              "the journaled terminal (idempotent: true, zero decode, "
              "sup_requests untouched) (SERVING.md 'Durable intake "
              "journal')", file=sys.stderr)
        rc = 1
    if journal.get("enabled") and (
            journal.get("torn_ok") is False
            or journal.get("killed_mid_storm") is False):
        print("  !! torn-tail recovery broken: a SIGKILL mid-storm must "
              "leave at most the one record being written torn, with "
              "streams genuinely in flight at the kill — otherwise the "
              "drill proved nothing (SERVING.md 'Durable intake "
              "journal')", file=sys.stderr)
        rc = 1
    if stream.get("enabled") and stream.get("prefix_ok") is False:
        print("  !! streamed chunks are not prefix-consistent with the "
              "final captions (SERVING.md 'Streaming & result cache')",
              file=sys.stderr)
        rc = 1
    if lifecycle.get("enabled") and lifecycle.get("terminal_ok") is False:
        print("  !! lifecycle accounting broken: some request id never "
              "reached exactly one terminal event — the flight "
              "recorder's stream is lying or a request was silently "
              "lost (OBSERVABILITY.md 'Request lifecycle')",
              file=sys.stderr)
        rc = 1
    if slo.get("enabled") and slo.get("ok") is False:
        print("  !! an SLO burn-rate alert was still firing at probe "
              "end: the fleet burned its error budget faster than the "
              "alert threshold in both windows (OBSERVABILITY.md "
              "'Fleet plane')", file=sys.stderr)
        rc = 1
    if attribution and attribution.get("reconcile_ok") is False:
        print("  !! latency attribution does not reconcile: component "
              "sums diverge from measured request latency beyond "
              "tolerance (OBSERVABILITY.md 'Request lifecycle')",
              file=sys.stderr)
        rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
