#!/usr/bin/env python
"""Exploit the next healthy-tunnel window automatically.

The remote-TPU tunnel in this environment flaps on a scale of minutes to
hours, and the perf evidence that needs the chip (driver-grade bench
cache refresh, fused-CST phase costs, an op-level profiler trace) has to
land inside whatever window appears — usually while the scale chain is
also claiming the device.  This script encodes the protocol so nobody
has to babysit the tunnel:

1. poll the device with fresh-process probes (scale_chain.probe_device)
   until one succeeds;
2. sleep a grace period so the concurrently-waiting scale chain can
   claim the chip and get past its first compile/upload (the most
   wedge-prone phase — don't pile on);
3. run, each under its own timeout, saving outputs into --out_dir:
   - ``cst_breakdown.py``      -> measured rollout/transfer/reward/grad
                                  phase costs (host path, wall clock)
   - ``bench.py``              -> ONE JSON line; refreshes the
                                  SHA-stamped BENCH_TPU_CACHE on success
   - a fused-CST profiler trace (N steps under ``jax.profiler.trace``)
     summarized via ``profile_top.py`` -> top device ops

A step that fails or times out is recorded and skipped — a closing
window should still yield whatever it had time for.  One-shot: exits
after one window; rerun for another.

Usage: python scripts/chip_window.py --out_dir /tmp/chip_window
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from cst_captioning_tpu.resilience.integrity import (  # noqa: E402
    atomic_json_write,
)
from cst_captioning_tpu.utils.platform import run_in_group  # noqa: E402
from scale_chain import probe_device  # noqa: E402

# Traces the fused CST step; run as `python -c` so a wedge mid-trace
# kills a subprocess, not the watcher.
TRACE_FUSED = """\
import sys, os
sys.path.insert(0, {repo!r})
import jax, numpy as np
from bench import build, synthetic_rewarder, parse_args
from cst_captioning_tpu.training.device_rewards import build_device_tables
from cst_captioning_tpu.training.steps import make_fused_cst_step
# bench's own defaults (sys.argv is just ['-c'] here), so the traced
# program is BY CONSTRUCTION the one the bench cache describes.
sys.argv = ["bench.py"]
ns = parse_args()
model, state, feats, labels = build(ns.batch_size, ns.seq_per_img,
                                    ns.seq_len, ns.vocab, ns.hidden,
                                    ns.bfloat16)
rc, video_ids, kind, refs, vocab = synthetic_rewarder(
    ns.batch_size, ns.seq_per_img, ns.vocab)
corpus, tables, _ = build_device_tables(refs, vocab.word_to_ix)
step = jax.jit(make_fused_cst_step(model, ns.seq_len, ns.seq_per_img,
                                   corpus, tables), donate_argnums=(0,))
vix = np.arange(ns.batch_size, dtype=np.int32)
state, m = step(state, feats, vix, jax.random.PRNGKey(0))  # compile
float(m["loss"])
with jax.profiler.trace({trace_dir!r}):
    for i in range(5):
        state, m = step(state, feats, vix, jax.random.PRNGKey(1 + i))
    float(m["loss"])
print("TRACED 5 fused steps on", jax.devices()[0].platform)
"""


def run_step(name: str, cmd: list, out_dir: str, timeout_s: float,
             log: list, env: dict | None = None) -> bool:
    path = os.path.join(out_dir, f"{name}.out")
    t0 = time.monotonic()
    with open(path, "w") as f:
        info: dict = {}
        rc = run_in_group(cmd, cwd=REPO, timeout=timeout_s, env=env,
                          stdout=f, stderr=f, timeout_info=info)
    entry = {"step": name, "rc": rc, "timed_out": info["timed_out"],
             "seconds": round(time.monotonic() - t0, 1), "output": path}
    log.append(entry)
    print(json.dumps(entry), flush=True)
    return rc == 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out_dir", default="/tmp/chip_window")
    ap.add_argument("--probe_timeout", type=float, default=120.0)
    ap.add_argument("--poll_s", type=float, default=180.0)
    ap.add_argument("--max_wait", type=float, default=24 * 3600.0,
                    help="give up if no healthy window appears")
    ap.add_argument("--grace_s", type=float, default=600.0,
                    help="head start for the scale chain after a heal")
    ap.add_argument("--step_timeout", type=float, default=900.0)
    ap.add_argument("--skip_breakdown", action="store_true")
    ap.add_argument("--skip_bench", action="store_true")
    ap.add_argument("--skip_trace", action="store_true")
    args = ap.parse_args()
    os.makedirs(args.out_dir, exist_ok=True)

    # Monotonic: the max-wait window spans hours on a box whose wall
    # clock the tunnel host may step (cstlint:monotonic-deadline).
    deadline = time.monotonic() + args.max_wait
    waited_from = time.monotonic()
    while True:
        verdict, detail = probe_device(args.probe_timeout)
        if verdict == "broken":
            print(f"environment broken, not wedged: {detail}", flush=True)
            return 2
        if verdict == "ok":
            print(f"device healthy after "
                  f"{time.monotonic() - waited_from:.0f}s; "
                  f"grace {args.grace_s:.0f}s for the scale chain",
                  flush=True)
            time.sleep(args.grace_s)
            # Windows can close within minutes (observed in the field):
            # re-probe after the grace sleep, and fall back to polling
            # rather than burning three step-timeouts on a dead backend.
            verdict, _ = probe_device(args.probe_timeout)
            if verdict == "ok":
                break
            print("window closed during the grace period; back to polling",
                  flush=True)
        if time.monotonic() > deadline:
            print(f"no healthy window within {args.max_wait / 3600:.1f}h",
                  flush=True)
            return 3
        print(f"wedged ({time.monotonic() - waited_from:.0f}s); "
              f"retry in {args.poll_s:.0f}s", flush=True)
        time.sleep(args.poll_s)

    log: list = []
    if not args.skip_breakdown:
        run_step("cst_breakdown",
                 [sys.executable, "scripts/cst_breakdown.py", "--steps", "10"],
                 args.out_dir, args.step_timeout, log)
    if not args.skip_bench:
        # _BENCH_CHILD=1 runs the measurement in THIS subprocess instead
        # of bench's own probe+re-exec machinery: chip_window already
        # probed, and a single process is group-killable on timeout —
        # bench's internal child would start its own session and survive
        # our kill, holding the device as an orphan.
        env = dict(os.environ)
        env["_BENCH_CHILD"] = "1"
        if run_step("bench", [sys.executable, "bench.py"],
                    args.out_dir, args.step_timeout, log, env=env):
            # Scaling datapoint (only on a backend the default bench just
            # proved alive): the fused step's per-timestep GEMMs are small
            # at batch 32 (640 rows); doubling the batch may lift MXU
            # utilization.  --cache 0 — an exploratory config must not
            # clobber the shipped-config cache entry the CPU fallback
            # attaches.
            run_step("bench_cst_b64",
                     [sys.executable, "bench.py", "--stage", "cst",
                      "--batch_size", "64", "--cache", "0"],
                     args.out_dir, args.step_timeout, log, env=env)
    if not args.skip_trace:
        trace_dir = os.path.join(args.out_dir, "fused_trace")
        code = TRACE_FUSED.format(repo=REPO, trace_dir=trace_dir)
        if run_step("trace_fused", [sys.executable, "-c", code],
                    args.out_dir, args.step_timeout, log):
            run_step("trace_top",
                     [sys.executable, "scripts/profile_top.py", trace_dir,
                      "--top", "25"],
                     args.out_dir, args.step_timeout, log)

    atomic_json_write(os.path.join(args.out_dir, "window_log.json"),
                      log, indent=2)
    ok = sum(1 for e in log if e["rc"] == 0)
    print(f"window done: {ok}/{len(log)} steps succeeded "
          f"-> {args.out_dir}", flush=True)
    return 0 if ok or not log else 1


if __name__ == "__main__":
    sys.exit(main())
