#!/usr/bin/env python
"""MSR-VTT-scale chain on the chip: XE -> WXE -> CST, with learning curves.

The scale twin of scripts/demo.py and the runner for the north-star
evidence (VERDICT r3 #1): synthesizes an MSR-VTT-shaped dataset (default
640 train videos x 20 captions, ~8k vocab via SyntheticSpec.rich_vocab,
ResNet-152 (28, 2048) + C3D (1, 4096) feature shapes, 30-token captions)
and runs the real CLI chain at the shipped trainer defaults
(--device_rewards fused CST, --device_feats, bf16).

Stages are individually selectable and RESUMABLE: each stage trains into
its own checkpoint dir and the Trainer auto-resumes from the newest
checkpoint, so a tunnel wedge mid-stage loses at most
--save_every_steps steps.  Learning curves land in each stage dir's
metrics.jsonl; val scores per epoch are in infos.json / the metrics log.

Usage (full chain):            python scripts/scale_chain.py --out_dir DIR
One stage (e.g. after wedge):  python scripts/scale_chain.py --out_dir DIR \
                                   --stages cst
SCB variant of the CST stage:  --stages cst_scb
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def generate_data(root: str, num_videos: int, num_val: int,
                  feat_dims=(2048, 4096), feat_times=(28, 1),
                  rich_vocab: int = 8000, guard_dir: str | None = None):
    from cst_captioning_tpu.data.synthetic import SyntheticSpec, generate
    from cst_captioning_tpu.data.vocab import load_vocab

    marker = os.path.join(root, "SCALE_SPEC.json")
    spec_dict = {"num_videos": num_videos, "num_val": num_val,
                 "feat_dims": list(feat_dims), "feat_times": list(feat_times),
                 "rich_vocab": rich_vocab, "v": 4}  # v4 = consensus-gap grammar
    if os.path.exists(marker) and os.path.exists(marker + ".paths"):
        with open(marker) as f:
            if json.load(f) == spec_dict:
                print(f"reusing dataset in {root}")
                with open(marker + ".paths") as f:
                    return json.load(f)
        # Spec/grammar changed: checkpoints trained on the OLD dataset
        # must not silently chain against regenerated data (different
        # vocab size/word-id mapping -> shape crash, or worse, scrambled
        # embeddings with garbage metrics).  Refuse; the operator picks a
        # fresh --out_dir or deletes the stale checkpoints deliberately.
        if guard_dir and os.path.isdir(guard_dir) and os.listdir(guard_dir):
            raise SystemExit(
                f"dataset spec changed but {guard_dir} holds checkpoints "
                "trained on the previous dataset; use a fresh --out_dir "
                "(or delete the old checkpoints) instead of mixing them")
    os.makedirs(root, exist_ok=True)
    t0 = time.time()
    spec = SyntheticSpec(
        num_videos=num_videos, captions_per_video=20, max_len=30,
        feat_dims=tuple(feat_dims), feat_times=tuple(feat_times),
        rich_vocab=rich_vocab,
    )
    train = generate(root, "train", spec)
    vocab = load_vocab(train["vocab_json"])
    val_spec = SyntheticSpec(
        num_videos=num_val, captions_per_video=20, max_len=30,
        feat_dims=tuple(feat_dims), feat_times=tuple(feat_times),
        rich_vocab=rich_vocab,
    )
    val = generate(root, "val", val_spec, vocab=vocab)
    paths = {"train": train, "val": val}
    with open(marker + ".paths", "w") as f:
        json.dump(paths, f)
    with open(marker, "w") as f:
        json.dump(spec_dict, f)
    print(f"dataset generated in {time.time() - t0:.0f}s -> {root}")
    return paths


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--out_dir", default="/tmp/cst_scale")
    p.add_argument("--num_videos", type=int, default=640)
    p.add_argument("--num_val", type=int, default=128)
    p.add_argument("--batch_size", type=int, default=32)
    # XE must run to CONVERGENCE before RL: the round-4 CPU probes showed
    # REINFORCE from a half-trained policy degrades val CIDEr (sampled
    # rewards far below baseline, noisy negative advantages), while the
    # same CST stage from a converged XE is stable-to-improving.  Epoch
    # caps are ceilings; early stop (--max_patience below) ends stages.
    p.add_argument("--xe_epochs", type=int, default=80)
    p.add_argument("--wxe_epochs", type=int, default=20)
    p.add_argument("--cst_epochs", type=int, default=25)
    p.add_argument("--patience", type=int, default=15,
                   help="early-stop patience for XE/WXE (0 = off); CST "
                        "stages always run their full epoch budget so the "
                        "learning curves are complete.  Generous default: "
                        "synthetic epochs are tiny (20 steps at 640 "
                        "videos) and greedy-decode val scores plateau in "
                        "EXACT ties, so short patience fires early "
                        "(round-4 midscale probe stopped XE at 16/100 "
                        "epochs, well short of convergence)")
    p.add_argument("--lr_decay_every", type=int, default=25,
                   help="staircase decay period in epochs for XE/WXE "
                        "(the 640-video synthetic has ~1/10 the steps of "
                        "real MSR-VTT epochs, so decay slower than the "
                        "reference's every-3)")
    p.add_argument("--stages", default="xe,wxe,cst",
                   help="comma list from xe,wxe,cst,cst_scb,"
                        "cst_scb_sample,eval")
    p.add_argument("--cst_temperature", default="1.0",
                   help="multinomial sampling temperature for CST stages")
    p.add_argument("--cst_lr", default="2e-5",
                   help="probe-validated: 5e-5 destabilized REINFORCE "
                        "from a converged warm start; 2e-5 was stable")
    p.add_argument("--device_rewards", default="1")
    p.add_argument("--device_feats", default="1",
                   help="0 streams features per batch via the prefetch "
                        "thread — the safer path over a flaky remote "
                        "tunnel, where the full-table HBM upload's bulk "
                        "transfers have wedged the transport")
    p.add_argument("--rnn_size", type=int, default=512)
    p.add_argument("--rich_vocab", type=int, default=8000)
    p.add_argument("--feat_dims", type=int, nargs="+", default=[2048, 4096])
    p.add_argument("--feat_times", type=int, nargs="+", default=[28, 1])
    p.add_argument("--xe_lr", default="2e-4")
    args = p.parse_args()

    import train as train_cli

    root = os.path.join(args.out_dir, "data")
    ckpt = os.path.join(args.out_dir, "checkpoints")
    paths = generate_data(root, args.num_videos, args.num_val,
                          feat_dims=args.feat_dims,
                          feat_times=args.feat_times,
                          rich_vocab=args.rich_vocab, guard_dir=ckpt)
    train, val = paths["train"], paths["val"]

    common = [
        "--train_feat_h5", *json.loads(train["feat_h5"]),
        "--train_label_h5", train["label_h5"],
        "--train_info_json", train["info_json"],
        "--train_cocofmt_file", train["cocofmt_json"],
        "--val_feat_h5", *json.loads(val["feat_h5"]),
        "--val_label_h5", val["label_h5"],
        "--val_info_json", val["info_json"],
        "--val_cocofmt_file", val["cocofmt_json"],
        "--batch_size", str(args.batch_size), "--seq_per_img", "20",
        "--rnn_size", str(args.rnn_size),
        "--input_encoding_size", str(args.rnn_size),
        "--att_size", str(args.rnn_size), "--max_length", "30",
        "--use_bfloat16", "1", "--device_feats", args.device_feats,
        "--save_every_steps", "100",  # tunnel-wedge recovery granularity
        "--log_every", "10", "--fast_val", "1",
    ]
    xe_sched = [
        "--max_patience", str(args.patience),
        "--learning_rate_decay_every", str(args.lr_decay_every),
        "--learning_rate_decay_rate", "0.5",
    ]
    stages = [s.strip() for s in args.stages.split(",") if s.strip()]

    def report(tag, res):
        print(f"=== {tag} done: best {res.get('best_score')} @ step "
              f"{res.get('best_step')} (last step {res.get('last_step')}) ===",
              flush=True)

    if "xe" in stages:
        print("=== stage: XE pretrain ===", flush=True)
        report("xe", train_cli.main([
            *common, *xe_sched, "--checkpoint_path", f"{ckpt}/xe",
            "--max_epochs", str(args.xe_epochs),
            "--learning_rate", args.xe_lr,
        ], return_result=True))

    if "wxe" in stages:
        print("=== stage: WXE warm-start ===", flush=True)
        report("wxe", train_cli.main([
            *common, *xe_sched, "--checkpoint_path", f"{ckpt}/wxe",
            "--start_from", f"{ckpt}/xe",
            "--use_consensus_weights", "1",
            "--train_bcmrscores_pkl", train["consensus_pkl"],
            "--max_epochs", str(args.wxe_epochs),
            "--learning_rate", "1e-4",
        ], return_result=True))

    cst_common = [
        "--start_from", f"{ckpt}/wxe",
        "--use_rl", "1", "--max_patience", "0",  # full curves, no early stop
        "--device_rewards", args.device_rewards,
        "--temperature", args.cst_temperature,
        "--train_cached_tokens", train["cached_tokens"],
        "--max_epochs", str(args.cst_epochs),
        "--learning_rate", args.cst_lr,
    ]

    if "cst" in stages:
        print("=== stage: CST (greedy baseline, fused rewards) ===",
              flush=True)
        report("cst", train_cli.main([
            *common, *cst_common, "--checkpoint_path", f"{ckpt}/cst",
            "--rl_baseline", "greedy",
        ], return_result=True))

    if "cst_scb_sample" in stages:
        print("=== stage: CST (SCB-sample leave-one-out baseline) ===",
              flush=True)
        report("cst_scb_sample", train_cli.main([
            *common, *cst_common,
            "--checkpoint_path", f"{ckpt}/cst_scb_sample",
            "--rl_baseline", "scb-sample",
        ], return_result=True))

    if "cst_scb" in stages:
        print("=== stage: CST (SCB-gt baseline, fused rewards) ===",
              flush=True)
        report("cst_scb", train_cli.main([
            *common, *cst_common, "--checkpoint_path", f"{ckpt}/cst_scb",
            "--rl_baseline", "scb-gt",
            "--train_bcmrscores_pkl", train["consensus_pkl"],
        ], return_result=True))

    if "eval" in stages:
        import eval as eval_cli

        for stage in ("wxe", "cst", "cst_scb", "cst_scb_sample"):
            d = f"{ckpt}/{stage}"
            if not os.path.exists(os.path.join(d, "infos.json")):
                continue
            print(f"=== beam-5 eval: {stage} ===", flush=True)
            eval_cli.main([
                "--checkpoint_path", d,
                "--test_feat_h5", *json.loads(val["feat_h5"]),
                "--test_label_h5", val["label_h5"],
                "--test_info_json", val["info_json"],
                "--test_cocofmt_file", val["cocofmt_json"],
                "--beam_size", "5", "--batch_size", str(args.batch_size),
                "--max_length", "30",
                "--result_file", os.path.join(args.out_dir,
                                              f"{stage}_beam5.json"),
            ])
    return 0


if __name__ == "__main__":
    sys.exit(main())
