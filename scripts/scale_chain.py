#!/usr/bin/env python
"""MSR-VTT-scale chain on the chip: XE -> WXE -> CST, with learning curves.

The scale twin of scripts/demo.py and the runner for the north-star
evidence (VERDICT r3 #1): synthesizes an MSR-VTT-shaped dataset (default
640 train videos x 20 captions, ~8k vocab via SyntheticSpec.rich_vocab,
ResNet-152 (28, 2048) + C3D (1, 4096) feature shapes, 30-token captions)
and runs the real CLI chain at the shipped trainer defaults
(--device_rewards fused CST, --device_feats, bf16).

Stages are individually selectable and RESUMABLE: each stage trains into
its own checkpoint dir and the Trainer auto-resumes from the newest
checkpoint, so a tunnel wedge mid-stage loses at most
--save_every_steps steps.  Learning curves land in each stage dir's
metrics.jsonl; val scores per epoch are in infos.json / the metrics log.

Usage (full chain):            python scripts/scale_chain.py --out_dir DIR
One stage (e.g. after wedge):  python scripts/scale_chain.py --out_dir DIR \
                                   --stages cst
SCB variant of the CST stage:  --stages cst_scb

Wedge recovery: every stage runs as a SUBPROCESS with the trainer's
``--wedge_timeout`` watchdog armed, so a wedged remote-device transport
kills the stage (exit 124) instead of hanging it.  The harness then polls
the device with fresh probe processes until the transport heals and
re-runs the stage, which auto-resumes from its newest checkpoint (the
2026-07-31 field pattern: the tunnel flaps on a scale of tens of minutes
to hours, and a chain left unattended must survive that).  Stage exits
are classified through the resilience exit-code taxonomy
(cst_captioning_tpu/resilience/exitcodes.py): RESUMABLE exits — 75
(preempted: the trainer checkpointed at a step boundary and asked to be
restarted), 143/137 (external kills) — restart immediately without a
device probe, and a preempt exit's checkpoint advance counts as
progress.  A FATAL exit while the device probe SUCCEEDS is a real
failure and aborts the chain — retrying can only hide it.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from cst_captioning_tpu.resilience import exitcodes  # noqa: E402
from cst_captioning_tpu.resilience.integrity import (  # noqa: E402
    atomic_json_write,
)
from cst_captioning_tpu.utils.platform import run_in_group  # noqa: E402
from cst_captioning_tpu.utils.watchdog import WEDGE_EXIT_CODE  # noqa: E402


class EventLog:
    """Append-only JSONL record of the chain's lifecycle — the machine-
    readable twin of the ``=== ... ===`` console markers, so
    chain_report.py can say WHY a chain has produced no curves yet
    (wedged since when, probes so far, attempts per stage) without
    anyone spelunking console logs.  Best-effort by design: a full disk
    must not kill the harness whose job is riding out failures."""

    def __init__(self, path: str | None):
        self.path = path

    def emit(self, event: str, **fields) -> None:
        if not self.path:
            return
        rec = {"ts": time.time(), "event": event, **fields}
        try:
            with open(self.path, "a") as f:
                f.write(json.dumps(rec) + "\n")
        except OSError:
            pass


def probe_device(timeout_s: float = 120.0,
                 env: dict | None = None) -> tuple[str, str]:
    """Can a FRESH process initialize the default jax backend right now?

    A new process is the only honest probe: the wedged client in a stuck
    stage never recovers in place, and this parent must not touch the
    backend itself (a wedged init would hang the harness too).  ``env``
    must match the environment the stages run under — probing a different
    backend than the stages use answers the wrong question.

    Returns ``(verdict, detail)`` with verdict one of:
    - ``"ok"``     — backend initializes;
    - ``"wedged"`` — init hung or failed while plain ``import jax`` works:
      waiting may heal it;
    - ``"broken"`` — the interpreter/env itself is dead (import fails):
      no amount of waiting helps, surface it immediately.
    """
    def grouped(py_code: str) -> tuple[int, bool, str]:
        """(rc, timed_out, stderr tail) — run_in_group so a hung probe's
        whole tree (tunnel helper processes included) is SIGKILLed, not
        just the direct python child; stderr goes through a temp FILE,
        which stays safe across the group kill unlike a pipe."""
        import tempfile

        with tempfile.TemporaryFile(mode="w+") as ef:
            info: dict = {}
            rc = run_in_group([sys.executable, "-c", py_code],
                              env=env, cwd=REPO, timeout=timeout_s,
                              stdout=subprocess.DEVNULL, stderr=ef,
                              timeout_info=info)
            ef.seek(0)
            return rc, info["timed_out"], ef.read().strip()[-2000:]

    rc, timed_out, detail = grouped("import jax; jax.devices()")
    if rc == 0:
        return "ok", ""
    if timed_out:
        return "wedged", f"device probe timed out after {timeout_s:.0f}s"
    # Fast nonzero: either the backend refused (transient — treat as
    # wedged) or the environment cannot even import jax (permanent).
    rc2, timed_out2, detail2 = grouped("import jax")
    if rc2 == 0 or timed_out2:
        return "wedged", detail
    return "broken", detail2 or detail


def run_stage(tag: str, cmd: list, *, max_attempts: int,
              wedge_poll_s: float, max_wedge_wait_s: float,
              timeout_s: float = 0.0, probe_timeout_s: float = 120.0,
              env: dict | None = None, fingerprint=None,
              events: EventLog | None = None) -> None:
    """Run ``cmd`` to completion, resuming across device wedges.

    ``max_attempts`` bounds CONSECUTIVE attempts without progress, not
    total attempts: a long stage checkpointing its way through many tunnel
    flaps retries indefinitely, while a stage wedging at the same point
    every time (e.g. a first compile longer than --wedge_timeout) aborts
    with advice instead of burning attempts x timeout.  ``fingerprint``
    (optional zero-arg callable) returns any comparable snapshot of the
    stage's on-disk progress — checkpoint steps, metrics length; without
    one, every failed attempt counts as no-progress.

    ``timeout_s`` is a harness-side hard cap layered on top of the
    command's own in-process watchdog (both train and eval stages arm
    ``--wedge_timeout``); 0 means none.  The subprocess gets its own
    session so a timeout kill takes the whole process group."""
    probed_detail = {"printed": False}
    events = events or EventLog(None)

    def abort(reason: str, msg: str) -> SystemExit:
        events.emit("stage_abort", tag=tag, reason=reason)
        return SystemExit(msg)

    def probe() -> str:
        verdict, detail = probe_device(probe_timeout_s, env)
        events.emit("probe", tag=tag, verdict=verdict)
        if verdict == "broken":
            raise abort(
                "broken_env",
                f"stage {tag}: the stage environment cannot even import "
                f"jax — not a wedge, aborting immediately:\n{detail}")
        if verdict == "wedged" and detail and not probed_detail["printed"]:
            # Surface the first probe's actual error once: a deterministic
            # fast failure (expired credentials, refused endpoint) would
            # otherwise heal-poll for hours with its cause never shown.
            # Collapsed to ONE line so chain_report's marker parser (and
            # any grep) sees the whole detail.
            probed_detail["printed"] = True
            one_line = " | ".join(
                s for s in (x.strip() for x in detail.splitlines()) if s)
            print(f"=== {tag}: device probe detail: {one_line} ===",
                  flush=True)
        return verdict

    healthy_timeouts = 0
    no_progress = 0
    last_rc = None
    last_fp = fingerprint() if fingerprint else None
    attempt = 0
    while True:
        if no_progress >= max_attempts:
            # Diagnose by what the attempts actually died OF: the
            # resumable branch never probes the device, so "the device
            # stayed healthy" / "raise --wedge_timeout" would be the
            # wrong remediation for an exit-at-startup loop.
            if (last_rc is not None
                    and exitcodes.classify(last_rc) == exitcodes.RESUMABLE):
                why = (f"every attempt exited resumable (last: "
                       f"{exitcodes.describe(last_rc)}) without advancing "
                       "its checkpoint — an exit-during-startup loop (OOM "
                       "kill, preemption storm), not a wedge; fix the "
                       "external cause and rerun, the newest checkpoint "
                       "is intact")
            else:
                why = ("the device stayed healthy — if each died at exit "
                       "124 at the same point, a legitimate blocking phase "
                       "(first compile/upload) likely exceeds "
                       "--wedge_timeout; raise it rather than retrying")
            raise abort(
                "no_progress_cap",
                f"stage {tag}: {no_progress} consecutive attempts made no "
                f"on-disk progress; {why}")
        attempt += 1
        if attempt > 1:
            print(f"=== {tag}: attempt {attempt} (resume; {no_progress} "
                  f"healthy attempts since progress, cap {max_attempts}) "
                  "===", flush=True)
        events.emit("attempt_start", tag=tag, attempt=attempt,
                    no_progress=no_progress)
        # run_in_group owns the kill semantics: own session, group-SIGKILL
        # on timeout AND on any unwind (Ctrl-C / SIGTERM-as-SystemExit), so
        # an interrupted harness never leaves a stage holding the device.
        info: dict = {}
        rc = run_in_group(cmd, env=env, cwd=REPO,
                          timeout=timeout_s or None, timeout_info=info)
        timed_out = info["timed_out"]
        if rc == 0:
            events.emit("stage_done", tag=tag, attempts=attempt)
            return
        progressed = False
        if fingerprint:
            fp = fingerprint()
            progressed, last_fp = fp != last_fp, fp
        events.emit("attempt_exit", tag=tag, attempt=attempt, rc=rc,
                    timed_out=timed_out, progressed=progressed)
        # Exit-code taxonomy (resilience/exitcodes.py): what the rc MEANS
        # decides the response, instead of pattern-matching magic numbers.
        category = exitcodes.classify(rc)
        last_rc = rc
        # One probe decides this attempt's classification; the heal loop
        # below reuses that verdict for its first wait instead of
        # immediately spawning a second backend-init probe at a device we
        # just found wedged.
        known_wedged = False
        if timed_out:
            if probe() == "ok":
                # Harness-cap timeout while the device probe succeeds:
                # either a per-connection wedge (fresh connections work,
                # the stage's own RPC died — retry helps) or a genuinely
                # too-slow command (commands under timeout_s have no
                # checkpoint resume, so a retry repeats the identical
                # run).  One retry distinguishes them; a second
                # CONSECUTIVE healthy timeout means raise the cap.
                if progressed:
                    # Progress clears BOTH counters FIRST: a checkpointed
                    # attempt that later times out is a new situation, not
                    # "twice in a row" — it must not trip the abort below.
                    no_progress, healthy_timeouts = 0, 0
                else:
                    no_progress += 1
                healthy_timeouts += 1
                if healthy_timeouts >= 2:
                    raise abort(
                        "healthy_timeout",
                        f"stage {tag} exceeded its {timeout_s:.0f}s harness "
                        "timeout twice in a row while the device probe "
                        "succeeds — not a wedge; raise the timeout (e.g. "
                        "--eval_timeout) instead of retrying")
                continue
            known_wedged = True
        elif category == exitcodes.RESUMABLE:
            # The stage exited by choice or external kill with its
            # checkpoint intact: 75 (preempted) means the trainer SAVED a
            # verified checkpoint before exiting — the fingerprint
            # advances and the attempt counts as progress instead of
            # burning the no-progress cap; 143/137 (unhandled
            # SIGTERM/SIGKILL) resume from the newest checkpoint the same
            # way.  No device probe: the exit came from the process, not
            # from a wedged transport.
            print(f"=== {tag}: resumable exit rc={rc} "
                  f"({exitcodes.describe(rc)}); restarting ===", flush=True)
            events.emit("resumable_exit", tag=tag, rc=rc,
                        preempted=(exitcodes.normalize(rc)
                                   == exitcodes.EXIT_PREEMPTED),
                        progressed=progressed)
            if progressed:
                no_progress, healthy_timeouts = 0, 0
            else:
                no_progress += 1
            continue
        elif category != exitcodes.WEDGE:
            if probe() == "ok":
                raise abort(
                    "real_failure",
                    f"stage {tag} failed with rc={rc} "
                    f"({exitcodes.describe(rc)}) while the device probe "
                    "succeeds — a real failure, not a wedge; aborting")
            known_wedged = True
        print(f"=== {tag}: wedge (rc={rc}); polling for the device "
              f"every {wedge_poll_s:.0f}s ===", flush=True)
        events.emit("wedge", tag=tag, rc=rc, attempt=attempt)
        # Monotonic, not wall clock: an NTP step during the hours-long
        # heal wait must not shrink or stretch the deadline
        # (cstlint:monotonic-deadline).
        wedge_t0 = time.monotonic()
        deadline = wedge_t0 + max_wedge_wait_s
        healed = False
        observed_wedged = known_wedged
        if known_wedged:
            time.sleep(wedge_poll_s)  # just probed wedged; wait first
        while time.monotonic() < deadline:
            if probe() == "ok":
                healed = True
                break
            observed_wedged = True
            time.sleep(wedge_poll_s)
        if not healed:
            raise abort(
                "heal_wait_exhausted",
                f"stage {tag}: device did not heal within "
                f"{max_wedge_wait_s / 3600:.1f}h; giving up")
        events.emit("healed", tag=tag,
                    waited_s=round(time.monotonic() - wedge_t0, 1))
        # Attempt accounting AFTER the facts are in: progress resets the
        # cap; an attempt that died while the device was observably down
        # proves nothing about the stage and does not count; only
        # healthy-device, zero-progress attempts (e.g. a deterministic
        # 124 at the same setup point) approach the cap.
        if progressed:
            no_progress, healthy_timeouts = 0, 0
        elif observed_wedged:
            healthy_timeouts = 0
        else:
            no_progress += 1


def stage_fingerprint(stage_dir):
    """Snapshot of the stage's REAL progress markers: the recorded
    last/best step from infos.json plus the set of on-disk checkpoint
    step directories (best-score and recovery managers).  Deliberately
    NOT every file's size — metrics.jsonl/TB appends from re-running
    the same steps after a resume would otherwise count as 'progress'
    and reset the no-progress cap on every attempt, letting a
    deterministic wedge firing past the last checkpoint retry forever."""
    def fp():
        marks = []
        try:
            with open(os.path.join(stage_dir, "infos.json")) as f:
                infos = json.load(f)
            marks.append(("infos", infos.get("last_step"),
                          infos.get("best_step")))
        except (OSError, ValueError):
            pass
        for sub in (".", "recovery"):
            d = os.path.join(stage_dir, sub)
            try:
                steps = sorted(e for e in os.listdir(d) if e.isdigit())
            except OSError:
                steps = []
            marks.append((sub, tuple(steps)))
        return tuple(marks)
    return fp


def generate_data(root: str, num_videos: int, num_val: int,
                  feat_dims=(2048, 4096), feat_times=(28, 1),
                  rich_vocab: int = 8000, guard_dir: str | None = None):
    from cst_captioning_tpu.data.synthetic import SyntheticSpec, generate
    from cst_captioning_tpu.data.vocab import load_vocab

    marker = os.path.join(root, "SCALE_SPEC.json")
    spec_dict = {"num_videos": num_videos, "num_val": num_val,
                 "feat_dims": list(feat_dims), "feat_times": list(feat_times),
                 "rich_vocab": rich_vocab, "v": 4}  # v4 = consensus-gap grammar
    if os.path.exists(marker) and os.path.exists(marker + ".paths"):
        with open(marker) as f:
            if json.load(f) == spec_dict:
                print(f"reusing dataset in {root}")
                with open(marker + ".paths") as f:
                    return json.load(f)
        # Spec/grammar changed: checkpoints trained on the OLD dataset
        # must not silently chain against regenerated data (different
        # vocab size/word-id mapping -> shape crash, or worse, scrambled
        # embeddings with garbage metrics).  Refuse; the operator picks a
        # fresh --out_dir or deletes the stale checkpoints deliberately.
        if guard_dir and os.path.isdir(guard_dir) and os.listdir(guard_dir):
            print(f"dataset spec changed but {guard_dir} holds checkpoints "
                  "trained on the previous dataset; use a fresh --out_dir "
                  "(or delete the old checkpoints) instead of mixing them",
                  file=sys.stderr)
            # Operator-config refusal -> the taxonomy's usage class, so
            # a supervisor never retries what only a human can resolve.
            raise SystemExit(exitcodes.EXIT_USAGE)
    os.makedirs(root, exist_ok=True)
    t0 = time.monotonic()
    spec = SyntheticSpec(
        num_videos=num_videos, captions_per_video=20, max_len=30,
        feat_dims=tuple(feat_dims), feat_times=tuple(feat_times),
        rich_vocab=rich_vocab,
    )
    train = generate(root, "train", spec)
    vocab = load_vocab(train["vocab_json"])
    val_spec = SyntheticSpec(
        num_videos=num_val, captions_per_video=20, max_len=30,
        feat_dims=tuple(feat_dims), feat_times=tuple(feat_times),
        rich_vocab=rich_vocab,
    )
    val = generate(root, "val", val_spec, vocab=vocab)
    paths = {"train": train, "val": val}
    # The marker seals "dataset generation completed": it must never be
    # readable half-written, or a resumed chain would trust a torn spec.
    atomic_json_write(marker + ".paths", paths)
    atomic_json_write(marker, spec_dict)
    print(f"dataset generated in {time.monotonic() - t0:.0f}s -> {root}")
    return paths


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--out_dir", default="/tmp/cst_scale")
    p.add_argument("--num_videos", type=int, default=640)
    p.add_argument("--num_val", type=int, default=128)
    p.add_argument("--batch_size", type=int, default=32)
    # XE must run to CONVERGENCE before RL: the round-4 CPU probes showed
    # REINFORCE from a half-trained policy degrades val CIDEr (sampled
    # rewards far below baseline, noisy negative advantages), while the
    # same CST stage from a converged XE is stable-to-improving.  Epoch
    # caps are ceilings; early stop (--max_patience below) ends stages.
    p.add_argument("--xe_epochs", type=int, default=80)
    p.add_argument("--wxe_epochs", type=int, default=20)
    p.add_argument("--cst_epochs", type=int, default=25)
    p.add_argument("--patience", type=int, default=15,
                   help="early-stop patience for XE/WXE (0 = off); CST "
                        "stages always run their full epoch budget so the "
                        "learning curves are complete.  Generous default: "
                        "synthetic epochs are tiny (20 steps at 640 "
                        "videos) and greedy-decode val scores plateau in "
                        "EXACT ties, so short patience fires early "
                        "(round-4 midscale probe stopped XE at 16/100 "
                        "epochs, well short of convergence)")
    p.add_argument("--min_epochs", type=int, default=30,
                   help="floor under early stopping for the COLD-START XE "
                        "stage only (WXE warm-starts from a converged XE "
                        "and keeps normal early stopping — see xe_floor in "
                        "main): at small steps-per-epoch scales val CIDEr "
                        "ties at ~0 for many early epochs and patience "
                        "would fire before learning starts (observed live "
                        "at 64 videos / batch 16: stopped at epoch 18 with "
                        "CIDEr 0.02)")
    p.add_argument("--lr_decay_every", type=int, default=25,
                   help="staircase decay period in epochs for XE/WXE "
                        "(the 640-video synthetic has ~1/10 the steps of "
                        "real MSR-VTT epochs, so decay slower than the "
                        "reference's every-3)")
    p.add_argument("--stages", default="xe,wxe,cst",
                   help="comma list from xe,wxe,cst,cst_scb,"
                        "cst_scb_sample,eval")
    p.add_argument("--cst_temperature", default="1.0",
                   help="multinomial sampling temperature for CST stages")
    p.add_argument("--cst_lr", default="2e-5",
                   help="probe-validated: 5e-5 destabilized REINFORCE "
                        "from a converged warm start; 2e-5 was stable")
    p.add_argument("--device_rewards", default="1")
    p.add_argument("--device_feats", default="1",
                   help="0 streams features per batch via the prefetch "
                        "thread — the safer path over a flaky remote "
                        "tunnel, where the full-table HBM upload's bulk "
                        "transfers have wedged the transport")
    p.add_argument("--rnn_size", type=int, default=512)
    p.add_argument("--rich_vocab", type=int, default=8000)
    p.add_argument("--feat_dims", type=int, nargs="+", default=[2048, 4096])
    p.add_argument("--feat_times", type=int, nargs="+", default=[28, 1])
    p.add_argument("--xe_lr", default="2e-4")
    p.add_argument("--seed", type=int, default=123,
                   help="training seed passed to every stage (reproduce a "
                        "chain exactly, or rerun it at a new seed for "
                        "robustness evidence)")
    p.add_argument("--wedge_timeout", type=float, default=1500.0,
                   help="trainer watchdog (seconds without loop progress "
                        "-> exit 124 -> harness resume); must exceed the "
                        "worst legitimate first-compile stall over the "
                        "tunnel (~6 min observed at 640 videos). 0 off")
    p.add_argument("--wedge_poll", type=float, default=180.0,
                   help="seconds between device probes while wedged")
    p.add_argument("--max_wedge_wait", type=float, default=6 * 3600.0,
                   help="give up when the device stays wedged this long")
    p.add_argument("--max_stage_attempts", type=int, default=4,
                   help="max CONSECUTIVE attempts without on-disk progress "
                        "before a stage aborts; attempts that advance the "
                        "stage's checkpoints reset the count, so a long "
                        "run surviving many tunnel flaps is never capped")
    p.add_argument("--eval_timeout", type=float, default=3600.0,
                   help="harness-side hard cap per eval invocation, a "
                        "second safety net over eval's own in-process "
                        "--wedge_timeout watchdog; 0 = none")
    p.add_argument("--fault_plan", default=None,
                   help="CHAOS DRILL: forward this fault plan (see "
                        "RESILIENCE.md grammar) to every TRAIN stage — "
                        "e.g. 'wedge@step=70' proves the whole "
                        "wedge->probe->resume loop end to end.  Faults "
                        "fire once per stage run; the harness must ride "
                        "them out exactly like real failures")
    args = p.parse_args()
    # Stages run as subprocesses with cwd=REPO; a relative --out_dir must
    # mean the same directory in the harness and in every stage.
    args.out_dir = os.path.abspath(args.out_dir)
    # SIGTERM (scheduler stop, kill <pid>) must unwind like Ctrl-C so
    # run_in_group's finally can reap the stage child — the default
    # disposition would kill this harness and orphan the stage against
    # the device.
    signal.signal(signal.SIGTERM,
                  lambda *_: sys.exit(exitcodes.EXIT_SIGTERM))

    root = os.path.join(args.out_dir, "data")
    ckpt = os.path.join(args.out_dir, "checkpoints")
    os.makedirs(args.out_dir, exist_ok=True)
    events = EventLog(os.path.join(args.out_dir, "chain_events.jsonl"))
    events.emit("chain_start", argv=sys.argv[1:], pid=os.getpid(),
                stages=args.stages, num_videos=args.num_videos)
    paths = generate_data(root, args.num_videos, args.num_val,
                          feat_dims=args.feat_dims,
                          feat_times=args.feat_times,
                          rich_vocab=args.rich_vocab, guard_dir=ckpt)
    train, val = paths["train"], paths["val"]
    events.emit("dataset_ready", root=root)

    common = [
        "--train_feat_h5", *json.loads(train["feat_h5"]),
        "--train_label_h5", train["label_h5"],
        "--train_info_json", train["info_json"],
        "--train_cocofmt_file", train["cocofmt_json"],
        "--val_feat_h5", *json.loads(val["feat_h5"]),
        "--val_label_h5", val["label_h5"],
        "--val_info_json", val["info_json"],
        "--val_cocofmt_file", val["cocofmt_json"],
        "--batch_size", str(args.batch_size), "--seq_per_img", "20",
        "--rnn_size", str(args.rnn_size),
        "--input_encoding_size", str(args.rnn_size),
        "--att_size", str(args.rnn_size), "--max_length", "30",
        "--use_bfloat16", "1", "--device_feats", args.device_feats,
        "--save_every_steps", "100",  # tunnel-wedge recovery granularity
        "--log_every", "10", "--fast_val", "1",
        "--seed", str(args.seed),
        "--wedge_timeout", str(args.wedge_timeout),
    ]
    if args.fault_plan:
        common += ["--fault_plan", args.fault_plan]
    xe_sched = [
        "--max_patience", str(args.patience),
        "--learning_rate_decay_every", str(args.lr_decay_every),
        "--learning_rate_decay_rate", "0.5",
    ]
    # The early-stop floor exists for COLD-START training, whose first
    # epochs sit in the all-tie val regime; WXE warm-starts from a
    # converged XE and must keep normal early stopping (a 30-epoch floor
    # would silently disable it under the 20-epoch default budget).
    xe_floor = ["--min_epochs", str(min(args.min_epochs, args.xe_epochs))]
    stages = [s.strip() for s in args.stages.split(",") if s.strip()]

    def run_train_stage(tag, argv, label: str = ""):
        # Tags are SHORT ids (the checkpoint-dir name): they key the event
        # log, match chain_report's marker regexes, and join against the
        # curves/beam sections of the JSON report.  The human description
        # goes on its own line.
        print(f"=== stage: {tag} ===", flush=True)
        if label:
            print(f"    ({label})", flush=True)
        stage_dir = argv[argv.index("--checkpoint_path") + 1]
        events.emit("stage_start", tag=tag, stage_dir=stage_dir,
                    label=label)
        run_stage(tag, [sys.executable, "train.py", *argv],
                  max_attempts=args.max_stage_attempts,
                  wedge_poll_s=args.wedge_poll,
                  max_wedge_wait_s=args.max_wedge_wait,
                  fingerprint=stage_fingerprint(stage_dir),
                  events=events)
        try:
            with open(os.path.join(stage_dir, "infos.json")) as f:
                infos = json.load(f)
            print(f"=== {tag} done: best {infos.get('best_score')} @ step "
                  f"{infos.get('best_step')} ===", flush=True)
            events.emit("stage_best", tag=tag,
                        best_score=infos.get("best_score"),
                        best_step=infos.get("best_step"),
                        last_step=infos.get("last_step"))
        except (OSError, ValueError):  # report is best-effort only
            print(f"=== {tag} done ===", flush=True)

    if "xe" in stages:
        run_train_stage("xe", [
            *common, *xe_sched, *xe_floor, "--checkpoint_path", f"{ckpt}/xe",
            "--max_epochs", str(args.xe_epochs),
            "--learning_rate", args.xe_lr,
        ])

    if "wxe" in stages:
        run_train_stage("wxe", [
            *common, *xe_sched, "--checkpoint_path", f"{ckpt}/wxe",
            "--start_from", f"{ckpt}/xe",
            "--use_consensus_weights", "1",
            "--train_bcmrscores_pkl", train["consensus_pkl"],
            "--max_epochs", str(args.wxe_epochs),
            "--learning_rate", "1e-4",
        ])

    cst_common = [
        "--start_from", f"{ckpt}/wxe",
        "--use_rl", "1", "--max_patience", "0",  # full curves, no early stop
        "--device_rewards", args.device_rewards,
        "--temperature", args.cst_temperature,
        "--train_cached_tokens", train["cached_tokens"],
        "--max_epochs", str(args.cst_epochs),
        "--learning_rate", args.cst_lr,
    ]

    if "cst" in stages:
        run_train_stage("cst", [
            *common, *cst_common, "--checkpoint_path", f"{ckpt}/cst",
            "--rl_baseline", "greedy",
        ], label="greedy baseline, fused rewards")

    if "cst_scb_sample" in stages:
        run_train_stage("cst_scb_sample", [
            *common, *cst_common,
            "--checkpoint_path", f"{ckpt}/cst_scb_sample",
            "--rl_baseline", "scb-sample",
        ], label="leave-one-out baseline")

    if "cst_scb" in stages:
        run_train_stage("cst_scb", [
            *common, *cst_common, "--checkpoint_path", f"{ckpt}/cst_scb",
            "--rl_baseline", "scb-gt",
            "--train_bcmrscores_pkl", train["consensus_pkl"],
        ], label="SCB-gt baseline, fused rewards")

    if "eval" in stages:
        for stage in ("xe", "wxe", "cst", "cst_scb", "cst_scb_sample"):
            d = f"{ckpt}/{stage}"
            if not os.path.exists(os.path.join(d, "infos.json")):
                continue
            print(f"=== beam-5 eval: {stage} ===", flush=True)
            events.emit("stage_start", tag=f"eval:{stage}", stage_dir=d)
            run_stage(f"eval:{stage}", [
                sys.executable, "eval.py",
                "--checkpoint_path", d,
                "--test_feat_h5", *json.loads(val["feat_h5"]),
                "--test_label_h5", val["label_h5"],
                "--test_info_json", val["info_json"],
                "--test_cocofmt_file", val["cocofmt_json"],
                "--beam_size", "5", "--batch_size", str(args.batch_size),
                "--max_length", "30",
                "--wedge_timeout", str(args.wedge_timeout),
                "--result_file", os.path.join(args.out_dir,
                                              f"{stage}_beam5.json"),
            ], max_attempts=args.max_stage_attempts,
               wedge_poll_s=args.wedge_poll,
               max_wedge_wait_s=args.max_wedge_wait,
               timeout_s=args.eval_timeout, events=events)
    events.emit("chain_done", stages=args.stages)
    return 0


if __name__ == "__main__":
    sys.exit(main())
