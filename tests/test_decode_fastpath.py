"""Rollout fast path: chunked early-exit decode + buffer donation.

Pins the two contracts the fast path ships on (ISSUE 3):

1. ``decode_chunk > 0`` is BIT-IDENTICAL to the legacy full-length scan
   for the multinomial sampler, the mixed sampled+greedy rollout, greedy
   decode, and beam search — including a chunk that does not divide
   max_len (the overrun chunk), a batch whose rows all finish early
   (fewer executed steps), and a batch that never finishes (full length,
   same outputs).
2. Buffer donation on the 8-device CPU mesh: the donated STATE (params +
   optimizer moments, the largest live buffers) is consumed in place —
   the old state is deleted, reusing it raises, and the updated state
   threads through further steps; ``donate_batch=True`` aliases batch
   args into batch-shaped outputs where they exist and is provably
   skipped (buffer survives) where they don't — which is why the shipped
   train steps donate only the state; and the rollout->pipeline->
   grad-step ownership keeps in-flight feats alive until their grad step
   consumed them.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cst_captioning_tpu.models import CaptionModel
from cst_captioning_tpu.ops.beam import beam_search, beam_search_tokens
from cst_captioning_tpu.ops.sampling import (
    sample_captions,
    sample_tokens,
    sample_with_baseline,
)
from cst_captioning_tpu.parallel.dp import data_parallel_jit
from cst_captioning_tpu.parallel.mesh import batch_sharding, make_mesh
from cst_captioning_tpu.training.pipeline import RewardPipeline
from cst_captioning_tpu.training.state import create_train_state, make_optimizer
from cst_captioning_tpu.training.steps import (
    make_rl_grad_step,
    make_rollout_fused,
    make_xe_step,
)

VOCAB = 12
B = 3
T = 5
D = 7
MAX_LEN = 6


def make_model(decoder_type="lstm"):
    model = CaptionModel(
        vocab_size=VOCAB, embed_size=16, hidden_size=16, attn_size=16,
        use_attention=True, dropout_rate=0.0,
        decoder_type=decoder_type, num_heads=2, num_tx_layers=1,
        tx_max_len=MAX_LEN,
    )
    feats = [jnp.asarray(np.random.default_rng(0).normal(size=(B, T, D)),
                         jnp.float32)]
    labels = jnp.zeros((B, MAX_LEN), dtype=jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), feats, labels)
    return model, variables, feats


def assert_trees_equal(a, b):
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# -- bit-exactness vs the legacy scan -------------------------------------

# 2 divides MAX_LEN=6; 4 exercises the overrun chunk (padded length 8).
CHUNKS = (2, 4)


# lstm gets both chunk shapes; the transformer carry (token buffer +
# position counter) is pinned once on the harder overrun chunk — each
# combination is a fresh scan compile, and suite wall-time is budgeted.
@pytest.mark.parametrize("decoder_type,chunk",
                         [("lstm", 2), ("lstm", 4), ("transformer", 4)])
def test_chunked_sampler_bit_exact(decoder_type, chunk):
    model, variables, feats = make_model(decoder_type)
    legacy = sample_captions(model, variables, feats, jax.random.PRNGKey(1),
                             MAX_LEN, seq_per_img=2)
    chunked = sample_captions(model, variables, feats, jax.random.PRNGKey(1),
                              MAX_LEN, seq_per_img=2, decode_chunk=chunk)
    assert_trees_equal(legacy, chunked)


@pytest.mark.parametrize("chunk", (4,))  # overrun chunk; exact-division
def test_chunked_rollout_with_baseline_bit_exact(chunk):
    """The trainer's actual rollout program: multinomial rows + greedy
    baseline rows in one scan, per-row greedy flag.  (Exact-division
    chunks are covered by the sampler/beam/fused-step tests — each case
    is a fresh compile and suite wall-time is budgeted.)"""
    model, variables, feats = make_model()
    legacy = sample_with_baseline(model, variables, feats,
                                  jax.random.PRNGKey(2), MAX_LEN, 2)
    chunked = sample_with_baseline(model, variables, feats,
                                   jax.random.PRNGKey(2), MAX_LEN, 2,
                                   decode_chunk=chunk)
    assert_trees_equal(legacy, chunked)


@pytest.mark.parametrize("chunk", CHUNKS)
def test_chunked_beam_bit_exact(chunk):
    model, variables, feats = make_model()
    legacy = beam_search(model, variables, feats, beam_size=3,
                         max_len=MAX_LEN, length_norm=0.7)
    chunked = beam_search(model, variables, feats, beam_size=3,
                          max_len=MAX_LEN, length_norm=0.7,
                          decode_chunk=chunk)
    assert_trees_equal(legacy, chunked)


# -- early exit / never-finish on a controlled step -----------------------


class TableStep:
    """Deterministic decode 'model': logits from a fixed (L, V, V) table
    indexed by (step, prev token); carry counts steps.  EOS behavior is
    controlled by the table's column 0."""

    def __init__(self, vocab, table_len, eos_logit, seed=0):
        rng = np.random.default_rng(seed)
        tab = rng.normal(size=(table_len, vocab, vocab)).astype(np.float32)
        tab[:, :, 0] = eos_logit
        self.table = jnp.asarray(tab)

    def __call__(self, carry, token):
        return carry + 1, self.table[carry][token]


def test_sampler_early_exit_executes_fewer_steps():
    """All rows greedy-terminate at step 1 -> one chunk executes, outputs
    (incl. logprobs) still bit-equal to the 12-step legacy scan."""
    step = TableStep(5, 12, eos_logit=50.0)
    legacy = sample_tokens(step, jnp.zeros((), jnp.int32), 4, 12,
                           jax.random.PRNGKey(0), greedy=True,
                           return_steps=True)
    chunked = sample_tokens(step, jnp.zeros((), jnp.int32), 4, 12,
                            jax.random.PRNGKey(0), greedy=True,
                            decode_chunk=4, return_steps=True)
    assert_trees_equal(legacy[:2], chunked[:2])
    assert int(legacy[2]) == 12
    assert int(chunked[2]) == 4          # one chunk, not max_len


def test_sampler_never_finishes_runs_full_length():
    """EOS impossible -> every chunk runs; executed == max_len even with
    an overrun chunk (5 does not divide 12), outputs bit-equal."""
    step = TableStep(5, 15, eos_logit=-1e9, seed=1)
    legacy = sample_tokens(step, jnp.zeros((), jnp.int32), 4, 12,
                           jax.random.PRNGKey(3), return_steps=True)
    chunked = sample_tokens(step, jnp.zeros((), jnp.int32), 4, 12,
                            jax.random.PRNGKey(3), decode_chunk=5,
                            return_steps=True)
    assert_trees_equal(legacy[:2], chunked[:2])
    assert int(legacy[2]) == 12
    assert int(chunked[2]) == 12
    # nothing terminated: every row is full-length non-zero tokens
    assert (np.asarray(chunked[0]) != 0).all()


def test_beam_early_exit_and_never_finish():
    eos = TableStep(5, 15, eos_logit=50.0)
    legacy = beam_search_tokens(eos, jnp.zeros((), jnp.int32), batch=2,
                                beam_size=3, max_len=12, return_steps=True)
    chunked = beam_search_tokens(eos, jnp.zeros((), jnp.int32), batch=2,
                                 beam_size=3, max_len=12, decode_chunk=4,
                                 return_steps=True)
    assert_trees_equal(legacy[:3], chunked[:3])
    assert int(chunked[3]) == 4 and int(legacy[3]) == 12

    never = TableStep(5, 15, eos_logit=-1e9, seed=2)
    legacy = beam_search_tokens(never, jnp.zeros((), jnp.int32), batch=2,
                                beam_size=3, max_len=12, return_steps=True)
    chunked = beam_search_tokens(never, jnp.zeros((), jnp.int32), batch=2,
                                 beam_size=3, max_len=12, decode_chunk=5,
                                 return_steps=True)
    assert_trees_equal(legacy[:3], chunked[:3])
    assert int(chunked[3]) == 12


# -- fused CST step: chunked == legacy end to end -------------------------


def test_fused_cst_step_chunked_matches_legacy():
    from cst_captioning_tpu.training.device_rewards import build_device_tables
    from cst_captioning_tpu.training.steps import make_fused_cst_step

    words = ["a", "man", "is", "cooking", "dog", "runs", "the", "park"]
    w2i = {w: i + 1 for i, w in enumerate(words)}
    rng = np.random.default_rng(4)
    refs = {f"v{v}": [" ".join(rng.choice(words, 5)) for _ in range(3)]
            for v in range(4)}
    model = CaptionModel(vocab_size=len(words) + 1, embed_size=16,
                         hidden_size=16, attn_size=16, dropout_rate=0.0)
    tx, _ = make_optimizer(learning_rate=1e-2, grad_clip=5.0)
    state = create_train_state(model, jax.random.PRNGKey(0), [(3, 8)],
                               8, 2, tx, batch_size=4)
    feats = [jax.random.normal(jax.random.PRNGKey(1), (4, 3, 8))]
    corpus, tables, video_row = build_device_tables(refs, w2i)
    vix = np.asarray([video_row[v] for v in refs], np.int32)
    key = jax.random.PRNGKey(9)

    legacy = jax.jit(make_fused_cst_step(model, 8, 2, corpus, tables))
    chunked = jax.jit(make_fused_cst_step(model, 8, 2, corpus, tables,
                                          decode_chunk=3))
    s_legacy, m_legacy = legacy(state, feats, vix, key)
    s_chunked, m_chunked = chunked(state, feats, vix, key)
    assert_trees_equal(s_legacy.params, s_chunked.params)
    np.testing.assert_array_equal(np.asarray(m_legacy["loss"]),
                                  np.asarray(m_chunked["loss"]))
    assert float(m_legacy["rollout_steps"]) == 8.0
    assert 0 < float(m_chunked["rollout_steps"]) <= 8.0


# -- buffer donation under the 8-device mesh ------------------------------


def _xe_setup(mesh):
    model = CaptionModel(vocab_size=VOCAB, embed_size=16, hidden_size=16,
                         attn_size=16, dropout_rate=0.0)
    tx, _ = make_optimizer(learning_rate=1e-2)
    state = create_train_state(model, jax.random.PRNGKey(0), [(T, D)],
                               MAX_LEN, 1, tx, batch_size=8)
    sh = batch_sharding(mesh)
    feats = [jax.device_put(
        np.random.default_rng(0).normal(size=(8, T, D)).astype(np.float32),
        sh)]
    labels = jax.device_put(
        np.random.default_rng(1).integers(0, VOCAB, (8, MAX_LEN))
        .astype(np.int32), sh)
    weights = jax.device_put(np.ones((8,), np.float32), sh)
    return model, state, feats, labels, weights


def test_state_donation_consumes_old_state_on_mesh():
    """The big donation: the state (params + optimizer moments) aliases
    into the updated state.  Old state deleted, reuse raises, update
    threads through — and the numbers match an undonated reference."""
    mesh = make_mesh(jax.devices()[:8])
    model, state, feats, labels, weights = _xe_setup(mesh)
    raw = make_xe_step(model, 1)
    rng = jax.random.PRNGKey(0)

    plain = data_parallel_jit(raw, mesh, batch_argnums=(1, 2, 3),
                              donate_argnums=())
    ref_state, m_ref = plain(state, feats, labels, weights, rng)
    assert not jax.tree_util.tree_leaves(state.params)[0].is_deleted()

    donating = data_parallel_jit(raw, mesh, batch_argnums=(1, 2, 3),
                                 donate_argnums=(0,))
    new_state, m = donating(state, feats, labels, weights, rng)
    np.testing.assert_array_equal(np.asarray(m["loss"]),
                                  np.asarray(m_ref["loss"]))
    # donated and undonated programs compile to different XLA buffer
    # assignments, so tight-allclose (not bitwise) is the right contract
    for a, b in zip(jax.tree_util.tree_leaves(ref_state.params),
                    jax.tree_util.tree_leaves(new_state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)
    # the donated state was consumed in place
    assert all(l.is_deleted()
               for l in jax.tree_util.tree_leaves(state.params))
    with pytest.raises(RuntimeError):
        np.asarray(jax.tree_util.tree_leaves(state.params)[0])
    # batch args were NOT donated (no batch-shaped output to alias onto;
    # the trainer deliberately leaves donate_batch off — see dp.py)
    assert not labels.is_deleted()
    # the updated state keeps training
    _, m2 = donating(new_state, feats, labels, weights, rng)
    assert np.isfinite(float(m2["loss"]))


def test_donate_batch_aliases_only_matching_outputs():
    """donate_batch contract: a batch arg aliases into a batch-shaped
    output of the same shape/dtype (buffer consumed); one without a
    matching output survives — donation can never invalidate a buffer a
    program could not reuse."""
    mesh = make_mesh(jax.devices()[:8])
    sh = batch_sharding(mesh)

    def transform(_state, tokens, scale):
        return (tokens * 2).astype(tokens.dtype), scale.sum()

    fn = data_parallel_jit(transform, mesh, batch_argnums=(1, 2),
                           donate_argnums=(), donate_batch=True,
                           out_batch_tree=(True, False))
    tokens = jax.device_put(np.arange(48, dtype=np.int32).reshape(8, 6), sh)
    scale = jax.device_put(np.ones((8,), np.float32), sh)
    out, s = fn(jnp.zeros(()), tokens, scale)
    np.testing.assert_array_equal(np.asarray(out),
                                  np.arange(48).reshape(8, 6) * 2)
    assert tokens.is_deleted()       # aliased into `out`
    assert not scale.is_deleted()    # only output is replicated: skipped


def test_rl_pipeline_keeps_inflight_feats_alive():
    """Host-path ownership at depth 2 on the mesh: the rollout donates
    nothing, so feats stay readable while their grad step is still
    pending; every step completes exactly once through the real
    RewardPipeline with the donated-state grad step."""
    mesh = make_mesh(jax.devices()[:8])
    model, state, *_ = _xe_setup(mesh)
    rollout = data_parallel_jit(
        make_rollout_fused(model, MAX_LEN, 1, decode_chunk=2),
        mesh, batch_argnums=(1,), donate_argnums=(),
        out_batch_tree=(True, True))
    rl_step = data_parallel_jit(
        make_rl_grad_step(model, 1), mesh, batch_argnums=(1, 2, 3),
        donate_argnums=(0,))
    sh = batch_sharding(mesh)
    rng = np.random.default_rng(7)

    def fresh_feats():
        return [jax.device_put(
            rng.normal(size=(8, T, D)).astype(np.float32), sh)]

    pipe = RewardPipeline(
        rollout, rl_step,
        lambda ctx, s, g: (np.ones(s.shape[0], np.float32), {}), depth=2)
    batches = [fresh_feats() for _ in range(4)]
    done = 0
    for i, feats in enumerate(batches):
        state, completed = pipe.push(state, feats, jax.random.PRNGKey(i),
                                     jax.random.PRNGKey(100 + i), i)
        done += len(completed)
        # in-flight feats must remain readable until their grad step runs
        for pending in pipe._pending:
            assert not pending[2][0].is_deleted()
            np.asarray(pending[2][0])
    state, completed = pipe.drain(state)
    done += len(completed)
    assert done == 4
    assert len(pipe) == 0
