"""End-to-end stage pipeline on synthetic data: XE -> WXE -> CST -> eval.

The CPU-mesh analogue of driver config 1 (SURVEY.md §4, §6): tiny synthetic
HDF5 fixture, real Trainer/CLI surfaces, all three training regimes chained
via --start_from, then checkpoint eval with beam search.
"""

import json
import os

import numpy as np
import pytest

from cst_captioning_tpu.data.synthetic import SyntheticSpec, generate
from cst_captioning_tpu.opts import parse_opts
from cst_captioning_tpu.training.trainer import Trainer

pytestmark = pytest.mark.e2e


@pytest.fixture(scope="module")
def data(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("e2e"))
    spec = SyntheticSpec(num_videos=8, captions_per_video=4, max_len=12,
                         feat_dims=(16, 8), feat_times=(3, 1))
    train = generate(root, "train", spec)
    from cst_captioning_tpu.data.vocab import load_vocab
    vocab = load_vocab(train["vocab_json"])
    val_spec = SyntheticSpec(num_videos=4, captions_per_video=4, max_len=12,
                             feat_dims=(16, 8), feat_times=(3, 1))
    val = generate(root, "val", val_spec, vocab=vocab)
    return {"root": root, "train": train, "val": val}


def base_args(data, ckpt_dir, **over):
    t, v = data["train"], data["val"]
    args = {
        "--train_feat_h5": json.loads(t["feat_h5"]),
        "--train_label_h5": [t["label_h5"]],
        "--train_info_json": [t["info_json"]],
        "--train_cocofmt_file": [t["cocofmt_json"]],
        "--val_feat_h5": json.loads(v["feat_h5"]),
        "--val_label_h5": [v["label_h5"]],
        "--val_info_json": [v["info_json"]],
        "--val_cocofmt_file": [v["cocofmt_json"]],
        "--checkpoint_path": [ckpt_dir],
        "--batch_size": ["4"],
        "--seq_per_img": ["2"],
        "--rnn_size": ["32"],
        "--input_encoding_size": ["16"],
        "--att_size": ["16"],
        "--drop_prob": ["0.0"],
        "--max_epochs": ["2"],
        "--learning_rate": ["0.01"],
        "--max_length": ["12"],
        "--log_every": ["1"],
        "--fast_val": ["1"],
        "--max_patience": ["0"],
        "--seed": ["0"],
    }
    args.update({k: [str(x) for x in v] for k, v in over.items()})
    return flatten_argv(args)


def flatten_argv(args: dict) -> list:
    """{--flag: [values]} -> flat argv list (shared by every opts-driven
    test in this module)."""
    flat = []
    for k, vals in args.items():
        flat.append(k)
        flat.extend(vals)
    return flat


def run_stage(data, ckpt_dir, **over):
    opt = parse_opts(base_args(data, ckpt_dir, **over))
    trainer = Trainer(opt)
    try:
        return trainer.train()
    finally:
        trainer.close()


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cli_env():
    from conftest import CACHE_DIR

    env = dict(os.environ)
    env.update(PYTHONPATH="", JAX_PLATFORMS="cpu")
    env.setdefault("JAX_COMPILATION_CACHE_DIR", CACHE_DIR)
    return env


def run_stage_cli(data, ckpt_dir, **over):
    """``run_stage``'s production twin: the stage runs as a ``train.py``
    subprocess (one process per stage — scale_chain's shape) and returns
    the parsed summary JSON line.  Used for every stage that RESTORES a
    checkpoint (``--start_from`` warm start / auto-resume): in-process
    orbax restore is this environment's documented native instability
    (RESILIENCE.md) — at the previous HEAD a fired defect SIGABRT'd the
    whole pytest process mid-module, killing every test after it.

    The defect also fires INSIDE a fresh child (quantified in
    RESILIENCE.md): signal death in tensorstore (negative returncode —
    SKIPPED with the evidence in the skip message), or a silently
    garbled restored step scalar — which the trainer's host-side control
    plane no longer consumes (it logs and loops on the checkpoint
    directory's verified step, not a device fetch), so it cannot alter a
    child's control flow here; the device-scalar form is pinned by
    test_cst_resume_continues_rng_stream's contained child instead.  The
    "resumed from step N" log is therefore host-vs-host bookkeeping: a
    child that logs a different step than the infos.json the parent read
    is a real resume regression (or an un-injected integrity walk-back)
    and FAILS.  Any other child failure is a real regression and fails."""
    import subprocess
    import sys as _sys

    expected_resume = None
    infos_path = os.path.join(ckpt_dir, "infos.json")
    if os.path.exists(infos_path):  # host-side truth the restore must match
        with open(infos_path) as f:
            expected_resume = json.load(f).get("last_step")
    proc = subprocess.run(
        [_sys.executable, os.path.join(REPO, "train.py"),
         *base_args(data, ckpt_dir, **over)],
        capture_output=True, text=True, timeout=420, env=_cli_env(),
        cwd=REPO,
    )
    if proc.returncode < 0:
        pytest.skip("documented native restore instability (RESILIENCE.md):"
                    f" train.py child died with signal {-proc.returncode}; "
                    f"stderr tail: {proc.stderr.strip()[-160:]}")
    assert proc.returncode == 0, proc.stderr[-3000:]
    if expected_resume is not None:
        assert f"resumed from step {expected_resume} " in proc.stderr, (
            f"child did not resume from the on-disk step {expected_resume}"
            f" (host-side bookkeeping regression); log tail: "
            f"{proc.stderr.strip()[-400:]}")
    for line in reversed(proc.stdout.splitlines()):
        if line.strip().startswith("{"):
            return json.loads(line)
    raise AssertionError(f"no summary JSON from train.py: {proc.stdout!r}")


def run_eval_cli(argv):
    """eval.py as a subprocess -> returncode (same restore-containment
    rationale as run_stage_cli; eval restores the best checkpoint)."""
    import subprocess
    import sys as _sys

    proc = subprocess.run(
        [_sys.executable, os.path.join(REPO, "eval.py"), *argv],
        capture_output=True, text=True, timeout=420, env=_cli_env(),
        cwd=REPO,
    )
    if proc.returncode != 0:
        print(proc.stderr[-3000:])
    return proc.returncode


def test_full_pipeline(data, tmp_path_factory):
    out = str(tmp_path_factory.mktemp("ckpts"))
    xe_dir = os.path.join(out, "xe")
    wxe_dir = os.path.join(out, "wxe")
    cst_dir = os.path.join(out, "cst")

    # -- XE pretrain -------------------------------------------------------
    xe = run_stage(data, xe_dir)
    assert xe["best_score"] is not None
    assert os.path.exists(os.path.join(xe_dir, "infos.json"))
    assert xe["last_step"] == 4  # 8 videos / batch 4 * 2 epochs

    # -- WXE warm-start (subprocess: restore-bearing; run_stage_cli) -------
    wxe = run_stage_cli(
        data, wxe_dir,
        **{"--start_from": [xe_dir],
           "--train_bcmrscores_pkl": [data["train"]["consensus_pkl"]],
           "--use_consensus_weights": ["1"],
           "--max_epochs": ["1"]},
    )
    assert wxe["best_score"] is not None

    # -- CST / REINFORCE (greedy + SCB baselines share the stage code) -----
    cst = run_stage_cli(
        data, cst_dir,
        **{"--start_from": [wxe_dir],
           "--use_rl": ["1"],
           "--rl_baseline": ["greedy"],
           "--train_cached_tokens": [data["train"]["cached_tokens"]],
           "--max_epochs": ["1"],
           "--learning_rate": ["0.0005"]},
    )
    assert cst["best_score"] is not None
    assert np.isfinite(cst["best_score"])

    # -- checkpoint eval via the eval.py surface ---------------------------
    result_file = os.path.join(out, "scores.json")
    t = data["val"]  # reuse val artifacts as a "test" split
    rc = run_eval_cli([
        "--checkpoint_path", cst_dir,
        "--test_feat_h5", *json.loads(t["feat_h5"]),
        "--test_label_h5", t["label_h5"],
        "--test_info_json", t["info_json"],
        "--test_cocofmt_file", t["cocofmt_json"],
        "--beam_size", "2",
        "--batch_size", "4",
        "--max_length", "12",
        "--result_file", result_file,
    ])
    assert rc == 0
    with open(result_file) as f:
        blob = json.load(f)
    assert "CIDEr" in blob["scores"]
    assert len(blob["predictions"]) == 4  # deduped to the split's videos


def test_transformer_decoder_stage(data, tmp_path_factory):
    """Driver config 5: Transformer-decoder swap behind the same CLI."""
    out = str(tmp_path_factory.mktemp("tx"))
    ckpt = os.path.join(out, "tx_xe")
    res = run_stage(
        data, ckpt,
        **{"--model_type": ["transformer"],
           "--num_heads": ["2"], "--num_tx_layers": ["2"],
           "--max_epochs": ["1"]},
    )
    assert res["best_score"] is not None

    # RL stage + beam eval must also work on the transformer carry
    # (subprocess: restore-bearing — see run_stage_cli)
    res_rl = run_stage_cli(
        data, os.path.join(out, "tx_cst"),
        **{"--model_type": ["transformer"],
           "--num_heads": ["2"], "--num_tx_layers": ["2"],
           "--start_from": [ckpt],
           "--use_rl": ["1"], "--max_epochs": ["1"]},
    )
    assert res_rl["best_score"] is not None

    t = data["val"]
    rc = run_eval_cli([
        "--checkpoint_path", ckpt,
        "--test_feat_h5", *json.loads(t["feat_h5"]),
        "--test_label_h5", t["label_h5"],
        "--test_info_json", t["info_json"],
        "--test_cocofmt_file", t["cocofmt_json"],
        "--beam_size", "2", "--batch_size", "4", "--max_length", "12",
    ])
    assert rc == 0


def test_cst_resume_continues_rng_stream(data, tmp_path_factory):
    """A CST run resumed from a recovery checkpoint must continue the
    rollout key stream from the restored step, not replay the multinomial
    draws of steps it already trained on (round-3 resume fix).

    The resume half runs in a FRESH subprocess: cross-process resume is
    the production path (scale_chain's wedge recovery, any restart), and
    a contained child also protects the rest of the suite from this CPU
    stack's documented native restore instability (RESILIENCE.md) — the
    in-process form of this test aborted the whole pytest run 5/5 at the
    previous HEAD (SIGABRT in tensorstore), losing every test after it.
    In this environment even a fresh-process orbax restore of a
    VERIFIED-GOOD checkpoint nondeterministically garbles the restored
    step scalar (observed 0 and 21 for a stored 2 across runs of
    identical code) or heap-corrupts ("malloc(): largebin ...
    corrupted"); the checkpoint contents are asserted host-side either
    way, and the run is SKIPPED (not failed) only when the child dies
    with that documented signature."""
    import subprocess
    import sys as _sys

    out = str(tmp_path_factory.mktemp("resume"))
    ckpt = os.path.join(out, "cst")
    common = {"--use_rl": ["1"], "--save_every_steps": ["1"],
              "--max_epochs": ["2"]}
    run_stage(data, ckpt, **{**common, "--max_epochs": ["1"]})  # epoch 1

    # Host-side (orbax-free) half of the contract: the stage committed a
    # verified step-2 checkpoint with the bookkeeping resume reads.
    with open(os.path.join(ckpt, "infos.json")) as f:
        infos = json.load(f)
    assert infos["last_step"] == 2
    assert os.path.exists(os.path.join(ckpt, "2", "manifest.json"))

    child = """
import json, sys
sys.path.insert(0, {repo!r})
from cst_captioning_tpu.opts import parse_opts
from cst_captioning_tpu.training.trainer import Trainer

opt = parse_opts(json.loads(sys.argv[1]))
tr = Trainer(opt)
try:
    restored = int(tr.state.step)
    if restored != 2:
        print("RESTORE_GARBLED step=%d" % restored)
        sys.exit(3)
    assert tr._rl_dispatch_step == 2, (
        "rollout key stream restarted from 0 on resume")
    res = tr.train()
    assert res["last_step"] == 4, res
finally:
    tr.close()
print("RESUME_OK")
""".format(repo=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    proc = subprocess.run(
        [_sys.executable, "-c", child,
         json.dumps(base_args(data, ckpt, **common))],
        capture_output=True, text=True, timeout=420, env=_cli_env(),
    )
    if proc.returncode == 0:
        assert "RESUME_OK" in proc.stdout
        return
    # Known native-instability signatures: negative rc = signal death
    # (SIGABRT/SIGSEGV inside tensorstore), rc 3 = the garbled-scalar
    # read of a checkpoint this test just PROVED correct on disk.
    # Anything else is a real resume regression and fails.
    if proc.returncode < 0 or "RESTORE_GARBLED" in proc.stdout:
        pytest.skip(
            "documented native restore instability (RESILIENCE.md): "
            f"child rc={proc.returncode} {proc.stdout.strip()[-80:]}")
    raise AssertionError(proc.stderr[-3000:])


def test_early_stop_patience_survives_resume(data, tmp_path_factory):
    """Early-stop bookkeeping is part of the checkpoint: a run interrupted
    mid-plateau must fire early stop at the same epoch as the uninterrupted
    twin (round-3 weak #4 — patience used to reset to 0 on every resume, so
    a run crashing each epoch could never early-stop)."""
    out = str(tmp_path_factory.mktemp("patience"))
    # lr 0 -> params frozen -> the val metric is identical every epoch, so
    # every epoch after the first is plateau; patience 2 stops after epoch 3
    # (bpe = 8 videos / batch 4 = 2 -> stop at step 6).
    common = {"--learning_rate": ["0.0"], "--max_patience": ["2"]}

    solid = run_stage(data, os.path.join(out, "solid"),
                      **{**common, "--max_epochs": ["6"]})
    assert solid["last_step"] == 6, "uninterrupted twin must stop after epoch 3"

    # interrupted twin: "crash" after epoch 2 (one plateau epoch recorded)
    ckpt = os.path.join(out, "interrupted")
    run_stage(data, ckpt, **{**common, "--max_epochs": ["2"]})
    with open(os.path.join(ckpt, "infos.json")) as f:
        assert json.load(f)["patience"] == 1
    # resume (subprocess: restore-bearing — see run_stage_cli): restored
    # patience=1 means ONE more flat epoch fires the stop at the exact
    # step the uninterrupted twin stopped
    res = run_stage_cli(data, ckpt, **{**common, "--max_epochs": ["6"]})
    assert res["last_step"] == solid["last_step"] == 6
    # re-running an already-early-stopped stage must be a NO-OP: zero
    # extra epochs, not one noisy epoch that could resurrect the run
    rerun = run_stage_cli(data, ckpt, **{**common, "--max_epochs": ["6"]})
    assert rerun["last_step"] == 6, "stopped stage trained extra epochs"
    assert rerun["best_score"] == res["best_score"]


def test_min_epochs_floors_early_stop(data, tmp_path_factory):
    """--min_epochs keeps patience from ending a run while val scores are
    still in the early all-tie regime (observed live at probe scale:
    4 steps/epoch, val CIDEr ties at ~0, patience fired at epoch 18 of a
    run that converges by 150).  The floor gates the STOP only — the
    patience counter itself keeps accumulating."""
    out = str(tmp_path_factory.mktemp("minep"))
    # lr 0 -> permanent plateau: patience 2 alone stops after epoch 3.
    common = {"--learning_rate": ["0.0"], "--max_patience": ["2"]}

    floored = run_stage(data, os.path.join(out, "floored"),
                        **{**common, "--max_epochs": ["6"],
                           "--min_epochs": ["5"]})
    # bpe = 2 (8 videos / batch 4): stop fires at the first boundary at
    # or past the floor — epoch 5, step 10 — not epoch 3, step 6.
    assert floored["last_step"] == 10

    # A stopped stage below the floor is NOT no-op'd on rerun with a
    # raised floor: resume trains to the floor, then stops.
    ckpt = os.path.join(out, "resume")
    run_stage(data, ckpt, **{**common, "--max_epochs": ["4"]})
    # subprocess: restore-bearing resume — see run_stage_cli
    res = run_stage_cli(data, ckpt, **{**common, "--max_epochs": ["8"],
                                       "--min_epochs": ["6"]})
    assert res["last_step"] == 12  # epoch 6: floor reached, stop fires


def test_long_feature_stream_transformer(tmp_path_factory):
    """Config-5 shape check (SURVEY §6): minutes-long feature streams
    (T=192 frames) through attention-over-time, both decoders, without
    pooling away the temporal axis."""
    import json as _json

    root = str(tmp_path_factory.mktemp("anet"))
    spec = SyntheticSpec(num_videos=4, captions_per_video=2, max_len=12,
                         feat_dims=(24,), feat_times=(192,))
    art = generate(root, "train", spec)
    for model_type in ("lstm", "transformer"):
        opt_args = {
            "--train_feat_h5": _json.loads(art["feat_h5"]),
            "--train_label_h5": [art["label_h5"]],
            "--train_info_json": [art["info_json"]],
            "--checkpoint_path": [os.path.join(root, f"ck_{model_type}")],
            "--batch_size": ["2"], "--seq_per_img": ["2"],
            "--rnn_size": ["32"], "--input_encoding_size": ["16"],
            "--att_size": ["16"], "--model_type": [model_type],
            "--num_heads": ["2"], "--num_tx_layers": ["2"],
            "--max_epochs": ["1"], "--max_length": ["12"],
            "--log_every": ["1"], "--seed": ["0"],
        }
        from cst_captioning_tpu.opts import parse_opts
        from cst_captioning_tpu.training.trainer import Trainer

        tr = Trainer(parse_opts(flatten_argv(opt_args)))
        try:
            res = tr.train()
            assert res["last_step"] == 2
        finally:
            tr.close()


def test_manet_fusion_stage(data, tmp_path_factory):
    """Modality-attention ('manet') variant through the CLI surface."""
    out = str(tmp_path_factory.mktemp("manet"))
    res = run_stage(
        data, os.path.join(out, "manet_xe"),
        **{"--fusion_type": ["manet"], "--max_epochs": ["1"]},
    )
    assert res["best_score"] is not None


def test_fast_val_with_non_cider_metric(data, tmp_path_factory):
    """--fast_val must still score the selection metric: selecting on
    METEOR while fast_val only computed CIDEr used to zero every epoch's
    score, so best never improved and early stop fired blind."""
    out = str(tmp_path_factory.mktemp("fastval"))
    res = run_stage(
        data, os.path.join(out, "meteor_sel"),
        **{"--fast_val": ["1"], "--eval_metric": ["METEOR"],
           "--max_epochs": ["1"]},
    )
    val = res["history"]["val"][-1]
    # the approximation is never published under the bare key METEOR
    # (VERDICT r3 #4) — selection maps to the _approx column
    assert "METEOR" not in val
    assert "METEOR_approx" in val, "fast_val dropped the selection metric"
    assert res["best_score"] == pytest.approx(val["METEOR_approx"])
    assert res["best_score"] > 0.0, "METEOR selection stuck at zero"


def test_unknown_eval_metric_fails_fast(data, tmp_path_factory):
    out = str(tmp_path_factory.mktemp("badmetric"))
    with pytest.raises(ValueError, match="eval_metric"):
        run_stage(data, os.path.join(out, "bad"),
                  **{"--eval_metric": ["SPICE"]})


@pytest.mark.parametrize("device_rewards", ["0", "1"])
def test_bad_cached_tokens_pickle_fails_loudly(data, tmp_path_factory,
                                               device_rewards):
    """A corrupt --train_cached_tokens must abort the run on BOTH reward
    paths, not silently train on a refs-derived df."""
    out = str(tmp_path_factory.mktemp("badpkl"))
    bad = os.path.join(out, "corrupt.pkl")
    with open(bad, "wb") as f:
        f.write(b"not a pickle")
    with pytest.raises(Exception):
        run_stage(data, os.path.join(out, "cst"),
                  **{"--use_rl": ["1"], "--device_rewards": [device_rewards],
                     "--train_cached_tokens": [bad],
                     "--max_epochs": ["1"]})


def test_device_feats_training_is_identical(data, tmp_path_factory):
    """--device_feats pins features in HBM and gathers by video_ix inside
    jit; with the same seed (f32, no host casting) it must produce exactly
    the training trajectory of the host-streamed path — XE and fused CST."""
    out = str(tmp_path_factory.mktemp("devfeats"))

    def run(tag, extra):
        opt = parse_opts(base_args(
            data, os.path.join(out, tag),
            **{"--max_epochs": ["1"], **extra}))
        tr = Trainer(opt)
        try:
            tr.train()
            return jax.tree_util.tree_map(np.asarray, tr.state.params)
        finally:
            tr.close()

    import jax

    stages = (
        ("xe", {}),
        ("fused", {"--use_rl": ["1"]}),
        # host-reward pipeline: rollout/grad consume the video-ix wrappers
        ("hostrl", {"--use_rl": ["1"], "--device_rewards": ["0"]}),
    )
    for tag, stage_args in stages:
        host = run(f"host_{tag}", {**stage_args, "--device_feats": ["0"]})
        dev = run(f"dev_{tag}", {**stage_args, "--device_feats": ["1"]})
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(a, b), host, dev)


def test_device_feats_budget_guard(data, tmp_path_factory):
    """--device_feats replicates the FULL feature table on every device;
    over-budget tables must fail at startup with the size in the message,
    not as an opaque device OOM mid-epoch (ADVICE r3)."""
    out = str(tmp_path_factory.mktemp("dfguard"))
    opt = parse_opts(base_args(
        data, out,
        **{"--device_feats": ["1"], "--device_feats_max_gb": ["1e-9"]}))
    with pytest.raises(ValueError, match="PER DEVICE"):
        Trainer(opt)


@pytest.mark.parametrize("bf16", [False, True])
def test_chunked_table_upload_equals_direct(bf16):
    """The --device_feats upload is chunked (bounded transfer size / host
    RAM; a monolithic device_put wedged a remote tunnel) — the assembled
    device tables must equal a direct whole-array device_put exactly, for
    any chunk boundary including a ragged tail."""
    import jax

    from cst_captioning_tpu.parallel.mesh import (
        make_mesh, replicated_sharding)
    from cst_captioning_tpu.training.trainer import upload_table_chunked

    n, shapes = 13, [(4, 32), (1, 8)]
    rng = np.random.default_rng(0)
    full = [rng.standard_normal((n, t, d)).astype(np.float32)
            for t, d in shapes]
    reads = []

    def read_fn(ix):
        reads.append(len(ix))
        return [a[ix] for a in full]

    dtype = None
    if bf16:
        import ml_dtypes

        dtype = ml_dtypes.bfloat16
    mesh = make_mesh()
    sharding = replicated_sharding(mesh)
    # ~3 rows of the larger modality per chunk -> 5 chunks, ragged tail
    row_mb = max(t * d for t, d in shapes) * 4 / 1e6
    tables = upload_table_chunked(read_fn, n, shapes, dtype, sharding,
                                  upload_mb=3 * row_mb)
    assert len(reads) > 2 and sum(reads) == n
    for m, a in enumerate(full):
        want = a.astype(dtype) if dtype is not None else a
        got = np.asarray(tables[m]).astype(np.float32)
        np.testing.assert_array_equal(got, want.astype(np.float32))
        assert str(tables[m].dtype) == ("bfloat16" if bf16 else "float32")


def test_default_rl_path_is_fused(data, tmp_path_factory):
    """The shipped CST default is the fused on-device reward path
    (opts.DEFAULT_DEVICE_REWARDS = 1): a plain --use_rl 1 run must build
    the fused step and no host reward pipeline."""
    out = str(tmp_path_factory.mktemp("defpath"))
    opt = parse_opts(base_args(data, os.path.join(out, "cst"),
                               **{"--use_rl": ["1"]}))
    assert opt.device_rewards == 1
    tr = Trainer(opt)
    try:
        assert tr._fused_step is not None
        assert tr._rl_pipeline is None
        assert tr.reward_computer is None
    finally:
        tr.close()


def test_cst_overlap_depths(data, tmp_path_factory):
    """The overlapped reward pipeline (--overlap_rewards k) must drain at
    epoch boundaries: every dispatched rollout gets its grad step, so
    state.step ends at batches-per-epoch regardless of depth.  Depth 0 is
    the strict serial reference semantics."""
    out = str(tmp_path_factory.mktemp("depths"))
    for depth in (0, 2):
        res = run_stage(
            data, os.path.join(out, f"d{depth}"),
            **{"--use_rl": ["1"], "--device_rewards": ["0"],
               "--overlap_rewards": [str(depth)],
               "--max_epochs": ["1"]},
        )
        assert res["last_step"] == 2, f"depth {depth} lost pipelined steps"
        assert res["best_score"] is not None


def test_device_rewards_stage(data, tmp_path_factory):
    """--device_rewards 1: the fused on-device CIDEr-D CST step through the
    full CLI surface, for every baseline variant."""
    out = str(tmp_path_factory.mktemp("devrl"))
    res = run_stage(
        data, os.path.join(out, "greedy"),
        **{"--use_rl": ["1"], "--device_rewards": ["1"],
           "--train_cached_tokens": [data["train"]["cached_tokens"]],
           "--max_epochs": ["1"]},
    )
    assert res["best_score"] is not None
    assert res["last_step"] == 2
    res_scb = run_stage(
        data, os.path.join(out, "scb"),
        **{"--use_rl": ["1"], "--device_rewards": ["1"],
           "--rl_baseline": ["scb-sample"], "--seq_per_img": ["4"],
           "--max_epochs": ["1"]},
    )
    assert res_scb["best_score"] is not None
    res_gt = run_stage(
        data, os.path.join(out, "scbgt"),
        **{"--use_rl": ["1"], "--device_rewards": ["1"],
           "--rl_baseline": ["scb-gt"],
           "--train_bcmrscores_pkl": [data["train"]["consensus_pkl"]],
           "--scb_captions": ["2"], "--max_epochs": ["1"]},
    )
    assert res_gt["best_score"] is not None


def test_device_rewards_chunked_envelope(data, tmp_path_factory):
    """A micro --device_cider_chunk_mb forces the reward contraction into
    ref-axis chunks (the HBM-envelope bound); the fused stage must train
    through the full CLI surface exactly as the one-shot path does."""
    out = str(tmp_path_factory.mktemp("devrl_chunk"))
    res = run_stage(
        data, os.path.join(out, "chunked"),
        **{"--use_rl": ["1"], "--device_rewards": ["1"],
           "--device_cider_chunk_mb": ["0.0001"],
           "--max_epochs": ["1"]},
    )
    assert res["best_score"] is not None
    assert res["last_step"] == 2


def test_scb_sample_stage(data, tmp_path_factory):
    """Host-path (--device_rewards 0) SCB-sample e2e; the fused-path SCB
    variants live in test_device_rewards_stage."""
    out = str(tmp_path_factory.mktemp("scb"))
    res = run_stage(
        data, os.path.join(out, "cst_scb"),
        **{"--use_rl": ["1"], "--device_rewards": ["0"],
           "--rl_baseline": ["scb-sample"],
           "--seq_per_img": ["4"],
           "--max_epochs": ["1"]},
    )
    assert res["best_score"] is not None


def test_scb_gt_stage(data, tmp_path_factory):
    out = str(tmp_path_factory.mktemp("scbgt"))
    res = run_stage(
        data, os.path.join(out, "cst_scbgt"),
        **{"--use_rl": ["1"], "--device_rewards": ["0"],
           "--rl_baseline": ["scb-gt"],
           "--train_bcmrscores_pkl": [data["train"]["consensus_pkl"]],
           "--scb_captions": ["2"],
           "--max_epochs": ["1"]},
    )
    assert res["best_score"] is not None


def test_abort_on_negative_advantage_window(data, tmp_path_factory):
    """Opt-in unattended-chain protection (ISSUE 3 satellite): a rigged
    scb-gt consensus pickle whose baseline (100.0) towers over any sampled
    reward drives every logged advantage negative; with
    --abort_on_negative_advantage_window the stage must abort through the
    real train.py CLI with the dedicated exit code 4 (not train to the
    epoch budget, not exit 1), printing a machine-readable abort line."""
    import pickle

    out = str(tmp_path_factory.mktemp("advabort"))
    with open(data["train"]["consensus_pkl"], "rb") as f:
        cons = pickle.load(f)
    rigged_path = os.path.join(out, "rigged_consensus.pkl")
    with open(rigged_path, "wb") as f:
        pickle.dump({v: np.full(4, 100.0, np.float64) for v in cons}, f)

    import subprocess
    import sys as _sys

    argv = base_args(
        data, os.path.join(out, "cst"),
        **{"--use_rl": [1], "--device_rewards": [1],
           "--rl_baseline": ["scb-gt"],
           "--train_bcmrscores_pkl": [rigged_path],
           "--abort_on_negative_advantage_window": [1],
           # detector window = 5 logged steps; 2 steps/epoch at this
           # scale, so a 3-epoch budget proves the abort fired EARLY
           "--max_epochs": [3]},
    )
    proc = subprocess.run(
        [_sys.executable, os.path.join(REPO, "train.py"), *argv],
        capture_output=True, text=True, timeout=420, env=_cli_env(),
        cwd=REPO,
    )
    assert proc.returncode == 4, (proc.returncode, proc.stderr[-2000:])
    assert "negative_advantage_window" in proc.stdout
    # (the warn-but-continue default of the same detector is pinned by
    # test_training::TestAdvantageRegimeDetector — no second stage here)
