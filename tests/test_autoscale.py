"""Attribution-driven autoscaler + overload brownout (ISSUE 19,
SERVING.md "Autoscaling & brownout").

Fast in-process slice (tier-1, sanitizer-armed like test_supervisor):

- the decision engine against a scripted attribution feed — burst
  scales up within the fast window, a full quiet slow window scales
  down, hysteresis/cooldowns/bounds hold, flapping is damped, decode-
  driven latency does NOT scale, the idle-child ring-cumulative
  correction, the brownout ladder escalates/de-escalates on patience;
- the supervisor's grow/shrink surface with FakeChild fleets —
  add_replica spawns warm, retire_worst drains to ``retired`` with no
  incident and no restart, SIGKILL mid-retire falls through the
  crash-requeue path exactly-once, candidates exclude retiring slots;
- the brownout shed sites (deadline / parked / stream), each typed;
- the durable decisions log, counters, lifecycle events, opts flags +
  env fallbacks, arrival-shape generators, report gates, the
  durable-rename satellite, and the doc pins.

The real-subprocess burst drill through ``scripts/serve_supervisor.py
--autoscale_probe`` is marked ``slow`` and runs via
``make autoscale-chaos``.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from cst_captioning_tpu.resilience.exitcodes import EXIT_SIGKILL
from cst_captioning_tpu.serving.autoscale import (
    AUTOSCALE_COUNTERS,
    AUTOSCALE_SCHEMA,
    BROWNOUT_RUNGS,
    Autoscaler,
)
from cst_captioning_tpu.serving.bench import (
    burst_arrivals,
    diurnal_arrivals,
    make_arrivals,
    replay_arrivals,
)

from test_supervisor import (  # the shared process-fleet fakes
    FakeChild,
    FakeClock,
    build_sup,
    child_of,
    tick_until,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _lock_sanitizer(monkeypatch, tmp_path):
    """Sanitizer-armed like the supervisor suite: the autoscale state
    lock is exercised against the declared LOCK_ORDER in every test."""
    from cst_captioning_tpu.analysis import locksan

    receipt = tmp_path / "locksan_violation.json"
    monkeypatch.setenv(locksan.ENV_FLAG, "1")
    monkeypatch.setenv(locksan.ENV_RECEIPT, str(receipt))
    before = len(locksan.violations())
    yield
    after = locksan.violations()
    assert len(after) == before, f"lock-order violations: {after[before:]}"
    assert not receipt.exists(), (
        f"lock sanitizer receipt: {receipt.read_text()}")


# -- scripted decision-engine fixtures --------------------------------------


class SeriesObs:
    """A scriptable stand-in for FleetObs.series(): push one scrape
    sample per call, shaped like telemetry/fleetobs.py's rows."""

    def __init__(self):
        self._samples = []

    def series(self):
        return list(self._samples)

    def push(self, qw=0.0, dc=5.0, *, busy=True, settled=True,
             firing=False):
        self._samples.append({
            "seq": len(self._samples) + 1,
            "children": [{
                "index": 0, "state": "ok" if settled else "backoff",
                "live": True, "retiring": False,
                "inflight": 1 if busy else 0,
                "queue_depth": 1 if busy else 0,
                "attribution_p99_ms": {"queue_wait": qw, "decode": dc},
            }],
            "slo": {"firing": ["p99"] if firing else []},
        })


class CountSup:
    """Duck-typed supervisor: the autoscaler only needs the grow/shrink
    verbs and the active count."""

    def __init__(self, n=1):
        self.n = n
        self.adds = 0
        self.retires = 0

    def active_replicas(self):
        return self.n

    def add_replica(self):
        self.n += 1
        self.adds += 1
        return self.n - 1

    def retire_worst(self):
        self.n -= 1
        self.retires += 1
        return self.n


def mk_scaler(tmp_path=None, **kw):
    obs = SeriesObs()
    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 3)
    kw.setdefault("queue_hi_ms", 50.0)
    kw.setdefault("queue_lo_ms", 5.0)
    kw.setdefault("fast_samples", 3)
    kw.setdefault("slow_samples", 9)
    kw.setdefault("up_cooldown_s", 0.0)
    kw.setdefault("down_cooldown_s", 0.0)
    if tmp_path is not None:
        kw.setdefault("out_dir", str(tmp_path))
    return Autoscaler(obs, **kw), obs


# -- the decision engine ----------------------------------------------------


def test_bounds_and_hysteresis_validated():
    obs = SeriesObs()
    with pytest.raises(ValueError):
        Autoscaler(obs, min_replicas=0)
    with pytest.raises(ValueError):
        Autoscaler(obs, min_replicas=3, max_replicas=2)
    with pytest.raises(ValueError):
        Autoscaler(obs, queue_hi_ms=10.0, queue_lo_ms=10.0)


def test_burst_scales_up_within_the_fast_window(tmp_path):
    asc, obs = mk_scaler(tmp_path)
    sup = CountSup(1)
    for _ in range(3):          # exactly the fast window
        obs.push(qw=500.0)
    asc.tick(sup, now=1.0)
    assert sup.adds == 1 and sup.n == 2
    c = asc.counters()
    assert c["autoscale_scale_ups"] == 1 and c["autoscale_ticks"] == 3
    # One durable decision line, schema-stamped, with the attribution
    # evidence it acted on.
    lines = [json.loads(l) for l in
             open(tmp_path / "autoscale_decisions.jsonl")]
    assert len(lines) == 1
    rec = lines[0]
    assert rec["schema"] == AUTOSCALE_SCHEMA
    assert rec["kind"] == "autoscale_decision"
    assert rec["action"] == "scale_up" and rec["seq"] == 1
    assert rec["replicas_before"] == 1 and rec["replicas_after"] == 2
    assert rec["reason"]["queue_wait_fast_ms"] >= 50.0
    assert rec["reason"]["decode_flat"] is True
    assert rec["thresholds"]["queue_hi_ms"] == 50.0


def test_up_cooldown_damps_consecutive_scale_ups():
    asc, obs = mk_scaler(up_cooldown_s=10.0)
    sup = CountSup(1)
    for _ in range(3):
        obs.push(qw=500.0)
    asc.tick(sup, now=1.0)
    assert sup.adds == 1
    obs.push(qw=500.0)
    asc.tick(sup, now=2.0)      # still burning, but inside the cooldown
    assert sup.adds == 1
    assert asc.counters()["autoscale_holds_cooldown"] == 1
    obs.push(qw=500.0)
    asc.tick(sup, now=12.0)     # cooldown expired
    assert sup.adds == 2


def test_decode_driven_latency_does_not_scale_up():
    """queue_wait burning because DECODE got slower is not a capacity
    problem: the fast-window decode p99 outgrowing the slow baseline
    vetoes the scale-up."""
    asc, obs = mk_scaler()
    sup = CountSup(1)
    for _ in range(6):
        obs.push(qw=500.0, dc=1.0)
    for _ in range(3):
        obs.push(qw=500.0, dc=100.0)   # decode exploded in the fast window
    asc.tick(sup, now=1.0)
    assert sup.adds == 0 and not asc.decisions


def test_full_quiet_slow_window_scales_down_and_reearns(tmp_path):
    asc, obs = mk_scaler(tmp_path)
    sup = CountSup(3)
    for _ in range(9):          # the ENTIRE slow window quiet
        obs.push(qw=0.0, busy=False)
    asc.tick(sup, now=1.0)
    assert sup.retires == 1 and sup.n == 2
    # The window was cleared: 3 more quiet samples are NOT yet a full
    # slow window at the new size — no second retire.
    for _ in range(3):
        obs.push(qw=0.0, busy=False)
    asc.tick(sup, now=2.0)
    assert sup.retires == 1
    for _ in range(6):
        obs.push(qw=0.0, busy=False)
    asc.tick(sup, now=3.0)
    assert sup.retires == 2 and sup.n == 1
    acts = [d["action"] for d in asc.decisions]
    assert acts == ["scale_down", "scale_down"]


def test_down_cooldown_and_min_bound_hold():
    asc, obs = mk_scaler(down_cooldown_s=100.0, min_replicas=1)
    sup = CountSup(3)
    for _ in range(9):
        obs.push(qw=0.0, busy=False)
    asc.tick(sup, now=1.0)
    assert sup.retires == 1
    for _ in range(9):
        obs.push(qw=0.0, busy=False)
    asc.tick(sup, now=2.0)      # quiet again, but inside the cooldown
    assert sup.retires == 1
    assert asc.counters()["autoscale_holds_cooldown"] == 1
    # At min, quiet holds on the bound instead.
    asc2, obs2 = mk_scaler()
    sup2 = CountSup(1)
    for _ in range(9):
        obs2.push(qw=0.0, busy=False)
    asc2.tick(sup2, now=1.0)
    assert sup2.retires == 0
    assert asc2.counters()["autoscale_holds_bounds"] == 1


def test_firing_slo_blocks_scale_down():
    asc, obs = mk_scaler()
    sup = CountSup(2)
    for _ in range(9):
        obs.push(qw=0.0, busy=False, firing=True)
    asc.tick(sup, now=1.0)
    assert sup.retires == 0 and not asc.decisions


def test_hysteresis_band_makes_no_decision():
    asc, obs = mk_scaler()      # lo=5 < 20 < hi=50
    sup = CountSup(2)
    for _ in range(9):
        obs.push(qw=20.0)
    asc.tick(sup, now=1.0)
    assert sup.adds == 0 and sup.retires == 0 and not asc.decisions


def test_idle_child_zeroes_ring_cumulative_queue_pressure():
    """The scraped attribution p99 never decays after a burst (the ring
    is cumulative); a child with NOTHING waiting must still read as
    quiet or the fleet could never scale back down."""
    asc, obs = mk_scaler()
    sup = CountSup(2)
    for _ in range(9):
        obs.push(qw=5000.0, busy=False)   # stale burst p99, idle child
    asc.tick(sup, now=1.0)
    assert sup.retires == 1


def test_unsettled_fleet_defers_decisions():
    asc, obs = mk_scaler()
    sup = CountSup(1)
    for _ in range(3):
        obs.push(qw=500.0, settled=False)  # a spawn/backoff in flight
    asc.tick(sup, now=1.0)
    assert sup.adds == 0


def test_brownout_ladder_escalates_on_patience_and_deescalates(tmp_path):
    asc, obs = mk_scaler(tmp_path, max_replicas=2, brownout_patience=2)
    sup = CountSup(2)           # pinned at max
    for _ in range(3):
        obs.push(qw=500.0)
    asc.tick(sup, now=1.0)      # sat 1: bound hold, no rung yet
    assert asc.brownout_rung() == 0
    assert asc.counters()["autoscale_holds_bounds"] == 1
    t = 2.0
    for want_rung in (1, 2, 3):
        for _ in range(2):      # patience=2 burning evaluations per rung
            obs.push(qw=500.0)
            asc.tick(sup, now=t)
            t += 1.0
        assert asc.brownout_rung() == want_rung
    # Capped at the last rung.
    for _ in range(4):
        obs.push(qw=500.0)
        asc.tick(sup, now=t)
        t += 1.0
    assert asc.brownout_rung() == len(BROWNOUT_RUNGS)
    # Sustained calm walks back down one rung per patience window —
    # but "calm" means the FAST window stopped burning, so the burst
    # samples must flush out of it first (3 calm pushes, 1 evaluation).
    for _ in range(3):
        obs.push(qw=20.0)       # hysteresis band: calm but not "down"
    asc.tick(sup, now=t)        # calm evaluation #1
    t += 1.0
    for want_rung in (2, 2, 1, 1, 0, 0):
        obs.push(qw=20.0)
        asc.tick(sup, now=t)    # every 2nd calm evaluation de-escalates
        t += 1.0
        assert asc.brownout_rung() == want_rung
    acts = [d["action"] for d in asc.decisions]
    assert acts == ["brownout_enter"] * 3 + ["brownout_exit"] * 3
    names = [d["rung_name"] for d in asc.decisions]
    assert names == list(BROWNOUT_RUNGS) + list(reversed(BROWNOUT_RUNGS))
    c = asc.counters()
    assert c["brownout_entries"] == 3 and c["brownout_exits"] == 3
    assert sup.adds == 0        # brownout replaced growth at the bound


def test_flapping_traffic_yields_at_most_two_changes():
    """The drill's no-thrash promise: a burst that keeps flickering on
    and off inside the cooldowns produces one up and (after sustained
    quiet) one down — not a change per flicker."""
    asc, obs = mk_scaler(up_cooldown_s=30.0, down_cooldown_s=30.0)
    sup = CountSup(1)
    t = 1.0
    for flick in range(6):      # 6 on/off flickers, 1s apart
        for _ in range(3):
            obs.push(qw=500.0 if flick % 2 == 0 else 0.0,
                     busy=flick % 2 == 0)
        asc.tick(sup, now=t)
        t += 1.0
    # Sustained quiet long after the cooldown.
    for _ in range(9):
        obs.push(qw=0.0, busy=False)
    asc.tick(sup, now=t + 60.0)
    changes = sup.adds + sup.retires
    assert sup.adds == 1 and changes <= 2


def test_note_shed_status_and_registry():
    class Reg:
        def __init__(self):
            self.declared = []
            self.counts = {}

        def declare(self, *names):
            self.declared += list(names)

        def inc(self, name, n=1):
            self.counts[name] = self.counts.get(name, 0) + n

    reg = Reg()
    asc = Autoscaler(SeriesObs(), registry=reg)
    assert set(AUTOSCALE_COUNTERS) <= set(reg.declared)
    asc.note_shed("deadline")
    asc.note_shed("stream")
    assert reg.counts["brownout_shed_deadline"] == 1
    assert reg.counts["brownout_shed_stream"] == 1
    st = asc.status()
    assert st["enabled"] is True and st["rung"] == 0
    assert st["min"] == 1 and st["max"] == 4
    assert set(AUTOSCALE_COUNTERS) == set(st["counters"])


def test_decisions_emit_valid_lifecycle_events():
    from cst_captioning_tpu.telemetry.lifecycle import LifecycleTracer

    clk = FakeClock(5.0)
    lc = LifecycleTracer(clock=clk)
    asc, obs = mk_scaler()
    asc._lifecycle = lc
    sup = CountSup(1)
    for _ in range(3):
        obs.push(qw=500.0)
    asc.tick(sup, now=1.0)      # would raise on an unregistered kind
    evs = [e for e in lc.events() if e["kind"] == "autoscale_decision"]
    assert len(evs) == 1
    assert evs[0]["id"] == "autoscale:1"
    assert evs[0]["action"] == "scale_up"


# -- the supervisor's grow/shrink surface -----------------------------------


def test_add_replica_appends_and_spawns_a_warm_slot(tmp_path):
    sup, children, _ = build_sup(tmp_path, 1)
    assert sup.active_replicas() == 1
    ix = sup.add_replica()
    assert ix == 1 and sup.active_replicas() == 2
    assert len(children) == 2 and children[1].alive
    assert sup.supervisor_counters()["sup_replicas_added"] == 1
    # The new slot takes load immediately.
    got = []
    for i in range(4):
        sup.submit(i, f"v{i}", respond=got.append)
    assert len(children[0].jobs) == 2 and len(children[1].jobs) == 2


def test_retire_worst_drains_to_retired_without_incident(tmp_path):
    sup, children, _ = build_sup(tmp_path, 2)
    got = []
    for i in range(4):
        sup.submit(i, f"v{i}", respond=got.append)
    ix = sup.retire_worst()
    assert ix == 1              # tie on load -> highest index is worst
    rep = sup._replicas[1]
    assert rep.retiring and children[1].draining
    # New work routes around the retiring slot.
    sup.submit(9, "v9", respond=got.append)
    assert len(children[0].jobs) == 3 and children[1].sent[-1:] != [9]
    tick_until(sup, lambda: rep.state == "retired")
    tick_until(sup, lambda: len(got) == 5)
    # Every request answered with its real caption — the in-flight work
    # FINISHED on the draining child, nothing was requeued by the
    # scale-down itself.
    by_id = {a["id"]: a for a in got}
    assert sorted(by_id) == [0, 1, 2, 3, 9]
    for i in range(4):
        assert by_id[i]["caption"] == FakeChild.caption_for(f"v{i}")
    c = sup.supervisor_counters()
    assert c["sup_replicas_retired"] == 1
    assert c["sup_requeued"] == 0 and c["sup_replica_restarts"] == 0
    assert not sup._incidents   # a deliberate retire is not an incident
    assert sup.active_replicas() == 1
    # The retired slot never restarts.
    for _ in range(8):
        sup.tick()
    assert sup._replicas[1].state == "retired"
    assert sup._replicas[1].child is None


def test_retire_worst_refuses_to_empty_the_fleet(tmp_path):
    sup, children, _ = build_sup(tmp_path, 1)
    assert sup.retire_worst() is None
    sup2, children2, _ = build_sup(tmp_path / "b", 2)
    children2[0].die(EXIT_SIGKILL)
    sup2.tick()                 # one slot in backoff -> one candidate
    assert sup2.retire_worst() is None


def test_sigkill_mid_retire_requeues_exactly_once(tmp_path):
    """A child murdered MID-drain falls through the ordinary crash
    requeue: its in-flight work lands on a survivor, every id answered
    exactly once, bit-identical captions, slot still ends retired."""
    sup, children, _ = build_sup(tmp_path, 2)
    got = []
    sup.submit("a", "v1", respond=got.append)
    sup.submit("b", "v2", respond=got.append)
    ix = sup.retire_worst()
    assert ix == 1
    child_of(children, 1).kill()          # SIGKILL before the drain lands
    tick_until(sup, lambda: len([a for a in got
                                 if a.get("caption")]) == 2)
    by_id = {}
    for a in got:
        by_id.setdefault(a["id"], []).append(a)
    assert sorted(by_id) == ["a", "b"]
    for rid, answers in by_id.items():
        assert len(answers) == 1          # exactly once, never double
    assert by_id["a"][0]["caption"] == FakeChild.caption_for("v1")
    assert by_id["b"][0]["caption"] == FakeChild.caption_for("v2")
    c = sup.supervisor_counters()
    assert c["sup_requeued"] == 1
    assert c["sup_replicas_retired"] == 1
    assert sup._replicas[1].state == "retired"


# -- the brownout shed sites ------------------------------------------------


class StubScaler:
    """Just the rung surface the supervisor's shed sites read."""

    def __init__(self, rung=0, deadline_margin=4.0, parked_cap=0):
        self.rung = rung
        self.deadline_margin = deadline_margin
        self.parked_cap = parked_cap
        self.sheds = []

    def brownout_rung(self):
        return self.rung

    def note_shed(self, rung):
        self.sheds.append(rung)

    def tick(self, sup, now):
        pass

    def status(self):
        return {"enabled": True, "rung": self.rung}


def test_rung1_tightens_deadline_admission(tmp_path):
    """A deadline that clears the plain service floor but not the
    brownout margin is shed with its own typed reason."""
    scaler = StubScaler(rung=1, deadline_margin=4.0)
    sup, children, _ = build_sup(
        tmp_path, 2, autoscaler=scaler,
        child_kw={k: {"min_service_ms": 100.0} for k in range(2)})
    sup.tick()
    sup.tick()                  # health floors in
    got = []
    # 150ms > 100ms floor (admit normally) but < 4x100ms margin.
    sup.submit("a", "v1", respond=got.append, deadline_ms=150.0)
    assert got[-1]["error"] == "expired"
    assert got[-1]["why"] == "brownout_deadline"
    assert scaler.sheds == ["deadline"]
    assert not children[0].jobs and not children[1].jobs
    # A comfortable deadline still admits under rung 1.
    sup.submit("b", "v2", respond=got.append, deadline_ms=5000.0)
    assert children[0].jobs or children[1].jobs


def test_rung2_caps_parked_depth(tmp_path):
    scaler = StubScaler(rung=2, parked_cap=0)
    sup, children, clock = build_sup(tmp_path, 1, autoscaler=scaler)
    children[0].die(EXIT_SIGKILL)
    sup.tick()                  # no live replica: placement would park
    got = []
    sup.submit("a", "v2", respond=got.append, deadline_ms=5000.0)
    assert got[-1]["error"] == "shed"
    assert got[-1]["why"] == "brownout_parked"
    assert scaler.sheds == ["parked"]
    assert sup.supervisor_counters()["sup_parked"] == 0


def test_rung3_rejects_new_stream_ops_only(tmp_path):
    scaler = StubScaler(rung=3)
    sup, children, _ = build_sup(tmp_path, 1, autoscaler=scaler)
    got = []
    sup.submit("s", "v1", respond=got.append, stream=True)
    assert got[-1]["error"] == "shed"
    assert got[-1]["why"] == "brownout_stream" and got[-1]["final"]
    assert scaler.sheds == ["stream"]
    # Plain requests still flow at rung 3.
    sup.submit("p", "v2", respond=got.append)
    tick_until(sup, lambda: any(a.get("caption") for a in got))
    assert got[-1]["caption"] == FakeChild.caption_for("v2")


def test_snapshot_and_stats_carry_autoscale_and_retiring(tmp_path):
    scaler = StubScaler(rung=1)
    sup, children, _ = build_sup(tmp_path, 2, autoscaler=scaler)
    sup.retire_worst()
    snap = sup.scrape_snapshot()
    assert [c["retiring"] for c in snap["children"]] == [False, True]
    assert snap["fleet"]["active"] == 2
    assert snap["fleet"]["autoscale"]["enabled"] is True
    assert sup.stats()["autoscale"]["rung"] == 1
    # The health view never counts a retired slot's terminal state.
    tick_until(sup, lambda: sup._replicas[1].state == "retired")
    sup.tick()
    assert sup.health_payload()["status"] == "ok"


# -- the closed loop: Autoscaler driving a real FakeChild fleet -------------


def test_closed_loop_burst_grows_then_quiet_drains(tmp_path):
    """The in-process twin of the CLI drill: a scripted attribution
    burst makes the autoscaler grow a REAL (FakeChild) supervisor, and
    scripted quiet drains it back — at most one up and one down."""
    obs = SeriesObs()
    asc = Autoscaler(obs, min_replicas=1, max_replicas=3,
                     fast_samples=3, slow_samples=9,
                     up_cooldown_s=0.0, down_cooldown_s=0.0)
    clock = FakeClock()
    sup, children, _ = build_sup(tmp_path, 1, clock=clock,
                                 autoscaler=asc)
    for _ in range(3):
        obs.push(qw=500.0)
    sup.tick()                  # the supervisor tick runs asc.tick
    assert sup.active_replicas() == 2 and len(children) == 2
    got = []
    sup.submit("x", "v3", respond=got.append)
    for _ in range(9):
        obs.push(qw=0.0, busy=False)
    clock.advance(1.0)
    sup.tick()
    # The worst-ranked slot (the one holding the in-flight request) is
    # draining out.
    assert any(r.retiring or r.state == "retired"
               for r in sup._replicas)
    tick_until(sup, lambda: sup.active_replicas() == 1)
    tick_until(sup, lambda: got)
    assert got[-1]["caption"] == FakeChild.caption_for("v3")
    assert [d["action"] for d in asc.decisions] == \
        ["scale_up", "scale_down"]


# -- arrival shapes ---------------------------------------------------------


def test_arrival_shapes_deterministic_sorted_and_sized():
    for shape in ("poisson", "diurnal", "burst"):
        a = make_arrivals(shape, 64, 20.0, seed=3)
        b = make_arrivals(shape, 64, 20.0, seed=3)
        assert np.array_equal(a, b), shape
        assert len(a) == 64 and a[0] >= 0.0
        assert np.all(np.diff(a) >= 0.0), shape
    assert not np.array_equal(make_arrivals("burst", 64, 20.0, seed=3),
                              make_arrivals("burst", 64, 20.0, seed=4))


def test_burst_arrivals_cluster_in_the_duty_window():
    a = burst_arrivals(400, 10.0, seed=0, period_s=8.0, duty=0.25,
                       burst_factor=4.0)
    phase = np.mod(a, 8.0)
    in_burst = np.mean(phase < 2.0)     # 25% of the period
    # Expected mass in the window: 4x0.25 / (4x0.25 + 0.75) ~= 0.57,
    # vs 0.25 if the shape were flat.
    assert in_burst > 0.45


def test_diurnal_arrivals_modulate_rate():
    a = diurnal_arrivals(400, 10.0, seed=0, period_s=10.0, depth=0.9)
    phase = np.mod(a, 10.0)
    peak = np.mean((phase > 1.0) & (phase < 4.0))
    trough = np.mean((phase > 6.0) & (phase < 9.0))
    assert peak > trough                # sinusoid peak draws more


def test_replay_arrivals_roundtrip_and_errors(tmp_path):
    trace = tmp_path / "trace.jsonl"
    ts = [0.5, 0.1, 0.9, 0.3]
    trace.write_text("".join(json.dumps({"t": t}) + "\n" for t in ts))
    a = replay_arrivals(str(trace), 4)
    assert a[0] == 0.0                  # rebased to the first arrival
    assert np.allclose(a, [0.0, 0.2, 0.4, 0.8])
    with pytest.raises(ValueError):
        replay_arrivals(str(trace), 5)  # fewer stamps than requests
    with pytest.raises(ValueError):
        make_arrivals("replay", 4, 10.0)  # no trace path
    with pytest.raises(ValueError):
        make_arrivals("sawtooth", 4, 10.0)


# -- opts flags + env fallbacks ---------------------------------------------


def test_autoscale_opts_defaults_and_env_fallbacks(monkeypatch):
    from cst_captioning_tpu.opts import parse_opts

    opt = parse_opts([])
    assert opt.autoscale_min == 1 and opt.autoscale_max == 0  # disarmed
    assert opt.autoscale_queue_hi_ms == 50
    assert opt.autoscale_up_cooldown_s == 2
    assert opt.autoscale_down_cooldown_s == 10
    monkeypatch.setenv("CST_AUTOSCALE_MAX", "5")
    monkeypatch.setenv("CST_AUTOSCALE_QUEUE_HI_MS", "80")
    opt = parse_opts([])
    assert opt.autoscale_max == 5 and opt.autoscale_queue_hi_ms == 80
    # The flag beats the env.
    opt = parse_opts(["--autoscale_max", "2"])
    assert opt.autoscale_max == 2


def test_autoscale_opts_validators_reject_nonsense():
    from cst_captioning_tpu.opts import parse_opts

    with pytest.raises(SystemExit):
        parse_opts(["--autoscale_min", "0"])
    with pytest.raises(SystemExit):
        parse_opts(["--autoscale_max", "-1"])
    with pytest.raises(SystemExit):
        parse_opts(["--autoscale_queue_hi_ms", "0"])


def test_build_autoscaler_arms_only_on_positive_max(tmp_path):
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        from serve_supervisor import build_autoscaler
        from cst_captioning_tpu.opts import parse_opts
    finally:
        sys.path.pop(0)

    opt = parse_opts([])
    assert build_autoscaler(opt, str(tmp_path), SeriesObs()) is None
    opt = parse_opts(["--autoscale_min", "2", "--autoscale_max", "4"])
    asc = build_autoscaler(opt, str(tmp_path), SeriesObs())
    assert asc.min_replicas == 2 and asc.max_replicas == 4
    assert asc.queue_lo_ms < asc.queue_hi_ms
    assert asc.decisions_path == os.path.join(
        str(tmp_path), "autoscale_decisions.jsonl")
    # max below min is coerced up, never a crash at the CLI edge.
    opt = parse_opts(["--autoscale_min", "3", "--autoscale_max", "1"])
    asc = build_autoscaler(opt, str(tmp_path), SeriesObs())
    assert asc.max_replicas >= asc.min_replicas


# -- report gates -----------------------------------------------------------


def _mk_fleet_sample(seq, wall, *, active=2, outstanding=0, parked=0,
                     rung=0, p99=9.0, autoscale=True, slo_target=50.0):
    fleet = {"replicas": active, "in_service": active, "active": active,
             "outstanding": outstanding, "parked": parked,
             "completed": 5 * seq, "latency_p50_ms": 4.0,
             "latency_p99_ms": p99}
    if autoscale:
        fleet["autoscale"] = {"enabled": True, "min": 1, "max": 3,
                              "rung": rung, "scale_ups": 0,
                              "scale_downs": 0, "brownout_entries": 0,
                              "decisions": 0}
    return {
        "schema": 1, "kind": "fleet_sample", "seq": seq, "t": wall,
        "wall": wall, "interval_ms": 1000.0, "fleet": fleet,
        "children": [
            {"index": k, "state": "ok", "live": True, "restarts": 0,
             "inflight": 0, "queue_depth": 0, "latency_p50_ms": 4.0,
             "latency_p99_ms": p99, "compiles": 2}
            for k in range(active)],
        "slo": {"enabled": True, "firing": [],
                "objectives": {"p99": {"target": slo_target,
                                       "fast_burn": 0.1,
                                       "slow_burn": 0.1,
                                       "firing": False}},
                "alerts_fired": 0, "alerts_cleared": 0},
    }


def _run_fleet_report(tmp_path, samples, extra=()):
    path = tmp_path / "fleet_metrics.jsonl"
    with open(path, "w") as f:
        for s in samples:
            f.write(json.dumps(s) + "\n")
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts",
                                      "fleet_report.py"),
         "--file", str(path), *extra],
        capture_output=True, text=True, cwd=REPO)


def test_fleet_report_renders_replica_timeline_and_passes(tmp_path):
    actives = [1, 1, 2, 2, 2, 1]
    samples = [_mk_fleet_sample(k + 1, 100.0 + k, active=n)
               for k, n in enumerate(actives)]
    proc = _run_fleet_report(tmp_path, samples)
    assert proc.returncode == 0, proc.stderr
    assert "replica timeline" in proc.stdout
    assert "1->2->1" in proc.stdout and "2 change(s)" in proc.stdout
    assert "autoscale" in proc.stdout


def test_fleet_report_gates_on_scale_event_loss(tmp_path):
    samples = [_mk_fleet_sample(1, 100.0, active=1),
               _mk_fleet_sample(2, 101.0, active=2),
               _mk_fleet_sample(3, 102.0, active=1, outstanding=2)]
    proc = _run_fleet_report(tmp_path, samples)
    assert proc.returncode == 1
    assert "scale-event loss" in proc.stderr


def test_fleet_report_gates_on_thrash(tmp_path):
    actives = [1, 2, 1, 2, 1, 2, 1]      # 6 changes
    samples = [_mk_fleet_sample(k + 1, 100.0 + k, active=n)
               for k, n in enumerate(actives)]
    proc = _run_fleet_report(tmp_path, samples)
    assert proc.returncode == 1
    assert "thrash" in proc.stderr
    # The budget is a flag.
    proc = _run_fleet_report(tmp_path, samples,
                             extra=("--max_scale_changes", "8"))
    assert proc.returncode == 0, proc.stderr


def test_fleet_report_gates_on_brownout_p99_breach(tmp_path):
    samples = [_mk_fleet_sample(1, 100.0),
               _mk_fleet_sample(2, 101.0, rung=2, p99=90.0)]
    proc = _run_fleet_report(tmp_path, samples)
    assert proc.returncode == 1
    assert "brownout p99 breach" in proc.stderr
    # Held p99 under brownout passes.
    samples = [_mk_fleet_sample(1, 100.0),
               _mk_fleet_sample(2, 101.0, rung=2, p99=30.0)]
    proc = _run_fleet_report(tmp_path, samples)
    assert proc.returncode == 0, proc.stderr


def test_fleet_report_old_records_skip_autoscale_gates(tmp_path):
    """A pre-autoscaler series (no fleet.active, no fleet.autoscale)
    renders and passes exactly as before — the new gates never judge
    old evidence."""
    samples = []
    for k in range(4):
        s = _mk_fleet_sample(k + 1, 100.0 + k, autoscale=False)
        del s["fleet"]["active"]
        samples.append(s)
    proc = _run_fleet_report(tmp_path, samples)
    assert proc.returncode == 0, proc.stderr
    assert "thrash" not in proc.stderr
    assert "scale-event loss" not in proc.stderr


def _run_serve_report(record, tmp_path):
    path = tmp_path / "serving.json"
    path.write_text(json.dumps(record) + "\n")
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts",
                                      "serve_report.py"),
         "--file", str(path)], capture_output=True, text=True, cwd=REPO)


def _autoscale_record(**over):
    rec = {
        "metric": "serve_captions_per_sec_per_chip", "value": 12.0,
        "latency_p50_ms": 40.0, "latency_p99_ms": 90.0,
        "completed": 18, "num_requests": 18, "shed": 0,
        "recompiles_after_warmup": 0,
        "autoscale": {"enabled": True, "min": 1, "max": 3,
                      "started_at_min": True, "scaled_up": True,
                      "scale_up_intervals": 4,
                      "scale_up_budget_intervals": 40,
                      "scaled_down": True, "scale_ups": 1,
                      "scale_downs": 1, "replica_changes": 2,
                      "no_thrash": True, "brownout_entries": 0,
                      "rung": 0, "decisions": 2, "answered_ok": True},
    }
    rec["autoscale"].update(over)
    return rec


def test_serve_report_renders_autoscale_and_passes(tmp_path):
    proc = _run_serve_report(_autoscale_record(), tmp_path)
    assert proc.returncode == 0, proc.stderr
    assert "autoscale drill" in proc.stdout
    assert "scaled_up=True" in proc.stdout
    assert "brownout" in proc.stdout


@pytest.mark.parametrize("flag,needle", [
    ("started_at_min", "did not start at"),
    ("scaled_up", "never triggered a scale-up"),
    ("scaled_down", "never drained back"),
    ("no_thrash", "flapped"),
    ("answered_ok", "lost or double-answered"),
])
def test_serve_report_gates_each_autoscale_flag(tmp_path, flag, needle):
    proc = _run_serve_report(_autoscale_record(**{flag: False}),
                             tmp_path)
    assert proc.returncode == 1
    assert needle in proc.stderr


def test_serve_report_old_records_render_unchanged(tmp_path):
    """A record with no autoscale section gains no rows, no gates —
    the pin that old committed evidence re-renders as it always did."""
    rec = {"metric": "serve_captions_per_sec_per_chip", "value": 12.0,
           "latency_p50_ms": 40.0, "latency_p99_ms": 90.0,
           "completed": 18, "num_requests": 18, "shed": 0,
           "recompiles_after_warmup": 0}
    proc = _run_serve_report(rec, tmp_path)
    assert proc.returncode == 0, proc.stderr
    assert "autoscale" not in proc.stdout
    assert "brownout" not in proc.stdout


# -- durable-rename satellite -----------------------------------------------


def test_durable_rename_moves_and_overwrites(tmp_path):
    from cst_captioning_tpu.resilience.integrity import durable_rename

    src = tmp_path / "a.json"
    dst = tmp_path / "b.json"
    src.write_text("new")
    dst.write_text("old")
    durable_rename(str(src), str(dst))
    assert not src.exists() and dst.read_text() == "new"


def test_publishing_renames_go_through_the_discipline():
    """Source pin: every rename that publishes a durable artifact uses
    integrity.durable_rename, not a bare os.rename/os.replace — the
    audit that closed the checkpoint-quarantine and metrics-rotation
    stragglers stays closed."""
    for rel in ("cst_captioning_tpu/training/checkpoint.py",
                "cst_captioning_tpu/telemetry/fleetobs.py"):
        src = open(os.path.join(REPO, rel)).read()
        assert "durable_rename" in src, rel
        assert "os.rename(" not in src, rel


# -- dataset fingerprint satellite ------------------------------------------


def test_generate_without_features_skips_the_h5s(tmp_path):
    from cst_captioning_tpu.data.synthetic import SyntheticSpec, generate

    paths = generate(str(tmp_path), "train",
                     SyntheticSpec(num_videos=4, captions_per_video=2),
                     features=False)
    assert "feat_h5" not in paths
    assert not [f for f in os.listdir(tmp_path) if "feat" in f]
    assert os.path.exists(paths["label_h5"])


def test_dataset_fingerprint_roundtrip_and_drift(tmp_path):
    """Two independent regenerations fingerprint identically (the
    post-/tmp-wipe rebuild proof); a perturbed record is caught."""
    script = os.path.join(REPO, "scripts", "dataset_fingerprint.py")
    artifact = tmp_path / "fp.json"
    args = ["--num_videos", "12", "--num_val", "4",
            "--feat_dims", "16", "--feat_times", "2",
            "--rich_vocab", "0", "--artifact", str(artifact)]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    up = subprocess.run(
        [sys.executable, script, *args, "--update"],
        capture_output=True, text=True, cwd=REPO, env=env)
    assert up.returncode == 0, up.stderr
    chk = subprocess.run(
        [sys.executable, script, *args, "--check"],
        capture_output=True, text=True, cwd=REPO, env=env)
    assert chk.returncode == 0, chk.stderr
    assert "IDENTICAL" in chk.stdout
    doc = json.loads(artifact.read_text())
    doc["splits"]["train"]["label_h5"] = "0" * 64
    doc["combined"] = "0" * 64
    artifact.write_text(json.dumps(doc))
    bad = subprocess.run(
        [sys.executable, script, *args, "--check"],
        capture_output=True, text=True, cwd=REPO, env=env)
    assert bad.returncode == 1
    assert "mismatch" in bad.stderr
    spec = subprocess.run(
        [sys.executable, script, *args, "--check",
         "--num_videos", "13"],
        capture_output=True, text=True, cwd=REPO, env=env)
    assert spec.returncode == 1
    assert "spec differs" in spec.stderr


def test_committed_fingerprint_artifact_is_wellformed():
    path = os.path.join(REPO, "artifacts", "dataset_fingerprint.json")
    doc = json.load(open(path))
    assert doc["schema"] == 1
    assert doc["spec"]["num_videos"] == 6513      # the north-star scale
    assert doc["spec"]["num_val"] == 497
    assert set(doc["splits"]) == {"train", "val"}
    for rec in doc["splits"].values():
        assert len(rec["label_h5"]) == 64
        assert len(rec["vocab_json"]) == 64


# -- doc pins ---------------------------------------------------------------


def test_serving_md_pins_the_autoscale_counter_table():
    doc = open(os.path.join(REPO, "SERVING.md")).read()
    assert "## Autoscaling & brownout" in doc
    for name in AUTOSCALE_COUNTERS:
        assert f"`{name}`" in doc, name
    for why in ("brownout_deadline", "brownout_parked",
                "brownout_stream"):
        assert why in doc, why


def test_observability_md_documents_the_decisions_log():
    doc = open(os.path.join(REPO, "OBSERVABILITY.md")).read()
    assert "autoscale_decisions.jsonl" in doc
    assert "autoscale_decision" in doc


def test_resilience_md_has_the_brownout_ladder_row():
    doc = open(os.path.join(REPO, "RESILIENCE.md")).read()
    assert "brownout" in doc.lower()
    for rung in BROWNOUT_RUNGS:
        assert rung in doc


# -- slow: the real-subprocess burst drill ----------------------------------


@pytest.mark.slow
def test_cli_autoscale_burst_drill_end_to_end(tmp_path):
    """THE acceptance drill through the real CLI: idle -> 4x burst ->
    idle against real serve.py children — starts at --autoscale_min,
    scales up within the scrape-interval budget, drains back down,
    answers every request exactly once bit-identical to the fault-free
    single-engine reference, zero post-warmup compiles, and the record
    survives serve_report's + fleet_report's gates."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    root = str(tmp_path / "autoscale")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts",
                                      "serve_supervisor.py"),
         "--serve_demo", "1", "--autoscale_probe", "1",
         "--autoscale_min", "1", "--autoscale_max", "3",
         "--autoscale_up_cooldown_s", "1",
         "--autoscale_down_cooldown_s", "1",
         "--serve_demo_eos_bias", "-2", "--decode_chunk", "2",
         "--beam_size", "1", "--fleet_scrape_ms", "200",
         "--serve_lifecycle", "1",
         "--supervise_dir", root],
        capture_output=True, text=True, timeout=600, cwd=REPO, env=env)
    assert proc.returncode == 0, proc.stderr[-4000:]
    rec = json.loads(proc.stdout.splitlines()[-1])
    a = rec["autoscale"]
    assert a["enabled"] and a["started_at_min"]
    assert a["scaled_up"] and a["scaled_down"]
    assert a["scale_up_intervals"] <= a["scale_up_budget_intervals"]
    assert a["no_thrash"] and a["answered_ok"]
    assert rec["completed"] == rec["num_requests"]
    assert rec["recompiles_after_warmup"] == 0
    sup = rec["supervisor"]
    assert sup["parity_ok"] and sup["parity_mismatches"] == 0
    # The durable decision trail exists and replays the story.
    decisions = [json.loads(l) for l in
                 open(os.path.join(root, "autoscale_decisions.jsonl"))]
    acts = [d["action"] for d in decisions]
    assert "scale_up" in acts and "scale_down" in acts
    assert all(d["schema"] == AUTOSCALE_SCHEMA for d in decisions)
    # Both report planes re-gate the evidence.
    report = _run_serve_report(rec, tmp_path)
    assert report.returncode == 0, report.stderr
    fleet = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts",
                                      "fleet_report.py"),
         "--dir", root], capture_output=True, text=True, cwd=REPO)
    assert fleet.returncode == 0, fleet.stderr
    assert "replica timeline" in fleet.stdout
