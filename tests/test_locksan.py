"""Runtime lock sanitizer (ISSUE 11): the dynamic cross-check on the
declared LOCK_ORDER.

Pins the acceptance contract:
- disabled (default) the factory returns plain ``threading.Lock`` — zero
  overhead, zero behavior change;
- armed, acquisitions that follow a declared table pass and record their
  edges;
- a DELIBERATELY mis-declared order produces the violation receipt: a
  durable JSON written through ``atomic_json_write`` naming the edge,
  the holder's stack, and the declared tables — and raises
  :class:`LockOrderViolation` BEFORE blocking on the lock that would
  deadlock;
- undeclared nestings are violations too (the "static declarations rot"
  failure mode) — and since edges are only ever recorded when declared,
  those two checks catch every would-be cross-thread cycle at one of
  its edges;
- the serving plane's shipped tables (server write->conn,
  ProgramCache->registry) are registered at import time.
"""

import json
import os
import threading

import pytest

from cst_captioning_tpu.analysis import locksan
from cst_captioning_tpu.analysis.locksan import (
    LockOrderViolation,
    declare_order,
    named_lock,
)


@pytest.fixture(autouse=True)
def _armed(monkeypatch, tmp_path):
    receipt = tmp_path / "locksan_violation.json"
    monkeypatch.setenv(locksan.ENV_FLAG, "1")
    monkeypatch.setenv(locksan.ENV_RECEIPT, str(receipt))
    locksan.reset_observed()
    yield receipt
    locksan.reset_observed()


def test_disabled_factory_returns_plain_lock(monkeypatch):
    monkeypatch.delenv(locksan.ENV_FLAG, raising=False)
    lk = named_lock("ls.plain")
    assert isinstance(lk, type(threading.Lock()))


def test_runtime_import_is_lint_engine_free():
    """The implementation lives in utils/ so runtime lock creators never
    pull the lint machinery: importing utils.locksan (what telemetry/
    serving/native do) must leave the analysis package unloaded;
    analysis.locksan is the re-exporting façade."""
    import subprocess
    import sys

    code = (
        "import sys\n"
        "import cst_captioning_tpu.utils.locksan as ls\n"
        "bad = [m for m in sys.modules if 'analysis' in m]\n"
        "assert not bad, f'lint engine leaked into runtime import: {bad}'\n"
        "import cst_captioning_tpu.analysis.locksan as facade\n"
        "assert facade.named_lock is ls.named_lock\n"
        "assert facade.declare_order is ls.declare_order\n")
    p = subprocess.run([sys.executable, "-c", code],
                       capture_output=True, text=True, timeout=120,
                       cwd=os.path.dirname(os.path.dirname(
                           os.path.abspath(__file__))))
    assert p.returncode == 0, p.stderr


def test_armed_factory_returns_sanitized_lock():
    lk = named_lock("ls.sanitized")
    assert lk.__class__.__name__ == "_SanitizedLock"
    assert "ls.sanitized" in repr(lk)
    with lk:
        assert lk.locked()
    assert not lk.locked()


def test_declared_order_passes_and_records_edges():
    declare_order("ls.ok.a", "ls.ok.b")
    a, b = named_lock("ls.ok.a"), named_lock("ls.ok.b")
    with a:
        with b:
            pass
    assert locksan.violations() == []


def test_misdeclared_order_produces_receipt(_armed):
    """THE acceptance drill: the declared table says b-before-a, the
    code nests a->b — the sanitizer refuses the acquisition, writes the
    durable receipt, and raises."""
    declare_order("ls.bad.b", "ls.bad.a")
    a, b = named_lock("ls.bad.a"), named_lock("ls.bad.b")
    with pytest.raises(LockOrderViolation, match="inverts the declared"):
        with a:
            with b:
                pass
    doc = json.loads(_armed.read_text())
    assert doc["schema"] == locksan.LOCKSAN_SCHEMA
    assert doc["kind"] == "inverted-order"
    assert doc["edge"] == ["ls.bad.a", "ls.bad.b"]
    assert "ls.bad.a" in doc["held_stack"]
    assert ["ls.bad.b", "ls.bad.a"] in doc["declared_tables"]
    assert locksan.violations()[-1]["kind"] == "inverted-order"


def test_undeclared_nesting_is_a_violation(_armed):
    a, c = named_lock("ls.und.a"), named_lock("ls.und.c")
    with pytest.raises(LockOrderViolation, match="not covered by any"):
        with a:
            with c:
                pass
    assert json.loads(_armed.read_text())["kind"] == "undeclared-edge"


def test_contradictory_tables_fail_both_directions_across_threads():
    """Two modules declaring opposite orders for one pair: EVERY nesting
    of that pair is refused, on any thread, before it can block — the
    deadlock is reported instead of entered."""
    declare_order("ls.cyc.x", "ls.cyc.y")
    declare_order("ls.cyc.y", "ls.cyc.x")   # the contradictory table
    x, y = named_lock("ls.cyc.x"), named_lock("ls.cyc.y")
    caught = []

    def nest_xy():
        try:
            with x:
                with y:
                    pass
        except LockOrderViolation as e:
            caught.append(e)

    t = threading.Thread(target=nest_xy, name="locksan-test-xy",
                         daemon=True)
    t.start()
    t.join(timeout=10.0)
    assert not t.is_alive() and len(caught) == 1
    with pytest.raises(LockOrderViolation):
        with y:
            with x:
                pass


def test_release_out_of_lifo_order_is_legal():
    declare_order("ls.fifo.a", "ls.fifo.b")
    a, b = named_lock("ls.fifo.a"), named_lock("ls.fifo.b")
    a.acquire()
    b.acquire()
    a.release()           # handoff pattern: outer released first
    b.release()
    assert locksan.violations() == []


def test_shipped_serving_tables_are_registered():
    """Importing the serving plane declares its LOCK_ORDER tables — the
    same declaration the static rule reads (one source of truth)."""
    from cst_captioning_tpu.serving import buckets, server

    assert buckets.LOCK_ORDER == ("serving.programs", "telemetry.registry")
    assert server.LOCK_ORDER == ("serving.server.write",
                                 "serving.server.conn")
    # And the runtime registry honors them end to end.
    progs = named_lock("serving.programs")
    reg = named_lock("telemetry.registry")
    with progs:
        with reg:
            pass
    assert locksan.violations() == []
