"""Pallas fused attention: interpret-mode parity with the XLA path.

Forward values, gradients (custom VJP), and the full DecoderCell/CaptionModel
integration must match the plain flax computation — the kernel is a pure
performance substitution (SURVEY.md §7 step 8).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cst_captioning_tpu.models import CaptionModel
from cst_captioning_tpu.ops.pallas_attention import fused_additive_attention

B, T, A, H = 5, 7, 16, 12  # deliberately unaligned (pads to block_b)


@pytest.fixture(scope="module")
def inputs():
    k = jax.random.PRNGKey(0)
    ks = jax.random.split(k, 4)
    return (
        jax.random.normal(ks[0], (B, A)),        # query_proj
        jax.random.normal(ks[1], (B, T, A)),     # proj_mem
        jax.random.normal(ks[2], (B, T, H)),     # memory
        jax.random.normal(ks[3], (A,)),          # score_v
    )


def reference(q, pm, mem, v):
    scores = jnp.einsum("bta,a->bt", jnp.tanh(pm + q[:, None, :]), v)
    w = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bt,bth->bh", w, mem), w


class TestForward:
    def test_matches_reference(self, inputs):
        ctx, w = fused_additive_attention(*inputs, block_b=2, interpret=True)
        ref_ctx, ref_w = reference(*inputs)
        np.testing.assert_allclose(np.asarray(ctx), np.asarray(ref_ctx),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(w), np.asarray(ref_w),
                                   rtol=1e-5, atol=1e-6)

    def test_weights_normalized(self, inputs):
        _, w = fused_additive_attention(*inputs, block_b=4, interpret=True)
        np.testing.assert_allclose(np.asarray(w.sum(-1)), 1.0, rtol=1e-5)

    def test_block_size_invariance(self, inputs):
        a, _ = fused_additive_attention(*inputs, block_b=1, interpret=True)
        b, _ = fused_additive_attention(*inputs, block_b=8, interpret=True)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)

    def test_jit_compatible(self, inputs):
        fn = jax.jit(lambda *a: fused_additive_attention(
            *a, block_b=2, interpret=True)[0])
        np.testing.assert_allclose(
            np.asarray(fn(*inputs)),
            np.asarray(reference(*inputs)[0]), rtol=1e-5, atol=1e-6,
        )


class TestBF16:
    def test_bf16_inputs_stay_bf16_and_match(self, inputs):
        q, pm, mem = (x.astype(jnp.bfloat16) for x in inputs[:3])
        v = inputs[3]
        ctx, w = fused_additive_attention(q, pm, mem, v, 2, True)
        assert ctx.dtype == jnp.bfloat16  # storage dtype preserved
        ref_ctx, _ = reference(q.astype(jnp.float32), pm.astype(jnp.float32),
                               mem.astype(jnp.float32), v)
        np.testing.assert_allclose(np.asarray(ctx, np.float32),
                                   np.asarray(ref_ctx), rtol=5e-2, atol=5e-2)


class TestBF16Parity:
    def test_bf16_model_logits_match_across_flag(self):
        labels = jnp.array([[3, 4, 5, 0, 0, 0], [6, 7, 0, 0, 0, 0]])
        feats = [jax.random.normal(jax.random.PRNGKey(1), (2, 4, 8))]
        kw = dict(vocab_size=12, embed_size=16, hidden_size=16,
                  attn_size=16, dropout_rate=0.0, dtype=jnp.bfloat16)
        plain = CaptionModel(**kw)
        fused = CaptionModel(**kw, use_pallas_attention=True)
        variables = plain.init(jax.random.PRNGKey(0), feats, labels)
        a = plain.apply(variables, feats, labels).astype(jnp.float32)
        b = fused.apply(variables, feats, labels).astype(jnp.float32)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-2, atol=2e-2)

    def test_bf16_grads_finite_and_close(self):
        k = jax.random.PRNGKey(3)
        q, pm, mem = (jax.random.normal(jax.random.fold_in(k, i),
                                        s).astype(jnp.bfloat16)
                      for i, s in enumerate([(B, A), (B, T, A), (B, T, H)]))
        v = jax.random.normal(jax.random.fold_in(k, 3), (A,))

        def loss_pallas(q, pm, mem, v):
            ctx, _ = fused_additive_attention(q, pm, mem, v, 2, True)
            return jnp.sum(ctx.astype(jnp.float32) ** 2)

        def loss_ref(q, pm, mem, v):
            ctx, _ = reference(q.astype(jnp.float32), pm.astype(jnp.float32),
                               mem.astype(jnp.float32), v)
            return jnp.sum(ctx ** 2)

        g_p = jax.grad(loss_pallas, argnums=(1, 3))(q, pm, mem, v)
        g_r = jax.grad(loss_ref, argnums=(1, 3))(q, pm, mem, v)
        for a, b in zip(g_p, g_r):
            a = np.asarray(a, np.float32)
            b = np.asarray(b, np.float32)
            assert np.isfinite(a).all()
            np.testing.assert_allclose(a, b, rtol=5e-2, atol=5e-2)


class TestGradients:
    def test_vjp_matches_reference_grads(self, inputs):
        target = jax.random.normal(jax.random.PRNGKey(9), (B, H))

        def loss_pallas(q, pm, mem, v):
            ctx, w = fused_additive_attention(q, pm, mem, v, 2, True)
            return jnp.sum((ctx - target) ** 2) + jnp.sum(w * w)

        def loss_ref(q, pm, mem, v):
            ctx, w = reference(q, pm, mem, v)
            return jnp.sum((ctx - target) ** 2) + jnp.sum(w * w)

        g_p = jax.grad(loss_pallas, argnums=(0, 1, 2, 3))(*inputs)
        g_r = jax.grad(loss_ref, argnums=(0, 1, 2, 3))(*inputs)
        for a, b in zip(g_p, g_r):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)


class TestModelIntegration:
    def test_captioner_logits_match(self):
        labels = jnp.array([[3, 4, 5, 0, 0, 0], [6, 7, 0, 0, 0, 0]])
        feats = [jax.random.normal(jax.random.PRNGKey(1), (2, 4, 8))]
        kw = dict(vocab_size=12, embed_size=16, hidden_size=16,
                  attn_size=16, dropout_rate=0.0)
        plain = CaptionModel(**kw)
        fused = CaptionModel(**kw, use_pallas_attention=True)
        variables = plain.init(jax.random.PRNGKey(0), feats, labels)
        # identical param trees: the flag changes compute only
        logits_plain = plain.apply(variables, feats, labels)
        logits_fused = fused.apply(variables, feats, labels)
        np.testing.assert_allclose(np.asarray(logits_fused),
                                   np.asarray(logits_plain),
                                   rtol=1e-4, atol=1e-5)

    def test_grads_flow_through_model(self):
        labels = jnp.array([[3, 4, 0, 0], [6, 7, 2, 0]])
        feats = [jax.random.normal(jax.random.PRNGKey(1), (2, 3, 8))]
        model = CaptionModel(vocab_size=12, embed_size=8, hidden_size=8,
                             attn_size=8, dropout_rate=0.0,
                             use_pallas_attention=True)
        variables = model.init(jax.random.PRNGKey(0), feats, labels)

        def loss(params):
            logits = model.apply({"params": params}, feats, labels)
            return jnp.mean(logits ** 2)

        grads = jax.grad(loss)(variables["params"])
        leaves = jax.tree_util.tree_leaves(grads)
        assert all(np.isfinite(np.asarray(g)).all() for g in leaves)
        # attention params receive nonzero grads
        attn = grads["cell"]["attn"]
        assert float(jnp.abs(attn["score_v"]).max()) > 0
        assert float(jnp.abs(attn["query_proj"]["kernel"]).max()) > 0
