"""cstlint acceptance (ISSUE 10): every rule proven by its seeded
corpus (positive fires, near-miss doesn't), the suppression grammar
(required justification, statement-span coverage, stale detection), the
donation audit against every registered jit entry point, the CLI
contract, and — the CI-equivalent enforcement — the clean-tree gate:
the committed tree reports ZERO unsuppressed violations.
"""

import importlib.util
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CORPUS = os.path.join(REPO, "tests", "fixtures", "lint_corpus")

from cst_captioning_tpu.analysis import (  # noqa: E402
    RULES,
    lint_sources,
    lint_tree,
    render_json,
)
from cst_captioning_tpu.analysis.donation import (  # noqa: E402
    audit_entry_points,
    audit_lowered,
    ENTRY_POINTS,
)
from cst_captioning_tpu.resilience.exitcodes import (  # noqa: E402
    EXIT_FAILURE,
    EXIT_OK,
    EXIT_USAGE,
)

#: rule -> (corpus basename, virtual repo path the rule scopes to).
AST_CORPUS = {
    "device-scalar-fetch": ("device_scalar_fetch",
                            "cst_captioning_tpu/training/trainer.py"),
    "atomic-write": ("atomic_write", "scripts/somescript.py"),
    "declared-counters": ("declared_counters",
                          "cst_captioning_tpu/data/somemodule.py"),
    "exit-taxonomy": ("exit_taxonomy", "scripts/somescript.py"),
    "bare-except-swallow": ("bare_except",
                            "cst_captioning_tpu/serving/somemodule.py"),
    # Concurrency contracts (ISSUE 11; ANALYSIS.md "Concurrency
    # contracts") — all six are tree-wide or annotation-scoped, so any
    # virtual path works; these mirror where each rule's real catches
    # live.
    "guarded-by": ("guarded_by",
                   "cst_captioning_tpu/telemetry/somemodule.py"),
    "thread-ownership": ("thread_ownership",
                         "cst_captioning_tpu/serving/somemodule.py"),
    "lock-order": ("lock_order",
                   "cst_captioning_tpu/serving/somemodule.py"),
    "signal-safe-handler": ("signal_safe_handler",
                            "cst_captioning_tpu/resilience/somemodule.py"),
    "thread-discipline": ("thread_discipline",
                          "cst_captioning_tpu/data/somemodule.py"),
    "monotonic-deadline": ("monotonic_deadline", "scripts/somescript.py"),
    # The intake journal's single-append-path rule (ISSUE 20): *.wal
    # writes outside serving/journal.py tear the exactly-once record.
    "journal-append": ("journal_append",
                       "cst_captioning_tpu/serving/somemodule.py"),
}


def corpus_text(basename: str, kind: str) -> str:
    with open(os.path.join(CORPUS, f"{basename}_{kind}.py")) as f:
        return f.read()


def run_rule(rule: str, text: str, relpath: str):
    res = lint_sources([(relpath, text)], rules=[rule])
    return [v for v in res.violations if v.rule == rule]


# -- per-rule corpus: positive fires, near-miss doesn't --------------------


@pytest.mark.parametrize("rule", sorted(AST_CORPUS))
def test_corpus_positive_fires(rule):
    base, vpath = AST_CORPUS[rule]
    hits = run_rule(rule, corpus_text(base, "pos"), vpath)
    assert hits, f"{rule} must fire on its seeded positive"


@pytest.mark.parametrize("rule", sorted(AST_CORPUS))
def test_corpus_near_miss_negative_silent(rule):
    base, vpath = AST_CORPUS[rule]
    hits = run_rule(rule, corpus_text(base, "neg"), vpath)
    assert hits == [], f"{rule} fired on its near-miss negative: {hits}"


def test_device_scalar_fetch_scoped_to_hot_paths():
    """The SAME positive source outside the hot-path set is silent —
    the rule encodes where the garble caveat bites, not a style ban."""
    text = corpus_text("device_scalar_fetch", "pos")
    assert run_rule("device-scalar-fetch", text,
                    "cst_captioning_tpu/metrics/ngrams.py") == []


def test_atomic_write_home_module_exempt():
    """integrity.py itself must spell the raw write."""
    text = corpus_text("atomic_write", "pos")
    assert run_rule("atomic-write", text,
                    "cst_captioning_tpu/resilience/integrity.py") == []


def test_journal_append_home_module_exempt():
    """serving/journal.py itself must spell the raw segment write —
    its _append IS the discipline the rule enforces elsewhere."""
    text = corpus_text("journal_append", "pos")
    assert run_rule("journal-append", text,
                    "cst_captioning_tpu/serving/journal.py") == []


def test_bare_except_scoped_to_failure_domains():
    text = corpus_text("bare_except", "pos")
    assert run_rule("bare-except-swallow", text,
                    "cst_captioning_tpu/metrics/ngrams.py") == []


# -- concurrency contracts (ISSUE 11) --------------------------------------


def test_guarded_by_flags_both_access_kinds():
    """The positive's unlocked read AND write both fire."""
    hits = run_rule("guarded-by", corpus_text("guarded_by", "pos"),
                    "cst_captioning_tpu/telemetry/somemodule.py")
    assert len(hits) >= 2
    assert all("guarded_by=self._lock" in h.message for h in hits)


def test_lock_order_positive_diagnoses_inversion_and_unnamed():
    hits = run_rule("lock-order", corpus_text("lock_order", "pos"),
                    "cst_captioning_tpu/serving/somemodule.py")
    msgs = " | ".join(h.message for h in hits)
    assert "INVERTS" in msgs
    assert "unnamed locks" in msgs


def test_lock_order_cycle_across_conflicting_tables():
    """Two modules declaring opposite orders for the same pair: the
    nested acquisition that closes the loop is a cycle violation even
    though each table alone is consistent."""
    a = ('from cst_captioning_tpu.analysis.locksan import named_lock\n'
         'LOCK_ORDER = ("cyc.a", "cyc.b")\n'
         '_A = named_lock("cyc.a")\n'
         '_B = named_lock("cyc.b")\n'
         'def f():\n'
         '    with _A:\n'
         '        with _B:\n'
         '            pass\n')
    b = ('from cst_captioning_tpu.analysis.locksan import named_lock\n'
         'LOCK_ORDER = ("cyc.b", "cyc.a")\n')
    res = lint_sources(
        [("cst_captioning_tpu/serving/a.py", a),
         ("cst_captioning_tpu/serving/b.py", b)],
        rules=["lock-order"])
    msgs = " | ".join(v.message for v in res.violations)
    assert "INVERTS" in msgs or "cycle" in msgs


def test_signal_safe_handler_resolves_lambda_registration():
    """scale_chain's lambda handler shape: sys.exit through a constant
    is allowed; an Event.set in the lambda is not."""
    ok = ('import signal, sys\n'
          'from x import EXIT_SIGTERM\n'
          'signal.signal(signal.SIGTERM,\n'
          '              lambda *_: sys.exit(EXIT_SIGTERM))\n')
    assert run_rule("signal-safe-handler", ok, "scripts/somescript.py") == []
    bad = ('import signal, threading\n'
           'EVT = threading.Event()\n'
           'signal.signal(signal.SIGTERM, lambda *_: EVT.set())\n')
    hits = run_rule("signal-safe-handler", bad, "scripts/somescript.py")
    assert hits and ".set()" in hits[0].message


def test_thread_discipline_counts_three_distinct_failures():
    hits = run_rule("thread-discipline",
                    corpus_text("thread_discipline", "pos"),
                    "cst_captioning_tpu/data/somemodule.py")
    msgs = [h.message for h in hits]
    assert any("without name=" in m for m in msgs)
    assert any("explicit daemon=" in m for m in msgs)
    assert any("no .join()" in m for m in msgs)


def test_monotonic_deadline_allows_bare_timestamps():
    """`{"ts": time.time()}` and `now = time.time()` are legal: the rule
    bans arithmetic/comparisons, not wall-clock labels."""
    hits = run_rule("monotonic-deadline",
                    corpus_text("monotonic_deadline", "neg"),
                    "cst_captioning_tpu/utils/somemodule.py")
    assert hits == []


# -- donation audit (jaxpr-level) ------------------------------------------


def _load_corpus_module(name):
    spec = importlib.util.spec_from_file_location(
        f"lint_corpus_{name}", os.path.join(CORPUS, f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_donation_corpus_positive_fires():
    lowered, donated = _load_corpus_module("donation_audit_pos").build()
    problems = audit_lowered(lowered, donated)
    assert problems and "aliased" in problems[0]


def test_donation_corpus_negative_clean():
    lowered, donated = _load_corpus_module("donation_audit_neg").build()
    assert audit_lowered(lowered, donated) == []


def test_registered_entry_points_all_alias():
    """Acceptance: the donation-audit rule passes against EVERY
    registered jit entry point (trainer XE, fused CST, serving
    greedy/beam chunk + admit) — the mechanized form of the PR-3/PR-6
    hand audits."""
    results = audit_entry_points()
    assert set(results) == set(ENTRY_POINTS)
    assert len(results) >= 6
    bad = {k: v for k, v in results.items() if v}
    assert not bad, f"donation regressions: {bad}"


# -- suppression grammar ---------------------------------------------------


POS_EXIT = 'import sys\nsys.exit(3)\n'


def test_suppression_with_justification_applies():
    src = ('import sys\n'
           '# cstlint: disable=exit-taxonomy -- corpus: typed exit '
           'tested elsewhere\n'
           'sys.exit(3)\n')
    res = lint_sources([("scripts/x.py", src)], rules=["exit-taxonomy"])
    assert res.clean
    assert len(res.suppressed) == 1
    assert res.suppressed[0][1].justification.startswith("corpus:")


def test_trailing_suppression_applies_to_own_line():
    src = ('import sys\n'
           'sys.exit(3)  # cstlint: disable=exit-taxonomy -- corpus ok\n')
    res = lint_sources([("scripts/x.py", src)], rules=["exit-taxonomy"])
    assert res.clean and len(res.suppressed) == 1


def test_suppression_without_justification_is_violation_and_inert():
    src = ('import sys\n'
           '# cstlint: disable=exit-taxonomy\n'
           'sys.exit(3)\n')
    res = lint_sources([("scripts/x.py", src)], rules=["exit-taxonomy"])
    rules_hit = sorted(v.rule for v in res.violations)
    assert rules_hit == ["exit-taxonomy", "suppression-format"]


def test_suppression_covers_multiline_statement():
    src = ('import sys\n'
           '# cstlint: disable=exit-taxonomy -- corpus: spans the call\n'
           'sys.exit(\n'
           '    3)\n')
    res = lint_sources([("scripts/x.py", src)], rules=["exit-taxonomy"])
    assert res.clean and len(res.suppressed) == 1


def test_stale_suppression_reported():
    """Satellite: a disable whose rule no longer fires is itself a
    violation — justified exceptions can't rot silently."""
    src = ('import sys\n'
           '# cstlint: disable=exit-taxonomy -- was a literal, now fixed\n'
           'sys.exit()\n')
    res = lint_sources([("scripts/x.py", src)], rules=["exit-taxonomy"])
    assert [v.rule for v in res.violations] == ["stale-suppression"]
    assert "was a literal" in res.violations[0].message


def test_stale_not_reported_for_rules_that_did_not_run():
    """A --rules subset must not mass-expire other rules' receipts."""
    src = ('import sys\n'
           '# cstlint: disable=exit-taxonomy -- exercised under full runs\n'
           'sys.exit(3)\n')
    res = lint_sources([("scripts/x.py", src)], rules=["atomic-write"])
    assert res.clean


def test_wrong_rule_suppression_does_not_apply():
    src = ('import sys\n'
           '# cstlint: disable=atomic-write -- wrong rule on purpose\n'
           'sys.exit(3)\n')
    res = lint_sources([("scripts/x.py", src)],
                       rules=["exit-taxonomy", "atomic-write"])
    assert sorted(v.rule for v in res.violations) == [
        "exit-taxonomy", "stale-suppression"]


# -- the clean-tree gate (CI-equivalent enforcement) -----------------------


def test_tree_is_clean_ast_rules():
    """The committed tree has zero unsuppressed AST-rule violations and
    every suppression carries a justification.  (The donation rule is
    covered by test_registered_entry_points_all_alias; skipping trace
    here keeps this test jax-build-free.)"""
    res = lint_tree(REPO, trace=False)
    assert res.files_scanned > 80
    assert res.violations == [], "\n".join(
        v.render() for v in res.violations)
    for v, s in res.suppressed:
        assert s.justification, f"unjustified suppression at {v.path}"


def test_render_json_schema():
    res = lint_tree(REPO, trace=False,
                    paths=["cst_captioning_tpu/resilience/exitcodes.py"])
    import json as _json

    doc = _json.loads(render_json(res))
    assert doc["schema"] == 1
    assert doc["clean"] is True
    assert doc["files_scanned"] == 1
    assert "donation-audit" not in doc["rules_ran"]  # trace off


def test_every_shipped_rule_registered():
    expected = {"device-scalar-fetch", "atomic-write", "declared-counters",
                "exit-taxonomy", "bare-except-swallow", "donation-audit",
                "guarded-by", "thread-ownership", "lock-order",
                "signal-safe-handler", "thread-discipline",
                "monotonic-deadline", "journal-append"}
    assert expected <= set(RULES)
    for name in ("guarded-by", "thread-ownership", "lock-order",
                 "signal-safe-handler", "thread-discipline",
                 "monotonic-deadline"):
        assert RULES[name].category == "concurrency"


def test_lint_json_carries_concurrency_rules_zero_schema_change():
    """Satellite pin: collect_evidence's bundled lint.json picks the new
    rules up through `rules_ran` with NO schema change — same schema 1,
    same top-level keys the MANIFEST contract reads."""
    res = lint_tree(REPO, trace=False,
                    paths=["cst_captioning_tpu/resilience/exitcodes.py"])
    import json as _json

    doc = _json.loads(render_json(res))
    assert doc["schema"] == 1
    assert set(doc) == {"schema", "clean", "files_scanned", "rules_ran",
                        "summary", "violations", "suppressed"}
    assert {"guarded-by", "thread-ownership", "lock-order",
            "signal-safe-handler", "thread-discipline",
            "monotonic-deadline"} <= set(doc["rules_ran"])


# -- CLI contract ----------------------------------------------------------


def _run_cli(*args):
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    return subprocess.run(
        [sys.executable, "scripts/cstlint.py", *args],
        cwd=REPO, capture_output=True, text=True, timeout=120, env=env)


def test_cli_list_rules():
    p = _run_cli("--list-rules")
    assert p.returncode == EXIT_OK
    for name in ("device-scalar-fetch", "donation-audit"):
        assert name in p.stdout


def test_cli_clean_subset_exits_ok():
    p = _run_cli("--no-trace", "scripts/cstlint.py")
    assert p.returncode == EXIT_OK, p.stdout + p.stderr
    assert "clean" in p.stdout


def test_cli_unknown_rule_is_usage_error():
    """Satellite pin: a bad --rules token exits 2 (usage) with a
    one-line error NAMING the bad rule, not a stack trace."""
    p = _run_cli("--rules", "no-such-rule")
    assert p.returncode == EXIT_USAGE
    assert "unknown rule" in p.stderr
    assert "no-such-rule" in p.stderr
    assert "Traceback" not in p.stderr


def test_cli_list_rules_groups_by_category():
    p = _run_cli("--list-rules")
    assert p.returncode == EXIT_OK
    out = p.stdout
    assert "[concurrency]" in out and "[core]" in out
    # The concurrency block lists the six contracts together.
    conc = out.split("[concurrency]")[1].split("[core]")[0]
    for name in ("guarded-by", "thread-ownership", "lock-order",
                 "signal-safe-handler", "thread-discipline",
                 "monotonic-deadline"):
        assert name in conc


def test_cli_violations_exit_failure(tmp_path):
    # A seeded-bad file via explicit path: corpus positive, linted as a
    # scripts/ file.  Write it inside the repo? No — paths are
    # repo-relative, so use a relative path pointing at the corpus copy
    # presented under its real (tests/...) path, where exit-taxonomy
    # still applies (the rule is tree-wide).
    p = _run_cli("--no-trace", "--rules", "exit-taxonomy",
                 "tests/fixtures/lint_corpus/exit_taxonomy_pos.py")
    assert p.returncode == EXIT_FAILURE
    assert "exit-taxonomy" in p.stdout


# -- satellite: profile_top's usage error (first exit-taxonomy catch) ------


def test_profile_top_missing_trace_is_usage_error(tmp_path):
    """scripts/profile_top.py with a capture-less dir exits 2 (usage)
    with a one-line diagnostic — no longer sys.exit(<string>) == 1."""
    p = subprocess.run(
        [sys.executable, "scripts/profile_top.py", str(tmp_path)],
        cwd=REPO, capture_output=True, text=True, timeout=60)
    assert p.returncode == EXIT_USAGE
    assert "no *.xplane.pb" in p.stderr
    # argparse prints usage + the one-line error; nothing on stdout.
    assert p.stdout == ""
