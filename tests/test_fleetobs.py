"""Fleet-wide observability plane (ISSUE 17): cross-process trace
stitching, continuous metrics aggregation, SLO burn-rate monitoring.

Fast slice (tier-1, NO jax import — the plane is pure host code):
- :class:`serving.policy.QueryPacer` — the ONE interval/backoff policy
  the health poll, the metrics scraper and the clock pings share;
- :class:`telemetry.fleetobs.ClockSync` — midpoint offset estimation
  (skew = child_wall - (wall_send + rtt/2), uncertainty = rtt/2),
  min-RTT best sample per child *pid*, bounded pending table;
- :class:`telemetry.fleetobs.SLOMonitor` — burn formulas per objective,
  the fast+slow dual-window fire/clear state machine, ``min_requests``
  guard, typed ``slo_alert`` lifecycle events whose chains the
  accounting audit counts truncated (never a terminal violation);
- :class:`telemetry.fleetobs.FleetObs` — scrape cadence, the zero-gap
  row-per-replica-slot contract across a kill/restart, schema-stamped
  append-only ``fleet_metrics.jsonl`` + rotation index, the bounded
  in-memory ring, ``slo_alerts.jsonl`` / ``clock_sync.json`` output;
- ``scripts/fleet_report.py`` gates (burn-rate violation, scrape
  blackout, coverage hole, no-samples) and ``scripts/fleet_trace.py``
  merging (ts rebase by the skew table, child async ids stitched onto
  the supervisor's request ids, per-process labels, skew instants);
- ``scripts/trace_report.py``: the legacy single-process rendering
  pinned unchanged, plus the merged-mode cross-pid track pairing;
- supervisor integration against a ping-answering fake child: the wire
  trace stamp (armed vs unarmed), the shared query_child path, the
  SLO-driven fleet-health degraded flip;
- the four ``--fleet_scrape_ms`` / ``--slo_*`` flags (env fallbacks,
  one-line usage errors) and the OBSERVABILITY.md/SERVING.md doc pins.

The real-subprocess drill (3 children, SIGKILL mid-stream, merge +
report the whole plane end to end) is marked ``slow`` — it is the
``make fleet-obs-demo`` path under test.
"""

import json
import os
import subprocess
import sys

import pytest

from cst_captioning_tpu.serving.policy import QueryPacer
from cst_captioning_tpu.telemetry.fleetobs import (
    FLEETOBS_COUNTERS,
    ClockSync,
    FleetObs,
    SLOMonitor,
)
from cst_captioning_tpu.telemetry.lifecycle import LifecycleTracer
from cst_captioning_tpu.telemetry.registry import MetricsRegistry

from test_supervisor import FakeChild, FakeClock, tick_until

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _lock_sanitizer(monkeypatch, tmp_path):
    """Sanitizer-armed like every serving/telemetry fast slice: the
    ring/registry lock order is re-validated under each drill."""
    from cst_captioning_tpu.analysis import locksan

    receipt = tmp_path / "locksan_violation.json"
    monkeypatch.setenv(locksan.ENV_FLAG, "1")
    monkeypatch.setenv(locksan.ENV_RECEIPT, str(receipt))
    before = len(locksan.violations())
    yield
    after = locksan.violations()
    assert len(after) == before, f"lock-order violations: {after[before:]}"
    assert not receipt.exists()


# -- QueryPacer: the shared child-query policy ------------------------------


def test_query_pacer_first_query_always_due():
    p = QueryPacer(1.0)
    assert p.due(0, 100.0)          # never queried -> due immediately
    p.sent(0, 100.0)
    assert not p.due(0, 100.5)
    assert p.due(0, 101.0)          # interval elapsed


def test_query_pacer_failure_backoff_doubles_capped_then_ok_snaps():
    p = QueryPacer(1.0, backoff_cap=4)
    p.sent(0, 100.0)
    for k, want in ((1, 2.0), (2, 4.0), (3, 4.0)):   # 2x, 4x, cap at 4x
        p.failed(0)
        p.sent(0, 100.0)
        assert not p.due(0, 100.0 + want - 0.01), k
        assert p.due(0, 100.0 + want), k
    p.ok(0)
    p.sent(0, 100.0)
    assert p.due(0, 101.0)          # back to the base interval


def test_query_pacer_forget_resets_key():
    p = QueryPacer(10.0)
    p.sent(3, 100.0)
    p.failed(3)
    assert not p.due(3, 101.0)
    p.forget(3)
    assert p.due(3, 101.0)          # a fresh process is queried NOW


# -- ClockSync: the midpoint offset estimate --------------------------------


def test_clock_sync_midpoint_math_and_uncertainty():
    wall = FakeClock(1000.0)
    cs = ClockSync(wall)
    ping = cs.ping_payload(0, t0=50.0)
    assert ping["op"] == "ping" and ping["t0"] == 50.0
    # Echo arrives 40ms later on the monotonic clock; the child's wall
    # read was 2.5s ahead of the midpoint estimate.
    sample = cs.on_echo(0, {"seq": ping["seq"], "wall": 1002.52,
                            "pid": 777}, t1=50.04)
    assert sample["pid"] == 777
    assert sample["rtt_s"] == pytest.approx(0.04)
    assert sample["uncertainty_s"] == pytest.approx(0.02)
    # mid_wall = 1000.0 + rtt/2 = 1000.02 -> skew = 2.5
    assert sample["skew_s"] == pytest.approx(2.5)
    doc = cs.doc()
    assert doc["schema"] == 1
    assert doc["children"]["777"]["skew_s"] == pytest.approx(2.5)


def test_clock_sync_keeps_min_rtt_sample_per_pid():
    wall = FakeClock(1000.0)
    cs = ClockSync(wall)
    p1 = cs.ping_payload(0, t0=10.0)
    cs.on_echo(0, {"seq": p1["seq"], "wall": 1001.0, "pid": 9}, t1=10.2)
    p2 = cs.ping_payload(0, t0=20.0)
    cs.on_echo(0, {"seq": p2["seq"], "wall": 1001.0, "pid": 9}, t1=20.02)
    p3 = cs.ping_payload(0, t0=30.0)
    cs.on_echo(0, {"seq": p3["seq"], "wall": 1001.0, "pid": 9}, t1=30.5)
    best = cs.skew_for_pid(9)
    assert best["rtt_s"] == pytest.approx(0.02)     # the tightest bound
    assert best["samples"] == 3                     # but every echo counted
    # A restarted replica is a NEW pid: measured from scratch.
    p4 = cs.ping_payload(0, t0=40.0)
    cs.on_echo(0, {"seq": p4["seq"], "wall": 1001.0, "pid": 10}, t1=40.3)
    assert cs.skew_for_pid(10)["rtt_s"] == pytest.approx(0.3)
    assert cs.skew_for_pid(9)["rtt_s"] == pytest.approx(0.02)


def test_clock_sync_unmatched_and_dropped_pings():
    cs = ClockSync(FakeClock(0.0))
    assert cs.on_echo(0, {"seq": 999}, t1=1.0) is None   # never sent
    ping = cs.ping_payload(2, t0=1.0)
    cs.drop_pending(2)          # replica 2 got a fresh process
    assert cs.on_echo(2, {"seq": ping["seq"], "wall": 5.0, "pid": 1},
                      t1=2.0) is None
    # The pending table is hard-bounded.
    for _ in range(ClockSync.MAX_PENDING + 50):
        cs.ping_payload(0, t0=0.0)
    assert len(cs._pending) <= ClockSync.MAX_PENDING


# -- SLOMonitor: burn formulas + the dual-window state machine --------------


def test_slo_disabled_monitor_is_inert():
    slo = SLOMonitor()
    assert not slo.enabled
    slo.observe(False, 1e9, now=0.0)
    st = slo.evaluate(0.0)
    assert st == {"enabled": False, "firing": []}
    assert not slo.alerting and not slo.alerts


def test_slo_p99_fires_on_dual_window_burn_and_clears():
    clk = FakeClock(1000.0)
    slo = SLOMonitor(p99_ms=10.0, clock=clk, min_requests=4)
    for _ in range(6):
        slo.observe(True, 50.0)     # all over target: burn = 1/0.01 = 100
    st = slo.evaluate()
    obj = st["objectives"]["p99"]
    assert obj["firing"] and st["firing"] == ["p99"]
    assert obj["fast_burn"] == pytest.approx(100.0)
    assert slo.alerting and slo.alerts_fired == 1
    assert slo.alerts[-1]["state"] == "firing"
    # The fast window drains past 60s -> burn 0 -> the alert clears.
    clk.advance(61.0)
    st = slo.evaluate()
    assert st["firing"] == [] and not slo.alerting
    assert slo.alerts_cleared == 1
    assert [a["state"] for a in slo.alerts] == ["firing", "cleared"]


def test_slo_min_requests_guards_one_bad_second():
    slo = SLOMonitor(p99_ms=10.0, clock=FakeClock(0.0), min_requests=12)
    for _ in range(5):
        slo.observe(True, 99.0)
    assert not slo.evaluate()["objectives"]["p99"]["firing"]
    for _ in range(7):
        slo.observe(True, 99.0)     # now n >= min_requests
    assert slo.evaluate()["objectives"]["p99"]["firing"]


def test_slo_availability_and_error_rate_burn_formulas():
    slo = SLOMonitor(availability=0.9, error_rate=0.25,
                     clock=FakeClock(0.0), min_requests=1)
    for ok in (True, False, True, False):    # 50% errors
        slo.observe(ok, 1.0)
    st = slo.evaluate()
    # availability budget = 0.1 -> burn 5; error_rate budget = 0.25 -> 2.
    assert st["objectives"]["availability"]["fast_burn"] == \
        pytest.approx(5.0)
    assert st["objectives"]["error_rate"]["fast_burn"] == pytest.approx(2.0)
    assert st["firing"] == ["availability", "error_rate"]


def test_slo_alert_lifecycle_events_count_truncated_not_violation():
    """slo_alert chains have no `received`: the exactly-once terminal
    audit must report them truncated, never as an accounting failure."""
    clk = FakeClock(0.0)
    lc = LifecycleTracer(clock=clk)
    slo = SLOMonitor(p99_ms=1.0, clock=clk, min_requests=1, lifecycle=lc)
    for _ in range(3):
        slo.observe(True, 50.0)
    slo.evaluate()
    evs = [e for e in lc.events() if e["kind"] == "slo_alert"]
    assert evs and evs[-1]["id"] == "slo:p99"
    assert evs[-1]["state"] == "firing"
    acc = lc.accounting()
    assert acc["terminal_ok"] and acc["truncated"] >= 1


def test_slo_registry_counters_on_transitions():
    reg = MetricsRegistry()
    reg.declare(*FLEETOBS_COUNTERS)
    clk = FakeClock(0.0)
    slo = SLOMonitor(p99_ms=1.0, clock=clk, min_requests=1, registry=reg)
    for _ in range(3):
        slo.observe(True, 50.0)
    slo.evaluate()
    clk.advance(61.0)
    slo.evaluate()
    counters = reg.snapshot()["counters"]
    assert counters["slo_alerts_fired"] == 1
    assert counters["slo_alerts_cleared"] == 1


# -- FleetObs: the scraper ---------------------------------------------------


class StubSup:
    """Duck-typed supervisor surface FleetObs.tick consumes."""

    def __init__(self, clock, n=2):
        self.clock = clock
        self.queries = []
        self.fail = set()
        self.children = [
            {"index": k, "state": "ok", "live": True, "restarts": 0,
             "inflight": 0, "pid": 500 + k, "health": {},
             "stats": {"queue_depth": k, "latency_p50_ms": 4.0,
                       "latency_p99_ms": 9.0, "compiles": 2,
                       "slots": 8, "residents": 2,
                       "cache_hits": 3, "cache_misses": 1,
                       "attribution": {"components": {
                           "decode": {"p99_ms": 5.5}}}}}
            for k in range(n)]

    def scrape_snapshot(self):
        return {
            "fleet": {"replicas": len(self.children),
                      "in_service": sum(1 for c in self.children
                                        if c["live"]),
                      "outstanding": 0, "parked": 0, "completed": 7,
                      "latency_p50_ms": 4.0, "latency_p99_ms": 9.0},
            "children": [dict(c) for c in self.children],
        }

    def query_child(self, index, payload):
        self.queries.append((index, dict(payload)))
        return index not in self.fail


def make_obs(tmp_path, clk=None, **kw):
    clk = clk or FakeClock(100.0)
    kw.setdefault("scrape_interval_s", 1.0)
    kw.setdefault("wall", FakeClock(5000.0))
    fo = FleetObs(str(tmp_path / "obs"), clock=clk, **kw)
    return fo, StubSup(clk), clk


def read_jsonl(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def test_fleetobs_scrapes_on_cadence_with_schema_stamp(tmp_path):
    fo, sup, clk = make_obs(tmp_path)
    fo.tick(sup, clk())
    fo.tick(sup, clk())             # same instant: no second sample
    clk.advance(0.5)
    fo.tick(sup, clk())             # mid-interval: still one
    clk.advance(0.5)
    fo.tick(sup, clk())             # the cadence: two
    rows = read_jsonl(fo.metrics_path)
    assert len(rows) == 2 and len(fo.series()) == 2
    row = rows[0]
    assert row["schema"] == 1 and row["kind"] == "fleet_sample"
    assert row["seq"] == 1 and row["interval_ms"] == 1000.0
    assert row["fleet"]["replicas"] == 2
    c0 = row["children"][0]
    assert c0["slot_occupancy"] == pytest.approx(0.25)    # 2/8 slots
    assert c0["cache_hit_rate"] == pytest.approx(0.75)    # 3/(3+1)
    assert c0["attribution_p99_ms"] == {"decode": 5.5}
    # Stats queries went to both live children through query_child.
    stats_q = [q for q in sup.queries if q[1] == {"op": "stats"}]
    assert [i for i, _ in stats_q][:2] == [0, 1]


def test_fleetobs_zero_gap_rows_cover_dead_replicas(tmp_path):
    fo, sup, clk = make_obs(tmp_path)
    fo.tick(sup, clk())
    sup.children[1].update(live=False, state="backoff", stats=None,
                           restarts=1)
    n_alive = len(sup.queries)
    clk.advance(1.0)
    fo.tick(sup, clk())
    rows = read_jsonl(fo.metrics_path)
    assert [len(r["children"]) for r in rows] == [2, 2]   # zero gaps
    dead = rows[1]["children"][1]
    assert dead["live"] is False and dead["state"] == "backoff"
    assert dead["latency_p99_ms"] is None     # tolerant of missing stats
    # But no stats/ping queries go to a dead child.
    sent_while_dead = [q for q in sup.queries[n_alive:] if q[0] == 1]
    assert not sent_while_dead


def test_fleetobs_ping_flow_writes_clock_sync(tmp_path):
    reg = MetricsRegistry()
    fo, sup, clk = make_obs(tmp_path, registry=reg)
    fo.tick(sup, clk())
    pings = [(i, q) for i, q in sup.queries if q.get("op") == "ping"]
    assert sorted(i for i, _ in pings) == [0, 1]
    for idx, ping in pings:
        fo.on_ping(idx, {"seq": ping["seq"], "wall": 9000.0,
                         "pid": 500 + idx}, t1=clk())
    clk.advance(1.0)
    fo.tick(sup, clk())             # the scrape turn flushes the doc
    with open(fo.sync_path) as f:
        doc = json.load(f)
    assert set(doc["children"]) == {"500", "501"}
    # rtt 0 on the fake clock: skew is exactly child_wall - wall_send.
    assert doc["children"]["500"]["skew_s"] == pytest.approx(4000.0)
    counters = reg.snapshot()["counters"]
    assert counters["fleet_pings"] >= 2
    assert counters["fleet_ping_echoes"] == 2
    assert counters["fleet_samples"] == 2
    assert counters["fleet_child_rows"] == 4


def test_fleetobs_failed_query_backs_off_then_forget_resets(tmp_path):
    fo, sup, clk = make_obs(tmp_path)
    sup.fail.add(1)
    fo.tick(sup, clk())
    n0 = len([1 for i, q in sup.queries
              if i == 1 and q.get("op") == "ping"])
    clk.advance(1.0)
    fo.tick(sup, clk())             # child 1 backed off: not due at 1x
    n1 = len([1 for i, q in sup.queries
              if i == 1 and q.get("op") == "ping"])
    assert n0 == 1 and n1 == 1
    fo.on_child_assigned(1)         # fresh process: queried immediately
    clk.advance(0.1)
    fo.tick(sup, clk())
    n2 = len([1 for i, q in sup.queries
              if i == 1 and q.get("op") == "ping"])
    assert n2 == 2


def test_fleetobs_ring_is_bounded_and_file_is_complete(tmp_path):
    fo, sup, clk = make_obs(tmp_path, ring_len=8)
    for _ in range(12):
        fo.tick(sup, clk())
        clk.advance(1.0)
    assert len(fo.series()) == 8                     # bounded view
    assert fo.series()[-1]["seq"] == 12
    assert len(read_jsonl(fo.metrics_path)) == 12    # durable: everything


def test_fleetobs_rotation_writes_parts_and_atomic_index(tmp_path):
    fo, sup, clk = make_obs(tmp_path, rotate_rows=16, fsync_every=4)
    for _ in range(20):
        fo.tick(sup, clk())
        clk.advance(1.0)
    part0 = os.path.join(fo.out_dir, "fleet_metrics_part0.jsonl")
    assert len(read_jsonl(part0)) == 16
    assert len(read_jsonl(fo.metrics_path)) == 4
    with open(os.path.join(fo.out_dir, "fleet_metrics_index.json")) as f:
        index = json.load(f)
    assert index["parts"] == ["fleet_metrics_part0.jsonl"]
    assert index["active"] == "fleet_metrics.jsonl"


def test_fleetobs_drains_alerts_and_close_flushes(tmp_path):
    clk = FakeClock(100.0)
    slo = SLOMonitor(p99_ms=1.0, clock=clk, min_requests=1)
    fo, sup, _ = make_obs(tmp_path, clk=clk, slo=slo)
    for _ in range(3):
        fo.observe_request(True, 50.0)
    fo.tick(sup, clk())             # evaluate fires + drains the alert
    alerts = read_jsonl(fo.alerts_path)
    assert len(alerts) == 1 and alerts[0]["state"] == "firing"
    assert fo.alerting
    assert fo.series()[-1]["slo"]["firing"] == ["p99"]
    # The clear transition drains on the NEXT scrape turn, and close()
    # flushes anything still unwritten.
    clk.advance(61.0)
    clk.advance(1.0)
    fo.tick(sup, clk())
    assert read_jsonl(fo.alerts_path)[-1]["state"] == "cleared"
    fo.close()
    fo.tick(sup, clk())             # closed: a late tick is a no-op
    assert len(read_jsonl(fo.alerts_path)) == 2


def test_fleetobs_attaches_slo_provider_to_blackbox(tmp_path):
    clk = FakeClock(0.0)
    lc = LifecycleTracer(clock=clk)
    slo = SLOMonitor(p99_ms=1.0, clock=clk, min_requests=1, lifecycle=lc)
    fo, sup, _ = make_obs(tmp_path, clk=clk, slo=slo, lifecycle=lc)
    for _ in range(2):
        fo.observe_request(True, 9.0)
    fo.tick(sup, clk())
    bb = lc.blackbox(reason="test")
    assert bb["fleet_slo"]["firing"] == ["p99"]
    acc = bb["accounting"]
    assert acc["terminal_ok"]       # the slo_alert chain is truncated,
    assert acc["truncated"] >= 1    # never an accounting violation


# -- fleet_report gates ------------------------------------------------------


def _mk_sample(seq, wall, *, replicas=2, n_children=None, firing=(),
               interval_ms=1000.0):
    n = replicas if n_children is None else n_children
    return {
        "schema": 1, "kind": "fleet_sample", "seq": seq, "t": wall,
        "wall": wall, "interval_ms": interval_ms,
        "fleet": {"replicas": replicas, "in_service": n, "outstanding": 0,
                  "parked": 0, "completed": 5 * seq,
                  "latency_p50_ms": 4.0, "latency_p99_ms": 9.0},
        "children": [
            {"index": k, "state": "ok", "live": True, "restarts": 0,
             "inflight": 0, "queue_depth": 0, "latency_p50_ms": 4.0,
             "latency_p99_ms": 9.0, "compiles": 2}
            for k in range(n)],
        "slo": {"enabled": True, "firing": sorted(firing),
                "objectives": {"p99": {"target": 50.0, "fast_burn": 0.1,
                                       "slow_burn": 0.1,
                                       "firing": bool(firing)}},
                "alerts_fired": len(firing), "alerts_cleared": 0},
    }


def _run_fleet_report(tmp_path, samples, extra=()):
    path = tmp_path / "fleet_metrics.jsonl"
    with open(path, "w") as f:
        for s in samples:
            f.write(json.dumps(s) + "\n")
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "fleet_report.py"),
         "--file", str(path), *extra],
        capture_output=True, text=True, cwd=REPO)


def test_fleet_report_healthy_run_renders_and_passes(tmp_path):
    samples = [_mk_sample(k + 1, 100.0 + k) for k in range(6)]
    proc = _run_fleet_report(tmp_path, samples)
    assert proc.returncode == 0, proc.stderr
    assert "fleet metrics" in proc.stdout
    assert "child 0" in proc.stdout and "child 1" in proc.stdout
    assert "slo p99" in proc.stdout and "FIRING" not in proc.stdout


def test_fleet_report_gates_on_firing_slo(tmp_path):
    samples = [_mk_sample(1, 100.0),
               _mk_sample(2, 101.0, firing=("p99",)),
               _mk_sample(3, 102.0)]
    proc = _run_fleet_report(tmp_path, samples)
    assert proc.returncode == 1
    assert "SLO burn-rate violation" in proc.stderr
    assert "FIRING" not in proc.stdout  # last sample's view is clean


def test_fleet_report_gates_on_scrape_blackout(tmp_path):
    samples = [_mk_sample(1, 100.0), _mk_sample(2, 101.0),
               _mk_sample(3, 108.0)]     # 7s gap at a 1s cadence
    proc = _run_fleet_report(tmp_path, samples)
    assert proc.returncode == 1
    assert "scrape blackout" in proc.stderr


def test_fleet_report_gates_on_coverage_hole(tmp_path):
    samples = [_mk_sample(1, 100.0),
               _mk_sample(2, 101.0, n_children=1)]   # a missing slot row
    proc = _run_fleet_report(tmp_path, samples)
    assert proc.returncode == 1
    assert "coverage hole" in proc.stderr and "zero-gap" in proc.stderr


def test_fleet_report_no_samples_and_torn_lines(tmp_path):
    path = tmp_path / "fleet_metrics.jsonl"
    path.write_text('{"kind": "fleet_sa')      # only a torn line
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "fleet_report.py"),
         "--file", str(path)], capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 1
    assert "no fleet_sample rows" in proc.stderr
    # A torn TAIL after good rows is skipped, not fatal.
    with open(path, "w") as f:
        f.write(json.dumps(_mk_sample(1, 100.0)) + "\n")
        f.write('{"kind": "fleet_sa')
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "fleet_report.py"),
         "--file", str(path)], capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 0, proc.stderr


def test_fleet_report_reads_rotated_parts_from_dir(tmp_path):
    fo, sup, clk = make_obs(tmp_path, rotate_rows=16)
    for _ in range(20):
        fo.tick(sup, clk())
        clk.advance(1.0)
    fo.close()
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "fleet_report.py"),
         "--dir", fo.out_dir, "--json", str(tmp_path / "fr.json")],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 0, proc.stderr
    with open(tmp_path / "fr.json") as f:
        assert json.load(f)["samples"] == 20      # parts + active file


# -- fleet_trace: the cross-process stitch ----------------------------------


def _import_fleet_trace():
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        import fleet_trace
    finally:
        sys.path.pop(0)
    return fleet_trace


def _write_trace(path, pid, epoch, events):
    doc = {"traceEvents":
           [{"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
             "args": {"name": "M"}}] + events,
           "displayTimeUnit": "ms",
           "otherData": {"pid": pid, "wall_epoch_unix_s": epoch}}
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        json.dump(doc, f)


def _seed_fleet_traces(root):
    """Supervisor pid 100 (epoch 1000.0) owns request id "7"; replica0
    pid 200 runs 2.0s fast (epoch 1002.55, true offset 0.55s) and its
    local track "v1" echoes trace_id 7; replica1 pid 300 has no sync."""
    sup_events = [
        {"name": "request", "cat": "lifecycle", "ph": "b", "id": "7",
         "ts": 0.0, "pid": 100, "tid": 0, "args": {"kind": "received"}},
        {"name": "routed", "cat": "lifecycle", "ph": "n", "id": "7",
         "ts": 100.0, "pid": 100, "tid": 0},
        {"name": "request", "cat": "lifecycle", "ph": "e", "id": "7",
         "ts": 600000.0, "pid": 100, "tid": 0,
         "args": {"kind": "completed"}},
    ]
    child_events = [
        {"name": "request", "cat": "lifecycle", "ph": "b", "id": "v1",
         "ts": 0.0, "pid": 200, "tid": 0,
         "args": {"kind": "received", "trace_id": 7}},
        {"name": "decode_chunk", "cat": "lifecycle", "ph": "n",
         "id": "v1", "ts": 200.0, "pid": 200, "tid": 0},
        {"name": "request", "cat": "lifecycle", "ph": "e", "id": "v1",
         "ts": 1500.0, "pid": 200, "tid": 0,
         "args": {"kind": "completed"}},
    ]
    _write_trace(os.path.join(root, "trace", "trace_100r0.json"),
                 100, 1000.0, sup_events)
    _write_trace(os.path.join(root, "replica0", "trace",
                              "trace_200r0.json"), 200, 1002.55,
                 child_events)
    _write_trace(os.path.join(root, "replica1", "trace",
                              "trace_300r0.json"), 300, 1000.2,
                 [{"name": "host", "cat": "span", "ph": "X", "ts": 10.0,
                   "dur": 5.0, "pid": 300, "tid": 1}])
    with open(os.path.join(root, "clock_sync.json"), "w") as f:
        json.dump({"schema": 1, "supervisor_pid": 100,
                   "children": {"200": {"index": 0, "pid": 200,
                                        "skew_s": 2.0,
                                        "uncertainty_s": 0.002,
                                        "rtt_s": 0.004, "samples": 3}}},
                  f)


def test_fleet_trace_merges_rebases_and_stitches(tmp_path):
    ft = _import_fleet_trace()
    root = str(tmp_path)
    _seed_fleet_traces(root)
    summary = ft.merge_fleet_trace(root)
    assert summary["stitched_tracks"] == 1
    assert summary["child_pids"] == 2
    assert summary["missing_sync_pids"] == [300]
    with open(summary["out"]) as f:
        doc = json.load(f)
    other = doc["otherData"]
    assert other["merged"] is True
    assert other["base_wall_epoch_unix_s"] == pytest.approx(1000.0)
    evs = doc["traceEvents"]
    # Child timeline rebased: corrected epoch 1002.55 - 2.0 = 1000.55,
    # so its local ts 0 lands at +550000us on the merged timeline; its
    # async ids are rewritten onto the supervisor's request id.
    child_b = [e for e in evs if e.get("ph") == "b" and e["pid"] == 200]
    assert child_b[0]["id"] == "7"
    assert child_b[0]["ts"] == pytest.approx(550000.0)
    names = {e["args"]["name"] for e in evs
             if e.get("ph") == "M" and e["name"] == "process_name"}
    assert names == {"supervisor (pid 100)", "replica0 (pid 200)",
                     "replica1 (pid 300)"}
    skews = {e["pid"]: e["args"] for e in evs
             if e["name"] == "clock_skew"}
    assert skews[200]["skew_ms"] == pytest.approx(2000.0)
    assert skews[200]["synced"] is True
    assert skews[300]["synced"] is False     # merged with zero skew
    assert evs == sorted(evs, key=lambda e: e.get("ts", 0.0))


def test_fleet_trace_cli_exit_codes(tmp_path):
    script = os.path.join(REPO, "scripts", "fleet_trace.py")
    proc = subprocess.run(
        [sys.executable, script, "--dir", str(tmp_path / "empty")],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 1
    assert "no supervisor trace" in proc.stderr
    _seed_fleet_traces(str(tmp_path))
    proc = subprocess.run(
        [sys.executable, script, "--dir", str(tmp_path)],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 0, proc.stderr
    summary = json.loads(proc.stdout.split("fleet_trace: ", 1)[1])
    assert summary["stitched_tracks"] == 1
    assert "WARNING" in proc.stderr      # pid 300 had no sync sample


# -- trace_report: merged rendering + the legacy pin ------------------------


def _run_trace_report(trace_dir, json_out=None):
    cmd = [sys.executable, os.path.join(REPO, "scripts",
                                        "trace_report.py"),
           "--trace_dir", str(trace_dir)]
    if json_out:
        cmd += ["--json", str(json_out)]
    return subprocess.run(cmd, capture_output=True, text=True, cwd=REPO)


def test_trace_report_merged_view_pairs_across_pids(tmp_path):
    ft = _import_fleet_trace()
    _seed_fleet_traces(str(tmp_path))
    summary = ft.merge_fleet_trace(str(tmp_path),
                                   str(tmp_path / "out" /
                                       "fleet_trace.json"))
    proc = _run_trace_report(tmp_path / "out",
                             json_out=tmp_path / "tr.json")
    assert proc.returncode == 0, proc.stderr
    assert "[merged fleet trace]" in proc.stdout
    assert "process rows" in proc.stdout
    assert "stitched across processes" in proc.stdout
    assert "supervisor (pid 100)" in proc.stdout
    with open(tmp_path / "tr.json") as f:
        rep = json.load(f)
    assert rep["merged"] is True
    # Depth-counted pairing: the supervisor's b..e encloses the child's
    # — ONE track whose duration is the outer (cross-process) span.
    track = {r["span"]: r for r in rep["async_tracks"]}["request"]
    assert track["count"] == 1
    assert track["total_ms"] == pytest.approx(600.0)
    assert rep["async_meta"]["open_tracks"] == 0
    skew = {int(p["pid"]): p for p in rep["processes"]}
    assert skew[200]["skew_ms"] == pytest.approx(2000.0)


def test_trace_report_single_process_rendering_unchanged(tmp_path):
    """The legacy pin: a plain (non-merged) trace dir renders with the
    pid-keyed async pairing and NO merged/process-row sections."""
    _write_trace(str(tmp_path / "trace_100r0.json"), 100, 1000.0, [
        {"name": "request", "cat": "lifecycle", "ph": "b", "id": "a",
         "ts": 0.0, "pid": 100, "tid": 0},
        {"name": "request", "cat": "lifecycle", "ph": "e", "id": "a",
         "ts": 2000.0, "pid": 100, "tid": 0},
        {"name": "compute", "cat": "span", "ph": "X", "ts": 0.0,
         "dur": 1000.0, "pid": 100, "tid": 1},
    ])
    proc = _run_trace_report(tmp_path, json_out=tmp_path / "tr.json")
    assert proc.returncode == 0, proc.stderr
    assert "[merged fleet trace]" not in proc.stdout
    assert "process rows" not in proc.stdout
    with open(tmp_path / "tr.json") as f:
        rep = json.load(f)
    assert rep["merged"] is False
    track = {r["span"]: r for r in rep["async_tracks"]}["request"]
    assert track["count"] == 1 and track["total_ms"] == pytest.approx(2.0)


# -- supervisor integration --------------------------------------------------


class PingFakeChild(FakeChild):
    """FakeChild + the clock-sync echo (server.py's ping handler) and a
    unique pid per process life, so per-pid skew tables distinguish a
    restarted replica."""

    WALL = 9000.0
    _next_pid = [61000]

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self._next_pid[0] += 1
        self.pid = self._next_pid[0]

    def send_line(self, line):
        if self.alive and not self.frozen:
            req = json.loads(line)
            if req.get("op") == "ping":
                self.sent.append(line)
                self._outbox.append(json.dumps(
                    {"op": "ping", "seq": req.get("seq"),
                     "t0": req.get("t0"), "mono": 0.0,
                     "wall": self.WALL, "pid": self.pid}))
                return
        super().send_line(line)


def build_sup_obs(tmp_path, n=2, *, slo=None, obs_kw=None, **kw):
    from cst_captioning_tpu.serving.supervisor import ProcessFleetSupervisor

    clock = kw.pop("clock", None) or FakeClock()
    fo = FleetObs(str(tmp_path / "obs"), clock=clock,
                  wall=FakeClock(5000.0), slo=slo, **(obs_kw or {}))
    children = []

    def launcher(k):
        child = PingFakeChild(k, os.path.join(str(tmp_path),
                                              f"replica{k}"))
        children.append(child)
        return child

    kw.setdefault("backoff_ms", 200.0)
    kw.setdefault("incident_dir", os.path.join(str(tmp_path), "incidents"))
    sup = ProcessFleetSupervisor(launcher, n, clock=clock,
                                 spawn_async=False, fleet_obs=fo, **kw)
    return sup, children, clock, fo


def test_supervisor_stamps_trace_context_only_when_armed(tmp_path):
    sup, children, clock, fo = build_sup_obs(tmp_path, 1)
    got = []
    sup.submit("c1", "v3", respond=got.append)
    msg = json.loads(children[0].sent[-1])
    assert msg["trace"]["id"] == msg["id"]
    assert msg["trace"]["recv_s"] == pytest.approx(clock())
    tick_until(sup, lambda: got)
    assert got[-1]["caption"] == FakeChild.caption_for("v3")

    from test_supervisor import build_sup
    sup2, children2, _ = build_sup(tmp_path / "unarmed", 1)
    sup2.submit("c2", "v3", respond=[].append)
    assert "trace" not in json.loads(children2[0].sent[-1])


def test_supervisor_clock_sync_end_to_end_and_restart_remeasures(tmp_path):
    sup, children, clock, fo = build_sup_obs(tmp_path, 2)
    sup.tick()                   # pings out with the scrape turn
    sup.tick()                   # echoes pumped in
    doc = fo.clock_sync.doc()
    pids = {children[0].pid, children[1].pid}
    assert {int(p) for p in doc["children"]} == pids
    # Fake clocks never advance: rtt 0, skew = 9000 - 5000 exactly.
    for rec in doc["children"].values():
        assert rec["skew_s"] == pytest.approx(4000.0)
        assert rec["uncertainty_s"] == 0.0
    clock.advance(1.1)
    sup.tick()                   # next scrape turn flushes the table
    assert os.path.exists(fo.sync_path)

    children[0].kill()
    sup.tick()                   # reap -> backoff
    clock.advance(0.5)
    sup.tick()                   # restart hatches: a NEW pid
    clock.advance(1.1)
    sup.tick()                   # fresh process pinged immediately
    sup.tick()
    new_pid = [c for c in children if c.replica == 0][-1].pid
    assert new_pid not in pids
    assert str(new_pid) in fo.clock_sync.doc()["children"]


def test_supervisor_scrape_covers_every_slot_across_restart(tmp_path):
    sup, children, clock, fo = build_sup_obs(
        tmp_path, 2, obs_kw={"scrape_interval_s": 0.5})
    sup.tick()
    children[1].kill()
    for _ in range(6):
        clock.advance(0.5)
        sup.tick()               # through backoff AND restart
    rows = read_jsonl(fo.metrics_path)
    assert len(rows) >= 5
    assert all(len(r["children"]) == 2 for r in rows)      # zero gaps
    states = [r["children"][1]["state"] for r in rows]
    assert "backoff" in states and states[-1] == "ok"
    assert rows[-1]["children"][1]["restarts"] == 1


def test_supervisor_health_poll_is_paced_through_shared_pacer(tmp_path):
    sup, children, clock, fo = build_sup_obs(tmp_path, 1)
    sup.tick()
    sup.tick()                   # same instant: the pacer holds it back
    health_sent = [l for l in children[0].sent
                   if json.loads(l).get("op") == "health"]
    assert len(health_sent) == 1
    clock.advance(sup.health_interval_s + 0.01)
    sup.tick()
    health_sent = [l for l in children[0].sent
                   if json.loads(l).get("op") == "health"]
    assert len(health_sent) == 2
    # The one shared query path answers False for a dead replica.
    children[0].kill()
    assert sup.query_child(0, {"op": "health"}) is False


def test_supervisor_health_degrades_while_slo_fires(tmp_path):
    clock = FakeClock()
    slo = SLOMonitor(p99_ms=1.0, clock=clock, min_requests=1)
    sup, children, clock, fo = build_sup_obs(tmp_path, 1, slo=slo,
                                             clock=clock)
    got = []
    sup.submit("a", "v1", respond=got.append)
    clock.advance(0.05)          # 50ms >> the 1ms objective
    tick_until(sup, lambda: got)
    clock.advance(1.1)
    sup.tick()                   # the scrape turn evaluates and fires
    assert fo.alerting
    h = sup.health_payload()
    assert h["status"] == "degraded"       # every replica reports ok...
    assert h["per_replica"][0]["status"] == "ok"
    assert h["slo"]["firing"] == ["p99"]
    assert sup.stats()["slo"]["firing"] == ["p99"]
    # The supervisor-written terminals count as failed outcomes.
    sup2, _, clock2, fo2 = build_sup_obs(
        tmp_path / "b", 1,
        slo=SLOMonitor(error_rate=0.1, clock=FakeClock(),
                       min_requests=1))
    got2 = []
    sup2.submit("x", "v1", respond=got2.append)
    sup2.hard_abort()
    assert got2 and got2[-1].get("error") == "rejected_draining"
    assert fo2.slo._outcomes and fo2.slo._outcomes[-1][1] is False


# -- opts --------------------------------------------------------------------


def test_fleet_obs_flags_defaults_env_fallback_and_validation(monkeypatch):
    from cst_captioning_tpu.opts import parse_opts

    ns = parse_opts(["--serve_demo", "1"])
    assert ns.fleet_scrape_ms == 1000
    assert ns.slo_p99_ms == 0
    assert ns.slo_availability == 0.0
    assert ns.slo_error_rate == 0.0

    monkeypatch.setenv("CST_FLEET_SCRAPE_MS", "250")
    monkeypatch.setenv("CST_SLO_P99_MS", "80")
    monkeypatch.setenv("CST_SLO_AVAILABILITY", "0.99")
    monkeypatch.setenv("CST_SLO_ERROR_RATE", "0.05")
    ns = parse_opts(["--serve_demo", "1"])
    assert ns.fleet_scrape_ms == 250
    assert ns.slo_p99_ms == 80
    assert ns.slo_availability == pytest.approx(0.99)
    assert ns.slo_error_rate == pytest.approx(0.05)
    # Explicit flags beat the environment.
    ns = parse_opts(["--serve_demo", "1", "--slo_p99_ms", "120"])
    assert ns.slo_p99_ms == 120

    for argv in (["--fleet_scrape_ms", "0"],
                 ["--slo_p99_ms", "-1"],
                 ["--slo_availability", "1.0"],   # zero error budget
                 ["--slo_availability", "-0.1"],
                 ["--slo_error_rate", "1.5"],
                 ["--slo_error_rate", "nope"]):
        with pytest.raises(SystemExit):
            parse_opts(argv)


def test_ratio_usage_error_is_one_line(capsys):
    from cst_captioning_tpu.opts import parse_opts

    with pytest.raises(SystemExit):
        parse_opts(["--slo_availability", "1.0"])
    err = capsys.readouterr().err
    msg = [l for l in err.splitlines() if "slo_availability" in l
           and "error" in l]
    assert len(msg) == 1
    assert "[0, 1)" in msg[0] and "CST_SLO_AVAILABILITY" in msg[0]


# -- doc pins ----------------------------------------------------------------


def test_observability_doc_pins_fleet_plane():
    with open(os.path.join(REPO, "OBSERVABILITY.md")) as f:
        text = f.read()
    for name in FLEETOBS_COUNTERS:
        assert name in text, f"OBSERVABILITY.md fleet counter: {name}"
    for token in ("Fleet plane", "fleet_metrics.jsonl", "clock_sync.json",
                  "slo_alerts.jsonl", "fleet_trace.py", "fleet_report.py",
                  "--fleet_scrape_ms", "--slo_p99_ms",
                  "--slo_availability", "--slo_error_rate",
                  "fleet-obs-demo", "burn"):
        assert token in text, f"OBSERVABILITY.md Fleet plane: {token!r}"


def test_serving_doc_pins_wire_addendum():
    with open(os.path.join(REPO, "SERVING.md")) as f:
        text = f.read()
    for token in ('"op": "ping"', "serve_ping_queries", "trace",
                  "recv_s"):
        assert token in text, f"SERVING.md wire addendum: {token!r}"


# -- slow: the real-subprocess drill ----------------------------------------


@pytest.mark.slow
def test_fleet_obs_probe_drill_end_to_end(tmp_path):
    """THE acceptance drill: the seeded 3-child SIGKILL probe with the
    fleet plane armed — scraped series with every slot covered each
    interval (zero gaps across the restart), clock-synced children, a
    merged skew-corrected Perfetto file with stitched per-request
    cross-process tracks, and every report gate green."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    root = str(tmp_path / "supervise")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts",
                                      "serve_supervisor.py"),
         "--serve_demo", "1", "--supervise_probe", "1",
         "--supervise_replicas", "3", "--serve_demo_eos_bias", "-2",
         "--decode_chunk", "2", "--beam_size", "1",
         "--fleet_scrape_ms", "200", "--slo_p99_ms", "60000",
         "--slo_availability", "0.5", "--supervise_dir", root],
        capture_output=True, text=True, timeout=600, cwd=REPO, env=env)
    assert proc.returncode == 0, proc.stderr[-4000:]
    rec = json.loads(proc.stdout.splitlines()[-1])
    assert rec["slo"]["enabled"] and rec["slo"]["ok"]
    assert rec["slo"]["firing"] == []
    assert rec["fleet_obs"]["samples"] >= 1
    assert rec["fleet_obs"]["clock_synced_pids"] >= 3   # incl. restart
    assert rec["supervisor"]["requeued"] >= 1           # the kill landed

    # The scraped series: schema-stamped, one row per slot per sample.
    samples = [r for r in read_jsonl(os.path.join(
        root, "fleet_metrics.jsonl")) if r.get("kind") == "fleet_sample"]
    assert samples
    assert all(r["schema"] == 1 for r in samples)
    assert all(len(r["children"]) == 3 for r in samples)
    restarts = max(c["restarts"] for c in samples[-1]["children"])
    assert restarts >= 1                                # ...and covered it

    # The merge: one Perfetto file, stitched tracks, skew-corrected.
    merge = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "fleet_trace.py"),
         "--dir", root], capture_output=True, text=True, cwd=REPO)
    assert merge.returncode == 0, merge.stderr
    summary = json.loads(merge.stdout.split("fleet_trace: ", 1)[1])
    assert summary["stitched_tracks"] >= 1
    assert summary["child_pids"] >= 3
    assert not summary["missing_sync_pids"]

    # trace_report renders the merged file (root holds fleet_trace.json).
    tr = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "trace_report.py"),
         "--trace_dir", root, "--json", str(tmp_path / "tr.json")],
        capture_output=True, text=True, cwd=REPO)
    assert tr.returncode == 0, tr.stderr
    assert "[merged fleet trace]" in tr.stdout
    with open(tmp_path / "tr.json") as f:
        rep = json.load(f)
    assert rep["merged"] and len(rep["processes"]) >= 4
    tracks = {r["span"]: r for r in rep["async_tracks"]}
    assert tracks["request"]["count"] >= 1

    # Both report gates pass: the SLO held, the scrape never went dark.
    fr = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "fleet_report.py"),
         "--dir", root], capture_output=True, text=True, cwd=REPO)
    assert fr.returncode == 0, fr.stderr
    assert "fleet metrics" in fr.stdout
    rec_path = tmp_path / "serving.json"
    rec_path.write_text(json.dumps(rec) + "\n")
    sr = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "serve_report.py"),
         "--file", str(rec_path)], capture_output=True, text=True,
        cwd=REPO)
    assert sr.returncode == 0, sr.stderr
    assert "slo" in sr.stdout
