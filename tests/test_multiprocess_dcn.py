"""REAL multi-process distributed backend test — no simulation.

Everything else in the suite exercises multi-host code paths either on a
single-process 8-device mesh or with an injected allgather
(test_multihost_eval).  This test launches TWO actual JAX processes
(``jax.distributed`` over a localhost coordinator, one CPU device each),
forms the 2-device GLOBAL mesh across them, and checks the cross-process
collectives for real — the CPU stand-in for the DCN backend (SURVEY.md §5
"Distributed communication backend"):

- a sharded reduction whose result needs data from both processes;
- one real-model XE train step sharded across the processes, equal to a
  single-device run of the same batch on every host;
- ``gather_strided_predictions`` with the REAL
  ``multihost_utils.process_allgather`` (unequal shard sizes included).
"""

import json
import os
import socket
import subprocess
import sys

import pytest

pytestmark = pytest.mark.e2e

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CHILD = r"""
import hashlib, json, sys
sys.path.insert(0, %(repo)r)
pid = int(sys.argv[1]); port = sys.argv[2]
from cst_captioning_tpu.parallel.dp import distributed_init
distributed_init(f"localhost:{port}", 2, pid)
import jax
import jax.numpy as jnp
import numpy as np
assert jax.process_count() == 2
assert jax.process_index() == pid
from cst_captioning_tpu.models import CaptionModel
from cst_captioning_tpu.parallel import (
    data_parallel_jit, make_mesh, replicated_sharding, shard_batch_arrays,
)
from cst_captioning_tpu.training.state import create_train_state, make_optimizer
from cst_captioning_tpu.training.steps import make_xe_step

mesh = make_mesh(jax.devices())          # 2 global devices, 1 per process

# -- cross-process reduction over sharded data ---------------------------
def stats(state, x):
    return state, {"s": jnp.sum(x), "m": jnp.mean(x * x)}

_, out = data_parallel_jit(stats, mesh, batch_argnums=(1,),
                           donate_argnums=())(
    None, shard_batch_arrays(
        mesh, jnp.arange(8, dtype=jnp.float32).reshape(8, 1)))
red = {"s": float(out["s"]), "m": float(out["m"])}

# -- real-model XE step across the process boundary ----------------------
V, H, B, S, L = 30, 16, 4, 2, 6
model = CaptionModel(vocab_size=V, embed_size=H, hidden_size=H, attn_size=H,
                     dropout_rate=0.0)
tx, _ = make_optimizer(learning_rate=1e-3, grad_clip=5.0)
feat_shapes = [(3, 8), (1, 5)]
state = create_train_state(model, jax.random.PRNGKey(0), feat_shapes, L, S,
                           tx, batch_size=B)
rng = np.random.default_rng(0)
feats_np = [rng.standard_normal((B,) + s).astype(np.float32)
            for s in feat_shapes]
labels_np = rng.integers(1, V, (B * S, L)).astype(np.int32)
weights_np = np.ones((B * S,), np.float32)
key = jax.random.PRNGKey(1)

step = make_xe_step(model, S)
# single-device reference on this host's own device
_, m_ref = jax.jit(step)(state, [jnp.asarray(f) for f in feats_np],
                         jnp.asarray(labels_np), jnp.asarray(weights_np), key)
loss_ref = float(m_ref["loss"])

# host-numpy detour: device_put of an on-device state can ALIAS its
# buffers into the global array, so donating one sharded copy would
# delete the other's (and state's) underlying storage
host_state = jax.tree_util.tree_map(np.asarray, state)
dstate = jax.device_put(host_state, replicated_sharding(mesh))
dfeats = shard_batch_arrays(mesh, [jnp.asarray(f) for f in feats_np])
dlabels = shard_batch_arrays(mesh, jnp.asarray(labels_np))
dweights = shard_batch_arrays(mesh, jnp.asarray(weights_np))
_, m = data_parallel_jit(step, mesh, batch_argnums=(1, 2, 3),
                         donate_argnums=(0,))(
    dstate, dfeats, dlabels, dweights, key)
loss = float(m["loss"])

# -- fused device-reward CST step across the process boundary ------------
# (--device_rewards 1 — the path pods actually train; VERDICT r3 #6)
from cst_captioning_tpu.training.device_rewards import build_device_tables
from cst_captioning_tpu.training.steps import make_fused_cst_step

NV = 5
vocab_words = {i: f"w{i}" for i in range(1, V)}
w2i = {w: i for i, w in vocab_words.items()}
refs = {f"v{i}": [" ".join(f"w{1 + ((i + j + k) %% (V - 1))}"
                           for k in range(5)) for j in range(3)]
        for i in range(NV)}
corpus, tables, video_row = build_device_tables(refs, w2i)
fused = make_fused_cst_step(model, L, S, corpus, tables)
vix_np = np.asarray([video_row[f"v{i}"] for i in range(B)], np.int32)
dstate2 = jax.device_put(host_state, replicated_sharding(mesh))
dfeats2 = shard_batch_arrays(mesh, [jnp.asarray(f) for f in feats_np])
dvix = shard_batch_arrays(mesh, jnp.asarray(vix_np))
fstate, fm = data_parallel_jit(fused, mesh, batch_argnums=(1, 2),
                               donate_argnums=(0,))(
    dstate2, dfeats2, dvix, key)
cst_loss = float(fm["loss"])
cst_reward = float(fm["reward"])
# post-step params must be IDENTICAL on both hosts (grad psum crossed the
# process boundary; any divergence here means pods drift silently)
params_digest = hashlib.sha256(b"".join(
    np.asarray(l).tobytes()
    for l in jax.tree_util.tree_leaves(fstate.params))).hexdigest()

# -- gather_strided_predictions with the REAL process_allgather ----------
from cst_captioning_tpu.training.evaluation import gather_strided_predictions
vids = [f"v{i}" for i in range(NV)]      # P0 strides 3 rows, P1 strides 2
mine = np.asarray([[1 + (3 * i) %% (V - 1), 1 + (5 * i) %% (V - 1), 0]
                   for i in range(NV) if i %% 2 == pid], dtype=np.int32)
ids, rows = gather_strided_predictions(mine, vids, pid, 2)
digest = hashlib.sha256(
    (",".join(ids) + "|" + np.concatenate(rows).tobytes().hex())
    .encode()).hexdigest()

# -- validate()-equivalence: every host scores the identical full split --
# (identical metric value -> identical best-step/early-stop bookkeeping)
from cst_captioning_tpu.data.vocab import Vocab
from cst_captioning_tpu.metrics.coco_eval import language_eval
vb = Vocab(vocab_words)
preds = [{"image_id": vid, "caption": vb.decode(r)}
         for vid, r in zip(ids, rows)]
val_metric = language_eval(preds, refs, scorers=("CIDEr",))["CIDEr"]

print(json.dumps({"pid": pid, "red": red, "loss": loss,
                  "loss_ref": loss_ref, "ids": ids, "digest": digest,
                  "cst_loss": cst_loss, "cst_reward": cst_reward,
                  "params_digest": params_digest,
                  "val_metric": val_metric}),
      flush=True)
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def test_two_process_backend(tmp_path):
    script = tmp_path / "child.py"
    script.write_text(CHILD % {"repo": REPO})
    port = _free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    from conftest import CACHE_DIR

    env.setdefault("JAX_COMPILATION_CACHE_DIR", CACHE_DIR)
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(i), str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env, cwd=REPO,
        )
        for i in range(2)
    ]
    # Container signature (PR 9 notes): this image's jaxlib CPU client
    # has no cross-process collective support at all — the very first
    # sharded device_put dies fast with this exact XLA error.  That is
    # an environment capability gap, not a regression in the code under
    # test, so it skips with the documented reason; ANY other child
    # failure (hang, assert, different error) still fails the test, and
    # on a container whose jaxlib does support multiprocess CPU this
    # test runs for real again.
    NO_MULTIPROCESS_CPU = (
        "Multiprocess computations aren't implemented on the CPU backend")
    results = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=420)
            if p.returncode != 0 and NO_MULTIPROCESS_CPU in err:
                pytest.skip(
                    "container jaxlib lacks multiprocess CPU collectives "
                    f"({NO_MULTIPROCESS_CPU!r}); the 2-process DCN "
                    "drill needs a backend with cross-process support")
            assert p.returncode == 0, f"child failed:\n{err[-3000:]}"
            results.append(json.loads(out.strip().splitlines()[-1]))
    finally:
        # One child failing leaves its sibling blocked in the
        # distributed-init barrier forever — always reap both.
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()

    a, b = sorted(results, key=lambda r: r["pid"])
    # Reduction saw BOTH shards: sum(0..7) = 28 (each process alone holds
    # only half), and both processes read the identical global value.
    assert a["red"] == b["red"]
    assert a["red"]["s"] == pytest.approx(28.0)
    assert a["red"]["m"] == pytest.approx(17.5)
    # The cross-process XE step agrees on both hosts and matches the
    # single-device reference loss computed on each host alone.
    assert a["loss"] == pytest.approx(b["loss"], rel=1e-6)
    for r in (a, b):
        assert r["loss"] == pytest.approx(r["loss_ref"], rel=1e-5), r
    # The fused device-reward CST step (the shipped --device_rewards path)
    # agrees across the process boundary: same loss/reward on both hosts
    # and BIT-identical post-step params (grad psum crossed DCN).
    assert a["cst_loss"] == pytest.approx(b["cst_loss"], rel=1e-6)
    assert a["cst_reward"] == pytest.approx(b["cst_reward"], rel=1e-6)
    assert a["params_digest"] == b["params_digest"]
    # Real process_allgather reassembled the FULL split (every video,
    # shard-concatenation order) identically on both hosts.
    assert sorted(a["ids"]) == [f"v{i}" for i in range(5)]
    assert a["ids"] == b["ids"]
    assert a["digest"] == b["digest"]
    # ...and the selection metric computed from it is identical, so
    # best-step / early-stop bookkeeping cannot diverge across hosts.
    assert a["val_metric"] == b["val_metric"]
    assert a["val_metric"] > 0.0
