"""Process-fleet supervisor (ISSUE 16): OS-process replica lifecycle
driven by the exit taxonomy, crash-proof requeue, blackbox harvest.

Fast slice (tier-1, lock-sanitizer armed, NO jax import — the
supervisor is pure host code and these tests keep it that way):
- the shared routing policy (serving/policy.py) driving placement:
  healthy-tier-first, least-loaded, index tiebreak, route-around-
  ``degraded``, fleet-edge deadline shed with an explicit answer;
- THE lifecycle drill against a strict in-process fake child (a fake
  whose ``lines()`` never advances work after death — a dead child
  cannot answer): SIGKILL mid-stream -> in-flight requeued with the
  ARRIVAL clock preserved (remaining TTL forwarded), captions
  bit-identical to the fault-free twin, stream chunks prefix-consistent
  through the supervisor watermark (every token exactly once, ``seq``
  re-issued contiguously);
- the exit taxonomy as policy: resumable (143) restarts burn NO budget;
  fatal (1) exits burn ``restart_limit`` and escalate to
  :class:`SupervisorUnrecoverable` when every replica is dead; bounded
  exponential backoff that doubles per consecutive death and resets on
  the next healthy completion;
- child-level ``shed``/``rejected_draining`` answers rerouted/requeued
  (the client never sees a drain it did not cause), parking while every
  replica is mid-restart, wedge detection killing a line-silent child
  as exit 124;
- ``proc_kill``/``proc_wedge``/``proc_preempt`` fault kinds firing
  exactly once, "mid-work" (in-flight + at least one response line),
  with dump-before-kill landing blackbox.json in the incident bundle;
- the aggregated health plane (worst-of-replicas, restarts/backoff
  folded in), the SupervisorServer wire (health/stats/dump/bad lines),
  drain/hard-abort semantics, opts flags/env/warn-once, serve_report's
  process-fleet rows + gates, and the SERVING.md/RESILIENCE.md pins.

The real-subprocess drills (the seeded SIGKILL acceptance probe through
``scripts/serve_supervisor.py --supervise_probe`` and the double-SIGTERM
abort drill) are marked ``slow`` and run via ``make serve-proc-chaos``.
"""

import io
import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from cst_captioning_tpu.resilience.exitcodes import (
    EXIT_PREEMPTED,
    EXIT_SIGKILL,
    EXIT_SIGTERM,
    EXIT_WEDGE,
)
from cst_captioning_tpu.resilience.faults import FaultPlan
from cst_captioning_tpu.serving.policy import (
    deadline_unmeetable,
    rank_key,
    worst_status,
)
from cst_captioning_tpu.serving.supervisor import (
    SUPERVISOR_COUNTERS,
    ProcessFleetSupervisor,
    SupervisorServer,
    SupervisorUnrecoverable,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _lock_sanitizer(monkeypatch, tmp_path):
    """The supervisor fast slice runs sanitizer-armed (the PR 11/13
    discipline): scheduler/health/requeue/front-end locks re-validated
    against the declared LOCK_ORDER under every drill in this file."""
    from cst_captioning_tpu.analysis import locksan

    receipt = tmp_path / "locksan_violation.json"
    monkeypatch.setenv(locksan.ENV_FLAG, "1")
    monkeypatch.setenv(locksan.ENV_RECEIPT, str(receipt))
    before = len(locksan.violations())
    yield
    after = locksan.violations()
    assert len(after) == before, f"lock-order violations: {after[before:]}"
    assert not receipt.exists(), (
        f"lock sanitizer receipt from a child process: "
        f"{receipt.read_text()}")


class FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


class FakeChild:
    """A strict serve.py stand-in with the ServeChild surface.  One
    decode chunk of work advances per ``lines()`` call while the child
    is alive and unfrozen; after ``die()`` the transport raises and
    ``lines()`` only returns what was ALREADY buffered — a dead child
    can never quietly answer its residents (that laxness would let a
    requeue test pass without requeueing anything)."""

    CHUNK = 2
    CAP_LEN = 6

    def __init__(self, replica, workdir, *, status="ok", compiles=0,
                 min_service_ms=1.0, shed_all=False, reject_all=False):
        self.replica = int(replica)
        self.workdir = workdir
        os.makedirs(workdir, exist_ok=True)
        self.pid = 40000 + self.replica
        self.alive = True
        self.frozen = False
        self.draining = False
        self.rc = None
        self.status = status
        self.compiles = compiles
        self.min_service_ms = min_service_ms
        self.shed_all = shed_all
        self.reject_all = reject_all
        self.sent = []
        self.jobs = []
        self.dumps = 0
        self._outbox = []
        self._stalled = []

    # -- the deterministic demo decode ----------------------------------

    @classmethod
    def tokens_for(cls, vid):
        base = int(str(vid).lstrip("v"))
        return [base * 10 + j + 1 for j in range(cls.CAP_LEN)]

    @classmethod
    def caption_for(cls, vid):
        return " ".join(f"w{t}" for t in cls.tokens_for(vid))

    # -- the ServeChild surface -----------------------------------------

    def send_line(self, line):
        if not self.alive:
            raise OSError("child is dead")
        if self.frozen:
            # A SIGSTOP'd process accepts bytes into its socket buffer
            # but processes nothing: stall the line until cont().
            self._stalled.append(line)
            return
        self.sent.append(line)
        req = json.loads(line)
        op = req.get("op", "caption")
        if op == "health":
            self._outbox.append(json.dumps({
                "op": "health", "status": self.status, "queue_depth": 0,
                "residents": len(self.jobs), "compiles": self.compiles,
                "min_service_ms": self.min_service_ms}))
            return
        if op == "stats":
            self._outbox.append(json.dumps(
                {"op": "stats", "compiles": self.compiles}))
            return
        if op == "dump":
            self.dumps += 1
            with open(os.path.join(self.workdir, "blackbox.json"),
                      "w") as f:
                json.dump({"reason": "wire_dump",
                           "replica": self.replica}, f)
            self._outbox.append(json.dumps({"op": "dump"}))
            return
        rid = req["id"]
        if self.shed_all:
            self._outbox.append(json.dumps(
                {"id": rid, "error": "shed", "queue_depth": 1}))
            return
        if self.reject_all or self.draining:
            self._outbox.append(json.dumps(
                {"id": rid, "error": "rejected_draining"}))
            return
        self.jobs.append({"id": rid, "vid": req["video_id"],
                          "deadline_ms": req.get("deadline_ms"),
                          "stream": op == "stream", "pos": 0, "seq": 0})

    def lines(self):
        if self.alive and not self.frozen:
            self._advance()
        out, self._outbox = self._outbox, []
        return out

    def _advance(self):
        for job in list(self.jobs):
            toks = self.tokens_for(job["vid"])
            if job["pos"] < len(toks):
                chunk = toks[job["pos"]:job["pos"] + self.CHUNK]
                if job["stream"]:
                    self._outbox.append(json.dumps({
                        "id": job["id"], "video_id": job["vid"],
                        "stream": True, "seq": job["seq"],
                        "tokens": chunk,
                        "text": " ".join(f"w{t}" for t in chunk),
                        "final": False}))
                job["seq"] += 1
                job["pos"] += self.CHUNK
                continue
            term = {"id": job["id"], "video_id": job["vid"],
                    "caption": self.caption_for(job["vid"]),
                    "tokens": toks, "latency_ms": 7.0}
            if job["stream"]:
                term.update(stream=True, final=True, chunks=job["seq"])
            self._outbox.append(json.dumps(term))
            self.jobs.remove(job)
        if self.draining and not self.jobs:
            self.die(EXIT_PREEMPTED)

    def poll(self):
        return None if self.alive else self.rc

    def die(self, rc):
        self.alive = False
        self.rc = rc

    def terminate(self):
        if not self.alive:
            return
        self.draining = True
        if not self.jobs:
            self.die(EXIT_PREEMPTED)

    def kill(self):
        if self.alive:
            self.die(EXIT_SIGKILL)

    def stop(self):
        self.frozen = True

    def cont(self):
        self.frozen = False
        stalled, self._stalled = self._stalled, []
        for line in stalled:
            self.send_line(line)

    def close(self):
        pass


def build_sup(tmp_path, n=2, **kw):
    clock = kw.pop("clock", None) or FakeClock()
    child_kw = kw.pop("child_kw", {})
    children = []

    def launcher(k):
        child = FakeChild(k, os.path.join(str(tmp_path), f"replica{k}"),
                          **child_kw.get(k, {}))
        children.append(child)
        return child

    kw.setdefault("backoff_ms", 200.0)
    kw.setdefault("incident_dir", os.path.join(str(tmp_path),
                                               "incidents"))
    sup = ProcessFleetSupervisor(launcher, n, clock=clock,
                                 spawn_async=False, **kw)
    return sup, children, clock


def child_of(children, k):
    """The CURRENT (latest-spawned) child of replica k."""
    return [c for c in children if c.replica == k][-1]


def tick_until(sup, pred, n=64):
    for _ in range(n):
        sup.tick()
        if pred():
            return
    raise AssertionError(f"predicate never held within {n} ticks")


# -- shared policy ---------------------------------------------------------


def test_policy_identity_with_fleet_router():
    """Both fleets import ONE policy: the supervisor's placement order,
    worst-of health, and deadline shed are serving/policy.py verbatim
    — spot-check the semantics the supervisor leans on."""
    assert rank_key(False, 3, 1) < rank_key(True, 0, 0)
    assert rank_key(False, 1, 2) < rank_key(False, 2, 0)
    assert worst_status(["ok", "degraded"]) == "degraded"
    assert worst_status(["ok", "restarting"]) == "degraded"  # unknown
    assert worst_status([]) == "degraded"
    assert deadline_unmeetable(10.0, [5.0, 7.0]) is True
    assert deadline_unmeetable(10.0, [5.0, None]) is False  # never guess


def test_placement_spreads_load_then_index(tmp_path):
    sup, children, _ = build_sup(tmp_path, 3)
    got = []
    for i in range(6):
        sup.submit(i, f"v{i}", respond=got.append)
    # 0,1,2 then back to 0,1,2: least-loaded within the healthy tier,
    # index as the tiebreak.
    owners = [len(c.jobs) for c in children]
    assert owners == [2, 2, 2]
    c = sup.supervisor_counters()
    assert c["sup_requests"] == 6 and c["sup_routed"] == 6
    assert c["sup_rerouted"] == 0


def test_route_around_degraded_child(tmp_path):
    sup, children, _ = build_sup(
        tmp_path, 2, child_kw={0: {"status": "degraded"}})
    sup.tick()   # health poll out
    sup.tick()   # health replies in
    got = []
    sup.submit("a", "v1", respond=got.append)
    assert len(children[1].jobs) == 1 and not children[0].jobs


def test_caption_completes_with_supervisor_edge_latency(tmp_path):
    sup, children, clock = build_sup(tmp_path, 1)
    got = []
    sup.submit("cli-7", "v3", respond=got.append)
    clock.advance(0.25)
    tick_until(sup, lambda: got)
    fin = got[-1]
    assert fin["id"] == "cli-7"
    assert fin["caption"] == FakeChild.caption_for("v3")
    # The child said 7.0ms; the supervisor's answer spans ITS intake.
    assert fin["latency_ms"] == pytest.approx(250.0)
    assert sup.outstanding == 0 and sup.quiet


# -- THE drill: kill mid-stream, requeue, bit-identity ---------------------


def test_kill_midstream_requeues_bit_identical_prefix_consistent(tmp_path):
    """The in-process acceptance drill: SIGKILL the owner mid-stream —
    the request is requeued with its arrival clock preserved (remaining
    TTL forwarded to the new owner), the replayed chunks fall inside
    the watermark, and the client sees every token exactly once with
    contiguous supervisor-issued ``seq`` and the bit-identical caption
    of the fault-free twin."""
    sup, children, clock = build_sup(tmp_path, 2)
    got = []
    sup.submit("s1", "v4", respond=got.append, stream=True,
               deadline_ms=1000.0)
    first = json.loads(children[0].sent[-1])
    assert first["op"] == "stream" and first["deadline_ms"] == 1000.0

    sup.tick()   # chunk 0 (tokens 0-1)
    sup.tick()   # chunk 1 (tokens 2-3)
    chunks = [a for a in got if a.get("stream") and not a.get("final")]
    assert [c["seq"] for c in chunks] == [0, 1]

    clock.advance(0.3)
    children[0].kill()   # mid-decode: 4 of 6 tokens forwarded
    sup.tick()           # reap 137 -> requeue to replica 1

    c = sup.supervisor_counters()
    assert c["sup_requeued"] == 1 and c["sup_rerouted"] == 1
    # Arrival preserved: the new owner gets the REMAINING TTL.
    replay = json.loads(children[1].sent[-1])
    assert replay["op"] == "stream"
    assert replay["deadline_ms"] == pytest.approx(700.0)

    tick_until(sup, lambda: any(a.get("final") and "caption" in a
                                for a in got))
    fin = got[-1]
    assert fin["caption"] == FakeChild.caption_for("v4")   # bit-identical
    chunks = [a for a in got if a.get("stream") and not a.get("final")]
    # Every token exactly once, seq contiguous, text == caption.
    assert [c["seq"] for c in chunks] == [0, 1, 2]
    toks = [t for c in chunks for t in c["tokens"]]
    assert toks == FakeChild.tokens_for("v4")
    assert " ".join(c["text"] for c in chunks) == fin["caption"]
    assert fin["chunks"] == 3   # chunks the CLIENT saw, not the child's

    # The dead replica restarts after backoff, free of fatal budget.
    clock.advance(0.5)
    sup.tick()
    rep0 = sup._replicas[0]
    assert rep0.live and rep0.restarts == 1 and rep0.fatal_spent == 0
    assert len(sup._incidents) == 1
    assert sup._incidents[0]["classification"] == "resumable"


def test_watermark_slices_mid_chunk(tmp_path):
    """A replay chunk STRADDLING the watermark is sliced, tokens and
    text in lockstep (Vocab.decode is one word per non-zero token)."""
    sup, _, _ = build_sup(tmp_path, 1)
    got = []
    sup.submit("s", "v1", respond=got.append, stream=True)
    pr = next(iter(sup._pending.values()))
    pr.sent_tokens, pr.cur_tokens, pr.seq_out = 3, 0, 2
    sup._forward_chunk(pr, {"stream": True, "seq": 0,
                            "tokens": [11, 12, 13, 14],
                            "text": "a b c d", "final": False})
    assert got[-1]["tokens"] == [14] and got[-1]["text"] == "d"
    assert got[-1]["seq"] == 2 and pr.sent_tokens == 4


# -- the exit taxonomy as lifecycle policy ---------------------------------


def test_fatal_exits_burn_budget_then_unrecoverable(tmp_path):
    sup, children, clock = build_sup(tmp_path, 1, restart_limit=1)
    children[0].die(1)          # fatal
    sup.tick()
    rep = sup._replicas[0]
    assert rep.fatal_spent == 1 and rep.state == "backoff"
    clock.advance(0.5)
    sup.tick()                  # restart 1 hatches
    assert rep.live and rep.restarts == 1
    child_of(children, 0).die(1)
    with pytest.raises(SupervisorUnrecoverable):
        sup.tick()              # budget spent fleet-wide -> 124 upstream
    assert rep.state == "dead"
    assert sup.supervisor_counters()["sup_replica_deaths"] == 1


def test_resumable_exits_restart_free_of_budget(tmp_path):
    sup, children, clock = build_sup(tmp_path, 1, restart_limit=0)
    for _ in range(3):
        child_of(children, 0).die(EXIT_SIGTERM)
        sup.tick()
        clock.advance(3.0)      # never mind the doubling here
        sup.tick()
    rep = sup._replicas[0]
    assert rep.live and rep.restarts == 3 and rep.fatal_spent == 0


def test_backoff_doubles_caps_and_resets_on_completion(tmp_path):
    sup, children, clock = build_sup(tmp_path, 1, backoff_ms=200.0,
                                     backoff_cap_ms=1000.0)
    delays = []
    for _ in range(4):
        child_of(children, 0).die(EXIT_SIGTERM)
        sup.tick()
        delays.append(round((sup._replicas[0].backoff_until - clock.t)
                            * 1e3))
        clock.advance(2.0)
        sup.tick()
    assert delays == [200, 400, 800, 1000]   # doubling, then the cap
    got = []
    sup.submit("a", "v1", respond=got.append)
    tick_until(sup, lambda: got)             # healthy completion...
    child_of(children, 0).die(EXIT_SIGTERM)
    sup.tick()
    assert round((sup._replicas[0].backoff_until - clock.t) * 1e3) == 200


# -- child-level answers routed around -------------------------------------


def test_child_shed_reroutes_then_fleet_shed(tmp_path):
    sup, children, _ = build_sup(
        tmp_path, 2, child_kw={0: {"shed_all": True}})
    got = []
    sup.submit("a", "v1", respond=got.append)
    tick_until(sup, lambda: got)
    assert got[-1]["caption"] == FakeChild.caption_for("v1")
    c = sup.supervisor_counters()
    assert c["sup_rerouted"] == 1 and c["sup_shed"] == 0

    sup2, _, _ = build_sup(tmp_path / "b", 2,
                           child_kw={0: {"shed_all": True},
                                     1: {"shed_all": True}})
    got2 = []
    sup2.submit("b", "v2", respond=got2.append)
    tick_until(sup2, lambda: got2)
    assert got2[-1]["error"] == "shed"       # honest fleet-edge answer
    assert sup2.supervisor_counters()["sup_shed"] == 1


def test_child_drain_is_requeued_not_leaked_to_client(tmp_path):
    """A CHILD draining (proc_preempt, an external SIGTERM) while the
    fleet is not: the client must never see rejected_draining — the
    request requeues to a live sibling."""
    sup, children, _ = build_sup(
        tmp_path, 2, child_kw={0: {"reject_all": True}})
    got = []
    sup.submit("a", "v5", respond=got.append)
    tick_until(sup, lambda: got)
    assert got[-1]["caption"] == FakeChild.caption_for("v5")
    assert sup.supervisor_counters()["sup_requeued"] == 1


def test_parked_while_every_replica_restarts_then_retried(tmp_path):
    sup, children, clock = build_sup(tmp_path, 1)
    children[0].die(EXIT_SIGKILL)
    sup.tick()                   # backoff; no live replica now
    got = []
    sup.submit("a", "v2", respond=got.append, deadline_ms=5000.0)
    assert not got               # HELD, not shed: a restart is due
    assert sup.supervisor_counters()["sup_parked"] == 1
    clock.advance(0.5)
    tick_until(sup, lambda: got)
    assert got[-1]["caption"] == FakeChild.caption_for("v2")


def test_deadline_unmeetable_shed_at_the_edge(tmp_path):
    sup, children, _ = build_sup(
        tmp_path, 2, child_kw={k: {"min_service_ms": 5000.0}
                               for k in range(2)})
    sup.tick()
    sup.tick()                   # health floors in
    got = []
    sup.submit("a", "v1", respond=got.append, deadline_ms=10.0)
    assert got[-1]["error"] == "expired"
    assert got[-1]["why"] == "deadline_unmeetable"
    assert not children[0].jobs and not children[1].jobs


# -- wedge detection & proc faults -----------------------------------------


def test_wedge_detection_kills_silent_child_as_124(tmp_path):
    sup, children, clock = build_sup(tmp_path, 2, wedge_timeout_s=1.0)
    got = []
    sup.submit("a", "v6", respond=got.append, stream=True)
    children[0].stop()           # frozen: every thread, incl. watchdog
    sup.tick()
    clock.advance(1.5)
    sup.tick()                   # line-silent with work owed -> kill
    c = sup.supervisor_counters()
    assert c["sup_wedge_kills"] == 1 and c["sup_requeued"] == 1
    rep = sup._replicas[0]
    assert rep.last_rc == EXIT_WEDGE and rep.state == "backoff"
    assert sup._incidents[0]["classification"] == "wedge"
    tick_until(sup, lambda: any(a.get("final") for a in got))
    assert got[-1]["caption"] == FakeChild.caption_for("v6")


def test_proc_kill_fires_once_midwork_with_dump_before_kill(tmp_path):
    plan = FaultPlan.parse("proc_kill@replica=0")
    sup, children, clock = build_sup(tmp_path, 2, fault_plan=plan,
                                     dump_grace_s=0.2)
    for _ in range(3):
        sup.tick()               # armed but NOT mid-work: never fires
    assert children[0].alive and children[0].dumps == 0

    got = []
    sup.submit("a", "v7", respond=got.append, stream=True)
    tick_until(sup, lambda: not children[0].alive, n=8)
    assert children[0].dumps == 1          # dump-before-kill
    assert children[0].rc == EXIT_SIGKILL
    sup.tick()                             # reap + harvest + requeue
    assert plan.fire_replica("proc_kill", 0) is False   # single-shot
    inc = sup._incidents[0]
    assert inc["rc"] == EXIT_SIGKILL and "blackbox.json" in inc["files"]
    bb = os.path.join(inc["dir"], "blackbox.json")
    assert os.path.exists(bb)
    with open(os.path.join(inc["dir"], "incident.json")) as f:
        assert json.load(f)["replica"] == 0
    clock.advance(0.5)
    tick_until(sup, lambda: any(a.get("final") for a in got))
    assert got[-1]["caption"] == FakeChild.caption_for("v7")


def test_proc_wedge_freezes_until_the_wedge_timer_takes_it(tmp_path):
    plan = FaultPlan.parse("proc_wedge@replica=0")
    sup, children, clock = build_sup(tmp_path, 2, fault_plan=plan,
                                     wedge_timeout_s=1.0)
    got = []
    sup.submit("a", "v8", respond=got.append, stream=True)
    tick_until(sup, lambda: children[0].frozen, n=8)
    clock.advance(1.5)
    sup.tick()
    assert sup._replicas[0].last_rc == EXIT_WEDGE
    assert sup.supervisor_counters()["sup_wedge_kills"] == 1
    tick_until(sup, lambda: any(a.get("final") for a in got))
    assert got[-1]["caption"] == FakeChild.caption_for("v8")


def test_proc_preempt_lets_the_child_drain_itself(tmp_path):
    plan = FaultPlan.parse("proc_preempt@replica=0")
    sup, children, clock = build_sup(tmp_path, 2, fault_plan=plan)
    got = []
    sup.submit("a", "v9", respond=got.append, stream=True)
    tick_until(sup, lambda: children[0].draining, n=8)
    # The child's OWN drain contract: the resident completes, then 75.
    tick_until(sup, lambda: any(a.get("final") for a in got))
    assert got[-1]["caption"] == FakeChild.caption_for("v9")
    tick_until(sup, lambda: not children[0].alive, n=8)
    sup.tick()
    rep = sup._replicas[0]
    assert rep.last_rc == EXIT_PREEMPTED and rep.fatal_spent == 0
    assert sup.supervisor_counters()["sup_requeued"] == 0


def test_proc_fault_grammar_and_child_plan_slices():
    plan = FaultPlan.parse(
        "proc_kill@replica=1,serve_wedge@replica=1")
    assert plan.fire_replica("proc_kill", 0) is False
    assert plan.fire_replica("proc_kill", 1) is True
    assert plan.fire_replica("proc_kill", 1) is False   # once, ever
    with pytest.raises(ValueError):
        plan.fire_replica("serve_wedge", 1)    # not a process-level kind
    # Serving kinds forward into the CHILD's plan; proc kinds never do.
    assert plan.cli_for_child(1) == "serve_wedge@req=0"
    assert plan.cli_for_child(0) is None
    with pytest.raises(ValueError):
        FaultPlan.parse("proc_kill@req=3")     # wrong axis for the kind


# -- health plane ----------------------------------------------------------


def test_health_aggregates_worst_of_and_lifecycle(tmp_path):
    sup, children, clock = build_sup(tmp_path, 3)
    sup.tick()
    sup.tick()
    h = sup.health_payload()
    assert h["status"] == "ok" and h["replicas"] == 3
    assert h["in_service"] == 3 and h["parked"] == 0
    assert set(SUPERVISOR_COUNTERS) == set(h["supervisor"])

    sup._replicas[1].health = {"status": "degraded"}
    sup._update_snapshots()
    assert sup.health_payload()["status"] == "degraded"

    children[2].die(EXIT_SIGKILL)
    sup.tick()
    h = sup.health_payload()
    per = {s["replica"]: s for s in h["per_replica"]}
    assert per[2]["status"] == "restarting"    # ranks degraded fleet-wide
    assert h["status"] == "degraded" and h["in_service"] == 2

    st = sup.stats()
    assert st["replicas"] == 3 and st["in_service"] == 2
    assert st["supervisor"] == sup.supervisor_counters()


# -- drain / abort ---------------------------------------------------------


def test_drain_completes_residents_and_rejects_new_work(tmp_path):
    sup, children, _ = build_sup(tmp_path, 2)
    got = {0: [], 1: []}
    sup.submit(0, "v1", respond=got[0].append)
    sup.submit(1, "v2", respond=got[1].append)
    sup.begin_drain()
    tick_until(sup, sup.drain_done)
    # Residents completed through the children's OWN drain...
    assert got[0][-1]["caption"] == FakeChild.caption_for("v1")
    assert got[1][-1]["caption"] == FakeChild.caption_for("v2")
    # ...their 75 exits are EXPECTED: no incident, no restart.
    assert not sup._incidents
    assert all(r.state == "drained" for r in sup._replicas)
    late = []
    sup.submit(2, "v3", respond=late.append, stream=True)
    assert late[-1]["error"] == "rejected_draining"
    assert late[-1]["final"] is True and late[-1]["stream"] is True


def test_hard_abort_answers_every_outstanding_id(tmp_path):
    sup, children, _ = build_sup(tmp_path, 2)
    got = {i: [] for i in range(3)}
    for i in range(3):
        sup.submit(i, f"v{i}", respond=got[i].append, stream=(i == 0))
    sup.hard_abort()
    for i in range(3):
        assert got[i][-1]["error"] == "rejected_draining"
    assert got[0][-1]["final"] is True     # streamed terminal invariant
    assert sup.outstanding == 0
    assert all(not c.alive for c in children)


# -- the SupervisorServer wire ---------------------------------------------


def server_rig(tmp_path, n=1, **kw):
    sup, children, clock = build_sup(tmp_path, n, **kw)
    server = SupervisorServer(sup, out=io.StringIO())
    replies = []
    return sup, children, server, replies, replies.append


def test_server_health_stats_dump_ops(tmp_path):
    sup, children, server, replies, respond = server_rig(tmp_path)
    server._handle_line('{"op": "health"}', respond)
    h = json.loads(replies[-1])
    assert h["op"] == "health" and h["status"] == "ok"
    server._handle_line('{"op": "stats"}', respond)
    assert json.loads(replies[-1])["replicas"] == 1
    server._handle_line('{"op": "dump"}', respond)
    d = json.loads(replies[-1])
    # No lifecycle tracer armed on the rig: honest error, children
    # still asked for THEIR blackboxes.
    assert d["error"] == "no_recorder" and d["children_asked"] == 1
    assert children[0].dumps == 1


def test_server_hardened_intake(tmp_path):
    sup, _, server, replies, respond = server_rig(tmp_path)
    for line, want in [
            ("not json", "bad_request"),
            ('["a", "list"]', "bad_request"),
            ('{"op": "nope", "id": 1}', "unknown_op"),
            ('{"id": 1}', "bad_request"),                 # no video_id
            ('{"id": 1, "video_id": "v1", "deadline_ms": -5}',
             "bad_request")]:
        server._handle_line(line, respond)
        assert json.loads(replies[-1])["error"] == want, line
    assert sup.outstanding == 0


def test_server_stdin_front_end_end_to_end(tmp_path):
    sup, children, clock = build_sup(tmp_path, 2)
    out = io.StringIO()
    server = SupervisorServer(sup, out=out, idle_sleep=0.0)
    lines = [json.dumps({"id": i, "video_id": f"v{i}"}) + "\n"
             for i in range(4)] + ['{"op": "health"}\n']
    rc = server.run_stdin(lines=lines)
    assert rc == 0
    outs = [json.loads(l) for l in out.getvalue().splitlines()]
    caps = {o["id"]: o["caption"] for o in outs if "caption" in o}
    assert caps == {i: FakeChild.caption_for(f"v{i}") for i in range(4)}
    assert any(o.get("op") == "health" for o in outs)
    assert all(not c.alive for c in children)   # EOF shutdown drained


# -- opts ------------------------------------------------------------------


def test_supervise_flags_env_fallback_and_validation(monkeypatch):
    from cst_captioning_tpu.opts import parse_opts

    ns = parse_opts(["--serve_demo", "1"])
    assert ns.supervise_replicas == 3
    assert ns.supervise_restart_limit == 3
    assert ns.supervise_backoff_ms == 200

    monkeypatch.setenv("CST_SUPERVISE_REPLICAS", "5")
    monkeypatch.setenv("CST_SUPERVISE_RESTART_LIMIT", "0")
    ns = parse_opts(["--serve_demo", "1"])
    assert ns.supervise_replicas == 5
    assert ns.supervise_restart_limit == 0
    # Explicit flag beats the environment.
    ns = parse_opts(["--serve_demo", "1", "--supervise_replicas", "2"])
    assert ns.supervise_replicas == 2

    with pytest.raises(SystemExit):
        parse_opts(["--supervise_replicas", "0"])      # needs >= 1
    with pytest.raises(SystemExit):
        parse_opts(["--supervise_backoff_ms", "-1"])   # needs >= 0


def test_supervise_conflict_warns_once(capsys, monkeypatch):
    from cst_captioning_tpu import opts

    monkeypatch.setattr(opts, "_warned_supervise_conflict", False)
    opts.parse_opts(["--serve_demo", "1", "--serve_replicas", "2",
                     "--supervise_replicas", "2"])
    warned = [l for l in capsys.readouterr().err.splitlines()
              if "supervise_replicas" in l]
    assert len(warned) == 1
    opts.parse_opts(["--serve_demo", "1", "--serve_replicas", "2",
                     "--supervise_replicas", "2"])
    assert not capsys.readouterr().err.strip()         # once per process

    monkeypatch.setattr(opts, "_warned_supervise_conflict", False)
    opts.parse_opts(["--serve_demo", "1", "--supervise_replicas", "2"])
    assert not capsys.readouterr().err.strip()         # one axis: fine


# -- serve_report ----------------------------------------------------------


def _run_report(record, tmp_path):
    path = tmp_path / "serving.json"
    path.write_text(json.dumps(record) + "\n")
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "serve_report.py"),
         "--file", str(path)], capture_output=True, text=True, cwd=REPO)


def _sup_record(**over):
    rec = {
        "metric": "serve_captions_per_sec_per_chip", "value": 12.0,
        "latency_p50_ms": 40.0, "latency_p99_ms": 90.0, "completed": 18,
        "num_requests": 18, "shed": 0, "recompiles_after_warmup": 0,
        "platform": "cpu",
        "stream": {"enabled": True, "prefix_ok": True, "chunks": 144},
        "supervisor": {
            "enabled": True, "replicas": 3, "restart_limit": 3,
            "killed_replica": 1, "restarts": 1, "requeued": 6,
            "deaths": 0, "wedge_kills": 0, "budget_ok": True,
            "parity_ok": True, "parity_mismatches": 0, "incidents": 1,
            "blackbox_harvested": True,
            "per_replica": [
                {"replica": k, "state": "ok", "completed": 6,
                 "restarts": int(k == 1), "kills": int(k == 1),
                 "last_rc": 137 if k == 1 else None}
                for k in range(3)]},
    }
    rec["supervisor"].update(over)
    return rec


def test_serve_report_renders_supervisor_rows(tmp_path):
    proc = _run_report(_sup_record(), tmp_path)
    assert proc.returncode == 0, proc.stderr
    assert "process fleet" in proc.stdout
    assert "process incidents" in proc.stdout
    assert "blackbox_harvested=True" in proc.stdout
    for k in range(3):
        assert f"child {k}" in proc.stdout
    assert "budget_ok=True" in proc.stdout


def test_serve_report_gates_on_process_parity(tmp_path):
    proc = _run_report(_sup_record(parity_ok=False,
                                   parity_mismatches=2), tmp_path)
    assert proc.returncode == 1
    assert "bit-identical" in proc.stderr


def test_serve_report_gates_on_restart_budget(tmp_path):
    proc = _run_report(_sup_record(budget_ok=False, deaths=1), tmp_path)
    assert proc.returncode == 1
    assert "restart budget" in proc.stderr


def test_serve_report_old_records_render_unchanged(tmp_path):
    rec = {"metric": "serve_captions_per_sec_per_chip", "value": 50.0,
           "latency_p50_ms": 4.0, "latency_p99_ms": 8.0,
           "recompiles_after_warmup": 0, "platform": "cpu"}
    proc = _run_report(rec, tmp_path)
    assert proc.returncode == 0, proc.stderr
    assert "process fleet" not in proc.stdout


# -- doc pins --------------------------------------------------------------


def test_serving_doc_pins_supervisor_counter_table():
    with open(os.path.join(REPO, "SERVING.md")) as f:
        text = f.read()
    for name in SUPERVISOR_COUNTERS:
        assert name in text, f"SERVING.md process-fleet table: {name}"
    for token in ("serve_supervisor.py", "--supervise_replicas",
                  "serve-proc-chaos", "supervisor_exit.json"):
        assert token in text, f"SERVING.md Process fleet: {token!r}"


def test_resilience_doc_pins_proc_fault_grammar():
    with open(os.path.join(REPO, "RESILIENCE.md")) as f:
        text = f.read()
    for token in ("proc_kill", "proc_wedge", "proc_preempt",
                  "incident.json", "incidents/"):
        assert token in text, f"RESILIENCE.md process faults: {token!r}"


# -- slow: the real-subprocess drills --------------------------------------


@pytest.mark.slow
def test_cli_probe_sigkill_drill_end_to_end(tmp_path):
    """THE acceptance drill through the real CLI: 3 serve.py children,
    SIGKILL replica 1 mid-stream — every request answered, captions
    bit-identical to the fault-free single-engine reference, zero
    post-warmup compiles per surviving child, blackbox harvested from
    the dead replica, and the record survives serve_report's gates."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    root = str(tmp_path / "supervise")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts",
                                      "serve_supervisor.py"),
         "--serve_demo", "1", "--supervise_probe", "1",
         "--supervise_replicas", "3", "--serve_demo_eos_bias", "-2",
         "--decode_chunk", "2", "--beam_size", "1",
         "--supervise_dir", root],
        capture_output=True, text=True, timeout=600, cwd=REPO, env=env)
    assert proc.returncode == 0, proc.stderr[-4000:]
    rec = json.loads(proc.stdout.splitlines()[-1])
    sup = rec["supervisor"]
    assert rec["completed"] == rec["num_requests"]
    assert sup["parity_ok"] and sup["parity_mismatches"] == 0
    assert sup["requeued"] >= 1 and sup["restarts"] >= 1
    assert sup["budget_ok"] and sup["deaths"] == 0
    assert sup["blackbox_harvested"] and sup["incidents"] >= 1
    assert rec["recompiles_after_warmup"] == 0
    assert rec["stream"]["prefix_ok"]
    assert os.path.exists(os.path.join(root, "supervisor_exit.json"))
    # The record renders and passes serve_report's process gates.
    report = _run_report(rec, tmp_path)
    assert report.returncode == 0, report.stderr


@pytest.mark.slow
def test_double_sigterm_supervisor_drill(tmp_path):
    """The two-signal contract at the SUPERVISOR level: first SIGTERM
    drains (children run their own drains), a second mid-drain aborts —
    exit 143, every submitted id answered exactly once (caption or
    rejected_draining, nothing silent), the supervisor's own blackbox
    dumped with reason drain_abort.  SIGSTOP/SIGCONT sequence the two
    signals deterministically."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    root = str(tmp_path / "supervise")
    stderr_path = tmp_path / "stderr.log"
    n = 24
    with open(stderr_path, "w") as errf:
        proc = subprocess.Popen(
            [sys.executable, os.path.join(REPO, "scripts",
                                          "serve_supervisor.py"),
             "--serve_demo", "1", "--supervise_replicas", "2",
             "--serve_demo_eos_bias", "-2", "--decode_chunk", "2",
             "--beam_size", "1", "--supervise_dir", root,
             "--loglevel", "WARNING"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, stderr=errf,
            text=True, cwd=REPO, env=env)
    out_lines = []

    def read_out():
        for line in proc.stdout:
            if line.strip():
                out_lines.append(json.loads(line))

    reader = threading.Thread(target=read_out, daemon=True)
    reader.start()
    try:
        for i in range(n):
            proc.stdin.write(json.dumps(
                {"id": i, "video_id": f"v{i % 16}", "op": "stream"})
                + "\n")
        proc.stdin.flush()          # stdin stays OPEN: no EOF shutdown
        deadline = time.monotonic() + 300.0
        while not out_lines:        # first chunk: the fleet is mid-work
            assert time.monotonic() < deadline, "no output in 300s"
            assert proc.poll() is None, stderr_path.read_text()[-4000:]
            time.sleep(0.01)
        proc.send_signal(signal.SIGTERM)
        while "draining" not in stderr_path.read_text():
            assert time.monotonic() < deadline, "drain never announced"
            assert proc.poll() is None, stderr_path.read_text()[-4000:]
            time.sleep(0.005)
        # Freeze the supervisor, queue the second signal, thaw: the
        # abort lands at a deterministic point mid-drain.
        os.kill(proc.pid, signal.SIGSTOP)
        proc.send_signal(signal.SIGTERM)
        os.kill(proc.pid, signal.SIGCONT)
        rc = proc.wait(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
        proc.stdin.close()
    reader.join(timeout=30)
    err = stderr_path.read_text()
    assert rc == EXIT_SIGTERM, err[-4000:]
    assert "drain aborted" in err
    terminals = {}
    for obj in out_lines:
        if obj.get("final") or "error" in obj:
            assert obj["id"] not in terminals, f"double answer: {obj}"
            terminals[obj["id"]] = obj
    assert set(terminals) == set(range(n)), err[-4000:]
    kinds = {("caption" if "caption" in t else t["error"])
             for t in terminals.values()}
    assert kinds <= {"caption", "rejected_draining"}
    assert any("error" in t for t in terminals.values()), \
        "the abort should have left unfinished work answered honestly"
    bb = os.path.join(root, "blackbox.json")
    assert os.path.exists(bb)
    with open(bb) as f:
        assert json.load(f)["reason"] == "drain_abort"
