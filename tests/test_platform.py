"""Session-quirk guards in utils/platform.py."""

import logging

from cst_captioning_tpu.utils.platform import configure_cli_logging


class TestConfigureCliLogging:
    def _restore(self, handlers, level):
        root = logging.getLogger()
        for h in list(root.handlers):
            root.removeHandler(h)
        for h in handlers:
            root.addHandler(h)
        root.setLevel(level)

    def test_displaces_preinstalled_root_handler(self):
        """A sitecustomize-style pre-installed WARNING handler must not
        turn the CLI's logging setup into a no-op (the field failure: a
        whole training run with every INFO progress line swallowed)."""
        root = logging.getLogger()
        saved_handlers, saved_level = list(root.handlers), root.level
        try:
            self._restore([], logging.WARNING)
            squelcher = logging.StreamHandler()
            squelcher.setLevel(logging.WARNING)
            root.addHandler(squelcher)
            root.setLevel(logging.WARNING)

            configure_cli_logging("info")

            assert squelcher not in root.handlers
            assert root.level == logging.INFO
            assert len(root.handlers) == 1
            assert logging.getLogger("cst_captioning_tpu.anything").isEnabledFor(
                logging.INFO)
        finally:
            self._restore(saved_handlers, saved_level)

    def test_bad_loglevel_falls_back_to_info(self):
        root = logging.getLogger()
        saved_handlers, saved_level = list(root.handlers), root.level
        try:
            configure_cli_logging("not-a-level")
            assert root.level == logging.INFO
        finally:
            self._restore(saved_handlers, saved_level)
