"""Native C++ CIDEr-D: parity with the Python scorer + edge cases.

The Python scorer (metrics/ciderd.py) is itself oracle-tested; the native
scorer must match it numerically so the RL reward is identical whichever
path the trainer picks (SURVEY.md §7 hard part (e) — reward hot loop).
"""

import numpy as np
import pytest

from cst_captioning_tpu.data.vocab import Vocab
from cst_captioning_tpu.metrics.ciderd import CiderD, build_corpus_df
from cst_captioning_tpu.training.rewards import RewardComputer

try:  # missing toolchain is a supported fallback path, not a failure
    from cst_captioning_tpu.native import NativeCiderD, load_library

    load_library()
except Exception as _e:  # NativeUnavailable or loader error
    pytest.skip(f"native scorer unavailable: {_e}", allow_module_level=True)

WORDS = ["a", "man", "is", "cooking", "dog", "runs", "the", "park",
         "woman", "sings", "plays", "guitar", "cat", "sleeps"]


def make_refs(num_videos=10, caps_per_video=5, seed=0):
    rng = np.random.default_rng(seed)
    refs = {}
    for v in range(num_videos):
        caps = []
        for _ in range(caps_per_video):
            n = rng.integers(3, 9)
            caps.append(" ".join(rng.choice(WORDS, n)))
        refs[f"v{v}"] = caps
    return refs


@pytest.fixture(scope="module")
def refs():
    return make_refs()


@pytest.fixture(scope="module")
def py_scorer(refs):
    df, n = build_corpus_df(refs)
    return CiderD(df_mode="corpus", df=df, ref_len=float(n))


@pytest.fixture(scope="module")
def native_scorer(refs):
    return NativeCiderD(refs)


def py_score(py_scorer, video_ids, captions):
    per_vid = len(captions) // len(video_ids)
    gts, res = {}, []
    for i, cap in enumerate(captions):
        key = str(i)
        gts[key] = list(
            make_refs()[video_ids[i // per_vid]]
        )
        res.append({"image_id": key, "caption": [cap]})
    _, scores = py_scorer.compute_score(gts, res)
    return scores


class TestParity:
    def test_matches_python_scorer(self, refs, py_scorer, native_scorer):
        rng = np.random.default_rng(1)
        video_ids = list(refs.keys())
        hyps = []
        for v in video_ids:
            # one near-match (a real reference) and one random caption each
            hyps.append(refs[v][0])
            hyps.append(" ".join(rng.choice(WORDS, int(rng.integers(2, 10)))))
        native = native_scorer.score_strings(video_ids, hyps)
        python = py_score(py_scorer, video_ids, hyps)
        np.testing.assert_allclose(native, python, rtol=1e-9, atol=1e-12)
        assert native.max() > 1.0  # exact-match rows score high

    def test_score_ids_equals_score_strings(self, refs, native_scorer):
        vocab_words = {i + 1: w for i, w in enumerate(WORDS)}
        vocab = Vocab(vocab_words)
        scorer = NativeCiderD(refs, vocab.word_to_ix)
        video_ids = list(refs.keys())[:4]
        caps = [refs[v][1] for v in video_ids]
        ids = np.zeros((4, 12), dtype=np.int32)
        for i, c in enumerate(caps):
            row = vocab.encode(c.split(), 12)
            ids[i] = row
        a = scorer.score_ids(video_ids, ids)
        # strings path allocates the same ids (vocab seeded identically)
        b = scorer.score_strings(video_ids, caps)
        np.testing.assert_allclose(a, b, rtol=1e-9)


class TestExternalDf:
    def test_pickle_df_parity_with_python_scorer(self, refs):
        """--train_cached_tokens path: the native scorer loaded with an
        EXTERNAL corpus df (built over a superset corpus, so it differs
        from this run's refs-derived df) must match the Python scorer
        loaded from the same table."""
        big_corpus = {**refs, **make_refs(num_videos=25, seed=9)}
        df, ndocs = build_corpus_df(big_corpus)
        py = CiderD(df_mode="corpus", df=df, ref_len=float(ndocs))

        native = NativeCiderD(refs)
        native.load_df(df, float(ndocs))

        video_ids = list(refs.keys())[:4]
        rng = np.random.default_rng(5)
        caps = [" ".join(rng.choice(WORDS, int(rng.integers(3, 9))))
                for _ in range(8)]
        got = native.score_strings(video_ids, caps)
        want = py_score(py, video_ids, caps)
        np.testing.assert_allclose(got, want, rtol=1e-9, atol=1e-12)
        # and the external df genuinely changes scores vs the internal one
        internal = NativeCiderD(refs).score_strings(video_ids, caps)
        assert not np.allclose(got, internal)


class TestEdgeCases:
    def test_empty_hypothesis_scores_zero(self, refs, native_scorer):
        ids = np.zeros((2, 8), dtype=np.int32)
        out = native_scorer.score_ids(list(refs.keys())[:2], ids)
        np.testing.assert_allclose(out, 0.0)

    def test_degenerate_repetition_clipped(self, refs, native_scorer):
        vid = list(refs.keys())[0]
        exact = native_scorer.score_strings([vid], [refs[vid][0]])[0]
        first_word = refs[vid][0].split()[0]
        stutter = native_scorer.score_strings(
            [vid], [" ".join([first_word] * 8)]
        )[0]
        assert stutter < exact

    def test_unknown_video_raises(self, native_scorer):
        with pytest.raises(KeyError):
            native_scorer.score_ids(["nope"], np.zeros((1, 4), np.int32))

    def test_non_multiple_rows_raises(self, refs, native_scorer):
        vids = list(refs.keys())[:4]
        with pytest.raises(ValueError, match="multiple"):
            native_scorer.score_ids(vids, np.zeros((10, 4), np.int32))
        with pytest.raises(ValueError, match="multiple"):
            native_scorer.score_ids(vids, np.zeros((3, 4), np.int32))

    def test_multiple_hyps_per_video_grouping(self, refs, native_scorer):
        video_ids = list(refs.keys())[:2]
        # 2 hyps per video: [v0 ref, garbage, v1 ref, garbage]
        caps = [refs[video_ids[0]][0], "cat cat cat",
                refs[video_ids[1]][0], "cat cat cat"]
        out = native_scorer.score_strings(video_ids, caps)
        assert out[0] > out[1]
        assert out[2] > out[3]


class TestConsensusLOO:
    def test_matches_python_consensus(self, refs):
        from cst_captioning_tpu.metrics.consensus import compute_consensus_scores

        py = compute_consensus_scores(refs, native=False)
        nat = NativeCiderD(refs).consensus_scores()
        assert set(py) == set(nat)
        for vid in py:
            np.testing.assert_allclose(nat[vid], py[vid],
                                       rtol=1e-9, atol=1e-12)

    def test_single_caption_video_scores_zero(self):
        refs = {"v0": ["a man is cooking"], "v1": ["a dog runs", "dog runs"]}
        out = NativeCiderD(refs).consensus_scores()
        np.testing.assert_allclose(out["v0"], [0.0])
        assert out["v1"].shape == (2,)
        assert (out["v1"] > 0).all()  # overlapping siblings score nonzero


class TestRewardComputerIntegration:
    def test_native_and_python_advantages_match(self, refs, py_scorer):
        vocab = Vocab({i + 1: w for i, w in enumerate(WORDS)})
        native = NativeCiderD(refs, vocab.word_to_ix)
        rc_py = RewardComputer(vocab, py_scorer, refs, seq_per_img=2)
        rc_nat = RewardComputer(vocab, native, refs, seq_per_img=2)
        assert rc_nat._native and not rc_py._native

        rng = np.random.default_rng(3)
        video_ids = list(refs.keys())[:3]
        sampled = np.zeros((6, 10), dtype=np.int32)
        for i in range(6):
            n = int(rng.integers(2, 9))
            sampled[i, :n] = rng.integers(1, len(WORDS) + 1, n)
        greedy = sampled[::2].copy()
        adv_py, stats_py = rc_py(video_ids, sampled, greedy)
        adv_nat, stats_nat = rc_nat(video_ids, sampled, greedy)
        np.testing.assert_allclose(adv_nat, adv_py, rtol=1e-5, atol=1e-7)
        assert stats_nat["reward"] == pytest.approx(stats_py["reward"], rel=1e-6)
