"""Multi-host validation consistency (VERDICT r2 item 4).

Simulates a pod on one process: every host decodes its strided loader
shard, shards are all-gathered (injected fake allgather), and each host
must end up with the IDENTICAL full prediction set — the property that
keeps best-checkpoint bookkeeping in lockstep across processes.
"""

import jax
import numpy as np
import pytest

from cst_captioning_tpu.data.dataset import CaptionDataset, SplitPaths
from cst_captioning_tpu.data.loader import CaptionLoader
from cst_captioning_tpu.data.synthetic import SyntheticSpec, generate
from cst_captioning_tpu.models import CaptionModel
from cst_captioning_tpu.training.evaluation import (
    _decode_local,
    decode_split,
    gather_strided_predictions,
)

MAX_LEN = 8


@pytest.fixture(scope="module")
def setup(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("mh"))
    spec = SyntheticSpec(num_videos=5, captions_per_video=3, max_len=MAX_LEN,
                         feat_dims=(12, 6), feat_times=(3, 1))
    art = generate(root, "train", spec)
    paths = SplitPaths(
        feat_h5=__import__("json").loads(art["feat_h5"]),
        label_h5=art["label_h5"], info_json=art["info_json"],
    )
    ds = CaptionDataset(paths)
    model = CaptionModel(vocab_size=ds.vocab.size_with_pad, embed_size=16,
                         hidden_size=16, attn_size=16, use_attention=True,
                         dropout_rate=0.0)
    feats = [np.zeros((2, t, d), np.float32)
             for t, d in zip(ds.feat_times, ds.feat_dims)]
    labels = np.ones((2, ds.seq_length), np.int32)
    params = model.init(jax.random.PRNGKey(0), [np.asarray(f) for f in feats],
                        labels, 1)["params"]
    yield ds, model, params
    ds.close()


def _loader(ds, q, P):
    return CaptionLoader(ds, batch_size=2, seq_per_img=1, shuffle=False,
                         process_index=q, process_count=P)


def test_every_host_reconstructs_identical_full_split(setup):
    ds, model, params = setup
    P = 2  # 5 videos -> shard sizes 3 and 2: exercises the gather padding

    # Per-host local decodes (what each process computes on a real pod).
    shard_rows = []
    for q in range(P):
        ids_q, rows_q = _decode_local(model, params, _loader(ds, q, P),
                                      MAX_LEN, 1, 0.0)
        assert ids_q == [ds.video_ids[i] for i in range(q, ds.num_videos, P)]
        shard_rows.append(np.stack(rows_q))

    maxn = max(len(r) for r in shard_rows)
    stacked = np.stack([
        np.pad(r, ((0, maxn - len(r)), (0, 0))) for r in shard_rows
    ])
    fake_allgather = lambda local: stacked  # what a pod's allgather returns

    # Ground truth: the single-host full decode.
    full = decode_split(model, params, _loader(ds, 0, 1), ds.vocab, MAX_LEN)
    full_by_id = {p["image_id"]: p["caption"] for p in full}

    per_host = []
    for q in range(P):
        preds = decode_split(model, params, _loader(ds, q, P), ds.vocab,
                             MAX_LEN, allgather=fake_allgather)
        per_host.append({p["image_id"]: p["caption"] for p in preds})

    assert per_host[0] == per_host[1], "hosts disagree on the gathered split"
    assert per_host[0] == full_by_id, "gathered split != single-host decode"


def test_gather_rejects_wrong_row_count(setup):
    ds, _, _ = setup
    with pytest.raises(ValueError, match="expected"):
        gather_strided_predictions(
            np.zeros((1, MAX_LEN), np.int32), ds.video_ids,
            process_index=0, process_count=2,
            allgather=lambda x: np.stack([x, x]),
        )


def test_sharded_decode_matches_single_device(setup):
    """Validation decode routed over the data-parallel mesh (all devices)
    must produce exactly the single-device predictions; batch sizes that
    don't divide the mesh fall back to single-device decode."""
    ds, model, params = setup
    from cst_captioning_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(jax.devices())
    assert mesh.shape["data"] > 1, "test needs the multi-device CPU mesh"
    base = decode_split(model, params, _loader(ds, 0, 1), ds.vocab, MAX_LEN)
    # batch_size=2 doesn't divide 8 devices -> exercises the fallback
    sharded_fallback = decode_split(model, params, _loader(ds, 0, 1),
                                    ds.vocab, MAX_LEN, mesh=mesh)
    assert sharded_fallback == base
    # batch_size == device count -> genuinely sharded decode
    big = CaptionLoader(ds, batch_size=mesh.shape["data"], seq_per_img=1,
                        shuffle=False)
    sharded = decode_split(model, params, big, ds.vocab, MAX_LEN, mesh=mesh)
    assert {p["image_id"]: p["caption"] for p in sharded} == \
        {p["image_id"]: p["caption"] for p in base}


def test_mesh_dropped_under_multihost(setup):
    """On a pod each process holds a DIFFERENT local batch, so sharding it
    over the global mesh would stitch unrelated rows together — the decode
    must fall back to per-host single-device + gather."""
    ds, model, params = setup
    from cst_captioning_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(jax.devices())
    P = 2
    shard_rows = []
    for q in range(P):
        _, rows_q = _decode_local(model, params, _loader(ds, q, P),
                                  MAX_LEN, 1, 0.0)
        shard_rows.append(np.stack(rows_q))
    maxn = max(len(r) for r in shard_rows)
    stacked = np.stack([
        np.pad(r, ((0, maxn - len(r)), (0, 0))) for r in shard_rows
    ])
    base = decode_split(model, params, _loader(ds, 0, 1), ds.vocab, MAX_LEN)
    preds = decode_split(model, params, _loader(ds, 0, P), ds.vocab, MAX_LEN,
                         allgather=lambda x: stacked, mesh=mesh)
    assert {p["image_id"]: p["caption"] for p in preds} == \
        {p["image_id"]: p["caption"] for p in base}


def test_single_process_skips_gather(setup):
    """process_count == 1 must not touch any allgather machinery."""
    ds, model, params = setup
    boom = lambda x: (_ for _ in ()).throw(AssertionError("allgather called"))
    preds = decode_split(model, params, _loader(ds, 0, 1), ds.vocab,
                         MAX_LEN, allgather=boom)
    assert len(preds) == ds.num_videos
