"""On-device CIDEr-D (ops/jax_ciderd.py) parity with the Python oracle.

The Python scorer (metrics/ciderd.py) is itself oracle-tested and the C++
scorer matches it at 1e-9; the device scorer must agree so the fused CST
step's rewards are interchangeable with the host path.
"""

import jax
import numpy as np
import pytest

from cst_captioning_tpu.data.vocab import Vocab
from cst_captioning_tpu.metrics.ciderd import CiderD, build_corpus_df
from cst_captioning_tpu.ops.jax_ciderd import ciderd_scores
from cst_captioning_tpu.training.device_rewards import build_device_tables
from cst_captioning_tpu.tuning.sweep import PARITY_SHAPE_GRID

WORDS = ["a", "man", "is", "cooking", "dog", "runs", "the", "park",
         "woman", "sings", "plays", "guitar", "cat", "sleeps"]
W2I = {w: i + 1 for i, w in enumerate(WORDS)}
VOCAB = Vocab({i + 1: w for i, w in enumerate(WORDS)})


def make_refs(num_videos=8, caps_per_video=4, seed=0):
    rng = np.random.default_rng(seed)
    refs = {}
    for v in range(num_videos):
        refs[f"v{v}"] = [
            " ".join(rng.choice(WORDS, int(rng.integers(3, 9))))
            for _ in range(caps_per_video)
        ]
    return refs


def py_scores(py_scorer, refs, video_ids, captions):
    per_vid = len(captions) // len(video_ids)
    gts, res = {}, []
    for i, cap in enumerate(captions):
        key = str(i)
        gts[key] = list(refs[video_ids[i // per_vid]])
        res.append({"image_id": key, "caption": [cap]})
    return py_scorer.compute_score(gts, res)[1]


def encode_rows(captions, max_len=12):
    rows = np.zeros((len(captions), max_len), np.int32)
    for i, c in enumerate(captions):
        ids = [W2I[w] for w in c.split()][:max_len]
        rows[i, :len(ids)] = ids
    return rows


@pytest.fixture(scope="module")
def setup():
    refs = make_refs()
    df, n = build_corpus_df(refs)
    py = CiderD(df_mode="corpus", df=df, ref_len=float(n))
    corpus, tables, video_row = build_device_tables(refs, W2I)
    return refs, py, corpus, tables, video_row


def test_parity_with_python_scorer(setup):
    refs, py, corpus, tables, video_row = setup
    rng = np.random.default_rng(3)
    video_ids = list(refs.keys())[:4]
    caps = [" ".join(rng.choice(WORDS, int(rng.integers(2, 10))))
            for _ in range(8)]
    rows = encode_rows(caps)
    vix = np.repeat([video_row[v] for v in video_ids], 2).astype(np.int32)
    got = np.asarray(jax.jit(ciderd_scores, static_argnames="sigma")(
        rows, vix, corpus, tables))
    want = py_scores(py, refs, video_ids, caps)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_parity_reference_captions_score_high(setup):
    """A hypothesis equal to one of its own references must score exactly
    what the Python scorer gives (a high score), including the clipping."""
    refs, py, corpus, tables, video_row = setup
    video_ids = list(refs.keys())[:3]
    caps = [refs[v][0] for v in video_ids]
    rows = encode_rows(caps)
    vix = np.asarray([video_row[v] for v in video_ids], np.int32)
    got = np.asarray(ciderd_scores(rows, vix, corpus, tables))
    want = py_scores(py, refs, video_ids, caps)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
    assert (got > 1.0).all()


def test_empty_and_degenerate_rows(setup):
    refs, py, corpus, tables, video_row = setup
    video_ids = list(refs.keys())[:2]
    caps = ["", "dog dog dog dog dog dog"]
    rows = encode_rows(caps)
    vix = np.asarray([video_row[v] for v in video_ids], np.int32)
    got = np.asarray(ciderd_scores(rows, vix, corpus, tables))
    want = py_scores(py, refs, video_ids, caps)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
    assert got[0] == pytest.approx(0.0, abs=1e-6)


def test_ref_chunked_scores_identical_to_ulp(setup):
    """Chunking the hyp-ref match contraction over the reference axis
    (the HBM-envelope bound, VERDICT r3 #3) computes element-for-element
    the same math; the only permitted difference is XLA's reduction
    tiling for the differently-shaped G-axis sum, which is float32
    ULP-level (observed max 1 ULP).  Pin that bound for every chunk
    size, including non-dividing ones."""
    refs, py, corpus, tables, video_row = setup
    rng = np.random.default_rng(11)
    video_ids = list(refs.keys())[:4]
    caps = [" ".join(rng.choice(WORDS, int(rng.integers(2, 10))))
            for _ in range(8)]
    rows = encode_rows(caps)
    vix = np.repeat([video_row[v] for v in video_ids], 2).astype(np.int32)
    base = np.asarray(jax.jit(
        ciderd_scores, static_argnames=("sigma", "ref_chunk")
    )(rows, vix, corpus, tables))
    R = tables.slot.shape[1]
    for chunk in (1, 2, 3, R, R + 5):
        got = np.asarray(jax.jit(
            ciderd_scores, static_argnames=("sigma", "ref_chunk")
        )(rows, vix, corpus, tables, ref_chunk=chunk))
        # a few float32 ULPs, NOT a loose tolerance: rtol 5e-7 ~ 4 ULP
        np.testing.assert_allclose(got, base, rtol=5e-7, atol=1e-8,
                                   err_msg=f"chunk={chunk}")
        if chunk >= R:
            # chunk >= R short-circuits to the very same one-shot program
            np.testing.assert_array_equal(got, base)


def test_auto_ref_chunk_envelope():
    from cst_captioning_tpu.ops.jax_ciderd import (
        auto_ref_chunk,
        match_tensor_bytes,
    )

    refs = make_refs()
    _, tables, _ = build_device_tables(refs, W2I)
    R = tables.slot.shape[1]
    total = match_tensor_bytes(640, 30, tables)
    assert total > 0
    # generous budget -> one-shot
    assert auto_ref_chunk(640, 30, tables, budget_bytes=total) is None
    # tight budget -> chunked, within [1, R], and actually under budget
    chunk = auto_ref_chunk(640, 30, tables, budget_bytes=total // 4)
    assert 1 <= chunk <= R
    assert chunk * (total // R) <= total // 4 or chunk == 1


def test_external_df_parity(setup):
    """--train_cached_tokens path: tables built from a superset-corpus df
    must match the Python scorer loaded with the same df."""
    refs, _, _, _, _ = setup
    big = {**refs, **make_refs(num_videos=20, seed=7)}
    df, n = build_corpus_df(big)
    py = CiderD(df_mode="corpus", df=df, ref_len=float(n))
    corpus, tables, video_row = build_device_tables(
        refs, W2I, external_df=df, external_ref_len=float(n))
    rng = np.random.default_rng(5)
    video_ids = list(refs.keys())[:4]
    caps = [" ".join(rng.choice(WORDS, int(rng.integers(2, 10))))
            for _ in range(4)]
    rows = encode_rows(caps)
    vix = np.asarray([video_row[v] for v in video_ids], np.int32)
    got = np.asarray(ciderd_scores(rows, vix, corpus, tables))
    want = py_scores(py, refs, video_ids, caps)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


class TestFusedStep:
    """The fused on-device CST step must be EQUIVALENT to the host path:
    same rollout key -> same samples -> same advantages (device scorer vs
    Python scorer) -> same parameter update."""

    def _build(self):
        from cst_captioning_tpu.models import CaptionModel
        from cst_captioning_tpu.training.state import (
            create_train_state,
            make_optimizer,
        )

        refs = make_refs(num_videos=4, caps_per_video=3, seed=2)
        model = CaptionModel(
            vocab_size=len(WORDS) + 1, embed_size=16, hidden_size=16,
            attn_size=16, use_attention=True, dropout_rate=0.5,
        )
        tx, _ = make_optimizer(learning_rate=1e-2, grad_clip=5.0)
        state = create_train_state(
            model, jax.random.PRNGKey(0), [(3, 8)], 8, 2, tx, batch_size=4
        )
        feats = [jax.random.normal(jax.random.PRNGKey(1), (4, 3, 8))]
        return refs, model, state, feats

    def test_matches_host_path_update(self):
        from cst_captioning_tpu.training.rewards import RewardComputer
        from cst_captioning_tpu.training.steps import (
            make_fused_cst_step,
            make_rl_grad_step,
            make_rollout_fused,
        )

        refs, model, state, feats = self._build()
        corpus, tables, video_row = build_device_tables(refs, W2I)
        video_ids = list(refs.keys())
        vix = np.asarray([video_row[v] for v in video_ids], np.int32)
        key = jax.random.PRNGKey(9)

        fused = jax.jit(make_fused_cst_step(model, 8, 2, corpus, tables))
        new_fused, m_fused = fused(state, feats, vix, key)

        df, n = build_corpus_df(refs)
        py = CiderD(df_mode="corpus", df=df, ref_len=float(n))
        rc = RewardComputer(VOCAB, py, refs, seq_per_img=2)
        rollout = jax.jit(make_rollout_fused(model, 8, 2))
        rl_step = jax.jit(make_rl_grad_step(model, 2))
        sampled, fetch = rollout(state.params, feats, key)
        fetched = np.asarray(fetch)
        adv, stats = rc(video_ids, fetched[:8], fetched[8:])
        new_host, m_host = rl_step(state, feats, sampled, adv, key)

        assert float(m_fused["reward"]) == pytest.approx(
            stats["reward"], rel=1e-4, abs=1e-5)
        assert float(m_fused["advantage"]) == pytest.approx(
            stats["advantage"], rel=1e-4, abs=1e-5)
        for a, b in zip(jax.tree_util.tree_leaves(new_fused.params),
                        jax.tree_util.tree_leaves(new_host.params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    def test_scb_sample_baseline(self):
        from cst_captioning_tpu.training.steps import make_fused_cst_step

        refs, model, state, feats = self._build()
        corpus, tables, video_row = build_device_tables(refs, W2I)
        vix = np.asarray([video_row[v] for v in refs], np.int32)
        fused = jax.jit(make_fused_cst_step(
            model, 8, 2, corpus, tables, baseline="scb-sample"))
        new_state, m = fused(state, feats, vix, jax.random.PRNGKey(3))
        assert np.isfinite(float(m["loss"]))
        # leave-one-out baselines average to the per-video sample mean
        assert float(m["baseline"]) == pytest.approx(
            float(m["reward"]), abs=1e-4)

    def test_scb_gt_baseline(self):
        from cst_captioning_tpu.training.steps import make_fused_cst_step

        refs, model, state, feats = self._build()
        corpus, tables, video_row = build_device_tables(refs, W2I)
        vix = np.asarray([video_row[v] for v in refs], np.int32)
        base = np.linspace(0.5, 2.0, len(refs)).astype(np.float32)
        fused = jax.jit(make_fused_cst_step(
            model, 8, 2, corpus, tables, baseline="scb-gt",
            scb_gt_baseline=jax.numpy.asarray(base)))
        _, m = fused(state, feats, vix, jax.random.PRNGKey(3))
        assert float(m["baseline"]) == pytest.approx(base.mean(), rel=1e-5)


def test_oov_reference_words_match_python_scorer():
    """References containing words OUTSIDE the model vocab must still
    weigh df and reference norms exactly like the string scorers do
    (they can never match a hypothesis, whose ids come from the vocab)."""
    refs = make_refs(num_videos=4, caps_per_video=3, seed=4)
    refs = {v: caps + [caps[0] + " zzunseen qqrare"]
            for v, caps in refs.items()}
    df, n = build_corpus_df(refs)
    py = CiderD(df_mode="corpus", df=df, ref_len=float(n))
    corpus, tables, video_row = build_device_tables(refs, W2I)  # W2I lacks them
    rng = np.random.default_rng(6)
    video_ids = list(refs.keys())
    caps = [" ".join(rng.choice(WORDS, int(rng.integers(2, 10))))
            for _ in range(4)]
    rows = encode_rows(caps)
    vix = np.asarray([video_row[v] for v in video_ids], np.int32)
    got = np.asarray(ciderd_scores(rows, vix, corpus, tables))
    want = py_scores(py, refs, video_ids, caps)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("vocab_size,seq_len,seq_per_img",
                         PARITY_SHAPE_GRID)
def test_parity_across_tuner_shape_grid(vocab_size, seq_len, seq_per_img):
    """Device-scorer parity at every (vocab, seq_len, seq_per_img) corner
    of the autotuner's swept shape space (tuning.sweep.PARITY_SHAPE_GRID).

    --device_rewards 1 is the shipped default and the autotuner sweeps
    shapes around it; this pin guarantees that no swept configuration can
    move rewards off the host scorers — the acceptance criterion for
    making the fused path the default everywhere the tuner may land."""
    words = [f"w{i}" for i in range(1, vocab_size)]
    w2i = {w: i + 1 for i, w in enumerate(words)}
    rng = np.random.default_rng(vocab_size * 1000 + seq_len)
    n_videos = 6
    refs = {
        f"v{v}": [
            " ".join(rng.choice(words, int(rng.integers(3, seq_len + 1))))
            for _ in range(3)
        ]
        for v in range(n_videos)
    }
    df, n = build_corpus_df(refs)
    py = CiderD(df_mode="corpus", df=df, ref_len=float(n))
    corpus, tables, video_row = build_device_tables(refs, w2i)
    video_ids = list(refs.keys())
    caps = [" ".join(rng.choice(words, int(rng.integers(1, seq_len + 1))))
            for _ in range(n_videos * seq_per_img)]
    rows = np.zeros((len(caps), seq_len), np.int32)
    for i, c in enumerate(caps):
        ids = [w2i[w] for w in c.split()][:seq_len]
        rows[i, :len(ids)] = ids
    vix = np.repeat([video_row[v] for v in video_ids],
                    seq_per_img).astype(np.int32)
    got = np.asarray(jax.jit(ciderd_scores, static_argnames="sigma")(
        rows, vix, corpus, tables))
    want = py_scores(py, refs, video_ids, caps)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=1e-4)


def test_large_random_fuzz(setup):
    """256 random hypotheses across all videos, bulk parity."""
    refs, py, corpus, tables, video_row = setup
    rng = np.random.default_rng(11)
    video_ids = list(refs.keys())
    caps = [" ".join(rng.choice(WORDS, int(rng.integers(1, 12))))
            for _ in range(32 * len(video_ids))]
    rows = encode_rows(caps)
    vix = np.repeat([video_row[v] for v in video_ids], 32).astype(np.int32)
    got = np.asarray(ciderd_scores(rows, vix, corpus, tables))
    want = py_scores(py, refs, video_ids, caps)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=1e-4)
