"""C++ PTB tokenizer (native/tokenizer.cpp) parity vs the Python oracle.

The native tokenizer replaces the reference's Java PTBTokenizer subprocess
for bulk corpus paths; metrics/tokenizer.py stays the oracle.  Parity must
be token-for-token on everything the native path can receive (ASCII) —
any divergence would silently shift every metric downstream.
"""

import random
import string

import pytest

from cst_captioning_tpu.metrics.tokenizer import (
    tokenize_corpus,
    tokenize_to_str,
)

try:
    from cst_captioning_tpu.native import NativeUnavailable, ptb_tokenize_str

    try:
        ptb_tokenize_str("probe")
        NATIVE = True
    except NativeUnavailable:
        NATIVE = False
except ImportError:  # pragma: no cover
    NATIVE = False

pytestmark = pytest.mark.skipif(not NATIVE, reason="no native toolchain")

GOLDEN = [
    "A man is cooking.",
    "a woman is playing in the park",
    "don't run!",
    "DON'T RUN!!",
    "cannot.",
    "cannot", "gonna", "gotta", "wanna", "lemme", "gimme", "d'ye",
    "'tis gonna rain", "'twas the night",
    "the dog... ran (fast)",
    "it's the dogs' ball",
    "the child's toy, and the cats' bowls",
    "u.s. army",
    "e.g. a dog",
    "...", "--", "-", "''", "``",
    "a-b c--d e---f",
    "he said \"hello there\" loudly",
    "score: 3/4 (75%)",
    "x's y're z've w'll v'm u'd tn't",
    "'quoted' ''double'' '''triple'''",
    "trailing. .leading .both.",
    "a.", "a.b.", "A.B.C.",
    "[brackets] {braces} <angles>",
    "semi;colon and co:lon",
    "multi   spaces\tand\nnewlines",
    "",
    "   ",
    "!!!???",
    "can't won't shouldn't couldn't it'll they're we've i'm you'd",
]


def test_golden_parity():
    for c in GOLDEN:
        assert ptb_tokenize_str(c) == tokenize_to_str(c), repr(c)


def test_fuzz_parity_caption_like():
    """Random caption-shaped ASCII strings: words, contractions, punct."""
    rng = random.Random(0)
    words = ["a", "man", "is", "cooking", "dog's", "don't", "cannot",
             "the", "u.s.", "it's", "runs", "fast", "...", "--", "(", ")",
             "ball,", "park.", "!", "?", "'quoted'", "x", "gonna", "I'm",
             "they'll", "we've", "isn't", '"say"', "end."]
    for _ in range(500):
        c = " ".join(rng.choices(words, k=rng.randint(0, 12)))
        assert ptb_tokenize_str(c) == tokenize_to_str(c), repr(c)


def test_fuzz_parity_raw_ascii():
    """Adversarial: arbitrary printable-ASCII soup must still agree."""
    rng = random.Random(1)
    alphabet = (string.ascii_letters + string.digits
                + " .',!?-()\"'&%$#@\x1c\x1e\t\n")
    for _ in range(500):
        c = "".join(rng.choices(alphabet, k=rng.randint(0, 60)))
        assert ptb_tokenize_str(c) == tokenize_to_str(c), repr(c)


def test_fuzz_parity_contraction_chains():
    """Dense random chains of contraction suffixes and letters — the
    left-to-right non-overlap semantics of re.sub must match exactly."""
    rng = random.Random(2)
    parts = ["'ll", "'re", "'ve", "n't", "'s", "'m", "'d", "a", "b", "'",
             "t", "n", "ca", "do"]
    for _ in range(800):
        c = "".join(rng.choices(parts, k=rng.randint(1, 8)))
        assert ptb_tokenize_str(c) == tokenize_to_str(c), repr(c)


def test_non_ascii_rejected_and_corpus_falls_back():
    with pytest.raises(ValueError):
        ptb_tokenize_str("café au lait")
    # tokenize_corpus routes non-ASCII through the Python oracle.
    out = tokenize_corpus({"v": ["café — au lait", "a man runs."]})
    assert out["v"][0] == tokenize_to_str("café — au lait")
    assert out["v"][1] == tokenize_to_str("a man runs.")


def test_corpus_native_matches_python():
    caps = {f"v{i}": [c for c in GOLDEN if c.strip()][i::4]
            for i in range(4)}
    assert tokenize_corpus(caps, use_native=True) == \
        tokenize_corpus(caps, use_native=False)


def test_long_caption_buffer():
    c = " ".join(["supercalifragilistic don't"] * 200)
    assert ptb_tokenize_str(c) == tokenize_to_str(c)


def test_review_found_divergences():
    """Regression pins for the empirically-found parity breaks: chained
    contractions (re.sub resumes after the consumed group-1 letter),
    literal lowercase bracket tags (kept by the oracle — the punctuation
    set holds uppercase only), and Python str.split's \\x1c-\\x1f
    whitespace that C isspace misses."""
    cases = [
        "can't've", "don't've", "isn't's", "y'all'll", "does's'm",
        "-lrb-", "-LrB-", "-LRB-", "(",
        "a\x1cb", "a\x1db c\x1ed", "x\x1fy",
    ]
    for c in cases:
        assert ptb_tokenize_str(c) == tokenize_to_str(c), repr(c)


def test_corpus_accepts_generators():
    """tokenize_corpus's values are Iterable[str]: one-shot generators
    must tokenize completely (the native path once consumed them twice)."""
    caps = ["a man runs.", "café au lait", "don't stop"]
    out = tokenize_corpus({"v": (c for c in caps)})
    assert out["v"] == [tokenize_to_str(c) for c in caps]


def test_batch_matches_scalar():
    from cst_captioning_tpu.native import ptb_tokenize_batch

    caps = [c for c in GOLDEN]
    assert ptb_tokenize_batch(caps) == [ptb_tokenize_str(c) for c in caps]
    assert ptb_tokenize_batch([]) == []
    with pytest.raises(ValueError):
        ptb_tokenize_batch(["ok", "café"])


def test_corpus_runtime_native_fault_falls_back(monkeypatch):
    """A RUNTIME fault of the batched native call (not just startup
    unavailability) must degrade to the Python oracle and pin the native
    path off for the rest of the process (ADVICE r3)."""
    from cst_captioning_tpu.metrics import tokenizer as tk

    def boom(flat):
        raise RuntimeError("simulated C++ fault")

    monkeypatch.setattr(tk, "_native_batch", boom)
    caps = {"v": ["a man runs.", "don't stop"]}
    out = tk.tokenize_corpus(caps)
    assert out["v"] == [tokenize_to_str(c) for c in caps["v"]]
    # pinned off: later corpus calls go straight to Python, no re-fault
    assert tk._native_batch is False
    assert tk.tokenize_corpus(caps)["v"] == out["v"]
    tk._native_batch = None  # un-pin for other tests in this process


def test_batch_int32_capacity_guard(monkeypatch):
    """A blob whose output capacity would overflow the C ABI's int32
    offsets must fail loudly (callers fall back to Python), not wrap to
    negative offsets (ADVICE r3)."""
    import cst_captioning_tpu.native as nat

    class FakeStr(str):
        # pretend to be gigantic without allocating 2 GiB in CI
        def isascii(self):
            return True

        def encode(self, *a):
            return FakeBytes()

    class FakeBytes(bytes):
        def __len__(self):
            return 2**31 - 100

    monkeypatch.setattr(nat, "load_tokenizer_library", lambda: object())
    with pytest.raises(ValueError, match="int32"):
        nat.ptb_tokenize_batch([FakeStr("x")])
