"""Driver-artifact contract test: bare ``python bench.py`` must emit ONE
parseable JSON line with the schema the driver and the judge consume
(metric/value/vs_baseline/unit + both stages + the cst path label).

Runs the real CLI in a subprocess on the host CPU with tiny shapes — this
pins the artifact format, not performance."""

import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.e2e

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TINY = ["--batch_size", "2", "--seq_per_img", "2", "--seq_len", "8",
        "--vocab", "60", "--hidden", "16", "--steps", "2",
        # child_timeout below the subprocess timeout: if the bench wedges,
        # its own-session measurement child dies before this test's 900s
        # kill (which can only reach the direct bench.py driver process).
        "--platform", "cpu", "--child_timeout", "600"]


from conftest import CACHE_DIR


def run_bench(*extra):
    env = dict(os.environ)
    env["PYTHONPATH"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    # share the suite's persistent compile cache (conftest.py): repeat
    # bench-child compiles of identical tiny-shape HLO become loads
    env.setdefault("JAX_COMPILATION_CACHE_DIR", CACHE_DIR)
    # Output to temp FILES, not pipes: bench's measurement child runs in
    # its own session and would keep inherited pipes open past a timeout
    # kill, turning the post-timeout drain into a second unbounded hang
    # (the hazard bench.py's probe_backend docstring documents).
    import tempfile

    with tempfile.TemporaryFile("w+") as out, \
            tempfile.TemporaryFile("w+") as err:
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py"), *TINY, *extra],
            stdout=out, stderr=err, text=True, timeout=900, cwd=REPO,
            env=env,
        )
        out.seek(0)
        err.seek(0)
        stdout, stderr = out.read(), err.read()
    assert proc.returncode == 0, stderr[-2000:]
    lines = [l for l in stdout.splitlines() if l.strip()]
    assert len(lines) == 1, f"expected ONE JSON line, got: {stdout!r}"
    return json.loads(lines[0])


def test_probe_backend_backoff_and_structured_diagnostic():
    """ISSUE 9 satellite: the platform probe retries with backoff and, on
    total failure, returns a classified machine-auditable record (kind +
    per-attempt latencies) instead of a silent CPU fallback.  A 1ms
    timeout forces every attempt to time out (jax init takes ~1s)."""
    sys.path.insert(0, REPO)
    from bench import probe_backend

    plat, info = probe_backend(0.001, retries=1, backoff_s=0.01)
    assert plat is None
    assert info["kind"] == "probe_timeout"
    assert info["timeouts"] == 2 and len(info["attempts"]) == 2
    for rec in info["attempts"]:
        assert rec["outcome"] == "timeout" and rec["latency_s"] >= 0
    # the backoff is recorded on every non-final attempt
    assert info["attempts"][0]["backoff_s"] == pytest.approx(0.01)
    assert info["backoff_s"] == 0.01


def test_default_emits_both_stages():
    out = run_bench()
    assert out["metric"] == "min_xe_cst_captions_per_sec_per_chip"
    assert out["unit"] == "captions/s/chip"
    assert out["platform"] == "cpu"
    assert out["value"] > 0
    assert out["vs_baseline"] == pytest.approx(out["value"] / 5000.0,
                                               abs=0.0015)
    assert out["xe_captions_per_sec"] > 0
    assert out["cst_captions_per_sec"] > 0
    # the headline must be the worse stage, and labeled with its path
    assert out["value"] == min(out["xe_captions_per_sec"],
                               out["cst_captions_per_sec"])
    assert out["cst_path"] in ("device_fused", "host_pipeline",
                               "host_pipeline_fallback")
    assert out["cst_scorer"] in ("native", "python")
    # host-path numbers are always reported alongside
    assert out["cst_host_pipeline_captions_per_sec"] > 0
    assert out["cst_serial_captions_per_sec"] > 0
    # an explicitly-requested CPU run is not a fallback, and no probe ran
    assert out["cpu_fallback"] is False
    assert "probe" not in out
    # tuned-config provenance (ISSUE 6): conftest pins CST_TUNED_CONFIGS=''
    # so this suite run is hermetically un-tuned, and the artifact must say
    # so explicitly — a hand-flagged run can never read as a tuned one
    assert out["tuned"] is False
    assert out["tuning_record"] is None
    # the resolved rollout axes ride in the artifact
    assert out["cst_decode_kernel"] in ("reference", "pallas")
    assert out["cst_scan_unroll"] >= 1


def test_mfu_fields_in_artifact():
    """The artifact self-reports utilization: analytic model FLOPs per
    step, achieved TFLOP/s from the measured captions/s, and mfu_pct
    (None on the host CPU, where no TPU peak applies)."""
    out = run_bench()
    for stage in ("xe", "cst"):
        assert out[f"{stage}_model_tflops_per_step"] > 0
        assert out[f"{stage}_achieved_tflops"] > 0
        assert out[f"{stage}_mfu_pct"] is None  # platform=cpu


def test_analytic_flops_defaults_magnitude():
    """At the default MSR-VTT bench shapes the analytic XE step must land
    where independent arithmetic puts it (~0.9 TFLOP: 640 captions x 30
    steps x (12H^2 gates + H*V head) x 6) — a regression here means the
    FLOPs model drifted from the architecture."""
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.pop(0)
    import argparse

    ns = argparse.Namespace(batch_size=32, seq_per_img=20, seq_len=30,
                            vocab=8000, hidden=512)
    flops = bench.analytic_step_flops(ns)
    assert 0.7e12 < flops["xe"] < 1.1e12, flops
    assert flops["cst"] > flops["xe"]  # rollouts + grad > grad alone

    # mfu_fields: 640 captions/step at 30k caps/s -> ~47 steps/s.
    f = bench.mfu_fields(flops["xe"], 30000.0, 640, "TPU v5 lite")
    assert f["achieved_tflops"] == pytest.approx(
        flops["xe"] * 30000.0 / 640 / 1e12, rel=1e-3)
    assert f["mfu_pct"] == pytest.approx(
        100 * f["achieved_tflops"] / 197.0, rel=1e-3)
    assert bench.mfu_fields(flops["xe"], 100.0, 640, "weird")["mfu_pct"] is None
    assert bench.mfu_fields(flops["xe"], None, 640, "TPU v4") == {}


def test_stage_xe_isolates():
    out = run_bench("--stage", "xe")
    assert out["metric"] == "xe_captions_per_sec_per_chip"
    assert out["value"] > 0


def test_stage_data_feed_probe_record():
    """ISSUE 15: the data-plane feed probe enters the one-JSON-line
    contract with the worker/shard identity axes AND the same
    cpu_fallback/probe provenance fields the training stages carry —
    plus the single-worker twin + speedup record data_report gates on."""
    out = run_bench("--stage", "data", "--cache", "0",
                    "--loader_workers", "2", "--data_videos", "8",
                    "--data_batches", "4", "--data_read_ms", "1")
    assert out["metric"] == "data_feed_captions_per_sec"
    assert out["value"] > 0
    assert out["unit"] == "captions/s"
    assert out["loader_workers"] == 2
    assert out["data_shards"] == 0
    assert out["read_ms"] == 1.0
    # provenance like the training stages (satellite): explicit
    # cpu_fallback + tuned-config fields, never implied
    assert out["cpu_fallback"] is False
    assert "tuned" in out
    assert out["vs_baseline"] == out["vs_xe_rate"]
    assert out["single_worker_captions_per_sec"] > 0
    assert out["workers_speedup"] > 0


def _run_wedged(platform):
    """Run bench with a child_timeout far below what even tiny shapes need
    to import jax and compile -> the measurement child is ALWAYS killed
    (rc 124 inside); returns (rc, stdout, stderr)."""
    env = dict(os.environ)
    env["PYTHONPATH"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    env.setdefault("JAX_COMPILATION_CACHE_DIR", CACHE_DIR)
    import tempfile

    args = TINY[:-1] + ["3"]
    args[args.index("--platform") + 1] = platform
    with tempfile.TemporaryFile("w+") as out, \
            tempfile.TemporaryFile("w+") as err:
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py"), *args],
            stdout=out, stderr=err, text=True, timeout=300, cwd=REPO,
            env=env,
        )
        out.seek(0)
        err.seek(0)
        return proc.returncode, out.read(), err.read()


def test_total_wedge_still_emits_one_json_line():
    """Round-3 judge repro: tunnel wedged AND the CPU-fallback child
    outlives --child_timeout -> bench used to exit 124 with NO JSON.  Now
    every exit path prints exactly one parseable line: the killed child is
    detected and the parent emits the degraded artifact (platform="none",
    child_rc, last cached device result attached when one exists)."""
    rc, stdout, stderr = _run_wedged("auto")
    assert rc == 0, stderr[-2000:]  # auto = graceful degradation by design
    lines = [l for l in stdout.splitlines() if l.strip()]
    assert len(lines) == 1, f"expected ONE JSON line, got: {stdout!r}"
    res = json.loads(lines[0])
    assert res["metric"] == "min_xe_cst_captions_per_sec_per_chip"
    assert res["value"] is None
    assert res["platform"] == "none"
    assert res["child_rc"] == 124
    assert "timed out" in res["error"]
    # --platform auto probed the backend first: the attempt record (with
    # per-attempt latency + timeout count) must ride in the artifact even
    # on this degraded path
    assert res["probe"]["timeouts"] == 0
    attempts = res["probe"]["attempts"]
    assert attempts and attempts[-1]["outcome"] == "ok"
    assert attempts[-1]["platform"] == "cpu"
    assert attempts[-1]["latency_s"] > 0
    # the committed BENCH_TPU_CACHE.json holds the last device measurement;
    # when present for this metric it must ride along, self-describing
    cache_path = os.path.join(REPO, "BENCH_TPU_CACHE.json")
    if os.path.exists(cache_path):
        with open(cache_path) as f:
            entry = json.load(f).get("entries", {}).get(res["metric"])
        if entry is not None:
            assert res["last_tpu_result"]["result"]["platform"] != "cpu"
            assert "measured_at" in res["last_tpu_result"]


def test_wedge_with_required_platform_emits_but_fails():
    """An explicitly-required platform (--platform cpu/device) that
    measured nothing still prints its one JSON line but exits nonzero —
    a CI gate on rc must not record a passing benchmark that measured
    nothing (review finding, round 4)."""
    rc, stdout, stderr = _run_wedged("cpu")
    assert rc == 1, stderr[-2000:]
    lines = [l for l in stdout.splitlines() if l.strip()]
    assert len(lines) == 1, f"expected ONE JSON line, got: {stdout!r}"
    res = json.loads(lines[0])
    assert res["value"] is None
    assert res["platform"] == "none"
    assert res["child_rc"] == 124
