"""Request-lifecycle tracing + flight recorder (ISSUE 14).

Fast slice (tier-1, lock-sanitizer armed like the serving slices):
- :func:`attribute_request` units — components partition the total for
  plain decode, admit carve-out, retry->recovery, kill->requeue;
- :class:`LifecycleTracer` units — bounded ring + truncated-chain
  accounting, unknown-kind rejection, id-reuse chain splitting,
  multi-terminal detection, the replica view's intake suppression,
  blackbox providers (including a dying one) and the dump counter;
- the Chrome-trace async mirror (``SpanTracer.async_event`` phases) and
  trace_report's async/instant rendering + extended ``--json``;
- engine integration: a traced run's accounting/attribution reconcile
  with the engine's own latency bookkeeping; an UNTRACED engine's
  ``stats()`` keeps its historical shape; shed/drop terminals are
  accounted;
- the server wire ops: ``{"op": "stats"}`` (attribution included) and
  ``{"op": "dump"}`` (blackbox written; ``no_recorder`` when disarmed),
  plus ``responded`` terminals on the stream;
- the serving probe's ``lifecycle``/``attribution`` record + blackbox,
  and serve_report's two new exit-1 gates;
- doc pins (OBSERVABILITY.md section, SERVING.md wire ops + counters).

The subprocess CLI drill (scripts/serve.py demo with blackbox +
telemetry snapshot) is marked ``slow``; ``make serve-trace-demo`` is
its zero-setup twin.
"""

import io
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cst_captioning_tpu.data.vocab import Vocab
from cst_captioning_tpu.models import CaptionModel
from cst_captioning_tpu.serving.bench import serving_probe
from cst_captioning_tpu.serving.engine import ServingEngine
from cst_captioning_tpu.serving.server import CaptionServer
from cst_captioning_tpu.telemetry.lifecycle import (
    COMPONENTS,
    EVENT_KINDS,
    LifecycleTracer,
    attribute_request,
)
from cst_captioning_tpu.telemetry.registry import MetricsRegistry
from cst_captioning_tpu.telemetry.spans import SpanTracer

V, B, T, D, MAX_LEN = 12, 5, 3, 7, 8
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _lock_sanitizer(monkeypatch, tmp_path):
    """Sanitizer-armed (the PR 11 discipline): the new
    ``telemetry.lifecycle`` lock is re-validated against the declared
    LOCK_ORDER under every drill in this file."""
    from cst_captioning_tpu.analysis import locksan

    receipt = tmp_path / "locksan_violation.json"
    monkeypatch.setenv(locksan.ENV_FLAG, "1")
    monkeypatch.setenv(locksan.ENV_RECEIPT, str(receipt))
    before = len(locksan.violations())
    yield
    after = locksan.violations()
    assert len(after) == before, f"lock-order violations: {after[before:]}"


@pytest.fixture(scope="module")
def setup():
    model = CaptionModel(vocab_size=V, embed_size=16, hidden_size=16,
                         attn_size=16, dropout_rate=0.0)
    feats_np = np.random.default_rng(0).normal(
        size=(B, T, D)).astype(np.float32)
    variables = model.init(jax.random.PRNGKey(0), [jnp.asarray(feats_np)],
                           np.zeros((B, MAX_LEN), np.int32))
    params = {**variables["params"]}
    params["logit"] = {**params["logit"]}
    params["logit"]["bias"] = params["logit"]["bias"].at[0].add(0.4)
    return model, {"params": params}, feats_np


def _ev(ts, kind, **attrs):
    return {"ts": float(ts), "id": 0, "kind": kind, **attrs}


def _total(comp):
    return sum(comp[c] for c in COMPONENTS)


# -- attribution units -----------------------------------------------------


def test_attribute_plain_decode_partitions_total():
    comp = attribute_request([
        _ev(0, "received"), _ev(0, "queued"),
        _ev(5, "admitted", admit_ms=1000.0),
        _ev(7, "decode_chunk"), _ev(9, "decode_chunk"),
        _ev(9, "completed", latency_ms=9000.0),
    ])
    assert comp["total"] == pytest.approx(9.0)
    assert _total(comp) == pytest.approx(comp["total"])
    assert comp["queue_wait"] == pytest.approx(4.0)   # 5s wait - 1s admit
    assert comp["admit"] == pytest.approx(1.0)
    assert comp["decode"] == pytest.approx(4.0)
    assert comp["recovery"] == 0.0 and comp["requeue"] == 0.0


def test_attribute_kill_requeue_window():
    comp = attribute_request([
        _ev(0, "received"), _ev(0, "queued"), _ev(1, "admitted"),
        _ev(2, "decode_chunk"), _ev(3, "killed"), _ev(4, "requeued"),
        _ev(4, "queued"), _ev(6, "admitted"), _ev(7, "decode_chunk"),
        _ev(8, "completed"),
    ])
    # killed(3) -> readmission(6) is the requeue window — the fleet
    # restart's cost attributed, never hidden in queue_wait.
    assert comp["requeue"] == pytest.approx(3.0)
    assert comp["decode"] == pytest.approx(4.0)
    assert comp["queue_wait"] == pytest.approx(1.0)
    assert _total(comp) == pytest.approx(comp["total"]) == pytest.approx(8.0)


def test_attribute_retry_recovery():
    comp = attribute_request([
        _ev(0, "received"), _ev(0, "queued"), _ev(1, "admitted"),
        _ev(2, "decode_chunk"), _ev(4, "retry"), _ev(6, "decode_chunk"),
        _ev(6, "completed"),
    ])
    # The failed dispatch (2->4) and its re-run (4->6) are both
    # recovery; only the clean first chunk is decode.
    assert comp["recovery"] == pytest.approx(4.0)
    assert comp["decode"] == pytest.approx(1.0)
    assert _total(comp) == pytest.approx(comp["total"])


def test_attribute_incomplete_chains_are_none():
    assert attribute_request([_ev(1, "queued"), _ev(2, "completed")]) is None
    assert attribute_request([_ev(0, "received"), _ev(1, "queued")]) is None


# -- tracer units ----------------------------------------------------------


def test_ring_bounded_and_truncated_chains_excluded():
    lc = LifecycleTracer(max_events=16, clock=lambda: 0.0)
    for i in range(20):
        lc.emit("received", i, ts=float(i))
        lc.emit("completed", i, ts=float(i), latency_ms=0.0)
    assert len(lc.events()) == 16
    assert lc.emitted() == 40
    acc = lc.accounting()
    # Chains whose "received" rotated out are truncated, not counted as
    # broken — a bounded recorder only vouches for the window it kept.
    assert acc["terminal_ok"]
    assert acc["submitted"] == 8 and acc["truncated"] == 0


def test_emit_rejects_unknown_kind():
    with pytest.raises(ValueError, match="unknown lifecycle event kind"):
        LifecycleTracer().emit("warp", 1)


def test_id_reuse_splits_chains():
    lc = LifecycleTracer(clock=lambda: 0.0)
    for ts in (0.0, 1.0):
        lc.emit("received", "a", ts=ts)
        lc.emit("completed", "a", ts=ts + 0.5, latency_ms=500.0)
    acc = lc.accounting()
    assert acc["submitted"] == 2 and acc["terminal_ok"]
    assert lc.attribution_report()["requests"] == 2


def test_unterminated_and_multi_terminal_flagged():
    lc = LifecycleTracer(clock=lambda: 0.0)
    lc.emit("received", "x")
    lc.emit("received", "y")
    lc.emit("completed", "y", latency_ms=0.0)
    lc.emit("completed", "y", latency_ms=0.0)
    acc = lc.accounting()
    assert not acc["terminal_ok"]
    assert acc["unterminated"] == 1 and acc["multi_terminal"] == 1
    assert set(acc["bad_ids"]) == {"x", "y"}


def test_replica_view_drops_intake_and_labels():
    lc = LifecycleTracer(clock=lambda: 0.0)
    view = lc.for_replica(3)
    view.emit("received", 1)     # router-owned: dropped by the view
    view.emit("shed", 1)         # ditto
    view.emit("queued", 1)
    evs = lc.events()
    assert [e["kind"] for e in evs] == ["queued"]
    assert evs[0]["replica"] == 3


def test_blackbox_providers_and_dump_counter(tmp_path):
    registry = MetricsRegistry()
    lc = LifecycleTracer(registry=registry, clock=lambda: 0.0)
    lc.emit("received", 1)
    lc.emit("completed", 1, latency_ms=0.0)
    lc.attach(good=lambda: {"x": 1}, bad=lambda: 1 / 0)
    path = tmp_path / "blackbox.json"
    doc = lc.dump(str(path), reason="drill")
    on_disk = json.loads(path.read_text())
    assert on_disk["schema"] == doc["schema"] == 1
    assert on_disk["reason"] == "drill"
    assert on_disk["good"] == {"x": 1}
    # A dying provider is reported, never mutes the rest of the dump.
    assert "provider_error" in on_disk["bad"]
    assert on_disk["accounting"]["terminal_ok"]
    assert registry.counter("lifecycle_dumps") == 1
    assert registry.counter("lifecycle_events") == 2


def test_async_mirror_phases(tmp_path):
    tracer = SpanTracer(str(tmp_path))
    lc = LifecycleTracer(tracer=tracer, clock=lambda: 0.0)
    lc.emit("received", 5)
    lc.emit("queued", 5)
    lc.emit("completed", 5, latency_ms=0.0)
    tracer.close()
    files = [f for f in os.listdir(tmp_path) if f.startswith("trace_")]
    doc = json.load(open(tmp_path / files[0]))
    evs = [e for e in doc["traceEvents"] if e.get("cat") == "request"]
    phases = {e["ph"]: e for e in evs}
    # b/e pair on the constant track name (Chrome pairing rule), the
    # step as an instant named by its kind; all share the request id.
    assert phases["b"]["name"] == phases["e"]["name"] == "request"
    assert phases["n"]["name"] == "queued"
    assert {e["id"] for e in evs} == {"5"}
    with pytest.raises(ValueError):
        tracer.async_event("x", "request", 5)


# -- engine integration ----------------------------------------------------


def test_engine_traced_run_reconciles(setup):
    model, variables, feats = setup
    registry = MetricsRegistry()
    lc = LifecycleTracer(registry=registry)
    eng = ServingEngine(model, variables, [(T, D)], max_len=MAX_LEN,
                        decode_chunk=2, bucket_sizes=(1, 2),
                        queue_limit=0, registry=registry, lifecycle=lc)
    for i in range(3):
        eng.submit(i, [feats[i]])
    comps = eng.run_until_idle()
    assert len(comps) == 3
    acc = lc.accounting()
    assert acc["terminal_ok"] and acc["submitted"] == 3
    rep = lc.attribution_report()
    assert rep["requests"] == 3 and rep["reconcile_ok"]
    # Components sum to the engine's own measured latency (tolerance is
    # for float noise only — same clock, same timestamps).
    assert rep["max_residual_ms"] < 1.0
    st = eng.stats()
    assert st["attribution"]["reconcile_ok"]
    assert registry.counter("lifecycle_events") == lc.emitted()


def test_untraced_engine_keeps_historical_stats_shape(setup):
    model, variables, feats = setup
    eng = ServingEngine(model, variables, [(T, D)], max_len=MAX_LEN,
                        decode_chunk=2, bucket_sizes=(1,), queue_limit=0)
    eng.submit(0, [feats[0]])
    eng.run_until_idle()
    assert "attribution" not in eng.stats()


def test_shed_and_drop_terminals_accounted(setup):
    model, variables, feats = setup
    lc = LifecycleTracer()
    eng = ServingEngine(model, variables, [(T, D)], max_len=MAX_LEN,
                        decode_chunk=2, bucket_sizes=(1,),
                        queue_limit=1, lifecycle=lc)
    assert eng.submit(0, [feats[0]])
    assert not eng.submit(1, [feats[1]])      # bounded queue: shed
    eng.run_until_idle()
    acc = lc.accounting()
    assert acc["terminal_ok"] and acc["submitted"] == 2
    kinds = {e["id"]: e["kind"] for e in lc.events()
             if e["kind"] in ("completed", "shed")}
    assert kinds == {0: "completed", 1: "shed"}


# -- the server wire ops ---------------------------------------------------


def _server(setup, lc, out, tmp_path, registry=None):
    model, variables, feats = setup
    vocab = Vocab({i: f"w{i}" for i in range(1, V)})
    engine = ServingEngine(model, variables, [(T, D)], max_len=MAX_LEN,
                           decode_chunk=2, bucket_sizes=(2,),
                           queue_limit=0, lifecycle=lc, registry=registry)
    return CaptionServer(engine, vocab, lambda vid: [feats[int(vid)]],
                         out=out, lifecycle=lc, registry=registry,
                         blackbox_path=str(tmp_path / "blackbox.json"))


def test_server_stats_and_dump_ops(setup, tmp_path):
    registry = MetricsRegistry()
    lc = LifecycleTracer(registry=registry)
    out = io.StringIO()
    server = _server(setup, lc, out, tmp_path, registry)
    rc = server.run_stdin([json.dumps({"id": 1, "video_id": "1"}),
                           json.dumps({"op": "stats"}),
                           json.dumps({"op": "dump"})])
    assert rc == 0
    replies = [json.loads(l) for l in out.getvalue().splitlines()]
    stats = next(r for r in replies if r.get("op") == "stats")
    assert "attribution" in stats and "queue_depth" in stats
    dump = next(r for r in replies if r.get("op") == "dump")
    assert dump["path"] == str(tmp_path / "blackbox.json")
    assert json.loads((tmp_path / "blackbox.json").read_text())["schema"] == 1
    assert registry.counter("serve_stats_queries") == 1
    assert registry.counter("serve_dump_queries") == 1
    # The full story ends in the front end's "responded" marker.
    chain = [e["kind"] for e in lc.events() if e["id"] == (1, "1")]
    assert chain[0] == "received" and chain[-1] == "responded"
    assert "completed" in chain
    assert lc.accounting()["terminal_ok"]


def test_server_dump_without_recorder_errors(setup, tmp_path):
    out = io.StringIO()
    server = _server(setup, None, out, tmp_path)
    rc = server.run_stdin([json.dumps({"op": "dump"})])
    assert rc == 0
    reply = json.loads(out.getvalue().splitlines()[0])
    assert reply["error"] == "no_recorder"


# -- probe + serve_report gates --------------------------------------------


def test_probe_lifecycle_record_and_blackbox(setup, tmp_path):
    model, variables, _ = setup
    bb = tmp_path / "bb.json"
    rec = serving_probe(model, variables, [(T, D)], num_requests=6,
                        rate_hz=500.0, max_len=MAX_LEN, decode_chunk=2,
                        bucket_sizes=(1, 2), seed=3, lifecycle=True,
                        blackbox_path=str(bb))
    assert rec["lifecycle"]["enabled"] and rec["lifecycle"]["terminal_ok"]
    assert rec["lifecycle"]["submitted"] == 6
    assert rec["attribution"]["reconcile_ok"]
    comps = rec["attribution"]["components"]
    assert set(comps) == set(COMPONENTS)
    assert comps["decode"]["p50_ms"] > 0
    doc = json.loads(bb.read_text())
    assert doc["reason"] == "probe_end"
    assert doc["accounting"]["terminal_ok"]
    assert doc["program_cache"]["builds"] > 0


def test_untraced_probe_record_shape(setup):
    model, variables, _ = setup
    rec = serving_probe(model, variables, [(T, D)], num_requests=3,
                        rate_hz=500.0, max_len=MAX_LEN, decode_chunk=2,
                        bucket_sizes=(1,), seed=3)
    assert rec["lifecycle"] == {"enabled": False}
    assert "attribution" not in rec


def _run_report(record, tmp_path):
    path = tmp_path / "serving.json"
    path.write_text(json.dumps(record) + "\n")
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "serve_report.py"),
         "--file", str(path)], capture_output=True, text=True, cwd=REPO)


def _base_record(**over):
    rec = {"metric": "serve_captions_per_sec_per_chip", "value": 10.0,
           "completed": 4, "num_requests": 4, "shed": 0,
           "recompiles_after_warmup": 0, "rebuild_recompiles": 0}
    rec.update(over)
    return rec


def test_serve_report_gates_on_lifecycle_accounting(tmp_path):
    res = _run_report(_base_record(
        lifecycle={"enabled": True, "terminal_ok": False,
                   "submitted": 4, "unterminated": 1,
                   "multi_terminal": 0}), tmp_path)
    assert res.returncode == 1
    assert "lifecycle accounting broken" in res.stderr


def test_serve_report_gates_on_attribution_reconcile(tmp_path):
    res = _run_report(_base_record(
        lifecycle={"enabled": True, "terminal_ok": True, "submitted": 4},
        attribution={"reconcile_ok": False, "reconciled": 4,
                     "max_residual_ms": 999.0, "tolerance_ms": 50.0,
                     "components": {}}), tmp_path)
    assert res.returncode == 1
    assert "attribution does not reconcile" in res.stderr


def test_serve_report_renders_attribution_rows(tmp_path):
    comps = {c: {"p50_ms": 1.0, "p99_ms": 2.0, "sum_ms": 4.0}
             for c in COMPONENTS}
    res = _run_report(_base_record(
        lifecycle={"enabled": True, "terminal_ok": True, "submitted": 4,
                   "unterminated": 0, "multi_terminal": 0, "events": 30,
                   "retained": 30, "blackbox": "/tmp/bb.json"},
        attribution={"reconcile_ok": True, "reconciled": 4,
                     "max_residual_ms": 0.01, "tolerance_ms": 50.0,
                     "components": comps,
                     "per_replica": {"0": comps}}), tmp_path)
    assert res.returncode == 0
    assert "attr decode p50 / p99" in res.stdout
    assert "lifecycle accounting" in res.stdout
    assert "replica 0 attr" in res.stdout


def test_serve_report_old_records_render_unchanged(tmp_path):
    # A pre-ISSUE-14 record (no lifecycle/attribution keys) must render
    # exactly as before, exit 0, and show none of the new rows.
    res = _run_report(_base_record(), tmp_path)
    assert res.returncode == 0
    assert "attr " not in res.stdout and "lifecycle" not in res.stdout


# -- trace_report: instant/async rendering ---------------------------------


def test_trace_report_renders_async_and_instants(tmp_path):
    trace = {"traceEvents": [
        {"name": "serve.admit", "ph": "X", "ts": 0.0, "dur": 500.0,
         "pid": 1, "tid": 1},
        {"name": "fault", "ph": "i", "ts": 10.0, "pid": 1, "tid": 1},
        {"name": "request", "ph": "b", "cat": "request", "id": "7",
         "ts": 100.0, "pid": 1, "tid": 1},
        {"name": "queued", "ph": "n", "cat": "request", "id": "7",
         "ts": 150.0, "pid": 1, "tid": 1},
        {"name": "request", "ph": "e", "cat": "request", "id": "7",
         "ts": 1100.0, "pid": 1, "tid": 1},
        {"name": "request", "ph": "b", "cat": "request", "id": "8",
         "ts": 200.0, "pid": 1, "tid": 1},
    ]}
    (tmp_path / "trace_1r0.json").write_text(json.dumps(trace))
    out_json = tmp_path / "summary.json"
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "trace_report.py"),
         "--trace_dir", str(tmp_path), "--json", str(out_json)],
        capture_output=True, text=True, cwd=REPO)
    assert res.returncode == 0
    assert "async tracks" in res.stdout
    assert "instant markers" in res.stdout
    assert "1 track(s) still open" in res.stdout
    doc = json.loads(out_json.read_text())
    track = doc["async_tracks"][0]
    assert track["span"] == "request" and track["count"] == 1
    assert track["total_ms"] == pytest.approx(1.0)
    assert doc["async_steps"] == [{"name": "queued", "count": 1}]
    assert doc["instants"] == [{"name": "fault", "count": 1}]
    assert doc["async_meta"]["open_tracks"] == 1


# -- doc pins --------------------------------------------------------------


def test_observability_doc_pins_lifecycle():
    with open(os.path.join(REPO, "OBSERVABILITY.md")) as f:
        text = f.read()
    assert "Request lifecycle & flight recorder" in text
    for kind in EVENT_KINDS:
        assert f"`{kind}`" in text, f"OBSERVABILITY.md missing {kind}"
    for comp in COMPONENTS:
        assert comp in text, f"OBSERVABILITY.md missing component {comp}"


def test_serving_doc_pins_wire_ops_and_counters():
    with open(os.path.join(REPO, "SERVING.md")) as f:
        text = f.read()
    for token in ('{"op": "stats"}', '{"op": "dump"}', "blackbox",
                  "lifecycle_events", "lifecycle_dumps",
                  "serve_stats_queries", "serve_dump_queries",
                  '"schema": 1'):
        assert token in text, f"SERVING.md missing {token!r}"


# -- the CLI drill (slow) --------------------------------------------------


@pytest.mark.slow
def test_cli_demo_blackbox_and_exit_snapshot(tmp_path):
    """scripts/serve.py demo mode: the {"op": "dump"} wire op writes the
    blackbox, and exit leaves the telemetry.json snapshot (the train.py
    artifact discipline on the serving plane)."""
    bb = tmp_path / "blackbox.json"
    snap = tmp_path / "telemetry.json"
    lines = "\n".join([json.dumps({"id": 1, "video_id": "v0"}),
                       json.dumps({"op": "dump"})]) + "\n"
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "serve.py"),
         "--serve_demo", "1", "--beam_size", "1",
         "--serve_blackbox", str(bb),
         "--serve_telemetry_file", str(snap)],
        input=lines, capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, timeout=240)
    assert res.returncode == 0, res.stderr
    doc = json.loads(bb.read_text())
    assert doc["schema"] == 1 and doc["reason"] == "wire_dump"
    assert doc["health"]["status"] == "ok"
    assert doc["program_cache"]["builds"] > 0
    snap_doc = json.loads(snap.read_text())
    assert snap_doc["schema"] == 2
    assert snap_doc["counters"]["lifecycle_dumps"] == 1
    assert snap_doc["counters"]["serve_dump_queries"] == 1
