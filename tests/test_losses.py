import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cst_captioning_tpu.ops.losses import (
    cross_entropy_loss,
    reward_loss,
    sequence_mask,
    token_logprobs,
)


class TestSequenceMask:
    def test_covers_words_and_first_eos(self):
        targets = jnp.array([[3, 5, 0, 0], [1, 2, 3, 4], [0, 0, 0, 0]])
        mask = sequence_mask(targets)
        np.testing.assert_array_equal(
            mask, [[1, 1, 1, 0], [1, 1, 1, 1], [1, 0, 0, 0]]
        )


class TestCrossEntropy:
    def test_perfect_prediction_near_zero(self):
        targets = jnp.array([[2, 1, 0]])
        logits = jnp.full((1, 3, 4), -1e9).at[0, 0, 2].set(0.0)
        logits = logits.at[0, 1, 1].set(0.0).at[0, 2, 0].set(0.0)
        assert cross_entropy_loss(logits, targets) < 1e-3

    def test_uniform_prediction_log_vocab(self):
        targets = jnp.array([[2, 1, 0]])
        logits = jnp.zeros((1, 3, 4))
        assert cross_entropy_loss(logits, targets) == pytest.approx(np.log(4), rel=1e-5)

    def test_padding_excluded(self):
        targets = jnp.array([[2, 0, 0, 0]])
        good = jnp.zeros((1, 4, 4))
        # garbage at padded positions must not change the loss
        bad = good.at[0, 2:, :].set(jnp.array([100.0, -50.0, 3.0, 7.0]))
        assert cross_entropy_loss(good, targets) == pytest.approx(
            float(cross_entropy_loss(bad, targets)), rel=1e-6
        )

    def test_weights_scale_per_caption(self):
        targets = jnp.array([[2, 0], [3, 0]])
        logits = jnp.zeros((2, 2, 4))
        base = cross_entropy_loss(logits, targets)
        # doubling one caption's weight moves the loss up (same mask norm)
        w = cross_entropy_loss(logits, targets, weights=jnp.array([2.0, 1.0]))
        assert w == pytest.approx(float(base) * 1.5, rel=1e-5)

    def test_gradient_flows(self):
        targets = jnp.array([[2, 1, 0]])
        g = jax.grad(lambda l: cross_entropy_loss(l, targets))(jnp.zeros((1, 3, 4)))
        assert np.isfinite(np.asarray(g)).all()
        assert np.abs(np.asarray(g)).sum() > 0


class TestRewardLoss:
    def test_positive_advantage_pushes_up_logprob(self):
        sampled = jnp.array([[2, 3, 0]])
        adv = jnp.array([1.0])

        def loss_of(lp_scale):
            lp = jnp.full((1, 3), lp_scale)
            return reward_loss(lp, sampled, adv)

        # higher logprob of the sampled tokens -> lower loss
        assert loss_of(-0.1) < loss_of(-2.0)

    def test_zero_advantage_zero_loss(self):
        lp = jnp.full((1, 3), -1.0)
        sampled = jnp.array([[2, 3, 0]])
        assert reward_loss(lp, sampled, jnp.array([0.0])) == 0.0

    def test_advantage_gets_no_gradient(self):
        sampled = jnp.array([[2, 0]])

        def f(adv):
            return reward_loss(jnp.full((1, 2), -1.0), sampled, adv)

        g = jax.grad(f)(jnp.array([1.5]))
        np.testing.assert_array_equal(np.asarray(g), [0.0])

    def test_mask_limits_to_sampled_length(self):
        sampled = jnp.array([[2, 0, 0, 0]])
        lp_short = jnp.array([[-1.0, -1.0, 0.0, 0.0]])
        lp_junk = jnp.array([[-1.0, -1.0, -99.0, -42.0]])
        a = reward_loss(lp_short, sampled, jnp.array([1.0]))
        b = reward_loss(lp_junk, sampled, jnp.array([1.0]))
        assert a == pytest.approx(float(b))


class TestTokenLogprobs:
    def test_matches_manual(self):
        logits = jnp.array([[[1.0, 2.0, 0.5]]])
        targets = jnp.array([[1]])
        expected = jax.nn.log_softmax(logits[0, 0])[1]
        assert token_logprobs(logits, targets)[0, 0] == pytest.approx(float(expected))
