"""Low-precision decode variant (ISSUE 12): --decode_kernel bf16.

Fast slice (tier-1):
- routing: make_decode_step serves the bf16 step for eligible models and
  falls back (warn-once) to the bit-exact reference cell for ineligible
  ones — the pallas fallback discipline;
- boundary contract: fp32 carry in/out, fp32 logits, logits close to the
  fp32 path (the variant changes precision, not formulation);
- serving parity PER KERNEL: the engine under decode_kernel=bf16 serves
  captions bit-identical to the offline bf16 decode (the engine changes
  scheduling, never captions — for every kernel);
- the parity gate: within the declared CIDEr-delta bound -> "bf16",
  outside -> "reference" pinned as the fallback;
- the sweep grid carries the bf16 axis so TUNED_CONFIGS.json can record
  a per-platform winner;
- program/result-cache identity: bf16 and reference engines never share
  compiled programs or cached captions.

The end-to-end CLI gate (scripts/bf16_parity.py --synthetic) is marked
slow; `make bf16-parity` runs it.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cst_captioning_tpu.models import CaptionModel
from cst_captioning_tpu.ops.bf16_decode import (
    DEFAULT_CIDER_DELTA_BOUND,
    bf16_decode_supported,
    make_bf16_decode_step,
    parity_gate,
)
from cst_captioning_tpu.ops.sampling import make_decode_step, sample_captions
from cst_captioning_tpu.serving.engine import ServingEngine

V, B, T, D, MAX_LEN = 12, 5, 3, 7, 8
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def build(decode_kernel="reference", dtype=jnp.float32):
    return CaptionModel(vocab_size=V, embed_size=16, hidden_size=16,
                        attn_size=16, dropout_rate=0.0,
                        decode_kernel=decode_kernel, dtype=dtype)


@pytest.fixture(scope="module")
def setup():
    model = build()
    feats_np = np.random.default_rng(0).normal(
        size=(B, T, D)).astype(np.float32) * 2.0
    variables = model.init(jax.random.PRNGKey(0), [jnp.asarray(feats_np)],
                           np.zeros((B, MAX_LEN), np.int32))
    return model, variables, feats_np


def encodings(model, variables, feats_np):
    memory, proj_mem, pooled = model.apply(
        variables, [jnp.asarray(feats_np)], method="encode")
    carry = model.apply(variables, pooled, MAX_LEN, method="init_carry")
    return memory, proj_mem, pooled, carry


# -- eligibility + routing -------------------------------------------------


def test_supported_gate():
    ok, _ = bf16_decode_supported(build())
    assert ok
    ok, reason = bf16_decode_supported(build(dtype=jnp.bfloat16))
    assert not ok and "already bfloat16" in reason


def test_ineligible_model_falls_back_bit_exact(setup, caplog):
    """An already-bf16 model under decode_kernel=bf16 routes to the
    reference cell (bit-identical decode) with ONE warning."""
    _, variables, feats_np = setup
    import cst_captioning_tpu.ops.bf16_decode as mod

    mod._warned_fallback.clear()
    kw = dict(rng=jax.random.PRNGKey(0), max_len=MAX_LEN, greedy=True)
    with caplog.at_level("WARNING"):
        got, _ = sample_captions(build("bf16", jnp.bfloat16), variables,
                                 [jnp.asarray(feats_np)], kw["rng"],
                                 MAX_LEN, greedy=True)
        ref, _ = sample_captions(build("reference", jnp.bfloat16),
                                 variables, [jnp.asarray(feats_np)],
                                 kw["rng"], MAX_LEN, greedy=True)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
    warns = [r for r in caplog.records
             if "falling back to the reference decode cell" in r.message]
    assert len(warns) == 1                      # warn-once per reason


def test_step_boundary_contract(setup):
    """fp32 carry in -> fp32 carry out, fp32 logits, values close to the
    fp32 reference step (precision, not formulation, changed)."""
    model, variables, feats_np = setup
    memory, proj_mem, pooled, carry = encodings(model, variables, feats_np)
    ref_step = make_decode_step(model, variables, memory, proj_mem, pooled)
    bf_step = make_bf16_decode_step(model, variables, memory, proj_mem,
                                    pooled)
    tok = jnp.zeros((B,), jnp.int32)
    (c_ref, l_ref), (c_bf, l_bf) = ref_step(carry, tok), bf_step(carry, tok)
    assert l_bf.dtype == jnp.float32
    for leaf in jax.tree_util.tree_leaves(c_bf):
        assert leaf.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(l_bf), np.asarray(l_ref),
                               atol=0.15, rtol=0.1)


def test_routing_via_model_attr(setup):
    """make_decode_step keys off model.decode_kernel — the same routing
    the samplers, beam, eval, and the serving engine all share."""
    model, variables, feats_np = setup
    memory, proj_mem, pooled, carry = encodings(model, variables, feats_np)
    step = make_decode_step(build("bf16"), variables, memory, proj_mem,
                            pooled)
    twin = make_bf16_decode_step(model, variables, memory, proj_mem,
                                 pooled)
    tok = jnp.zeros((B,), jnp.int32)
    np.testing.assert_array_equal(np.asarray(step(carry, tok)[1]),
                                  np.asarray(twin(carry, tok)[1]))


# -- serving parity under the bf16 kernel ----------------------------------


def test_serving_engine_bf16_bit_identical_to_offline(setup):
    _, variables, feats_np = setup
    model = build("bf16")
    offline, _ = sample_captions(model, variables, [jnp.asarray(feats_np)],
                                 jax.random.PRNGKey(0), MAX_LEN,
                                 greedy=True)
    engine = ServingEngine(model, variables, [(T, D)], max_len=MAX_LEN,
                           decode_chunk=2, bucket_sizes=(2,), queue_limit=0)
    for i in range(B):
        engine.submit(i, [feats_np[i]])
    got = {c.request_id: c.tokens for c in engine.run_until_idle()}
    np.testing.assert_array_equal(np.stack([got[i] for i in range(B)]),
                                  np.asarray(offline))


def test_program_and_result_cache_identity_split(setup):
    """bf16 and reference engines share neither compiled programs nor
    cached captions: decode_kernel is part of both identities."""
    from cst_captioning_tpu.serving.cache import ResultCache

    _, variables, feats_np = setup
    cache = ResultCache(8)
    e_ref = ServingEngine(build("reference"), variables, [(T, D)],
                          max_len=MAX_LEN, decode_chunk=2,
                          bucket_sizes=(1,), queue_limit=0,
                          result_cache=cache)
    assert e_ref._config_key(1, "programs") != \
        ServingEngine(build("bf16"), variables, [(T, D)],
                      max_len=MAX_LEN, decode_chunk=2, bucket_sizes=(1,),
                      queue_limit=0)._config_key(1, "programs")
    e_ref.submit(0, [feats_np[0]])
    e_ref.run_until_idle()
    e_bf = ServingEngine(build("bf16"), variables, [(T, D)],
                         max_len=MAX_LEN, decode_chunk=2, bucket_sizes=(1,),
                         queue_limit=0, result_cache=cache)
    e_bf.submit(0, [feats_np[0]])
    e_bf.run_until_idle()
    s = e_bf.stats()
    assert s["cache_hits"] == 0 and s["cache_misses"] == 1


def test_transformer_decoder_bf16_step(setup):
    """The bf16 variant serves the transformer decoder too: its int32
    (token-buffer, position) carry leaves keep their dtype through the
    boundary casts (regression: a blind astype crashed
    dynamic_update_slice), and the step output tracks the fp32 path."""
    _, __, feats_np = setup
    kw = dict(vocab_size=V, embed_size=16, hidden_size=16, attn_size=16,
              dropout_rate=0.0, decoder_type="transformer", num_heads=2,
              num_tx_layers=1, tx_max_len=MAX_LEN)
    ref = CaptionModel(**kw)
    variables = ref.init(jax.random.PRNGKey(0), [jnp.asarray(feats_np)],
                         np.zeros((B, MAX_LEN), np.int32))
    out_ref, _ = sample_captions(ref, variables, [jnp.asarray(feats_np)],
                                 jax.random.PRNGKey(0), MAX_LEN,
                                 greedy=True)
    bf = CaptionModel(**kw, decode_kernel="bf16")
    out_bf, _ = sample_captions(bf, variables, [jnp.asarray(feats_np)],
                                jax.random.PRNGKey(0), MAX_LEN, greedy=True)
    assert out_bf.shape == out_ref.shape
    # precision, not formulation: the tiny model's margins are wide
    # enough that the decodes agree here (not a general guarantee —
    # that is what the parity gate is for)
    assert float((np.asarray(out_bf) == np.asarray(out_ref)).mean()) > 0.9


# -- the parity gate -------------------------------------------------------


def test_parity_gate_decision_rule():
    ok = parity_gate(3.10, 3.095)
    assert ok["within_bound"] and ok["kernel_recommendation"] == "bf16"
    assert ok["delta"] == pytest.approx(-0.005)
    assert ok["bound"] == DEFAULT_CIDER_DELTA_BOUND
    bad = parity_gate(3.10, 3.00)              # -0.10 CIDEr: outside
    assert not bad["within_bound"]
    assert bad["kernel_recommendation"] == "reference"   # pinned fallback
    # The bound is two-sided: a suspicious IMPROVEMENT is flagged too
    # (a low-precision decode that scores better is measuring noise).
    assert not parity_gate(3.10, 3.20)["within_bound"]


def test_opts_and_bench_accept_bf16():
    from cst_captioning_tpu.opts import parse_opts

    assert parse_opts(["--decode_kernel", "bf16"]).decode_kernel == "bf16"


def test_sweep_grid_carries_bf16_axis():
    from cst_captioning_tpu.tuning.sweep import base_namespace, sweep_space

    points = sweep_space(base_namespace())
    kernels = {p["decode_kernel"] for p in points}
    assert kernels == {"reference", "pallas", "bf16"}
    # Deterministic point order: bf16 points sit in the fused branch.
    bf16_pts = [p for p in points if p["decode_kernel"] == "bf16"]
    assert len(bf16_pts) == 8                  # 4 chunks x 2 unrolls
    assert all(p["device_rewards"] == 1 for p in bf16_pts)


# -- the CLI gate (make bf16-parity) ---------------------------------------


@pytest.mark.slow
def test_bf16_parity_cli_synthetic(tmp_path):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "bf16_parity.py"),
         "--synthetic", "1", "--max_length", "8", "--beam_size", "2",
         "--loglevel", "WARNING"],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=600)
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = json.loads(proc.stdout.splitlines()[-1])
    assert out["supported"] and "delta" in out
    assert out["kernel_recommendation"] in ("bf16", "reference")
    # The pinned-fallback path: an impossible bound forces exit 1 with
    # the bit-exact recommendation.
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "bf16_parity.py"),
         "--synthetic", "1", "--max_length", "8", "--beam_size", "2",
         "--cider_delta_bound", "-1", "--loglevel", "WARNING"],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=600)
    assert proc.returncode == 1
    out = json.loads(proc.stdout.splitlines()[-1])
    assert out["kernel_recommendation"] == "reference"
    assert "reference" in proc.stderr
