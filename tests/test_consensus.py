import numpy as np
import pytest

from cst_captioning_tpu.metrics.consensus import (
    compute_consensus_scores,
    load_consensus,
    normalize_weights,
    save_consensus,
)

REFS = {
    "v1": [
        "a man is cooking food",
        "a man cooks food in a kitchen",
        "a man is cooking",
        "purple elephants juggle quantum physics",   # outlier caption
    ],
    "v2": ["a dog runs", "the dog is running"],
}


def test_outlier_gets_lowest_consensus():
    scores = compute_consensus_scores(REFS)
    v1 = scores["v1"]
    assert v1.shape == (4,)
    assert np.argmin(v1) == 3          # the outlier
    assert v1[3] < v1[:3].min()


def test_consensus_captions_score_positive():
    scores = compute_consensus_scores(REFS)
    assert (scores["v1"][:3] > 0).all()


def test_normalize_weights_mean_one():
    scores = compute_consensus_scores(REFS)
    weights = normalize_weights(scores, temperature=1.0)
    for vid, w in weights.items():
        assert w.mean() == pytest.approx(1.0)
        assert (w >= 0).all()
    # Outlier weight below average, consensus captions above the outlier.
    assert weights["v1"][3] < 1.0
    assert weights["v1"][3] == weights["v1"].min()


def test_pickle_roundtrip(tmp_path):
    scores = compute_consensus_scores(REFS)
    p = str(tmp_path / "consensus.pkl")
    save_consensus(p, scores)
    loaded = load_consensus(p)
    for k in scores:
        np.testing.assert_allclose(loaded[k], scores[k])


def test_single_caption_video():
    scores = compute_consensus_scores({"v": ["only one caption"]})
    assert scores["v"].shape == (1,) and scores["v"][0] == 0.0
