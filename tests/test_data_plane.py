"""Sharded multi-worker data plane (ISSUE 15).

Tier-1 fast slice, sanitizer-armed like the serving suites:

- shard math: N shards partition every epoch's global shuffle EXACTLY
  (no dup, no drop), deterministically, at any shard count;
- the bit-identity contracts: a multi-worker prefetch stream equals the
  single-thread stream batch for batch, and a sharded skip_batches
  resume equals its uninterrupted twin bit-exactly (the PR 4 RNG-replay
  discipline under sharding);
- chaos: a transient ``loader_err`` inside ONE worker retries the same
  plan without reordering or dropping batches (stream still equals the
  fault-free twin); exhausted retries poison the stream in order; the
  abandon path reaps every ``loader-prefetch-*`` thread;
- telemetry: queue depth/capacity gauges + per-worker retry counters
  declared at 0 and riding the heartbeat payload;
- opts: type-validator usage errors + env fallbacks for
  --loader_workers/--data_shards/--data_shard_id;
- the feed probe (``make data-bench``'s API twin) and
  scripts/data_report.py's render + >= 2x-at-4-workers gate;
- bench config identity: the data stage's worker/shard/latency axes.
"""

import json
import os
import sys
import threading

import numpy as np
import pytest

from cst_captioning_tpu.data.bench import SyntheticFeedDataset, feed_probe
from cst_captioning_tpu.data.loader import (
    CaptionLoader,
    prefetch_to_device,
)
from cst_captioning_tpu.data.sharding import (
    ShardSpec,
    global_epoch_order,
    resolve_shard_spec,
    shard_epoch_order,
    shard_size,
)
from cst_captioning_tpu.opts import parse_opts
from cst_captioning_tpu.resilience.faults import FaultPlan
from cst_captioning_tpu.telemetry import Telemetry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _lock_sanitizer(monkeypatch, tmp_path):
    """ISSUE 11 discipline: the data-plane fast slice runs sanitizer-
    armed so the new ``data.loader.plan``/``data.loader.queue`` locks
    are runtime-validated under every multi-worker test."""
    from cst_captioning_tpu.analysis import locksan

    receipt = tmp_path / "locksan_violation.json"
    monkeypatch.setenv(locksan.ENV_FLAG, "1")
    monkeypatch.setenv(locksan.ENV_RECEIPT, str(receipt))
    before = len(locksan.violations())
    yield
    after = locksan.violations()
    assert len(after) == before, f"lock-order violations: {after[before:]}"
    assert not receipt.exists(), (
        f"lock sanitizer receipt: {receipt.read_text()}")


def tiny_ds(num_videos=12, **kw):
    kw.setdefault("seq_len", 8)
    kw.setdefault("captions_per_video", 4)
    kw.setdefault("vocab", 50)
    kw.setdefault("feat_shapes", ((3, 6), (1, 4)))
    return SyntheticFeedDataset(num_videos, **kw)


def assert_batches_equal(a, b):
    assert a.video_ids == b.video_ids
    np.testing.assert_array_equal(a.labels, b.labels)
    np.testing.assert_array_equal(a.weights, b.weights)
    np.testing.assert_array_equal(a.video_ix, b.video_ix)
    assert len(a.feats) == len(b.feats)
    for fa, fb in zip(a.feats, b.feats):
        np.testing.assert_array_equal(np.asarray(fa), np.asarray(fb))


class TestShardSpec:
    def test_validation(self):
        with pytest.raises(ValueError):
            ShardSpec(0, 0)
        with pytest.raises(ValueError):
            ShardSpec(3, 3)
        with pytest.raises(ValueError):
            ShardSpec(3, -1)
        assert ShardSpec(3, 2).shard_id == 2

    def test_resolve(self):
        assert resolve_shard_spec(0, 0) is None
        assert resolve_shard_spec(4, 1) == ShardSpec(4, 1)

    def test_shard_size_matches_order(self):
        for n in (7, 12, 13):
            for s in (1, 2, 3, 5):
                for k in range(s):
                    spec = ShardSpec(s, k)
                    assert shard_size(n, spec) == len(
                        shard_epoch_order(n, 0, 0, spec))


class TestShardUnion:
    def test_shards_partition_every_epoch_exactly(self):
        """THE union contract: N shards of one epoch are the N strided
        slices of ONE global permutation — no video duplicated, none
        dropped, at any shard count, every epoch."""
        for n_shards in (1, 2, 3, 5):
            for epoch in range(3):
                parts = [
                    shard_epoch_order(13, 7, epoch, ShardSpec(n_shards, k))
                    for k in range(n_shards)
                ]
                union = np.concatenate(parts)
                assert sorted(union.tolist()) == list(range(13)), (
                    f"shards={n_shards} epoch={epoch}: not a partition")

    def test_deterministic_and_epoch_varying(self):
        a = global_epoch_order(20, 3, 1)
        b = global_epoch_order(20, 3, 1)
        np.testing.assert_array_equal(a, b)
        assert not np.array_equal(a, global_epoch_order(20, 3, 2))
        assert not np.array_equal(a, global_epoch_order(20, 4, 1))

    def test_unshuffled_shard_is_strided_identity(self):
        order = shard_epoch_order(10, 0, 5, ShardSpec(3, 1), shuffle=False)
        np.testing.assert_array_equal(order, np.arange(10)[1::3])


class TestOptsFlags:
    def test_loader_workers_zero_is_usage_error(self, capsys):
        with pytest.raises(SystemExit) as e:
            parse_opts(["--loader_workers", "0"])
        assert e.value.code == 2
        assert "--loader_workers" in capsys.readouterr().err

    def test_shard_id_out_of_range_is_usage_error(self, capsys):
        with pytest.raises(SystemExit) as e:
            parse_opts(["--data_shards", "3", "--data_shard_id", "3"])
        assert e.value.code == 2
        assert "0 <= id < --data_shards" in capsys.readouterr().err

    def test_shard_id_without_shards_is_usage_error(self, capsys):
        with pytest.raises(SystemExit) as e:
            parse_opts(["--data_shard_id", "1"])
        assert e.value.code == 2
        assert "--data_shards >= 1" in capsys.readouterr().err

    def test_defaults(self):
        ns = parse_opts([])
        assert ns.loader_workers == 1
        assert ns.data_shards == 0
        assert ns.data_shard_id == 0

    def test_env_fallbacks(self, monkeypatch):
        monkeypatch.setenv("CST_LOADER_WORKERS", "5")
        monkeypatch.setenv("CST_DATA_SHARDS", "4")
        monkeypatch.setenv("CST_DATA_SHARD_ID", "2")
        ns = parse_opts([])
        assert ns.loader_workers == 5
        assert ns.data_shards == 4
        assert ns.data_shard_id == 2
        # explicit flag beats env
        assert parse_opts(["--loader_workers", "2"]).loader_workers == 2

    def test_malformed_env_is_usage_error(self, monkeypatch, capsys):
        monkeypatch.setenv("CST_LOADER_WORKERS", "many")
        with pytest.raises(SystemExit) as e:
            parse_opts([])
        assert e.value.code == 2
        assert "CST_LOADER_WORKERS" in capsys.readouterr().err


class TestShardedLoader:
    def test_sharded_epoch_covers_dataset_exactly(self):
        ds = tiny_ds(12)
        seen = []
        for k in range(3):
            loader = CaptionLoader(ds, batch_size=2, seq_per_img=2, seed=9,
                                   shard_spec=ShardSpec(3, k))
            for _ in range(2):  # 4 videos per shard / batch 2
                seen.extend(loader.next_batch().video_ids)
        assert sorted(seen) == sorted(ds.video_ids)

    def test_shard_spec_excludes_process_striding(self):
        with pytest.raises(ValueError):
            CaptionLoader(tiny_ds(12), batch_size=2,
                          shard_spec=ShardSpec(2, 0), process_count=2)

    def test_sharded_stream_deterministic(self):
        ds = tiny_ds(10)
        a = CaptionLoader(ds, batch_size=3, seq_per_img=2, seed=4,
                          shard_spec=ShardSpec(2, 1))
        b = CaptionLoader(ds, batch_size=3, seq_per_img=2, seed=4,
                          shard_spec=ShardSpec(2, 1))
        for _ in range(7):
            assert_batches_equal(a.next_batch(), b.next_batch())

    def test_sharded_resume_twin_bit_identical(self):
        """The acceptance drill's loader half: a sharded stream resumed
        via skip_batches equals its uninterrupted twin bit-exactly —
        the global shuffle consumes no caption-RNG draws, so the PR 4
        replay discipline holds under any shard count."""
        ds = tiny_ds(11)
        for spec in (None, ShardSpec(1, 0), ShardSpec(3, 2)):
            twin = CaptionLoader(ds, batch_size=3, seq_per_img=2, seed=5,
                                 shard_spec=spec)
            resumed = CaptionLoader(ds, batch_size=3, seq_per_img=2, seed=5,
                                    shard_spec=spec)
            ref = [twin.next_batch() for _ in range(9)]
            resumed.skip_batches(4)
            for i in range(4, 9):
                assert_batches_equal(ref[i], resumed.next_batch())


class TestMultiWorkerPrefetch:
    def test_bit_identical_to_single_thread(self):
        """THE multi-worker contract: batch order and content identical
        to the single-thread stream, at any worker count."""
        ds = tiny_ds(10)
        for workers in (2, 4):
            ref = CaptionLoader(ds, batch_size=3, seq_per_img=2, seed=6)
            par = CaptionLoader(ds, batch_size=3, seq_per_img=2, seed=6)
            it = prefetch_to_device(par, size=3, workers=workers)
            for _ in range(12):
                assert_batches_equal(ref.next_batch(), next(it))
            it.close()

    def test_sharded_multiworker_resume_twin(self):
        """Shards + workers + resume composed: the resumed multi-worker
        stream equals the uninterrupted single-thread twin."""
        ds = tiny_ds(12)
        spec = ShardSpec(2, 1)
        twin = CaptionLoader(ds, batch_size=2, seq_per_img=2, seed=8,
                             shard_spec=spec)
        ref = [twin.next_batch() for _ in range(8)]
        resumed = CaptionLoader(ds, batch_size=2, seq_per_img=2, seed=8,
                                shard_spec=spec)
        resumed.skip_batches(3)
        it = prefetch_to_device(resumed, size=2, workers=3)
        for i in range(3, 8):
            assert_batches_equal(ref[i], next(it))
        it.close()

    def test_device_put_and_feat_dtype_applied(self):
        import ml_dtypes
        import jax.numpy as jnp

        ds = tiny_ds(8)
        loader = CaptionLoader(ds, batch_size=2, seq_per_img=2, seed=1)
        it = prefetch_to_device(loader, size=2, workers=2,
                                device_put=jnp.asarray,
                                feat_dtype=ml_dtypes.bfloat16)
        b = next(it)
        assert isinstance(b.labels, jnp.ndarray)
        assert b.feats[0].dtype == jnp.bfloat16
        it.close()

    def test_worker_fault_retries_without_reorder_or_drop(self):
        """Chaos satellite: a transient loader_err inside ONE worker is
        retried by re-assembling the SAME plan — the stream stays
        bit-identical to the fault-free twin (nothing reordered,
        nothing dropped), the retry lands in the global counter AND
        exactly one per-worker counter."""
        ds = tiny_ds(10)
        ref = CaptionLoader(ds, batch_size=3, seq_per_img=2, seed=2)
        faulty = CaptionLoader(ds, batch_size=3, seq_per_img=2, seed=2,
                               fault_plan=FaultPlan.parse(
                                   "loader_err@batch=2"))
        telemetry = Telemetry()
        it = prefetch_to_device(faulty, size=3, workers=3,
                                telemetry=telemetry)
        for _ in range(8):
            assert_batches_equal(ref.next_batch(), next(it))
        it.close()
        reg = telemetry.registry
        assert reg.counter("loader_retries") == 1
        per_worker = [reg.counter(f"loader_retries_worker{i}")
                      for i in range(3)]
        assert sorted(per_worker) == [0, 0, 1]

    def test_exhausted_retries_raise_in_order(self):
        """A persistently failing read poisons the stream AT ITS SEQ:
        every earlier batch is still delivered, then the error raises."""

        class FlakyDS:
            def __init__(self, inner, bad_after):
                self._inner = inner
                self._reads = 0
                self._bad_after = bad_after

            def __getattr__(self, name):
                return getattr(self._inner, name)

            def features(self, ix):
                self._reads += 1
                if self._reads > self._bad_after:
                    raise OSError("dead transport")
                return self._inner.features(ix)

        ds = FlakyDS(tiny_ds(10), bad_after=3)
        loader = CaptionLoader(ds, batch_size=2, seq_per_img=2, seed=3)
        it = prefetch_to_device(loader, size=2, workers=2, retries=1,
                                retry_backoff_s=0.001)
        got = 0
        with pytest.raises(OSError):
            for _ in range(10):
                next(it)
                got += 1
        assert got >= 1  # earlier batches delivered before the poison
        it.close()

    def test_abandon_reaps_all_workers(self):
        """Abandoning the stream joins every loader-prefetch-* thread —
        no stray worker (or the prefetched buffer it holds) survives."""
        ds = tiny_ds(10)
        loader = CaptionLoader(ds, batch_size=2, seq_per_img=2, seed=4)
        it = prefetch_to_device(loader, size=4, workers=4)
        next(it)
        next(it)
        it.close()  # break / GeneratorExit path
        stray = [t.name for t in threading.enumerate()
                 if t.name.startswith("loader-prefetch")]
        assert stray == [], f"stray prefetch threads: {stray}"

    def test_queue_gauges_and_declared_counters(self):
        """Satellite: queue depth/capacity gauges + per-worker retry
        counters declared at 0, all visible in the heartbeat payload
        (between-steps state, not just end-of-run counters)."""
        ds = tiny_ds(8)
        loader = CaptionLoader(ds, batch_size=2, seq_per_img=2, seed=5)
        telemetry = Telemetry()
        it = prefetch_to_device(loader, size=3, workers=2,
                                telemetry=telemetry)
        next(it)
        hb = telemetry.registry.heartbeat_payload()
        assert "loader_queue_depth" in hb["gauges"]
        assert hb["gauges"]["loader_queue_capacity"] == 3
        assert hb["counters"]["loader_retries"] == 0
        assert hb["counters"]["loader_retries_worker0"] == 0
        assert hb["counters"]["loader_retries_worker1"] == 0
        it.close()

    def test_single_thread_path_gains_queue_gauge(self):
        ds = tiny_ds(8)
        loader = CaptionLoader(ds, batch_size=2, seq_per_img=2, seed=5)
        telemetry = Telemetry()
        it = prefetch_to_device(loader, size=2, telemetry=telemetry)
        next(it)
        assert "loader_queue_depth" in (
            telemetry.registry.heartbeat_payload()["gauges"])
        it.close()

    def test_plain_iterator_falls_back_to_single_thread(self):
        ds = tiny_ds(8)
        ref = CaptionLoader(ds, batch_size=2, seq_per_img=2, seed=7)
        src = CaptionLoader(ds, batch_size=2, seq_per_img=2, seed=7)
        it = prefetch_to_device(iter(src), size=2, workers=4)
        for _ in range(3):
            assert_batches_equal(ref.next_batch(), next(it))
        it.close()


class TestFeedProbe:
    def test_probe_record_fields(self):
        rec = feed_probe(batch_size=2, seq_per_img=2, seq_len=8, vocab=50,
                         num_videos=8, workers=2, read_ms=0.5, batches=6,
                         warmup=2, feat_shapes=((2, 4), (1, 3)))
        assert rec["captions_per_sec"] > 0
        assert rec["batches_per_sec"] > 0
        assert rec["loader_workers"] == 2
        assert rec["data_shards"] == 0
        assert 0 <= rec["data_wait_share"] <= 1
        assert rec["queue_depth_mean"] >= 0
        assert rec["retries"] == 0
        assert rec["vs_xe_rate"] == pytest.approx(
            rec["captions_per_sec"] / 30447.0, abs=1e-3)

    def test_probe_sharded(self):
        rec = feed_probe(batch_size=2, seq_per_img=2, seq_len=8, vocab=50,
                         num_videos=10, workers=1, data_shards=2,
                         data_shard_id=1, read_ms=0.0, batches=4,
                         warmup=1, feat_shapes=((2, 4),))
        assert rec["data_shards"] == 2
        assert rec["data_shard_id"] == 1
        assert rec["captions_per_sec"] > 0


def _report_main(tmp_path, rec):
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        import data_report
    finally:
        sys.path.pop(0)
    f = tmp_path / "rec.json"
    f.write_text(json.dumps(rec) + "\n")
    return data_report.main(["--file", str(f)])


class TestDataReport:
    BASE = {"metric": "data_feed_captions_per_sec", "value": 1000.0,
            "batches_per_sec": 10.0, "vs_xe_rate": 0.03,
            "loader_workers": 4, "data_shards": 0, "data_shard_id": 0,
            "read_ms": 2.0, "data_wait_share": 0.1,
            "data_wait_ms_p99": 1.0, "queue_depth_mean": 1.5,
            "queue_capacity": 4, "retries": 0, "platform": "cpu",
            "single_worker_captions_per_sec": 400.0,
            "workers_speedup": 2.5}

    def test_renders_and_passes_gate(self, tmp_path, capsys):
        assert _report_main(tmp_path, dict(self.BASE)) == 0
        out = capsys.readouterr().out
        assert "feed rate" in out
        assert "2.50x" in out

    def test_gate_fails_below_2x_at_4_workers(self, tmp_path, capsys):
        rec = dict(self.BASE, workers_speedup=1.4,
                   single_worker_captions_per_sec=714.0)
        assert _report_main(tmp_path, rec) == 1
        assert "GATE FAILED" in capsys.readouterr().err

    def test_no_gate_below_4_workers(self, tmp_path):
        rec = dict(self.BASE, loader_workers=2, workers_speedup=1.4)
        assert _report_main(tmp_path, rec) == 0

    def test_missing_record_exits_1(self, tmp_path):
        assert _report_main(tmp_path, {"metric": "other"}) == 1

    def test_null_value_exits_1(self, tmp_path):
        assert _report_main(tmp_path, dict(self.BASE, value=None)) == 1


class TestBenchIdentity:
    def test_data_stage_config_identity_axes(self, monkeypatch):
        """Satellite: loader_workers/data_shards (and the simulated-
        latency protocol knobs) join the bench cache-config identity, so
        records at different data-plane configurations can never share a
        cache entry."""
        import bench

        monkeypatch.setattr(sys, "argv", [
            "bench.py", "--stage", "data", "--loader_workers", "4",
            "--data_shards", "2", "--data_shard_id", "1",
            "--data_read_ms", "3.5"])
        args = bench.parse_args()
        config = bench.resolved_config(args)
        assert config["loader_workers"] == 4
        assert config["data_shards"] == 2
        assert config["data_shard_id"] == 1
        assert config["data_read_ms"] == 3.5
        assert "data_batches" in config and "data_compare" in config
        # training stages keep their historical identity shape
        monkeypatch.setattr(sys, "argv", ["bench.py", "--stage", "xe"])
        assert "loader_workers" not in bench.resolved_config(
            bench.parse_args())

    def test_headline_metric_registered(self):
        import bench

        assert bench.HEADLINE_METRIC["data"] == "data_feed_captions_per_sec"
