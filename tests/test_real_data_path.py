"""The REAL-dataset path, end to end, before the real data exists.

tests/fixtures/mini_videodatainfo.json is a hand-written miniature of
MSR-VTT's actual release format (``videos`` with a ``split`` field, a
flat ``sentences`` list — SURVEY.md §7 step 2).  This test drives it
through the ACTUAL CLIs a user would run the day real MSR-VTT lands:

    converters (msrvtt) -> prepro (train vocab reused for val/test)
    -> train.py (one XE stage with val) -> eval.py (beam on test)

Features are written in-test: in the real pipeline they are
pre-extracted CNN outputs the user supplies, not something these CLIs
produce.  So when the dataset shows up, the ONLY new variable is the
data itself (VERDICT r4, next #6).
"""

import json
import os
import subprocess
import sys

import h5py
import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE = os.path.join(REPO, "tests", "fixtures", "mini_videodatainfo.json")


def _env():
    env = dict(os.environ)
    env["PYTHONPATH"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    from conftest import CACHE_DIR

    env.setdefault("JAX_COMPILATION_CACHE_DIR", CACHE_DIR)
    return env


def _run(cmd, env, timeout=600):
    proc = subprocess.run(cmd, env=env, cwd=REPO, capture_output=True,
                          text=True, timeout=timeout)
    assert proc.returncode == 0, (
        f"{' '.join(cmd[:4])}... rc={proc.returncode}\n"
        f"stdout:{proc.stdout[-2000:]}\nstderr:{proc.stderr[-2000:]}")
    return proc.stdout


def _write_feats(info_json: str, path: str, dim: int = 8, t: int = 4):
    """Pre-extracted-feature stand-in: rows follow the info json's video
    order, exactly the contract real extracted features must meet."""
    with open(info_json) as f:
        vids = [v["id"] for v in json.load(f)["videos"]]
    import zlib

    rng = np.random.default_rng(zlib.crc32(os.path.basename(path).encode()))
    with h5py.File(path, "w") as f:
        f.create_dataset(
            "feats", data=rng.standard_normal(
                (len(vids), t, dim)).astype(np.float32))
    return path


@pytest.mark.e2e
def test_msrvtt_format_to_trained_eval(tmp_path):
    env = _env()
    pre = str(tmp_path / "mini_")

    # 1. Official-format annotations -> per-split annotation JSONs.
    out = _run([sys.executable, "-m", "cst_captioning_tpu.data.converters",
                "--format", "msrvtt", "--input", FIXTURE,
                "--out_prefix", pre], env)
    written = json.loads(out)
    assert set(written) == {"train", "val", "test"}

    # 2. Offline prepro: train builds the vocab; val/test REUSE it (the
    # reference's convention — val tokens outside the train vocab map to
    # UNK instead of shifting ids).
    d = str(tmp_path / "data")
    paths = {}
    for split in ("train", "val", "test"):
        argv = [sys.executable, "-m", "cst_captioning_tpu.data.prepro",
                "--annotations", written[split], "--split", split,
                "--out_dir", d, "--max_len", "12"]
        if split != "train":
            argv += ["--vocab_json", paths["train"]["vocab_json"]]
        paths[split] = json.loads(_run(argv, env))
    assert os.path.exists(paths["train"]["cached_tokens"])
    assert os.path.exists(paths["train"]["consensus_pkl"])

    # Same vocab file contents for every split.
    with open(paths["train"]["vocab_json"]) as f:
        train_vocab = json.load(f)
    with open(paths["test"]["vocab_json"]) as f:
        assert json.load(f) == train_vocab

    # 3. The user's pre-extracted features (2 modalities, like the
    # reference's ResNet + C3D pairing).
    feats = {}
    for split in ("train", "val", "test"):
        feats[split] = [
            _write_feats(paths[split]["info_json"],
                         str(tmp_path / f"{split}_feat{m}.h5"))
            for m in range(2)
        ]

    # 4. One XE stage through the real trainer CLI, with val scoring.
    ck = str(tmp_path / "ck")
    _run([sys.executable, "train.py",
          "--train_feat_h5", *feats["train"],
          "--train_label_h5", paths["train"]["label_h5"],
          "--train_info_json", paths["train"]["info_json"],
          "--train_cocofmt_file", paths["train"]["cocofmt_json"],
          "--val_feat_h5", *feats["val"],
          "--val_label_h5", paths["val"]["label_h5"],
          "--val_info_json", paths["val"]["info_json"],
          "--val_cocofmt_file", paths["val"]["cocofmt_json"],
          "--checkpoint_path", ck,
          "--batch_size", "2", "--seq_per_img", "3", "--rnn_size", "16",
          "--input_encoding_size", "16", "--att_size", "16",
          "--max_length", "12", "--max_epochs", "2", "--log_every", "1"],
         env)
    with open(os.path.join(ck, "infos.json")) as f:
        infos = json.load(f)
    assert infos["last_step"] > 0

    # 5. Beam eval on the held-out test split through the real eval CLI.
    result = str(tmp_path / "test_beam.json")
    _run([sys.executable, "eval.py",
          "--checkpoint_path", ck,
          "--test_feat_h5", *feats["test"],
          "--test_label_h5", paths["test"]["label_h5"],
          "--test_info_json", paths["test"]["info_json"],
          "--test_cocofmt_file", paths["test"]["cocofmt_json"],
          "--beam_size", "2", "--batch_size", "2", "--max_length", "12",
          "--result_file", result], env)
    with open(result) as f:
        res = json.load(f)
    scores = res["scores"]
    for k in ("Bleu_1", "CIDEr", "ROUGE_L"):
        assert k in scores and np.isfinite(scores[k])
    # Predictions cover exactly the test split's videos.
    pred_ids = {p["image_id"] for p in res["predictions"]}
    with open(paths["test"]["info_json"]) as f:
        assert pred_ids == {v["id"] for v in json.load(f)["videos"]}
