"""Fused Pallas decode cell (ops/pallas_decode_cell.py) vs the reference.

The kernel's numeric contract (module doc): BIT-IDENTICAL to the composed
fused-attention cell (same VPU attention formulation + flax-order LSTM
algebra — interpret mode executes the identical op sequence), and float32-
ULP-close to the plain einsum reference cell.  Greedy decodes, beam search,
the chunked early-exit invariant, and the fused CST step must all hold
under the new kernel; ineligible configs must FALL BACK, not diverge.

Skips cleanly where Pallas is unavailable (the satellite requirement).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytest.importorskip("jax.experimental.pallas",
                    reason="Pallas unavailable in this jax build")

from cst_captioning_tpu.models import CaptionModel  # noqa: E402
from cst_captioning_tpu.ops.sampling import (  # noqa: E402
    make_decode_step,
    sample_captions,
    sample_with_baseline,
)

B, T, H, E, A, V, L = 6, 4, 16, 12, 16, 30, 8


def _models(**overrides):
    kw = dict(vocab_size=V, embed_size=E, hidden_size=H, attn_size=A,
              dropout_rate=0.5)
    kw.update(overrides)
    ref = CaptionModel(**kw)
    composed = CaptionModel(**kw, use_pallas_attention=True)
    fused = CaptionModel(**kw, decode_kernel="pallas")
    return ref, composed, fused


@pytest.fixture(scope="module")
def setup():
    ref, composed, fused = _models()
    feats = [jax.random.normal(jax.random.PRNGKey(1), (B, T, 8))]
    variables = ref.init(jax.random.PRNGKey(0), feats,
                         np.zeros((B, L), np.int32))
    return ref, composed, fused, feats, variables


def _drive(model, variables, feats, steps=5):
    """Greedy-feed the decode step eagerly; returns stacked logits and the
    token trajectory — the per-step surface every sampler drives."""
    mem, pm, pooled = model.apply(variables, feats, method="encode")
    carry = model.apply(variables, pooled, L, method="init_carry")
    step = make_decode_step(model, variables, mem, pm, pooled)
    tok = jnp.arange(B, dtype=jnp.int32) % (V - 1) + 1
    logits, toks = [], []
    for _ in range(steps):
        carry, lg = step(carry, tok)
        tok = jnp.argmax(lg, axis=-1).astype(jnp.int32)
        logits.append(np.asarray(lg))
        toks.append(np.asarray(tok))
    return np.stack(logits), np.stack(toks)


class TestBitExactness:
    def test_bit_identical_to_composed_fused_attention_cell(self, setup):
        """The pin: one fused kernel == attention kernel + flax LSTM,
        bit for bit (identical op sequence, interpret mode)."""
        _, composed, fused, feats, variables = setup
        lg_c, tk_c = _drive(composed, variables, feats)
        lg_f, tk_f = _drive(fused, variables, feats)
        np.testing.assert_array_equal(lg_f, lg_c)
        np.testing.assert_array_equal(tk_f, tk_c)

    def test_ulp_close_to_plain_reference_cell(self, setup):
        """The einsum-based reference cell differs from the VPU
        formulation by float32 ULPs only (same bound the fused-attention
        kernel is pinned to in tests/test_pallas_attention.py)."""
        ref, _, fused, feats, variables = setup
        lg_r, _ = _drive(ref, variables, feats)
        lg_f, _ = _drive(fused, variables, feats)
        np.testing.assert_allclose(lg_f, lg_r, rtol=1e-5, atol=1e-6)

    def test_block_size_does_not_change_results(self, setup):
        from cst_captioning_tpu.ops.pallas_decode_cell import (
            make_pallas_decode_step,
        )

        _, _, fused, feats, variables = setup
        mem, pm, pooled = fused.apply(variables, feats, method="encode")
        carry = fused.apply(variables, pooled, L, method="init_carry")
        tok = jnp.arange(B, dtype=jnp.int32) % (V - 1) + 1
        outs = []
        for bb in (1, 4, 8):  # 4 pads B=6 -> 8: padding must be inert
            step = make_pallas_decode_step(fused, variables, mem, pm,
                                           block_b=bb)
            _, lg = step(carry, tok)
            outs.append(np.asarray(lg))
        np.testing.assert_array_equal(outs[0], outs[1])
        np.testing.assert_array_equal(outs[0], outs[2])


class TestSamplers:
    def test_greedy_decode_tokens_match_reference(self, setup):
        ref, _, fused, feats, variables = setup
        want, _ = sample_captions(ref, variables, feats,
                                  jax.random.PRNGKey(2), L, greedy=True)
        got, _ = sample_captions(fused, variables, feats,
                                 jax.random.PRNGKey(2), L, greedy=True)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_chunked_early_exit_bit_identical_under_pallas(self, setup):
        """--decode_chunk's bit-identity contract must survive the kernel
        swap: chunked pallas rollout == legacy pallas rollout."""
        _, _, fused, feats, variables = setup
        legacy = sample_with_baseline(fused, variables, feats,
                                      jax.random.PRNGKey(3), L,
                                      seq_per_img=2)
        for chunk in (3, 8):
            chunked = sample_with_baseline(fused, variables, feats,
                                           jax.random.PRNGKey(3), L,
                                           seq_per_img=2,
                                           decode_chunk=chunk)
            for a, b in zip(chunked, legacy):
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_jit_rollout_deterministic_and_terminated(self, setup):
        _, _, fused, feats, variables = setup
        fn = jax.jit(lambda v, f, k: sample_captions(
            fused, v, f, k, L, seq_per_img=2, decode_chunk=4))
        t1, lp1 = fn(variables, feats, jax.random.PRNGKey(5))
        t2, _ = fn(variables, feats, jax.random.PRNGKey(5))
        np.testing.assert_array_equal(np.asarray(t1), np.asarray(t2))
        toks = np.asarray(t1)
        assert toks.shape == (B * 2, L)
        # 0-termination: nothing after the first EOS
        for row in toks:
            eos = np.argmax(row == 0) if (row == 0).any() else L
            assert (row[eos:] == 0).all()
        assert np.isfinite(np.asarray(lp1)).all()

    def test_beam_search_matches_composed_cell(self, setup):
        from cst_captioning_tpu.ops.beam import beam_search

        _, composed, fused, feats, variables = setup
        want = beam_search(composed, variables, feats, beam_size=3,
                           max_len=L)
        got = beam_search(fused, variables, feats, beam_size=3, max_len=L)
        for a, b in zip(got, want):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestFallback:
    def test_multilayer_falls_back_to_reference(self, setup):
        """num_layers=2 is outside the kernel's scope: --decode_kernel
        pallas must produce EXACTLY the reference computation (fallback),
        never a silently different one."""
        ref2, _, fused2 = _models(num_layers=2)
        feats = [jax.random.normal(jax.random.PRNGKey(1), (B, T, 8))]
        variables = ref2.init(jax.random.PRNGKey(0), feats,
                              np.zeros((B, L), np.int32))
        lg_r, _ = _drive(ref2, variables, feats, steps=3)
        lg_f, _ = _drive(fused2, variables, feats, steps=3)
        np.testing.assert_array_equal(lg_f, lg_r)

    def test_pooled_model_falls_back(self):
        ref0, _, fused0 = _models(use_attention=False)
        feats = [jax.random.normal(jax.random.PRNGKey(1), (B, T, 8))]
        variables = ref0.init(jax.random.PRNGKey(0), feats,
                              np.zeros((B, L), np.int32))
        lg_r, _ = _drive(ref0, variables, feats, steps=3)
        lg_f, _ = _drive(fused0, variables, feats, steps=3)
        np.testing.assert_array_equal(lg_f, lg_r)

    def test_supported_predicate(self):
        from cst_captioning_tpu.ops.pallas_decode_cell import (
            pallas_decode_supported,
        )

        ref, _, fused = _models()
        assert pallas_decode_supported(fused) == (True, "")
        ok, why = pallas_decode_supported(_models(num_layers=2)[2])
        assert not ok and "num_layers" in why
        ok, why = pallas_decode_supported(
            CaptionModel(vocab_size=V, decoder_type="transformer"))
        assert not ok and "decoder_type" in why


class TestBF16:
    def test_bf16_rollout_close_to_reference(self):
        ref, _, fused = _models(dtype=jnp.bfloat16, dropout_rate=0.0)
        feats = [jax.random.normal(jax.random.PRNGKey(1), (B, T, 8))]
        variables = ref.init(jax.random.PRNGKey(0), feats,
                             np.zeros((B, L), np.int32))
        lg_r, _ = _drive(ref, variables, feats, steps=3)
        lg_f, _ = _drive(fused, variables, feats, steps=3)
        assert lg_f.dtype == lg_r.dtype
        np.testing.assert_allclose(lg_f.astype(np.float32),
                                   lg_r.astype(np.float32),
                                   rtol=5e-2, atol=5e-2)


class TestFusedCstStep:
    def test_fused_step_runs_with_pallas_kernel(self):
        """The tentpole composition: device-native rewards + pallas decode
        cell in ONE program — the exact configuration the autotuner
        sweeps as (device_rewards=1, decode_kernel=pallas)."""
        from cst_captioning_tpu.training.device_rewards import (
            build_device_tables,
        )
        from cst_captioning_tpu.training.state import (
            create_train_state,
            make_optimizer,
        )
        from cst_captioning_tpu.training.steps import make_fused_cst_step

        words = {f"w{k}": k for k in range(1, V)}
        refs = {f"v{i}": [f"w{1 + (i + j) % (V - 1)} w{1 + i % (V - 1)}"
                          for j in range(3)] for i in range(4)}
        corpus, tables, _ = build_device_tables(refs, words)
        _, _, fused = _models()
        tx, _ = make_optimizer(learning_rate=1e-2, grad_clip=5.0)
        state = create_train_state(fused, jax.random.PRNGKey(0), [(T, 8)],
                                   L, 2, tx, batch_size=4)
        feats = [jax.random.normal(jax.random.PRNGKey(1), (4, T, 8))]
        step = jax.jit(make_fused_cst_step(fused, L, 2, corpus, tables,
                                           decode_chunk=4))
        new_state, m = step(state, feats, np.arange(4, dtype=np.int32),
                            jax.random.PRNGKey(9))
        assert np.isfinite(float(m["loss"]))
        assert float(m["rollout_steps"]) <= L
        # params actually moved
        moved = any(
            not np.array_equal(np.asarray(a), np.asarray(b))
            for a, b in zip(jax.tree_util.tree_leaves(state.params),
                            jax.tree_util.tree_leaves(new_state.params)))
        assert moved


@pytest.mark.slow
def test_dp_pipeline_completes_with_pallas_kernel():
    """Donation audit for the kernel path (parallel/dp.py note): the DP
    pipeline — state donation on, batch donation contract unchanged —
    runs end to end with the fused decode cell on the mesh.

    Marked ``slow`` (outside tier-1): the fresh 2-device child compiles
    the whole pipeline cold (its XLA_FLAGS differ from the suite's, so
    the persistent compile cache cannot help), ~30-60s this suite's
    wall budget cannot spare — the kernel path's correctness is fully
    pinned by the in-process tests above; this drill only re-proves the
    donation wiring end to end.

    Runs in a FRESH 2-device subprocess: in-process it is stable
    standalone but segfaulted deep into a full tier-1 run (suite-context
    native instability — the class of defect RESILIENCE.md documents for
    this environment's CPU stack, same subprocess-isolation remedy as the
    restore-path e2e stages).  A signal-death child that produced NO
    Python traceback is that documented environment defect and skips with
    its signature; a child that fails WITH a traceback is a real
    kernel-path regression and fails loudly."""
    import os
    import subprocess
    import sys

    from cst_captioning_tpu.utils.platform import with_host_device_count

    code = (
        "import numpy as np\n"
        "from cst_captioning_tpu.parallel.dryrun import run_dp_pipeline\n"
        "out = run_dp_pipeline(2, batch_size=4, decode_kernel='pallas')\n"
        "assert np.isfinite(out['xe_losses']).all()\n"
        "assert np.isfinite(np.asarray(out['rl_loss']))\n"
        "assert out['sampled'].shape[0] == 8\n"
        "print('DP_PALLAS_OK')\n"
    )
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo
    env["XLA_FLAGS"] = with_host_device_count(env.get("XLA_FLAGS", ""), 2)
    proc = subprocess.run([sys.executable, "-c", code], cwd=repo, env=env,
                          capture_output=True, text=True, timeout=420)
    if proc.returncode < 0 and "Traceback" not in proc.stderr:
        pytest.skip(
            f"child died on signal {-proc.returncode} with no Python "
            "traceback — the documented native-stack instability of this "
            "environment's CPU backend (RESILIENCE.md), not a kernel-path "
            "failure; the kernel itself is pinned by the in-process tests "
            "above")
    assert proc.returncode == 0, proc.stderr[-3000:]
    assert "DP_PALLAS_OK" in proc.stdout
