"""Parallel layer: mesh construction, batch sharding, DP == single-device.

Runs on the 8-device virtual CPU mesh (conftest.py) — SURVEY.md §4
"Distributed without a cluster".
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from cst_captioning_tpu.parallel import (
    batch_sharding,
    data_parallel_jit,
    host_local_slice,
    make_mesh,
    replicated_sharding,
    shard_batch_arrays,
)


class TestMesh:
    def test_make_mesh_all_devices(self):
        mesh = make_mesh()
        assert mesh.devices.size == jax.device_count()
        assert mesh.axis_names == ("data", "model")

    def test_make_mesh_subset(self):
        mesh = make_mesh(jax.devices()[:4])
        assert mesh.devices.size == 4

    def test_model_parallel_axis(self):
        mesh = make_mesh(jax.devices()[:8], model_parallel=2)
        assert mesh.shape["data"] == 4
        assert mesh.shape["model"] == 2

    def test_indivisible_model_parallel_raises(self):
        with pytest.raises(ValueError):
            make_mesh(jax.devices()[:6], model_parallel=4)

    def test_shard_batch_arrays(self):
        mesh = make_mesh(jax.devices()[:8])
        batch = {
            "feats": [np.ones((16, 4, 8), np.float32)],
            "labels": np.zeros((16 * 2, 5), np.int32),
        }
        out = shard_batch_arrays(mesh, batch)
        assert out["feats"][0].sharding == batch_sharding(mesh)
        # 16 rows over 8 devices -> 2 rows per shard
        shard_shapes = {s.data.shape for s in out["feats"][0].addressable_shards}
        assert shard_shapes == {(2, 4, 8)}
        assert out["labels"].sharding.spec == batch_sharding(mesh).spec

    def test_host_local_slice(self):
        assert host_local_slice(32, 1, 4) == slice(8, 16)
        with pytest.raises(ValueError):
            host_local_slice(30, 0, 4)


class TestDataParallelJit:
    """A toy regression step must produce bitwise-identical math whether run
    on 1 device or sharded over 8 — the grad all-reduce is XLA's job."""

    def _make_step(self):
        def step(state, batch, rng):
            params, opt_state = state
            x, y = batch

            def loss_fn(p):
                pred = x @ p["w"] + p["b"]
                return jnp.mean((pred - y) ** 2)

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state), loss

        return step

    def _init(self):
        rng = np.random.default_rng(0)
        params = {
            "w": jnp.asarray(rng.standard_normal((8, 1)), jnp.float32),
            "b": jnp.zeros((1,), jnp.float32),
        }
        self.tx = optax.adam(1e-2)
        return params, self.tx.init(params)

    def _run(self, n_devices, steps=5):
        mesh = make_mesh(jax.devices()[:n_devices])
        state = jax.device_put(self._init(), replicated_sharding(mesh))
        step = data_parallel_jit(self._make_step(), mesh,
                                 batch_argnums=(1,), donate_argnums=(0,))
        rng = np.random.default_rng(42)
        x = jnp.asarray(rng.standard_normal((16, 8)), jnp.float32)
        y = jnp.asarray(rng.standard_normal((16, 1)), jnp.float32)
        batch = shard_batch_arrays(mesh, (x, y))
        losses = []
        for _ in range(steps):
            state, loss = step(state, batch, jax.random.PRNGKey(0))
            losses.append(float(loss))
        return losses, jax.device_get(state[0])

    def test_dp_matches_single_device(self):
        losses1, params1 = self._run(1)
        losses8, params8 = self._run(8)
        np.testing.assert_allclose(losses1, losses8, rtol=1e-5)
        for k in params1:
            np.testing.assert_allclose(params1[k], params8[k], rtol=1e-5)

    def test_loss_decreases(self):
        losses, _ = self._run(8, steps=20)
        assert losses[-1] < losses[0]

    def test_jit_cache_reused(self):
        mesh = make_mesh(jax.devices()[:2])
        calls = []

        def step(state, batch, rng):
            calls.append(1)  # traced once per structure, not per call
            return state, batch.sum()

        fn = data_parallel_jit(step, mesh, batch_argnums=(1,),
                               donate_argnums=())
        x = shard_batch_arrays(mesh, jnp.ones((4, 2)))
        s = jax.device_put(jnp.zeros(()), replicated_sharding(mesh))
        for _ in range(3):
            s, _ = fn(s, x, jax.random.PRNGKey(0))
        assert len(calls) == 1
