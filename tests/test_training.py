"""Training layer units: optimizer, XE/RL steps, rewards, checkpointing.

SURVEY.md §4: XE overfit-to-zero, RL advantage-sign sanity, checkpoint
save/restore exactness.
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cst_captioning_tpu.data.vocab import Vocab
from cst_captioning_tpu.metrics.ciderd import CiderD, build_corpus_df
from cst_captioning_tpu.models import CaptionModel
from cst_captioning_tpu.ops.losses import token_logprobs
from cst_captioning_tpu.training.checkpoint import CheckpointManager
from cst_captioning_tpu.training.rewards import RewardComputer, decode_sequences
from cst_captioning_tpu.training.state import (
    create_train_state,
    make_optimizer,
    param_count,
)
from cst_captioning_tpu.training.steps import (
    make_rl_grad_step,
    make_rollout,
    make_xe_step,
)

VOCAB_WORDS = {1: "a", 2: "man", 3: "is", 4: "cooking", 5: "dog", 6: "runs"}
B, S, L = 2, 2, 6


@pytest.fixture(scope="module")
def vocab():
    return Vocab(VOCAB_WORDS)


def tiny_model(vocab):
    return CaptionModel(vocab_size=vocab.size_with_pad, embed_size=16,
                        hidden_size=16, attn_size=16, dropout_rate=0.0)


@pytest.fixture(scope="module")
def setup(vocab):
    model = tiny_model(vocab)
    tx, _ = make_optimizer(learning_rate=3e-2, grad_clip=5.0)
    state = create_train_state(
        model, jax.random.PRNGKey(0), [(3, 8)], L, S, tx, batch_size=B
    )
    feats = [jax.random.normal(jax.random.PRNGKey(1), (B, 3, 8))]
    labels = jnp.array([[1, 2, 3, 4, 0, 0]] * S + [[5, 6, 0, 0, 0, 0]] * S,
                       dtype=jnp.int32)
    return model, state, feats, labels


class TestOptimizer:
    def test_unknown_optim_raises(self):
        with pytest.raises(ValueError):
            make_optimizer(optim="lbfgs")

    def test_lr_decay_staircase(self):
        _, sched = make_optimizer(learning_rate=1.0, decay_rate=0.5,
                                  decay_every_steps=10)
        assert float(sched(0)) == pytest.approx(1.0)
        assert float(sched(9)) == pytest.approx(1.0)
        assert float(sched(10)) == pytest.approx(0.5)
        assert float(sched(25)) == pytest.approx(0.25)

    def test_no_decay_by_default(self):
        _, sched = make_optimizer(learning_rate=0.1)
        assert float(sched(10_000)) == pytest.approx(0.1)

    def test_param_count_positive(self, setup):
        _, state, _, _ = setup
        assert param_count(state.params) > 1000


class TestXEStep:
    def test_overfit_to_near_zero(self, setup):
        model, state, feats, labels = setup
        step = jax.jit(make_xe_step(model, S))
        weights = jnp.ones((B * S,))
        rng = jax.random.PRNGKey(2)
        first = None
        for _ in range(150):
            state, metrics = step(state, feats, labels, weights, rng)
            if first is None:
                first = float(metrics["loss"])
        assert first > 0.5
        assert float(metrics["loss"]) < 0.15

    def test_wxe_weighting_changes_grads(self, setup):
        model, state, feats, labels = setup
        step = jax.jit(make_xe_step(model, S))
        rng = jax.random.PRNGKey(2)
        _, m_flat = step(state, feats, labels, jnp.ones((B * S,)), rng)
        # rows 0/1 and 2/3 are duplicate captions, so weights must shift
        # mass BETWEEN videos (not within) to change the total
        w = jnp.array([4.0, 0.0, 0.0, 0.0])
        _, m_wxe = step(state, feats, labels, w, rng)
        assert float(m_flat["loss"]) != pytest.approx(float(m_wxe["loss"]))


class TestBFloat16:
    """--use_bfloat16: bf16 compute on the MXU, fp32 params/updates."""

    def test_bf16_trains_and_decodes(self, vocab):
        model = CaptionModel(vocab_size=vocab.size_with_pad, embed_size=16,
                             hidden_size=16, attn_size=16, dropout_rate=0.0,
                             dtype=jnp.bfloat16)
        tx, _ = make_optimizer(learning_rate=1e-2)
        state = create_train_state(model, jax.random.PRNGKey(0), [(3, 8)],
                                   L, S, tx, batch_size=B)
        # flax keeps params fp32 when only compute dtype is bf16
        assert jax.tree_util.tree_leaves(state.params)[0].dtype == jnp.float32
        feats = [jax.random.normal(jax.random.PRNGKey(1), (B, 3, 8))]
        labels = jnp.array([[1, 2, 3, 0, 0, 0]] * (B * S), dtype=jnp.int32)
        step = jax.jit(make_xe_step(model, S))
        first = None
        for _ in range(40):
            state, m = step(state, feats, labels, jnp.ones((B * S,)),
                            jax.random.PRNGKey(2))
            if first is None:
                first = float(m["loss"])
        assert float(m["loss"]) < first
        from cst_captioning_tpu.ops.beam import beam_search

        best, _, scores = beam_search(model, {"params": state.params},
                                      feats, 3, L)
        assert best.shape == (B, L) and best.dtype == jnp.int32
        assert np.isfinite(np.asarray(scores, np.float32)).all()


class TestRewards:
    def _computer(self, vocab, baseline="greedy", **kw):
        refs = {"v0": ["a man is cooking"], "v1": ["a dog runs"]}
        df, n = build_corpus_df(refs)
        scorer = CiderD(df_mode="corpus", df=df, ref_len=float(n))
        return RewardComputer(vocab, scorer, refs, seq_per_img=S,
                              baseline=baseline, **kw)

    def test_decode_sequences(self, vocab):
        toks = np.array([[1, 2, 0, 0], [5, 6, 0, 0]])
        assert decode_sequences(vocab, toks) == ["a man", "dog runs"]

    def test_greedy_baseline_advantage_sign(self, vocab):
        rc = self._computer(vocab)
        # v0 samples: exact match + garbage; greedy: garbage for v0, exact for v1
        sampled = np.array([
            [1, 2, 3, 4, 0, 0],   # v0 sample 0: perfect
            [5, 5, 5, 5, 0, 0],   # v0 sample 1: garbage
            [1, 5, 6, 0, 0, 0],   # v1 sample 0: perfect
            [2, 2, 2, 2, 0, 0],   # v1 sample 1: garbage
        ])
        greedy = np.array([
            [6, 6, 6, 0, 0, 0],   # v0 greedy: garbage -> sample 0 adv > 0
            [1, 5, 6, 0, 0, 0],   # v1 greedy: perfect -> sample 1 adv < 0
        ])
        adv, stats = rc(["v0", "v1"], sampled, greedy)
        assert adv.shape == (4,)
        assert adv[0] > 0          # better than its baseline
        assert adv[1] <= 0         # garbage vs garbage baseline
        assert adv[2] == pytest.approx(0.0, abs=1e-6)  # perfect vs perfect
        assert adv[3] < 0          # garbage vs perfect baseline
        assert stats["reward"] > 0

    def test_scb_sample_baseline_zero_mean_per_video(self, vocab):
        rc = self._computer(vocab, baseline="scb-sample")
        sampled = np.array([
            [1, 2, 3, 4, 0, 0], [5, 5, 5, 5, 0, 0],
            [1, 5, 6, 0, 0, 0], [2, 2, 2, 2, 0, 0],
        ])
        adv, _ = rc(["v0", "v1"], sampled)
        # with S=2 leave-one-out, advantages are antisymmetric per video
        assert adv[0] == pytest.approx(-adv[1], abs=1e-5)
        assert adv[2] == pytest.approx(-adv[3], abs=1e-5)
        assert adv[0] > 0  # perfect sample beats its garbage sibling

    def test_scb_gt_baseline(self, vocab):
        cons = {"v0": np.array([2.0, 4.0]), "v1": np.array([1.0])}
        rc = self._computer(vocab, baseline="scb-gt", consensus_scores=cons,
                            scb_captions=1)
        sampled = np.zeros((4, 6), dtype=np.int64)
        adv, stats = rc(["v0", "v1"], sampled)
        # empty samples score 0; baseline = top-1 consensus
        assert adv[0] == pytest.approx(-4.0)
        assert adv[2] == pytest.approx(-1.0)

    def test_bad_config_raises(self, vocab):
        with pytest.raises(ValueError):
            self._computer(vocab, baseline="scb-gt")  # no consensus scores
        with pytest.raises(ValueError):
            self._computer(vocab, baseline="nope")


class TestRLStep:
    def test_positive_advantage_raises_sample_logprob(self, setup):
        model, state, feats, _ = setup
        rollout = jax.jit(make_rollout(model, L, S))
        rl_step = jax.jit(make_rl_grad_step(model, S))
        sampled, greedy = rollout(state.params, feats, jax.random.PRNGKey(3))
        assert sampled.shape == (B * S, L)
        assert greedy.shape == (B, L)
        adv = jnp.ones((B * S,))  # uniformly reward the sampled captions

        def mean_logp(params):
            logits = model.apply({"params": params}, feats, sampled, S)
            return float(token_logprobs(logits, sampled).mean())

        before = mean_logp(state.params)
        for _ in range(5):
            state, metrics = rl_step(state, feats, sampled, adv,
                                     jax.random.PRNGKey(4))
        after = mean_logp(state.params)
        assert after > before
        assert np.isfinite(float(metrics["loss"]))

    def test_grad_step_policy_is_sampling_policy(self, setup):
        """The RL gradient must reinforce the SAME policy the rollout
        sampled from: teacher-forced log-probs recomputed in the grad step
        (train=False, no dropout) equal the rollout's own per-token
        log-probs on every supervised position (PARITY.md decision)."""
        from cst_captioning_tpu.ops.sampling import sample_with_baseline
        from cst_captioning_tpu.ops.losses import sequence_mask

        model, state, feats, _ = setup
        sampled, roll_logp, _ = jax.jit(
            lambda p, f, r: sample_with_baseline(
                model, {"params": p}, f, r, L, seq_per_img=S)
        )(state.params, feats, jax.random.PRNGKey(7))
        logits = model.apply({"params": state.params}, feats, sampled, S,
                             train=False)
        recomputed = token_logprobs(logits, sampled)
        mask = np.asarray(sequence_mask(sampled))
        np.testing.assert_allclose(
            np.asarray(roll_logp) * mask, np.asarray(recomputed) * mask,
            atol=1e-5,
        )

    def test_zero_advantage_no_update(self, setup):
        model, state, feats, _ = setup
        rollout = jax.jit(make_rollout(model, L, S))
        rl_step = jax.jit(make_rl_grad_step(model, S))
        sampled, _ = rollout(state.params, feats, jax.random.PRNGKey(3))
        new_state, metrics = rl_step(state, feats, sampled,
                                     jnp.zeros((B * S,)), jax.random.PRNGKey(4))
        assert float(metrics["loss"]) == 0.0
        # adam with zero grads produces zero updates
        np.testing.assert_allclose(
            np.asarray(jax.tree_util.tree_leaves(new_state.params)[0]),
            np.asarray(jax.tree_util.tree_leaves(state.params)[0]),
        )


class TestScalarWriter:
    def test_writes_event_file(self, tmp_path):
        pytest.importorskip("tensorboard")
        from cst_captioning_tpu.utils.tb import ScalarWriter

        d = str(tmp_path / "tb")
        w = ScalarWriter(d)
        w.add_scalar("train/loss", 1.5, 1)
        w.add_scalar("val/CIDEr", 0.4, 2)
        w.close()
        import glob
        import os

        files = glob.glob(d + "/events.out.tfevents.*")
        assert len(files) == 1
        assert os.path.getsize(files[0]) > 0


class TestCheckpoint:
    def test_save_restore_roundtrip(self, setup, tmp_path):
        _, state, _, _ = setup
        mgr = CheckpointManager(str(tmp_path / "ckpt"))
        mgr.save(1, state, score=0.5)
        restored = mgr.restore(state)
        for a, b in zip(jax.tree_util.tree_leaves(state),
                        jax.tree_util.tree_leaves(restored)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        mgr.close()

    def test_best_tracking_and_reload(self, setup, tmp_path):
        _, state, _, _ = setup
        d = str(tmp_path / "ckpt2")
        mgr = CheckpointManager(d)
        mgr.save(1, state, score=0.3)
        mgr.save(2, state.replace(step=jnp.asarray(2)), score=0.7)
        mgr.save(3, state.replace(step=jnp.asarray(3)), score=0.4)
        assert mgr.best_step == 2
        assert mgr.latest_step == 3
        mgr.close()
        # a fresh manager on the same dir sees the same bookkeeping
        mgr2 = CheckpointManager(d)
        assert mgr2.best_step == 2
        best = mgr2.restore(state, best=True)
        assert int(best.step) == 2
        mgr2.close()

    def test_best_falls_back_to_latest_without_scores(self, setup, tmp_path):
        # stage trained without a val split: no scores ever recorded
        _, state, _, _ = setup
        mgr = CheckpointManager(str(tmp_path / "noval"))
        mgr.save(5, state.replace(step=jnp.asarray(5)))
        assert mgr.best_step is None
        restored = mgr.restore_params(state.params, best=True)
        assert jax.tree_util.tree_structure(restored) == \
            jax.tree_util.tree_structure(state.params)
        mgr.close()

    def test_tied_score_plateau_best_restorable(self, setup, tmp_path):
        """Round-4 field bug: on a val-score PLATEAU (ties), orbax's
        best_fn retention keeps the top-k by score with ties broken
        arbitrarily, while best_step records the FIRST tied step (strict
        >).  After enough tied epochs the recorded best step's data is
        trimmed, and restore(best=True) used to crash with
        FileNotFoundError mid stage-chain.  It must instead restore the
        best RETAINED step (same score == same quality)."""
        _, state, _, _ = setup
        d = str(tmp_path / "plateau")
        mgr = CheckpointManager(d, max_to_keep=2)
        for s, sc in [(1, 0.5), (2, 0.5), (3, 0.5), (4, 0.5), (5, 0.2)]:
            mgr.save(s, state.replace(step=jnp.asarray(s)), score=sc)
        assert mgr.best_step == 1  # first of the tied scores
        restored = mgr.restore(state, best=True)  # must NOT raise
        kept = set(mgr._mgr.all_steps())
        assert int(restored.step) in kept
        # among retained steps, the one restored has the top score
        assert mgr.infos["step_scores"][str(int(restored.step))] == 0.5
        mgr.close()
        # fresh manager over the same dir (the stage-chain warm-start path)
        mgr2 = CheckpointManager(d)
        p = mgr2.restore_params(state.params, best=True)  # must NOT raise
        assert jax.tree_util.tree_structure(p) == \
            jax.tree_util.tree_structure(state.params)
        mgr2.close()

    def test_recovery_saves_trim_and_resume(self, setup, tmp_path):
        _, state, _, _ = setup
        d = str(tmp_path / "rec")
        mgr = CheckpointManager(d, max_to_keep=2)
        mgr.save(2, state.replace(step=jnp.asarray(2)), score=0.5)
        # periodic recovery saves: only the newest survives, best untouched
        mgr.save_recovery(3, state.replace(step=jnp.asarray(3)))
        mgr.save_recovery(5, state.replace(step=jnp.asarray(5)))
        assert mgr.best_step == 2
        assert mgr.latest_step == 5  # recovery step wins as resume point
        restored = mgr.restore(state)
        assert int(restored.step) == 5
        # best restore still routes to the scored main checkpoint
        best = mgr.restore(state, best=True)
        assert int(best.step) == 2
        mgr.close()
        import os
        rec_steps = [p for p in os.listdir(os.path.join(d, "recovery"))
                     if p.isdigit()]
        assert rec_steps == ["5"]  # max_to_keep=1 trimmed step 3

    def test_restore_empty_raises(self, setup, tmp_path):
        _, state, _, _ = setup
        mgr = CheckpointManager(str(tmp_path / "empty"))
        with pytest.raises(FileNotFoundError):
            mgr.restore(state)
        mgr.close()


class TestAdvantageRegimeDetector:
    """The trainer warns ONCE, early, when every logged advantage is
    negative — the greedy-baseline degeneration regime observed live at
    512-video scale (reward 0.12 vs baseline 0.26 -> collapse)."""

    def _detector(self):
        import types

        from cst_captioning_tpu.training.trainer import Trainer

        obj = types.SimpleNamespace(_ADV_WARN_STEPS=Trainer._ADV_WARN_STEPS)
        return obj, lambda m: Trainer._check_advantage_regime(obj, m)

    def test_warns_on_all_negative_advantages(self, caplog):
        obj, check = self._detector()
        with caplog.at_level("WARNING",
                             logger="cst_captioning_tpu.train"):
            for _ in range(5):
                check({"advantage": -0.15, "reward": 0.1, "baseline": 0.25})
        assert any("advantage has been negative" in r.message
                   for r in caplog.records)
        assert obj._adv_warned
        # One warning only: further steps stay silent.
        n = len(caplog.records)
        check({"advantage": -0.2, "reward": 0.05, "baseline": 0.25})
        assert len(caplog.records) == n

    def test_silent_when_any_advantage_positive(self, caplog):
        _, check = self._detector()
        with caplog.at_level("WARNING",
                             logger="cst_captioning_tpu.train"):
            for i in range(6):
                check({"advantage": -0.2 if i % 2 else 0.05,
                       "reward": 0.2, "baseline": 0.2})
        assert not caplog.records

    def test_silent_when_mean_is_mild(self, caplog):
        _, check = self._detector()
        with caplog.at_level("WARNING",
                             logger="cst_captioning_tpu.train"):
            for _ in range(6):
                check({"advantage": -0.01, "reward": 0.2, "baseline": 0.21})
        assert not caplog.records

    def test_ignores_xe_metrics(self, caplog):
        _, check = self._detector()
        with caplog.at_level("WARNING",
                             logger="cst_captioning_tpu.train"):
            for _ in range(8):
                check({"loss": 4.2})
        assert not caplog.records

    def test_one_early_noise_positive_only_delays_detection(self, caplog):
        obj, check = self._detector()
        with caplog.at_level("WARNING", logger="cst_captioning_tpu.train"):
            check({"advantage": 0.001, "reward": 0.2, "baseline": 0.2})
            for _ in range(5):  # window slides past the noise positive
                check({"advantage": -0.2, "reward": 0.1, "baseline": 0.3})
        assert any("advantage has been negative" in r.message
                   for r in caplog.records)
