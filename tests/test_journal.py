"""Durable intake journal (ISSUE 20): exactly-once across supervisor death.

Fast slice (tier-1, lock-sanitizer armed, NO jax import — the journal is
pure host code like the supervisor it serves):
- write/scan round-trip: accepts, chunk marks, terminals survive a
  close + reopen; every open starts a FRESH segment so recovery
  evidence stays byte-frozen;
- THE torn-tail sweep: the active segment truncated at EVERY byte
  boundary of its final record — a SEALED record (checksummed +
  newline-terminated) is never dropped and never double-applied, and
  the scan never crashes;
- segment rotation + compaction bound disk while preserving the exact
  recoverable state (terminals retire their accept/mark entries);
- duplicate-id suppression through the supervisor: a resubmit of an
  already-terminal idempotency key is answered from the record with
  ``idempotent: true`` and ZERO decode work; a duplicate of an OPEN
  key attaches the new channel and catches it up from the journaled
  marks past ``have_seq``;
- the in-process supervisor-death drill against the strict FakeChild
  harness (tests/test_supervisor.py): storm streams, abandon the
  supervisor mid-stream WITHOUT drain (the SIGKILL analogue), rebuild
  on the same journal dir, replay — every request answered exactly
  once, captions bit-identical, chunk seqs contiguous across the
  crash, arrival clocks/TTLs preserved via the journal's wall clock;
- opts flags/env fallbacks/validators, serve_report's journal rows +
  exit-1 gates, fleet_report's journal coverage cross-check.

The real-subprocess SIGKILL-the-SUPERVISOR drill
(``scripts/serve_supervisor.py --journal_probe``) is marked ``slow``
and runs via ``make journal-chaos``.
"""

import json
import os
import subprocess
import sys

import pytest

from cst_captioning_tpu.serving.journal import (
    JOURNAL_SCHEMA,
    IntakeJournal,
    _encode,
    list_segments,
    scan_dir,
)

from test_supervisor import (  # noqa: F401  (same-dir test harness)
    REPO,
    FakeChild,
    FakeClock,
    _run_report,
    _sup_record,
    build_sup,
    tick_until,
)


@pytest.fixture(autouse=True)
def _lock_sanitizer(monkeypatch, tmp_path):
    """Sanitizer-armed like the supervisor slice: the journal's one
    declared lock (serving.journal.state) is re-validated against the
    LOCK_ORDER under every drill in this file."""
    from cst_captioning_tpu.analysis import locksan

    receipt = tmp_path / "locksan_violation.json"
    monkeypatch.setenv(locksan.ENV_FLAG, "1")
    monkeypatch.setenv(locksan.ENV_RECEIPT, str(receipt))
    before = len(locksan.violations())
    yield
    after = locksan.violations()
    assert len(after) == before, f"lock-order violations: {after[before:]}"
    assert not receipt.exists(), (
        f"lock sanitizer receipt from a child process: "
        f"{receipt.read_text()}")


# -- write/scan round-trip -------------------------------------------------


def _storm(j):
    """One deterministic record mix: k0 terminal, k1 open with a mark,
    k2 terminal (the FINAL record in the segment)."""
    j.accept("k0", "c0", "v0", stream=False, ttl_ms=None, no_cache=False,
             arrival_wall=500.0)
    j.terminal("k0", {"id": "c0", "video_id": "v0",
                      "caption": FakeChild.caption_for("v0")})
    j.accept("k1", "c1", "v1", stream=True, ttl_ms=60000.0,
             no_cache=False, arrival_wall=501.0)
    j.mark("k1", 0, [11, 12], "w11 w12", 2)
    j.accept("k2", "c2", "v2", stream=False, ttl_ms=None, no_cache=False,
             arrival_wall=502.0)
    j.terminal("k2", _TAIL_RESP)


#: The exact final record _storm appends — byte length computed at
#: collection time so the torn-tail sweep can parametrize over every
#: byte boundary of it (the encoding is canonical: sorted keys,
#: schema-stamped, checksum-framed, newline-terminated).
_TAIL_RESP = {"id": "c2", "video_id": "v2",
              "caption": FakeChild.caption_for("v2")}
_TAIL_REC = {"kind": "term", "key": "k2", "resp": dict(_TAIL_RESP),
             "schema": JOURNAL_SCHEMA}
_TAIL_BYTES = _encode(_TAIL_REC)


def test_roundtrip_survives_close_and_reopen(tmp_path):
    root = str(tmp_path / "journal")
    j1 = IntakeJournal(root)
    _storm(j1)
    hw = j1.high_water()
    assert hw["segment"] == "seg-00000001.wal"
    assert hw["offset"] == os.path.getsize(os.path.join(root,
                                                        hw["segment"]))
    st = j1.stats()
    assert st["appends"] == st["fsyncs"] == 6
    assert st["open"] == 1 and st["terminals"] == 2
    j1.close()

    j2 = IntakeJournal(root)
    rec = j2.recovery
    assert set(rec.accepts) == {"k0", "k1", "k2"}
    assert set(rec.terminals) == {"k0", "k2"}
    assert [m["seq"] for m in rec.marks["k1"]] == [0]
    assert rec.torn_records == 0
    # The open request carries everything replay needs, verbatim.
    (open_req,) = j2.open_requests()
    assert open_req["key"] == "k1" and open_req["stream"] is True
    assert open_req["ttl_ms"] == 60000.0
    assert open_req["arrival_wall"] == 501.0
    # Recovered terminals answer duplicates with zero decode.
    assert j2.terminal_for("k0")["caption"] == FakeChild.caption_for("v0")
    assert j2.terminal_for("k1") is None
    # Every open starts a FRESH segment: the crash evidence is frozen.
    assert j2.high_water()["segment"] == "seg-00000002.wal"
    assert j2.stats()["recovered_open"] == 1
    assert j2.stats()["recovered_terminals"] == 2
    j2.close()


def test_scan_dir_is_read_only(tmp_path):
    root = str(tmp_path / "journal")
    j = IntakeJournal(root)
    _storm(j)
    j.close()
    before = sorted(os.listdir(root))
    rec = scan_dir(root)
    assert sorted(os.listdir(root)) == before   # no new segment
    assert set(rec.terminals) == {"k0", "k2"}
    assert rec.segments_scanned == 1
    assert scan_dir(str(tmp_path / "nowhere")).records == 0


# -- THE torn-tail sweep ---------------------------------------------------


@pytest.mark.parametrize("keep", range(len(_TAIL_BYTES)))
def test_torn_tail_at_every_byte_boundary(tmp_path, keep):
    """Truncate the segment mid-way through its FINAL record at every
    byte boundary: the torn record is dropped (counted honestly), every
    SEALED record survives exactly once, and the scan never crashes.
    ``keep=0`` is the clean-cut case — the file ends at the previous
    record's newline, so nothing is torn at all."""
    root = str(tmp_path / "journal")
    j = IntakeJournal(root)
    _storm(j)
    j.close()
    seg = os.path.join(root, "seg-00000001.wal")
    with open(seg, "rb") as f:
        data = f.read()
    # Sanity: the on-disk tail is byte-for-byte the record this sweep
    # was parametrized against (guards the sweep against encode drift).
    assert data.endswith(_TAIL_BYTES)
    with open(seg, "r+b") as f:
        f.truncate(len(data) - len(_TAIL_BYTES) + keep)

    rec = scan_dir(root)
    # Sealed records: never dropped, never double-applied.
    assert set(rec.accepts) == {"k0", "k1", "k2"}
    assert set(rec.terminals) == {"k0"}      # k2's terminal was torn
    assert [m["seq"] for m in rec.marks["k1"]] == [0]
    assert rec.records == 5
    assert rec.torn_records == (0 if keep == 0 else 1)
    assert {r["key"] for r in rec.open_requests()} == {"k1", "k2"}

    # A journal reopened over the torn dir recovers identically and
    # appends into a FRESH segment — never after the torn bytes.
    j2 = IntakeJournal(root)
    assert j2.stats()["torn_records"] == rec.torn_records
    assert j2.is_open("k2")
    j2.terminal("k2", _TAIL_RESP)            # re-answer lands sealed
    j2.close()
    assert os.path.getsize(seg) == len(data) - len(_TAIL_BYTES) + keep
    assert set(scan_dir(root).terminals) == {"k0", "k2"}


# -- rotation + compaction -------------------------------------------------


def test_rotation_compacts_and_bounds_disk(tmp_path):
    root = str(tmp_path / "journal")
    # segment_bytes=1: every append seals the segment and compacts.
    j = IntakeJournal(root, segment_bytes=1, compact=True)
    for i in range(6):
        j.accept(f"k{i}", f"c{i}", f"v{i}", stream=False, ttl_ms=None,
                 no_cache=False)
        j.terminal(f"k{i}", {"id": f"c{i}", "video_id": f"v{i}",
                             "caption": FakeChild.caption_for(f"v{i}")})
    j.accept("kopen", "co", "v7", stream=True, ttl_ms=None,
             no_cache=False)
    j.mark("kopen", 0, [71, 72], "w71 w72", 2)
    st = j.stats()
    assert st["rotations"] >= 6 and st["compactions"] >= 6
    j.close()
    # Disk is bounded: one compact file + the few live segments after
    # it — never the 14 segments the appends sealed.
    names = list_segments(root)
    assert len(names) <= 3 and names[0].startswith("compact-")
    # ...and the compacted state is EXACTLY the recoverable state:
    # terminals retired their accept/mark entries, the open request
    # kept its accept + marks.
    rec = scan_dir(root)
    assert set(rec.terminals) == {f"k{i}" for i in range(6)}
    assert set(rec.open_requests()[0]["key"]) <= set("kopen")
    assert [m["tokens"] for m in rec.marks["kopen"]] == [[71, 72]]
    assert rec.torn_records == 0

    # Forensic mode: compaction off keeps every sealed segment.
    root2 = str(tmp_path / "forensic")
    j2 = IntakeJournal(root2, segment_bytes=1, compact=False)
    for i in range(4):
        j2.accept(f"k{i}", f"c{i}", f"v{i}", stream=False, ttl_ms=None,
                  no_cache=False)
    j2.close()
    assert len(list_segments(root2)) == 5    # 4 sealed + the active
    assert set(scan_dir(root2).accepts) == {f"k{i}" for i in range(4)}


# -- duplicate-id suppression through the supervisor -----------------------


def test_duplicate_terminal_answered_idempotent_zero_decode(tmp_path):
    j = IntakeJournal(str(tmp_path / "journal"))
    sup, children, _ = build_sup(tmp_path / "sup", 1, journal=j)
    got = []
    sup.submit("a", "v1", respond=got.append, idem="kA")
    tick_until(sup, lambda: got)
    assert got[-1]["caption"] == FakeChild.caption_for("v1")
    jobs_before = len(children[0].sent)
    reqs_before = sup.supervisor_counters()["sup_requests"]

    dup = []
    sup.submit("b", "v1", respond=dup.append, idem="kA")
    # Answered synchronously from the record: the id is the
    # RESUBMITTER's, the caption the journaled terminal's, and no
    # child saw any work — zero decode, sup_requests untouched.
    assert dup[-1]["id"] == "b" and dup[-1]["idempotent"] is True
    assert dup[-1]["caption"] == FakeChild.caption_for("v1")
    assert len(children[0].sent) == jobs_before
    c = sup.supervisor_counters()
    assert c["sup_requests"] == reqs_before
    assert c["sup_journal_dup_hits"] == 1

    # No idem field -> the "<id>|<video_id>" default key dedupes too.
    got2, dup2 = [], []
    sup.submit("c", "v2", respond=got2.append)
    tick_until(sup, lambda: got2)
    sup.submit("c", "v2", respond=dup2.append)
    assert dup2[-1]["idempotent"] is True
    assert sup.supervisor_counters()["sup_journal_dup_hits"] == 2


def test_duplicate_open_stream_attaches_and_catches_up(tmp_path):
    j = IntakeJournal(str(tmp_path / "journal"))
    sup, children, _ = build_sup(tmp_path / "sup", 1, journal=j)
    got1, got2 = [], []
    sup.submit("a", "v1", respond=got1.append, stream=True, idem="kS")
    sup.tick()
    sup.tick()                        # chunks seq 0, 1 to channel 1
    assert [o["seq"] for o in got1] == [0, 1]

    # A reconnect with no have_seq is caught up from ALL journaled
    # marks, synchronously, then adopts the live tail.
    sup.submit("a2", "v1", respond=got2.append, stream=True, idem="kS")
    assert [o["seq"] for o in got2] == [0, 1]
    assert got2[0]["tokens"] == FakeChild.tokens_for("v1")[:2]
    assert sup.supervisor_counters()["sup_journal_attached"] == 1
    n1 = len(got1)
    tick_until(sup, lambda: any(o.get("final") for o in got2))
    assert len(got1) == n1            # the old channel went quiet
    fin = got2[-1]
    assert fin["caption"] == FakeChild.caption_for("v1")
    toks = [t for o in got2 if not o.get("final") for t in o["tokens"]]
    assert toks == FakeChild.tokens_for("v1")   # every token ONCE

    # A reconnect that already HAS seq<=floor only gets the marks past
    # its watermark.
    got3, got4 = [], []
    sup.submit("b", "v3", respond=got3.append, stream=True, idem="kT")
    sup.tick()
    sup.tick()
    sup.submit("b2", "v3", respond=got4.append, stream=True, idem="kT",
               have_seq=0)
    assert [o["seq"] for o in got4] == [1]


# -- the in-process supervisor-death drill ---------------------------------


def test_supervisor_death_replay_exactly_once_prefix_consistent(tmp_path):
    """SIGKILL analogue: abandon supervisor+journal WITHOUT drain or
    close mid-stream (every journal append was fsync'd, so the on-disk
    state is exactly what a SIGKILL leaves), rebuild on the same dir,
    replay, reattach — exactly-once, bit-identical, prefix-consistent,
    arrival clocks rebased through the journal's wall clock."""
    jdir = str(tmp_path / "journal")
    wall = FakeClock(500.0)
    j1 = IntakeJournal(jdir, wall=wall)
    sup1, _, _ = build_sup(tmp_path / "a", 2, journal=j1)
    pre = {}
    # One request runs to terminal BEFORE the death...
    done = []
    sup1.submit("q0", "v0", respond=done.append, stream=True, idem="k0")
    tick_until(sup1, lambda: any(o.get("final") for o in done))
    # ...then a storm of streams gets exactly 2 chunks each and DIES.
    for i in (1, 2, 3):
        pre[i] = []
        sup1.submit(f"q{i}", f"v{i}", respond=pre[i].append, stream=True,
                    idem=f"k{i}",
                    deadline_ms=(60000.0 if i == 1 else None))
    sup1.tick()
    sup1.tick()
    for i in (1, 2, 3):
        assert [o["seq"] for o in pre[i]] == [0, 1]
    # No drain, no close: sup1/j1 are simply never touched again.

    wall.advance(30.0)                       # 30s of process death
    clock2 = FakeClock(200.0)
    j2 = IntakeJournal(jdir, wall=wall)
    sup2, children2, _ = build_sup(tmp_path / "b", 2, journal=j2,
                                   clock=clock2)
    ledger = sup2.replay_journal()
    assert ledger["enabled"] and ledger["torn_records"] == 0
    assert {r["key"] for r in ledger["replayed"]} == {"k1", "k2", "k3"}
    assert ledger["recovered_terminals"] == 1
    for r in ledger["replayed"]:             # watermark primed from
        assert r["seq_out"] == 2 and r["sent_tokens"] == 4   # the marks
    c = sup2.supervisor_counters()
    assert c["sup_journal_replayed"] == 3 and c["sup_journal_torn"] == 0
    # Arrival rebased into THIS incarnation's clock domain: the 30s the
    # process was dead counts against the TTL, which itself survives.
    pr1 = sup2._inflight_keys["k1"]
    assert pr1.arrival == pytest.approx(clock2() - 30.0)
    assert pr1.ttl_ms == 60000.0

    # Clients resubmit the SAME ids/keys, holding seqs 0-1 already.
    post = {}
    for i in (1, 2, 3):
        post[i] = []
        sup2.submit(f"q{i}", f"v{i}", respond=post[i].append,
                    stream=True, idem=f"k{i}", have_seq=1)
    assert sup2.supervisor_counters()["sup_journal_attached"] == 3
    tick_until(sup2, lambda: all(
        any(o.get("final") for o in post[i]) for i in (1, 2, 3)))

    for i in (1, 2, 3):
        vid = f"v{i}"
        both = pre[i] + post[i]
        finals = [o for o in both if o.get("final")]
        # Exactly once, bit-identical to the fault-free caption.
        assert len(finals) == 1
        assert finals[0]["caption"] == FakeChild.caption_for(vid)
        assert "idempotent" not in finals[0]
        # Prefix-consistent across the crash: seqs contiguous, every
        # token exactly once, the continuation starting precisely at
        # the journaled watermark.
        chunks = [o for o in both if not o.get("final")]
        assert [o["seq"] for o in chunks] == [0, 1, 2]
        toks = [t for o in chunks for t in o["tokens"]]
        assert toks == FakeChild.tokens_for(vid)
        assert post[i][0]["tokens"] == FakeChild.tokens_for(vid)[4:6]

    # The pre-death terminal answers its duplicate from the record.
    dup = []
    sup2.submit("q0", "v0", respond=dup.append, stream=True, idem="k0")
    assert dup[-1]["idempotent"] is True
    assert dup[-1]["caption"] == FakeChild.caption_for("v0")
    assert sup2.supervisor_counters()["sup_journal_dup_hits"] == 1
    assert not any(c.jobs for c in children2)
    # Ledger accounting: replayed + recovered == every accepted key,
    # and nothing is left open once the storm drains.
    assert len(ledger["replayed"]) + ledger["recovered_terminals"] == 4
    st = j2.stats()
    assert st["open"] == 0 and st["recovered_open"] == 3
    assert ledger["high_water"]["segment"] == "seg-00000002.wal"
    j2.close()


# -- opts ------------------------------------------------------------------


def test_journal_flags_env_fallback_and_validation(monkeypatch):
    from cst_captioning_tpu.opts import parse_opts

    ns = parse_opts(["--serve_demo", "1"])
    assert ns.journal_dir is None            # conftest blanks the envs
    assert ns.journal_segment_bytes == 1048576
    assert ns.journal_compact == 1

    monkeypatch.setenv("CST_JOURNAL_DIR", "/tmp/j")
    monkeypatch.setenv("CST_JOURNAL_SEGMENT_BYTES", "4096")
    monkeypatch.setenv("CST_JOURNAL_COMPACT", "0")
    ns = parse_opts(["--serve_demo", "1"])
    assert ns.journal_dir == "/tmp/j"
    assert ns.journal_segment_bytes == 4096
    assert ns.journal_compact == 0
    # Explicit flag beats the environment.
    ns = parse_opts(["--serve_demo", "1", "--journal_dir", "/tmp/k",
                     "--journal_segment_bytes", "512"])
    assert ns.journal_dir == "/tmp/k"
    assert ns.journal_segment_bytes == 512

    with pytest.raises(SystemExit):
        parse_opts(["--journal_segment_bytes", "0"])    # needs >= 1
    with pytest.raises(SystemExit):
        parse_opts(["--journal_compact", "-1"])         # needs >= 0
    monkeypatch.setenv("CST_JOURNAL_SEGMENT_BYTES", "-5")
    with pytest.raises(SystemExit):
        parse_opts(["--serve_demo", "1"])   # env values validated too


# -- serve_report ----------------------------------------------------------


def _journal_record(**over):
    rec = _sup_record()
    rec["journal"] = {
        "enabled": True, "dir": "/tmp/j/journal",
        "killed_mid_storm": True, "terminals_before_kill": 2,
        "streams_in_flight_at_kill": 4, "replayed": 10,
        "recovered_terminals": 2, "replay_accounted": True,
        "exactly_once": True, "idempotent_answers": 2,
        "dup_suppressed": True, "dup_hits": 3, "attached": 10,
        "torn_records": 1, "torn_ok": True, "segments_scanned": 2,
        "high_water": {"segment": "seg-00000002.wal", "offset": 4096},
        "open_at_exit": 0, "relaunch_rc": 75, "clean_exit": True,
    }
    rec["journal"].update(over)
    return rec


def test_serve_report_renders_journal_rows(tmp_path):
    proc = _run_report(_journal_record(), tmp_path)
    assert proc.returncode == 0, proc.stderr
    for row in ("journal drill", "journal replay",
                "journal exactly-once", "journal torn tail"):
        assert row in proc.stdout
    assert "killed_mid_storm=True" in proc.stdout
    assert "seg-00000002.wal@4096" in proc.stdout


def test_serve_report_gates_on_replay_accounting(tmp_path):
    for over in ({"replay_accounted": False}, {"exactly_once": False},
                 {"clean_exit": False}):
        proc = _run_report(_journal_record(**over), tmp_path)
        assert proc.returncode == 1, over
        assert "journal replay accounting broken" in proc.stderr, over


def test_serve_report_gates_on_dup_suppression(tmp_path):
    proc = _run_report(_journal_record(dup_suppressed=False), tmp_path)
    assert proc.returncode == 1
    assert "duplicate-id suppression broken" in proc.stderr


def test_serve_report_gates_on_torn_tail_and_mid_storm(tmp_path):
    for over in ({"torn_ok": False}, {"killed_mid_storm": False}):
        proc = _run_report(_journal_record(**over), tmp_path)
        assert proc.returncode == 1, over
        assert "torn-tail recovery broken" in proc.stderr, over


def test_serve_report_journal_free_records_unchanged(tmp_path):
    proc = _run_report(_sup_record(), tmp_path)
    assert proc.returncode == 0, proc.stderr
    assert "journal" not in proc.stdout


# -- fleet_report coverage cross-check -------------------------------------


def _fleet_sample(seq, wall):
    return {
        "schema": 1, "kind": "fleet_sample", "seq": seq, "t": wall,
        "wall": wall, "interval_ms": 1000.0,
        "fleet": {"replicas": 2, "in_service": 2, "outstanding": 0,
                  "parked": 0, "completed": 5 * seq,
                  "latency_p50_ms": 4.0, "latency_p99_ms": 9.0},
        "children": [
            {"index": k, "state": "ok", "live": True, "restarts": 0,
             "inflight": 0, "queue_depth": 0, "latency_p50_ms": 4.0,
             "latency_p99_ms": 9.0, "compiles": 2} for k in range(2)],
    }


def _fleet_rig(tmp_path, *, answer=("k0", "k1"), hw_lie=0):
    """A run dir with healthy fleet samples, a real journal, and an
    exit snapshot whose high-water mark can be made to LIE by
    ``hw_lie`` bytes (claiming more durable bytes than exist)."""
    root = tmp_path / "run"
    root.mkdir()
    with open(root / "fleet_metrics.jsonl", "w") as f:
        for k in range(4):
            f.write(json.dumps(_fleet_sample(k + 1, 100.0 + k)) + "\n")
    j = IntakeJournal(str(root / "journal"))
    for key in ("k0", "k1"):
        j.accept(key, key, "v1", stream=False, ttl_ms=None,
                 no_cache=False)
    for key in answer:
        j.terminal(key, {"id": key, "caption": "w11"})
    stats = j.stats()
    j.close()
    stats["high_water"]["offset"] += hw_lie
    with open(root / "supervisor_exit.json", "w") as f:
        json.dump({"schema": 1, "journal": stats}, f)
    return root


def _run_fleet_report(root):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "fleet_report.py"),
         "--dir", str(root)], capture_output=True, text=True, cwd=REPO)


def test_fleet_report_journal_coverage_clean(tmp_path):
    proc = _run_fleet_report(_fleet_rig(tmp_path))
    assert proc.returncode == 0, proc.stderr
    assert "journal" in proc.stdout
    assert "2 accept(s) / 2 terminal(s)" in proc.stdout


def test_fleet_report_gates_on_journal_coverage_hole(tmp_path):
    proc = _run_fleet_report(_fleet_rig(tmp_path, answer=("k0",)))
    assert proc.returncode == 1
    assert "journal coverage hole" in proc.stderr
    assert "k1" in proc.stderr                # the vanished id, named


def test_fleet_report_gates_on_high_water_truncation(tmp_path):
    proc = _run_fleet_report(_fleet_rig(tmp_path, hw_lie=64))
    assert proc.returncode == 1
    assert "journal high-water truncated" in proc.stderr


def test_fleet_report_journal_free_runs_untouched(tmp_path):
    root = _fleet_rig(tmp_path)
    os.remove(root / "supervisor_exit.json")
    proc = _run_fleet_report(root)
    assert proc.returncode == 0, proc.stderr
    assert "journal" not in proc.stdout


# -- slow: the real-subprocess drill ---------------------------------------


@pytest.mark.slow
def test_cli_journal_probe_sigkill_supervisor_end_to_end(tmp_path):
    """THE acceptance drill through the real CLI: SIGKILL the
    SUPERVISOR (whole process group) mid-storm with streams in flight,
    relaunch on the same journal dir — every accepted request answered
    exactly once, captions bit-identical to the fault-free
    single-engine twin, stream prefixes consistent across the crash,
    the duplicate id answered from the journal, zero post-warmup
    compiles, and the record survives serve_report's gates."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    root = str(tmp_path / "supervise")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts",
                                      "serve_supervisor.py"),
         "--serve_demo", "1", "--journal_probe", "1",
         "--supervise_replicas", "2", "--serve_demo_eos_bias", "-2",
         "--decode_chunk", "2", "--beam_size", "1",
         "--slo_p99_ms", "60000", "--slo_availability", "0.5",
         "--supervise_dir", root],
        capture_output=True, text=True, timeout=900, cwd=REPO, env=env)
    assert proc.returncode == 0, proc.stderr[-4000:]
    rec = json.loads(proc.stdout.splitlines()[-1])
    jn = rec["journal"]
    assert jn["killed_mid_storm"] and jn["streams_in_flight_at_kill"] >= 1
    assert jn["exactly_once"] and jn["replay_accounted"]
    assert jn["dup_suppressed"] and jn["torn_ok"]
    assert jn["clean_exit"] and jn["open_at_exit"] == 0
    assert rec["completed"] == rec["num_requests"]
    assert rec["supervisor"]["parity_ok"]
    assert rec["recompiles_after_warmup"] == 0
    assert rec["stream"]["prefix_ok"]
    assert os.path.exists(os.path.join(root, "recovery_ledger.json"))
    assert os.path.exists(os.path.join(root, "supervisor_exit.json"))
    with open(os.path.join(root, "supervisor_exit.json")) as f:
        assert "journal" in json.load(f)
    report = _run_report(rec, tmp_path)
    assert report.returncode == 0, report.stderr
