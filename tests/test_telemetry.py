"""Unified telemetry subsystem (ISSUE 2): span tracer, metrics registry,
step phases, and the trainer wiring that threads them everywhere.

Fast, hermetic units ride tier-1; the trainer-integration tests drive a
real in-process Trainer on tiny synthetic fixtures (the
tests/test_trainer_e2e.py pattern — no subprocess drills)."""

import glob
import json
import os
import threading
import time

import numpy as np
import pytest

from cst_captioning_tpu.telemetry import (
    METRICS_SCHEMA,
    NULL_SPAN,
    STEP_PHASES,
    JsonlSink,
    MetricsRegistry,
    ScalarWriterSink,
    SpanTracer,
    StepPhases,
    Telemetry,
    caption_step_flops,
    mfu_fields,
    trace_span,
)


def load_trace_events(trace_dir):
    """All complete-span events from every part file in a trace dir,
    going through plain json.load — i.e. asserting Chrome-trace validity
    the same way Perfetto's JSON importer starts."""
    events = []
    files = sorted(glob.glob(os.path.join(str(trace_dir), "*.json")))
    for path in files:
        doc = json.load(open(path))
        assert "traceEvents" in doc, f"{path} is not a Chrome trace"
        events.extend(e for e in doc["traceEvents"] if e.get("ph") == "X")
    return events, files


class TestSpanTracer:
    def test_nested_spans_export_valid_chrome_trace(self, tmp_path):
        tr = SpanTracer(str(tmp_path))
        with tr.span("outer", step=3):
            with tr.span("inner"):
                time.sleep(0.01)
        tr.close()
        events, files = load_trace_events(tmp_path)
        assert len(files) == 1
        by_name = {e["name"]: e for e in events}
        assert set(by_name) == {"outer", "inner"}
        outer, inner = by_name["outer"], by_name["inner"]
        # µs complete events, properly nested on one thread
        assert outer["tid"] == inner["tid"]
        assert outer["ts"] <= inner["ts"]
        assert outer["ts"] + outer["dur"] >= inner["ts"] + inner["dur"]
        assert inner["dur"] >= 9_000  # the 10ms sleep, in µs
        assert outer["args"] == {"step": 3}

    def test_thread_safety_no_lost_spans(self, tmp_path):
        tr = SpanTracer(str(tmp_path))
        n_threads, n_spans = 8, 200

        def work(i):
            for _ in range(n_spans):
                with tr.span(f"t{i}"):
                    pass

        threads = [threading.Thread(target=work, args=(i,))
                   for i in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        tr.close()
        events, _ = load_trace_events(tmp_path)
        assert len(events) == n_threads * n_spans
        # no thread's spans were lost or cross-attributed (tids themselves
        # can be reused by the OS once a thread exits, so count by name)
        by_name = {}
        for e in events:
            by_name[e["name"]] = by_name.get(e["name"], 0) + 1
        assert by_name == {f"t{i}": n_spans for i in range(n_threads)}

    def test_rotation_bounds_memory_and_keeps_all_events(self, tmp_path):
        tr = SpanTracer(str(tmp_path), max_buffered_events=1000)
        for _ in range(2500):
            with tr.span("s"):
                pass
        tr.close()
        events, files = load_trace_events(tmp_path)
        assert len(files) >= 2, "buffer never rotated to a part file"
        assert len(events) == 2500, "rotation lost events"

    def test_record_after_close_is_dropped_not_raised(self, tmp_path):
        tr = SpanTracer(str(tmp_path))
        span = tr.span("late")
        tr.close()
        with span:  # a straggler prefetch thread finishing after shutdown
            pass

    def test_disabled_hook_is_shared_noop(self):
        # The zero-overhead contract: no tracer -> the ONE shared no-op
        # object, not a fresh allocation per hook.
        assert trace_span(None, "x") is NULL_SPAN
        assert trace_span(None, "y") is NULL_SPAN
        with trace_span(None, "z"):
            pass


class TestStepPhases:
    def test_nested_phase_time_is_exclusive(self):
        ph = StepPhases()
        with ph.phase("compute"):
            time.sleep(0.01)
            with ph.phase("score"):
                time.sleep(0.03)
        ms = ph.drain_ms(1)
        assert ms["score_ms"] >= 25.0
        # compute excludes the nested score: it must be well under the
        # combined 40ms, not double-counted.
        assert ms["compute_ms"] < ms["score_ms"]

    def test_drain_always_emits_canonical_phases_and_resets(self):
        ph = StepPhases()
        with ph.phase("data_wait"):
            pass
        ms = ph.drain_ms(2)
        assert set(ms) == {f"{p}_ms" for p in STEP_PHASES}
        assert ph.drain_ms(1)["data_wait_ms"] == 0.0  # reset

    def test_per_step_mean(self):
        ph = StepPhases()
        for _ in range(4):
            with ph.phase("compute"):
                time.sleep(0.005)
        ms = ph.drain_ms(4)
        assert 3.0 <= ms["compute_ms"] <= 50.0  # ~5ms/step, slop for CI


class _FakeSink:
    def __init__(self):
        self.records = []
        self.flushes = []
        self.closed = False

    def log_step(self, step, scope, metrics, wall_time):
        self.records.append((step, scope, dict(metrics)))

    def flush(self, fsync=False):
        self.flushes.append(fsync)

    def close(self):
        self.closed = True


class TestMetricsRegistry:
    def test_fanout_to_every_sink(self, tmp_path):
        reg = MetricsRegistry()
        a, b = _FakeSink(), _FakeSink()
        reg.add_sink(a)
        reg.add_sink(b)
        reg.log_step(3, "train", {"loss": 1.25})
        reg.flush(fsync=True)
        assert a.records == b.records == [(3, "train", {"loss": 1.25})]
        assert a.flushes == [True]
        reg.close()
        assert a.closed and b.closed

    def test_jsonl_sink_schema2_records(self, tmp_path):
        path = str(tmp_path / "metrics.jsonl")
        reg = MetricsRegistry()
        reg.add_sink(JsonlSink(path))
        reg.log_step(1, "train", {"loss": 2.0})
        reg.log_step(2, "val", {"CIDEr": 0.5})
        reg.close()
        recs = [json.loads(l) for l in open(path)]
        assert [r["schema"] for r in recs] == [METRICS_SCHEMA] * 2
        assert recs[0]["scope"] == "train" and recs[0]["loss"] == 2.0
        assert recs[1]["scope"] == "val" and recs[1]["CIDEr"] == 0.5
        assert all("time" in r for r in recs)

    def test_counters_gauges_histograms_in_snapshot(self):
        reg = MetricsRegistry()
        reg.inc("fault_firings")
        reg.inc("fault_firings", 2)
        reg.set_gauge("mfu_pct", 41.5)
        for v in (1.0, 3.0, 5.0):
            reg.observe("probe_latency_s", v)
        snap = reg.snapshot()
        assert snap["schema"] == METRICS_SCHEMA
        assert snap["counters"]["fault_firings"] == 3
        assert snap["gauges"]["mfu_pct"] == 41.5
        h = snap["histograms"]["probe_latency_s"]
        assert (h["count"], h["min"], h["max"], h["mean"]) == (3, 1.0, 5.0, 3.0)

    def test_heartbeat_payload_carries_last_step_and_counters(self):
        reg = MetricsRegistry()
        reg.inc("divergence_guard_trips")
        reg.log_step(7, "train", {"loss": 1.0, "data_wait_ms": 0.4})
        hb = reg.heartbeat_payload()
        assert hb["last_train"]["step"] == 7
        assert hb["last_train"]["data_wait_ms"] == 0.4
        assert hb["counters"]["divergence_guard_trips"] == 1

    def test_write_snapshot(self, tmp_path):
        reg = MetricsRegistry()
        reg.inc("checkpoints_saved")
        path = str(tmp_path / "telemetry.json")
        reg.write_snapshot(path)
        assert json.load(open(path))["counters"]["checkpoints_saved"] == 1

    def test_preemption_counters_in_exit_snapshot(self, tmp_path):
        """The preemption audit trail (ISSUE 4): counters DECLARED at 0
        (so 'armed, nothing happened' is visible) plus the signal-to-exit
        gauge all land in the telemetry.json exit snapshot the trainer
        writes on the preempt path."""
        reg = MetricsRegistry()
        reg.declare("preempt_signals", "preempt_saves")
        path = str(tmp_path / "telemetry.json")
        reg.write_snapshot(path)
        armed = json.load(open(path))
        assert armed["counters"]["preempt_signals"] == 0
        assert armed["counters"]["preempt_saves"] == 0

        reg.inc("preempt_signals", 2)
        reg.inc("preempt_saves")
        reg.set_gauge("preempt_exit_ms", 812.5)
        reg.write_snapshot(path)
        fired = json.load(open(path))
        assert fired["counters"]["preempt_signals"] == 2
        assert fired["counters"]["preempt_saves"] == 1
        assert fired["gauges"]["preempt_exit_ms"] == 812.5
        # The watchdog heartbeat carries the counters too.
        assert reg.heartbeat_payload()["counters"]["preempt_signals"] == 2

    def test_scalarwriter_sink_skips_non_scalars(self):
        class FakeWriter:
            def __init__(self):
                self.scalars = []

            def add_scalar(self, tag, value, step):
                self.scalars.append((tag, value, step))

        w = FakeWriter()
        sink = ScalarWriterSink(w)
        sink.log_step(5, "train", {"loss": 1.0, "mfu_pct": None,
                                   "flag": True}, 0.0)
        assert w.scalars == [("train/loss", 1.0, 5)]

    def test_thread_safe_counters(self):
        reg = MetricsRegistry()

        def work():
            for _ in range(1000):
                reg.inc("n")

        threads = [threading.Thread(target=work) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert reg.counter("n") == 8000


class TestTelemetryFacade:
    def test_defaults_are_fully_disarmed(self):
        from cst_captioning_tpu.opts import parse_opts

        tel = Telemetry.from_opts(parse_opts([]))
        assert tel.tracer is None and tel.phases is None
        # every hook resolves to the shared no-op: nothing to allocate
        assert tel.span("x") is NULL_SPAN
        assert tel.phase("x") is NULL_SPAN

    def test_trace_dir_arms_tracer_and_phases(self, tmp_path):
        from cst_captioning_tpu.opts import parse_opts

        tel = Telemetry.from_opts(
            parse_opts(["--trace_dir", str(tmp_path / "tr")]))
        assert tel.tracer is not None and tel.phases is not None
        tel.close()

    def test_step_timing_alone_arms_phases_without_tracing(self):
        from cst_captioning_tpu.opts import parse_opts

        tel = Telemetry.from_opts(parse_opts(["--step_timing", "1"]))
        assert tel.tracer is None and tel.phases is not None

    def test_close_idempotent_and_writes_snapshot(self, tmp_path):
        tel = Telemetry(tracer=SpanTracer(str(tmp_path / "tr")))
        tel.inc("fault_firings")
        snap = str(tmp_path / "telemetry.json")
        tel.snapshot_path = snap
        tel.close()
        tel.close()  # idempotent (atexit + finally double cover)
        assert json.load(open(snap))["counters"]["fault_firings"] == 1


class TestScalarWriterLifecycle:
    def test_tolerates_use_after_close(self, tmp_path):
        pytest.importorskip("tensorboard")
        from cst_captioning_tpu.utils.tb import ScalarWriter

        with ScalarWriter(str(tmp_path)) as w:
            w.add_scalar("train/loss", 1.0, 1)
        # closed by the context manager: all of these must be no-ops
        w.add_scalar("train/loss", 2.0, 2)
        w.flush()
        w.close()


class TestResilienceCounters:
    def test_fault_plan_counts_firings(self):
        from cst_captioning_tpu.resilience.faults import FaultPlan

        reg = MetricsRegistry()
        plan = FaultPlan.parse("nan_grad@step=5*2").bind_metrics(reg)
        assert plan.fire("nan_grad", 5)
        assert not plan.fire("nan_grad", 5)  # replay: consumed, not counted
        assert plan.fire("nan_grad", 6)
        assert reg.counter("fault_firings") == 2
        assert reg.counter("fault_nan_grad") == 2

    def test_guard_counts_trips_and_rollbacks(self):
        from cst_captioning_tpu.resilience.guard import DivergenceGuard

        reg = MetricsRegistry()
        g = DivergenceGuard(max_bad=2, max_rollbacks=2, lag=0, metrics=reg)
        g.observe(0, np.asarray(1.0))
        g.observe(1, np.asarray(1.0))
        assert g.poll()
        g.note_rollback()
        assert reg.counter("divergence_guard_trips") == 2
        assert reg.counter("divergence_guard_rollbacks") == 1

    def test_loader_retries_counted(self):
        from cst_captioning_tpu.data.loader import prefetch_to_device
        from test_resilience import _FlakySource

        tel = Telemetry()
        it = prefetch_to_device(_FlakySource(fail_times=2), size=1,
                                retries=3, retry_backoff_s=0.001,
                                telemetry=tel)
        next(it)
        it.close()
        assert tel.registry.counter("loader_retries") == 2


# -- trainer integration (in-process, tiny synthetic fixtures) -------------

@pytest.fixture(scope="module")
def data(tmp_path_factory):
    from cst_captioning_tpu.data.synthetic import SyntheticSpec, generate

    root = str(tmp_path_factory.mktemp("telemetry"))
    spec = SyntheticSpec(num_videos=4, captions_per_video=4, max_len=10,
                         feat_dims=(12, 6), feat_times=(3, 1))
    return generate(root, "train", spec)


def run_trainer(data, ckpt_dir, **over):
    from cst_captioning_tpu.opts import parse_opts
    from cst_captioning_tpu.training.trainer import Trainer

    args = {
        "--train_feat_h5": json.loads(data["feat_h5"]),
        "--train_label_h5": [data["label_h5"]],
        "--train_info_json": [data["info_json"]],
        "--train_cocofmt_file": [data["cocofmt_json"]],
        "--checkpoint_path": [ckpt_dir],
        "--batch_size": ["2"], "--seq_per_img": ["2"],
        "--rnn_size": ["16"], "--input_encoding_size": ["16"],
        "--att_size": ["16"], "--drop_prob": ["0.0"],
        "--max_epochs": ["2"], "--learning_rate": ["0.01"],
        "--max_length": ["10"], "--log_every": ["1"], "--seed": ["0"],
    }
    args.update({k: [str(x) for x in v] for k, v in over.items()})
    flat = []
    for k, vals in args.items():
        flat.append(k)
        flat.extend(vals)
    trainer = Trainer(parse_opts(flat))
    try:
        trainer.train()
    finally:
        trainer.close()
    return trainer


@pytest.mark.e2e
@pytest.mark.chaos
def test_traced_chaos_run_produces_full_telemetry(data, tmp_path):
    """The acceptance scenario, in-process: a traced XE run with an
    injected nan_grad fault must leave (a) a loadable Chrome trace with
    the step-phase spans, (b) schema-2 metrics.jsonl records carrying
    per-phase *_ms + mfu fields, (c) an exit telemetry.json whose
    counters show the guard tripping, and (d) a heartbeat file enriched
    from the registry."""
    ck = str(tmp_path / "xe")
    trace = str(tmp_path / "trace")
    run_trainer(data, ck, **{"--trace_dir": [trace],
                             "--fault_plan": ["nan_grad@step=1"],
                             "--wedge_timeout": ["300"]})

    # (a) Chrome trace loads and has the phase + component spans
    events, files = load_trace_events(trace)
    names = {e["name"] for e in events}
    assert {"data_wait", "compute", "ckpt", "ckpt_commit",
            "prefetch_assemble"} <= names, names

    # (b) metrics.jsonl: schema 2 with phase gauges + mfu fields
    recs = [json.loads(l) for l in open(os.path.join(ck, "metrics.jsonl"))]
    train_recs = [r for r in recs if r["scope"] == "train"]
    assert train_recs, "no train records"
    assert all(r["schema"] == 2 for r in recs)
    gauged = [r for r in train_recs if "data_wait_ms" in r]
    assert gauged, "phase gauges never reached metrics.jsonl"
    for key in ("data_wait_ms", "compute_ms", "score_ms", "ckpt_ms",
                "mfu_pct", "achieved_tflops"):
        assert key in gauged[-1], f"missing {key}"
    assert gauged[-1]["mfu_pct"] is None  # CPU: no TPU peak to compare to

    # (c) exit snapshot: the drill is auditable
    tel = json.load(open(os.path.join(ck, "telemetry.json")))
    assert tel["counters"]["divergence_guard_trips"] >= 1
    assert tel["counters"]["fault_firings"] == 1
    assert tel["counters"]["fault_nan_grad"] == 1
    assert tel["counters"]["checkpoints_saved"] >= 1

    # (d) heartbeat: written by the armed watchdog, registry-enriched
    hb = json.load(open(os.path.join(ck, "heartbeat.json")))
    assert hb["pid"] == os.getpid()
    assert hb["counters"]["fault_firings"] == 1
    assert hb["last_train"]["step"] >= 1


@pytest.mark.e2e
def test_traced_cst_host_run_shows_score_phase(data, tmp_path):
    """Host-reward CST is the path with a real host scoring gap: the
    trace must show `score` (inside the RewardComputer) and `fetch_wait`
    (the pipeline's device fetch), and the score_ms gauge must be
    nonzero in at least one logged interval."""
    ck = str(tmp_path / "cst")
    trace = str(tmp_path / "trace")
    run_trainer(data, ck, **{"--trace_dir": [trace],
                             "--use_rl": ["1"],
                             "--rl_baseline": ["greedy"],
                             "--device_rewards": ["0"],
                             "--overlap_rewards": ["1"],
                             "--max_epochs": ["1"],
                             "--learning_rate": ["0.0005"]})
    events, _ = load_trace_events(trace)
    names = {e["name"] for e in events}
    assert {"score", "fetch_wait", "compute", "data_wait"} <= names, names
    recs = [json.loads(l) for l in open(os.path.join(ck, "metrics.jsonl"))]
    score_ms = [r.get("score_ms") for r in recs
                if r["scope"] == "train" and "score_ms" in r]
    assert score_ms and max(score_ms) > 0.0, score_ms


@pytest.mark.e2e
def test_untraced_run_has_zero_telemetry_surface(data, tmp_path):
    """Telemetry flags unset: no tracer, no phase timer (the loop hooks
    reduce to one is-None check), no trace files, no *_ms keys — but the
    registry still exists, metrics.jsonl is schema 2, and the exit
    telemetry.json still records counters."""
    ck = str(tmp_path / "plain")
    trainer = run_trainer(data, ck)
    assert trainer._telemetry.tracer is None
    assert trainer._telemetry.phases is None
    recs = [json.loads(l) for l in open(os.path.join(ck, "metrics.jsonl"))]
    assert all(r["schema"] == 2 for r in recs)
    assert not any("data_wait_ms" in r for r in recs)
    tel = json.load(open(os.path.join(ck, "telemetry.json")))
    assert tel["counters"].get("divergence_guard_trips", 0) == 0
    assert tel["counters"]["checkpoints_saved"] >= 1
