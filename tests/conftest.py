"""Test harness config: force a local 8-device virtual CPU mesh.

Tests never touch the real TPU chip (driver config 1 is a CPU smoke test —
SURVEY.md §4); multi-device sharding tests run on XLA's host-platform
virtual devices.

Subtlety: this session's interpreter boots with an `.axon_site`
sitecustomize that imports jax and registers the remote-TPU "axon" PJRT
plugin *before* conftest runs, with JAX_PLATFORMS=axon and remote XLA
compilation over a tunnel.  Setting env vars here is therefore too late —
jax has already read them — so we must (a) update jax's config directly and
(b) deregister the axon backend factory so `backends()` never initializes
the tunnel client (which blocks indefinitely when the tunnel is down, and
routes every test compile through the wire even when it is up).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# XLA_FLAGS must be set before jax initializes the cpu client.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    flags = (flags + " --xla_force_host_platform_device_count=8").strip()
    os.environ["XLA_FLAGS"] = flags

from cst_captioning_tpu.utils.platform import force_cpu_platform  # noqa: E402

force_cpu_platform()

# Hermetic tuned-config resolution: neither an operator's repo-root
# TUNED_CONFIGS.json nor an exported CST_TUNED_CONFIGS may change the
# defaults the suite pins (opts.py resolves tuning records at parse time
# — PARITY.md "Tuned configs"), so this is a FORCE-assign, not a
# setdefault.  '' disables resolution; tests that exercise it point
# CST_TUNED_CONFIGS at their own tmp record via monkeypatch, and spawned
# train/eval/bench children inherit this isolation from the environment.
os.environ["CST_TUNED_CONFIGS"] = ""

# Same hermeticity for the serving engine's env knobs: an operator's
# exported bucket ladder / queue bound (opts.py resolves CST_SERVE_* as
# argparse defaults) must not change what the suite pins.  '' falls back
# to the built-in defaults; serving tests pass explicit values instead.
os.environ["CST_SERVE_BUCKETS"] = ""
os.environ["CST_SERVE_QUEUE_LIMIT"] = ""
os.environ["CST_SERVE_DEADLINE_MS"] = ""
os.environ["CST_SERVE_CACHE"] = ""
os.environ["CST_SERVE_REPLICAS"] = ""

# Process-fleet supervisor env knobs (ISSUE 16): an operator's exported
# replica count / restart budget / backoff base (opts.py resolves
# CST_SUPERVISE_* as argparse defaults) must not change what the suite
# pins.  '' falls back to the built-in defaults; supervisor tests pass
# explicit values instead.
os.environ["CST_SUPERVISE_REPLICAS"] = ""
os.environ["CST_SUPERVISE_RESTART_LIMIT"] = ""
os.environ["CST_SUPERVISE_BACKOFF_MS"] = ""

# Fleet-observability / SLO env knobs (ISSUE 17): an operator's exported
# scrape cadence or SLO targets (opts.py resolves CST_FLEET_*/CST_SLO_*
# as argparse defaults) must not change what the suite pins.  '' falls
# back to the built-in defaults; fleetobs tests pass explicit values
# instead.
os.environ["CST_FLEET_SCRAPE_MS"] = ""
os.environ["CST_SLO_P99_MS"] = ""
os.environ["CST_SLO_AVAILABILITY"] = ""
os.environ["CST_SLO_ERROR_RATE"] = ""

# Autoscaler env knobs (ISSUE 19): an operator's exported fleet bounds
# or cooldowns (opts.py resolves CST_AUTOSCALE_* as argparse defaults)
# must not change what the suite pins.  '' falls back to the built-in
# defaults; autoscale tests pass explicit values instead.
os.environ["CST_AUTOSCALE_MIN"] = ""
os.environ["CST_AUTOSCALE_MAX"] = ""
os.environ["CST_AUTOSCALE_QUEUE_HI_MS"] = ""
os.environ["CST_AUTOSCALE_UP_COOLDOWN_S"] = ""
os.environ["CST_AUTOSCALE_DOWN_COOLDOWN_S"] = ""

# Intake-journal env knobs (ISSUE 20): an operator's exported journal
# directory / segment size / compaction switch (opts.py resolves
# CST_JOURNAL_* as argparse defaults) must not change what the suite
# pins — a leaked CST_JOURNAL_DIR would silently ARM the journal in
# every spawned supervisor.  '' falls back to the built-in defaults;
# journal tests pass explicit values instead.
os.environ["CST_JOURNAL_DIR"] = ""
os.environ["CST_JOURNAL_SEGMENT_BYTES"] = ""
os.environ["CST_JOURNAL_COMPACT"] = ""

# Data-plane env knobs (ISSUE 15): an operator's exported worker count or
# shard assignment (opts.py resolves CST_LOADER_WORKERS/CST_DATA_SHARDS/
# CST_DATA_SHARD_ID as argparse defaults) must not change what the suite
# pins.  '' falls back to the built-in defaults; data-plane tests pass
# explicit values instead.
os.environ["CST_LOADER_WORKERS"] = ""
os.environ["CST_DATA_SHARDS"] = ""
os.environ["CST_DATA_SHARD_ID"] = ""

import jax  # noqa: E402

assert jax.devices()[0].platform == "cpu", (
    "tests must run on the virtual CPU mesh, got " + repr(jax.devices())
)
assert jax.device_count() >= 8, (
    "xla_force_host_platform_device_count did not take effect: "
    f"{jax.device_count()} devices"
)

# Persistent XLA compilation cache across test processes: the e2e family
# compiles many identical-HLO programs (same tiny shapes, fresh function
# objects each test), and the cache turns those recompiles into loads —
# measured 4x on test_full_pipeline (98s -> 24s).  Keyed by HLO hash, so
# it cannot go stale against code changes; JAX_COMPILATION_CACHE_DIR in
# the environment (e.g. a CI-scoped tmpdir) overrides the default.
# CACHE_DIR is imported by the subprocess-launching tests (test_bench,
# test_multiprocess_dcn) so their children share the same cache.
CACHE_DIR = os.environ.get(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.expanduser("~/.cache/cst_captioning_tpu/xla_test"),
)
try:
    os.makedirs(CACHE_DIR, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", CACHE_DIR)
    # 0.1s, not the 1.0s default: the suite's programs are mostly tiny
    # (sub-second compiles on warm XLA), so the default threshold left
    # the bulk of them recompiling every run — in this process AND in
    # every train.py/eval.py/bench child.  Loads are behavior-identical
    # (keyed by HLO hash + compile options); the env vars below are
    # inherited by every subprocess the tests spawn, so children get the
    # same cache policy without each call site re-plumbing it.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", CACHE_DIR)
    os.environ.setdefault(
        "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.1")
except Exception:  # read-only fs etc. — the cache is only an optimization
    pass
