"""Test harness config: force a local 8-device virtual CPU mesh.

Tests never touch the real TPU chip (driver config 1 is a CPU smoke test —
SURVEY.md §4); multi-device sharding tests run on XLA's host-platform
virtual devices.

Subtlety: this session's interpreter boots with an `.axon_site`
sitecustomize that imports jax and registers the remote-TPU "axon" PJRT
plugin *before* conftest runs, with JAX_PLATFORMS=axon and remote XLA
compilation over a tunnel.  Setting env vars here is therefore too late —
jax has already read them — so we must (a) update jax's config directly and
(b) deregister the axon backend factory so `backends()` never initializes
the tunnel client (which blocks indefinitely when the tunnel is down, and
routes every test compile through the wire even when it is up).
"""

import os

# Env vars still matter for any subprocess the tests spawn.
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_REMOTE_COMPILE", None)
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    flags = (flags + " --xla_force_host_platform_device_count=8").strip()
    os.environ["XLA_FLAGS"] = flags

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:  # deregister the axon remote-TPU plugin if sitecustomize installed it
    import jax._src.xla_bridge as _xb

    _xb._backend_factories.pop("axon", None)
except Exception:  # pragma: no cover - jax internals moved; cpu config above still holds
    pass

assert jax.devices()[0].platform == "cpu", (
    "tests must run on the virtual CPU mesh, got " + repr(jax.devices())
)
assert jax.device_count() >= 8, (
    "xla_force_host_platform_device_count did not take effect: "
    f"{jax.device_count()} devices"
)
