"""Test harness config: force an 8-device virtual CPU mesh.

Tests never touch the real TPU chip (driver config 1 is a CPU smoke test —
SURVEY.md §4); multi-device sharding tests run on XLA's host-platform
virtual devices.  Must run before jax is imported anywhere.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
