import numpy as np
import pytest

from cst_captioning_tpu.metrics.bleu import compute_bleu
from cst_captioning_tpu.metrics.meteor import compute_meteor, meteor_segment
from cst_captioning_tpu.metrics.rouge import compute_rouge, rouge_l_segment, _lcs_len


GTS = {
    "a": ["the cat sat on the mat", "a cat is sitting on a mat"],
    "b": ["a man rides a horse", "the man is riding a horse"],
}


class TestBleu:
    def test_perfect_match(self):
        res = {"a": ["the cat sat on the mat"], "b": ["a man rides a horse"]}
        bleus, _ = compute_bleu(GTS, res)
        for b in bleus:
            assert b == pytest.approx(1.0, abs=1e-6)

    def test_orders_decreasing_for_partial(self):
        res = {"a": ["the cat sat on a chair"], "b": ["a man rides a bike"]}
        bleus, _ = compute_bleu(GTS, res)
        assert bleus[0] > bleus[3]
        assert all(0.0 <= b <= 1.0 for b in bleus)

    def test_brevity_penalty(self):
        full = {"a": ["the cat sat on the mat"], "b": ["a man rides a horse"]}
        clipped = {"a": ["the cat"], "b": ["a man"]}
        b_full, _ = compute_bleu(GTS, full)
        b_clip, _ = compute_bleu(GTS, clipped)
        assert b_clip[0] < b_full[0]

    def test_no_overlap_near_zero(self):
        res = {"a": ["zz qq ww"], "b": ["xx yy vv"]}
        bleus, _ = compute_bleu(GTS, res)
        assert bleus[0] < 1e-3


class TestRouge:
    def test_lcs(self):
        assert _lcs_len("a b c d".split(), "a c d".split()) == 3
        assert _lcs_len([], ["a"]) == 0

    def test_perfect(self):
        assert rouge_l_segment("a man rides a horse", ["a man rides a horse"]) == pytest.approx(1.0)

    def test_partial_between_0_1(self):
        s = rouge_l_segment("a man rides", ["a man rides a horse"])
        assert 0.0 < s < 1.0

    def test_corpus_mean(self):
        res = {"a": ["the cat sat on the mat"], "b": ["a man walks"]}
        mean, scores = compute_rouge(GTS, res)
        assert mean == pytest.approx(scores.mean())
        assert scores[0] == pytest.approx(1.0)


class TestMeteor:
    def test_perfect(self):
        s = meteor_segment("a man rides a horse", ["a man rides a horse"])
        # single chunk → penalty = gamma * 1^beta? chunks/m = 1/5 → small penalty
        assert s > 0.9

    def test_stem_matching(self):
        # "riding" should stem-match "rides"... both stem to "ride"/"rid".
        s = meteor_segment("the man riding a horse", ["the man rides a horse"])
        assert s > 0.6

    def test_word_order_penalty(self):
        ordered = meteor_segment("a man rides a horse", ["a man rides a horse"])
        shuffled = meteor_segment("horse a rides man a", ["a man rides a horse"])
        assert ordered > shuffled

    def test_no_match(self):
        assert meteor_segment("zz qq", ["a man rides"]) == 0.0

    def test_identical_with_repeated_words_is_one_chunk(self):
        """Repeated words ('a ... a ...') must not split the alignment:
        the adjacency tie-break keeps an identical sentence one chunk."""
        from cst_captioning_tpu.metrics.meteor import _align

        m, chunks = _align("a man rides a horse".split(),
                           "a man rides a horse".split())
        assert (m, chunks) == (5, 1)
        assert meteor_segment("a man rides a horse",
                              ["a man rides a horse"]) > 0.99

    def test_corpus(self):
        res = {"a": ["the cat sat on the mat"], "b": ["a man rides a horse"]}
        mean, scores = compute_meteor(GTS, res)
        assert mean == pytest.approx(scores.mean())
        assert all(s > 0.9 for s in scores)


def test_porter_e_restoration():
    from cst_captioning_tpu.metrics.meteor import _porter_stem
    assert _porter_stem("riding") == _porter_stem("rides") == _porter_stem("ride")
    assert _porter_stem("making") == _porter_stem("makes") == _porter_stem("make")
    assert _porter_stem("cooking") == _porter_stem("cooks")
    assert _porter_stem("running") == _porter_stem("runs")
    assert _porter_stem("playing") == _porter_stem("plays")
