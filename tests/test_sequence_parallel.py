"""Sequence/context parallelism: time-sharded attention equivalence.

parallel/sequence.py computes cross-attention over an encoder memory whose
T axis is sharded over the mesh ``model`` axis, via streaming-softmax
collectives (combine) or a ppermute ring.  The contract: numerically
equivalent (f32, 1e-5) to plain single-device softmax attention over the
full T, for any shard count, ragged padding masks included.  The mesh here
is (data=4, model=2) over the 8 virtual CPU devices from conftest.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cst_captioning_tpu.parallel.mesh import make_mesh
from cst_captioning_tpu.parallel.sequence import (
    ring_cross_attention,
    shard_map,
    sp_additive_attention,
    sp_cross_attention_jit,
    sp_dot_attention,
    sp_multihead_cross_attention,
    time_sharding,
)
from jax.sharding import PartitionSpec as P


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device CPU mesh")
    return make_mesh(model_parallel=2)


def ref_attention(q, k, v, mask=None):
    """Single-device full-T softmax attention, f32."""
    s = np.einsum("bqd,btd->bqt", q, k) / np.sqrt(q.shape[-1])
    if mask is not None:
        s = np.where(mask[:, None, :], s, -1e30)
    w = np.exp(s - s.max(-1, keepdims=True))
    w = w / w.sum(-1, keepdims=True)
    return np.einsum("bqt,btd->bqd", w, v)


def _rand(rng, *shape):
    return rng.standard_normal(shape).astype(np.float32)


@pytest.mark.parametrize("ring", [False, True])
def test_sp_dot_attention_matches_full_softmax(mesh, ring):
    rng = np.random.default_rng(0)
    b, lq, t, d = 8, 5, 48, 16
    q, k, v = _rand(rng, b, lq, d), _rand(rng, b, t, d), _rand(rng, b, t, d)
    got = np.asarray(sp_cross_attention_jit(mesh, ring=ring)(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)))
    np.testing.assert_allclose(got, ref_attention(q, k, v), atol=1e-5)


@pytest.mark.parametrize("ring", [False, True])
def test_sp_dot_attention_ragged_mask(mesh, ring):
    """T not divisible by the axis: pad and mask.  Includes a row whose
    valid region lives entirely on ONE shard (the other shard fully
    masked) — the cross-shard combine must zero the dead block."""
    rng = np.random.default_rng(1)
    b, lq, t_valid, d = 8, 3, 19, 8
    shards = mesh.shape["model"]
    t_pad = -(-t_valid // shards) * shards  # 20
    q = _rand(rng, b, lq, d)
    k, v = _rand(rng, b, t_pad, d), _rand(rng, b, t_pad, d)
    mask = np.zeros((b, t_pad), dtype=bool)
    mask[:, :t_valid] = True
    mask[0, :] = False
    mask[0, :4] = True  # row 0: only the first shard's block has memory
    got = np.asarray(sp_cross_attention_jit(mesh, ring=ring)(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), jnp.asarray(mask)))
    np.testing.assert_allclose(got, ref_attention(q, k, v, mask), atol=1e-5)


def test_sp_additive_matches_module_math(mesh):
    """sp_additive_attention == the AdditiveAttention module's
    score->softmax->context chain on the full memory."""
    rng = np.random.default_rng(2)
    b, t, h, a = 8, 24, 12, 10
    qp = _rand(rng, b, a)
    mem, pm = _rand(rng, b, t, h), _rand(rng, b, t, a)
    sv = _rand(rng, a)

    scores = np.einsum("bta,a->bt", np.tanh(pm + qp[:, None, :]), sv)
    w = np.exp(scores - scores.max(-1, keepdims=True))
    w = w / w.sum(-1, keepdims=True)
    want = np.einsum("bt,bth->bh", w, mem)

    mapped = shard_map(
        lambda qp, m, p, v: sp_additive_attention(
            qp, m, p, v, axis_name="model"),
        mesh=mesh,
        in_specs=(P("data"), P("data", "model"), P("data", "model"), P()),
        out_specs=P("data"),
    )
    got = np.asarray(mapped(jnp.asarray(qp), jnp.asarray(mem),
                            jnp.asarray(pm), jnp.asarray(sv)))
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_multihead_wrapper_matches_per_head_reference(mesh):
    rng = np.random.default_rng(3)
    b, lq, t, nh, dh = 8, 4, 16, 2, 6
    q = _rand(rng, b, lq, nh, dh)
    k, v = _rand(rng, b, t, nh, dh), _rand(rng, b, t, nh, dh)
    want = np.stack([
        ref_attention(q[:, :, h], k[:, :, h], v[:, :, h])
        for h in range(nh)
    ], axis=2)

    mapped = shard_map(
        lambda q, k, v: sp_multihead_cross_attention(
            q, k, v, axis_name="model"),
        mesh=mesh,
        in_specs=(P("data"), P("data", "model"), P("data", "model")),
        out_specs=P("data"),
    )
    got = np.asarray(mapped(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)))
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_ring_equals_combine_bitwise_schedule_invariance(mesh):
    """Ring and combine schedules compute the same streaming merge; on
    identical inputs they must agree to float tolerance (not bitwise —
    the reduction orders differ)."""
    rng = np.random.default_rng(4)
    b, lq, t, d = 8, 2, 32, 8
    q, k, v = (jnp.asarray(_rand(rng, b, lq, d)),
               jnp.asarray(_rand(rng, b, t, d)),
               jnp.asarray(_rand(rng, b, t, d)))
    a = np.asarray(sp_cross_attention_jit(mesh, ring=False)(q, k, v))
    r = np.asarray(sp_cross_attention_jit(mesh, ring=True)(q, k, v))
    np.testing.assert_allclose(a, r, atol=1e-6)


def test_long_stream_memory_stays_sharded(mesh):
    """The point of SP: a long-T memory is placed time-sharded and the
    attention runs without any device ever holding full T.  Checks the
    input layout (per-device shard size) and the output value."""
    rng = np.random.default_rng(5)
    b, lq, t, d = 8, 4, 4096, 16
    q = _rand(rng, b, lq, d)
    k, v = _rand(rng, b, t, d), _rand(rng, b, t, d)
    ks = jax.device_put(jnp.asarray(k), time_sharding(mesh))
    vs = jax.device_put(jnp.asarray(v), time_sharding(mesh))
    # each device holds (B/4, T/2, d) — half the time axis, not all of it
    shard_shape = ks.sharding.shard_shape(ks.shape)
    assert shard_shape == (b // 4, t // 2, d)
    got = np.asarray(sp_cross_attention_jit(mesh)(jnp.asarray(q), ks, vs))
    np.testing.assert_allclose(got, ref_attention(q, k, v), atol=1e-5)


def test_context_parallel_xe_step_matches_unsharded(mesh):
    """GSPMD CP: the full XE train step with the long modality time-sharded
    over the model axis (parallel/cp.py) must produce the same loss and
    updated params as the plain unsharded step — XLA owns the collective
    and gradient bookkeeping, this pins that the annotations describe the
    same program."""
    from cst_captioning_tpu.models import CaptionModel
    from cst_captioning_tpu.parallel.cp import (
        context_parallel_jit,
        time_shard_memory,
    )
    from cst_captioning_tpu.training.state import create_train_state
    from cst_captioning_tpu.training.steps import make_xe_step

    B, S, L, V, H = 8, 2, 6, 40, 16
    # long stream (time-sharded) + clip-level vectors; both the sharded
    # modality's T and the concatenated memory T (64) must divide the
    # model axis (parallel/cp.py docstring)
    feat_shapes = [(62, 12), (2, 6)]
    kw = dict(vocab_size=V, embed_size=H, hidden_size=H, attn_size=H,
              num_layers=1, use_attention=True, dropout_rate=0.0,
              decoder_type="transformer", num_heads=2, num_tx_layers=1,
              tx_max_len=L + 1)
    model_cp = CaptionModel(**kw, encode_constraint=time_shard_memory(mesh))
    model_ref = CaptionModel(**kw)

    # SGD, not adam: adam normalizes by sqrt(v), turning float-noise-level
    # differences in near-zero grads into lr-scale sign flips — SGD keeps
    # the param delta linear in the grads so the tolerance tests grads.
    import optax

    tx = optax.sgd(1e-2)
    state0 = create_train_state(
        model_ref, jax.random.PRNGKey(0), feat_shapes, L, S, tx,
        batch_size=B)

    rng = np.random.default_rng(7)
    feats = [jnp.asarray(rng.standard_normal((B,) + s), jnp.float32)
             for s in feat_shapes]
    labels = jnp.asarray(rng.integers(1, V, (B * S, L)), jnp.int32)
    weights = jnp.ones((B * S,), jnp.float32)
    key = jax.random.PRNGKey(3)

    ref_state, ref_metrics = jax.jit(make_xe_step(model_ref, S))(
        state0, feats, labels, weights, key)

    state0b = create_train_state(
        model_cp, jax.random.PRNGKey(0), feat_shapes, L, S, tx,
        batch_size=B)
    cp_step = context_parallel_jit(
        make_xe_step(model_cp, S), mesh,
        feats_time_sharded=(True, False), batch_argnums=(1, 2, 3))
    cp_state, cp_metrics = cp_step(state0b, feats, labels, weights, key)

    np.testing.assert_allclose(float(cp_metrics["loss"]),
                               float(ref_metrics["loss"]), atol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5),
        cp_state.params, ref_state.params)


def test_degenerate_single_shard_axis():
    """model axis of size 1 (the default mesh): SP ops reduce to plain
    attention — no special-casing needed at call sites."""
    mesh1 = make_mesh(model_parallel=1)
    rng = np.random.default_rng(6)
    b, lq, t, d = 8, 3, 8, 4
    q, k, v = _rand(rng, b, lq, d), _rand(rng, b, t, d), _rand(rng, b, t, d)
    got = np.asarray(sp_cross_attention_jit(mesh1)(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)))
    np.testing.assert_allclose(got, ref_attention(q, k, v), atol=1e-5)
