"""RewardPipeline unit semantics (training/pipeline.py).

The e2e suite drives the pipeline through the Trainer; these tests pin the
class contract itself: fill behavior at each depth, completion order,
ctx passthrough, and drain.
"""

from types import SimpleNamespace

import numpy as np
import pytest

from cst_captioning_tpu.training.pipeline import RewardPipeline


class FakeDevice:
    """Stand-in device stack: rollout returns tagged arrays; rl_step
    counts updates into state."""

    def __init__(self):
        self.rollout_calls = []
        self.step_calls = []

    def rollout(self, params, feats, rng):
        self.rollout_calls.append(rng)
        sampled = np.full((4, 3), rng, np.int32)
        fetch = np.concatenate([sampled, np.full((2, 3), rng + 100, np.int32)])
        return sampled, fetch

    def rl_step(self, state, feats, sampled, advantage, rng):
        self.step_calls.append(int(sampled[0, 0]))
        new = SimpleNamespace(params=state.params, step=state.step + 1)
        return new, {"loss": float(advantage.mean())}


def advantage_fn(ctx, sampled_rows, greedy_rows):
    assert sampled_rows.shape == (4, 3)
    assert greedy_rows.shape == (2, 3)
    return np.full(4, float(ctx)), {"ctx": float(ctx)}


@pytest.mark.parametrize("depth", [0, 1, 3])
def test_fill_then_steady_state(depth):
    dev = FakeDevice()
    pipe = RewardPipeline(dev.rollout, dev.rl_step, advantage_fn, depth)
    state = SimpleNamespace(params=None, step=0)
    completed = []
    for k in range(6):
        state, done = pipe.push(state, None, k, k, k)
        assert len(done) <= 1
        completed += done
    # first `depth` pushes only fill the queue
    assert len(completed) == 6 - depth
    assert len(pipe) == depth
    state, drained = pipe.drain(state)
    completed += drained
    assert len(pipe) == 0
    # every step completed exactly once, in dispatch order, ctx intact
    assert [c[0] for c in completed] == list(range(6))
    assert [c[1]["ctx"] for c in completed] == list(range(6))
    # grad steps consumed the matching rollout's tokens
    assert dev.step_calls == list(range(6))
    assert state.step == 6


def test_depth_clamped_non_negative():
    dev = FakeDevice()
    pipe = RewardPipeline(dev.rollout, dev.rl_step, advantage_fn, -3)
    assert pipe.depth == 0
    state = SimpleNamespace(params=None, step=0)
    state, done = pipe.push(state, None, 0, 0, 0)
    assert len(done) == 1  # depth 0 == fully serial


def test_scb_fetch_without_greedy_rows():
    """When fetch == sampled (SCB baselines) the completion must pass
    greedy_rows=None to the advantage fn."""
    seen = {}

    def rollout(params, feats, rng):
        sampled = np.zeros((4, 3), np.int32)
        return sampled, sampled  # no baseline rows appended

    def adv(ctx, sampled_rows, greedy_rows):
        seen["greedy"] = greedy_rows
        return np.zeros(4), {}

    def rl(state, feats, sampled, advantage, rng):
        return state, {}

    pipe = RewardPipeline(rollout, rl, adv, 0)
    state = SimpleNamespace(params=None, step=0)
    pipe.push(state, None, 0, 0, "v")
    assert seen["greedy"] is None
