"""Chaos suite: fault injection, divergence guard, checkpoint integrity.

Layer map (RESILIENCE.md): resilience/faults.py injects deterministic
failures at the trainer's host-side seams; steps.py + resilience/guard.py
skip/roll-back non-finite steps; resilience/integrity.py + checkpoint.py
keep auto-resume off torn checkpoints; data/loader.py retries transient
reads.  The e2e tests here drive the REAL trainer (CLI surface included)
through each injected fault and assert the run completes with the expected
final step count and finite metrics.

Fast unit tests are unmarked (they ride in tier-1's ``-m 'not slow'``);
the subprocess wedge drill is ``slow`` and runs under ``make chaos``.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
from collections import Counter

import numpy as np
import pytest

from cst_captioning_tpu.data.loader import Batch, prefetch_to_device
from cst_captioning_tpu.resilience.faults import FaultPlan, InjectedFault
from cst_captioning_tpu.resilience.guard import (
    DivergenceGuard,
    DivergenceUnrecoverable,
)
from cst_captioning_tpu.resilience.integrity import (
    verify_step_dir,
    write_manifest,
)

pytestmark = pytest.mark.chaos

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- fault plan grammar ----------------------------------------------------

class TestFaultPlan:
    def test_parse_full_grammar(self):
        plan = FaultPlan.parse(
            "ckpt_torn@step=40,nan_grad@step=55*3,loader_err@batch=12,"
            "wedge@step=70")
        assert len(plan.specs) == 4
        assert str(plan) == ("ckpt_torn@step=40,nan_grad@step=55*3,"
                             "loader_err@batch=12,wedge@step=70")

    def test_empty_is_disarmed(self):
        assert FaultPlan.parse(None) is None
        assert FaultPlan.parse("") is None
        assert FaultPlan.parse("  ") is None

    @pytest.mark.parametrize("bad", [
        "explode@step=1",          # unknown kind
        "ckpt_torn@batch=1",       # wrong axis for the kind
        "nan_grad@step=x",         # non-numeric index
        "nan_grad=5",              # missing axis
    ])
    def test_bad_specs_fail_at_parse(self, bad):
        with pytest.raises(ValueError):
            FaultPlan.parse(bad)

    @pytest.mark.parametrize("bad", ["nan_grad@stp=3", "wedge@step"])
    def test_parse_error_is_single_line_naming_token_and_grammar(self, bad):
        """The error an operator actually reads: ONE line, quoting the bad
        token, stating the grammar — not a traceback to decode."""
        with pytest.raises(ValueError) as ei:
            FaultPlan.parse(bad)
        msg = str(ei.value)
        assert bad in msg, "message must name the offending token"
        assert "\n" not in msg, "must be a single line"
        assert "kind@step=N" in msg, "message must state the grammar"

    @pytest.mark.parametrize("bad", ["nan_grad@stp=3", "wedge@step"])
    def test_cli_rejects_malformed_plan_as_usage_error(self, bad, capsys):
        """--fault_plan validates at argparse time (opts.py): a malformed
        spec exits 2 with a usage line naming the token, instead of
        surfacing as a Trainer-startup ValueError traceback."""
        from cst_captioning_tpu.opts import parse_opts

        with pytest.raises(SystemExit) as ei:
            parse_opts(["--fault_plan", bad])
        assert ei.value.code == 2
        err = capsys.readouterr().err
        assert bad in err and "--fault_plan" in err
        assert "Traceback" not in err

    def test_env_var_plan_gets_the_same_usage_error(self, capsys,
                                                    monkeypatch):
        """The CST_FAULT_PLAN fallback is resolved as the argparse DEFAULT
        (opts.py), so a malformed env plan exits 2 with the same one-line
        usage error as a malformed flag — never a Trainer-startup
        traceback; a well-formed env plan lands in the namespace."""
        from cst_captioning_tpu.opts import parse_opts

        monkeypatch.setenv("CST_FAULT_PLAN", "nan_grad@stp=3")
        with pytest.raises(SystemExit) as ei:
            parse_opts([])
        assert ei.value.code == 2
        err = capsys.readouterr().err
        assert "nan_grad@stp=3" in err and "Traceback" not in err

        monkeypatch.setenv("CST_FAULT_PLAN", "wedge@step=7")
        assert parse_opts([]).fault_plan == "wedge@step=7"
        monkeypatch.setenv("CST_FAULT_PLAN", "")
        assert parse_opts([]).fault_plan is None

    def test_fire_is_single_shot_per_index(self):
        plan = FaultPlan.parse("nan_grad@step=5*2")
        assert not plan.fire("nan_grad", 4)
        assert plan.fire("nan_grad", 5)
        assert not plan.fire("nan_grad", 5), "replay must not re-fire"
        assert plan.fire("nan_grad", 6)
        assert not plan.fire("nan_grad", 7)
        assert plan.pending("nan_grad") == 0

    def test_kinds_are_independent(self):
        plan = FaultPlan.parse("wedge@step=3,nan_grad@step=3")
        assert plan.fire("wedge", 3)
        assert plan.fire("nan_grad", 3)

    def test_bound_state_survives_process_restart(self, tmp_path):
        """A process-killing fault (wedge) must be single-shot ACROSS the
        resume attempts a recovery harness spawns: firings persisted via
        bind_state are pre-consumed when a fresh process re-parses the
        same plan."""
        state = str(tmp_path / "fault_state.jsonl")
        p1 = FaultPlan.parse("wedge@step=7,nan_grad@step=9").bind_state(state)
        assert p1.fire("wedge", 7)
        # "new process": same plan text, fresh consumed set, same state file
        p2 = FaultPlan.parse("wedge@step=7,nan_grad@step=9").bind_state(state)
        assert not p2.fire("wedge", 7), "wedge re-fired after restart"
        assert p2.fire("nan_grad", 9), "unrelated firings must survive"
        p3 = FaultPlan.parse("wedge@step=7,nan_grad@step=9").bind_state(state)
        assert p3.pending("wedge") == 0 and p3.pending("nan_grad") == 0


# -- checkpoint integrity --------------------------------------------------

def _fake_step_dir(tmp_path, name="10"):
    d = tmp_path / name
    (d / "state").mkdir(parents=True)
    (d / "state" / "a.bin").write_bytes(b"payload-a" * 64)
    (d / "state" / "b.bin").write_bytes(b"payload-b" * 32)
    return str(d)


class TestManifest:
    def test_roundtrip_verifies(self, tmp_path):
        d = _fake_step_dir(tmp_path)
        m = write_manifest(d)
        assert set(m["files"]) == {"state/a.bin", "state/b.bin"}
        status, detail = verify_step_dir(d)
        assert status == "verified", detail

    def test_truncation_detected(self, tmp_path):
        d = _fake_step_dir(tmp_path)
        write_manifest(d)
        with open(os.path.join(d, "state", "a.bin"), "r+b") as f:
            f.truncate(10)
        assert verify_step_dir(d)[0] == "corrupt"

    def test_bitflip_detected(self, tmp_path):
        d = _fake_step_dir(tmp_path)
        write_manifest(d)
        p = os.path.join(d, "state", "b.bin")
        raw = bytearray(open(p, "rb").read())
        raw[0] ^= 0xFF
        open(p, "wb").write(bytes(raw))  # same size, different content
        status, detail = verify_step_dir(d)
        assert status == "corrupt" and "checksum" in detail

    def test_missing_file_detected(self, tmp_path):
        d = _fake_step_dir(tmp_path)
        write_manifest(d)
        os.unlink(os.path.join(d, "state", "a.bin"))
        assert verify_step_dir(d)[0] == "corrupt"

    def test_legacy_step_without_manifest_is_unverified(self, tmp_path):
        d = _fake_step_dir(tmp_path)
        assert verify_step_dir(d)[0] == "unverified"

    def test_torn_manifest_write_is_corrupt(self, tmp_path):
        """Marker present without a manifest == the save died between the
        orbax commit and the manifest landing: must NOT pass as legacy."""
        d = _fake_step_dir(tmp_path)
        open(os.path.join(d, ".manifest.writing"), "w").close()
        assert verify_step_dir(d)[0] == "corrupt"

    def test_stat_level_catches_truncation_not_bitflips(self, tmp_path):
        """level='stat' (the startup quarantine scan) is a size/existence
        check: it must catch the torn-write mode (truncation) without
        reading file contents; same-size bit rot is full-verify's job at
        restore time."""
        d = _fake_step_dir(tmp_path)
        write_manifest(d)
        p = os.path.join(d, "state", "b.bin")
        raw = bytearray(open(p, "rb").read())
        raw[0] ^= 0xFF
        open(p, "wb").write(bytes(raw))
        assert verify_step_dir(d, level="stat")[0] == "verified"
        assert verify_step_dir(d, level="full")[0] == "corrupt"
        with open(os.path.join(d, "state", "a.bin"), "r+b") as f:
            f.truncate(3)
        assert verify_step_dir(d, level="stat")[0] == "corrupt"


class TestCheckpointManagerIntegrity:
    @pytest.fixture()
    def state(self):
        import jax

        from cst_captioning_tpu.data.vocab import Vocab
        from cst_captioning_tpu.models import CaptionModel
        from cst_captioning_tpu.training.state import (
            create_train_state, make_optimizer)

        vocab = Vocab({1: "a", 2: "b"})
        model = CaptionModel(vocab_size=vocab.size_with_pad, embed_size=8,
                             hidden_size=8, attn_size=8, dropout_rate=0.0)
        tx, _ = make_optimizer(learning_rate=1e-2)
        return create_train_state(model, jax.random.PRNGKey(0), [(2, 4)],
                                  4, 1, tx, batch_size=2)

    def test_walk_back_past_torn_newest(self, tmp_path, state):
        from cst_captioning_tpu.training.checkpoint import CheckpointManager

        mgr = CheckpointManager(str(tmp_path / "ck"), max_to_keep=4)
        mgr.save(1, state, score=0.1)
        mgr.save(2, state, score=0.2)
        assert mgr.latest_step == 2
        assert mgr.latest_verified_step == 2
        # Tear the newest step the way a power cut would.
        CheckpointManager._tear_step(mgr._step_dir(2))
        assert mgr.verify_step(2)[0] == "corrupt"
        assert mgr.latest_verified_step == 1
        restored = mgr.restore(state)  # auto-resolution must walk back
        assert int(restored.step) == int(state.step)
        # An EXPLICITLY requested torn step is an error, never a substitute.
        with pytest.raises(ValueError, match="integrity"):
            mgr.restore(state, step=2)
        mgr.close()

    def test_walk_back_past_two_consecutive_torn_steps(self, tmp_path, state):
        """PR 1 pinned a single torn newest step; a crash storm (or a
        dying disk) can tear SEVERAL saves in a row.  Resolution must walk
        back past every consecutive corrupt step to the oldest good one,
        and a fresh manager must quarantine them all at startup."""
        import jax.numpy as jnp

        from cst_captioning_tpu.training.checkpoint import CheckpointManager

        d = str(tmp_path / "ck")
        mgr = CheckpointManager(d, max_to_keep=4)
        for s, score in ((1, 0.1), (2, 0.2), (3, 0.3)):
            mgr.save(s, state.replace(step=jnp.asarray(s)), score=score)
        CheckpointManager._tear_step(mgr._step_dir(2))
        CheckpointManager._tear_step(mgr._step_dir(3))
        # Same-process view: both newest steps corrupt, walk-back lands on 1.
        assert mgr.verify_step(3)[0] == "corrupt"
        assert mgr.verify_step(2)[0] == "corrupt"
        assert mgr.latest_verified_step == 1
        restored = mgr.restore(state)  # walks back 3 -> 2 -> 1
        assert int(restored.step) == 1
        mgr.close()
        # Fresh-process view (the resume shape): startup quarantine moves
        # BOTH torn steps aside and best bookkeeping falls to the oldest
        # good scored step.
        mgr2 = CheckpointManager(d, max_to_keep=4)
        assert os.path.isdir(os.path.join(d, "2.corrupt-quarantine"))
        assert os.path.isdir(os.path.join(d, "3.corrupt-quarantine"))
        assert mgr2.latest_verified_step == 1
        assert mgr2.best_step == 1
        assert mgr2.infos["best_score"] == 0.1
        assert set(mgr2.infos.get("step_scores", {})) == {"1"}
        mgr2.close()

    def test_ckpt_torn_fault_hook_tears_after_manifest(self, tmp_path, state):
        from cst_captioning_tpu.training.checkpoint import CheckpointManager

        plan = FaultPlan.parse("ckpt_torn@step=2")
        mgr = CheckpointManager(str(tmp_path / "ck"), max_to_keep=4,
                                fault_plan=plan)
        mgr.save(1, state, score=0.1)
        mgr.save(2, state, score=0.2)  # hook fires here, post-manifest
        assert mgr.verify_step(1)[0] == "verified"
        assert mgr.verify_step(2)[0] == "corrupt"
        assert mgr.latest_verified_step == 1
        mgr.close()

    def test_seal_targets_the_saving_manager(self, tmp_path, state):
        """The same step number can exist in BOTH managers (rollback
        replay crossing a save boundary): each save must seal — and a
        ckpt_torn hook must tear — the directory it actually wrote, not
        whichever _step_dir guesses first."""
        import jax.numpy as jnp

        from cst_captioning_tpu.resilience.integrity import manifest_path
        from cst_captioning_tpu.training.checkpoint import CheckpointManager

        d = str(tmp_path / "ck")
        mgr = CheckpointManager(d, max_to_keep=4)
        mgr.save(2, state.replace(step=jnp.asarray(2)), score=0.2)
        mgr.save_recovery(2, state.replace(step=jnp.asarray(2)))
        # both copies of step 2 carry their own manifest and verify
        assert os.path.exists(manifest_path(os.path.join(d, "2")))
        assert os.path.exists(manifest_path(os.path.join(d, "recovery", "2")))
        assert verify_step_dir(os.path.join(d, "2"))[0] == "verified"
        assert verify_step_dir(
            os.path.join(d, "recovery", "2"))[0] == "verified"
        mgr.close()

    def test_quarantine_scrubs_best_bookkeeping(self, tmp_path, state):
        """A quarantined best step must not leave its score behind: a
        replayed state at the same step number would otherwise inherit the
        torn checkpoint's (typically higher) recorded best score."""
        import jax.numpy as jnp

        from cst_captioning_tpu.training.checkpoint import CheckpointManager

        d = str(tmp_path / "ck")
        mgr = CheckpointManager(d, max_to_keep=4)
        mgr.save(1, state.replace(step=jnp.asarray(1)), score=0.5)
        mgr.save(2, state.replace(step=jnp.asarray(2)), score=0.9)
        assert mgr.best_step == 2
        CheckpointManager._tear_step(mgr._step_dir(2))
        mgr.close()
        mgr2 = CheckpointManager(d, max_to_keep=4)  # quarantines step 2
        assert mgr2.best_step == 1, "best must fall back to a real step"
        assert mgr2.infos["best_score"] == 0.5
        assert "2" not in mgr2.infos.get("step_scores", {})
        assert os.path.isdir(os.path.join(d, "2.corrupt-quarantine"))
        mgr2.close()

    def test_verified_recovery_save_refuses_torn_write(self, tmp_path, state):
        """save_recovery(verify=True) — the preemption boundary's save —
        must RAISE when the just-sealed step does not verify, instead of
        letting the process exit 'resumable: checkpoint advanced' on a
        checkpoint that cannot restore."""
        from cst_captioning_tpu.training.checkpoint import CheckpointManager

        plan = FaultPlan.parse("ckpt_torn@step=1")
        mgr = CheckpointManager(str(tmp_path / "ck"), max_to_keep=4,
                                fault_plan=plan)
        with pytest.raises(RuntimeError, match="post-save"):
            mgr.save_recovery(1, state, verify=True)
        mgr.close()
        # The clean path verifies and returns.
        mgr2 = CheckpointManager(str(tmp_path / "ck2"), max_to_keep=4)
        mgr2.save_recovery(1, state, verify=True)
        mgr2.close()

    def test_verification_cache_sees_external_tamper(self, tmp_path, state):
        """verify_step is stat-signature cached; a payload edit that does
        not touch the manifest (the tear hook, bit rot) must still be
        re-detected, not served stale from the cache."""
        from cst_captioning_tpu.training.checkpoint import CheckpointManager

        mgr = CheckpointManager(str(tmp_path / "ck"), max_to_keep=4)
        mgr.save(1, state, score=0.1)
        assert mgr.verify_step(1)[0] == "verified"  # caches the verdict
        CheckpointManager._tear_step(mgr._step_dir(1))
        assert mgr.verify_step(1)[0] == "corrupt"
        mgr.close()


# -- divergence guard (host half) ------------------------------------------

class TestDivergenceGuard:
    def test_consecutive_threshold(self):
        g = DivergenceGuard(max_bad=2, lag=0)
        g.observe(0, np.float32(0.0))
        assert not g.poll()
        g.observe(1, np.float32(1.0))
        assert not g.poll() and g.consecutive == 1
        g.observe(2, np.float32(1.0))
        assert g.poll() and g.total_skipped == 2

    def test_good_step_resets_consecutive(self):
        g = DivergenceGuard(max_bad=2, lag=0)
        for step, bad in enumerate([1.0, 0.0, 1.0, 0.0]):
            g.observe(step, np.float32(bad))
            assert not g.poll()
        assert g.total_skipped == 2 and g.consecutive == 0

    def test_lag_defers_reaping(self):
        g = DivergenceGuard(max_bad=1, lag=1)
        g.observe(0, np.float32(1.0))
        assert not g.poll(), "entry within the lag window must not block"
        g.observe(1, np.float32(0.0))
        assert g.poll(), "older entry now reaped"
        assert g.flush() is False  # the good step cleared the streak

    def test_rollback_budget(self):
        g = DivergenceGuard(max_bad=1, max_rollbacks=1, lag=0)
        g.observe(0, np.float32(1.0))
        assert g.poll()
        g.note_rollback()  # within budget; resets the streak
        assert g.consecutive == 0
        g.observe(1, np.float32(1.0))
        assert g.poll()
        with pytest.raises(DivergenceUnrecoverable):
            g.note_rollback()


# -- guarded train step (device half) --------------------------------------

class TestGuardedStep:
    @pytest.fixture(scope="class")
    def setup(self):
        import jax
        import jax.numpy as jnp

        from cst_captioning_tpu.data.vocab import Vocab
        from cst_captioning_tpu.models import CaptionModel
        from cst_captioning_tpu.training.state import (
            create_train_state, make_optimizer)

        vocab = Vocab({1: "a", 2: "b", 3: "c"})
        model = CaptionModel(vocab_size=vocab.size_with_pad, embed_size=8,
                             hidden_size=8, attn_size=8, dropout_rate=0.0)
        tx, _ = make_optimizer(learning_rate=1e-2)
        state = create_train_state(model, jax.random.PRNGKey(0), [(2, 4)],
                                   4, 2, tx, batch_size=2)
        feats = [np.random.default_rng(0).standard_normal(
            (2, 2, 4)).astype(np.float32)]
        labels = jnp.asarray(np.array([[1, 2, 3, 0]] * 4, dtype=np.int32))
        return model, state, feats, labels

    def test_nonfinite_step_is_skipped(self, setup):
        import jax
        import jax.numpy as jnp

        from cst_captioning_tpu.training.steps import make_xe_step

        model, state, feats, labels = setup
        step = jax.jit(make_xe_step(model, 2, guard=True))
        rng = jax.random.PRNGKey(0)
        bad_w = jnp.full((4,), np.nan, jnp.float32)
        new_state, metrics = step(state, feats, labels, bad_w, rng)
        assert float(metrics["bad_step"]) == 1.0
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)),
            new_state.params, state.params)
        assert int(new_state.step) == int(state.step) + 1, \
            "skipped steps still count (resume/log accounting)"
        # Optimizer moments must be untouched too, or the next good step
        # would apply Adam statistics polluted by the NaN.
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)),
            new_state.opt_state, state.opt_state)

    def test_good_step_identical_to_unguarded(self, setup):
        import jax
        import jax.numpy as jnp

        from cst_captioning_tpu.training.steps import make_xe_step

        model, state, feats, labels = setup
        rng = jax.random.PRNGKey(0)
        w = jnp.ones((4,), jnp.float32)
        s_plain, m_plain = jax.jit(make_xe_step(model, 2))(
            state, feats, labels, w, rng)
        s_guard, m_guard = jax.jit(make_xe_step(model, 2, guard=True))(
            state, feats, labels, w, rng)
        assert "bad_step" not in m_plain
        assert float(m_guard["bad_step"]) == 0.0
        assert float(m_plain["loss"]) == float(m_guard["loss"])
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)),
            s_plain.params, s_guard.params)


# -- prefetch retry + worker lifetime --------------------------------------

class _FlakySource:
    """next_batch-capable source failing transiently ``fail_times`` times."""

    def __init__(self, fail_times: int, error=InjectedFault):
        self.fail_times = fail_times
        self.calls = 0
        self.error = error

    def next_batch(self) -> Batch:
        self.calls += 1
        if self.fail_times > 0:
            self.fail_times -= 1
            raise self.error("transient read failure")
        return Batch(feats=[], labels=np.zeros((1, 2), np.int32),
                     weights=np.ones(1, np.float32), video_ids=["v0"])


class TestPrefetchResilience:
    def test_transient_errors_are_retried(self):
        src = _FlakySource(fail_times=2)
        it = prefetch_to_device(src, size=1, retries=3,
                                retry_backoff_s=0.001)
        got = [next(it) for _ in range(3)]
        it.close()
        assert len(got) == 3
        assert src.calls >= 5  # 3 successes + 2 retried failures

    def test_exhausted_retries_poison_the_stream(self):
        src = _FlakySource(fail_times=10)
        it = prefetch_to_device(src, size=1, retries=2,
                                retry_backoff_s=0.001)
        with pytest.raises(InjectedFault):
            next(it)

    def test_non_transient_errors_propagate_immediately(self):
        src = _FlakySource(fail_times=5, error=None)
        src.error = ValueError  # not in TRANSIENT_ERRORS
        it = prefetch_to_device(src, size=1, retries=5,
                                retry_backoff_s=0.001)
        with pytest.raises(ValueError):
            next(it)
        assert src.calls == 1, "non-transient error must not be retried"

    def test_worker_exits_when_consumer_abandons(self):
        src = _FlakySource(fail_times=0)
        before = set(threading.enumerate())
        it = prefetch_to_device(src, size=2)
        next(it)
        spawned = [t for t in threading.enumerate() if t not in before]
        assert spawned, "prefetch worker thread not found"
        it.close()  # consumer abandons the infinite stream
        deadline = time.time() + 5.0
        while any(t.is_alive() for t in spawned) and time.time() < deadline:
            time.sleep(0.02)
        assert not any(t.is_alive() for t in spawned), \
            "prefetch worker leaked after consumer abandoned the iterator"

    def test_plain_iterator_keeps_fail_fast_contract(self):
        def gen():
            yield Batch(feats=[], labels=np.zeros((1, 2), np.int32),
                        weights=np.ones(1, np.float32), video_ids=["v"])
            raise OSError("dead generator cannot be retried")

        it = prefetch_to_device(gen(), size=1, retries=3,
                                retry_backoff_s=0.001)
        next(it)
        with pytest.raises(OSError):
            next(it)


# -- deterministic-resume data alignment -----------------------------------

class TestResumeStreamAlignment:
    """loader.skip_batches is the data half of bit-exact resume: a
    fast-forwarded stream must serve the SAME batches (video order, epoch
    shuffles, per-video caption draws) as one that actually served the
    skipped prefix."""

    def _loader(self, data):
        from cst_captioning_tpu.data.dataset import CaptionDataset, SplitPaths
        from cst_captioning_tpu.data.loader import CaptionLoader

        t = data["train"]
        ds = CaptionDataset(SplitPaths(feat_h5=json.loads(t["feat_h5"]),
                                       label_h5=t["label_h5"],
                                       info_json=t["info_json"]))
        return ds, lambda: CaptionLoader(ds, batch_size=2, seq_per_img=2,
                                         shuffle=True, seed=0)

    def test_skip_batches_matches_served_stream(self, data):
        ds, mk = self._loader(data)
        try:
            full = mk()
            served = [full.next_batch() for _ in range(6)]  # 3 tiny epochs
            for n in (1, 2, 3, 5):  # mid-epoch AND boundary skips
                fast = mk()
                fast.skip_batches(n)
                for i in range(n, 6):
                    got = fast.next_batch()
                    want = served[i]
                    assert got.video_ids == want.video_ids, (n, i)
                    np.testing.assert_array_equal(got.labels, want.labels)
                    np.testing.assert_array_equal(got.weights, want.weights)
        finally:
            ds.close()

    def test_skip_zero_or_negative_is_noop(self, data):
        ds, mk = self._loader(data)
        try:
            a, b = mk(), mk()
            b.skip_batches(0)
            b.skip_batches(-3)
            assert a.next_batch().video_ids == b.next_batch().video_ids
        finally:
            ds.close()


# -- e2e chaos: the real trainer through injected faults -------------------

@pytest.fixture(scope="module")
def data(tmp_path_factory):
    from cst_captioning_tpu.data.synthetic import SyntheticSpec, generate
    from cst_captioning_tpu.data.vocab import load_vocab

    root = str(tmp_path_factory.mktemp("chaos"))
    spec = SyntheticSpec(num_videos=4, captions_per_video=4, max_len=10,
                         feat_dims=(12, 6), feat_times=(3, 1))
    train = generate(root, "train", spec)
    vocab = load_vocab(train["vocab_json"])
    val = generate(root, "val",
                   SyntheticSpec(num_videos=2, captions_per_video=4,
                                 max_len=10, feat_dims=(12, 6),
                                 feat_times=(3, 1)), vocab=vocab)
    return {"root": root, "train": train, "val": val}


def chaos_argv(data, ckpt_dir, **over):
    t, v = data["train"], data["val"]
    args = {
        "--train_feat_h5": json.loads(t["feat_h5"]),
        "--train_label_h5": [t["label_h5"]],
        "--train_info_json": [t["info_json"]],
        "--train_cocofmt_file": [t["cocofmt_json"]],
        "--val_feat_h5": json.loads(v["feat_h5"]),
        "--val_label_h5": [v["label_h5"]],
        "--val_info_json": [v["info_json"]],
        "--val_cocofmt_file": [v["cocofmt_json"]],
        "--checkpoint_path": [ckpt_dir],
        "--batch_size": ["2"], "--seq_per_img": ["2"],
        "--rnn_size": ["16"], "--input_encoding_size": ["16"],
        "--att_size": ["16"], "--drop_prob": ["0.0"],
        "--max_epochs": ["2"], "--learning_rate": ["0.01"],
        "--max_length": ["10"], "--log_every": ["1"],
        "--fast_val": ["1"], "--max_patience": ["0"], "--seed": ["0"],
    }
    args.update({k: [str(x) for x in vals] for k, vals in over.items()})
    flat = []
    for k, vals in args.items():
        flat.append(k)
        flat.extend(vals)
    return flat


def run_train_cli(data, ckpt_dir, **over):
    """The real ``train.py`` CLI in a FRESH subprocess — the shape every
    production resume takes (scale_chain runs one process per stage
    attempt).  Same-process restore over a directory whose files were
    modified externally (torn checkpoints) is explicitly NOT supported:
    tensorstore's in-process ocdbt caches do not see external truncation.
    Returns the completed process (check .returncode / stdout JSON)."""
    from conftest import CACHE_DIR

    env = dict(os.environ)
    env["PYTHONPATH"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    env.setdefault("JAX_COMPILATION_CACHE_DIR", CACHE_DIR)
    return subprocess.run(
        [sys.executable, "train.py", *chaos_argv(data, ckpt_dir, **over)],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=300)


def train_metrics(ckpt_dir):
    """metrics.jsonl train-scope records keyed by (1-based) step."""
    out = {}
    with open(os.path.join(ckpt_dir, "metrics.jsonl")) as f:
        for line in f:
            rec = json.loads(line)
            if rec.get("scope") == "train":
                out[rec["step"]] = rec
    return out


def infos(ckpt_dir):
    """The stage's infos.json.  Drill assertions prefer this over the CLI
    summary line: ``last_step`` here is the trainer's host-side loop
    counter, while the summary's comes from a device scalar fetch — which
    this environment's native stack occasionally garbles (RESILIENCE.md
    caveat)."""
    with open(os.path.join(ckpt_dir, "infos.json")) as f:
        return json.load(f)


@pytest.mark.e2e
@pytest.mark.slow
class TestChaosEndToEnd:
    """End-to-end chaos drills over the real trainer.  ``slow``-marked as
    a class: they run under ``make chaos``, not in the tier-1 ``-m 'not
    slow'`` selection — partly for runtime, partly because this
    environment's CPU jax stack is only reliably stable for trainer e2e
    runs in fresh subprocesses (see RESILIENCE.md caveat), and tier-1
    shares one process across the whole suite."""
    # 4 videos / batch 2 -> bpe 2; 2 epochs -> 4 steps total.

    def test_nan_grad_is_skipped_and_run_finishes(self, data, tmp_path):
        ck = str(tmp_path / "xe")
        proc = run_train_cli(data, ck,
                             **{"--fault_plan": ["nan_grad@step=1*2"]})
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert "2 step(s) skipped as non-finite, 0 rollback(s)" \
            in proc.stderr
        info = infos(ck)
        assert info["last_step"] == 4, \
            "skipped steps must still count toward the final step"
        assert info["best_score"] is not None
        assert np.isfinite(info["best_score"])
        # metrics.jsonl is the durable skip record: the two injected steps
        # carry bad_step=1.0 (and an honest NaN loss); the rest are clean
        # with finite losses.
        m = train_metrics(ck)
        assert set(m) == {1, 2, 3, 4}
        assert m[2]["bad_step"] == 1.0 and m[3]["bad_step"] == 1.0
        assert m[1]["bad_step"] == 0.0 and m[4]["bad_step"] == 0.0
        assert np.isfinite(m[1]["loss"]) and np.isfinite(m[4]["loss"])

    @pytest.mark.slow
    def test_nan_burst_triggers_rollback_and_recovers(self, data, tmp_path):
        """A burst of NaN steps past --divergence_max_bad must roll back
        to the last checkpoint and still finish the run.  Subprocess (real
        CLI): the mid-run restore must run in the stage's own process,
        like every production rollback would."""
        proc = run_train_cli(
            data, str(tmp_path / "xe_burst"),
            **{"--fault_plan": ["nan_grad@step=1*3"],
               "--save_every_steps": ["1"],
               "--divergence_max_bad": ["2"]})
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert "rolled back from step" in proc.stderr
        assert "re-seeded rollout key stream (salt 1)" in proc.stderr
        assert "step(s) skipped as non-finite, 1 rollback(s)" in proc.stderr
        info = infos(str(tmp_path / "xe_burst"))
        assert info["last_step"] == 4
        assert info["best_score"] is not None
        assert np.isfinite(info["best_score"])

    @pytest.mark.slow
    def test_persistent_divergence_aborts(self, data, tmp_path):
        """Every step NaN and a rollback budget of 0: the guard must
        refuse to loop forever and abort the run.  Subprocess: an aborted
        mid-run trainer must not share a process with later tests (this
        environment's XLA-CPU client is fragile after an unwound run)."""
        proc = run_train_cli(
            data, str(tmp_path / "xe_dead"),
            **{"--fault_plan": ["nan_grad@step=0*64"],
               "--divergence_max_bad": ["2"],
               "--divergence_max_rollbacks": ["0"]})
        assert proc.returncode not in (0, None), "run must abort, not finish"
        assert "diverged again" in proc.stderr, proc.stderr[-2000:]

    @pytest.mark.parametrize("device_rewards", ["1", "0"])
    def test_nan_grad_on_rl_paths(self, data, tmp_path, device_rewards):
        """NaN streamed features on both CST shapes (fused on-device
        rewards; host reward pipeline) must produce one skipped step and a
        finished run with finite selection metrics."""
        ck = str(tmp_path / f"rl{device_rewards}")
        proc = run_train_cli(
            data, ck,
            **{"--use_rl": ["1"], "--device_rewards": [device_rewards],
               "--max_epochs": ["1"], "--learning_rate": ["0.0005"],
               "--fault_plan": ["nan_grad@step=0"]})
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert "1 step(s) skipped as non-finite, 0 rollback(s)" \
            in proc.stderr
        info = infos(ck)
        assert info["last_step"] == 2
        assert info["best_score"] is not None
        assert np.isfinite(info["best_score"])
        m = train_metrics(ck)
        assert m[1]["bad_step"] == 1.0 and m[2]["bad_step"] == 0.0
        assert np.isfinite(m[2]["loss"])

    def test_loader_error_is_retried_through(self, data, tmp_path):
        ck = str(tmp_path / "ld")
        proc = run_train_cli(
            data, ck, **{"--fault_plan": ["loader_err@batch=1*2"]})
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert proc.stderr.count("transient batch-read error") == 2
        info = infos(ck)
        assert info["last_step"] == 4
        assert info["best_score"] is not None
        m = train_metrics(ck)
        assert set(m) == {1, 2, 3, 4}, "retried batches must not drop steps"
        assert all(rec["bad_step"] == 0.0 for rec in m.values())

    def test_debug_nans_disables_guard_with_warning(self, data, tmp_path,
                                                    caplog):
        import logging

        from cst_captioning_tpu.opts import parse_opts
        from cst_captioning_tpu.training.trainer import Trainer

        with caplog.at_level(logging.WARNING, "cst_captioning_tpu.train"):
            tr = Trainer(parse_opts(chaos_argv(
                data, str(tmp_path / "dbg"), **{"--debug_nans": ["1"]})))
        try:
            assert tr._guard is None, \
                "--debug_nans and the guard are mutually exclusive"
            assert any("mutually exclusive" in r.message
                       for r in caplog.records)
        finally:
            import jax

            tr.close()
            jax.config.update("jax_debug_nans", False)  # don't leak to peers

    @pytest.mark.slow
    def test_torn_checkpoint_resumes_from_last_verified(self, data,
                                                        tmp_path):
        """The acceptance scenario, through the real train.py CLI with one
        fresh process per run (the scale_chain stage shape): run 1 tears
        its newest (epoch-boundary) checkpoint; run 2 must quarantine it,
        resume from the last VERIFIED step, and finish with the expected
        step count."""
        ck = str(tmp_path / "torn")
        proc = run_train_cli(
            data, ck,
            **{"--max_epochs": ["1"], "--save_every_steps": ["1"],
               "--fault_plan": ["ckpt_torn@step=2"]})
        assert proc.returncode == 0, proc.stderr[-2000:]
        # Probe the torn state with the fs-level integrity API only — a
        # CheckpointManager would quarantine it, which is run 2's job.
        assert verify_step_dir(os.path.join(ck, "2"))[0] == "corrupt"
        assert verify_step_dir(
            os.path.join(ck, "recovery", "1"))[0] == "verified"

        proc = run_train_cli(data, ck, **{"--max_epochs": ["2"]})
        assert "quarantined torn checkpoint step 2" in proc.stderr, \
            proc.stderr[-2000:]
        assert "resumed from step 1" in proc.stderr, proc.stderr[-2000:]
        # The torn step was quarantined aside (forensics); when run 2 got
        # as far as its epoch save, the slot holds a fresh verified copy.
        assert os.path.isdir(os.path.join(ck, "2.corrupt-quarantine"))
        if os.path.isdir(os.path.join(ck, "2")):
            assert verify_step_dir(os.path.join(ck, "2"))[0] == "verified", \
                "replayed step 2 must be re-saved intact over the torn slot"
        # Durable proof training CONTINUED from the restore: only run 2
        # can write train metrics for steps past 2.  The exit code is
        # deliberately NOT asserted — this session's CPU jax/tensorstore
        # stack has a pre-existing, probabilistic native crash in
        # processes that restore-then-train (the seed's test_full_pipeline
        # warm-start crash is the same defect); the recovery semantics
        # under test are fully visible in the logs and on disk.
        steps_logged = Counter()
        with open(os.path.join(ck, "metrics.jsonl")) as f:
            for line in f:
                rec = json.loads(line)
                if rec.get("scope") == "train":
                    steps_logged[rec["step"]] += 1
        # Run 1 logged steps {1, 2}; a resumed run 2 re-logs step 2 (its
        # replay) before anything else, so a second step-2 line — or any
        # step past 2 — proves post-restore training progress.
        assert steps_logged[2] >= 2 or steps_logged[3] >= 1, (
            f"no post-resume training progress in metrics: "
            f"{dict(steps_logged)}\nrc={proc.returncode}\n"
            f"{proc.stderr[-1500:]}")
        if proc.returncode == 0:
            with open(os.path.join(ck, "infos.json")) as f:
                assert json.load(f)["last_step"] == 4, \
                    "clean run 2 must retrain steps 2..4"

    @pytest.mark.slow
    def test_all_checkpoints_torn_starts_fresh(self, data, tmp_path):
        """When EVERY checkpoint is torn, auto-resume must quarantine them
        all and start the stage from scratch (logged), not crash in orbax
        deserialization."""
        ck = str(tmp_path / "all_torn")
        proc = run_train_cli(data, ck, **{"--max_epochs": ["1"],
                                          "--save_every_steps": ["1"]})
        assert proc.returncode == 0, proc.stderr[-2000:]
        from cst_captioning_tpu.training.checkpoint import CheckpointManager

        for sub in (".", "recovery"):
            base = os.path.join(ck, sub)
            for name in os.listdir(base):
                if name.isdigit():
                    CheckpointManager._tear_step(os.path.join(base, name))
        proc = run_train_cli(data, ck, **{"--max_epochs": ["1"]})
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert "resumed from" not in proc.stderr, "must not resume torn state"
        assert proc.stderr.count("quarantined torn checkpoint") == 2
        # Fresh-start proof from durable artifacts: run 2 re-logs train
        # steps 1 and 2, so both appear twice across the two runs.
        m = Counter()
        with open(os.path.join(ck, "metrics.jsonl")) as f:
            for line in f:
                rec = json.loads(line)
                if rec.get("scope") == "train":
                    m[rec["step"]] += 1
        assert m[1] == 2 and m[2] == 2, dict(m)
        assert infos(ck)["last_step"] == 2


# -- preemption drills (subprocess; signal -> boundary save -> exit 75) ----

@pytest.fixture(scope="module")
def twin_run(data, tmp_path_factory):
    """Uninterrupted reference run (same seed/config as the drills): the
    preempted-and-resumed runs must reproduce its metrics stream — and its
    final params — bit-for-bit."""
    ck = str(tmp_path_factory.mktemp("twin") / "xe")
    proc = run_train_cli(data, ck)
    assert proc.returncode == 0, proc.stderr[-2000:]
    return ck


def _summary_json(proc):
    for line in reversed(proc.stdout.splitlines()):
        if line.strip().startswith("{"):
            return json.loads(line)
    raise AssertionError(f"no summary JSON on stdout: {proc.stdout!r}")


def _skip_if_native_restore_death(proc):
    """The documented environment defect (RESILIENCE.md caveat): a process
    that orbax-restores and keeps training can die in tensorstore with a
    signal.  The preemption semantics under test are asserted from durable
    artifacts BEFORE this call; only the clean-completion half is skipped,
    and only on that exact signature."""
    if proc.returncode < 0:
        pytest.skip("documented native restore instability (RESILIENCE.md): "
                    f"resumed child died with signal {-proc.returncode}; "
                    f"stderr tail: {proc.stderr.strip()[-160:]}")


PARAMS_COMPARE = """\
import sys
import jax
import numpy as np
import orbax.checkpoint as ocp

a = ocp.StandardCheckpointer().restore(sys.argv[1])
b = ocp.StandardCheckpointer().restore(sys.argv[2])
la = jax.tree_util.tree_leaves(a)
lb = jax.tree_util.tree_leaves(b)
assert len(la) == len(lb), (len(la), len(lb))
if all(np.array_equal(np.asarray(x), np.asarray(y))
       for x, y in zip(la, lb)):
    print("PARAMS_IDENTICAL")
else:
    print("PARAMS_DIFFER")
"""


def _assert_params_bit_identical(tmp_path, ck_a, ck_b, step):
    """Compare the two runs' step-``step`` params trees in a FRESH
    subprocess (orbax restore is contained, per the RESILIENCE.md caveat);
    a child killed by the documented native defect skips, a PARAMS_DIFFER
    verdict fails."""
    script = tmp_path / "params_compare.py"
    script.write_text(PARAMS_COMPARE)
    env = dict(os.environ)
    env["PYTHONPATH"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, str(script),
         os.path.join(ck_a, str(step), "params"),
         os.path.join(ck_b, str(step), "params")],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=120)
    if proc.returncode < 0:
        pytest.skip("documented native restore instability: params "
                    f"comparator died with signal {-proc.returncode}")
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "PARAMS_IDENTICAL" in proc.stdout, \
        "resumed run's final params differ from the uninterrupted twin's"


@pytest.mark.e2e
@pytest.mark.slow
class TestPreemptionEndToEnd:
    """The full preemption cycle over the real train.py CLI: a REAL
    SIGTERM (delivered by the preempt fault kind) -> checkpoint-requested
    flag -> boundary save through the manifest/integrity path -> exit with
    the taxonomy's resumable code -> fresh-process resume that ends
    bit-identical to an uninterrupted run of the same seed/config."""
    # 4 videos / batch 2 -> bpe 2; 2 epochs -> 4 steps total.

    def test_preempt_fault_saves_verified_checkpoint_and_exits_75(
            self, data, tmp_path, twin_run):
        from cst_captioning_tpu.resilience.exitcodes import EXIT_PREEMPTED

        ck = str(tmp_path / "preempt")
        proc = run_train_cli(data, ck,
                             **{"--fault_plan": ["preempt@step=0"]})
        assert proc.returncode == EXIT_PREEMPTED, (
            f"rc={proc.returncode}\n{proc.stderr[-2000:]}")
        assert "Traceback" not in proc.stderr
        assert "FAULT INJECTED: preempt" in proc.stderr
        assert "preemption (SIGTERM) honored at step boundary 1" \
            in proc.stderr
        summary = _summary_json(proc)
        assert summary == {"preempted": "SIGTERM", "step": 1, "saved": True,
                           "checkpoint_path": ck}
        # The boundary save went through the integrity path and verifies.
        assert verify_step_dir(os.path.join(ck, "recovery", "1"))[0] \
            == "verified"
        # Telemetry audit trail (exit snapshot).
        with open(os.path.join(ck, "telemetry.json")) as f:
            tel = json.load(f)
        assert tel["counters"]["preempt_signals"] >= 1
        assert tel["counters"]["preempt_saves"] == 1
        assert tel["counters"]["fault_preempt"] == 1
        assert tel["gauges"]["preempt_exit_ms"] >= 0

        # Restart with the SAME plan (the scale_chain shape): the firing
        # is single-shot across processes, so the resume trains through.
        res = run_train_cli(data, ck, **{"--fault_plan": ["preempt@step=0"]})
        assert "resumed from step 1" in res.stderr, res.stderr[-2000:]
        # (Metrics equality waits for the death check: a child dying of
        # the native defect can log a silently-garbled tail value — the
        # RESILIENCE.md "garbage scalar reads" form — which is not a
        # resume regression.)
        _skip_if_native_restore_death(res)
        assert res.returncode == 0, res.stderr[-2000:]
        assert infos(ck)["last_step"] == 4
        # Post-resume metrics continue the twin's stream bit-exactly.
        m, mt = train_metrics(ck), train_metrics(twin_run)
        assert set(m) >= {2, 3, 4}
        for s in sorted(set(m) & set(mt)):
            assert m[s]["loss"] == mt[s]["loss"], (
                f"step {s}: resumed loss {m[s]['loss']} != twin "
                f"{mt[s]['loss']} — resume is not deterministic")

    def test_preempt_resume_is_bit_identical_to_twin(self, data, tmp_path,
                                                     twin_run):
        """The acceptance drill's bit-exactness half.  preempt@step=1 is
        honored at boundary step 2, which an epoch-boundary save just made
        durable — so this also pins the redundant-save skip — and the
        resume restores a best-manager checkpoint (the stable restore
        shape in this environment, so the comparison usually completes
        instead of skipping on the native defect)."""
        from cst_captioning_tpu.resilience.exitcodes import EXIT_PREEMPTED

        ck = str(tmp_path / "preempt2")
        proc = run_train_cli(data, ck,
                             **{"--fault_plan": ["preempt@step=1"]})
        assert proc.returncode == EXIT_PREEMPTED, (
            f"rc={proc.returncode}\n{proc.stderr[-2000:]}")
        assert "checkpoint already current" in proc.stderr
        assert _summary_json(proc)["saved"] is False
        with open(os.path.join(ck, "telemetry.json")) as f:
            tel = json.load(f)
        assert tel["counters"]["preempt_saves"] == 0

        res = run_train_cli(data, ck, **{"--fault_plan": ["preempt@step=1"]})
        assert "resumed from step 2" in res.stderr, res.stderr[-2000:]
        _skip_if_native_restore_death(res)
        assert res.returncode == 0, res.stderr[-2000:]
        assert infos(ck)["last_step"] == 4 == infos(twin_run)["last_step"]
        m, mt = train_metrics(ck), train_metrics(twin_run)
        assert set(m) == {1, 2, 3, 4}
        for s in (1, 2, 3, 4):
            assert m[s]["loss"] == mt[s]["loss"], (
                f"step {s}: resumed loss {m[s]['loss']} != twin "
                f"{mt[s]['loss']} — resume is not deterministic")
        # The headline claim: final params bit-identical to the twin's.
        _assert_params_bit_identical(tmp_path, ck, twin_run, 4)

    def test_plain_sigterm_exits_cleanly_within_one_step(self, data,
                                                         tmp_path):
        """SIGTERM delivered EXTERNALLY to a plain train.py run (no fault
        plan) — the spot-reclaim shape: the run must exit via the
        checkpoint-and-exit path (rc 75, verified save, JSON summary),
        never via a traceback.  The loader is throttled so the kill
        reliably lands mid-run."""
        from cst_captioning_tpu.resilience.exitcodes import EXIT_PREEMPTED
        from conftest import CACHE_DIR

        ck = str(tmp_path / "sigterm")
        driver = tmp_path / "throttled_train.py"
        driver.write_text(
            "import sys, time\n"
            f"sys.path.insert(0, {REPO!r})\n"
            "from cst_captioning_tpu.data import loader as loader_mod\n"
            "_orig = loader_mod.CaptionLoader.next_batch\n"
            "def slow(self):\n"
            "    time.sleep(0.5)\n"
            "    return _orig(self)\n"
            "loader_mod.CaptionLoader.next_batch = slow\n"
            "import train as train_cli\n"
            "sys.exit(train_cli.main(sys.argv[1:]))\n")
        env = dict(os.environ)
        env["PYTHONPATH"] = ""
        env["JAX_PLATFORMS"] = "cpu"
        env.setdefault("JAX_COMPILATION_CACHE_DIR", CACHE_DIR)
        proc = subprocess.Popen(
            [sys.executable, str(driver),
             *chaos_argv(data, ck, **{"--max_epochs": ["50"]})],
            env=env, cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True)
        try:
            # Wait until the run is demonstrably mid-loop (first train
            # record durably logged), then deliver the reclaim signal.
            metrics = os.path.join(ck, "metrics.jsonl")
            deadline = time.time() + 240
            while time.time() < deadline:
                if os.path.exists(metrics) and open(metrics).read().strip():
                    break
                if proc.poll() is not None:
                    break
                time.sleep(0.1)
            assert proc.poll() is None, "run ended before it could be killed"
            proc.send_signal(signal.SIGTERM)
            out, err = proc.communicate(timeout=240)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.communicate()
        assert proc.returncode == EXIT_PREEMPTED, (
            f"rc={proc.returncode}\nstdout:{out[-2000:]}\nstderr:"
            f"{err[-2000:]}")
        assert "Traceback" not in err, err[-2000:]
        assert "will checkpoint and exit at the next step boundary" in err
        summary = json.loads(
            [ln for ln in out.splitlines() if ln.strip().startswith("{")][-1])
        assert summary["preempted"] == "SIGTERM"
        saved_step = summary["step"]
        if summary["saved"]:
            assert verify_step_dir(os.path.join(
                ck, "recovery", str(saved_step)))[0] == "verified"

    def test_save_interval_secs_bounds_lost_work_by_wallclock(self, data,
                                                              tmp_path):
        """--save_interval_secs: with a tiny interval every non-epoch step
        boundary produces a recovery save; with a huge one, none do (the
        wall clock, not the step count, is what gates)."""
        ck = str(tmp_path / "interval")
        proc = run_train_cli(data, ck,
                             **{"--save_interval_secs": ["0.001"]})
        assert proc.returncode == 0, proc.stderr[-2000:]
        # bpe 2, 4 steps: interval saves at steps 1 and 3 (recovery keeps
        # the newest), epoch saves at 2 and 4.
        assert verify_step_dir(os.path.join(ck, "recovery", "3"))[0] \
            == "verified"
        with open(os.path.join(ck, "telemetry.json")) as f:
            assert json.load(f)["counters"]["checkpoints_saved"] == 4

        ck2 = str(tmp_path / "interval_off")
        proc = run_train_cli(data, ck2,
                             **{"--save_interval_secs": ["3600"]})
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert not os.path.isdir(os.path.join(ck2, "recovery"))
        with open(os.path.join(ck2, "telemetry.json")) as f:
            assert json.load(f)["counters"]["checkpoints_saved"] == 2


# -- wedge drill (subprocess; the watchdog must exit 124) ------------------

WEDGE_DRIVER = """\
import sys, json
sys.path.insert(0, %(repo)r)
from cst_captioning_tpu.data.synthetic import SyntheticSpec, generate
import train as train_cli

root = sys.argv[1]
# Shapes/model dims deliberately MATCH the chaos ``data`` fixture runs so
# the persistent compile cache makes step 0 fast — the wedge must be what
# trips the watchdog, not a cold first compile.
spec = SyntheticSpec(num_videos=4, captions_per_video=4, max_len=10,
                     feat_dims=(12, 6), feat_times=(3, 1))
train = generate(root, "train", spec)
train_cli.main([
    "--train_feat_h5", *json.loads(train["feat_h5"]),
    "--train_label_h5", train["label_h5"],
    "--train_info_json", train["info_json"],
    "--train_cocofmt_file", train["cocofmt_json"],
    "--checkpoint_path", root + "/ck",
    "--batch_size", "2", "--seq_per_img", "2", "--rnn_size", "16",
    "--input_encoding_size", "16", "--att_size", "16",
    "--drop_prob", "0.0", "--max_length", "10",
    "--max_epochs", "1", "--log_every", "1", "--seed", "0",
    "--save_every_steps", "1",
    "--wedge_timeout", "30",
    "--fault_plan", "wedge@step=1",
])
print("UNREACHABLE")
"""


@pytest.mark.e2e
@pytest.mark.slow
def test_wedge_fault_exits_124_with_checkpoint(tmp_path):
    """``wedge@step=1`` blocks the loop after step 1's recovery save; the
    armed watchdog must exit WEDGE_EXIT_CODE with the step-1 checkpoint
    intact on disk — exactly what scale_chain's resume loop needs."""
    from cst_captioning_tpu.utils.watchdog import WEDGE_EXIT_CODE

    script = tmp_path / "wedge_drill.py"
    script.write_text(WEDGE_DRIVER % {"repo": REPO})
    env = dict(os.environ)
    env["PYTHONPATH"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    from conftest import CACHE_DIR

    env.setdefault("JAX_COMPILATION_CACHE_DIR", CACHE_DIR)
    proc = subprocess.run(
        [sys.executable, str(script), str(tmp_path / "d")],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == WEDGE_EXIT_CODE, (
        f"rc={proc.returncode}\nstdout:{proc.stdout[-2000:]}\n"
        f"stderr:{proc.stderr[-2000:]}")
    assert "UNREACHABLE" not in proc.stdout
    rec = tmp_path / "d" / "ck" / "recovery" / "1"
    assert rec.is_dir(), "step-1 recovery checkpoint missing after wedge"
    assert verify_step_dir(str(rec))[0] == "verified"
