import json

import numpy as np
import pytest

from cst_captioning_tpu.data import (
    Batch,
    CaptionDataset,
    CaptionLoader,
    PAD_EOS,
    Vocab,
    build_vocab,
    prefetch_to_device,
)
from cst_captioning_tpu.data.synthetic import SyntheticSpec, generate, split_paths
from cst_captioning_tpu.metrics.consensus import load_consensus


@pytest.fixture(scope="module")
def synth(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("synth"))
    paths = generate(root, "train", SyntheticSpec(num_videos=8, captions_per_video=5))
    return paths


@pytest.fixture(scope="module")
def ds(synth):
    return CaptionDataset(split_paths(synth))


class TestVocab:
    def test_roundtrip(self):
        v = build_vocab([["a", "dog", "runs"], ["a", "cat"]])
        ids = v.encode(["a", "dog", "runs"], max_len=6)
        assert ids.shape == (6,)
        assert v.decode(ids) == "a dog runs"

    def test_zero_reserved(self):
        v = build_vocab([["word"]])
        assert 0 not in v.ix_to_word
        with pytest.raises(ValueError):
            Vocab({0: "bad"})

    def test_unknown_maps_to_unk(self):
        v = build_vocab([["a", "dog"]])
        ids = v.encode(["a", "zebra"], max_len=4)
        assert v.decode(ids) == "a <unk>"

    def test_decode_stops_at_eos(self):
        v = build_vocab([["a", "dog"]])
        a, dog = v.word_to_ix["a"], v.word_to_ix["dog"]
        assert v.decode([a, PAD_EOS, dog]) == "a"


class TestDataset:
    def test_shapes(self, ds):
        assert ds.num_videos == 8
        assert ds.feat_dims == [32, 16]
        assert ds.feat_times == [4, 1]
        assert ds.seq_length == 16

    def test_features_batch(self, ds):
        feats = ds.features(np.array([3, 1, 1, 6]))
        assert feats[0].shape == (4, 4, 32)
        assert feats[1].shape == (4, 1, 16)
        # duplicate + order preserved
        np.testing.assert_array_equal(feats[0][1], feats[0][2])
        single = ds.features(np.array([3]))[0][0]
        np.testing.assert_array_equal(feats[0][0], single)

    def test_captions(self, ds):
        caps = ds.captions_for(0)
        assert caps.shape == (5, 16)
        assert caps.dtype == np.int32
        assert (caps[:, 0] != 0).all()  # every caption starts with a word

    def test_references_from_cocofmt(self, ds):
        refs = ds.references()
        assert len(refs) == 8
        assert all(len(v) == 5 for v in refs.values())

    def test_mismatched_videos_raises(self, synth, tmp_path):
        import h5py
        from cst_captioning_tpu.data.dataset import SplitPaths

        bad_info = tmp_path / "bad_info.json"
        with open(synth["info_json"]) as f:
            info = json.load(f)
        info["videos"] = info["videos"][:-1]
        bad_info.write_text(json.dumps(info))
        sp = split_paths(synth)
        with pytest.raises(ValueError):
            CaptionDataset(SplitPaths(feat_h5=sp.feat_h5, label_h5=sp.label_h5,
                                      info_json=str(bad_info)))


class TestLoader:
    def test_batch_shapes(self, ds):
        loader = CaptionLoader(ds, batch_size=4, seq_per_img=3, seed=1)
        b = loader.next_batch()
        assert b.feats[0].shape == (4, 4, 32)
        assert b.labels.shape == (12, 16)
        assert b.weights.shape == (12,)
        assert len(b.video_ids) == 4

    def test_epoch_wrap_covers_all_videos(self, ds):
        loader = CaptionLoader(ds, batch_size=3, seq_per_img=2, seed=0)
        seen = set()
        for _ in range(6):  # 18 draws over 8 videos
            seen.update(loader.next_batch().video_ids)
        assert len(seen) == 8
        assert loader.epoch >= 2

    def test_deterministic_given_seed(self, ds):
        a = CaptionLoader(ds, batch_size=4, seq_per_img=2, seed=7).next_batch()
        b = CaptionLoader(ds, batch_size=4, seq_per_img=2, seed=7).next_batch()
        assert a.video_ids == b.video_ids
        np.testing.assert_array_equal(a.labels, b.labels)

    def test_consensus_weights_applied(self, ds, synth):
        weights = load_consensus(synth["wxe_weights_pkl"])
        loader = CaptionLoader(ds, batch_size=4, seq_per_img=5, shuffle=False,
                               consensus_weights=weights)
        b = loader.next_batch()
        assert not np.allclose(b.weights, 1.0)  # real consensus variation
        # per-video mean weight ~1 (normalize_weights contract)
        for i in range(4):
            assert b.weights[i * 5 : (i + 1) * 5].mean() == pytest.approx(1.0, abs=1e-5)

    def test_preload_matches_lazy(self, synth):
        from cst_captioning_tpu.data.synthetic import split_paths as sp

        lazy = CaptionDataset(sp(synth))
        hot = CaptionDataset(sp(synth), preload=True)
        ix = np.array([3, 0, 3, 5])
        for a, b in zip(lazy.features(ix), hot.features(ix)):
            np.testing.assert_array_equal(a, b)
        assert hot.feat_dims == lazy.feat_dims
        hot.close()  # no-op file list; must not raise
        lazy.close()

    def test_gts_for_reward(self, ds):
        loader = CaptionLoader(ds, batch_size=2, seq_per_img=2, include_gts=True)
        b = loader.next_batch()
        assert set(b.gts.keys()) == set(b.video_ids)

    def test_host_sharding_disjoint(self, ds):
        l0 = CaptionLoader(ds, batch_size=2, process_index=0, process_count=2)
        l1 = CaptionLoader(ds, batch_size=2, process_index=1, process_count=2)
        assert set(l0._my_videos.tolist()).isdisjoint(l1._my_videos.tolist())
        assert len(l0._my_videos) + len(l1._my_videos) == 8

    def test_eval_iteration_covers_split_once(self, ds):
        loader = CaptionLoader(ds, batch_size=3, shuffle=False)
        ids = []
        for b in loader.iter_eval():
            ids.extend(b.video_ids)
        assert len(ids) == 9  # 3 batches of 3 (last wraps)
        assert set(ids) == set(ds.video_ids)

    def test_prefetch_matches_direct(self, ds):
        direct = CaptionLoader(ds, batch_size=2, seq_per_img=2, seed=3)
        pref = CaptionLoader(ds, batch_size=2, seq_per_img=2, seed=3)
        it = prefetch_to_device(iter(pref), size=2)
        for _ in range(3):
            a, b = direct.next_batch(), next(it)
            assert a.video_ids == b.video_ids
            np.testing.assert_array_equal(a.labels, b.labels)

    def test_prefetch_device_put_applied(self, ds):
        import jax.numpy as jnp
        loader = CaptionLoader(ds, batch_size=2, seq_per_img=2)
        it = prefetch_to_device(iter(loader), device_put=jnp.asarray)
        b = next(it)
        assert isinstance(b.labels, jnp.ndarray)

    def test_prefetch_feat_dtype_casts_feats_only(self, ds):
        """--bf16_feats: features are cast on the host before the transfer
        (half the wire bytes); labels/weights keep their exact dtypes."""
        import ml_dtypes

        ref = CaptionLoader(ds, batch_size=2, seq_per_img=2, seed=5)
        loader = CaptionLoader(ds, batch_size=2, seq_per_img=2, seed=5)
        it = prefetch_to_device(iter(loader), feat_dtype=ml_dtypes.bfloat16)
        a, b = ref.next_batch(), next(it)
        for fa, fb in zip(a.feats, b.feats):
            assert fb.dtype == ml_dtypes.bfloat16
            np.testing.assert_allclose(
                fa, fb.astype(np.float32), rtol=1e-2, atol=1e-2)
        assert b.labels.dtype == np.int32
        assert b.weights.dtype == np.float32
        np.testing.assert_array_equal(a.labels, b.labels)


class TestPrepro:
    def test_cli_roundtrip(self, tmp_path):
        anns = {"videos": [
            {"id": "v0", "captions": ["A man is cooking.", "a man cooks"]},
            {"id": "v1", "captions": ["A dog runs.", "the dog is running"]},
        ]}
        ann_path = tmp_path / "anns.json"
        ann_path.write_text(json.dumps(anns))
        from cst_captioning_tpu.data.prepro import main

        paths = main(["--annotations", str(ann_path), "--split", "train",
                      "--out_dir", str(tmp_path / "out"), "--max_len", "8"])
        from cst_captioning_tpu.data.dataset import SplitPaths

        ds = CaptionDataset(SplitPaths(
            feat_h5=[], label_h5=paths["label_h5"], info_json=paths["info_json"],
            cocofmt_json=paths["cocofmt_json"]))
        assert ds.num_videos == 2
        # vocab round-trips through the label encoding
        assert ds.vocab.decode(ds.captions_for(0)[1]) == "a man cooks"
        refs = ds.references()
        assert refs["v0"] == ["A man is cooking.", "a man cooks"]


class TestReviewRegressions:
    def test_encode_no_eos_hole_without_unk(self):
        v = build_vocab([["a", "dog"]], add_unk=False)
        ids = v.encode(["a", "zebra", "dog"], max_len=4)
        # unknown word dropped, no 0-hole: "dog" must survive
        assert v.decode(ids) == "a dog"

    def test_iter_eval_static_shape_tiny_shard(self, ds):
        loader = CaptionLoader(ds, batch_size=20, shuffle=False)  # 20 > 2*8
        batches = list(loader.iter_eval())
        assert len(batches) == 1
        assert batches[0].feats[0].shape[0] == 20
        assert len(batches[0].video_ids) == 20

    def test_synthetic_reproducible_across_calls(self, tmp_path):
        from cst_captioning_tpu.data.synthetic import SyntheticSpec, generate
        import h5py
        a = generate(str(tmp_path / "a"), "val", SyntheticSpec(num_videos=3))
        b = generate(str(tmp_path / "b"), "val", SyntheticSpec(num_videos=3))
        with h5py.File(json.loads(a["feat_h5"])[0]) as fa, \
             h5py.File(json.loads(b["feat_h5"])[0]) as fb:
            np.testing.assert_array_equal(fa["feats"][:], fb["feats"][:])

    def test_prefetch_early_exit_releases_worker(self, ds):
        import threading
        before = threading.active_count()
        loader = CaptionLoader(ds, batch_size=2, seq_per_img=2)
        it = prefetch_to_device(iter(loader), size=2)
        next(it)
        it.close()  # consumer walks away from the infinite stream
        import time
        for _ in range(50):
            if threading.active_count() <= before:
                break
            time.sleep(0.05)
        assert threading.active_count() <= before

    def test_zero_caption_video_rejected_at_prepro(self, tmp_path):
        from cst_captioning_tpu.data.prepro import build_split
        with pytest.raises(ValueError, match="zero captions"):
            build_split([{"id": "v0", "captions": []}], str(tmp_path), "train")

    def test_model_tx_max_len_plumbed(self):
        import jax
        import jax.numpy as jnp
        from cst_captioning_tpu.models import CaptionModel
        m = CaptionModel(vocab_size=8, embed_size=8, hidden_size=8,
                         decoder_type="transformer", num_heads=2,
                         tx_max_len=96, dropout_rate=0.0)
        feats = [jnp.ones((1, 2, 4))]
        labels = jnp.zeros((1, 80), jnp.int32)
        v = m.init(jax.random.key(0), feats, labels)
        assert m.apply(v, feats, labels).shape == (1, 80, 8)


class TestRichSyntheticGrammar:
    """SyntheticSpec.rich_vocab — the MSR-VTT-scale dataset generator
    (scripts/scale_chain.py) must have the statistics that make the
    staged training evidence meaningful."""

    def _gen(self, tmp_path, n_train=12, n_val=6, rich=300):
        spec = SyntheticSpec(num_videos=n_train, captions_per_video=10,
                             max_len=30, feat_dims=(64, 32),
                             feat_times=(4, 1), rich_vocab=rich)
        train = generate(str(tmp_path), "train", spec)
        from cst_captioning_tpu.data.vocab import load_vocab
        vocab = load_vocab(train["vocab_json"])
        val_spec = SyntheticSpec(num_videos=n_val, captions_per_video=10,
                                 max_len=30, feat_dims=(64, 32),
                                 feat_times=(4, 1), rich_vocab=rich)
        val = generate(str(tmp_path), "val", val_spec, vocab=vocab)
        return train, val, vocab

    def test_degenerate_word_exposure_warns(self, tmp_path, caplog):
        """A corpus whose median content word lives in one video is
        unlearnable (round-4 field collapse at 640 videos x 8k pools);
        the generator must say so loudly at generation time."""
        import logging

        spec = SyntheticSpec(num_videos=6, captions_per_video=6,
                             max_len=30, feat_dims=(32,), feat_times=(2,),
                             rich_vocab=4000)  # huge pools, few videos
        with caplog.at_level(logging.WARNING,
                             logger="cst_captioning_tpu.data.synthetic"):
            generate(str(tmp_path / "degen"), "train", spec)
        assert any("DEGENERATE" in r.message for r in caplog.records)

    def test_healthy_word_exposure_is_silent(self, tmp_path, caplog):
        import logging

        spec = SyntheticSpec(num_videos=40, captions_per_video=6,
                             max_len=30, feat_dims=(32,), feat_times=(2,),
                             rich_vocab=30)  # tiny pools -> median ~6
        with caplog.at_level(logging.WARNING,
                             logger="cst_captioning_tpu.data.synthetic"):
            generate(str(tmp_path / "healthy"), "train", spec)
        assert not any("DEGENERATE" in r.message for r in caplog.records)
        assert not any("THIN word exposure" in r.message
                       for r in caplog.records)

    def test_thin_word_exposure_warns(self, tmp_path, caplog):
        """Median videos-per-word in (1, 4) is the template-collapse zone
        (round-5 field: median 2 at 512 videos x 1500 pools -> beam
        decodes collapsed to 6 function-word templates): warn, with a
        distinct message from the hard DEGENERATE case."""
        import logging

        spec = SyntheticSpec(num_videos=40, captions_per_video=6,
                             max_len=30, feat_dims=(32,), feat_times=(2,),
                             rich_vocab=60)  # pools sized for median ~3
        with caplog.at_level(logging.WARNING,
                             logger="cst_captioning_tpu.data.synthetic"):
            generate(str(tmp_path / "thin"), "train", spec)
        assert any("THIN word exposure" in r.message for r in caplog.records)
        assert not any("DEGENERATE" in r.message for r in caplog.records)

    def test_val_vocabulary_subset_of_train(self, tmp_path):
        """Val concepts must be train-realized words: otherwise val
        metrics measure vocabulary luck, not learning (round-4 review)."""
        train, val, vocab = self._gen(tmp_path)
        with open(val["cocofmt_json"]) as f:
            coco = json.load(f)
        from cst_captioning_tpu.metrics import tokenize
        known = set(vocab.word_to_ix)
        for ann in coco["annotations"]:
            for w in tokenize(ann["caption"]):
                assert w in known, f"val word {w!r} unseen in train"

    def test_consensus_gap_structure(self, tmp_path):
        """Each video needs a DOMINANT caption form (consensus target)
        plus minority variants (likelihood-vs-consensus gap) — the
        structure CST exploits (arXiv:1712.09532 premise)."""
        import collections

        train, _, _ = self._gen(tmp_path)
        with open(train["cocofmt_json"]) as f:
            coco = json.load(f)
        per_vid = collections.defaultdict(list)
        for ann in coco["annotations"]:
            per_vid[str(ann["image_id"])].append(ann["caption"])
        for vid, caps in per_vid.items():
            counts = collections.Counter(caps)
            top_frac = counts.most_common(1)[0][1] / len(caps)
            assert 0.4 <= top_frac < 1.0, (
                f"{vid}: dominant form fraction {top_frac} outside the "
                "consensus-gap band")
            assert len(counts) >= 3, f"{vid}: no paraphrase diversity"

    def test_rich_vocab_scales(self, tmp_path):
        _, _, vocab = self._gen(tmp_path, n_train=40, rich=400)
        # 40 videos x (4 concept words + up to 4 noise adjs) from ~400
        # pools: the realized vocab must clearly exceed the tiny grammar's
        # ~20 words and include noise adjectives
        assert len(vocab) > 60
        assert any(w.startswith("adj") for w in vocab.word_to_ix)

    def test_rich_needs_five_captions(self, tmp_path):
        """< 5 captions/video cannot realize the 60/20/20 form mix (no
        adjectives, no consensus gap) — must fail loudly, not silently
        produce a gapless dataset (round-4 review)."""
        spec = SyntheticSpec(num_videos=4, captions_per_video=4,
                             rich_vocab=100, feat_dims=(16,),
                             feat_times=(1,))
        with pytest.raises(ValueError, match="captions_per_video"):
            generate(str(tmp_path), "train", spec)
