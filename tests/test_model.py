import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from cst_captioning_tpu.models import CaptionModel, shift_right
from cst_captioning_tpu.ops.losses import cross_entropy_loss

VOCAB = 12  # ids 0..11, 0 = PAD/EOS
B, L = 2, 6
# distinct per-video features: the overfit test needs feats -> caption to be
# a function (identical features with different targets would be unlearnable)
_fk = jax.random.key(42)
FEATS = [jax.random.normal(jax.random.fold_in(_fk, 0), (B, 4, 8)),
         jax.random.normal(jax.random.fold_in(_fk, 1), (B, 1, 5))]


def make_model(**kw):
    defaults = dict(vocab_size=VOCAB, embed_size=16, hidden_size=16,
                    attn_size=16, dropout_rate=0.0)
    defaults.update(kw)
    return CaptionModel(**defaults)


@pytest.fixture(scope="module",
                params=["lstm", "lstm_noattn", "lstm_manet", "transformer"])
def model_and_vars(request):
    kind = request.param
    kw = {}
    if kind == "lstm_noattn":
        kw = {"use_attention": False}
    elif kind == "lstm_manet":
        kw = {"fusion_type": "modality"}  # attention over modality tokens
    elif kind == "transformer":
        kw = {"decoder_type": "transformer", "num_heads": 2, "num_tx_layers": 2}
    model = make_model(**kw)
    labels = jnp.array([[3, 4, 5, 0, 0, 0], [6, 7, 0, 0, 0, 0]])
    variables = model.init(jax.random.key(0), FEATS, labels)
    return model, variables


class TestForward:
    def test_logit_shape(self, model_and_vars):
        model, variables = model_and_vars
        labels = jnp.array([[3, 4, 5, 0, 0, 0], [6, 7, 0, 0, 0, 0]])
        logits = model.apply(variables, FEATS, labels)
        assert logits.shape == (B, L, VOCAB)
        assert np.isfinite(np.asarray(logits)).all()

    def test_seq_per_img_expansion(self, model_and_vars):
        model, variables = model_and_vars
        labels = jnp.tile(jnp.array([[3, 4, 0, 0, 0, 0]]), (B * 3, 1))
        logits = model.apply(variables, FEATS, labels, seq_per_img=3)
        assert logits.shape == (B * 3, L, VOCAB)
        # captions of the same video see identical features -> identical logits
        np.testing.assert_allclose(np.asarray(logits[0]), np.asarray(logits[1]),
                                   rtol=1e-5)

    def test_causality(self, model_and_vars):
        """Changing a later input token must not affect earlier logits."""
        model, variables = model_and_vars
        a = jnp.array([[3, 4, 5, 6, 7, 8]])
        b = jnp.array([[3, 4, 5, 6, 9, 10]])  # differs from t=4 on
        feats1 = [f[:1] for f in FEATS]
        la = model.apply(variables, feats1, a)
        lb = model.apply(variables, feats1, b)
        # inputs are shift_right(labels): position t sees labels[:t]
        np.testing.assert_allclose(np.asarray(la[:, :5]), np.asarray(lb[:, :5]),
                                   atol=1e-5)

    def test_features_matter(self, model_and_vars):
        model, variables = model_and_vars
        labels = jnp.array([[3, 4, 5, 0, 0, 0], [6, 7, 0, 0, 0, 0]])
        base = model.apply(variables, FEATS, labels)
        other = model.apply(variables, [f * 2.0 for f in FEATS], labels)
        assert not np.allclose(np.asarray(base), np.asarray(other))


class TestDecodeStepConsistency:
    def test_stepwise_matches_teacher_forced(self, model_and_vars):
        """Driving decode() one token at a time must reproduce the
        teacher-forced logits — the property that makes sampling and
        training consistent."""
        model, variables = model_and_vars
        labels = jnp.array([[3, 4, 5, 2, 1, 6]])
        feats1 = [f[:1] for f in FEATS]
        full = model.apply(variables, feats1, labels)

        memory, proj_mem, pooled = model.apply(variables, feats1,
                                               method=CaptionModel.encode)
        carry = model.apply(variables, pooled, L,
                            method=CaptionModel.init_carry)
        inputs = shift_right(labels)
        step_logits = []
        for t in range(L):
            carry, lg = model.apply(variables, carry, inputs[:, t:t+1],
                                    memory, proj_mem, pooled,
                                    method=CaptionModel.decode)
            step_logits.append(lg[:, 0])
        np.testing.assert_allclose(np.asarray(jnp.stack(step_logits, 1)),
                                   np.asarray(full), atol=1e-4)


class TestTraining:
    def test_overfits_tiny_batch(self, model_and_vars):
        """XE loss must drive toward zero on a fixed batch (SURVEY §4:
        overfit-to-zero integration test)."""
        model, variables = model_and_vars
        labels = jnp.array([[3, 4, 5, 0, 0, 0], [6, 7, 0, 0, 0, 0]])
        tx = optax.adam(1e-2)
        params = variables["params"]
        opt_state = tx.init(params)

        @jax.jit
        def step(params, opt_state):
            def loss_fn(p):
                logits = model.apply({"params": p}, FEATS, labels)
                return cross_entropy_loss(logits, labels)
            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = tx.update(grads, opt_state)
            return optax.apply_updates(params, updates), opt_state, loss

        first = None
        for i in range(150):
            params, opt_state, loss = step(params, opt_state)
            if first is None:
                first = float(loss)
        assert float(loss) < 0.1, f"loss stuck at {float(loss)} (from {first})"

    def test_dropout_requires_rng_and_varies(self):
        model = make_model(dropout_rate=0.5)
        labels = jnp.array([[3, 4, 5, 0, 0, 0], [6, 7, 0, 0, 0, 0]])
        variables = model.init(jax.random.key(0), FEATS, labels)
        a = model.apply(variables, FEATS, labels, train=True,
                        rngs={"dropout": jax.random.key(1)})
        b = model.apply(variables, FEATS, labels, train=True,
                        rngs={"dropout": jax.random.key(2)})
        assert not np.allclose(np.asarray(a), np.asarray(b))
        # eval mode is deterministic
        c = model.apply(variables, FEATS, labels)
        d = model.apply(variables, FEATS, labels)
        np.testing.assert_allclose(np.asarray(c), np.asarray(d))


def test_unknown_decoder_type_raises():
    with pytest.raises(ValueError):
        make_model(decoder_type="gru").init(
            jax.random.key(0), FEATS, jnp.zeros((B, L), jnp.int32)
        )


def test_remat_cell_preserves_numerics():
    """--remat_cell recomputes the decoder cell in backward instead of
    storing its residuals; same params, same loss, same gradients (f32)."""
    labels = jnp.array([[3, 4, 5, 0, 0, 0], [6, 7, 0, 0, 0, 0]])
    weights = jnp.ones((B,))
    base = make_model(remat_cell=False)
    remat = make_model(remat_cell=True)
    variables = base.init(jax.random.key(0), FEATS, labels)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(a, b),
        variables, remat.init(jax.random.key(0), FEATS, labels))

    def loss_fn(model):
        def f(params):
            logits = model.apply({"params": params["params"]}, FEATS, labels)
            return cross_entropy_loss(logits, labels, weights)
        return f

    l0, g0 = jax.value_and_grad(loss_fn(base))(variables)
    l1, g1 = jax.value_and_grad(loss_fn(remat))(variables)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6),
        g0, g1)


def test_scan_unroll_is_pure_performance():
    """--scan_unroll must not change numerics: same params (the unroll
    doesn't touch the param tree), same teacher-forced logits, same
    sampled tokens/logprobs at every factor — including one that doesn't
    divide the sequence length."""
    from cst_captioning_tpu.ops.sampling import sample_captions

    labels = jnp.array([[3, 4, 5, 0, 0, 0], [6, 7, 0, 0, 0, 0]])
    base = make_model(scan_unroll=1)
    variables = base.init(jax.random.key(0), FEATS, labels)
    ref_logits = base.apply(variables, FEATS, labels)
    ref_toks, ref_logp = sample_captions(
        base, variables, FEATS, jax.random.key(7), L, seq_per_img=2)
    for unroll in (2, 4):  # 4 does not divide L=6: remainder path covered
        m = make_model(scan_unroll=unroll)
        jax.tree_util.tree_map(  # param trees identical
            lambda a, b: np.testing.assert_array_equal(a, b),
            variables, m.init(jax.random.key(0), FEATS, labels))
        np.testing.assert_allclose(
            np.asarray(m.apply(variables, FEATS, labels)),
            np.asarray(ref_logits), rtol=1e-6, atol=1e-6)
        toks, logp = sample_captions(
            m, variables, FEATS, jax.random.key(7), L, seq_per_img=2)
        np.testing.assert_array_equal(np.asarray(toks), np.asarray(ref_toks))
        np.testing.assert_allclose(np.asarray(logp), np.asarray(ref_logp),
                                   rtol=1e-6, atol=1e-6)
