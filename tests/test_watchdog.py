"""Wedge resilience: ProgressWatchdog + trainer wiring + chain recovery.

The failure mode being pinned (observed twice in the field this round):
the remote-device transport wedges mid-run, the training process blocks
forever inside a C++ call, and hours of chip time die silently.  The
watchdog turns that into a fast exit 124; the scale-chain harness turns
exit 124 into probe-wait-resume.
"""

import json
import os
import subprocess
import sys
import time

import pytest

from cst_captioning_tpu.utils.watchdog import WEDGE_EXIT_CODE, ProgressWatchdog

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestProgressWatchdog:
    def test_fires_after_timeout_without_beats(self):
        fired = []
        wd = ProgressWatchdog(0.2, describe=lambda: "ctx",
                              on_timeout=lambda gap: fired.append(gap))
        wd.start()
        try:
            deadline = time.time() + 5.0
            while not fired and time.time() < deadline:
                time.sleep(0.05)
        finally:
            wd.stop()
        assert fired and fired[0] > 0.2

    def test_beats_prevent_firing(self):
        fired = []
        wd = ProgressWatchdog(0.6, on_timeout=lambda gap: fired.append(gap))
        wd.start()
        try:
            for _ in range(6):
                time.sleep(0.2)
                wd.beat()
        finally:
            wd.stop()
        assert not fired

    def test_stop_disarms(self):
        fired = []
        wd = ProgressWatchdog(0.3, on_timeout=lambda gap: fired.append(gap))
        wd.start()
        wd.stop()
        time.sleep(0.6)
        assert not fired

    def test_zero_timeout_is_noop(self):
        wd = ProgressWatchdog(0.0, on_timeout=lambda gap: pytest.fail("fired"))
        wd.start()
        assert wd._thread is None
        wd.beat()
        wd.stop()

    def test_context_manager(self):
        fired = []
        with ProgressWatchdog(0.2, on_timeout=lambda g: fired.append(g)) as wd:
            assert wd._thread is not None
        time.sleep(0.5)
        assert not fired

    def test_restart_after_stop_monitors_again(self):
        """stop() then start() must rearm monitoring — the _stop Event is
        cleared, so the restarted thread does not return immediately."""
        fired = []
        wd = ProgressWatchdog(0.2, on_timeout=lambda g: fired.append(g))
        wd.start()
        wd.stop()
        wd.start()
        try:
            deadline = time.time() + 5.0
            while not fired and time.time() < deadline:
                time.sleep(0.05)
        finally:
            wd.stop()
        assert fired, "restarted watchdog never fired"

    def test_rearms_after_non_exiting_handler(self):
        """An injected on_timeout that RETURNS (unlike the default
        os._exit) keeps the monitor alive: the heartbeat is rearmed and a
        second stall fires again instead of leaving the process
        unmonitored."""
        fired = []
        wd = ProgressWatchdog(0.2, on_timeout=lambda g: fired.append(g))
        wd.start()
        try:
            deadline = time.time() + 10.0
            while len(fired) < 2 and time.time() < deadline:
                time.sleep(0.05)
        finally:
            wd.stop()
        assert len(fired) >= 2, f"watchdog fired {len(fired)}x, wanted >=2"

    def test_rearm_measures_fresh_gap(self):
        """The rearm path (watchdog.py _run: beat() after a returning
        handler) must reset the reference point: every firing after the
        first reports a gap measured from the PREVIOUS firing, not an
        ever-growing gap since the last real beat.  Without the rearm the
        second gap would be ~2x the first and grow each poll."""
        fired = []
        wd = ProgressWatchdog(0.2, on_timeout=lambda g: fired.append(g))
        wd.start()
        try:
            deadline = time.time() + 10.0
            while len(fired) < 3 and time.time() < deadline:
                time.sleep(0.05)
        finally:
            wd.stop()
        assert len(fired) >= 3
        # poll interval is max(1.0, timeout/4) = 1.0s, so a FRESH gap is
        # bounded by timeout + ~one poll (plus slop); a cumulative gap
        # would exceed 2x that bound by the third firing.
        for i, gap in enumerate(fired[:3]):
            assert gap < 2.5, (
                f"firing {i} measured gap {gap:.2f}s — heartbeat was not "
                "rearmed after the handler returned")


class TestHeartbeatFile:
    """Watchdog heartbeat writes go through the telemetry registry
    (ISSUE 2 satellite): the file carries liveness PLUS the last-step
    phase timings and resilience counters an external harness wants."""

    def test_heartbeat_carries_registry_payload(self, tmp_path):
        from cst_captioning_tpu.telemetry import MetricsRegistry

        reg = MetricsRegistry()
        reg.inc("divergence_guard_trips", 2)
        reg.log_step(7, "train", {"loss": 1.5, "data_wait_ms": 0.3,
                                  "compute_ms": 12.5})
        hb = tmp_path / "ck" / "heartbeat.json"  # dir does not exist yet
        wd = ProgressWatchdog(30.0, heartbeat_path=str(hb),
                              payload=reg.heartbeat_payload)
        wd.start()
        try:
            deadline = time.time() + 10.0
            while not hb.exists() and time.time() < deadline:
                time.sleep(0.05)
            assert hb.exists(), "heartbeat never written at thread start"
            doc = json.loads(hb.read_text())
        finally:
            wd.stop()
        assert doc["pid"] == os.getpid()
        assert doc["timeout_s"] == 30.0
        assert doc["beat_gap_s"] >= 0
        # the enriched payload: last-step phase timings + counters
        assert doc["counters"]["divergence_guard_trips"] == 2
        assert doc["last_train"]["step"] == 7
        assert doc["last_train"]["compute_ms"] == 12.5

    def test_heartbeat_only_mode_without_kill_policy(self, tmp_path):
        """ISSUE 9 (serving health plane): heartbeat_interval_s + a path
        arm the monitor thread with timeout 0 — liveness reporting with
        NO kill policy, repolled at the interval, never firing
        on_timeout."""
        hb = tmp_path / "heartbeat.json"
        fired = []
        wd = ProgressWatchdog(0.0, heartbeat_path=str(hb),
                              payload=lambda: {"serving": {"status": "ok"}},
                              on_timeout=lambda gap: fired.append(gap),
                              heartbeat_interval_s=0.05)
        wd.start()
        try:
            assert wd._thread is not None, "heartbeat-only mode never armed"
            deadline = time.time() + 10.0
            while not hb.exists() and time.time() < deadline:
                time.sleep(0.02)
            assert hb.exists()
            first = json.loads(hb.read_text())["time"]
            # The poll cadence follows the interval, not the 1s floor of
            # the timeout-derived poll: a rewrite lands well inside 10s.
            deadline = time.time() + 10.0
            while time.time() < deadline:
                if json.loads(hb.read_text())["time"] > first:
                    break
                time.sleep(0.02)
            assert json.loads(hb.read_text())["time"] > first
        finally:
            wd.stop()
        doc = json.loads(hb.read_text())
        assert doc["serving"]["status"] == "ok"
        assert doc["timeout_s"] == 0.0
        assert fired == [], "heartbeat-only mode must never kill"

    def test_no_heartbeat_no_timeout_stays_noop(self):
        wd = ProgressWatchdog(0.0, heartbeat_interval_s=1.0)  # no path
        wd.start()
        assert wd._thread is None
        wd.stop()

    def test_stop_writes_final_state(self, tmp_path):
        from cst_captioning_tpu.telemetry import MetricsRegistry

        reg = MetricsRegistry()
        hb = tmp_path / "heartbeat.json"
        wd = ProgressWatchdog(60.0, heartbeat_path=str(hb),
                              payload=reg.heartbeat_payload)
        wd.start()
        # counters that land AFTER the start-of-thread write (the poll is
        # 15s away) must still reach the file via the stop() flush
        reg.inc("fault_firings", 3)
        reg.log_step(9, "train", {"loss": 0.5})
        wd.stop()
        doc = json.loads(hb.read_text())
        assert doc["counters"]["fault_firings"] == 3
        assert doc["last_train"]["step"] == 9

    def test_heartbeat_write_is_atomic_and_leaves_no_tmp(self, tmp_path):
        """heartbeat.json follows the telemetry.json snapshot discipline
        (ISSUE 4 satellite): fsync'd tmp file + atomic rename — after any
        number of polls the published file is complete JSON and no .tmp
        litter remains for the harness to trip on."""
        from cst_captioning_tpu.telemetry import MetricsRegistry

        reg = MetricsRegistry()
        reg.declare("preempt_signals")
        hb = tmp_path / "hb.json"
        wd = ProgressWatchdog(0.5, on_timeout=lambda g: None,
                              heartbeat_path=str(hb),
                              payload=reg.heartbeat_payload)
        wd.start()
        try:
            deadline = time.time() + 10.0
            while not hb.exists() and time.time() < deadline:
                time.sleep(0.02)
            doc = json.loads(hb.read_text())  # complete JSON, every time
        finally:
            wd.stop()
        assert doc["counters"]["preempt_signals"] == 0
        assert not (tmp_path / "hb.json.tmp").exists(), \
            "tmp file must be renamed away, never left beside the heartbeat"
        # The final stop() write is also clean.
        json.loads(hb.read_text())
        assert list(tmp_path.iterdir()) == [hb]

    def test_wedge_exit_code_is_the_taxonomy_constant(self):
        """watchdog.WEDGE_EXIT_CODE is a re-export of the consolidated
        taxonomy (resilience/exitcodes.py) — the many existing importers
        and the taxonomy can never drift apart."""
        from cst_captioning_tpu.resilience.exitcodes import (EXIT_WEDGE,
                                                             classify)

        assert WEDGE_EXIT_CODE == EXIT_WEDGE == 124
        assert classify(WEDGE_EXIT_CODE) == "wedge"

    def test_payload_errors_never_kill_monitoring(self, tmp_path):
        fired = []
        wd = ProgressWatchdog(0.2, on_timeout=lambda g: fired.append(g),
                              heartbeat_path=str(tmp_path / "hb.json"),
                              payload=lambda: 1 / 0)
        wd.start()
        try:
            deadline = time.time() + 10.0
            while not fired and time.time() < deadline:
                time.sleep(0.05)
        finally:
            wd.stop()
        assert fired, "a broken payload callable silenced the watchdog"

    def test_no_heartbeat_path_writes_nothing(self, tmp_path):
        wd = ProgressWatchdog(0.5, on_timeout=lambda g: None)
        wd.start()
        time.sleep(0.1)
        wd.stop()
        assert list(tmp_path.iterdir()) == []


# Driver for the trainer-wiring test: a real Trainer on a tiny fixture
# whose validate() wedges forever — the armed --wedge_timeout must kill
# the process with WEDGE_EXIT_CODE instead of hanging the run.
WEDGED_TRAINER = """\
import sys, time, json
sys.path.insert(0, %(repo)r)
from cst_captioning_tpu.data.synthetic import SyntheticSpec, generate
from cst_captioning_tpu.opts import parse_opts
from cst_captioning_tpu.training import trainer as trainer_mod

root = sys.argv[1]
spec = SyntheticSpec(num_videos=4, captions_per_video=2, max_len=8,
                     feat_dims=(8,), feat_times=(2,))
train = generate(root, "train", spec)

opt = parse_opts([
    "--train_feat_h5", *json.loads(train["feat_h5"]),
    "--train_label_h5", train["label_h5"],
    "--train_info_json", train["info_json"],
    "--train_cocofmt_file", train["cocofmt_json"],
    "--checkpoint_path", root + "/ck",
    "--batch_size", "2", "--seq_per_img", "2", "--rnn_size", "16",
    "--input_encoding_size", "16", "--att_size", "16", "--max_length", "8",
    "--max_epochs", "1", "--log_every", "1", "--wedge_timeout", "2",
])
t = trainer_mod.Trainer(opt)
# Wedge the epoch-boundary save: a blocking call that never returns, like
# a dead transport under a device->host fetch.
t.ckpt.save = lambda *a, **k: time.sleep(3600)
t.train()
print("UNREACHABLE")
"""


@pytest.mark.e2e
def test_trainer_watchdog_kills_wedged_run(tmp_path):
    script = tmp_path / "wedged.py"
    script.write_text(WEDGED_TRAINER % {"repo": REPO})
    env = dict(os.environ)
    env["PYTHONPATH"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    from conftest import CACHE_DIR

    env.setdefault("JAX_COMPILATION_CACHE_DIR", CACHE_DIR)
    proc = subprocess.run(
        [sys.executable, str(script), str(tmp_path / "d")],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == WEDGE_EXIT_CODE, (
        f"rc={proc.returncode}\nstdout:{proc.stdout[-2000:]}\n"
        f"stderr:{proc.stderr[-2000:]}")
    assert "UNREACHABLE" not in proc.stdout
    assert "wedged" in proc.stderr  # the CRITICAL last word


# Driver for the eval-wiring test: real eval.py on a trained fixture
# whose decode wedges — the armed --wedge_timeout must kill it at 124.
WEDGED_EVAL = """\
import sys, time, json
sys.path.insert(0, %(repo)r)
from cst_captioning_tpu.data.synthetic import SyntheticSpec, generate
import train as train_cli
import eval as eval_cli

root = sys.argv[1]
spec = SyntheticSpec(num_videos=4, captions_per_video=2, max_len=8,
                     feat_dims=(8,), feat_times=(2,))
train = generate(root, "train", spec)
common = [
    "--train_feat_h5", *json.loads(train["feat_h5"]),
    "--train_label_h5", train["label_h5"],
    "--train_info_json", train["info_json"],
    "--train_cocofmt_file", train["cocofmt_json"],
    "--checkpoint_path", root + "/ck",
    "--batch_size", "2", "--seq_per_img", "2", "--rnn_size", "16",
    "--input_encoding_size", "16", "--att_size", "16", "--max_length", "8",
    "--max_epochs", "1", "--log_every", "1",
]
train_cli.main(common)
# Wedge the decode path: the compiled-decoder factory never returns, like
# a dead transport under the beam compile.
from cst_captioning_tpu.training import evaluation
evaluation._compiled_decoder = lambda *a, **k: time.sleep(3600)
eval_cli.main([
    "--checkpoint_path", root + "/ck",
    "--test_feat_h5", *json.loads(train["feat_h5"]),
    "--test_label_h5", train["label_h5"],
    "--test_info_json", train["info_json"],
    "--test_cocofmt_file", train["cocofmt_json"],
    "--beam_size", "2", "--batch_size", "2", "--max_length", "8",
    "--wedge_timeout", "2",
])
print("UNREACHABLE")
"""


@pytest.mark.e2e
def test_eval_watchdog_kills_wedged_eval(tmp_path):
    script = tmp_path / "wedged_eval.py"
    script.write_text(WEDGED_EVAL % {"repo": REPO})
    env = dict(os.environ)
    env["PYTHONPATH"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    from conftest import CACHE_DIR

    env.setdefault("JAX_COMPILATION_CACHE_DIR", CACHE_DIR)
    proc = subprocess.run(
        [sys.executable, str(script), str(tmp_path / "d")],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == WEDGE_EXIT_CODE, (
        f"rc={proc.returncode}\nstdout:{proc.stdout[-2000:]}\n"
        f"stderr:{proc.stderr[-2000:]}")
    assert "UNREACHABLE" not in proc.stdout
    assert "wedged" in proc.stderr


# -- scale_chain harness recovery -----------------------------------------

def _cpu_env():
    """The env the harness's stages (and therefore its probes) run under:
    CPU-only, axon sitecustomize scrubbed — probes answer instantly."""
    env = dict(os.environ)
    env["PYTHONPATH"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    return env


def _load_scale_chain():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "scale_chain", os.path.join(REPO, "scripts", "scale_chain.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


FLAKY = """\
import os, sys
marker = sys.argv[1]
if not os.path.exists(marker):
    open(marker, "w").close()
    sys.exit(124)
sys.exit(0)
"""


def test_run_stage_resumes_after_wedge_exit(tmp_path):
    sc = _load_scale_chain()
    script = tmp_path / "flaky.py"
    script.write_text(FLAKY)
    marker = tmp_path / "attempted"
    # First attempt exits WEDGE_EXIT_CODE; the probe (CPU env) heals
    # instantly; the retry succeeds.
    sc.run_stage("flaky", [sys.executable, str(script), str(marker)],
                 max_attempts=3, wedge_poll_s=0.1, max_wedge_wait_s=30.0,
                 probe_timeout_s=20.0, env=_cpu_env())
    assert marker.exists()


def test_run_stage_aborts_on_real_failure(tmp_path):
    sc = _load_scale_chain()
    script = tmp_path / "broken.py"
    script.write_text("import sys; sys.exit(3)\n")
    with pytest.raises(SystemExit, match="real failure"):
        sc.run_stage("broken", [sys.executable, str(script)],
                     max_attempts=3, wedge_poll_s=0.1, max_wedge_wait_s=30.0,
                     probe_timeout_s=20.0, env=_cpu_env())


def test_run_stage_caps_zero_progress_wedge_exits(tmp_path):
    """A stage that exits 124 at the same point every time on a healthy
    device (e.g. a setup phase deterministically outrunning
    --wedge_timeout) must abort with advice after max_attempts, not
    retry forever."""
    sc = _load_scale_chain()
    script = tmp_path / "always_124.py"
    script.write_text("import sys; sys.exit(124)\n")
    with pytest.raises(SystemExit, match="no on-disk progress"):
        sc.run_stage("det124", [sys.executable, str(script)],
                     max_attempts=2, wedge_poll_s=0.1, max_wedge_wait_s=30.0,
                     probe_timeout_s=20.0, env=_cpu_env())


def test_run_stage_aborts_fast_on_broken_env(tmp_path):
    """An environment that cannot even import jax (corrupt venv, bad
    PYTHONHOME) must abort with the diagnosis immediately — NOT be
    classified as a wedge and heal-polled for hours."""
    sc = _load_scale_chain()
    env = _cpu_env()
    env["PYTHONHOME"] = str(tmp_path / "nonexistent")
    script = tmp_path / "any.py"
    script.write_text("print('unreachable')\n")
    t0 = time.time()
    with pytest.raises(SystemExit, match="cannot even import"):
        sc.run_stage("broken-env", [sys.executable, str(script)],
                     max_attempts=3, wedge_poll_s=0.1, max_wedge_wait_s=600.0,
                     probe_timeout_s=20.0, env=env)
    assert time.time() - t0 < 60  # fast diagnosis, no heal-poll


def test_run_stage_timeout_kills_group_and_retries(tmp_path):
    sc = _load_scale_chain()
    script = tmp_path / "hang_once.py"
    marker = tmp_path / "attempted"
    script.write_text(
        "import os, sys, time\n"
        "m = sys.argv[1]\n"
        "if not os.path.exists(m):\n"
        "    open(m, 'w').close()\n"
        "    time.sleep(3600)\n"  # wedged eval: no in-process watchdog
        "sys.exit(0)\n")
    t0 = time.time()
    sc.run_stage("hang", [sys.executable, str(script), str(marker)],
                 max_attempts=3, wedge_poll_s=0.1, max_wedge_wait_s=30.0,
                 timeout_s=2.0, probe_timeout_s=20.0, env=_cpu_env())
    assert time.time() - t0 < 90
    assert marker.exists()


def test_stage_fingerprint_ignores_log_appends(tmp_path):
    """Only real progress markers count: infos.json step fields and the
    set of checkpoint step dirs.  metrics.jsonl/TB appends from re-running
    the same steps after a resume must NOT read as progress (they would
    defeat the no-progress attempt cap on a deterministic wedge)."""
    sc = _load_scale_chain()
    stage = tmp_path / "xe"
    stage.mkdir()
    fp = sc.stage_fingerprint(str(stage))
    base = fp()

    # Log/TB churn alone: no change.
    (stage / "metrics.jsonl").write_text('{"step": 1}\n')
    assert fp() == base
    (stage / "metrics.jsonl").write_text('{"step": 1}\n{"step": 1}\n')
    assert fp() == base

    # A new checkpoint step dir IS progress...
    (stage / "100").mkdir()
    after_ckpt = fp()
    assert after_ckpt != base
    # ...as is a recovery-manager save...
    (stage / "recovery").mkdir()
    (stage / "recovery" / "150").mkdir()
    after_rec = fp()
    assert after_rec != after_ckpt
    # ...and an infos.json step advance.
    (stage / "infos.json").write_text(
        json.dumps({"last_step": 150, "best_step": 100}))
    after_infos = fp()
    assert after_infos != after_rec
    # Rewriting infos.json with identical steps: no change.
    (stage / "infos.json").write_text(
        json.dumps({"best_step": 100, "last_step": 150}))
    assert fp() == after_infos


def test_run_stage_aborts_on_second_healthy_timeout(tmp_path):
    """A command that deterministically outruns the harness cap on a
    healthy device must not be retried to attempt exhaustion — one retry
    (for transient per-connection wedges), then 'raise the cap'."""
    sc = _load_scale_chain()
    script = tmp_path / "always_hangs.py"
    script.write_text("import time; time.sleep(3600)\n")
    counter = tmp_path / "runs"
    wrapper = tmp_path / "wrapped.py"
    wrapper.write_text(
        "import subprocess, sys, pathlib\n"
        f"p = pathlib.Path({str(counter)!r})\n"
        "p.write_text(p.read_text() + 'x' if p.exists() else 'x')\n"
        f"sys.exit(subprocess.call([sys.executable, {str(script)!r}]))\n")
    with pytest.raises(SystemExit, match="raise the timeout"):
        sc.run_stage("hang2", [sys.executable, str(wrapper)],
                     max_attempts=5, wedge_poll_s=0.1, max_wedge_wait_s=30.0,
                     timeout_s=2.0, probe_timeout_s=20.0, env=_cpu_env())
    assert counter.read_text() == "xx"  # exactly two attempts, not five
