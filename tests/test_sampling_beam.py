"""Samplers + beam search: shape/termination invariants, greedy-vs-forward
consistency, and beam search against a brute-force oracle (SURVEY.md §4
"beam-search against a brute-force reference on tiny vocab")."""

import itertools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cst_captioning_tpu.models import CaptionModel
from cst_captioning_tpu.ops.beam import (
    _expand_to_beams,
    beam_search,
    beam_search_tokens,
)
from cst_captioning_tpu.ops.losses import sequence_mask, token_logprobs
from cst_captioning_tpu.ops.sampling import sample_captions, sample_tokens

VOCAB = 12
B = 3
T = 5
D = 7
MAX_LEN = 6


def make_model(decoder_type="lstm", use_attention=True):
    model = CaptionModel(
        vocab_size=VOCAB, embed_size=16, hidden_size=16, attn_size=16,
        use_attention=use_attention, dropout_rate=0.0,
        decoder_type=decoder_type, num_heads=2, num_tx_layers=1,
        tx_max_len=MAX_LEN,
    )
    feats = [jnp.asarray(np.random.default_rng(0).normal(size=(B, T, D)),
                         jnp.float32)]
    labels = jnp.zeros((B, MAX_LEN), dtype=jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), feats, labels)
    return model, variables, feats


@pytest.mark.parametrize("decoder_type", ["lstm", "transformer"])
def test_sample_shapes_and_termination(decoder_type):
    model, variables, feats = make_model(decoder_type)
    toks, logps = sample_captions(
        model, variables, feats, jax.random.PRNGKey(1), MAX_LEN, seq_per_img=2
    )
    assert toks.shape == (2 * B, MAX_LEN)
    assert logps.shape == (2 * B, MAX_LEN)
    toks = np.asarray(toks)
    logps = np.asarray(logps)
    # 0-terminated: after the first 0 everything is 0 with logprob 0.
    for row_t, row_l in zip(toks, logps):
        zeros = np.nonzero(row_t == 0)[0]
        if len(zeros):
            first = zeros[0]
            assert (row_t[first:] == 0).all()
            assert (row_l[first + 1:] == 0).all()
    # Live logprobs are genuine log-probabilities.
    mask = np.asarray(sequence_mask(jnp.asarray(toks)))
    assert (logps[mask.astype(bool)] <= 0).all()


@pytest.mark.parametrize("decoder_type", ["lstm", "transformer"])
def test_greedy_logprobs_match_teacher_forced_forward(decoder_type):
    """The sampler's per-token logprobs must equal the training forward's —
    one-semantics guarantee between decode and train paths."""
    model, variables, feats = make_model(decoder_type)
    toks, logps = sample_captions(
        model, variables, feats, jax.random.PRNGKey(2), MAX_LEN, greedy=True
    )
    logits = model.apply(variables, feats, toks, seq_per_img=1)
    tf_logps = token_logprobs(logits, toks)
    mask = sequence_mask(toks)
    np.testing.assert_allclose(
        np.asarray(logps * mask), np.asarray(tf_logps * mask),
        rtol=2e-4, atol=2e-4,
    )


def test_multinomial_differs_across_keys_greedy_does_not():
    model, variables, feats = make_model()
    g1, _ = sample_captions(model, variables, feats, jax.random.PRNGKey(1),
                            MAX_LEN, greedy=True)
    g2, _ = sample_captions(model, variables, feats, jax.random.PRNGKey(9),
                            MAX_LEN, greedy=True)
    np.testing.assert_array_equal(np.asarray(g1), np.asarray(g2))
    draws = [
        np.asarray(sample_captions(model, variables, feats,
                                   jax.random.PRNGKey(k), MAX_LEN)[0])
        for k in range(4)
    ]
    assert any(not np.array_equal(draws[0], d) for d in draws[1:])


class FixedStep:
    """Deterministic decode 'model': logits depend on (prev token, step) via
    a fixed table, state counts steps.  Lets brute force enumerate exactly."""

    def __init__(self, vocab, max_len, seed=0):
        rng = np.random.default_rng(seed)
        self.table = jnp.asarray(
            rng.normal(size=(max_len, vocab, vocab)).astype(np.float32)
        )

    def __call__(self, carry, token):
        t = carry
        logits = self.table[t][token]            # (N, V)
        return t + 1, logits

    def logp(self, t, prev, nxt):
        row = np.asarray(jax.nn.log_softmax(self.table[t][prev]))
        return row[nxt]


def brute_force_best(step: FixedStep, vocab: int, max_len: int):
    """Enumerate all 0-terminated sequences; return (best_seq, best_logp)."""
    best, best_score = None, -np.inf
    for seq in itertools.product(range(vocab), repeat=max_len):
        # canonicalize: nothing after first 0
        arr = list(seq)
        if 0 in arr:
            first = arr.index(0)
            if any(x != 0 for x in arr[first:]):
                continue  # non-canonical duplicate
        score, prev = 0.0, 0
        for t, tok in enumerate(arr):
            score += step.logp(t, prev, tok)
            prev = tok
            if tok == 0:
                break
        if score > best_score:
            best_score, best = score, arr
    return np.array(best), best_score


def test_beam_matches_brute_force_on_tiny_vocab():
    vocab, max_len = 4, 4
    step = FixedStep(vocab, max_len, seed=3)
    oracle_seq, oracle_score = brute_force_best(step, vocab, max_len)
    # Wide beam == exhaustive on this tiny space.
    best, beams, scores = beam_search_tokens(
        step, jnp.zeros((), jnp.int32), batch=1, beam_size=vocab ** 2,
        max_len=max_len,
    )
    np.testing.assert_array_equal(np.asarray(best)[0], oracle_seq)
    assert np.isclose(float(scores[0, 0]), oracle_score, atol=1e-4)


def test_beam_scores_sorted_and_padded():
    model, variables, feats = make_model()
    best, beams, scores = beam_search(model, variables, feats,
                                      beam_size=3, max_len=MAX_LEN)
    assert best.shape == (B, MAX_LEN)
    assert beams.shape == (B, 3, MAX_LEN)
    s = np.asarray(scores)
    assert (np.diff(s, axis=1) <= 1e-6).all()
    toks = np.asarray(beams).reshape(-1, MAX_LEN)
    for row in toks:
        zeros = np.nonzero(row == 0)[0]
        if len(zeros):
            assert (row[zeros[0]:] == 0).all()


def test_beam_size_one_equals_greedy():
    model, variables, feats = make_model()
    greedy, _ = sample_captions(model, variables, feats,
                                jax.random.PRNGKey(0), MAX_LEN, greedy=True)
    best, _, _ = beam_search(model, variables, feats, beam_size=1,
                             max_len=MAX_LEN)
    np.testing.assert_array_equal(np.asarray(greedy), np.asarray(best))


@pytest.mark.parametrize("decoder_type", ["lstm", "transformer"])
def test_beam_improves_or_matches_greedy_logprob(decoder_type):
    """Beam-5's top hypothesis must score >= greedy under the model."""
    model, variables, feats = make_model(decoder_type)
    greedy, glogp = sample_captions(model, variables, feats,
                                    jax.random.PRNGKey(0), MAX_LEN, greedy=True)
    _, _, scores = beam_search(model, variables, feats, beam_size=5,
                               max_len=MAX_LEN)
    gscore = np.asarray((glogp * sequence_mask(greedy)).sum(axis=1))
    assert (np.asarray(scores[:, 0]) >= gscore - 1e-4).all()


def test_expand_to_beams_skips_scalars():
    tree = (jnp.ones((2, 3)), jnp.zeros((), jnp.int32))
    out = _expand_to_beams(tree, 4, 2)
    assert out[0].shape == (8, 3)
    assert out[1].shape == ()
