"""Fault-tolerant serving (ISSUE 9): deadlines, self-healing, health plane.

Fast slice (tier-1):
- the serving fault grammar (``serve_wedge@req=N`` / ``serve_garble@req=N``
  / ``admit_err@req=N``) and the shared garble/health helpers
  (``resilience/garble.py``);
- THE acceptance drill, in-process: a seeded run under all three injected
  serving faults completes every request with captions BIT-IDENTICAL to
  the fault-free twin, zero program builds after warmup (including across
  an engine rebuild), and every injected fault reflected in the
  registry counters — machine-checked, not eyeballed;
- the recovery ladder's escalation: retry -> rebuild (re-warmed from the
  ProgramCache, replay verified against persisted prefixes) ->
  ``ServingUnrecoverable``;
- request deadlines: mid-flight TTL eviction freeing the slot for the
  next queued request, queued expiry, p99-unmeetable shedding, the
  deadline-slack histogram, per-request override;
- the hardened JSONL intake (malformed line / unknown op / bad deadline
  -> per-line error + counter, never a dead scheduler loop) and the
  ``{"op": "health"}`` ok|degraded|draining contract;
- the double-signal drain abort (first TERM drains, second exits hard
  through the taxonomy) at the engine and server levels;
- doc pins: RESILIENCE.md lists every serving fault kind + the recovery
  escalation table; SERVING.md lists every engine counter.

The subprocess drills (scripts/serve.py under a real ``--fault_plan``,
real double SIGTERM, the heartbeat file) are marked ``slow`` and run via
``make serve-chaos``.
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cst_captioning_tpu.models import CaptionModel
from cst_captioning_tpu.ops.beam import beam_search
from cst_captioning_tpu.ops.sampling import sample_captions
from cst_captioning_tpu.resilience.faults import FaultPlan, InjectedFault
from cst_captioning_tpu.resilience.garble import (
    GarbledChunk,
    all_zero,
    garbled_decode_slots,
    health_status,
)
from cst_captioning_tpu.serving.engine import (
    COUNTERS,
    ServingEngine,
    ServingUnrecoverable,
)
from cst_captioning_tpu.serving.server import CaptionServer
from cst_captioning_tpu.telemetry.registry import MetricsRegistry

V, B, T, D, MAX_LEN = 12, 5, 3, 7, 8
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _lock_sanitizer(monkeypatch, tmp_path):
    """ISSUE 11: the serving fast slice runs with the runtime lock
    sanitizer ARMED — every engine/server/registry built inside a test
    gets sanitized locks, so the declared LOCK_ORDER is re-validated
    under the PR 9 fault drills on every tier-1 run.  A violation raises
    in place; this fixture additionally asserts none were recorded."""
    from cst_captioning_tpu.analysis import locksan

    receipt = tmp_path / "locksan_violation.json"
    monkeypatch.setenv(locksan.ENV_FLAG, "1")
    monkeypatch.setenv(locksan.ENV_RECEIPT, str(receipt))
    before = len(locksan.violations())
    yield
    after = locksan.violations()
    assert len(after) == before, f"lock-order violations: {after[before:]}"
    # Subprocess drills (scripts/serve.py) inherit the env: their
    # violations can't reach this process's registry, but the durable
    # receipt can — its absence IS the cross-process assertion.
    assert not receipt.exists(), (
        f"lock sanitizer receipt from a child process: "
        f"{receipt.read_text()}")


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def tick(self, dt=1.0):
        self.t += dt


def make_variables(model, feats, eos_bias=0.4):
    variables = model.init(jax.random.PRNGKey(0), feats,
                           np.zeros((B, MAX_LEN), np.int32))
    params = {**variables["params"]}
    params["logit"] = {**params["logit"]}
    params["logit"]["bias"] = params["logit"]["bias"].at[0].add(eos_bias)
    return {"params": params}


@pytest.fixture(scope="module")
def setup():
    model = CaptionModel(vocab_size=V, embed_size=16, hidden_size=16,
                         attn_size=16, dropout_rate=0.0)
    feats_np = np.random.default_rng(0).normal(
        size=(B, T, D)).astype(np.float32) * 2.0
    variables = make_variables(model, [jnp.asarray(feats_np)])
    return model, variables, feats_np


@pytest.fixture(scope="module")
def long_setup():
    """EOS-suppressed twin: captions run the full MAX_LEN, so residents
    stay in flight long enough for deterministic TTL-eviction drills."""
    model = CaptionModel(vocab_size=V, embed_size=16, hidden_size=16,
                         attn_size=16, dropout_rate=0.0)
    feats_np = np.random.default_rng(7).normal(
        size=(B, T, D)).astype(np.float32) * 2.0
    variables = make_variables(model, [jnp.asarray(feats_np)],
                               eos_bias=-8.0)
    return model, variables, feats_np


def submit_all(engine, feats_np, n=None):
    for i in range(n if n is not None else feats_np.shape[0]):
        assert engine.submit(i, [feats_np[i]])


def tokens_by_id(completions):
    return {c.request_id: c.tokens for c in completions}


# -- grammar + shared helpers ----------------------------------------------


def test_serving_fault_grammar_parses():
    plan = FaultPlan.parse(
        "serve_wedge@req=1,serve_garble@req=2,admit_err@req=0")
    assert plan.pending("serve_wedge") == 1
    assert plan.fire("serve_garble", 2) and not plan.fire("serve_garble", 2)
    with pytest.raises(ValueError, match="keys on 'req'"):
        FaultPlan.parse("serve_wedge@step=1")
    with pytest.raises(ValueError, match="keys on 'step'"):
        FaultPlan.parse("wedge@req=1")


def test_serving_fault_cli_usage_error():
    from cst_captioning_tpu.opts import parse_opts

    with pytest.raises(SystemExit) as exc:
        parse_opts(["--fault_plan", "serve_wedge@step=3"])
    assert exc.value.code == 2
    ns = parse_opts(["--fault_plan", "serve_garble@req=3"])
    assert ns.fault_plan == "serve_garble@req=3"


def test_all_zero_signature():
    assert all_zero([0.0, 0.0, 0.0])
    assert all_zero(np.zeros((3, 4), np.int32))
    assert not all_zero([0.0, 1e-30])
    assert not all_zero([])                 # empty is not a signature


def test_garbled_decode_slots_flags_impossible_rows():
    # greedy shape (slots, chunk): live row, not finished, all-zero chunk
    # = the impossible signature; a finished all-zero row is the normal
    # EOS-extension no-op and must NOT be flagged.
    toks = np.array([[0, 0], [3, 4], [0, 0]], np.int32)
    fin = np.array([False, False, True])
    assert garbled_decode_slots(toks, fin, [0, 1, 2]) == [0]
    assert garbled_decode_slots(toks, fin, [1, 2]) == []
    # beam shape (slots, chunk, k)
    btoks = np.zeros((2, 2, 3), np.int32)
    btoks[1, 0, 0] = 5
    bfin = np.array([False, False])
    assert garbled_decode_slots(btoks, bfin, [0, 1]) == [0]


def test_health_status_words():
    assert health_status(draining=False, recovering=False) == "ok"
    assert health_status(draining=False, recovering=True) == "degraded"
    assert health_status(draining=True, recovering=True) == "draining"


# -- THE acceptance drill: chaos-drilled self-healing, bit-identical -------


def test_chaos_drill_greedy_bit_identical_zero_recompiles(setup):
    """Acceptance: under serve_wedge + serve_garble + admit_err, every
    request completes with captions bit-identical to the fault-free run,
    zero program builds after warmup, and every injected fault lands in
    the counters."""
    model, variables, feats_np = setup
    offline, _ = sample_captions(model, variables, [jnp.asarray(feats_np)],
                                 jax.random.PRNGKey(0), MAX_LEN, greedy=True)
    plan = FaultPlan.parse(
        "serve_wedge@req=1,serve_garble@req=2,admit_err@req=3")
    registry = MetricsRegistry()
    plan.bind_metrics(registry)
    engine = ServingEngine(model, variables, [(T, D)], max_len=MAX_LEN,
                           decode_chunk=2, bucket_sizes=(2,), queue_limit=0,
                           fault_plan=plan, recover=True,
                           registry=registry)
    warm_builds = engine.warm()["compiles"]
    submit_all(engine, feats_np)
    got = tokens_by_id(engine.run_until_idle())
    # Every request completed, bit-identical to the offline decode.
    assert sorted(got) == list(range(B))
    np.testing.assert_array_equal(
        np.stack([got[i] for i in range(B)]), np.asarray(offline))
    # Zero post-warmup compiles — recovery re-ran and re-admitted through
    # the warm ProgramCache, it never rebuilt a program.
    stats = engine.stats()
    assert stats["compiles"] == warm_builds
    # Each injected fault is visible in the audit trail.
    snap = registry.snapshot()["counters"]
    assert snap["serve_wedge_detected"] == 1
    assert snap["serve_garble_detected"] == 1
    assert snap["serve_admit_errors"] == 1
    assert snap["serve_chunk_retries"] == 2      # one wedge + one garble
    assert snap["serve_rebuilds"] == 0
    assert snap["serve_replay_divergence"] == 0
    assert snap["fault_firings"] == 3            # the plan's own audit
    assert plan.pending("serve_wedge") == 0
    # Recovery events within the window: the health plane reads degraded.
    assert engine.health()["status"] == "degraded"
    assert stats["completed"] == B and stats["expired"] == 0


def test_chaos_drill_escalates_to_rebuild_zero_recompiles(setup):
    """retry_limit=0 sends the first garble straight up the ladder: the
    engine rebuilds — fresh slot state, residents re-admitted from their
    persisted requests, ZERO new program builds — and the deterministic
    replay still lands bit-identical captions (prefix-verified)."""
    model, variables, feats_np = setup
    offline, _ = sample_captions(model, variables, [jnp.asarray(feats_np)],
                                 jax.random.PRNGKey(0), MAX_LEN, greedy=True)
    registry = MetricsRegistry()
    plan = FaultPlan.parse("serve_garble@req=1").bind_metrics(registry)
    engine = ServingEngine(model, variables, [(T, D)], max_len=MAX_LEN,
                           decode_chunk=2, bucket_sizes=(2,), queue_limit=0,
                           fault_plan=plan, recover=True, retry_limit=0,
                           registry=registry)
    warm_builds = engine.warm()["compiles"]
    submit_all(engine, feats_np)
    got = tokens_by_id(engine.run_until_idle())
    np.testing.assert_array_equal(
        np.stack([got[i] for i in range(B)]), np.asarray(offline))
    stats = engine.stats()
    assert stats["rebuilds"] == 1
    assert stats["rebuild_recompiles"] == 0      # the compile-once contract
    assert stats["compiles"] == warm_builds
    snap = registry.snapshot()["counters"]
    assert snap["serve_rebuilds"] == 1
    assert snap["serve_rebuild_recompiles"] == 0
    assert snap["serve_replay_divergence"] == 0


def test_chaos_drill_beam_bit_identical(setup):
    model, variables, feats_np = setup
    best, _, _ = beam_search(model, variables, [jnp.asarray(feats_np)],
                             beam_size=3, max_len=MAX_LEN, length_norm=0.7)
    plan = FaultPlan.parse("serve_wedge@req=0,serve_garble@req=2")
    engine = ServingEngine(model, variables, [(T, D)], max_len=MAX_LEN,
                           beam_size=3, length_norm=0.7, decode_chunk=2,
                           bucket_sizes=(2,), queue_limit=0,
                           fault_plan=plan, recover=True)
    engine.warm()
    submit_all(engine, feats_np)
    got = tokens_by_id(engine.run_until_idle())
    np.testing.assert_array_equal(
        np.stack([got[i] for i in range(B)]), np.asarray(best))
    assert engine.stats()["chunk_retries"] == 2


def test_recovery_disabled_detects_but_proceeds(setup):
    """recover=0 (legacy donated fast path): the garble detector still
    counts the impossible signature, but nothing is re-run — detection
    without healing, never a crash."""
    model, variables, feats_np = setup
    plan = FaultPlan.parse("serve_garble@req=1")
    engine = ServingEngine(model, variables, [(T, D)], max_len=MAX_LEN,
                           decode_chunk=2, bucket_sizes=(2,), queue_limit=0,
                           fault_plan=plan, recover=False)
    submit_all(engine, feats_np, n=3)
    got = tokens_by_id(engine.run_until_idle())
    assert sorted(got) == [0, 1, 2]
    assert engine.stats()["garble_detected"] == 1
    assert engine.stats()["chunk_retries"] == 0


class _AlwaysWedge:
    """A fault plan stub that wedges EVERY chunk dispatch — the
    reproducible-failure case the single-shot plan grammar cannot
    express, driving the ladder to its unrecoverable end."""

    def fire(self, kind, index):
        return kind == "serve_wedge"


def test_ladder_exhaustion_raises_unrecoverable(setup):
    model, variables, feats_np = setup
    engine = ServingEngine(model, variables, [(T, D)], max_len=MAX_LEN,
                           decode_chunk=2, bucket_sizes=(1,), queue_limit=0,
                           fault_plan=_AlwaysWedge(), recover=True,
                           retry_limit=1, rebuild_limit=1)
    engine.warm()
    engine.submit(0, [feats_np[0]])
    with pytest.raises(ServingUnrecoverable, match="rebuild"):
        engine.run_until_idle()
    assert engine.stats()["rebuilds"] == 1


def test_unrecoverable_maps_to_wedge_exit_code():
    from cst_captioning_tpu.resilience.exitcodes import EXIT_WEDGE, classify

    assert classify(EXIT_WEDGE) == "wedge"       # supervisors restart it


# -- request deadlines & TTL eviction --------------------------------------


def test_expired_resident_frees_slot_and_next_request_is_admitted(
        long_setup):
    """The TTL tentpole pin: a resident past its deadline is evicted
    mid-flight (drop record, slot freed) and the next queued request is
    admitted into the recycled slot and completes normally."""
    model, variables, feats_np = long_setup
    clock = FakeClock()
    registry = MetricsRegistry()
    engine = ServingEngine(model, variables, [(T, D)], max_len=MAX_LEN,
                           decode_chunk=2, bucket_sizes=(1,), queue_limit=0,
                           registry=registry, clock=clock)
    assert engine.submit(0, [feats_np[0]], deadline_ms=3000)
    assert engine.submit(1, [feats_np[1]])       # no deadline
    done = engine.step()                         # 0 admitted, mid-flight
    assert done == [] and engine.resident_count == 1
    clock.tick(5.0)                              # past request 0's deadline
    done = engine.run_until_idle()
    drops = engine.pop_dropped()
    assert [d.request_id for d in drops] == [0]
    assert drops[0].reason == "expired" and drops[0].where == "resident"
    assert [c.request_id for c in done] == [1]
    assert done[0].slot == 0                     # the recycled slot
    snap = registry.snapshot()["counters"]
    assert snap["serve_expired"] == 1 and snap["serve_completed"] == 1


def test_queued_request_expires_before_admission(long_setup):
    model, variables, feats_np = long_setup
    clock = FakeClock()
    engine = ServingEngine(model, variables, [(T, D)], max_len=MAX_LEN,
                           decode_chunk=2, bucket_sizes=(1,), queue_limit=0,
                           clock=clock)
    engine.submit(0, [feats_np[0]])              # occupies the only slot
    engine.step()
    engine.submit(1, [feats_np[1]], deadline_ms=1000)
    clock.tick(2.0)                              # queued past its deadline
    engine.run_until_idle()
    drops = engine.pop_dropped()
    assert [(d.request_id, d.where) for d in drops] == [(1, "queued")]


def test_unmeetable_deadline_is_shed_at_p99_chunk_latency(long_setup):
    """A queued deadline smaller than one p99 chunk provably cannot be
    met: shed before admission, with its own counter."""
    model, variables, feats_np = long_setup
    clock = FakeClock()
    registry = MetricsRegistry()
    engine = ServingEngine(model, variables, [(T, D)], max_len=MAX_LEN,
                           decode_chunk=2, bucket_sizes=(1,), queue_limit=0,
                           registry=registry, clock=clock)
    engine._chunk_wall.extend([0.5] * 8)         # p99 chunk = 500ms
    engine.submit(0, [feats_np[0]], deadline_ms=100)   # < one chunk
    engine.submit(1, [feats_np[1]], deadline_ms=60000)
    got = tokens_by_id(engine.run_until_idle())
    drops = engine.pop_dropped()
    assert [d.request_id for d in drops] == [0]
    assert drops[0].reason == "deadline_shed"
    assert sorted(got) == [1]
    assert registry.snapshot()["counters"]["serve_deadline_shed"] == 1


def test_default_deadline_and_override_and_slack_histogram(setup):
    model, variables, feats_np = setup
    clock = FakeClock()
    registry = MetricsRegistry()
    engine = ServingEngine(model, variables, [(T, D)], max_len=MAX_LEN,
                           decode_chunk=2, bucket_sizes=(2,), queue_limit=0,
                           deadline_ms=60000, registry=registry, clock=clock)
    engine.submit(0, [feats_np[0]])                    # engine default
    engine.submit(1, [feats_np[1]], deadline_ms=90000)  # override
    engine.submit(2, [feats_np[2]], deadline_ms=0)      # explicit no-TTL
    reqs = {r.index: r for r in engine._queue}
    assert reqs[0].deadline == pytest.approx(60.0)
    assert reqs[1].deadline == pytest.approx(90.0)
    assert reqs[2].deadline is None
    engine.run_until_idle()
    hist = registry.snapshot()["histograms"]["serve_deadline_slack_ms"]
    assert hist["count"] == 2                    # only deadline-carrying
    assert hist["min"] > 0                       # all completed in time


# -- hardened JSONL intake + the health op ---------------------------------


@pytest.fixture()
def server(setup):
    model, variables, feats_np = setup
    registry = MetricsRegistry()
    engine = ServingEngine(model, variables, [(T, D)], max_len=MAX_LEN,
                           decode_chunk=2, bucket_sizes=(2,), queue_limit=2,
                           registry=registry)

    def feats_for(video_id):
        try:
            ix = int(str(video_id).lstrip("v"))
        except ValueError:
            return None
        return [feats_np[ix]] if 0 <= ix < B else None

    class Vocab:
        def decode(self, toks):
            return " ".join(f"w{t}" for t in np.asarray(toks) if t)

    class Handler:
        requested = False
        signal_count = 0

    srv = CaptionServer(engine, Vocab(), feats_for, handler=Handler(),
                        registry=registry)
    replies = []
    return srv, registry, replies, (lambda line: replies.append(
        json.loads(line)))


def test_intake_survives_malformed_lines_with_counted_errors(server):
    """Satellite pin: a malformed line or unknown op yields a per-line
    error response + counter — the scheduler loop survives any input.
    (Pre-ISSUE-9 behavior already answered unparseable JSON with
    bad_request; this pins it and adds the counter + op dispatch.)"""
    srv, registry, replies, respond = server
    srv._handle_line("this is not json", respond)
    srv._handle_line("[1, 2, 3]", respond)
    srv._handle_line('{"id": 7}', respond)                  # no video_id
    srv._handle_line('{"id": 8, "op": "selfdestruct"}', respond)
    srv._handle_line('{"id": 9, "video_id": "v0", "deadline_ms": "soon"}',
                     respond)
    srv._handle_line('{"id": 10, "video_id": "nope"}', respond)
    assert [r.get("error") for r in replies] == [
        "bad_request", "bad_request", "bad_request", "unknown_op",
        "bad_request", "unknown_video"]
    assert replies[3]["op"] == "selfdestruct"
    # unknown_video is a classified miss, not a malformed line.
    assert registry.snapshot()["counters"]["serve_bad_lines"] == 5
    # ...and a good line still works after all of that.
    srv._handle_line('{"id": 11, "video_id": "v0"}', respond)
    assert srv.engine.stats()["queue_depth"] == 1


def test_health_op_reports_ok_degraded_draining(server):
    srv, registry, replies, respond = server
    srv._handle_line('{"op": "health"}', respond)
    assert replies[-1]["op"] == "health"
    assert replies[-1]["status"] == "ok"
    assert replies[-1]["queue_depth"] == 0
    assert set(replies[-1]["recovery"]) >= {
        "expired", "chunk_retries", "rebuilds", "garble_detected"}
    # A recovery event inside the window reads degraded...
    srv.engine._note_recovery_event()
    srv._handle_line('{"op": "health"}', respond)
    assert replies[-1]["status"] == "degraded"
    # ...and a drain in progress dominates everything.
    srv.handler.requested = True
    srv._handle_line('{"op": "health"}', respond)
    assert replies[-1]["status"] == "draining"
    assert registry.snapshot()["counters"]["serve_health_queries"] == 3


def test_socket_reader_thread_lifecycle(server):
    """Satellite (ISSUE 11): the socket front end's reader-thread
    lifecycle, in-process and tier-1 — two connections interleave
    requests (their responses serialize through ``_write_lock`` under
    the armed lock sanitizer), one disconnects MID-LINE (the torn tail
    is a counted bad line, its error answer hits a dead socket and is
    absorbed), and EOF shutdown leaves no stray serve-* thread behind."""
    import socket as socketlib
    import threading

    from cst_captioning_tpu.resilience.exitcodes import EXIT_OK

    srv, registry, replies, respond = server
    rc = []
    loop = threading.Thread(target=lambda: rc.append(srv.run_socket(0)),
                            name="serve-loop-under-test", daemon=True)
    loop.start()
    deadline = time.monotonic() + 30.0
    while srv.bound_port is None:
        assert time.monotonic() < deadline, "server never bound"
        time.sleep(0.01)

    def rpc(sock, fh, obj):
        sock.sendall((json.dumps(obj) + "\n").encode())
        return json.loads(fh.readline())

    c1 = socketlib.create_connection(("127.0.0.1", srv.bound_port),
                                     timeout=30)
    c2 = socketlib.create_connection(("127.0.0.1", srv.bound_port),
                                     timeout=30)
    with c1, c2, c1.makefile("r") as f1, c2.makefile("r") as f2:
        # Interleaved requests across the two reader threads: each
        # response must land on ITS connection, whole-line.
        assert rpc(c1, f1, {"id": "a0", "video_id": "v0"})["id"] == "a0"
        assert rpc(c2, f2, {"id": "b0", "video_id": "v1"})["id"] == "b0"
        assert rpc(c1, f1, {"id": "a1", "video_id": "v2"})["id"] == "a1"
        assert rpc(c2, f2, {"id": "b1", "video_id": "nope"}
                   )["error"] == "unknown_video"
        bad0 = registry.counter("serve_bad_lines")
        # Disconnect MID-LINE: the torn tail reaches the scheduler as a
        # malformed line; its error answer goes to a closed socket.
        c2.sendall(b'{"id": "torn')
        c2.shutdown(socketlib.SHUT_RDWR)
    deadline = time.monotonic() + 30.0
    while registry.counter("serve_bad_lines") <= bad0:
        assert time.monotonic() < deadline, "torn line never counted"
        time.sleep(0.01)
    # Natural end: EOF with everything answered and the engine idle.
    srv._eof.set()
    loop.join(timeout=60.0)
    assert rc == [EXIT_OK]
    deadline = time.monotonic() + 10.0
    while any(t.name in ("serve-conn", "serve-accept")
              for t in threading.enumerate()):
        assert time.monotonic() < deadline, (
            f"stray serving threads: "
            f"{[t.name for t in threading.enumerate()]}")
        time.sleep(0.05)


def test_expired_request_gets_explicit_response(long_setup):
    model, variables, feats_np = long_setup
    clock = FakeClock()
    engine = ServingEngine(model, variables, [(T, D)], max_len=MAX_LEN,
                           decode_chunk=2, bucket_sizes=(1,), queue_limit=0,
                           clock=clock)

    class Vocab:
        def decode(self, toks):
            return "x"

    replies = []
    respond = lambda line: replies.append(json.loads(line))
    srv = CaptionServer(engine, Vocab(),
                        lambda vid: [feats_np[0]] if vid == "v0" else None)
    srv._handle_line('{"id": 1, "video_id": "v0", "deadline_ms": 1000}',
                     respond)
    engine.step()                                # admitted, mid-flight
    clock.tick(9.0)                              # deadline long gone
    while not engine.idle:
        engine.step()
    assert srv._respond_dropped_all()
    assert replies[-1]["error"] == "expired"
    assert replies[-1]["where"] == "resident"
    assert replies[-1]["id"] == 1 and replies[-1]["video_id"] == "v0"


# -- drain: first signal drains, second aborts hard ------------------------


def test_engine_drain_abort_stops_mid_drain(long_setup):
    model, variables, feats_np = long_setup
    engine = ServingEngine(model, variables, [(T, D)], max_len=MAX_LEN,
                           decode_chunk=2, bucket_sizes=(2,), queue_limit=0)
    submit_all(engine, feats_np)
    engine.step()                                # 2 residents mid-flight
    steps = []
    done, rejected = engine.drain(
        abort=lambda: len(steps) >= 1 or steps.append(1))
    assert [r.request_id for r in rejected] == [2, 3, 4]
    assert done == []                            # aborted before finishing
    assert engine.resident_count == 2            # abandoned, honest


def test_server_double_signal_drain_exits_143(long_setup):
    """First TERM -> drain; a second signal mid-drain -> abort, exit
    EXIT_SIGTERM (sigterm_unwind in the taxonomy)."""
    from cst_captioning_tpu.resilience.exitcodes import (
        EXIT_SIGTERM,
        classify,
    )

    model, variables, feats_np = long_setup
    engine = ServingEngine(model, variables, [(T, D)], max_len=MAX_LEN,
                           decode_chunk=2, bucket_sizes=(2,), queue_limit=0)

    class Handler:
        requested = True
        signal_count = 1

    class Vocab:
        def decode(self, toks):
            return "x"

    handler = Handler()
    srv = CaptionServer(engine, Vocab(), lambda vid: None, handler=handler,
                        out=open(os.devnull, "w"))
    submit_all(engine, feats_np)
    engine.step()
    orig_step = engine.step
    calls = []

    def step_with_second_signal():
        calls.append(1)
        if len(calls) == 1:
            handler.signal_count += 1            # the second TERM lands
        return orig_step()

    engine.step = step_with_second_signal
    rc = srv._drain_and_exit()
    assert rc == EXIT_SIGTERM
    assert classify(rc) == "resumable"
    assert engine.resident_count > 0             # drain really aborted


def test_server_single_signal_drain_exits_75(setup):
    from cst_captioning_tpu.resilience.exitcodes import EXIT_PREEMPTED

    model, variables, feats_np = setup

    class Handler:
        requested = True
        signal_count = 1

    class Vocab:
        def decode(self, toks):
            return "x"

    engine = ServingEngine(model, variables, [(T, D)], max_len=MAX_LEN,
                           decode_chunk=2, bucket_sizes=(2,), queue_limit=0)
    srv = CaptionServer(engine, Vocab(), lambda vid: None, handler=Handler(),
                        out=open(os.devnull, "w"))
    submit_all(engine, feats_np, n=3)
    engine.step()
    assert srv._drain_and_exit() == EXIT_PREEMPTED
    assert engine.idle


# -- opts: the unmeetable-deadline warn-once -------------------------------


def test_warn_once_deadline_below_chunk_budget(capsys):
    import cst_captioning_tpu.opts as opts

    opts._warned_serve_deadline = False
    opts.parse_opts(["--engine", "serving", "--serve_deadline_ms", "10",
                     "--serve_step_budget_ms", "250"])
    err = capsys.readouterr().err
    assert err.count("can never be met") == 1
    assert "--serve_deadline_ms 10" in err and "8 slots" in err
    opts.parse_opts(["--engine", "serving", "--serve_deadline_ms", "10",
                     "--serve_step_budget_ms", "250"])
    assert "can never be met" not in capsys.readouterr().err   # warn-once
    # A meetable deadline (or no budget) stays silent.
    opts._warned_serve_deadline = False
    opts.parse_opts(["--engine", "serving", "--serve_deadline_ms", "500",
                     "--serve_step_budget_ms", "250"])
    opts.parse_opts(["--engine", "serving", "--serve_deadline_ms", "10"])
    assert "can never be met" not in capsys.readouterr().err


# -- doc pins --------------------------------------------------------------


def test_resilience_doc_pins_serving_fault_kinds_and_escalation():
    """RESILIENCE.md's serving fault section is sourced from the code:
    every req-axis kind documented, the escalation ladder's knobs and
    terminal exit code named — docs and code cannot drift."""
    from cst_captioning_tpu.resilience.faults import KINDS

    with open(os.path.join(REPO, "RESILIENCE.md")) as f:
        text = f.read()
    for kind, axis in KINDS.items():
        assert kind in text, f"RESILIENCE.md missing fault kind {kind}"
        if axis == "req":
            assert f"`{kind}@req=N`" in text, \
                f"RESILIENCE.md missing serving grammar for {kind}"
    for token in ("--serve_retry_limit", "--serve_rebuild_limit",
                  "rebuild", "124", "serve_rebuild_recompiles"):
        assert token in text, f"RESILIENCE.md escalation table missing "\
                              f"{token!r}"


def test_serving_doc_pins_engine_counters():
    with open(os.path.join(REPO, "SERVING.md")) as f:
        text = f.read()
    for name in COUNTERS:
        assert name in text, f"SERVING.md telemetry table missing {name}"
    for token in ("deadline", "expired", "ok|degraded|draining"):
        assert token in text


# -- serve_report: the rebuild-recompile violation gate --------------------


def _run_report(record, tmp_path):
    path = tmp_path / "serving.json"
    path.write_text(json.dumps(record) + "\n")
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "serve_report.py"),
         "--file", str(path)], capture_output=True, text=True, cwd=REPO)


def test_serve_report_renders_recovery_and_gates_on_rebuild_recompiles(
        tmp_path):
    record = {"metric": "serve_captions_per_sec_per_chip", "value": 10.0,
              "latency_p50_ms": 1.0, "latency_p99_ms": 2.0,
              "completed": 4, "num_requests": 4, "shed": 0,
              "recompiles_after_warmup": 0, "expired": 1,
              "deadline_shed": 2, "chunk_retries": 3, "rebuilds": 1,
              "rebuild_recompiles": 0, "garble_detected": 1,
              "wedge_detected": 2, "admit_errors": 0, "platform": "cpu"}
    proc = _run_report(record, tmp_path)
    assert proc.returncode == 0, proc.stderr
    assert "1 rebuilds (0 recompiled)" in proc.stdout
    assert "1 / 2" in proc.stdout                # expired / deadline-shed
    # A rebuild that recompiled breaks the ProgramCache re-warm contract:
    # the report FAILS so CI catches it.
    proc = _run_report({**record, "rebuild_recompiles": 1}, tmp_path)
    assert proc.returncode == 1
    assert "rebuild compiled new programs" in proc.stderr


# -- slow subprocess drills (make serve-chaos) -----------------------------


def _run_serve(requests, extra, timeout=240):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "serve.py"),
         "--serve_demo", "1", "--beam_size", "1", "--max_length", "8",
         "--loglevel", "WARNING"] + extra,
        input="".join(json.dumps(r) + "\n" for r in requests),
        capture_output=True, text=True, cwd=REPO, env=env, timeout=timeout)
    replies = [json.loads(l) for l in proc.stdout.splitlines() if l.strip()]
    return proc, replies


@pytest.mark.slow
def test_serve_cli_chaos_drill_bit_identical(tmp_path):
    """The acceptance drill through the real CLI: scripts/serve.py under
    a seeded --fault_plan answers every request with captions identical
    to the fault-free twin, stamps the fault counters into the stats
    file, and writes a live heartbeat with the serving health payload."""
    reqs = [{"id": i, "video_id": f"v{i}"} for i in range(6)]
    clean, clean_replies = _run_serve(reqs, [])
    assert clean.returncode == 0, clean.stderr[-2000:]
    hb = tmp_path / "heartbeat.json"
    result = tmp_path / "serve_stats.json"
    faulted, fault_replies = _run_serve(reqs, [
        "--fault_plan", "serve_wedge@req=1,serve_garble@req=3,admit_err@req=4",
        "--serve_recover", "1", "--result_file", str(result),
        "--serve_heartbeat_file", str(hb)])
    assert faulted.returncode == 0, faulted.stderr[-2000:]
    assert faulted.stderr.count("FAULT INJECTED") == 3
    by_id = lambda rs: {r["id"]: r.get("caption") for r in rs}
    assert by_id(fault_replies) == by_id(clean_replies)
    assert all(c is not None for c in by_id(clean_replies).values())
    with open(result) as f:
        doc = json.load(f)
    stats = doc["stats"]
    assert stats["wedge_detected"] == 1
    assert stats["garble_detected"] == 1
    assert stats["admit_errors"] == 1
    assert stats["rebuild_recompiles"] == 0
    assert doc["telemetry"]["counters"]["fault_firings"] == 3
    assert doc["health"]["status"] in ("ok", "degraded")
    assert doc["health"]["recovery"]["chunk_retries"] == 2
    with open(hb) as f:
        beat = json.load(f)
    assert beat["serving"]["recovery"]["wedge_detected"] == 1
    assert "counters" in beat


@pytest.mark.slow
def test_serve_cli_double_sigterm_exits_hard(tmp_path):
    """First TERM drains; a second TERM mid-drain aborts it and exits
    143 (sigterm_unwind).  The demo model's EOS is suppressed
    (--serve_demo_eos_bias -8) so every resident decodes the full 60
    steps — a drain window of many chunk dispatches — and the second
    TERM is made un-missable by freezing the server (SIGSTOP) as soon as
    the first TERM's PREEMPT ack appears, queuing the TERM, and resuming
    (SIGCONT): the drain-loop's abort check sees it on the very next
    iteration.  The hard abort is also a flight-recorder trigger
    (ISSUE 14): the blackbox must land, reason ``drain_abort``, with
    the abandoned residents' terminals recorded."""
    import threading

    from cst_captioning_tpu.resilience.exitcodes import (
        EXIT_SIGTERM,
        classify,
    )

    blackbox = tmp_path / "blackbox.json"
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "scripts", "serve.py"),
         "--serve_demo", "1", "--serve_demo_eos_bias", "-8",
         "--beam_size", "1", "--max_length", "500", "--decode_chunk", "1",
         "--serve_buckets", "8", "--loglevel", "WARNING",
         "--serve_blackbox", str(blackbox)],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True, cwd=REPO, env=env)
    errlines = []
    draining_seen = threading.Event()

    def read_err():
        for line in proc.stderr:
            errlines.append(line.rstrip())
            if "serve: draining" in line:
                draining_seen.set()

    threading.Thread(target=read_err, daemon=True).start()
    try:
        for i in range(12):
            proc.stdin.write(json.dumps(
                {"id": i, "video_id": f"v{i % 8}"}) + "\n")
        # The health op is answered by the SAME scheduler loop, after the
        # FIFO inbox — its reply proves startup finished and every
        # request above was submitted (TERMing during the slow jax init
        # would otherwise drain an empty engine and prove nothing).
        proc.stdin.write('{"op": "health"}\n')
        proc.stdin.flush()
        health = json.loads(proc.stdout.readline())
        assert health["op"] == "health"
        time.sleep(0.05)       # a few chunks into the 500-step captions
        proc.send_signal(signal.SIGTERM)
        # The drain-start announcement is printed AFTER the abort
        # baseline is read, so a signal from here on must abort.
        assert draining_seen.wait(60), "drain never started"
        proc.send_signal(signal.SIGSTOP)
        proc.send_signal(signal.SIGTERM)       # pending while frozen
        proc.send_signal(signal.SIGCONT)
        proc.wait(timeout=120)
        err = "\n".join(errlines)
        assert proc.returncode == EXIT_SIGTERM, (proc.returncode, err[-2000:])
        assert classify(proc.returncode) == "resumable"
        assert "drain aborted" in err
        assert "0 resident(s) unfinished" not in err, \
            "degenerate drill: nothing was actually in flight"
        # Every request still got an answer: the abandoned residents are
        # rejected like the queued ones, never silently dropped.
        replies = [json.loads(l) for l in proc.stdout.read().splitlines()
                   if l.strip()]
        rejected = {r["id"] for r in replies
                    if r.get("error") == "rejected_draining"}
        answered = {r["id"] for r in replies if "id" in r}
        assert rejected and answered == set(range(12))
        # The abort blackbox: dumped DURING the abort, every answered
        # request terminal in the stream (the drain_abort drops cover
        # the abandoned residents).
        doc = json.loads(blackbox.read_text())
        assert doc["reason"] == "drain_abort"
        assert doc["accounting"]["terminal_ok"], doc["accounting"]
        assert any(e["kind"] == "dropped"
                   and e.get("where") == "drain_abort"
                   for e in doc["events"])
    finally:
        proc.kill()
