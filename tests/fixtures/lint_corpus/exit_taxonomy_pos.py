"""Positive: bare-int and string exits that bypass the taxonomy."""

import sys


def die_numeric():
    sys.exit(3)


def die_negative():
    sys.exit(-1)  # UnaryOp spelling: exits 255 untyped


def die_stringly(path):
    sys.exit(f"no trace under {path!r}")
