"""Positive: a jit program donating a buffer NO output can alias — the
donated (4,) f32 input has no same-shape/dtype output, so XLA silently
skips the donation (the audit must catch the unfreed buffer)."""


def build():
    import jax
    import jax.numpy as jnp

    def step(state, x):
        # state is donated but the outputs are (3,) i32 and scalar f32:
        # nothing matches the donated (4,) f32 aval.
        return jnp.zeros((3,), jnp.int32), jnp.sum(x) + jnp.sum(state)

    lowered = jax.jit(step, donate_argnums=(0,)).lower(
        jax.ShapeDtypeStruct((4,), jnp.float32),
        jax.ShapeDtypeStruct((4,), jnp.float32))
    return lowered, 1
