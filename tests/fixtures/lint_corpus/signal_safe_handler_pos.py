"""Positive: a signal handler (and a helper it calls) doing
non-async-signal-safe work — Event.set, logging, print."""

import logging
import signal
import threading

log = logging.getLogger(__name__)


class Handler:
    def __init__(self):
        self._evt = threading.Event()

    def install(self):
        signal.signal(signal.SIGTERM, self._handle)

    def _handle(self, signum, frame):
        # Event.set() takes a non-reentrant lock: a nested signal at the
        # next bytecode boundary deadlocks the main thread.
        self._evt.set()
        self._note(signum)

    def _note(self, signum):
        # Reachable FROM the handler: the logging module lock may be
        # held by the interrupted thread.
        log.warning("signal %s", signum)
        print("got signal", signum)
