"""Near-miss negative: every thread is named with explicit daemonhood,
and the non-daemon one has a reachable join."""

import threading


def work():
    pass


def spawn_daemon():
    threading.Thread(target=work, name="prefetch", daemon=True).start()


def spawn_and_reap():
    t = threading.Thread(target=work, name="flusher", daemon=False)
    t.start()
    t.join(timeout=5.0)
