"""Near-miss negative: monotonic deadlines, plus the legal wall-clock
uses — bare timestamp reads stored into records (no arithmetic)."""

import time


def wait_for(probe, max_wait_s):
    deadline = time.monotonic() + max_wait_s
    while time.monotonic() < deadline:
        if probe():
            return True
    return False


def stamp(event):
    # Wall-clock TIMESTAMPS are fine: they label, they do not wait.
    return {"ts": time.time(), "event": event}


def snapshot_time():
    now = time.time()
    return now
