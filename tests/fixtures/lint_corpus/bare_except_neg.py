"""Near-miss negative: broad excepts that account for the failure (log
or counter), and a NARROW except whose silent pass is allowed."""

import logging

log = logging.getLogger("corpus")


def respond_logged(write, payload):
    try:
        write(payload)
    except Exception as e:
        log.debug("write failed: %r", e)


def respond_counted(write, payload, registry):
    try:
        write(payload)
    except Exception:
        registry.inc("corpus_declared_retries")


def best_effort_unlink(os_mod, path):
    try:
        os_mod.unlink(path)
    except OSError:  # narrow type: deliberate best-effort cleanup
        pass
