"""Near-miss negative: reading segments is every consumer's right
(replay, fleet_report's coverage re-scan), and ordinary files keep
their ordinary writes."""

import os


def read_only_scan(root):
    # silent: read mode — scanning sealed segments is not an append
    with open(os.path.join(root, "seg-00000001.wal"), "rb") as f:
        return f.read()


def unrelated_write(root):
    # silent: not a journal segment path
    with open(os.path.join(root, "notes.txt"), "a") as f:
        f.write("x")
