"""Positive: a reader thread reaches into scheduler-owned state instead
of handing work through the inbox."""

import queue
import threading


class Server:
    def __init__(self, engine):
        self.engine = engine  # cstlint: owned_by=scheduler
        self._inbox = queue.Queue()

    def start(self):
        threading.Thread(target=self.reader_main, name="reader",
                         daemon=True).start()


def reader_main(self):
    for line in iter(input, ""):
        # The violation: submitting straight into the engine from the
        # reader thread, bypassing the inbox.
        self.engine.submit(line)


class Spawner:
    def __init__(self, engine):
        self.engine = engine  # cstlint: owned_by=scheduler

    def run(self):
        def read():
            self.engine.submit("direct")  # owned state, reader thread

        threading.Thread(target=read, name="conn", daemon=True).start()
