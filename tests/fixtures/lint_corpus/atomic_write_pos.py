"""Positive: durable JSON written raw — torn-file exposure on crash.
Both the positional and keyword mode spellings must be caught."""

import json


def save_run_summary(path, doc):
    with open(path + "/summary.json", "w") as f:
        json.dump(doc, f, indent=2)


def save_run_summary_kw(path, text):
    with open(path + "/summary.json", mode="w") as f:
        f.write(text)  # pre-rendered json.dumps: still a raw json write
