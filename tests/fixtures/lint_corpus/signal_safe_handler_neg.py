"""Near-miss negative: the PR 4 shape — plain-bool flag + os.write in
the handler; the Event.set lives in code NOT reachable from it."""

import os
import signal
import threading
import time


class Handler:
    def __init__(self):
        self._requested = False
        self._evt = threading.Event()

    def install(self):
        signal.signal(signal.SIGTERM, self._handle)

    def _handle(self, signum, frame):
        # GIL-atomic attribute write + raw fd write: async-signal-safe.
        self._requested = True
        self._when = time.monotonic()
        os.write(2, b"PREEMPT\n")

    def stop_event_from_main_thread(self):
        # Same unsafe calls, but NOT reachable from the handler.
        self._evt.set()
        print("stopping")
