"""Positive: a guarded_by-annotated attribute read and written outside
its declared lock."""

from cst_captioning_tpu.analysis.locksan import named_lock


class Registry:
    def __init__(self):
        self._lock = named_lock("corpus.registry")
        self._counters = {}  # cstlint: guarded_by=self._lock

    def inc(self, name):
        # No lock held: two threads lose increments here.
        self._counters[name] = self._counters.get(name, 0) + 1

    def snapshot(self):
        return dict(self._counters)
