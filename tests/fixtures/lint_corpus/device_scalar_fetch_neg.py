"""Near-miss negative: the same conversions, but host-safe (len/shape/
time arithmetic) or outside any loop — the PR-3/PR-8 discipline."""

import time


def train_loop(steps, state, step_fn):
    device_losses = []
    for i in range(steps):
        state, metrics = step_fn(state)
        device_losses.append(metrics["loss"])   # stays on device
        n = int(len(device_losses) + 1)          # host arithmetic: fine
        wall = float(time.perf_counter())        # time call: fine
        dims = int(metrics["loss"].shape[0])     # shape lookup: fine
        del n, wall, dims
    # ONE batched fetch after the loop is the blessed pattern.
    total = float(sum_host(device_losses))
    return state, total


def sum_host(xs):
    return len(xs)
