"""Seeded positive: raw writes to write-ahead segments outside
serving/journal.py — both the literal-suffix and the name-hint
spellings must fire."""

import os


def raw_segment_append(root):
    # fires: appending to a *.wal path bypasses the one fsync'd
    # frame+crc append helper
    with open(os.path.join(root, "seg-00000001.wal"), "a") as f:
        f.write("{}\n")


def raw_write_by_name(journal_path):
    # fires: a name hinting at the journal opened for (over)writing
    with open(journal_path, "wb") as f:
        f.write(b"")
